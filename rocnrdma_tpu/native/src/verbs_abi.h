// Minimal libibverbs ABI declarations for the dlopen'd verbs backend.
//
// This image ships libibverbs.so.1 but no headers, so the stable
// rdma-core ABI subset we need is declared here directly. Only the
// structs the backend touches are declared; layouts follow rdma-core's
// long-frozen verbs.h ABI (the _compat_* slots in ibv_context_ops are
// the historical ops-table entries that modern rdma-core routes through
// exported symbols instead).
//
// Everything here is accessed strictly at runtime behind dlopen; if the
// library (or a device) is absent, the backend reports failure and the
// engine falls back to "emu".
#ifndef TDR_VERBS_ABI_H_
#define TDR_VERBS_ABI_H_

#include <pthread.h>
#include <stddef.h>
#include <stdint.h>

extern "C" {

struct ibv_device;
struct ibv_context;
struct ibv_comp_channel;
struct ibv_srq;
struct ibv_mw;
struct ibv_ah;

union ibv_gid {
  uint8_t raw[16];
  struct {
    uint64_t subnet_prefix;
    uint64_t interface_id;
  } global;
};

enum ibv_qp_state {
  IBV_QPS_RESET = 0,
  IBV_QPS_INIT = 1,
  IBV_QPS_RTR = 2,
  IBV_QPS_RTS = 3,
  IBV_QPS_ERR = 6,
};

enum ibv_mtu {
  IBV_MTU_256 = 1,
  IBV_MTU_512 = 2,
  IBV_MTU_1024 = 3,
  IBV_MTU_2048 = 4,
  IBV_MTU_4096 = 5,
};

enum ibv_qp_type { IBV_QPT_RC = 2, IBV_QPT_UC = 3, IBV_QPT_UD = 4 };

enum ibv_access_flags {
  IBV_ACCESS_LOCAL_WRITE = 1,
  IBV_ACCESS_REMOTE_WRITE = 2,
  IBV_ACCESS_REMOTE_READ = 4,
  IBV_ACCESS_REMOTE_ATOMIC = 8,
};

enum ibv_wr_opcode {
  IBV_WR_RDMA_WRITE = 0,
  IBV_WR_RDMA_WRITE_WITH_IMM = 1,
  IBV_WR_SEND = 2,
  IBV_WR_SEND_WITH_IMM = 3,
  IBV_WR_RDMA_READ = 4,
};

enum ibv_send_flags {
  IBV_SEND_FENCE = 1,
  IBV_SEND_SIGNALED = 2,
  IBV_SEND_SOLICITED = 4,
  IBV_SEND_INLINE = 8,
};

enum ibv_wc_status { IBV_WC_SUCCESS = 0 };

enum ibv_wc_flags { IBV_WC_GRH = 1 << 0, IBV_WC_WITH_IMM = 1 << 1 };

enum ibv_wc_opcode {
  IBV_WC_SEND = 0,
  IBV_WC_RDMA_WRITE = 1,
  IBV_WC_RDMA_READ = 2,
  IBV_WC_RECV = 1 << 7,
};

/* ibv_modify_qp attr_mask bits */
enum {
  IBV_QP_STATE = 1 << 0,
  IBV_QP_ACCESS_FLAGS = 1 << 3,
  IBV_QP_PKEY_INDEX = 1 << 4,
  IBV_QP_PORT = 1 << 5,
  IBV_QP_AV = 1 << 7,
  IBV_QP_PATH_MTU = 1 << 8,
  IBV_QP_TIMEOUT = 1 << 9,
  IBV_QP_RETRY_CNT = 1 << 10,
  IBV_QP_RNR_RETRY = 1 << 11,
  IBV_QP_RQ_PSN = 1 << 12,
  IBV_QP_MAX_QP_RD_ATOMIC = 1 << 13,
  IBV_QP_MIN_RNR_TIMER = 1 << 15,
  IBV_QP_SQ_PSN = 1 << 16,
  IBV_QP_MAX_DEST_RD_ATOMIC = 1 << 17,
  IBV_QP_CAP = 1 << 19,
  IBV_QP_DEST_QPN = 1 << 20,
};

enum ibv_port_state { IBV_PORT_ACTIVE = 4 };
enum { IBV_LINK_LAYER_INFINIBAND = 1, IBV_LINK_LAYER_ETHERNET = 2 };

struct ibv_global_route {
  union ibv_gid dgid;
  uint32_t flow_label;
  uint8_t sgid_index;
  uint8_t hop_limit;
  uint8_t traffic_class;
};

struct ibv_ah_attr {
  struct ibv_global_route grh;
  uint16_t dlid;
  uint8_t sl;
  uint8_t src_path_bits;
  uint8_t static_rate;
  uint8_t is_global;
  uint8_t port_num;
};

struct ibv_qp_cap {
  uint32_t max_send_wr;
  uint32_t max_recv_wr;
  uint32_t max_send_sge;
  uint32_t max_recv_sge;
  uint32_t max_inline_data;
};

struct ibv_qp_init_attr {
  void *qp_context;
  struct ibv_cq *send_cq;
  struct ibv_cq *recv_cq;
  struct ibv_srq *srq;
  struct ibv_qp_cap cap;
  int qp_type; /* enum ibv_qp_type */
  int sq_sig_all;
};

struct ibv_qp_attr {
  int qp_state;     /* enum ibv_qp_state */
  int cur_qp_state; /* enum ibv_qp_state */
  int path_mtu;     /* enum ibv_mtu */
  int path_mig_state;
  uint32_t qkey;
  uint32_t rq_psn;
  uint32_t sq_psn;
  uint32_t dest_qp_num;
  unsigned int qp_access_flags;
  struct ibv_qp_cap cap;
  struct ibv_ah_attr ah_attr;
  struct ibv_ah_attr alt_ah_attr;
  uint16_t pkey_index;
  uint16_t alt_pkey_index;
  uint8_t en_sqd_async_notify;
  uint8_t sq_draining;
  uint8_t max_rd_atomic;
  uint8_t max_dest_rd_atomic;
  uint8_t min_rnr_timer;
  uint8_t port_num;
  uint8_t timeout;
  uint8_t retry_cnt;
  uint8_t rnr_retry;
  uint8_t alt_port_num;
  uint8_t alt_timeout;
  uint32_t rate_limit;
};

struct ibv_port_attr {
  int state;      /* enum ibv_port_state */
  int max_mtu;    /* enum ibv_mtu */
  int active_mtu; /* enum ibv_mtu */
  int gid_tbl_len;
  uint32_t port_cap_flags;
  uint32_t max_msg_sz;
  uint32_t bad_pkey_cntr;
  uint32_t qkey_viol_cntr;
  uint16_t pkey_tbl_len;
  uint16_t lid;
  uint16_t sm_lid;
  uint8_t lmc;
  uint8_t max_vl_num;
  uint8_t sm_sl;
  uint8_t subnet_timeout;
  uint8_t init_type_reply;
  uint8_t active_width;
  uint8_t active_speed;
  uint8_t phys_state;
  uint8_t link_layer;
  uint8_t flags;
  uint16_t port_cap_flags2;
  uint32_t active_speed_ex;
  /* Slack so newer rdma-core revisions writing extra trailing fields
   * stay within our allocation. */
  uint8_t reserved_[64];
};

struct ibv_sge {
  uint64_t addr;
  uint32_t length;
  uint32_t lkey;
};

struct ibv_send_wr {
  uint64_t wr_id;
  struct ibv_send_wr *next;
  struct ibv_sge *sg_list;
  int num_sge;
  int opcode; /* enum ibv_wr_opcode */
  unsigned int send_flags;
  union {
    uint32_t imm_data;
    uint32_t invalidate_rkey;
  };
  union {
    struct {
      uint64_t remote_addr;
      uint32_t rkey;
    } rdma;
    struct {
      uint64_t remote_addr;
      uint64_t compare_add;
      uint64_t swap;
      uint32_t rkey;
    } atomic;
    struct {
      struct ibv_ah *ah;
      uint32_t remote_qpn;
      uint32_t remote_qkey;
    } ud;
  } wr;
  union {
    struct {
      uint32_t remote_srqn;
    } xrc;
  } qp_type;
  union {
    struct {
      struct ibv_mw *mw;
      uint32_t rkey;
      uint8_t bind_info_[40]; /* struct ibv_mw_bind_info, unused here */
    } bind_mw;
    struct {
      void *hdr;
      uint16_t hdr_sz;
      uint16_t mss;
    } tso;
  };
};

struct ibv_recv_wr {
  uint64_t wr_id;
  struct ibv_recv_wr *next;
  struct ibv_sge *sg_list;
  int num_sge;
};

struct ibv_wc {
  uint64_t wr_id;
  int status; /* enum ibv_wc_status */
  int opcode; /* enum ibv_wc_opcode */
  uint32_t vendor_err;
  uint32_t byte_len;
  union {
    uint32_t imm_data;
    uint32_t invalidated_rkey;
  };
  uint32_t qp_num;
  uint32_t src_qp;
  unsigned int wc_flags;
  uint16_t pkey_index;
  uint16_t slid;
  uint8_t sl;
  uint8_t dlid_path_bits;
};

struct ibv_pd {
  struct ibv_context *context;
  uint32_t handle;
};

struct ibv_mr {
  struct ibv_context *context;
  struct ibv_pd *pd;
  void *addr;
  size_t length;
  uint32_t handle;
  uint32_t lkey;
  uint32_t rkey;
};

struct ibv_cq {
  struct ibv_context *context;
  struct ibv_comp_channel *channel;
  void *cq_context;
  uint32_t handle;
  int cqe;
  pthread_mutex_t mutex;
  pthread_cond_t cond;
  uint32_t comp_events_completed;
  uint32_t async_events_completed;
};

struct ibv_qp {
  struct ibv_context *context;
  void *qp_context;
  struct ibv_pd *pd;
  struct ibv_cq *send_cq;
  struct ibv_cq *recv_cq;
  struct ibv_srq *srq;
  uint32_t handle;
  uint32_t qp_num;
  int state;   /* enum ibv_qp_state */
  int qp_type; /* enum ibv_qp_type */
  pthread_mutex_t mutex;
  pthread_cond_t cond;
  uint32_t events_completed;
};

/* The legacy ops table embedded in ibv_context. The named non-compat
 * entries (poll_cq, post_send, post_recv) are the device-driver fast
 * paths; their slot positions are ABI-frozen. */
struct ibv_context_ops {
  void *(*_compat_query_device)(void);
  int (*_compat_query_port)(struct ibv_context *, uint8_t, void *);
  void *(*_compat_alloc_pd)(void);
  void *(*_compat_dealloc_pd)(void);
  void *(*_compat_reg_mr)(void);
  void *(*_compat_rereg_mr)(void);
  void *(*_compat_dereg_mr)(void);
  void *(*alloc_mw)(void);
  void *(*bind_mw)(void);
  void *(*dealloc_mw)(void);
  void *(*_compat_create_cq)(void);
  int (*poll_cq)(struct ibv_cq *, int, struct ibv_wc *);
  int (*req_notify_cq)(struct ibv_cq *, int);
  void *(*_compat_cq_event)(void);
  void *(*_compat_resize_cq)(void);
  void *(*_compat_destroy_cq)(void);
  void *(*_compat_create_srq)(void);
  void *(*_compat_modify_srq)(void);
  void *(*_compat_query_srq)(void);
  void *(*_compat_destroy_srq)(void);
  int (*post_srq_recv)(struct ibv_srq *, struct ibv_recv_wr *,
                       struct ibv_recv_wr **);
  void *(*_compat_create_qp)(void);
  void *(*_compat_query_qp)(void);
  void *(*_compat_modify_qp)(void);
  void *(*_compat_destroy_qp)(void);
  int (*post_send)(struct ibv_qp *, struct ibv_send_wr *,
                   struct ibv_send_wr **);
  int (*post_recv)(struct ibv_qp *, struct ibv_recv_wr *,
                   struct ibv_recv_wr **);
  void *(*_compat_create_ah)(void);
  void *(*_compat_destroy_ah)(void);
  void *(*_compat_attach_mcast)(void);
  void *(*_compat_detach_mcast)(void);
  void *(*_compat_async_event)(void);
};

struct ibv_context {
  struct ibv_device *device;
  struct ibv_context_ops ops;
  int cmd_fd;
  int async_fd;
  int num_comp_vectors;
  pthread_mutex_t mutex;
  void *abi_compat;
};

/* dlsym'd entry points (all exported by libibverbs.so.1). */
typedef struct ibv_device **(*fn_ibv_get_device_list)(int *);
typedef void (*fn_ibv_free_device_list)(struct ibv_device **);
typedef const char *(*fn_ibv_get_device_name)(struct ibv_device *);
typedef struct ibv_context *(*fn_ibv_open_device)(struct ibv_device *);
typedef int (*fn_ibv_close_device)(struct ibv_context *);
typedef struct ibv_pd *(*fn_ibv_alloc_pd)(struct ibv_context *);
typedef int (*fn_ibv_dealloc_pd)(struct ibv_pd *);
typedef struct ibv_mr *(*fn_ibv_reg_mr)(struct ibv_pd *, void *, size_t, int);
typedef struct ibv_mr *(*fn_ibv_reg_dmabuf_mr)(struct ibv_pd *, uint64_t,
                                               size_t, uint64_t, int, int);
typedef int (*fn_ibv_dereg_mr)(struct ibv_mr *);
typedef struct ibv_cq *(*fn_ibv_create_cq)(struct ibv_context *, int, void *,
                                           struct ibv_comp_channel *, int);
typedef int (*fn_ibv_destroy_cq)(struct ibv_cq *);
typedef struct ibv_qp *(*fn_ibv_create_qp)(struct ibv_pd *,
                                           struct ibv_qp_init_attr *);
typedef int (*fn_ibv_modify_qp)(struct ibv_qp *, struct ibv_qp_attr *, int);
typedef int (*fn_ibv_destroy_qp)(struct ibv_qp *);
typedef int (*fn_ibv_query_port)(struct ibv_context *, uint8_t,
                                 struct ibv_port_attr *);
typedef int (*fn_ibv_query_gid)(struct ibv_context *, uint8_t, int,
                                union ibv_gid *);

}  // extern "C"

#endif  // TDR_VERBS_ABI_H_
