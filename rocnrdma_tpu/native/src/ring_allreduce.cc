// Ring allreduce over the engine: reduce-scatter + all-gather.
//
// The reference stops at the transport (its consumers were MPI apps on
// IB Verbs, README.md:64); this file is the in-framework consumer that
// BASELINE.md configs 3-4 require — the collective that cross-slice
// gradient sync rides. Buffers are registered once per (buffer, ring)
// pair and cached, preserving the reference's front-loaded-registration
// invariant: steady-state steps post work requests only.

#include <cstdint>
#include <cstring>
#include <mutex>
#include <unordered_map>
#include <vector>

#include "common.h"
#include "tdr/tdr.h"

namespace {

size_t dtype_size(int dt) {
  switch (dt) {
    case TDR_DT_F32:
    case TDR_DT_I32:
      return 4;
    case TDR_DT_F64:
    case TDR_DT_I64:
      return 8;
    case TDR_DT_BF16:
      return 2;
    default:
      return 0;
  }
}

float bf16_to_f32(uint16_t v) {
  uint32_t u = static_cast<uint32_t>(v) << 16;
  float f;
  memcpy(&f, &u, 4);
  return f;
}

uint16_t f32_to_bf16(float f) {
  uint32_t u;
  memcpy(&u, &f, 4);
  // round-to-nearest-even, matching TPU bf16 semantics
  uint32_t rounding = 0x7fff + ((u >> 16) & 1);
  return static_cast<uint16_t>((u + rounding) >> 16);
}

template <typename T>
void reduce_typed(T *dst, const T *src, size_t n, int op) {
  switch (op) {
    case TDR_RED_SUM:
      for (size_t i = 0; i < n; i++) dst[i] += src[i];
      break;
    case TDR_RED_MAX:
      for (size_t i = 0; i < n; i++)
        if (src[i] > dst[i]) dst[i] = src[i];
      break;
    case TDR_RED_MIN:
      for (size_t i = 0; i < n; i++)
        if (src[i] < dst[i]) dst[i] = src[i];
      break;
  }
}

void reduce_bf16(uint16_t *dst, const uint16_t *src, size_t n, int op) {
  for (size_t i = 0; i < n; i++) {
    float a = bf16_to_f32(dst[i]), b = bf16_to_f32(src[i]);
    float r = a;
    switch (op) {
      case TDR_RED_SUM:
        r = a + b;
        break;
      case TDR_RED_MAX:
        r = b > a ? b : a;
        break;
      case TDR_RED_MIN:
        r = b < a ? b : a;
        break;
    }
    dst[i] = f32_to_bf16(r);
  }
}

void reduce_any(void *dst, const void *src, size_t n, int dt, int op) {
  switch (dt) {
    case TDR_DT_F32:
      reduce_typed(static_cast<float *>(dst), static_cast<const float *>(src),
                   n, op);
      break;
    case TDR_DT_F64:
      reduce_typed(static_cast<double *>(dst),
                   static_cast<const double *>(src), n, op);
      break;
    case TDR_DT_I32:
      reduce_typed(static_cast<int32_t *>(dst),
                   static_cast<const int32_t *>(src), n, op);
      break;
    case TDR_DT_I64:
      reduce_typed(static_cast<int64_t *>(dst),
                   static_cast<const int64_t *>(src), n, op);
      break;
    case TDR_DT_BF16:
      reduce_bf16(static_cast<uint16_t *>(dst),
                  static_cast<const uint16_t *>(src), n, op);
      break;
  }
}

}  // namespace

struct tdr_ring {
  tdr_engine *eng;
  tdr_qp *left;   // receive from
  tdr_qp *right;  // send to
  int rank;
  int world;
  std::vector<char> tmp;
  tdr_mr *tmp_mr = nullptr;
  // MRs for buffers the CALLER promised stable (tdr_ring_register) —
  // the front-loaded-registration fast path. Arbitrary buffers are
  // registered per call instead: a VA-keyed implicit cache would hand
  // out stale pins when an address gets recycled by the allocator
  // (the underlying physical pages of a dead buffer, not the new one).
  std::unordered_map<uint64_t, tdr_mr *> registered;
  std::mutex mu;

  // Returns the MR and whether it is borrowed (cached) or owned by
  // this call (must be deregistered before returning).
  tdr_mr *data_mr(void *base, size_t len, bool *owned) {
    uint64_t key = reinterpret_cast<uint64_t>(base);
    auto it = registered.find(key);
    if (it != registered.end() && tdr_mr_len(it->second) >= len) {
      *owned = false;
      return it->second;
    }
    *owned = true;
    return tdr_reg_mr(eng, base, len, 0);
  }

  tdr_mr *scratch(size_t len) {
    if (tmp.size() < len || !tmp_mr) {
      if (tmp_mr) {
        tdr_dereg_mr(tmp_mr);
        tmp_mr = nullptr;
      }
      tmp.resize(len);
      tmp_mr = tdr_reg_mr(eng, tmp.data(), tmp.size(), 0);
    }
    return tmp_mr;
  }
};

extern "C" {

tdr_ring *tdr_ring_create(tdr_engine *e, tdr_qp *left, tdr_qp *right,
                          int rank, int world) {
  if (!e || !left || !right || world < 2 || rank < 0 || rank >= world) {
    tdr::set_error("ring_create: bad topology");
    return nullptr;
  }
  auto *r = new tdr_ring();
  r->eng = e;
  r->left = left;
  r->right = right;
  r->rank = rank;
  r->world = world;
  return r;
}

void tdr_ring_destroy(tdr_ring *r) {
  if (!r) return;
  for (auto &kv : r->registered) tdr_dereg_mr(kv.second);
  if (r->tmp_mr) tdr_dereg_mr(r->tmp_mr);
  delete r;
}

// Pre-register a buffer whose lifetime the caller guarantees to
// outlast the ring (or until tdr_ring_unregister). Steady-state
// allreduces on it then post work requests only — the front-loaded
// registration invariant of the reference (SURVEY.md §3.3).
int tdr_ring_register(tdr_ring *r, void *base, size_t len) {
  if (!r || !base || !len) {
    tdr::set_error("ring_register: bad args");
    return -1;
  }
  std::lock_guard<std::mutex> g(r->mu);
  uint64_t key = reinterpret_cast<uint64_t>(base);
  auto it = r->registered.find(key);
  if (it != r->registered.end()) {
    if (tdr_mr_len(it->second) >= len) return 0;
    tdr_dereg_mr(it->second);
    r->registered.erase(it);
  }
  tdr_mr *mr = tdr_reg_mr(r->eng, base, len, 0);
  if (!mr) return -1;
  r->registered[key] = mr;
  return 0;
}

int tdr_ring_unregister(tdr_ring *r, void *base) {
  if (!r) return -1;
  std::lock_guard<std::mutex> g(r->mu);
  auto it = r->registered.find(reinterpret_cast<uint64_t>(base));
  if (it == r->registered.end()) return -1;
  tdr_dereg_mr(it->second);
  r->registered.erase(it);
  return 0;
}

// Wait for one completion with the given wr_id on qp; other completions
// arriving first are held by the caller loop (each step has at most one
// outstanding send + one recv per QP, so a two-slot check suffices).
static int wait_wr(tdr_qp *qp, uint64_t want_a, uint64_t want_b, int *got_a,
                   int *got_b) {
  while (!(*got_a && *got_b)) {
    tdr_wc wc[2];
    int n = tdr_poll(qp, wc, 2, 30000);
    if (n <= 0) {
      tdr::set_error("ring: poll timeout/failure");
      return -1;
    }
    for (int i = 0; i < n; i++) {
      if (wc[i].status != TDR_WC_SUCCESS) {
        tdr::set_error("ring: completion error status " +
                       std::to_string(wc[i].status));
        return -1;
      }
      if (wc[i].wr_id == want_a) *got_a = 1;
      if (wc[i].wr_id == want_b) *got_b = 1;
    }
  }
  return 0;
}

int tdr_ring_allreduce(tdr_ring *r, void *data, size_t count, int dtype,
                       int red_op) {
  if (!r || !data) {
    tdr::set_error("ring_allreduce: null ring or data");
    return -1;
  }
  size_t esz = dtype_size(dtype);
  if (esz == 0) {
    tdr::set_error("ring: bad dtype");
    return -1;
  }
  if (count == 0) return 0;
  std::lock_guard<std::mutex> g(r->mu);
  const int world = r->world;
  const size_t nbytes = count * esz;

  // Segment layout: world segments, first `rem` get one extra element.
  std::vector<size_t> seg_off(world), seg_len(world);
  size_t base = count / world, rem = count % world;
  size_t off = 0;
  for (int i = 0; i < world; i++) {
    seg_off[i] = off * esz;
    seg_len[i] = (base + (static_cast<size_t>(i) < rem ? 1 : 0)) * esz;
    off += base + (static_cast<size_t>(i) < rem ? 1 : 0);
  }
  size_t max_seg = 0;
  for (int i = 0; i < world; i++)
    if (seg_len[i] > max_seg) max_seg = seg_len[i];

  bool owned = false;
  tdr_mr *dmr = r->data_mr(data, nbytes, &owned);
  tdr_mr *tmr = max_seg ? r->scratch(max_seg) : nullptr;
  if (!dmr || (max_seg && !tmr)) {
    if (owned && dmr) tdr_dereg_mr(dmr);
    return -1;
  }
  struct OwnedGuard {
    tdr_mr *mr;
    bool active;
    ~OwnedGuard() {
      if (active && mr) tdr_dereg_mr(mr);
    }
  } guard{dmr, owned};
  (void)guard;

  char *cdata = static_cast<char *>(data);
  const bool same_qp = (r->left == r->right);
  const uint64_t WR_SEND = 0x53454e44, WR_RECV = 0x52454356;

  // Phase 1: reduce-scatter. After step s, segment (rank-s-1) holds the
  // partial sum of s+2 ranks; after world-1 steps each rank owns the
  // full reduction of segment (rank+1) mod world.
  for (int s = 0; s < world - 1; s++) {
    int send_seg = ((r->rank - s) % world + world) % world;
    int recv_seg = ((r->rank - s - 1) % world + world) % world;
    if (seg_len[recv_seg] &&
        tdr_post_recv(r->left, tmr, 0, seg_len[recv_seg], WR_RECV) != 0)
      return -1;
    if (seg_len[send_seg] &&
        tdr_post_send(r->right, dmr, seg_off[send_seg], seg_len[send_seg],
                      WR_SEND) != 0)
      return -1;
    int got_s = seg_len[send_seg] ? 0 : 1, got_r = seg_len[recv_seg] ? 0 : 1;
    if (same_qp) {
      if (wait_wr(r->left, WR_SEND, WR_RECV, &got_s, &got_r) != 0) return -1;
    } else {
      int one = 1;
      if (!got_r && wait_wr(r->left, WR_RECV, WR_RECV, &got_r, &one) != 0)
        return -1;
      one = 1;
      if (!got_s && wait_wr(r->right, WR_SEND, WR_SEND, &got_s, &one) != 0)
        return -1;
    }
    if (seg_len[recv_seg])
      reduce_any(cdata + seg_off[recv_seg], r->tmp.data(),
                 seg_len[recv_seg] / esz, dtype, red_op);
  }

  // Phase 2: all-gather — fully-reduced segments circulate; received
  // bytes land directly in the data MR (no scratch, no extra copy).
  for (int s = 0; s < world - 1; s++) {
    int send_seg = ((r->rank + 1 - s) % world + world) % world;
    int recv_seg = ((r->rank - s) % world + world) % world;
    if (seg_len[recv_seg] &&
        tdr_post_recv(r->left, dmr, seg_off[recv_seg], seg_len[recv_seg],
                      WR_RECV) != 0)
      return -1;
    if (seg_len[send_seg] &&
        tdr_post_send(r->right, dmr, seg_off[send_seg], seg_len[send_seg],
                      WR_SEND) != 0)
      return -1;
    int got_s = seg_len[send_seg] ? 0 : 1, got_r = seg_len[recv_seg] ? 0 : 1;
    if (same_qp) {
      if (wait_wr(r->left, WR_SEND, WR_RECV, &got_s, &got_r) != 0) return -1;
    } else {
      int one = 1;
      if (!got_r && wait_wr(r->left, WR_RECV, WR_RECV, &got_r, &one) != 0)
        return -1;
      one = 1;
      if (!got_s && wait_wr(r->right, WR_SEND, WR_SEND, &got_s, &one) != 0)
        return -1;
    }
  }
  return 0;
}

}  // extern "C"
