// Ring allreduce over the engine: reduce-scatter + all-gather.
//
// The reference stops at the transport (its consumers were MPI apps on
// IB Verbs, README.md:64); this file is the in-framework consumer that
// BASELINE.md configs 3-4 require — the collective that cross-slice
// gradient sync rides. Buffers are registered once per (buffer, ring)
// pair and cached, preserving the reference's front-loaded-registration
// invariant: steady-state steps post work requests only.
//
// Large segments are split into chunks (TDR_RING_CHUNK, default 4 MiB)
// with a small window of pre-posted receives, so the wire transfer of
// chunk i+1 overlaps the reduction of chunk i and the link never idles
// behind the ALU.
//
// Multi-channel striping (tdr_ring_create_channels): the ring may hold
// TDR_RING_CHANNELS independent QPs per neighbor; every striped
// schedule routes chunk i over channel i % channels, so the wire
// transfer, seal verification, and fold of CONSECUTIVE chunks run on
// independent progress engines instead of serializing on one QP's
// thread. FIFO recv matching holds per channel (both sides stripe by
// the same index rule, and channel c here is connected to channel c
// on the neighbor by bootstrap construction); cross-channel completion
// order is arbitrary, so the schedules track per-stream done-masks and
// use the in-order completed PREFIX wherever a dependency needs
// "everything before me landed". Scratch-window folds are handed to
// the fold-offload pool (TDR_FOLD_THREADS, copy_pool.cc) so the poll
// loop keeps posting while predecessors fold; the scratch window is
// sized at two slots per channel — a chunk can land while its
// predecessor on the same channel is still folding.

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <functional>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#include "common.h"
#include "tdr/tdr.h"

namespace {

// 4 MiB (was 8): striping needs at least `channels` chunks per ring
// segment to engage, and the finer grain pipelines land/fold/verify
// better on every schedule — measured on the bench host: world-2
// +25% median, world-4 best-median config (chunks below ~1 MiB start
// paying per-frame overhead instead). TDR_RING_CHUNK still overrides.
constexpr size_t kDefaultChunk = 4u << 20;
constexpr int kWindow = 4;  // pre-posted recv slots per step
// Cap on work requests in flight per direction, below the verbs
// backend's QP depth (max_send_wr/max_recv_wr = 512) with headroom —
// tiny TDR_RING_CHUNK values otherwise overflow ibv_post_* on large
// segments (the emu backend's unbounded queues would hide that).
constexpr size_t kMaxOutstanding = 256;

// Recv-window bound for reduce-recvs on a QP: engines that stage
// reduce-on-receive through bounded slots (verbs) advertise a budget
// via tdr_qp_rr_window; 0 means unbounded (emu folds off the wire).
size_t reduce_recv_window(tdr_qp *qp) {
  size_t w = tdr_qp_rr_window(qp);
  return w ? std::min(w, kMaxOutstanding) : kMaxOutstanding;
}

size_t ring_chunk_bytes() {
  const char *env = getenv("TDR_RING_CHUNK");
  if (env && *env) {
    long long v = atoll(env);
    if (v >= 4096) return static_cast<size_t>(v);
  }
  return kDefaultChunk;
}

using tdr::dtype_size;
using tdr::reduce_any;
using tdr::ring_timeout_ms;

// Human-readable WC status for the completion-error messages: the
// Python taxonomy keys off both the numeric status and message
// markers, and "integrity" must be visible to operators without a
// decoder ring.
const char *wc_status_name(int st) {
  switch (st) {
    case TDR_WC_SUCCESS:
      return "success";
    case TDR_WC_REM_ACCESS_ERR:
      return "rem_access_err";
    case TDR_WC_LOC_ACCESS_ERR:
      return "loc_access_err";
    case TDR_WC_FLUSH_ERR:
      return "flush_err";
    case TDR_WC_GENERAL_ERR:
      return "general_err";
    case TDR_WC_INTEGRITY_ERR:
      return "integrity_err";
    default:
      return "unknown";
  }
}

std::string wc_status_label(int st) {
  return std::to_string(st) + " (" + wc_status_name(st) + ")";
}

// wr_id tags for the pipeline: high 16 bits the kind, low bits the
// chunk index, so one poll loop can route recv completions (in posted
// order) and send acks (order-independent, only counted).
constexpr uint64_t kWrRecv = 0x5245ull << 48;
constexpr uint64_t kWrSend = 0x5345ull << 48;
constexpr uint64_t kWrKindMask = 0xffffull << 48;

// Flight recorder: per-collective call ordinal (process-wide) so
// ring_begin/ring_end pair up in the exported timeline.
std::atomic<uint64_t> g_ring_call_seq{0};

// Sharded progress engine accounting (registry progress.*): shard
// threads launched, idle wakeups, completions consumed on shards.
std::atomic<uint64_t> g_prog_shards{0};
std::atomic<uint64_t> g_prog_wakeups{0};
std::atomic<uint64_t> g_prog_wc{0};

// Bracket one collective call: RING_BEGIN/RING_END events plus the
// whole-collective latency and bandwidth histograms. Zero-cost when
// telemetry is off (the ctor takes the one-branch guard and leaves
// every field 0). Return paths route through finish(rc) to record
// the true status; the destructor is the backstop — a path that
// skips finish still emits a (failed) RING_END, so begin/end events
// always pair in exported timelines.
struct RingTelScope {
  uint16_t eng = 0;
  uint64_t seq = 0;
  uint64_t nbytes = 0;
  uint64_t t0 = 0;
  uint64_t coll = 0;
  bool done = false;
  RingTelScope(tdr_ring *r, uint64_t bytes);
  void record(int rc) {
    done = true;
    uint64_t dt_ns = tdr::tel_now_ns() - t0;
    tdr::tel_emit(TDR_TEL_RING_END, eng, 0, seq, rc == 0 ? 0 : 1, coll);
    tdr::tel_hist_add(TDR_HIST_RING_LAT_US, dt_ns / 1000);
    if (rc == 0 && dt_ns > 0)
      tdr::tel_hist_add(TDR_HIST_RING_MBPS, nbytes * 1000 / dt_ns);
  }
  int finish(int rc) {
    if (t0 && !done) record(rc);
    return rc;
  }
  ~RingTelScope() {
    if (t0 && !done) record(-1);
  }
};

}  // namespace

namespace tdr {

// Resolved progress-shard count for a `channels`-channel ring
// (TDR_PROGRESS_SHARDS). 0 = the legacy single-poll loop. Default:
// one shard per channel — the DMA-streaming model of one progress
// engine per buffer chain — capped at the host's usable cores, and 0
// on a 1-core host: shards win by polling in parallel with posting,
// and a single core can only interleave them with context switches
// (measured 5-10% WORSE than the inline loop — the same 1-core rule
// the fold pool applies). Per-PROCESS execution strategy, never
// negotiated and never in the schedule digest: any mix of shard
// counts across ranks is wire-compatible and bitwise-identical.
// Parsed per collective (getenv is nanoseconds next to an MB-scale
// collective) so tests may flip the knob between worlds.
size_t progress_shards_for(size_t channels) {
  if (channels < 1) channels = 1;
  const char *env = getenv("TDR_PROGRESS_SHARDS");
  if (env && *env) {
    long v = atol(env);
    if (v <= 0) return 0;
    return std::min(static_cast<size_t>(v), channels);
  }
  size_t cores = usable_cores();
  if (cores <= 1) return 0;
  return std::min(channels, cores);
}

void progress_counters(uint64_t *shards, uint64_t *wakeups, uint64_t *wc) {
  if (shards) *shards = g_prog_shards.load(std::memory_order_relaxed);
  if (wakeups) *wakeups = g_prog_wakeups.load(std::memory_order_relaxed);
  if (wc) *wc = g_prog_wc.load(std::memory_order_relaxed);
}

}  // namespace tdr

struct tdr_ring {
  tdr_engine *eng;
  // Channel 0 aliases: the chain collectives (reduce/broadcast/
  // alltoall — inherently order-dependent store-and-forward pipelines)
  // and the digest-era callers run on channel 0; the striped
  // schedules use the full vectors.
  tdr_qp *left;   // receive from
  tdr_qp *right;  // send to
  std::vector<tdr_qp *> lefts, rights;  // lefts[c] pairs with the
                                        // neighbor's rights[c]
  int rank;
  int world;
  size_t chunk = kDefaultChunk;
  int last_sched = TDR_SCHED_NONE;
  std::vector<char> tmp;
  tdr_mr *tmp_mr = nullptr;
  // MRs for buffers the CALLER promised stable (tdr_ring_register) —
  // the front-loaded-registration fast path. Arbitrary buffers are
  // registered per call instead: a VA-keyed implicit cache would hand
  // out stale pins when an address gets recycled by the allocator
  // (the underlying physical pages of a dead buffer, not the new one).
  std::unordered_map<uint64_t, tdr_mr *> registered;
  // Keys of ADOPTED entries (tdr_ring_adopt_mr): the MR is owned by
  // the caller (a dma-buf MR over device memory); never dereg it here.
  std::unordered_set<uint64_t> borrowed;
  std::mutex mu;

  // Returns the MR and whether it is borrowed (cached) or owned by
  // this call (must be deregistered before returning).
  tdr_mr *data_mr(void *base, size_t len, bool *owned) {
    uint64_t key = reinterpret_cast<uint64_t>(base);
    auto it = registered.find(key);
    if (it != registered.end() && tdr_mr_len(it->second) >= len) {
      *owned = false;
      return it->second;
    }
    *owned = true;
    return tdr_reg_mr(eng, base, len, 0);
  }

  tdr_mr *scratch(size_t len) {
    if (tmp.size() < len || !tmp_mr) {
      if (tmp_mr) {
        tdr_dereg_mr(tmp_mr);
        tmp_mr = nullptr;
      }
      tmp.resize(len);
      tmp_mr = tdr_reg_mr(eng, tmp.data(), tmp.size(), 0);
    }
    return tmp_mr;
  }

  // Collective trace ids (fleet tracing). next_coll: the id the
  // CALLER stamped for the next collective (tdr_ring_set_coll;
  // sticky, captured at blocking entry or async submission).
  // auto_coll: fallback counter for rings whose caller never stamps —
  // auto ids carry bit 63 so the two id spaces never collide.
  // cur_coll: the id of the collective currently RUNNING on this ring
  // (what the fold/fold_off event sites read).
  std::atomic<uint64_t> next_coll{0};
  std::atomic<uint64_t> auto_coll{0};
  std::atomic<uint64_t> cur_coll{0};

  // Async driver (tdr_ring_start): one dedicated thread per ring,
  // spawned at the first start and joined at destroy, executing
  // queued ops strictly in submission order — submission order IS the
  // SPMD contract, and serializing on this one thread keeps the wire
  // sequence identical to back-to-back blocking calls. After any
  // failure the driver fails the remaining queue fast (the ring is
  // suspect; the recovery ladder replaces it at rebuild) instead of
  // posting into a broken ring and eating a stall deadline per op.
  std::mutex amu;
  std::condition_variable acv;
  std::deque<tdr_ring_op *> aq;
  std::thread adrv;
  bool adrv_up = false;   // under amu
  bool astop = false;     // under amu
  bool afailed = false;   // under amu: sticky for this ring's lifetime
  std::string aerr;       // under amu
};

// Handle for one nonblocking collective (tdr_ring_start). Owned by
// the caller; the driver only writes it under op->mu and never
// touches it after marking done, so freeing a COMPLETED op is race-
// free. tdr_ring_op_free on a pending op blocks until completion
// (every op terminates — the stall deadline bounds a wedged ring).
struct tdr_ring_op {
  void *data = nullptr;
  size_t count = 0;
  int dtype = 0;
  int red_op = 0;
  // Which collective the driver runs for this op: the async surface
  // covers the allreduce AND its standalone phases (the hierarchical
  // schedule chains reduce-scatter → delegate allreduce → all-gather
  // through these handles), plus the int8 wire-compressed allreduce.
  enum {
    kAllreduce = 0,
    kReduceScatter = 1,
    kAllGather = 2,
    kAllreduceQ8 = 3
  };
  int kind = kAllreduce;
  // kAllreduceQ8 only: the per-bucket symmetric scale the caller
  // quantized with, and the f32 output buffer the dequantized result
  // lands in (both ride the op because the driver runs it later).
  float scale_in = 0.0f;
  float *f32_out = nullptr;
  // Collective trace id captured at SUBMISSION (the caller stamps the
  // ring, then starts): the driver re-arms it when the op actually
  // runs, so a queue of bucketed ops keeps per-op ids whatever the
  // interleaving of set_coll calls for later submissions.
  uint64_t coll = 0;
  std::mutex mu;
  std::condition_variable cv;
  bool done = false;  // under mu
  int rc = 0;         // under mu
  std::string err;    // under mu
};

namespace {

// Driver-forced collective id: the async driver hands each op's
// captured id to the blocking collective it runs on its own thread —
// a thread-local, so it can never race a caller thread's
// set_coll/start pair for a LATER op.
thread_local uint64_t t_forced_coll = 0;

// Resolve the collective trace id for a collective that is starting:
// the driver's forced id, else the caller-stamped next_coll, else an
// auto id (bit 63 set — disjoint from caller-stamped ids).
uint64_t take_coll(tdr_ring *r) {
  uint64_t v = t_forced_coll;
  if (v) {
    t_forced_coll = 0;
    return v;
  }
  v = r->next_coll.load(std::memory_order_relaxed);
  if (v) return v;
  return (1ull << 63) |
         (r->auto_coll.fetch_add(1, std::memory_order_relaxed) + 1);
}

void op_complete(tdr_ring_op *op, int rc, const std::string &err) {
  {
    std::lock_guard<std::mutex> g(op->mu);
    op->rc = rc;
    op->err = err;
    op->done = true;
  }
  op->cv.notify_all();
}

// The ring's async driver thread: pop in submission order, run the
// blocking collective, publish the result on the handle. Thread-local
// errors are bridged onto the HANDLE here — the waiting thread could
// never read this thread's tdr_last_error slot.
void async_driver(tdr_ring *r) {
  for (;;) {
    tdr_ring_op *op = nullptr;
    bool failed = false;
    std::string ferr;
    {
      std::unique_lock<std::mutex> lk(r->amu);
      r->acv.wait(lk, [&] { return r->astop || !r->aq.empty(); });
      if (r->aq.empty()) return;  // astop and drained
      op = r->aq.front();
      r->aq.pop_front();
      failed = r->afailed;
      if (failed) ferr = r->aerr;
    }
    if (failed) {
      op_complete(op, -1,
                  "ring async: aborted after earlier failure (" + ferr +
                      ")");
      continue;
    }
    int rc;
    t_forced_coll = op->coll;  // submission-time id, re-armed at run
    switch (op->kind) {
      case tdr_ring_op::kReduceScatter:
        rc = tdr_ring_reduce_scatter(r, op->data, op->count, op->dtype,
                                     op->red_op, nullptr, nullptr);
        break;
      case tdr_ring_op::kAllGather:
        rc = tdr_ring_all_gather(r, op->data, op->count, op->dtype);
        break;
      case tdr_ring_op::kAllreduceQ8:
        rc = tdr_ring_allreduce_q8(r, op->data, op->count, op->scale_in,
                                   op->f32_out);
        break;
      default:
        rc = tdr_ring_allreduce(r, op->data, op->count, op->dtype,
                                op->red_op);
    }
    std::string err = rc == 0 ? std::string() : tdr::get_error();
    if (rc != 0) {
      std::lock_guard<std::mutex> g(r->amu);
      r->afailed = true;
      r->aerr = err;
    }
    op_complete(op, rc, err);
  }
}

// Stop the driver and fail whatever it never started. Pending ops are
// completed with a retryable-classed error (teardown mid-flight is a
// transient, exactly like a connection drop) — never silently
// dropped, so a waiting thread always wakes.
void async_stop(tdr_ring *r) {
  std::deque<tdr_ring_op *> orphans;
  bool join = false;
  {
    std::lock_guard<std::mutex> g(r->amu);
    r->astop = true;
    orphans.swap(r->aq);
    join = r->adrv_up;
  }
  r->acv.notify_all();
  for (tdr_ring_op *op : orphans)
    op_complete(op, -1,
                "ring destroyed (connection down for pending async op)");
  if (join) {
    r->adrv.join();
    std::lock_guard<std::mutex> g(r->amu);
    r->adrv_up = false;
  }
}

}  // namespace

namespace {
RingTelScope::RingTelScope(tdr_ring *r, uint64_t bytes) {
  if (!tdr::tel_on()) return;
  eng = reinterpret_cast<tdr::Engine *>(r->eng)->tel_id;
  seq = g_ring_call_seq.fetch_add(1, std::memory_order_relaxed) + 1;
  nbytes = bytes;
  // Resolve and propagate the collective trace id: the ring remembers
  // it for the fold event sites, and every neighbor QP's posting path
  // (and, FEAT_COLL_ID negotiated, its outbound frame headers) stamps
  // it until the next collective re-stamps. One store per QP per
  // collective — noise next to the MB-scale transfers it labels.
  coll = take_coll(r);
  r->cur_coll.store(coll, std::memory_order_relaxed);
  for (tdr_qp *q : r->lefts)
    reinterpret_cast<tdr::Qp *>(q)->cur_coll.store(
        coll, std::memory_order_relaxed);
  for (tdr_qp *q : r->rights)
    reinterpret_cast<tdr::Qp *>(q)->cur_coll.store(
        coll, std::memory_order_relaxed);
  t0 = tdr::tel_now_ns();
  tdr::tel_emit(TDR_TEL_RING_BEGIN, eng, 0, seq, nbytes, coll);
}
}  // namespace

extern "C" {

tdr_ring *tdr_ring_create_channels(tdr_engine *e, tdr_qp *const *lefts,
                                   tdr_qp *const *rights, int channels,
                                   int rank, int world) {
  if (!e || !lefts || !rights || channels < 1 || world < 2 || rank < 0 ||
      rank >= world) {
    tdr::set_error("ring_create: bad topology");
    return nullptr;
  }
  for (int c = 0; c < channels; c++) {
    if (!lefts[c] || !rights[c]) {
      tdr::set_error("ring_create: null channel QP");
      return nullptr;
    }
  }
  // Capability skew across channels would desynchronize a striped
  // schedule mid-collective (chunk i fused, chunk i+1 not): all
  // channels to one neighbor must have negotiated identical features.
  // Same peer + same env makes this true in practice; a half-failed
  // handshake is caught here instead of as a wedged collective.
  for (int c = 1; c < channels; c++) {
    if (tdr_qp_has_recv_reduce(lefts[c]) !=
            tdr_qp_has_recv_reduce(lefts[0]) ||
        tdr_qp_has_send_foldback(rights[c]) !=
            tdr_qp_has_send_foldback(rights[0]) ||
        tdr_qp_has_send_foldback(lefts[c]) !=
            tdr_qp_has_send_foldback(lefts[0]) ||
        tdr_qp_has_fused2(lefts[c]) != tdr_qp_has_fused2(lefts[0]) ||
        tdr_qp_has_fused2(rights[c]) != tdr_qp_has_fused2(rights[0]) ||
        tdr_qp_has_seal(lefts[c]) != tdr_qp_has_seal(lefts[0]) ||
        tdr_qp_has_seal(rights[c]) != tdr_qp_has_seal(rights[0])) {
      tdr::set_error("ring_create: channel " + std::to_string(c) +
                     " negotiated different capabilities than channel 0");
      return nullptr;
    }
  }
  auto *r = new tdr_ring();
  r->eng = e;
  r->lefts.assign(lefts, lefts + channels);
  r->rights.assign(rights, rights + channels);
  r->left = r->lefts[0];
  r->right = r->rights[0];
  r->rank = rank;
  r->world = world;
  r->chunk = ring_chunk_bytes();
  // Stamp link identity on every channel QP: netem riders scope by
  // (lane, rank, peer) and stall/health attribution reads the same
  // labels. Ring neighbors: left = rank-1, right = rank+1 (mod world).
  for (int c = 0; c < channels; c++) {
    tdr_qp_set_link(r->lefts[c], c, rank, (rank + world - 1) % world);
    tdr_qp_set_link(r->rights[c], c, rank, (rank + 1) % world);
  }
  return r;
}

tdr_ring *tdr_ring_create(tdr_engine *e, tdr_qp *left, tdr_qp *right,
                          int rank, int world) {
  return tdr_ring_create_channels(e, &left, &right, 1, rank, world);
}

int tdr_ring_channels(const tdr_ring *r) {
  return r ? static_cast<int>(r->lefts.size()) : 0;
}

size_t tdr_ring_chunk_bytes(void) { return ring_chunk_bytes(); }

void tdr_ring_set_coll(tdr_ring *r, uint64_t coll_id) {
  if (r) r->next_coll.store(coll_id, std::memory_order_relaxed);
}

void tdr_ring_destroy(tdr_ring *r) {
  if (!r) return;
  // Quiesce the async driver FIRST: a queued op must fail fast (its
  // waiter wakes with a retryable error), and a running op must
  // finish before the MRs it posts against are deregistered below.
  async_stop(r);
  for (auto &kv : r->registered)
    if (!r->borrowed.count(kv.first)) tdr_dereg_mr(kv.second);
  if (r->tmp_mr) tdr_dereg_mr(r->tmp_mr);
  delete r;
}

static tdr_ring_op *ring_start_kind(tdr_ring *r, void *data, size_t count,
                                    int dtype, int red_op, int kind,
                                    float scale_in = 0.0f,
                                    float *f32_out = nullptr) {
  if (!r || !data) {
    tdr::set_error("ring_start: null ring or data");
    return nullptr;
  }
  if (dtype_size(dtype) == 0) {
    tdr::set_error("ring: bad dtype");
    return nullptr;
  }
  // The reducing kinds reject the byte-transport dtype; all_gather
  // moves bytes only (no folds) and accepts it, like the blocking API.
  if (dtype == TDR_DT_U8 && kind != tdr_ring_op::kAllGather) {
    tdr::set_error(
        "ring_start: u8 is byte-transport only (no fold semantics)");
    return nullptr;
  }
  // int8 only reduces through the scale-carrying q8 schedule (a plain
  // int8 sum overflows); byte transport via all_gather is fine.
  if (dtype == TDR_DT_I8 && kind != tdr_ring_op::kAllGather &&
      kind != tdr_ring_op::kAllreduceQ8) {
    tdr::set_error("ring_start: i8 reduces only via tdr_ring_start_q8");
    return nullptr;
  }
  auto *op = new tdr_ring_op();
  op->data = data;
  op->count = count;
  op->dtype = dtype;
  op->red_op = red_op;
  op->kind = kind;
  op->scale_in = scale_in;
  op->f32_out = f32_out;
  // Capture the caller-stamped trace id NOW (submission order is the
  // SPMD contract, so submission is when the id binds); the driver
  // re-arms it when the op runs.
  op->coll = take_coll(r);
  {
    std::lock_guard<std::mutex> g(r->amu);
    if (r->astop) {
      tdr::set_error("ring_start: ring is being destroyed");
      delete op;
      return nullptr;
    }
    if (!r->adrv_up) {
      r->adrv = std::thread(async_driver, r);
      r->adrv_up = true;
    }
    r->aq.push_back(op);
  }
  r->acv.notify_all();
  return op;
}

tdr_ring_op *tdr_ring_start(tdr_ring *r, void *data, size_t count,
                            int dtype, int red_op) {
  return ring_start_kind(r, data, count, dtype, red_op,
                         tdr_ring_op::kAllreduce);
}

tdr_ring_op *tdr_ring_start_reduce_scatter(tdr_ring *r, void *data,
                                           size_t count, int dtype,
                                           int red_op) {
  return ring_start_kind(r, data, count, dtype, red_op,
                         tdr_ring_op::kReduceScatter);
}

tdr_ring_op *tdr_ring_start_all_gather(tdr_ring *r, void *data,
                                       size_t count, int dtype) {
  return ring_start_kind(r, data, count, dtype, TDR_RED_SUM,
                         tdr_ring_op::kAllGather);
}

tdr_ring_op *tdr_ring_start_q8(tdr_ring *r, void *q8, size_t count,
                               float scale_in, float *f32_out) {
  if (!f32_out) {
    tdr::set_error("ring_start_q8: null f32_out");
    return nullptr;
  }
  return ring_start_kind(r, q8, count, TDR_DT_I8, TDR_RED_SUM,
                         tdr_ring_op::kAllreduceQ8, scale_in, f32_out);
}

int tdr_ring_owned_segment(tdr_ring *r, size_t count, int dtype,
                           size_t *own_off, size_t *own_len) {
  if (!r) {
    tdr::set_error("ring_owned_segment: null ring");
    return -1;
  }
  size_t esz = dtype_size(dtype);
  if (esz == 0) {
    tdr::set_error("ring: bad dtype");
    return -1;
  }
  // Same layout math the collectives run (seg_layout + the
  // (rank+1) % world ownership convention) — one source of truth, so
  // async callers can never drift from what reduce_scatter leaves.
  size_t base = count / static_cast<size_t>(r->world);
  size_t rem = count % static_cast<size_t>(r->world);
  size_t own = static_cast<size_t>((r->rank + 1) % r->world);
  size_t off = own * base + std::min(own, rem);
  size_t len = base + (own < rem ? 1 : 0);
  if (own_off) *own_off = off * esz;
  if (own_len) *own_len = len * esz;
  return 0;
}

int tdr_ring_test(tdr_ring_op *op) {
  if (!op) {
    tdr::set_error("ring_test: null op");
    return -1;
  }
  std::lock_guard<std::mutex> g(op->mu);
  if (!op->done) return 0;
  if (op->rc != 0) {
    tdr::set_error(op->err);
    return -1;
  }
  return 1;
}

int tdr_ring_wait(tdr_ring_op *op, int timeout_ms) {
  if (!op) {
    tdr::set_error("ring_wait: null op");
    return -1;
  }
  std::unique_lock<std::mutex> lk(op->mu);
  if (timeout_ms < 0) {
    op->cv.wait(lk, [&] { return op->done; });
  } else if (!op->cv.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                              [&] { return op->done; })) {
    tdr::set_error("ring_wait: timeout waiting for async collective");
    return -1;
  }
  if (op->rc != 0) {
    tdr::set_error(op->err);
    return -1;
  }
  return 0;
}

const char *tdr_ring_op_error(tdr_ring_op *op) {
  if (!op) return "";
  std::lock_guard<std::mutex> g(op->mu);
  return op->done && op->rc != 0 ? op->err.c_str() : "";
}

int tdr_ring_op_done(tdr_ring_op *op) {
  if (!op) return 0;
  std::lock_guard<std::mutex> g(op->mu);
  return op->done ? 1 : 0;
}

void tdr_ring_op_free(tdr_ring_op *op) {
  if (!op) return;
  {
    // A pending op is still owned by the driver: block until it
    // completes (bounded by the collective's own stall deadline)
    // rather than freeing memory another thread will write.
    std::unique_lock<std::mutex> lk(op->mu);
    op->cv.wait(lk, [&] { return op->done; });
  }
  delete op;
}

// Pre-register a buffer whose lifetime the caller guarantees to
// outlast the ring (or until tdr_ring_unregister). Steady-state
// allreduces on it then post work requests only — the front-loaded
// registration invariant of the reference (SURVEY.md §3.3).
int tdr_ring_register(tdr_ring *r, void *base, size_t len) {
  if (!r || !base || !len) {
    tdr::set_error("ring_register: bad args");
    return -1;
  }
  std::lock_guard<std::mutex> g(r->mu);
  uint64_t key = reinterpret_cast<uint64_t>(base);
  auto it = r->registered.find(key);
  if (it != r->registered.end()) {
    if (r->borrowed.count(key)) {
      // The key holds an ADOPTED (caller-owned) MR: silently
      // succeeding would bind this caller to the owner's MR (its
      // later unregister then orphans the owner's zero-copy binding),
      // and replacing/deregistering would double-free when the owner
      // deregisters. The owner must drop_buffer() first.
      tdr::set_error(
          "ring_register: key holds an adopted MR (drop it first)");
      return -1;
    }
    if (tdr_mr_len(it->second) >= len) return 0;
    tdr_dereg_mr(it->second);
    r->registered.erase(it);
  }
  tdr_mr *mr = tdr_reg_mr(r->eng, base, len, 0);
  if (!mr) return -1;
  r->registered[key] = mr;
  return 0;
}

int tdr_ring_last_schedule(const tdr_ring *r) {
  return r ? r->last_sched : TDR_SCHED_NONE;
}

int tdr_ring_unregister(tdr_ring *r, void *base) {
  if (!r) return -1;
  std::lock_guard<std::mutex> g(r->mu);
  uint64_t key = reinterpret_cast<uint64_t>(base);
  auto it = r->registered.find(key);
  if (it == r->registered.end()) return -1;
  if (r->borrowed.erase(key) == 0) tdr_dereg_mr(it->second);
  r->registered.erase(it);
  return 0;
}

// Adopt a caller-owned MR (dma-buf over device memory, iova == base)
// as the data MR for `base` — the zero-copy collective path. The
// caller retains ownership: unregister/destroy never dereg it.
int tdr_ring_adopt_mr(tdr_ring *r, void *base, tdr_mr *mr) {
  if (!r || !base || !mr) {
    tdr::set_error("ring_adopt_mr: bad args");
    return -1;
  }
  if (tdr_mr_addr(mr) != reinterpret_cast<uint64_t>(base)) {
    tdr::set_error("ring_adopt_mr: MR iova does not match base");
    return -1;
  }
  std::lock_guard<std::mutex> g(r->mu);
  uint64_t key = reinterpret_cast<uint64_t>(base);
  auto it = r->registered.find(key);
  if (it != r->registered.end()) {
    if (r->borrowed.erase(key) == 0) tdr_dereg_mr(it->second);
    r->registered.erase(it);
  }
  r->registered[key] = mr;
  r->borrowed.insert(key);
  return 0;
}

// The schedule structs and helpers below are C++ (templates) inside a
// file whose API surface is extern "C": reopen C++ linkage for them.
extern "C++" {
namespace {

// ------------------------------------------------------------------
// Progress plumbing shared by the striped schedules.
//
// A schedule exposes a THREAD-SAFE `int on_wc(bool left_side, size_t
// chan, const tdr_wc &wc)` (per-channel FIFO counters under the hub's
// per-channel locks, cross-channel watermarks/masks under the hub
// mutex) plus `post_more()`, `finished_locked()`, `owed_channel()`,
// and `stall_detail()`. Two drivers consume that surface:
//
//  - run_* legacy loop (TDR_PROGRESS_SHARDS=0): the calling thread
//    owns all polling — sweep_side() drains every channel without
//    blocking, wait_owed() parks a bounded slice on the channel owed
//    the oldest outstanding completion. One thread, one blocking
//    poll: wire progress on channel A can wait out a park owed to
//    channel B (the BENCH_r06 vs_bound gap).
//
//  - drive_sharded() (default): TDR_PROGRESS_SHARDS dedicated
//    progress threads, each polling ONLY its channel group's QPs and
//    publishing completion watermarks through on_wc; the schedule's
//    calling thread becomes a pure consumer — it posts what the
//    watermarks allow and sleeps on the hub's ONE condvar, which
//    every completion, fold, and failure notifies. No channel's
//    progress ever waits behind a blocking poll owed to another.
// ------------------------------------------------------------------

constexpr int kShardSliceMs = 2;  // shard park bound (verbs has no pulse)

template <typename S>
int sweep_side(const std::vector<tdr_qp *> &qps, S &sched, bool left) {
  tdr_wc wc[16];
  int total = 0;
  for (size_t c = 0; c < qps.size(); c++) {
    for (;;) {
      int n = tdr_poll(qps[c], wc, 16, 0);
      if (n < 0) return -1;
      for (int i = 0; i < n; i++)
        if (sched.on_wc(left, c, wc[i]) != 0) return -1;
      total += n;
      if (n < 16) break;
    }
  }
  return total;
}

// Block up to slice_ms on the channel the schedule says is OWED the
// oldest outstanding completion (sched.owed_channel — per-channel
// FIFO makes "oldest outstanding recv" a exact channel choice, so the
// blocking poll parks where the critical-path completion will arrive,
// not on an arbitrary channel while work queues elsewhere).
// Deadlock-free regardless of the choice: every owed completion
// eventually arrives on its own channel, and the caller re-sweeps all
// channels after each wake; a wrong guess costs at most slice_ms.
template <typename S>
int wait_owed(tdr_ring *r, S &sched, int slice_ms) {
  bool left = true;
  size_t chan = 0;
  sched.owed_channel(&left, &chan);
  tdr_qp *qp = (left ? r->lefts : r->rights)[chan];
  tdr_wc wc[16];
  int n = tdr_poll(qp, wc, 16, slice_ms);
  if (n < 0) return -1;
  for (int i = 0; i < n; i++)
    if (sched.on_wc(left, chan, wc[i]) != 0) return -1;
  return n;
}

// Watermark hub: the schedules' shared done-mask state. Fine-grained
// per-channel locks guard each channel's FIFO counters (single
// writer: the shard owning the channel — or the one polling thread in
// legacy mode); the hub mutex guards the cross-channel aggregates,
// masks, in-order-prefix frontiers, and fold bookkeeping; the ONE
// condvar carries every watermark publication. Lock discipline:
// chan_mu[c] and mu are never held together.
struct ProgressHub {
  std::mutex mu;
  std::condition_variable cv;
  std::deque<std::mutex> chan_mu;
  std::atomic<bool> stop{false};
  bool failed = false;   // under mu
  std::string err;       // under mu (thread-local errors bridged here:
                         // a shard's set_error is invisible to the
                         // posting thread's tdr_last_error slot)
  uint64_t stamp = 0;    // watermark publication count, under mu

  void init(size_t nc) {
    while (chan_mu.size() < nc) chan_mu.emplace_back();
    std::lock_guard<std::mutex> g(mu);
    failed = false;
    err.clear();
    stop.store(false, std::memory_order_relaxed);
  }
  void bump_locked() {
    stamp++;
    cv.notify_all();
  }
  void fail(const std::string &msg) {
    std::lock_guard<std::mutex> g(mu);
    if (!failed) {
      failed = true;
      err = msg;
    }
    bump_locked();
  }
};

// Error helpers: record in the calling thread's error slot AND the
// hub (on_wc may run on a shard thread whose thread-local error the
// posting thread can never read).
int wc_fail(ProgressHub &hub, const char *label, const tdr_wc &wc) {
  std::string msg = std::string(label) + ": completion error status " +
                    wc_status_label(wc.status);
  tdr::set_error(msg);
  hub.fail(msg);
  return -1;
}

int order_fail(ProgressHub &hub, const char *label, const char *what,
               size_t chan) {
  std::string msg = std::string(label) + ": " + what + " on channel " +
                    std::to_string(chan);
  tdr::set_error(msg);
  hub.fail(msg);
  return -1;
}

// Shared stall-deadline bookkeeping (factored from the schedules'
// previously-duplicated poll-timeout blocks): the deadline re-arms on
// ANY progress; expiry produces one labeled error whose detail names
// the owed channel/watermark, so a stall report says WHERE the
// schedule is blocked, not just that it is.
struct StallClock {
  std::chrono::steady_clock::time_point dl;
  // Hard per-collective deadline (TDR_COLL_DEADLINE_MS): unlike the
  // stall deadline it does NOT re-arm on progress — it bounds the
  // whole collective, so a link crawling under netem delay/throttle
  // that never quite stalls still trips it. Disabled (the default)
  // when the env knob is unset.
  std::chrono::steady_clock::time_point hard_dl;
  bool hard = false;
  StallClock() {
    int cd = tdr::coll_deadline_ms();
    if (cd > 0) {
      hard = true;
      hard_dl = std::chrono::steady_clock::now() +
                std::chrono::milliseconds(cd);
    }
    bump();
  }
  void bump() {
    dl = std::chrono::steady_clock::now() +
         std::chrono::milliseconds(ring_timeout_ms());
  }
  bool expired() const { return std::chrono::steady_clock::now() >= dl; }
  bool deadline_exceeded() const {
    return hard && std::chrono::steady_clock::now() >= hard_dl;
  }
};

// Hung-peer classification at expiry time: PING both ring neighbors
// (channel 0 — all channels reach the same peer processes) and fold
// the verdicts, worst first: a hung peer (probe sent, no pong inside
// the window) outranks a dead connection outranks "alive but slow".
// -2 = probing not negotiated anywhere (legacy peer / TDR_NO_PROBE),
// in which case error messages stay byte-identical to the pre-probe
// wording.
int stall_probe(tdr_ring *r) {
  int to = ring_timeout_ms() / 4;
  if (to < 50) to = 50;
  if (to > 2000) to = 2000;
  int verdict = -2;
  tdr_qp *qps[2] = {r->lefts[0], r->rights[0]};
  auto rank_of = [](int v) {
    return v == 0 ? 3 : v == -1 ? 2 : v == 1 ? 1 : 0;
  };
  for (tdr_qp *q : qps) {
    if (!q) continue;
    int pr = tdr_qp_probe(q, to);
    if (rank_of(pr) > rank_of(verdict)) verdict = pr;
  }
  return verdict;
}

// Verdict suffix appended to stall/deadline errors. The markers are
// load-bearing: the Python taxonomy keys retryability and `kind` off
// "peer hung" / "connection down" / plain timeout (see engine.py).
void append_probe_verdict(std::string *msg, int verdict) {
  if (verdict == 0)
    *msg += "; peer hung (probe unanswered)";
  else if (verdict == -1)
    *msg += "; peer connection down";
  else if (verdict == 1)
    *msg += "; peer alive (slow link)";
  // -2: keep the legacy message byte-identical.
}

int stall_fail(tdr_ring *r, const char *label, const std::string &detail) {
  std::string msg =
      std::string(label) + ": poll timeout (" + detail + ")";
  append_probe_verdict(&msg, r ? stall_probe(r) : -2);
  tdr::set_error(msg);
  return -1;
}

int deadline_fail(tdr_ring *r, const char *label,
                  const std::string &detail) {
  std::string msg = std::string(label) + ": collective deadline exceeded (" +
                    std::to_string(tdr::coll_deadline_ms()) + "ms; " +
                    detail + ")";
  append_probe_verdict(&msg, r ? stall_probe(r) : -2);
  tdr::set_error(msg);
  return -1;
}

// Channel holding the oldest outstanding item of one striped stream:
// per-channel FIFO means channel c's next completion is index
// c + done[c]*nc, so the argmin over channels with posted > done IS
// the stream's oldest outstanding chunk. SIZE_MAX when none. Reads
// each channel's counters under its own lock.
inline size_t oldest_outstanding(ProgressHub &hub,
                                 const std::vector<size_t> &posted,
                                 const std::vector<size_t> &done,
                                 size_t nc, size_t *chan) {
  size_t best = static_cast<size_t>(-1);
  for (size_t c = 0; c < nc; c++) {
    std::lock_guard<std::mutex> g(hub.chan_mu[c]);
    if (posted[c] <= done[c]) continue;
    size_t idx = c + done[c] * nc;
    if (idx < best) {
      best = idx;
      *chan = c;
    }
  }
  return best;
}

// One run's progress shards: shard s owns channels {s, s+n, ...} of
// both sides (each QP has exactly one poller), feeding completions
// through the schedule's thread-safe on_wc and — for the windowed
// schedule — enqueuing folds onto the fold pool straight from the
// shard thread. When its channels are idle the shard parks on the
// ENGINE's completion pulse: event-driven on emu (every CQ delivery
// pulses), a bounded kShardSliceMs slice on verbs.
template <typename S>
class ShardCrew {
 public:
  ShardCrew(tdr_ring *r, S *sched, ProgressHub *hub, size_t nshards,
            bool two_sides)
      : hub_(hub) {
    size_t nc = r->lefts.size();
    if (nshards > nc) nshards = nc;
    g_prog_shards.fetch_add(nshards, std::memory_order_relaxed);
    for (size_t s = 0; s < nshards; s++) {
      std::vector<Owned> own;
      for (size_t c = s; c < nc; c += nshards) {
        own.push_back({r->lefts[c], true, c});
        if (two_sides) own.push_back({r->rights[c], false, c});
      }
      threads_.emplace_back(
          [this, r, sched, own = std::move(own), s] {
            loop(r, sched, own, s);
          });
    }
  }
  ~ShardCrew() {
    hub_->stop.store(true, std::memory_order_release);
    for (auto &t : threads_) t.join();  // park is kShardSliceMs-bounded
  }

 private:
  struct Owned {
    tdr_qp *qp;
    bool left;
    size_t chan;
  };

  void loop(tdr_ring *r, S *sched, const std::vector<Owned> &own,
            size_t ordinal) {
    auto *eng = reinterpret_cast<tdr::Engine *>(r->eng);
    tdr_wc wc[16];
    uint64_t consumed = 0;
    while (!hub_->stop.load(std::memory_order_acquire)) {
      // Stamp BEFORE the sweep: a completion landing mid-sweep on an
      // already-swept QP moves the stamp, so the wait below returns
      // immediately instead of sleeping on work that already arrived.
      uint64_t seen = eng->cq_stamp();
      int got = 0;
      for (const Owned &o : own) {
        for (;;) {
          int n = tdr_poll(o.qp, wc, 16, 0);
          if (n < 0) {
            hub_->fail(std::string("ring progress shard: ") +
                       tdr::get_error());
            return;
          }
          for (int i = 0; i < n; i++)
            if (sched->on_wc(o.left, o.chan, wc[i]) != 0) return;
          got += n;
          if (n < 16) break;
        }
      }
      if (got > 0) {
        consumed += static_cast<uint64_t>(got);
        g_prog_wc.fetch_add(static_cast<uint64_t>(got),
                            std::memory_order_relaxed);
        // Process-level lane (engine=0, like the copy pool's events):
        // drain-batch boundaries ride thread timing and must not
        // perturb per-engine replay shapes.
        TDR_TEL(TDR_TEL_SHARD, 0, tdr::tel_thread_track(), ordinal,
                consumed);
        continue;
      }
      g_prog_wakeups.fetch_add(1, std::memory_order_relaxed);
      eng->cq_wait(seen, kShardSliceMs);
    }
  }

  ProgressHub *hub_;
  std::vector<std::thread> threads_;
};

// Watermark-consumer driver (sharded mode): posting stays on the
// calling thread; polling lives on the shards. The loop body is the
// whole schedule now — post what the watermarks allow, then sleep on
// the hub condvar until they move. The special-case idle states the
// legacy loops carry (fold-only wait, wire-idle-but-fold-gated)
// collapse into the one wait because folds publish on the same cv.
template <typename S>
int drive_sharded(tdr_ring *r, S &s, ProgressHub &hub, size_t nshards,
                  bool two_sides, const char *label) {
  ShardCrew<S> crew(r, &s, &hub, nshards, two_sides);
  StallClock clock;
  for (;;) {
    {
      std::lock_guard<std::mutex> g(hub.mu);
      if (hub.failed) {
        tdr::set_error(hub.err);
        return -1;
      }
      if (s.finished_locked()) return 0;
    }
    if (clock.deadline_exceeded())
      return deadline_fail(r, label, s.stall_detail());
    int p = s.post_more();
    if (p < 0) return -1;
    if (p > 0) {
      clock.bump();
      continue;
    }
    bool moved;
    {
      std::unique_lock<std::mutex> lk(hub.mu);
      uint64_t seen = hub.stamp;
      hub.cv.wait_for(lk, std::chrono::milliseconds(50), [&] {
        return hub.stamp != seen || hub.failed;
      });
      moved = hub.stamp != seen || hub.failed;
    }
    if (moved) {
      clock.bump();
      continue;
    }
    if (clock.expired()) return stall_fail(r, label, s.stall_detail());
  }
}

struct StepPipe {
  tdr_ring *r = nullptr;
  tdr_mr *dmr = nullptr;
  char *cdata = nullptr;
  int dtype = 0, red_op = 0;
  size_t esz = 0;

  StepPipe(tdr_ring *ring, tdr_mr *mr, char *data, int dt, int op,
           size_t elem)
      : r(ring), dmr(mr), cdata(data), dtype(dt), red_op(op), esz(elem) {}

  // ---- per-run state (reset at the top of run()) ----
  size_t chunk = 0, nc = 1;
  size_t send_off_ = 0, send_len_ = 0, recv_off_ = 0, recv_len_ = 0;
  size_t n_send = 0, n_recv = 0;
  bool fused = false, windowed = false;
  size_t slots = 0, slot_bytes = 0;
  // Posting cursors: single-writer (the posting thread).
  size_t posted_r = 0, posted_s = 0;
  // Completion watermarks. The per-channel FIFO counters live under
  // hub.chan_mu[c] (single writer: the shard owning channel c, or the
  // one polling thread in legacy mode); the cross-channel aggregates
  // and fold bookkeeping live under hub.mu.
  std::vector<size_t> posted_rc, done_rc, posted_sc, acked_sc;
  size_t done_r = 0, acked_s = 0;      // under hub.mu
  std::vector<size_t> rwin_c, swin_c;  // per-channel window budgets

  // Async fold tracking (windowed mode). fold_done gates scratch-slot
  // reuse: recv for chunk i may repost only once chunk i-slots has
  // FOLDED (not merely landed) — the slot is its fold's source. All
  // under hub.mu; fold completions publish on the hub condvar.
  bool offload = false;
  uint16_t eng_tel = 0;
  ProgressHub hub;
  std::vector<uint8_t> fold_done;
  size_t folds_out = 0;  // submitted to the pool, not yet finished
  size_t folded = 0;     // chunks whose fold completed (any path)

  size_t chunk_len(size_t total, size_t i) const {
    return std::min(chunk, total - i * chunk);
  }

  void fold_chunk(size_t idx) {
    size_t len = chunk_len(recv_len_, idx);
    // Single-threaded on the fold worker: parallelism comes from
    // channels × workers, not from forking each fold (which would
    // serialize jobs on the copy pool's one-region lock).
    tdr::reduce_any(cdata + recv_off_ + idx * chunk,
                    r->tmp.data() + (idx % slots) * slot_bytes, len / esz,
                    dtype, red_op);
    TDR_TELC(TDR_TEL_FOLD, eng_tel, tdr::tel_thread_track(), idx, len,
             r->cur_coll.load(std::memory_order_relaxed));
    std::lock_guard<std::mutex> g(hub.mu);
    fold_done[idx] = 1;
    folded++;
    folds_out--;
    hub.bump_locked();
  }

  bool fold_ready(size_t i) {
    if (!windowed || i < slots) return true;
    std::lock_guard<std::mutex> g(hub.mu);
    return fold_done[i - slots] != 0;
  }

  int post_recv_chunk(size_t i) {
    size_t len = chunk_len(recv_len_, i);
    size_t c = i % nc;
    tdr_qp *qp = r->lefts[c];
    int rc;
    if (fused)
      rc = tdr_post_recv_reduce(qp, dmr, recv_off_ + i * chunk, len, dtype,
                                red_op, kWrRecv | i);
    else if (windowed)
      rc = tdr_post_recv(qp, r->scratch(slots * slot_bytes),
                         (i % slots) * slot_bytes, len, kWrRecv | i);
    else
      rc = tdr_post_recv(qp, dmr, recv_off_ + i * chunk, len, kWrRecv | i);
    if (rc == 0) {
      std::lock_guard<std::mutex> g(hub.chan_mu[c]);
      posted_rc[c]++;
    }
    return rc;
  }

  // Where the oldest outstanding completion will arrive: the recv
  // stream first (it is the critical path — folds and the peer's send
  // window both key off landed chunks), else any channel owing a send
  // ack.
  void owed_channel(bool *left, size_t *chan) {
    size_t c = 0;
    if (oldest_outstanding(hub, posted_rc, done_rc, nc, &c) !=
        static_cast<size_t>(-1)) {
      *left = true;
      *chan = c;
      return;
    }
    for (size_t i = 0; i < nc; i++) {
      std::lock_guard<std::mutex> g(hub.chan_mu[i]);
      if (posted_sc[i] > acked_sc[i]) {
        *left = false;
        *chan = i;
        return;
      }
    }
    *left = true;
    *chan = 0;
  }

  int on_wc(bool left, size_t chan, const tdr_wc &wc) {
    (void)left;
    if (wc.status != TDR_WC_SUCCESS) return wc_fail(hub, "ring", wc);
    uint64_t kind = wc.wr_id & kWrKindMask;
    size_t idx = wc.wr_id & ~kWrKindMask;
    if (kind == kWrSend) {
      {
        std::lock_guard<std::mutex> g(hub.chan_mu[idx % nc]);
        acked_sc[idx % nc]++;
      }
      std::lock_guard<std::mutex> g(hub.mu);
      acked_s++;
      hub.bump_locked();
      return 0;
    }
    if (kind != kWrRecv) return 0;
    // Per-channel FIFO: channel c carries chunks c, c+nc, c+2nc, …
    // in posted order; cross-channel arrival order is free.
    {
      std::lock_guard<std::mutex> g(hub.chan_mu[chan]);
      if (idx != chan + done_rc[chan] * nc)
        goto out_of_order;
      done_rc[chan]++;
    }
    if (!windowed) {
      std::lock_guard<std::mutex> g(hub.mu);
      done_r++;
      hub.bump_locked();
      return 0;
    }
    {
      size_t len = chunk_len(recv_len_, idx);
      if (offload) {
        {
          std::lock_guard<std::mutex> g(hub.mu);
          done_r++;
          folds_out++;
          hub.bump_locked();
        }
        // Fold enqueued straight from the progress (shard) thread;
        // the job publishes its watermark back on the hub condvar.
        TDR_TELC(TDR_TEL_FOLD_OFF, eng_tel, tdr::tel_thread_track(), idx,
                 len, r->cur_coll.load(std::memory_order_relaxed));
        tdr::fold_submit([this, idx] { fold_chunk(idx); });
      } else {
        // Inline fallback (no fold workers): the legacy path, with
        // the copy pool forking the fold itself.
        tdr::par_reduce(cdata + recv_off_ + idx * chunk,
                        r->tmp.data() + (idx % slots) * slot_bytes,
                        len / esz, dtype, red_op);
        std::lock_guard<std::mutex> g(hub.mu);
        done_r++;
        fold_done[idx] = 1;
        folded++;
        hub.bump_locked();
      }
    }
    return 0;
  out_of_order:
    return order_fail(hub, "ring", "out-of-order recv completion", chan);
  }

  bool finished_locked() const {
    return done_r == n_recv && acked_s == n_send &&
           (!windowed || folded == n_recv);
  }

  std::string stall_detail() {
    bool left = true;
    size_t chan = 0;
    owed_channel(&left, &chan);
    size_t dr, as, fo;
    {
      std::lock_guard<std::mutex> g(hub.mu);
      dr = done_r;
      as = acked_s;
      fo = folded;
    }
    std::string d = std::string("owed ") + (left ? "recv" : "send-ack") +
                    " on channel " + std::to_string(chan) + "; s " +
                    std::to_string(as) + "/" + std::to_string(n_send) +
                    " r " + std::to_string(dr) + "/" +
                    std::to_string(n_recv);
    if (windowed)
      d += " folded " + std::to_string(fo) + "/" + std::to_string(n_recv);
    return d;
  }

  // Posting side, shared by both drivers: post whatever the windows
  // (and, windowed, the fold watermarks) allow, strictly in global
  // chunk order — which IS per-channel posted order, all FIFO
  // matching needs. Returns progress, or -1.
  int post_more() {
    bool progressed = false;
    while (posted_r < n_recv) {
      size_t c = posted_r % nc;
      {
        std::lock_guard<std::mutex> g(hub.chan_mu[c]);
        if (posted_rc[c] - done_rc[c] >= rwin_c[c]) break;
      }
      if (windowed && !fold_ready(posted_r)) break;
      if (post_recv_chunk(posted_r) != 0) return -1;
      posted_r++;
      progressed = true;
    }
    // Keep outbound traffic moving: in stream mode the post blocks
    // while the chunk drains into the socket (the progress threads
    // land inbound chunks concurrently); in CMA mode it just queues a
    // descriptor. The windowed throttle tracks LANDED chunks (the
    // peer's symmetric scratch window), not folds.
    while (posted_s < n_send) {
      size_t c = posted_s % nc;
      {
        std::lock_guard<std::mutex> g(hub.chan_mu[c]);
        if (posted_sc[c] - acked_sc[c] >= swin_c[c]) break;
      }
      if (windowed && n_recv) {
        std::lock_guard<std::mutex> g(hub.mu);
        if (posted_s >= done_r + slots) break;
      }
      size_t len = chunk_len(send_len_, posted_s);
      if (tdr_post_send(r->rights[c], dmr, send_off_ + posted_s * chunk,
                        len, kWrSend | posted_s) != 0)
        return -1;
      {
        std::lock_guard<std::mutex> g(hub.chan_mu[c]);
        posted_sc[c]++;
      }
      posted_s++;
      progressed = true;
    }
    return progressed ? 1 : 0;
  }

  // One neighbor-exchange step: stream `send_len` bytes of the data
  // buffer at `send_off` rightward while receiving `recv_len` bytes
  // from the left, chunk i striped over channel i % channels.
  //
  // reduce=true → phase-1 semantics: inbound chunks are folded into
  // data at recv_off. On engines with reduce-on-receive the fold
  // happens in the transport's progress engine directly from the
  // inbound bytes (no scratch at all); otherwise chunks land in a
  // double-buffered windowed scratch (two slots per channel) and fold
  // on the fold-offload pool — the poll loop keeps posting while
  // predecessors fold, and a chunk lands while the previous chunk on
  // its channel is still folding.
  // reduce=false → phase-2 semantics: receives land directly in the
  // data MR at recv_off (no copy, no reduce).
  int run(size_t send_off, size_t send_len, size_t recv_off, size_t recv_len,
          bool reduce) {
    chunk = r->chunk;
    nc = r->lefts.size();
    send_off_ = send_off;
    send_len_ = send_len;
    recv_off_ = recv_off;
    recv_len_ = recv_len;
    n_send = send_len ? (send_len + chunk - 1) / chunk : 0;
    n_recv = recv_len ? (recv_len + chunk - 1) / chunk : 0;
    fused = reduce && tdr_qp_has_recv_reduce(r->lefts[0]);
    windowed = reduce && !fused;
    // Double-buffered per channel (so landing i+nc overlaps folding i
    // on every channel), never below the legacy window depth.
    slots = windowed
                ? std::min(n_recv ? n_recv : 1,
                           std::max(static_cast<size_t>(kWindow), 2 * nc))
                : 0;
    slot_bytes = windowed ? std::min(chunk, recv_len ? recv_len : 1) : 0;
    if (windowed && n_recv && !r->scratch(slots * slot_bytes)) return -1;

    posted_r = posted_s = 0;
    posted_rc.assign(nc, 0);
    done_rc.assign(nc, 0);
    posted_sc.assign(nc, 0);
    acked_sc.assign(nc, 0);
    offload = windowed && tdr::fold_pool_workers() > 0;
    eng_tel = reinterpret_cast<tdr::Engine *>(r->eng)->tel_id;
    hub.init(nc);
    {
      std::lock_guard<std::mutex> g(hub.mu);
      done_r = acked_s = 0;
      fold_done.assign(windowed ? n_recv : 0, 0);
      folds_out = 0;
      folded = 0;
    }
    // Whatever happens below, never return while a fold job still
    // references the scratch window or the data buffer. Declared
    // FIRST so it drains AFTER the sharded driver has joined its
    // shard threads (destructors run in reverse order) — no shard can
    // submit a fold once the drain starts counting.
    struct FoldDrain {
      StepPipe *p;
      ~FoldDrain() {
        std::unique_lock<std::mutex> lk(p->hub.mu);
        p->hub.cv.wait(lk, [&] { return p->folds_out == 0; });
      }
    } fold_drain{this};
    (void)fold_drain;

    rwin_c.assign(nc, 0);
    swin_c.assign(nc, 0);
    for (size_t c = 0; c < nc; c++) {
      rwin_c[c] = fused ? reduce_recv_window(r->lefts[c]) : kMaxOutstanding;
      // In-flight send bound: the schedule is symmetric, so the peer's
      // reduce-recv window (≈ ours, same config) caps how many phase-1
      // sends can land — racing further ahead just RNR-NAK-storms a
      // real HCA (the mock and emu absorb it, hiding the collapse).
      swin_c[c] = reduce ? reduce_recv_window(r->rights[c])
                         : kMaxOutstanding;
    }

    const bool same_qp = (r->lefts[0] == r->rights[0]);
    const size_t shards = tdr::progress_shards_for(nc);
    // Tiny runs (a barrier's one chunk, a short tail segment) post
    // and finish faster than a shard thread spawns: keep them on the
    // legacy inline loop regardless of the knob.
    if (shards > 0 && n_recv + n_send >= 4)
      return drive_sharded(r, *this, hub, shards, !same_qp, "ring");
    return run_polled(same_qp);
  }

  // Legacy single-poll loop (TDR_PROGRESS_SHARDS=0, and tiny runs):
  // the calling thread owns all polling and folds gate its waits.
  int run_polled(bool same_qp) {
    StallClock clock;
    size_t last_folded = 0;
    for (;;) {
      {
        std::lock_guard<std::mutex> g(hub.mu);
        if (finished_locked()) break;
        if (folded != last_folded) {
          last_folded = folded;
          clock.bump();
        }
      }
      if (clock.deadline_exceeded())
        return deadline_fail(r, "ring", stall_detail());
      int p = post_more();
      if (p < 0) return -1;
      int nl = sweep_side(r->lefts, *this, true);
      if (nl < 0) return -1;
      int nr = same_qp ? 0 : sweep_side(r->rights, *this, false);
      if (nr < 0) return -1;
      if (p > 0 || nl > 0 || nr > 0) {
        clock.bump();
        continue;
      }
      size_t dr, as;
      {
        std::lock_guard<std::mutex> g(hub.mu);
        dr = done_r;
        as = acked_s;
      }
      if (dr == n_recv && as == n_send) {
        // Only folds left: they are pure local CPU work — wait on the
        // hub cv (fold completions publish there), not the wire.
        std::unique_lock<std::mutex> lk(hub.mu);
        hub.cv.wait(lk, [&] { return folded == n_recv; });
        continue;
      }
      // Wire idle but fold-gated (every posted recv landed, every
      // send acked, posting blocked on scratch slots): the only
      // possible progress is offloaded folds, and a fold completion
      // notifies the hub cv — a QP poll would just sleep its slice.
      if (windowed && posted_r == dr && posted_s == as) {
        bool fold_moved;
        {
          std::unique_lock<std::mutex> lk(hub.mu);
          hub.cv.wait_for(lk, std::chrono::milliseconds(50),
                          [&] { return folded != last_folded; });
          fold_moved = folded != last_folded;
        }
        if (!fold_moved && clock.expired())
          return stall_fail(r, "ring", "fold stall; " + stall_detail());
        continue;
      }
      // Nothing postable, nothing completed: block a slice on the
      // channel owed the oldest outstanding completion, so the wake
      // happens where the critical path advances and a genuine stall
      // still trips the ring deadline.
      int n = wait_owed(r, *this, 50);
      if (n < 0) return -1;
      if (n > 0) {
        clock.bump();
        continue;
      }
      bool fold_moved;
      {
        std::lock_guard<std::mutex> g(hub.mu);
        fold_moved = folded != last_folded;
      }
      if (!fold_moved && clock.expired())
        return stall_fail(r, "ring", stall_detail());
    }
    return 0;
  }
};

}  // namespace

namespace {

// World-2 fused exchange: reduce-scatter and all-gather overlapped
// (C++ linkage continues through these schedule structs; the linkage
// block closes before the extern-C collective entry points.)
// chunk-wise. The generic schedule runs the two phases back to back;
// for world=2 they use OPPOSITE directions of the two neighbor QPs
// (phase 1 rides right→peer-left, phase 2 rides left→peer-right), so
// there is no FIFO-matching conflict in running them concurrently:
// the moment chunk c of my reduce segment is folded, the reduced
// chunk is sent back while the next inbound chunk is still in flight.
// Besides hiding the phase-2 latency behind phase 1, the return
// transfer reads bytes the fold JUST wrote — LLC-hot instead of a
// DRAM re-read, which on a bandwidth-bound host is the difference
// between 5 and 6 passes over the buffer per allreduce.
//
// Requires reduce-on-receive (folds happen in the transport's
// progress engine as chunks arrive) and distinct left/right QPs; the
// caller falls back to the generic two-phase pipeline otherwise.
struct FusedTwo {
  tdr_ring *r = nullptr;
  tdr_mr *dmr = nullptr;
  int dtype = 0, red_op = 0;

  size_t chunk = 0;
  // A = the segment this rank sends out first and receives back
  // reduced; B = the segment it folds locally and returns.
  size_t a_off = 0, a_len = 0, b_off = 0, b_len = 0;
  size_t n_a = 0, n_b = 0;
  // Foldback mode: A chunks go out as fold-and-write-back sends whose
  // acks mean "the reduced final landed in place" — the two return
  // streams (reduced-B sends, A-final recvs) disappear entirely, and
  // the fold+return is one pass in the peer's progress engine.
  bool use_fb = false;

  // Stream bookkeeping, striped chunk i → channel i % nc. Recv
  // completions may arrive out of GLOBAL order across channels (per
  // channel they stay FIFO — asserted via the per-channel counters,
  // which live under the hub's per-channel locks), so both inbound
  // streams keep done-masks; the B stream also keeps the in-order
  // folded PREFIX (fr_rB) because returning reduced chunk k to the
  // peer requires k's fold complete AND FIFO order on the left
  // channel k % nc. Masks, prefixes, and aggregates live under
  // hub.mu — the in-order-prefix dependency state the one condvar
  // publishes.
  size_t nc = 1;
  size_t posted_rB = 0, posted_sB = 0;  // posting cursors (one writer)
  size_t posted_sA = 0, posted_rA = 0;
  size_t done_rB = 0, acked_sB = 0;     // aggregates, under hub.mu
  size_t acked_sA = 0, done_rA = 0;
  size_t need_sB = 0;
  std::vector<uint8_t> mask_rB, mask_rA;           // under hub.mu
  size_t fr_rB = 0;  // in-order folded prefix, under hub.mu
  std::vector<size_t> done_rBc, done_rAc;      // per-channel order check
  std::vector<size_t> pc_rB, pc_rA, pc_sA, ac_sA;  // per-channel windows
  std::vector<size_t> pc_sB, ac_sB;  // per-channel sB accounting
  std::vector<size_t> rb_win, sa_win;
  ProgressHub hub;

  static size_t nchunks(size_t len, size_t chunk) {
    return len ? (len + chunk - 1) / chunk : 0;
  }
  size_t clen(size_t total, size_t i) const {
    return std::min(chunk, total - i * chunk);
  }

  int post_recv_b(size_t i) {
    int rc = tdr_post_recv_reduce(r->lefts[i % nc], dmr, b_off + i * chunk,
                                  clen(b_len, i), dtype, red_op,
                                  kWrRecv | i);
    if (rc == 0) {
      std::lock_guard<std::mutex> g(hub.chan_mu[i % nc]);
      pc_rB[i % nc]++;
    }
    return rc;
  }
  int post_recv_a(size_t i) {
    int rc = tdr_post_recv(r->rights[i % nc], dmr, a_off + i * chunk,
                           clen(a_len, i), kWrRecv | i);
    if (rc == 0) {
      std::lock_guard<std::mutex> g(hub.chan_mu[i % nc]);
      pc_rA[i % nc]++;
    }
    return rc;
  }

  int on_wc(bool left, size_t chan, const tdr_wc &wc) {
    if (wc.status != TDR_WC_SUCCESS)
      return wc_fail(hub, "ring(fused2)", wc);
    uint64_t kind = wc.wr_id & kWrKindMask;
    size_t idx = wc.wr_id & ~kWrKindMask;
    if (kind == kWrSend) {
      {
        std::lock_guard<std::mutex> g(hub.chan_mu[idx % nc]);
        (left ? ac_sB : ac_sA)[idx % nc]++;
      }
      std::lock_guard<std::mutex> g(hub.mu);
      (left ? acked_sB : acked_sA)++;
      hub.bump_locked();
      return 0;
    }
    if (kind != kWrRecv) return 0;
    bool ooo = false;
    {
      std::lock_guard<std::mutex> g(hub.chan_mu[chan]);
      std::vector<size_t> &done_c = left ? done_rBc : done_rAc;
      if (idx != chan + done_c[chan] * nc)
        ooo = true;
      else
        done_c[chan]++;
    }
    if (!ooo) {
      std::lock_guard<std::mutex> g(hub.mu);
      std::vector<uint8_t> &mask = left ? mask_rB : mask_rA;
      if (idx >= mask.size() || mask[idx]) {
        ooo = true;
      } else {
        mask[idx] = 1;
        if (left) {
          done_rB++;
          while (fr_rB < n_b && mask_rB[fr_rB]) fr_rB++;
        } else {
          done_rA++;
        }
        hub.bump_locked();
      }
    }
    if (ooo)
      return order_fail(hub, "ring(fused2)",
                        "out-of-order recv completion", chan);
    return 0;
  }

  // Oldest outstanding completion: the B fold stream first (it gates
  // the reduced-return sends), then the A final stream, then send
  // acks on either side.
  void owed_channel(bool *left, size_t *chan) {
    size_t c = 0;
    if (oldest_outstanding(hub, pc_rB, done_rBc, nc, &c) !=
        static_cast<size_t>(-1)) {
      *left = true;
      *chan = c;
      return;
    }
    if (!use_fb && oldest_outstanding(hub, pc_rA, done_rAc, nc, &c) !=
                       static_cast<size_t>(-1)) {
      *left = false;
      *chan = c;
      return;
    }
    for (size_t i = 0; i < nc; i++) {
      std::lock_guard<std::mutex> g(hub.chan_mu[i]);
      if (pc_sA[i] > ac_sA[i]) {
        *left = false;
        *chan = i;
        return;
      }
      if (pc_sB[i] > ac_sB[i]) {
        *left = true;
        *chan = i;
        return;
      }
    }
    *left = true;
    *chan = 0;
  }

  bool finished_locked() const {
    return done_rB >= n_b && acked_sB >= need_sB && done_rA >= n_a &&
           acked_sA >= n_a;
  }

  std::string stall_detail() {
    bool left = true;
    size_t chan = 0;
    owed_channel(&left, &chan);
    size_t rB, sB, rA, sA;
    {
      std::lock_guard<std::mutex> g(hub.mu);
      rB = done_rB;
      sB = acked_sB;
      rA = done_rA;
      sA = acked_sA;
    }
    return std::string("owed ") + (left ? "left" : "right") +
           " channel " + std::to_string(chan) + "; rB " +
           std::to_string(rB) + "/" + std::to_string(n_b) + " sB " +
           std::to_string(sB) + "/" + std::to_string(posted_sB) + " rA " +
           std::to_string(rA) + "/" + std::to_string(n_a) + " sA " +
           std::to_string(sA) + "/" + std::to_string(posted_sA);
  }

  // Post the inbound streams deep (every target is a disjoint slice
  // of the data MR) and the outbound streams as their gates open,
  // all in global chunk order — which is per-channel FIFO order.
  int post_more() {
    bool progressed = false;
    while (posted_rB < n_b) {
      size_t c = posted_rB % nc;
      {
        std::lock_guard<std::mutex> g(hub.chan_mu[c]);
        if (pc_rB[c] - done_rBc[c] >= rb_win[c]) break;
      }
      if (post_recv_b(posted_rB) != 0) return -1;
      posted_rB++;
      progressed = true;
    }
    if (!use_fb) {
      while (posted_rA < n_a) {
        size_t c = posted_rA % nc;
        {
          std::lock_guard<std::mutex> g(hub.chan_mu[c]);
          if (pc_rA[c] - done_rAc[c] >= kMaxOutstanding) break;
        }
        if (post_recv_a(posted_rA) != 0) return -1;
        posted_rA++;
        progressed = true;
      }
    }
    while (posted_sA < n_a) {
      size_t c = posted_sA % nc;
      {
        std::lock_guard<std::mutex> g(hub.chan_mu[c]);
        if (pc_sA[c] - ac_sA[c] >= sa_win[c]) break;
      }
      int rc = use_fb
                   ? tdr_post_send_foldback(r->rights[c], dmr,
                                            a_off + posted_sA * chunk,
                                            clen(a_len, posted_sA),
                                            kWrSend | posted_sA)
                   : tdr_post_send(r->rights[c], dmr,
                                   a_off + posted_sA * chunk,
                                   clen(a_len, posted_sA),
                                   kWrSend | posted_sA);
      if (rc != 0) return -1;
      {
        std::lock_guard<std::mutex> g(hub.chan_mu[c]);
        pc_sA[c]++;
      }
      posted_sA++;
      progressed = true;
    }
    // Non-foldback: return a reduced B chunk the moment its fold
    // completes (cache-hot). The gate is the in-order folded
    // prefix, so the peer's rA stream sees its per-channel FIFO.
    while (!use_fb) {
      {
        std::lock_guard<std::mutex> g(hub.mu);
        if (!(posted_sB < fr_rB && posted_sB - acked_sB < kMaxOutstanding))
          break;
      }
      size_t c = posted_sB % nc;
      if (tdr_post_send(r->lefts[c], dmr, b_off + posted_sB * chunk,
                        clen(b_len, posted_sB), kWrSend | posted_sB) != 0)
        return -1;
      {
        std::lock_guard<std::mutex> g(hub.chan_mu[c]);
        pc_sB[c]++;
      }
      posted_sB++;
      progressed = true;
    }
    return progressed ? 1 : 0;
  }

  int run() {
    nc = r->lefts.size();
    hub.init(nc);
    {
      std::lock_guard<std::mutex> g(hub.mu);
      mask_rB.assign(n_b, 0);
      mask_rA.assign(use_fb ? 0 : n_a, 0);
      fr_rB = 0;
      done_rB = acked_sB = acked_sA = done_rA = 0;
      if (use_fb) done_rA = n_a;  // stream does not exist
    }
    need_sB = use_fb ? 0 : n_b;  // ditto
    done_rBc.assign(nc, 0);
    done_rAc.assign(nc, 0);
    pc_rB.assign(nc, 0);
    pc_rA.assign(nc, 0);
    pc_sA.assign(nc, 0);
    ac_sA.assign(nc, 0);
    pc_sB.assign(nc, 0);
    ac_sB.assign(nc, 0);
    rb_win.assign(nc, 0);
    sa_win.assign(nc, 0);
    for (size_t c = 0; c < nc; c++) {
      rb_win[c] = reduce_recv_window(r->lefts[c]);
      // A-chunks land in the peer's reduce-recvs: bound in-flight
      // sends by its window (≈ ours) so a real HCA doesn't
      // RNR-NAK-storm.
      sa_win[c] = reduce_recv_window(r->rights[c]);
    }

    const size_t shards = tdr::progress_shards_for(nc);
    if (shards > 0 && n_a + n_b >= 4)
      return drive_sharded(r, *this, hub, shards, true, "ring(fused2)");

    StallClock clock;
    for (;;) {
      {
        std::lock_guard<std::mutex> g(hub.mu);
        if (finished_locked()) break;
      }
      if (clock.deadline_exceeded())
        return deadline_fail(r, "ring(fused2)", stall_detail());
      int p = post_more();
      if (p < 0) return -1;
      int nl = sweep_side(r->lefts, *this, true);
      if (nl < 0) return -1;
      int nr = sweep_side(r->rights, *this, false);
      if (nr < 0) return -1;
      if (p > 0 || nl > 0 || nr > 0) {
        clock.bump();
        continue;
      }
      int n = wait_owed(r, *this, 50);
      if (n < 0) return -1;
      if (n > 0) {
        clock.bump();
        continue;
      }
      if (clock.expired())
        return stall_fail(r, "ring(fused2)", stall_detail());
    }
    return 0;
  }
};

bool wavefront_disabled() { return tdr::env_set("TDR_NO_WAVEFRONT"); }

// ------------------------------------------------------------------
// Wavefront ring (world > 2, reduce-on-receive engines): the classic
// schedule is 2(world-1) steps separated by barriers — the link idles
// while the last chunks of a step fold, and every step pays a full
// drain. Here the WHOLE schedule is flattened into two lexicographic
// (step, chunk) sequences — one of sends (right QP), one of receives
// (left QP) — and chunks advance through steps independently behind a
// sliding window. Correctness with FIFO recv matching holds because
// both sides post strictly in schedule order and TCP preserves it;
// the data dependency is exactly "send (t,c) needs recv (t-1,c)",
// and send step t's segment IS recv step t-1's segment, so a single
// monotone completed-receives counter encodes readiness.
// ------------------------------------------------------------------
struct WaveItem {
  size_t off;
  size_t len;
  bool reduce;     // recv side: fold vs place
  size_t dep = 0;  // send side: required done_recv count
  bool fb = false;  // send side: fold-and-write-back (last RS step)
};

struct Wavefront {
  tdr_ring *r = nullptr;
  tdr_mr *dmr = nullptr;
  int dtype = 0, red_op = 0;
  std::vector<WaveItem> sends, recvs;

  size_t nc = 1;
  size_t posted_s = 0, posted_r = 0;  // posting cursors (one writer)
  size_t acked_s = 0, done_r = 0;     // aggregates, under hub.mu
  // Completion bookkeeping tolerates out-of-schedule-order recv
  // completions: channels complete independently, and a foldback
  // recv's completion is DEFERRED until the peer's write-back pull
  // acks, so a later recv can complete first. Matching is still FIFO
  // per channel at the transport — only cross-channel reporting
  // reorders — and send dependencies use the in-order completed
  // PREFIX (frontier), never the raw count. Mask + frontier live
  // under hub.mu: they ARE the watermark the posting side consumes.
  std::vector<uint8_t> done_mask;
  size_t frontier = 0;
  // Per-channel in-flight accounting (window bounds) and send acks,
  // under the hub's per-channel locks.
  std::vector<size_t> pc_r, dc_r, pc_s, ac_s;
  std::vector<size_t> r_win;
  ProgressHub hub;

  int post_send_item(size_t i) {
    const WaveItem &it = sends[i];
    tdr_qp *qp = r->rights[i % nc];
    int rc = it.fb
                 ? tdr_post_send_foldback(qp, dmr, it.off, it.len,
                                          kWrSend | i)
                 : tdr_post_send(qp, dmr, it.off, it.len, kWrSend | i);
    if (rc == 0) {
      std::lock_guard<std::mutex> g(hub.chan_mu[i % nc]);
      pc_s[i % nc]++;
    }
    return rc;
  }
  int post_recv_item(size_t i) {
    const WaveItem &it = recvs[i];
    tdr_qp *qp = r->lefts[i % nc];
    int rc = it.reduce
                 ? tdr_post_recv_reduce(qp, dmr, it.off, it.len, dtype,
                                        red_op, kWrRecv | i)
                 : tdr_post_recv(qp, dmr, it.off, it.len, kWrRecv | i);
    if (rc == 0) {
      std::lock_guard<std::mutex> g(hub.chan_mu[i % nc]);
      pc_r[i % nc]++;
    }
    return rc;
  }

  int on_wc(bool left, size_t chan, const tdr_wc &wc) {
    (void)left;
    if (wc.status != TDR_WC_SUCCESS) return wc_fail(hub, "ring(wave)", wc);
    uint64_t kind = wc.wr_id & kWrKindMask;
    size_t idx = wc.wr_id & ~kWrKindMask;
    if (kind == kWrSend) {
      {
        std::lock_guard<std::mutex> g(hub.chan_mu[idx % nc]);
        ac_s[idx % nc]++;
      }
      std::lock_guard<std::mutex> g(hub.mu);
      acked_s++;
      hub.bump_locked();
      return 0;
    }
    if (kind != kWrRecv) return 0;
    bool bad = false;
    {
      std::lock_guard<std::mutex> g(hub.mu);
      if (idx >= done_mask.size() || done_mask[idx] || idx % nc != chan)
        bad = true;
    }
    if (bad)
      return order_fail(hub, "ring(wave)",
                        "duplicate/foreign recv completion", chan);
    // Per-channel counter BEFORE the watermark publication (the
    // StepPipe/FusedTwo order): a consumer woken by the bump must see
    // the recv window already refilled, or it re-sleeps its full
    // slice with nothing left to notify it.
    {
      std::lock_guard<std::mutex> g(hub.chan_mu[chan]);
      dc_r[chan]++;
    }
    std::lock_guard<std::mutex> g(hub.mu);
    done_mask[idx] = 1;
    done_r++;
    while (frontier < done_mask.size() && done_mask[frontier]) frontier++;
    hub.bump_locked();
    return 0;
  }

  // The frontier's channel owes the oldest outstanding recv (it is
  // what every send dependency waits on); else any channel owing a
  // send ack.
  void owed_channel(bool *left, size_t *chan) {
    size_t c = 0;
    if (oldest_outstanding(hub, pc_r, dc_r, nc, &c) !=
        static_cast<size_t>(-1)) {
      *left = true;
      *chan = c;
      return;
    }
    for (size_t i = 0; i < nc; i++) {
      std::lock_guard<std::mutex> g(hub.chan_mu[i]);
      if (pc_s[i] > ac_s[i]) {
        *left = false;
        *chan = i;
        return;
      }
    }
    *left = true;
    *chan = 0;
  }

  bool finished_locked() const {
    return acked_s >= sends.size() && done_r >= recvs.size();
  }

  std::string stall_detail() {
    bool left = true;
    size_t chan = 0;
    owed_channel(&left, &chan);
    size_t as, dr, fr, dep = 0;
    {
      std::lock_guard<std::mutex> g(hub.mu);
      as = acked_s;
      dr = done_r;
      fr = frontier;
    }
    if (posted_s < sends.size()) dep = sends[posted_s].dep;
    return std::string("owed ") + (left ? "recv" : "send-ack") +
           " on channel " + std::to_string(chan) + "; s " +
           std::to_string(as) + "/" + std::to_string(sends.size()) +
           " r " + std::to_string(dr) + "/" +
           std::to_string(recvs.size()) + " frontier " +
           std::to_string(fr) + " next-dep " + std::to_string(dep);
  }

  int post_more() {
    bool progressed = false;
    // Keep the recv windows deep (disjoint targets; per-channel
    // FIFO-matched because global order IS per-channel order).
    while (posted_r < recvs.size()) {
      size_t c = posted_r % nc;
      {
        std::lock_guard<std::mutex> g(hub.chan_mu[c]);
        if (pc_r[c] - dc_r[c] >= r_win[c]) break;
      }
      if (post_recv_item(posted_r) != 0) return -1;
      posted_r++;
      progressed = true;
    }
    // Post sends strictly in schedule order as their dependency
    // (the same-segment recv of the previous step) completes.
    // In-flight sends bounded by the peer's per-channel recv window
    // (≈ r_win; symmetric schedule) to avoid RNR storms on real
    // HCAs.
    while (posted_s < sends.size()) {
      size_t c = posted_s % nc;
      {
        std::lock_guard<std::mutex> g(hub.chan_mu[c]);
        if (pc_s[c] - ac_s[c] >= r_win[c]) break;
      }
      {
        std::lock_guard<std::mutex> g(hub.mu);
        if (frontier < sends[posted_s].dep) break;
      }
      if (post_send_item(posted_s) != 0) return -1;
      posted_s++;
      progressed = true;
    }
    return progressed ? 1 : 0;
  }

  int run() {
    nc = r->lefts.size();
    hub.init(nc);
    const size_t N = sends.size(), M = recvs.size();
    {
      std::lock_guard<std::mutex> g(hub.mu);
      done_mask.assign(M, 0);
      frontier = 0;
      acked_s = done_r = 0;
    }
    pc_r.assign(nc, 0);
    dc_r.assign(nc, 0);
    pc_s.assign(nc, 0);
    ac_s.assign(nc, 0);
    r_win.assign(nc, 0);
    // Mixed reduce/place recv stream: bound each channel's window by
    // its engine-side reduce-recv budget (conservative for place-only
    // spans, but the window refills as completions retire).
    for (size_t c = 0; c < nc; c++)
      r_win[c] = reduce_recv_window(r->lefts[c]);

    const size_t shards = tdr::progress_shards_for(nc);
    if (shards > 0 && N + M >= 4)
      return drive_sharded(r, *this, hub, shards, true, "ring(wave)");

    StallClock clock;
    for (;;) {
      {
        std::lock_guard<std::mutex> g(hub.mu);
        if (finished_locked()) break;
      }
      if (clock.deadline_exceeded())
        return deadline_fail(r, "ring(wave)", stall_detail());
      int p = post_more();
      if (p < 0) return -1;
      int nl = sweep_side(r->lefts, *this, true);
      if (nl < 0) return -1;
      int nr = sweep_side(r->rights, *this, false);
      if (nr < 0) return -1;
      if (p > 0 || nl > 0 || nr > 0) {
        clock.bump();
        continue;
      }
      int n = wait_owed(r, *this, 50);
      if (n < 0) return -1;
      if (n > 0) {
        clock.bump();
        continue;
      }
      if (clock.expired()) return stall_fail(r, "ring(wave)", stall_detail());
    }
    return 0;
  }
};


// Segment layout shared by allreduce and the standalone phases:
// world segments, first `rem` get one extra element.
void seg_layout(int world, size_t count, size_t esz,
                std::vector<size_t> *off, std::vector<size_t> *len) {
  off->resize(world);
  len->resize(world);
  size_t base = count / world, rem = count % world;
  size_t o = 0;
  for (int i = 0; i < world; i++) {
    (*off)[i] = o * esz;
    (*len)[i] = (base + (static_cast<size_t>(i) < rem ? 1 : 0)) * esz;
    o += base + (static_cast<size_t>(i) < rem ? 1 : 0);
  }
}

// Deregister a per-call (non-front-loaded) data MR on scope exit.
struct OwnedMrGuard {
  tdr_mr *mr;
  bool active;
  ~OwnedMrGuard() {
    if (active && mr) tdr_dereg_mr(mr);
  }
};

// Per-call-MR teardown race fix (documented by PR 7's conn-drop test):
// when a collective FAILS on this rank while its data MR was
// per-call-registered, returning immediately deregisters that MR while
// the peer may still have landings in flight on the surviving
// channels — those landings then complete the PEER's sends with
// LOC_ACCESS_ERR (non-retryable by taxonomy) even though the
// underlying fault was a transient drop. Defer the invalidation: keep
// the MR alive through a bounded quiet-interval drain, discarding
// completions until the QPs go quiet (the owed in-flight landings have
// materialized, or the sockets are dead and nothing more can arrive),
// and only then let OwnedMrGuard dereg. Success paths never get here —
// a finished schedule consumed every owed completion — so the steady
// state pays nothing; the discarded completions belong to the failed
// collective, which the caller recovers from by rebuilding.
void quiesce_before_dereg(tdr_ring *r, bool owned) {
  if (!owned) return;
  using clock = std::chrono::steady_clock;
  const auto quiet = std::chrono::milliseconds(100);
  const auto deadline =
      clock::now() +
      std::chrono::milliseconds(std::min(2000, ring_timeout_ms()));
  auto quiet_dl = clock::now() + quiet;
  tdr_wc wc[16];
  const bool same_qp = (r->lefts[0] == r->rights[0]);
  while (clock::now() < deadline && clock::now() < quiet_dl) {
    int got = 0;
    for (tdr_qp *qp : r->lefts) {
      int n = tdr_poll(qp, wc, 16, 0);
      if (n > 0) got += n;
    }
    if (!same_qp) {
      for (tdr_qp *qp : r->rights) {
        int n = tdr_poll(qp, wc, 16, 0);
        if (n > 0) got += n;
      }
    }
    if (got)
      quiet_dl = clock::now() + quiet;
    else
      std::this_thread::sleep_for(std::chrono::milliseconds(2));
  }
}

// The generic schedule's two phases, shared verbatim between
// allreduce and the standalone reduce_scatter/all_gather so the
// documented bit-for-bit composition identity cannot drift.
// Phase 1: reduce-scatter. After step s, segment (rank-s-1) holds the
// partial sum of s+2 ranks; after world-1 steps each rank owns the
// full reduction of segment (rank+1) mod world.
int run_rs_phase(StepPipe &pipe, tdr_ring *r,
                 const std::vector<size_t> &seg_off,
                 const std::vector<size_t> &seg_len) {
  const int world = r->world;
  for (int s = 0; s < world - 1; s++) {
    int send_seg = ((r->rank - s) % world + world) % world;
    int recv_seg = ((r->rank - s - 1) % world + world) % world;
    if (pipe.run(seg_off[send_seg], seg_len[send_seg], seg_off[recv_seg],
                 seg_len[recv_seg], /*reduce=*/true) != 0)
      return -1;
  }
  return 0;
}

// Phase 2: all-gather — fully-reduced segments circulate; received
// bytes land directly in the data MR (no scratch, no extra copy).
int run_ag_phase(StepPipe &pipe, tdr_ring *r,
                 const std::vector<size_t> &seg_off,
                 const std::vector<size_t> &seg_len) {
  const int world = r->world;
  for (int s = 0; s < world - 1; s++) {
    int send_seg = ((r->rank + 1 - s) % world + world) % world;
    int recv_seg = ((r->rank - s) % world + world) % world;
    if (pipe.run(seg_off[send_seg], seg_len[send_seg], seg_off[recv_seg],
                 seg_len[recv_seg], /*reduce=*/false) != 0)
      return -1;
  }
  return 0;
}

}  // namespace
}  // extern "C++"

int tdr_ring_allreduce(tdr_ring *r, void *data, size_t count, int dtype,
                       int red_op) {
  if (!r || !data) {
    tdr::set_error("ring_allreduce: null ring or data");
    return -1;
  }
  size_t esz = dtype_size(dtype);
  if (esz == 0) {
    tdr::set_error("ring: bad dtype");
    return -1;
  }
  if (dtype == TDR_DT_U8) {
    tdr::set_error("ring_allreduce: u8 is byte-transport only (no fold semantics)");
    return -1;
  }
  if (dtype == TDR_DT_I8) {
    tdr::set_error(
        "ring_allreduce: i8 reduces only via tdr_ring_allreduce_q8 "
        "(a scale-less int8 sum overflows)");
    return -1;
  }
  if (count == 0) return 0;
  // Fault-plan site "ring" (TDR_FAULT_PLAN, fault.cc): a transient
  // collective failure injected BEFORE any posting — the recovery
  // layer's deterministic trigger. The caller sees the same shape of
  // error a mid-step peer loss produces (retryable, nothing posted).
  {
    int f = tdr::fault_point("ring");
    if (f >= 0) {
      tdr::set_error("ring: fault injected (completion error status " +
                     std::to_string(f) + ")");
      return -1;
    }
  }
  std::lock_guard<std::mutex> g(r->mu);
  const int world = r->world;
  const size_t nbytes = count * esz;
  RingTelScope tel(r, nbytes);

  std::vector<size_t> seg_off, seg_len;
  seg_layout(world, count, esz, &seg_off, &seg_len);

  bool owned = false;
  tdr_mr *dmr = r->data_mr(data, nbytes, &owned);
  if (!dmr) return tel.finish(-1);
  if (!tdr_mr_cpu_foldable(dmr)) {
    // EVERY schedule folds host-side somewhere (recv_reduce slots or
    // the scratch window into the data pointer) — over a CPU-less
    // dma-buf MR that would scribble through a device IOVA. Fail
    // clearly up front; such buffers need switch offload or a
    // host-visible mapping (the emu backend mmaps its dma-bufs, so
    // only real-HCA device memory lands here).
    if (owned) tdr_dereg_mr(dmr);
    tdr::set_error(
        "ring_allreduce: data MR has no CPU mapping (verbs dma-buf); "
        "host-side reduction is impossible — register CPU-visible "
        "memory or use a host-staged collective");
    return tel.finish(-1);
  }
  OwnedMrGuard guard{dmr, owned};
  (void)guard;

  // World-2 fast path: phases overlapped chunk-wise (see FusedTwo).
  // Segment roles per the generic schedule below at world=2: this rank
  // sends seg[rank] out first (phase-1 send) and folds seg[1-rank].
  // Entry is gated on the NEGOTIATED fused2 capability (both ends
  // advertised it in the handshake; TDR_NO_FUSED2 acts there), so a
  // per-rank opt-out degrades BOTH ranks to the compatible rightward
  // schedule instead of a wire mismatch.
  if (world == 2 && r->left != r->right &&
      tdr_qp_has_recv_reduce(r->left) && tdr_qp_has_fused2(r->left) &&
      tdr_qp_has_fused2(r->right)) {
    FusedTwo f;
    f.r = r;
    f.dmr = dmr;
    f.dtype = dtype;
    f.red_op = red_op;
    f.chunk = r->chunk;
    f.a_off = seg_off[r->rank];
    f.a_len = seg_len[r->rank];
    f.b_off = seg_off[1 - r->rank];
    f.b_len = seg_len[1 - r->rank];
    f.n_a = FusedTwo::nchunks(f.a_len, f.chunk);
    f.n_b = FusedTwo::nchunks(f.b_len, f.chunk);
    // Foldback is a NEGOTIATED capability (both ends advertised it in
    // the QP handshake, where TDR_NO_FOLDBACK/TDR_NO_FUSED2 act), so
    // both ranks take the same branch here by construction.
    f.use_fb = tdr_qp_has_send_foldback(r->right);
    r->last_sched = f.use_fb ? TDR_SCHED_FUSED2_FB : TDR_SCHED_FUSED2;
    int rc = f.run();
    if (rc != 0) quiesce_before_dereg(r, owned);
    return tel.finish(rc);
  }

  // General wavefront path: the full 2(world-1)-step schedule
  // flattened into windowed lexicographic send/recv streams (see
  // Wavefront above). Needs reduce-on-receive (folds land in the data
  // MR from the progress engine) and distinct neighbor QPs.
  if (r->left != r->right && tdr_qp_has_recv_reduce(r->left) &&
      !wavefront_disabled()) {
    const size_t chunk = r->chunk;
    auto nch = [&](size_t len) {
      return len ? (len + chunk - 1) / chunk : size_t(0);
    };
    auto clen = [&](size_t total, size_t c) {
      return std::min(chunk, total - c * chunk);
    };
    const int steps = 2 * (world - 1);
    auto segs_at = [&](int t, int *send_seg, int *recv_seg) {
      if (t < world - 1) {  // reduce-scatter
        *send_seg = ((r->rank - t) % world + world) % world;
        *recv_seg = ((r->rank - t - 1) % world + world) % world;
      } else {  // all-gather
        int s2 = t - (world - 1);
        *send_seg = ((r->rank + 1 - s2) % world + world) % world;
        *recv_seg = ((r->rank - s2) % world + world) % world;
      }
    };
    // Last-RS-step foldback: the sends of step world-2 become
    // fold-and-write-back sends, so each rank's fully-reduced owned
    // segment comes back IN PLACE as the write-back — which is byte-
    // for-byte what the LAST all-gather step would have delivered.
    // That whole step (its sends and recvs, one full segment of
    // traffic and latency per rank) disappears: 2(world-1) steps
    // become 2*world-3. Every rank must take the same branch: the
    // gating condition (both neighbor QPs negotiated foldback) is
    // part of the Python layer's schedule digest, so a ring with
    // per-rank foldback divergence fails fast instead of
    // desynchronizing.
    const bool wave_fb = tdr_qp_has_send_foldback(r->right) &&
                         tdr_qp_has_send_foldback(r->left) &&
                         !tdr::env_set("TDR_NO_WAVE_FB");
    const int eff_steps = wave_fb ? steps - 1 : steps;
    Wavefront wf;
    wf.r = r;
    wf.dmr = dmr;
    wf.dtype = dtype;
    wf.red_op = red_op;
    std::vector<size_t> rprefix(steps + 1, 0);
    for (int t = 0; t < steps; t++) {
      int ss, rs;
      segs_at(t, &ss, &rs);
      rprefix[t + 1] = rprefix[t] + nch(seg_len[rs]);
    }
    for (int t = 0; t < eff_steps; t++) {
      int ss, rs;
      segs_at(t, &ss, &rs);
      const bool fold = t < world - 1;
      for (size_t c = 0; c < nch(seg_len[ss]); c++) {
        WaveItem it{seg_off[ss] + c * chunk, clen(seg_len[ss], c), false,
                    0, wave_fb && t == world - 2};
        // send (t,c) forwards the bytes recv (t-1,c) produced —
        // send_seg(t) IS recv_seg(t-1) — so its dependency is that
        // many completed receives.
        if (t > 0) it.dep = rprefix[t - 1] + c + 1;
        wf.sends.push_back(it);
      }
      for (size_t c = 0; c < nch(seg_len[rs]); c++)
        wf.recvs.push_back({seg_off[rs] + c * chunk, clen(seg_len[rs], c),
                            fold, 0});
    }
    r->last_sched = TDR_SCHED_WAVEFRONT;
    int rc = wf.run();
    if (rc != 0) quiesce_before_dereg(r, owned);
    return tel.finish(rc);
  }

  r->last_sched = TDR_SCHED_GENERIC;
  StepPipe pipe{r, dmr, static_cast<char *>(data), dtype, red_op, esz};
  int rc = run_rs_phase(pipe, r, seg_off, seg_len);
  if (rc == 0) rc = run_ag_phase(pipe, r, seg_off, seg_len);
  if (rc != 0) quiesce_before_dereg(r, owned);
  return tel.finish(rc);
}

// ------------------------------------------------------------------
// Standalone reduce-scatter / all-gather / broadcast — the rest of
// the MPI-app collective surface (SURVEY §1 L5, README.md:64: "IB
// Verbs interface must be used"; perftest/MPI consumers expect more
// than allreduce). reduce_scatter/all_gather ARE the allreduce's two
// generic phases (run_rs_phase/run_ag_phase — shared code, so the
// bit-for-bit composition identity cannot drift), with the same
// segment layout and the (rank+1) % world ownership convention.
// They always run the barrier-stepped generic schedule; the fused
// world-2 exchange and the flattened wavefront interleave the two
// phases and so exist only for the full allreduce — callers hot
// enough to care should call tdr_ring_allreduce, not the
// composition (measured 1.53x at world 4, SWEEP_W4_r05.json).
// ------------------------------------------------------------------

int tdr_ring_reduce_scatter(tdr_ring *r, void *data, size_t count,
                            int dtype, int red_op, size_t *own_off,
                            size_t *own_len) {
  if (!r || !data) {
    tdr::set_error("ring_reduce_scatter: null ring or data");
    return -1;
  }
  size_t esz = dtype_size(dtype);
  if (esz == 0) {
    tdr::set_error("ring: bad dtype");
    return -1;
  }
  if (dtype == TDR_DT_U8 || dtype == TDR_DT_I8) {
    tdr::set_error("ring_reduce_scatter: u8/i8 is byte-transport only (no fold semantics)");
    return -1;
  }
  std::lock_guard<std::mutex> g(r->mu);
  const int world = r->world;
  std::vector<size_t> seg_off, seg_len;
  seg_layout(world, count, esz, &seg_off, &seg_len);
  const int own = (r->rank + 1) % world;
  if (own_off) *own_off = seg_off[own];
  if (own_len) *own_len = seg_len[own];
  if (count == 0 || world == 1) return 0;
  RingTelScope tel(r, count * esz);
  bool owned = false;
  tdr_mr *dmr = r->data_mr(data, count * esz, &owned);
  if (!dmr) return tel.finish(-1);
  OwnedMrGuard guard{dmr, owned};
  (void)guard;
  if (!tdr_mr_cpu_foldable(dmr)) {
    tdr::set_error("ring_reduce_scatter: data MR has no CPU mapping");
    return tel.finish(-1);
  }
  StepPipe pipe{r, dmr, static_cast<char *>(data), dtype, red_op, esz};
  int rc = run_rs_phase(pipe, r, seg_off, seg_len);
  if (rc != 0) quiesce_before_dereg(r, owned);
  return tel.finish(rc);
}

int tdr_ring_all_gather(tdr_ring *r, void *data, size_t count, int dtype) {
  if (!r || !data) {
    tdr::set_error("ring_all_gather: null ring or data");
    return -1;
  }
  size_t esz = dtype_size(dtype);
  if (esz == 0) {
    tdr::set_error("ring: bad dtype");
    return -1;
  }
  if (count == 0) return 0;
  std::lock_guard<std::mutex> g(r->mu);
  const int world = r->world;
  if (world == 1) return 0;
  std::vector<size_t> seg_off, seg_len;
  seg_layout(world, count, esz, &seg_off, &seg_len);
  RingTelScope tel(r, count * esz);
  bool owned = false;
  tdr_mr *dmr = r->data_mr(data, count * esz, &owned);
  if (!dmr) return tel.finish(-1);
  OwnedMrGuard guard{dmr, owned};
  (void)guard;
  StepPipe pipe{r, dmr, static_cast<char *>(data), dtype, TDR_RED_SUM, esz};
  int rc = run_ag_phase(pipe, r, seg_off, seg_len);
  if (rc != 0) quiesce_before_dereg(r, owned);
  return tel.finish(rc);
}

namespace {

// Shared progress pump for the CHAIN collectives (broadcast down the
// ring, reduce converging toward root): window-bounded recv posting
// on the left QP, dependency-gated forwarding on the right (chunk i
// forwards only after chunk i landed, unless this rank is the chain
// head), opportunistic then blocking drains. The two callers differ
// ONLY in how a recv posts (plain vs reduce-on-receive), the window
// sizes, and the error label.
struct ChainPump {
  tdr_ring *r;
  size_t n_recv, n_send;
  size_t recv_win, send_win;
  bool head;  // no upstream: sends gate on nothing
  const char *label;
  // Dependency slack: send i may post once done_r >= i+1-send_lead.
  // 0 (chain collectives): forwarding send i needs recv i landed.
  // 1 (alltoall): send 0 carries locally-built data and must go
  // unconditionally or every rank deadlocks waiting for a first recv;
  // send i>=1 forwards the tail of recv i-1.
  size_t send_lead = 0;

  size_t posted_r = 0, done_r = 0, posted_s = 0, acked_s = 0;

  int run(const std::function<int(size_t)> &post_recv,
          const std::function<int(size_t)> &post_send) {
    const bool same_qp = (r->left == r->right);
    auto drain = [&](tdr_qp *qp, int timeout_ms) -> int {
      tdr_wc wc[16];
      int c = tdr_poll(qp, wc, 16, timeout_ms);
      if (c < 0) return -1;
      for (int i = 0; i < c; i++) {
        if (wc[i].status != TDR_WC_SUCCESS) {
          tdr::set_error(std::string(label) + ": completion error status " +
                         wc_status_label(wc[i].status));
          return -1;
        }
        uint64_t kind = wc[i].wr_id & kWrKindMask;
        if (kind == kWrSend) {
          acked_s++;
        } else if (kind == kWrRecv) {
          size_t idx = wc[i].wr_id & ~kWrKindMask;
          if (idx != done_r) {
            tdr::set_error(std::string(label) +
                           ": out-of-order recv completion");
            return -1;
          }
          done_r++;
        }
      }
      return c;
    };

    while (done_r < n_recv || acked_s < n_send) {
      bool progressed = false;
      while (posted_r < n_recv && posted_r - done_r < recv_win) {
        if (post_recv(posted_r) != 0) return -1;
        posted_r++;
        progressed = true;
      }
      while (posted_s < n_send && posted_s - acked_s < send_win &&
             (head || posted_s < done_r + send_lead)) {
        if (post_send(posted_s) != 0) return -1;
        posted_s++;
        progressed = true;
      }
      int nl = n_recv ? drain(r->left, 0) : 0;
      if (nl < 0) return -1;
      int nr = (n_send && !same_qp) ? drain(r->right, 0) : 0;
      if (nr < 0) return -1;
      if (nl > 0 || nr > 0) progressed = true;
      if (!progressed) {
        tdr_qp *qp = (done_r < n_recv) ? r->left : r->right;
        int c = drain(qp, ring_timeout_ms());
        if (c < 0) return -1;
        if (c == 0) {
          tdr::set_error(std::string(label) + ": poll timeout (s " +
                         std::to_string(acked_s) + "/" +
                         std::to_string(n_send) + " r " +
                         std::to_string(done_r) + "/" +
                         std::to_string(n_recv) + ")");
          return -1;
        }
      }
    }
    return 0;
  }
};

}  // namespace

int tdr_ring_reduce(tdr_ring *r, void *data, size_t count, int dtype,
                    int red_op, int root) {
  if (!r || !data) {
    tdr::set_error("ring_reduce: null ring or data");
    return -1;
  }
  size_t esz = dtype_size(dtype);
  if (esz == 0) {
    tdr::set_error("ring: bad dtype");
    return -1;
  }
  if (dtype == TDR_DT_U8 || dtype == TDR_DT_I8) {
    tdr::set_error("ring_reduce: u8/i8 is byte-transport only (no fold semantics)");
    return -1;
  }
  std::lock_guard<std::mutex> g(r->mu);
  const int world = r->world;
  if (root < 0 || root >= world) {
    tdr::set_error("ring_reduce: bad root");
    return -1;
  }
  if (count == 0 || world == 1) return 0;
  const size_t nbytes = count * esz;
  RingTelScope tel(r, nbytes);
  bool owned = false;
  tdr_mr *dmr = r->data_mr(data, nbytes, &owned);
  if (!dmr) return tel.finish(-1);
  OwnedMrGuard guard{dmr, owned};
  (void)guard;
  if (!tdr_mr_cpu_foldable(dmr)) {
    tdr::set_error("ring_reduce: data MR has no CPU mapping");
    return tel.finish(-1);
  }
  if (!tdr_qp_has_recv_reduce(r->left)) {
    // Only the RECEIVING side needs the fused op (a plain SEND
    // matches a posted recv_reduce fine); both in-repo engines
    // advertise it, so this guards future engines only.
    tdr::set_error("ring_reduce: engine lacks reduce-on-receive");
    return tel.finish(-1);
  }

  // Converging fold toward root, rightward along the ring: the chain
  // head ((root+1) % world) streams its buffer right; every
  // intermediate rank reduce-receives inbound chunks INTO its own
  // buffer (the fused recv_reduce op — fold completion IS the recv
  // completion) and forwards the folded chunk on; root only
  // reduce-receives. One N-byte pass per link, chunk-pipelined.
  // In-place and destructive on non-root ranks: their buffers end
  // holding the partial sums that passed through them. Windows: recv
  // bounded by OUR reduce-recv budget, sends by the downstream
  // peer's (symmetric config).
  const size_t chunk = r->chunk;
  const size_t n = (nbytes + chunk - 1) / chunk;
  const int d = ((r->rank - root) % world + world) % world;
  auto clen = [&](size_t i) { return std::min(chunk, nbytes - i * chunk); };
  ChainPump pump{r,
                 /*n_recv=*/d != 1 ? n : 0,
                 /*n_send=*/d != 0 ? n : 0,
                 /*recv_win=*/reduce_recv_window(r->left),
                 /*send_win=*/reduce_recv_window(r->right),
                 /*head=*/d == 1,
                 "ring(reduce)"};
  return tel.finish(pump.run(
      [&](size_t i) {
        return tdr_post_recv_reduce(r->left, dmr, i * chunk, clen(i),
                                    dtype, red_op, kWrRecv | i);
      },
      [&](size_t i) {
        return tdr_post_send(r->right, dmr, i * chunk, clen(i),
                             kWrSend | i);
      }));
}

int tdr_ring_broadcast(tdr_ring *r, void *data, size_t nbytes, int root) {
  if (!r || !data) {
    tdr::set_error("ring_broadcast: null ring or data");
    return -1;
  }
  std::lock_guard<std::mutex> g(r->mu);
  const int world = r->world;
  if (root < 0 || root >= world) {
    tdr::set_error("ring_broadcast: bad root");
    return -1;
  }
  if (nbytes == 0 || world == 1) return 0;
  RingTelScope tel(r, nbytes);
  bool owned = false;
  tdr_mr *dmr = r->data_mr(data, nbytes, &owned);
  if (!dmr) return tel.finish(-1);
  OwnedMrGuard guard{dmr, owned};
  (void)guard;

  // Store-and-forward pipeline down the ring: the root streams chunks
  // rightward; middle ranks forward chunk i the moment its receive
  // lands (bytes are final — each chunk is received exactly once, so
  // the forwarding send may safely read the data MR); the last rank
  // ((root-1+world) % world) only receives. Bandwidth-optimal for
  // large messages, latency (world-1) extra chunks.
  const size_t chunk = r->chunk;
  const size_t n = (nbytes + chunk - 1) / chunk;
  const int d = ((r->rank - root) % world + world) % world;
  auto clen = [&](size_t i) { return std::min(chunk, nbytes - i * chunk); };
  ChainPump pump{r,
                 /*n_recv=*/d != 0 ? n : 0,
                 /*n_send=*/d != world - 1 ? n : 0,
                 /*recv_win=*/kMaxOutstanding,
                 /*send_win=*/kMaxOutstanding,
                 /*head=*/d == 0,
                 "ring(bcast)"};
  return tel.finish(pump.run(
      [&](size_t i) {
        return tdr_post_recv(r->left, dmr, i * chunk, clen(i),
                             kWrRecv | i);
      },
      [&](size_t i) {
        return tdr_post_send(r->right, dmr, i * chunk, clen(i),
                             kWrSend | i);
      }));
}

namespace {
// The alltoall staging can dwarf what any other collective retains:
// keep small scratch cached (steady-state reuse) but release
// oversized growth rather than pinning it for the ring's lifetime.
void release_big_scratch(tdr_ring *r, size_t total) {
  if (total <= (64u << 20)) return;
  if (r->tmp_mr) {
    tdr_dereg_mr(r->tmp_mr);
    r->tmp_mr = nullptr;
  }
  r->tmp.clear();
  r->tmp.shrink_to_fit();
}
}  // namespace

/* In-place all-to-all (MPI_Alltoall with MPI_IN_PLACE semantics):
 * ``data`` holds ``world`` equal segments; segment j is FOR rank j on
 * entry and FROM rank j on return (this rank's own segment is
 * untouched). Bundle-shrink ring schedule: rank r first sends the
 * w-1 foreign segments ordered by destination distance
 * [dst r+1, r+2, ...]; each received bundle's head is addressed to
 * this rank (kept) and its tail IS the next step's send bundle,
 * forwarded straight out of the receive slot — no re-pack copy. Per
 * link w(w-1)/2 segments cross, the ring-topology optimum for
 * store-and-forward all-to-all. */
int tdr_ring_alltoall(tdr_ring *r, void *data, size_t count, int dtype) {
  if (!r || !data) {
    tdr::set_error("ring_alltoall: null ring or data");
    return -1;
  }
  size_t esz = dtype_size(dtype);
  if (esz == 0) {
    tdr::set_error("ring: bad dtype");
    return -1;
  }
  std::lock_guard<std::mutex> g(r->mu);
  const int world = r->world;
  if (count % static_cast<size_t>(world) != 0) {
    tdr::set_error("ring_alltoall: count must divide evenly by world "
                   "(equal segments, MPI_Alltoall semantics)");
    return -1;
  }
  if (count == 0 || world == 1) return 0;
  RingTelScope tel(r, count * esz);
  const size_t segsz = count / world * esz;
  const int rank = r->rank;
  const size_t steps = static_cast<size_t>(world) - 1;

  if (world == 2) {
    // Direct exchange: ONE foreign segment each way. Stage only the
    // outgoing segment (its slot in `data` is about to be overwritten
    // by the inbound one — sending straight from `data` would race
    // the landing recv), receive the peer's segment directly into
    // place. One local copy instead of the bundle path's three.
    const size_t peer = static_cast<size_t>(1 - rank);
    char *db = static_cast<char *>(data);
    // Prefer a caller-registered full-buffer MR (front-loaded
    // registration); otherwise pin ONLY the received segment — the
    // wire never touches the rest of the buffer.
    tdr_mr *dmr = nullptr;
    bool owned = false;
    size_t roff = peer * segsz;
    auto it = r->registered.find(reinterpret_cast<uint64_t>(data));
    if (it != r->registered.end() &&
        tdr_mr_len(it->second) >= count * esz) {
      dmr = it->second;
    } else {
      dmr = tdr_reg_mr(r->eng, db + peer * segsz, segsz, 0);
      owned = true;
      roff = 0;
    }
    if (!dmr) return tel.finish(-1);
    OwnedMrGuard guard{dmr, owned};
    (void)guard;
    tdr_mr *smr = r->scratch(segsz);
    if (!smr) return tel.finish(-1);
    std::memcpy(r->tmp.data(), db + peer * segsz, segsz);
    ChainPump pump{r, /*n_recv=*/1, /*n_send=*/1, 1, 1, /*head=*/true,
                   "ring(alltoall2)"};
    int rc = pump.run(
        [&](size_t) {
          return tdr_post_recv(r->left, dmr, roff, segsz, kWrRecv | 0);
        },
        [&](size_t) {
          return tdr_post_send(r->right, smr, 0, segsz, kWrSend | 0);
        });
    if (rc == 0) release_big_scratch(r, segsz);
    return tel.finish(rc);
  }
  // No data MR on the general path: the user buffer never touches the
  // wire — bundles stage through the scratch MR and the buffer is
  // only memcpy'd, so registering it would be a pure per-call
  // pin/unpin tax.

  // Scratch: the outgoing first bundle (w-1 segments) + one receive
  // slot per step, slot ri sized (w-1-ri) segments.
  std::vector<size_t> slot_off(steps);
  size_t total = steps * segsz;  // first-bundle staging at offset 0
  for (size_t ri = 0; ri < steps; ri++) {
    slot_off[ri] = total;
    total += (steps - ri) * segsz;
  }
  tdr_mr *smr = r->scratch(total);
  if (!smr) return tel.finish(-1);
  char *sb = r->tmp.data();
  char *db = static_cast<char *>(data);

  // First bundle: foreign segments by destination distance.
  for (size_t i = 0; i < steps; i++) {
    int dst = static_cast<int>((rank + 1 + i) % world);
    std::memcpy(sb + i * segsz, db + static_cast<size_t>(dst) * segsz,
                segsz);
  }

  ChainPump pump{r,
                 /*n_recv=*/steps,
                 /*n_send=*/steps,
                 /*recv_win=*/kMaxOutstanding,
                 /*send_win=*/kMaxOutstanding,
                 /*head=*/false,
                 "ring(alltoall)"};
  pump.send_lead = 1;  // send 0 is locally built; send i forwards recv i-1
  int rc = pump.run(
      [&](size_t ri) {
        return tdr_post_recv(r->left, smr, slot_off[ri],
                             (steps - ri) * segsz, kWrRecv | ri);
      },
      [&](size_t i) {
        size_t off = i == 0 ? 0 : slot_off[i - 1] + segsz;
        return tdr_post_send(r->right, smr, off, (steps - i) * segsz,
                             kWrSend | i);
      });
  if (rc != 0) return tel.finish(rc);

  // Keep every bundle head: recv step ri carried the segment from
  // src (rank-1-ri) mod world addressed to this rank.
  for (size_t ri = 0; ri < steps; ri++) {
    int src = static_cast<int>(
        ((rank - 1 - static_cast<int>(ri)) % world + world) % world);
    std::memcpy(db + static_cast<size_t>(src) * segsz, sb + slot_off[ri],
                segsz);
  }
  release_big_scratch(r, total);
  return tel.finish(0);
}

/* int8 wire-compressed allreduce (see tdr.h): the textbook RS+AG ring
 * where every wire piece is [f32 running scale][int8 segment] inside
 * an ordinary sealed SEND payload — no frame-format change, so seal
 * verification and the NAK/retransmit heal apply to the compressed
 * pieces exactly as to any other payload. The fold REQUANTIZES under
 * the summed scale (fold_q8, util.cc), so magnitudes never clip at
 * any world size; the all-gather then circulates the reduced
 * [scale][q8] pieces VERBATIM, which is what makes the final dequant
 * bitwise identical on every rank (each segment's bits were produced
 * once, by its owner's fold chain, in ring order). Pieces stage
 * through the ring-owned scratch MR (the alltoall staging precedent):
 * the caller's q8/f32_out buffers never touch the wire, so no
 * per-call data MR and no quiesce-before-dereg hazard on failure. */
int tdr_ring_allreduce_q8(tdr_ring *r, void *q8, size_t count,
                          float scale_in, float *f32_out) {
  if (!r || !q8 || !f32_out) {
    tdr::set_error("ring_allreduce_q8: null ring or buffer");
    return -1;
  }
  // Capability gate, fatal: the peer must run the SAME quantized
  // schedule (piece sizes halve), so an un-negotiated ring fails fast
  // here instead of desynchronizing the wire. The Python digest pins
  // the fleet-wide agreement; this pins the per-link handshake.
  for (size_t c = 0; c < r->lefts.size(); c++) {
    if (!tdr_qp_has_wire_q8(r->lefts[c]) ||
        !tdr_qp_has_wire_q8(r->rights[c])) {
      tdr::set_error(
          "ring_allreduce_q8: FEAT_WIRE_Q8 not negotiated on this ring "
          "(legacy peer or TDR_NO_WIRE_Q8)");
      return -1;
    }
  }
  if (count == 0) return 0;
  // Same deterministic fault trigger as the blocking allreduce.
  {
    int f = tdr::fault_point("ring");
    if (f >= 0) {
      tdr::set_error("ring: fault injected (completion error status " +
                     std::to_string(f) + ")");
      return -1;
    }
  }
  std::lock_guard<std::mutex> g(r->mu);
  const int world = r->world;
  int8_t *q = static_cast<int8_t *>(q8);
  RingTelScope tel(r, count);  // semantic payload: count int8 bytes
  r->last_sched = TDR_SCHED_Q8;

  // esz 1: offsets/lengths are in elements AND bytes.
  std::vector<size_t> seg_off, seg_len;
  seg_layout(world, count, 1, &seg_off, &seg_len);
  size_t max_len = 0;
  for (size_t l : seg_len) max_len = std::max(max_len, l);
  const size_t piece = sizeof(float) + max_len;
  // Scratch: [send piece][recv piece]. Sends drain fully (send acked)
  // before the next step restages, so one slot each suffices.
  tdr_mr *smr = r->scratch(2 * piece);
  if (!smr) return tel.finish(-1);
  char *sb = r->tmp.data();
  char *rb = r->tmp.data() + piece;

  // Per-segment running scales: every rank starts from its own
  // per-bucket scale; a fold advances the receiving segment's scale
  // to the sum of the contributions folded so far.
  std::vector<float> scales(static_cast<size_t>(world), scale_in);

  // One ring step: stage [scale][q8] of send_seg, exchange with the
  // neighbors (recv posted before send, ChainPump discipline). Empty
  // segments still move their 4-byte scale header so the step count
  // stays uniform across ranks whatever count % world is.
  auto xfer = [&](int send_seg, int recv_seg) -> int {
    std::memcpy(sb, &scales[static_cast<size_t>(send_seg)], sizeof(float));
    std::memcpy(sb + sizeof(float), q + seg_off[send_seg],
                seg_len[send_seg]);
    ChainPump pump{r, /*n_recv=*/1, /*n_send=*/1, 1, 1, /*head=*/true,
                   "ring(q8)"};
    return pump.run(
        [&](size_t) {
          return tdr_post_recv(r->left, smr, piece,
                               sizeof(float) + seg_len[recv_seg],
                               kWrRecv | 0);
        },
        [&](size_t) {
          return tdr_post_send(r->right, smr, 0,
                               sizeof(float) + seg_len[send_seg],
                               kWrSend | 0);
        });
  };

  // Phase 1: reduce-scatter with the requantizing dequant-fold —
  // run_rs_phase's segment walk, piece-sized steps.
  int rc = 0;
  for (int s = 0; s < world - 1 && rc == 0; s++) {
    int send_seg = ((r->rank - s) % world + world) % world;
    int recv_seg = ((r->rank - s - 1) % world + world) % world;
    rc = xfer(send_seg, recv_seg);
    if (rc != 0) break;
    float s_f;
    std::memcpy(&s_f, rb, sizeof(float));
    tdr::fold_q8(q + seg_off[recv_seg],
                 scales[static_cast<size_t>(recv_seg)],
                 reinterpret_cast<const int8_t *>(rb + sizeof(float)),
                 s_f, seg_len[recv_seg]);
    scales[static_cast<size_t>(recv_seg)] += s_f;
  }

  // Phase 2: all-gather — the reduced [scale][q8] pieces circulate
  // verbatim (byte transport, no refold), run_ag_phase's walk.
  for (int s = 0; s < world - 1 && rc == 0; s++) {
    int send_seg = ((r->rank + 1 - s) % world + world) % world;
    int recv_seg = ((r->rank - s) % world + world) % world;
    rc = xfer(send_seg, recv_seg);
    if (rc != 0) break;
    std::memcpy(&scales[static_cast<size_t>(recv_seg)], rb,
                sizeof(float));
    std::memcpy(q + seg_off[recv_seg], rb + sizeof(float),
                seg_len[recv_seg]);
  }

  if (rc == 0) {
    for (int i = 0; i < world; i++)
      tdr::dequant_q8(f32_out + seg_off[i], q + seg_off[i], seg_len[i],
                      scales[static_cast<size_t>(i)]);
    release_big_scratch(r, 2 * piece);
  }
  return tel.finish(rc);
}

}  // extern "C"
