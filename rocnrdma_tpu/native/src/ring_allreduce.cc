// Ring allreduce over the engine: reduce-scatter + all-gather.
//
// The reference stops at the transport (its consumers were MPI apps on
// IB Verbs, README.md:64); this file is the in-framework consumer that
// BASELINE.md configs 3-4 require — the collective that cross-slice
// gradient sync rides. Buffers are registered once per (buffer, ring)
// pair and cached, preserving the reference's front-loaded-registration
// invariant: steady-state steps post work requests only.
//
// Large segments are split into chunks (TDR_RING_CHUNK, default 8 MiB)
// with a small window of pre-posted receives, so the wire transfer of
// chunk i+1 overlaps the reduction of chunk i and the link never idles
// behind the ALU.

#include <algorithm>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <unordered_map>
#include <vector>

#include "common.h"
#include "tdr/tdr.h"

namespace {

constexpr size_t kDefaultChunk = 8u << 20;
constexpr int kWindow = 4;  // pre-posted recv slots per step
// Cap on work requests in flight per direction, below the verbs
// backend's QP depth (max_send_wr/max_recv_wr = 512) with headroom —
// tiny TDR_RING_CHUNK values otherwise overflow ibv_post_* on large
// segments (the emu backend's unbounded queues would hide that).
constexpr size_t kMaxOutstanding = 256;

size_t ring_chunk_bytes() {
  const char *env = getenv("TDR_RING_CHUNK");
  if (env && *env) {
    long long v = atoll(env);
    if (v >= 4096) return static_cast<size_t>(v);
  }
  return kDefaultChunk;
}

using tdr::dtype_size;
using tdr::reduce_any;

// wr_id tags for the pipeline: high 16 bits the kind, low bits the
// chunk index, so one poll loop can route recv completions (in posted
// order) and send acks (order-independent, only counted).
constexpr uint64_t kWrRecv = 0x5245ull << 48;
constexpr uint64_t kWrSend = 0x5345ull << 48;
constexpr uint64_t kWrKindMask = 0xffffull << 48;

}  // namespace

struct tdr_ring {
  tdr_engine *eng;
  tdr_qp *left;   // receive from
  tdr_qp *right;  // send to
  int rank;
  int world;
  size_t chunk = kDefaultChunk;
  std::vector<char> tmp;
  tdr_mr *tmp_mr = nullptr;
  // MRs for buffers the CALLER promised stable (tdr_ring_register) —
  // the front-loaded-registration fast path. Arbitrary buffers are
  // registered per call instead: a VA-keyed implicit cache would hand
  // out stale pins when an address gets recycled by the allocator
  // (the underlying physical pages of a dead buffer, not the new one).
  std::unordered_map<uint64_t, tdr_mr *> registered;
  std::mutex mu;

  // Returns the MR and whether it is borrowed (cached) or owned by
  // this call (must be deregistered before returning).
  tdr_mr *data_mr(void *base, size_t len, bool *owned) {
    uint64_t key = reinterpret_cast<uint64_t>(base);
    auto it = registered.find(key);
    if (it != registered.end() && tdr_mr_len(it->second) >= len) {
      *owned = false;
      return it->second;
    }
    *owned = true;
    return tdr_reg_mr(eng, base, len, 0);
  }

  tdr_mr *scratch(size_t len) {
    if (tmp.size() < len || !tmp_mr) {
      if (tmp_mr) {
        tdr_dereg_mr(tmp_mr);
        tmp_mr = nullptr;
      }
      tmp.resize(len);
      tmp_mr = tdr_reg_mr(eng, tmp.data(), tmp.size(), 0);
    }
    return tmp_mr;
  }
};

extern "C" {

tdr_ring *tdr_ring_create(tdr_engine *e, tdr_qp *left, tdr_qp *right,
                          int rank, int world) {
  if (!e || !left || !right || world < 2 || rank < 0 || rank >= world) {
    tdr::set_error("ring_create: bad topology");
    return nullptr;
  }
  auto *r = new tdr_ring();
  r->eng = e;
  r->left = left;
  r->right = right;
  r->rank = rank;
  r->world = world;
  r->chunk = ring_chunk_bytes();
  return r;
}

void tdr_ring_destroy(tdr_ring *r) {
  if (!r) return;
  for (auto &kv : r->registered) tdr_dereg_mr(kv.second);
  if (r->tmp_mr) tdr_dereg_mr(r->tmp_mr);
  delete r;
}

// Pre-register a buffer whose lifetime the caller guarantees to
// outlast the ring (or until tdr_ring_unregister). Steady-state
// allreduces on it then post work requests only — the front-loaded
// registration invariant of the reference (SURVEY.md §3.3).
int tdr_ring_register(tdr_ring *r, void *base, size_t len) {
  if (!r || !base || !len) {
    tdr::set_error("ring_register: bad args");
    return -1;
  }
  std::lock_guard<std::mutex> g(r->mu);
  uint64_t key = reinterpret_cast<uint64_t>(base);
  auto it = r->registered.find(key);
  if (it != r->registered.end()) {
    if (tdr_mr_len(it->second) >= len) return 0;
    tdr_dereg_mr(it->second);
    r->registered.erase(it);
  }
  tdr_mr *mr = tdr_reg_mr(r->eng, base, len, 0);
  if (!mr) return -1;
  r->registered[key] = mr;
  return 0;
}

int tdr_ring_unregister(tdr_ring *r, void *base) {
  if (!r) return -1;
  std::lock_guard<std::mutex> g(r->mu);
  auto it = r->registered.find(reinterpret_cast<uint64_t>(base));
  if (it == r->registered.end()) return -1;
  tdr_dereg_mr(it->second);
  r->registered.erase(it);
  return 0;
}

namespace {

struct StepPipe {
  tdr_ring *r;
  tdr_mr *dmr;
  char *cdata;
  int dtype, red_op;
  size_t esz;

  // One neighbor-exchange step: stream `send_len` bytes of the data
  // buffer at `send_off` rightward while receiving `recv_len` bytes
  // from the left, chunked so transfer and reduction overlap.
  //
  // reduce=true → phase-1 semantics: inbound chunks are folded into
  // data at recv_off. On engines with reduce-on-receive the fold
  // happens in the transport's progress engine directly from the
  // inbound bytes (no scratch at all); otherwise chunks land in a
  // windowed scratch and are folded here.
  // reduce=false → phase-2 semantics: receives land directly in the
  // data MR at recv_off (no copy, no reduce).
  int run(size_t send_off, size_t send_len, size_t recv_off, size_t recv_len,
          bool reduce) {
    const size_t chunk = r->chunk;
    const size_t n_send = send_len ? (send_len + chunk - 1) / chunk : 0;
    const size_t n_recv = recv_len ? (recv_len + chunk - 1) / chunk : 0;
    const bool fused = reduce && tdr_qp_has_recv_reduce(r->left);
    const bool windowed = reduce && !fused;
    const size_t slots =
        windowed ? (n_recv < static_cast<size_t>(kWindow)
                        ? (n_recv ? n_recv : 1)
                        : kWindow)
                 : 0;
    const size_t slot_bytes =
        windowed ? std::min(chunk, recv_len ? recv_len : 1) : 0;
    tdr_mr *tmr = nullptr;
    if (windowed && n_recv) {
      tmr = r->scratch(slots * slot_bytes);
      if (!tmr) return -1;
    }

    auto chunk_len = [chunk](size_t total, size_t i) {
      size_t start = i * chunk;
      return std::min(chunk, total - start);
    };

    size_t posted_r = 0, done_r = 0, posted_s = 0, acked_s = 0;

    auto post_recv_chunk = [&](size_t i) -> int {
      size_t len = chunk_len(recv_len, i);
      if (fused)
        return tdr_post_recv_reduce(r->left, dmr, recv_off + i * chunk, len,
                                    dtype, red_op, kWrRecv | i);
      if (windowed) {
        size_t slot = i % slots;
        return tdr_post_recv(r->left, tmr, slot * slot_bytes, len,
                             kWrRecv | i);
      }
      return tdr_post_recv(r->left, dmr, recv_off + i * chunk, len,
                           kWrRecv | i);
    };

    // Receives without a slot dependency (phase 2, and fused phase 1 —
    // disjoint folds straight into the data MR) are pre-posted deep so
    // inbound chunks always have a landing target; windowed phase-1
    // receives pre-post up to the scratch window. Both bounded by the
    // QP depth — drain() reposts as completions retire.
    size_t prepost = windowed ? std::min(n_recv, slots)
                              : std::min(n_recv, kMaxOutstanding);
    for (; posted_r < prepost; posted_r++)
      if (post_recv_chunk(posted_r) != 0) return -1;

    const bool same_qp = (r->left == r->right);
    auto drain = [&](tdr_qp *qp, int timeout_ms) -> int {
      tdr_wc wc[16];
      int n = tdr_poll(qp, wc, 16, timeout_ms);
      if (n < 0) return -1;
      for (int i = 0; i < n; i++) {
        if (wc[i].status != TDR_WC_SUCCESS) {
          tdr::set_error("ring: completion error status " +
                         std::to_string(wc[i].status));
          return -1;
        }
        uint64_t kind = wc[i].wr_id & kWrKindMask;
        if (kind == kWrSend) {
          acked_s++;
        } else if (kind == kWrRecv) {
          // TCP FIFO + FIFO recv queue ⇒ recv completions arrive in
          // chunk order; fold and recycle the slot.
          size_t idx = wc[i].wr_id & ~kWrKindMask;
          if (idx != done_r) {
            tdr::set_error("ring: out-of-order recv completion");
            return -1;
          }
          if (windowed) {
            size_t len = chunk_len(recv_len, idx);
            tdr::par_reduce(cdata + recv_off + idx * chunk,
                            r->tmp.data() + (idx % slots) * slot_bytes,
                            len / esz, dtype, red_op);
          }
          done_r++;
          if (posted_r < n_recv) {
            if (post_recv_chunk(posted_r) != 0) return -1;
            posted_r++;
          }
        }
      }
      return n;
    };

    while (done_r < n_recv || acked_s < n_send) {
      // Keep outbound traffic moving: in stream mode this blocks while
      // the chunk drains into the socket (the progress thread lands
      // inbound chunks concurrently); in CMA mode it just queues a
      // descriptor. In phase 1 stay within the peer's recv window —
      // the schedule is symmetric, so our reduce progress tracks the
      // peer's posted recvs; racing ahead would push inbound messages
      // onto the unexpected (bounce-buffer) path and double-copy them.
      bool may_send = posted_s < n_send &&
                      posted_s - acked_s < kMaxOutstanding &&
                      (!windowed || n_recv == 0 || posted_s < done_r + slots);
      if (may_send) {
        size_t len = chunk_len(send_len, posted_s);
        if (tdr_post_send(r->right, dmr, send_off + posted_s * chunk, len,
                          kWrSend | posted_s) != 0)
          return -1;
        posted_s++;
        // Opportunistically reap without blocking so slots recycle.
        if (drain(r->left, 0) < 0) return -1;
        if (!same_qp && drain(r->right, 0) < 0) return -1;
        continue;
      }
      // All sends posted: block for what remains.
      bool need_recv = done_r < n_recv;
      tdr_qp *qp = need_recv ? r->left : r->right;
      int n = drain(qp, 30000);
      if (n < 0) return -1;
      if (n == 0) {
        tdr::set_error("ring: poll timeout");
        return -1;
      }
      if (!same_qp && need_recv && acked_s < n_send) {
        if (drain(r->right, 0) < 0) return -1;
      }
    }
    return 0;
  }
};

}  // namespace

int tdr_ring_allreduce(tdr_ring *r, void *data, size_t count, int dtype,
                       int red_op) {
  if (!r || !data) {
    tdr::set_error("ring_allreduce: null ring or data");
    return -1;
  }
  size_t esz = dtype_size(dtype);
  if (esz == 0) {
    tdr::set_error("ring: bad dtype");
    return -1;
  }
  if (count == 0) return 0;
  std::lock_guard<std::mutex> g(r->mu);
  const int world = r->world;
  const size_t nbytes = count * esz;

  // Segment layout: world segments, first `rem` get one extra element.
  std::vector<size_t> seg_off(world), seg_len(world);
  size_t base = count / world, rem = count % world;
  size_t off = 0;
  for (int i = 0; i < world; i++) {
    seg_off[i] = off * esz;
    seg_len[i] = (base + (static_cast<size_t>(i) < rem ? 1 : 0)) * esz;
    off += base + (static_cast<size_t>(i) < rem ? 1 : 0);
  }

  bool owned = false;
  tdr_mr *dmr = r->data_mr(data, nbytes, &owned);
  if (!dmr) return -1;
  struct OwnedGuard {
    tdr_mr *mr;
    bool active;
    ~OwnedGuard() {
      if (active && mr) tdr_dereg_mr(mr);
    }
  } guard{dmr, owned};
  (void)guard;

  StepPipe pipe{r, dmr, static_cast<char *>(data), dtype, red_op, esz};

  // Phase 1: reduce-scatter. After step s, segment (rank-s-1) holds the
  // partial sum of s+2 ranks; after world-1 steps each rank owns the
  // full reduction of segment (rank+1) mod world.
  for (int s = 0; s < world - 1; s++) {
    int send_seg = ((r->rank - s) % world + world) % world;
    int recv_seg = ((r->rank - s - 1) % world + world) % world;
    if (pipe.run(seg_off[send_seg], seg_len[send_seg], seg_off[recv_seg],
                 seg_len[recv_seg], /*reduce=*/true) != 0)
      return -1;
  }

  // Phase 2: all-gather — fully-reduced segments circulate; received
  // bytes land directly in the data MR (no scratch, no extra copy).
  for (int s = 0; s < world - 1; s++) {
    int send_seg = ((r->rank + 1 - s) % world + world) % world;
    int recv_seg = ((r->rank - s) % world + world) % world;
    if (pipe.run(seg_off[send_seg], seg_len[send_seg], seg_off[recv_seg],
                 seg_len[recv_seg], /*reduce=*/false) != 0)
      return -1;
  }
  return 0;
}

}  // extern "C"
