// Flight recorder: the engine-side telemetry subsystem.
//
// The reference's only observability was printk-and-read-dmesg
// (amdp2p.c:57-64) and this repo's Python tracer covered only the
// Python tiers — everything inside the engine (chunk post → wire →
// land → verify → fold → complete, seal NAK/retransmit, copy-pool
// work) was invisible except as aggregate counters bridged after the
// fact. This file is the missing half: a bounded ring of fixed-size
// timestamped events, log2-bucket latency/bandwidth histograms, and a
// unified counter registry, all behind a single TDR_TELEMETRY gate
// whose off state costs one predicted branch per event site.
//
// Concurrency model: producers are the posting threads and each QP's
// progress thread. Events are 40 bytes; recording takes a short
// mutex-protected append (the "drained under the engine lock" option
// the design allows — contention is negligible next to the payload
// copies the instrumented paths perform, and a mutex keeps the drain
// and overwrite-oldest semantics trivially correct under ASan/TSan).
// The ring OVERWRITES OLDEST when full — flight-recorder semantics:
// after an unbounded soak the recent past is what the crash report
// needs — and counts every overwrite in `dropped`.
//
// Clock: CLOCK_MONOTONIC ns, the same clock CPython's time.monotonic()
// reads on Linux, so native and Python events merge with no epoch
// translation.

#include <time.h>

#include <atomic>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <vector>

#include "common.h"
#include "tdr/tdr.h"

namespace tdr {

std::atomic<int> g_tel_state{0};

namespace {

constexpr size_t kRingDefault = 65536;
constexpr size_t kRingMin = 1024;
constexpr size_t kRingMax = 4u << 20;

std::mutex g_mu;  // guards the ring, its cursors, and reconfiguration
std::vector<tdr_tel_event> g_ring;
size_t g_head = 0;   // oldest live event
size_t g_count = 0;  // live events in the ring
std::atomic<uint64_t> g_recorded{0};
std::atomic<uint64_t> g_dropped{0};

// Histograms are log-linear ("log2 × 8"): 8 linear sub-buckets per
// power-of-two octave, values 0..15 exact. BENCH_r06 showed why pure
// log2 buckets are not enough: every latency percentile sat at an
// octave upper edge (8191/32767/65535 µs) — the estimate was the
// bucket, not the value. Sub-bucketing bounds the relative error at
// 12.5% while keeping the same O(1) atomic-increment recording; the
// legacy 64-octave read view is derived by folding sub-buckets.
std::atomic<uint64_t> g_hists[TDR_HIST_COUNT][TDR_HIST_FINE_BUCKETS];

std::atomic<uint32_t> g_next_engine{0};
std::atomic<uint32_t> g_next_qp{0};

size_t ring_capacity_env() {
  const char *env = getenv("TDR_TELEMETRY_RING");
  if (env && *env) {
    long long v = atoll(env);
    if (v >= static_cast<long long>(kRingMin))
      return static_cast<size_t>(
          v > static_cast<long long>(kRingMax) ? kRingMax : v);
    if (v > 0) return kRingMin;  // clamp UP, like TDR_TRACE_RING
  }
  return kRingDefault;
}

int bucket_of(uint64_t v) {
  // Octave index: bucket 0 holds zeros; bucket b (1..63) holds
  // [2^(b-1), 2^b) — i.e. b = bit_length(v), mirroring Python's
  // int.bit_length(). Values with bit 63 set would index bucket 64:
  // clamp into the last bucket instead of storing past the row.
  int b = v ? 64 - __builtin_clzll(v) : 0;
  return b > 63 ? 63 : b;
}

// Fine (log-linear) bucket: values < 16 index themselves; above that,
// the 3 bits below the MSB select one of 8 linear sub-buckets inside
// the value's octave. Contiguous: v=15 -> 15, v=16 -> 16.
int fine_bucket_of(uint64_t v) {
  if (v < 16) return static_cast<int>(v);
  int b = 64 - __builtin_clzll(v);  // bit_length, >= 5
  int sub = static_cast<int>((v >> (b - 4)) & 7);
  int idx = (b - 4) * 8 + 8 + sub;
  return idx >= TDR_HIST_FINE_BUCKETS ? TDR_HIST_FINE_BUCKETS - 1 : idx;
}

// Inclusive upper edge of a fine bucket (the conservative percentile
// estimate the Python side mirrors byte-for-byte).
uint64_t fine_upper_of(int idx) {
  if (idx < 16) return idx < 0 ? 0 : static_cast<uint64_t>(idx);
  int b = (idx - 8) / 8 + 4;       // octave (bit_length of members)
  int sub = (idx - 8) % 8;
  // Members are [ (8+sub) << (b-4), (8+sub+1) << (b-4) ); the << can
  // reach 2^64 at the top octave — unsigned wrap makes the -1 yield
  // UINT64_MAX, which is exactly the intended edge.
  return (static_cast<uint64_t>(8 + sub + 1) << (b - 4)) - 1;
}

// Octave a fine bucket belongs to — the legacy 64-bucket fold
// (buckets below 16 hold exact values, so their octave is bucket_of
// of the value itself). Clamped at 63 like bucket_of: the top fine
// buckets (bit-length-64 values) must fold into the last octave row,
// not index out[64] past the caller's array.
int fine_to_octave(int idx) {
  if (idx < 16) return bucket_of(static_cast<uint64_t>(idx));
  int oct = (idx - 8) / 8 + 4;
  return oct > 63 ? 63 : oct;
}

const char *kEventNames[] = {
    "none",       "post_send", "post_recv", "post_write", "post_read",
    "wire_tx",    "wire_rx",   "land",      "verify_ok",  "verify_fail",
    "nak",        "retx",      "fold",      "wc",         "copy_enq",
    "copy_run",   "ring_begin", "ring_end", "fold_off",   "shard",
    "fault_injected",
};
constexpr int kEventCount =
    static_cast<int>(sizeof(kEventNames) / sizeof(kEventNames[0]));

const char *kHistNames[TDR_HIST_COUNT] = {
    "chunk_lat_us", "chunk_bytes", "copy_bytes", "ring_lat_us", "ring_MBps",
};

}  // namespace

int tel_state_init() {
  std::lock_guard<std::mutex> g(g_mu);
  int s = g_tel_state.load(std::memory_order_relaxed);
  if (s != 0) return s;
  s = env_set("TDR_TELEMETRY") ? 2 : 1;
  if (s == 2 && g_ring.empty()) g_ring.resize(ring_capacity_env());
  g_tel_state.store(s, std::memory_order_release);
  return s;
}

uint64_t tel_now_ns() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<uint64_t>(ts.tv_sec) * 1000000000ull +
         static_cast<uint64_t>(ts.tv_nsec);
}

void tel_emit(uint16_t type, uint16_t engine, uint32_t qp, uint64_t id,
              uint64_t arg, uint64_t coll) {
  tdr_tel_event ev{tel_now_ns(), type, engine, qp, id, arg, coll};
  std::lock_guard<std::mutex> g(g_mu);
  if (g_ring.empty()) return;  // reset raced a producer: drop quietly
  size_t cap = g_ring.size();
  if (g_count == cap) {
    g_ring[g_head] = ev;  // overwrite oldest
    g_head = (g_head + 1) % cap;
    g_dropped.fetch_add(1, std::memory_order_relaxed);
  } else {
    g_ring[(g_head + g_count) % cap] = ev;
    g_count++;
  }
  g_recorded.fetch_add(1, std::memory_order_relaxed);
}

void tel_hist_add(int which, uint64_t value) {
  if (which < 0 || which >= TDR_HIST_COUNT) return;
  g_hists[which][fine_bucket_of(value)].fetch_add(
      1, std::memory_order_relaxed);
}

uint16_t tel_next_engine_id() {
  return static_cast<uint16_t>(
      g_next_engine.fetch_add(1, std::memory_order_relaxed) + 1);
}

uint32_t tel_next_qp_id() {
  return g_next_qp.fetch_add(1, std::memory_order_relaxed) + 1;
}

uint32_t tel_thread_track() {
  // One lane per helper thread, drawn lazily from the QP track space
  // the first time the thread emits — fold workers and progress
  // shards get stable exported lanes without pre-registration.
  thread_local uint32_t track = tel_next_qp_id();
  return track;
}

}  // namespace tdr

extern "C" {

int tdr_tel_enabled(void) { return tdr::tel_on() ? 1 : 0; }

void tdr_tel_reset(void) {
  std::lock_guard<std::mutex> g(tdr::g_mu);
  tdr::g_tel_state.store(0, std::memory_order_relaxed);
  int s = tdr::env_set("TDR_TELEMETRY") ? 2 : 1;
  tdr::g_ring.clear();
  if (s == 2) tdr::g_ring.resize(tdr::ring_capacity_env());
  tdr::g_head = 0;
  tdr::g_count = 0;
  tdr::g_recorded.store(0, std::memory_order_relaxed);
  tdr::g_dropped.store(0, std::memory_order_relaxed);
  for (auto &h : tdr::g_hists)
    for (auto &b : h) b.store(0, std::memory_order_relaxed);
  tdr::g_tel_state.store(s, std::memory_order_release);
}

uint64_t tdr_tel_now_ns(void) { return tdr::tel_now_ns(); }

int tdr_tel_drain(tdr_tel_event *out, int max) {
  if (!out || max <= 0) return 0;
  std::lock_guard<std::mutex> g(tdr::g_mu);
  size_t cap = tdr::g_ring.size();
  int n = 0;
  while (n < max && tdr::g_count > 0) {
    out[n++] = tdr::g_ring[tdr::g_head];
    tdr::g_head = (tdr::g_head + 1) % cap;
    tdr::g_count--;
  }
  return n;
}

uint64_t tdr_tel_recorded(void) {
  return tdr::g_recorded.load(std::memory_order_relaxed);
}

uint64_t tdr_tel_dropped(void) {
  return tdr::g_dropped.load(std::memory_order_relaxed);
}

const char *tdr_tel_event_name(int type) {
  return (type >= 0 && type < tdr::kEventCount) ? tdr::kEventNames[type]
                                                : "unknown";
}

int tdr_tel_hist_count(void) { return TDR_HIST_COUNT; }

const char *tdr_tel_hist_name(int which) {
  return (which >= 0 && which < TDR_HIST_COUNT) ? tdr::kHistNames[which]
                                                : "unknown";
}

void tdr_tel_hist_read(int which, uint64_t out[64]) {
  // Legacy 64-octave view, derived by folding the fine sub-buckets —
  // existing consumers (tdr_top sparklines, /metrics quantiles) keep
  // their shape; percentile consumers should read the fine view.
  if (!out) return;
  memset(out, 0, 64 * sizeof(uint64_t));
  if (which < 0 || which >= TDR_HIST_COUNT) return;
  for (int b = 0; b < TDR_HIST_FINE_BUCKETS; b++) {
    uint64_t c = tdr::g_hists[which][b].load(std::memory_order_relaxed);
    if (c) out[tdr::fine_to_octave(b)] += c;
  }
}

int tdr_tel_hist_fine_buckets(void) { return TDR_HIST_FINE_BUCKETS; }

uint64_t tdr_tel_hist_fine_upper(int idx) { return tdr::fine_upper_of(idx); }

int tdr_tel_hist_read_fine(int which, uint64_t *out, int max) {
  if (!out || max <= 0) return 0;
  int n = max < TDR_HIST_FINE_BUCKETS ? max : TDR_HIST_FINE_BUCKETS;
  if (which < 0 || which >= TDR_HIST_COUNT) {
    memset(out, 0, static_cast<size_t>(n) * sizeof(uint64_t));
    return n;
  }
  for (int b = 0; b < n; b++)
    out[b] = tdr::g_hists[which][b].load(std::memory_order_relaxed);
  return n;
}

int tdr_tel_engine_id(const tdr_engine *e) {
  return e ? reinterpret_cast<const tdr::Engine *>(e)->tel_id : 0;
}

int tdr_tel_qp_id(const tdr_qp *qp) {
  return qp ? static_cast<int>(reinterpret_cast<const tdr::Qp *>(qp)->tel_id)
            : 0;
}

/* ------------------------------------------------------------------ *
 * Counter registry: the one native surface every engine-side counter
 * lives behind. Each entry is a named getter over the subsystem's own
 * atomics — registering here does not move the counter, it unifies
 * how it is read (one call, one consistent snapshot, stable names).
 * ------------------------------------------------------------------ */

namespace {

const char *kCounterNames[] = {
    "integrity.sealed",   "integrity.verified", "integrity.failed",
    "integrity.retransmitted", "fault.seen",    "fault.hits",
    "copy.nt_bytes",      "copy.plain_bytes",   "telemetry.recorded",
    "telemetry.dropped",  "fold.jobs",          "fold.busy_us",
    "fold.pending",       "progress.shards",    "progress.wakeups",
    "progress.wc",        "probe.sent",         "probe.pong",
    "probe.timeout",
};
constexpr int kRegistryCount =
    static_cast<int>(sizeof(kCounterNames) / sizeof(kCounterNames[0]));

// One pass per subsystem: counters that share a producer lock (the
// fault clauses) or a producer call (the copy tiers) are read
// TOGETHER, so a snapshot can never show impossible relations like
// hits > seen. Counters from different subsystems are still
// individually-atomic monotonic reads, not a global freeze.
void read_all(uint64_t out[kRegistryCount]) {
  for (int i = 0; i < 4; i++) out[i] = tdr::seal_counter(i);
  tdr::fault_totals(&out[4], &out[5]);
  tdr::copy_counters(&out[6], &out[7]);
  out[8] = tdr::g_recorded.load(std::memory_order_relaxed);
  out[9] = tdr::g_dropped.load(std::memory_order_relaxed);
  out[10] = tdr::fold_jobs();
  out[11] = tdr::fold_busy_us();
  out[12] = tdr::fold_pending();
  tdr::progress_counters(&out[13], &out[14], &out[15]);
  for (int i = 0; i < 3; i++) out[16 + i] = tdr::probe_counter(i);
}

}  // namespace

int tdr_counter_count(void) { return kRegistryCount; }

const char *tdr_counter_name(int idx) {
  return (idx >= 0 && idx < kRegistryCount) ? kCounterNames[idx] : "";
}

int tdr_counters_read(uint64_t *out, int max) {
  if (!out || max <= 0) return 0;
  uint64_t vals[kRegistryCount];
  read_all(vals);
  int n = max < kRegistryCount ? max : kRegistryCount;
  for (int i = 0; i < n; i++) out[i] = vals[i];
  return n;
}

}  // extern "C"
