// mock_ibverbs.cc — an in-process fake libibverbs provider.
//
// Built as its own shared object (libmockibverbs.so); tests point
// TDR_VERBS_LIB at it and the UNMODIFIED verbs backend
// (verbs_engine.cc) runs against it — bring-up, MR registration, RC
// SEND/RECV with FIFO matching and RNR queueing, one-sided WRITE/READ
// with rkey/bounds/access checks, WITH_IMM delivery, and CQ polling.
// This plays the role kernelmod/mock plays for the kernel modules
// (SURVEY.md §4's "fake backend" lesson): the product path is
// exercised by CI on machines with no HCA, and the same engine binary
// talks to real hardware unchanged.
//
// Model: one process-global registry pairs QPs by dest_qp_num (set at
// RTR, exactly what the real rendezvous exchanges), so two Engine
// instances in one process form a loopback "fabric". Placement is
// synchronous at post/match time under one lock; CQEs appear in
// posted order, which satisfies RC's ordering guarantees.
//
// Deliberately NOT implemented: SRQs, atomics, UD, multi-sge — the
// engine uses none of them.

#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <set>
#include <unordered_map>
#include <vector>

#include "verbs_abi.h"

namespace {

constexpr int kWcFlushErr = 5;     // IBV_WC_WR_FLUSH_ERR
constexpr int kWcRemAccessErr = 10;  // IBV_WC_REM_ACCESS_ERR
constexpr int kWcGeneralErr = 13;  // IBV_WC_REM_OP_ERR (any generic)
constexpr int kWcOpRecv = 1 << 7;  // IBV_WC_RECV

struct MockCq {
  ibv_cq cq;  // ABI view — must be first (pointer-cast identity)
  std::deque<ibv_wc> wcs;
};

struct PostedRecv {
  uint64_t wr_id;
  uint64_t addr;
  uint32_t len;
};

struct Inbound {
  std::vector<char> data;
  uint32_t imm = 0;
  bool has_imm = false;
  uint32_t src_qpn = 0;   // deferred sender completion on match
  uint64_t src_wr_id = 0;
};

struct MockQp {
  ibv_qp qp;  // ABI view — must be first
  uint32_t dest = 0;
  MockCq *scq = nullptr;
  MockCq *rcq = nullptr;
  std::deque<PostedRecv> recvs;
  std::deque<Inbound> inbound;
};

struct MockMr {
  ibv_mr mr;  // ABI view — must be first
  int access = 0;
};

struct Global {
  std::mutex mu;
  std::unordered_map<uint32_t, MockQp *> qps;        // qp_num → qp
  std::unordered_map<uint32_t, MockMr *> mrs;        // rkey → mr
  std::set<MockCq *> live_cqs;
  uint32_t next_qpn = 1000;
  uint32_t next_key = 0x4000;
  uint16_t next_lid = 7;
};

Global &g() {
  static Global *inst = new Global();
  return *inst;
}

void push_wc(MockCq *cq, const ibv_wc &wc) {
  if (cq && g().live_cqs.count(cq)) cq->wcs.push_back(wc);
}

ibv_wc make_wc(uint64_t wr_id, int status, int opcode, uint32_t byte_len,
               uint32_t imm = 0, bool with_imm = false) {
  ibv_wc wc;
  memset(&wc, 0, sizeof(wc));
  wc.wr_id = wr_id;
  wc.status = status;
  wc.opcode = opcode;
  wc.byte_len = byte_len;
  wc.imm_data = imm;
  wc.wc_flags = with_imm ? IBV_WC_WITH_IMM : 0;
  return wc;
}

// Place an inbound message into a posted recv; generates the receiver
// CQE and the (possibly deferred) sender CQE. Caller holds g().mu.
void deliver(MockQp *dst, const PostedRecv &r, Inbound &in) {
  MockQp *src = nullptr;
  auto sit = g().qps.find(in.src_qpn);
  if (sit != g().qps.end()) src = sit->second;
  if (in.data.size() > r.len) {
    push_wc(dst->rcq, make_wc(r.wr_id, kWcGeneralErr, kWcOpRecv, 0));
    if (src) push_wc(src->scq, make_wc(in.src_wr_id, kWcGeneralErr, 0, 0));
    return;
  }
  if (!in.data.empty())
    memcpy(reinterpret_cast<void *>(r.addr), in.data.data(), in.data.size());
  push_wc(dst->rcq,
          make_wc(r.wr_id, IBV_WC_SUCCESS, kWcOpRecv,
                  static_cast<uint32_t>(in.data.size()), in.imm, in.has_imm));
  if (src)
    push_wc(src->scq, make_wc(in.src_wr_id, IBV_WC_SUCCESS, 0,
                              static_cast<uint32_t>(in.data.size())));
}

int mock_post_send(ibv_qp *qp, ibv_send_wr *wr, ibv_send_wr **bad) {
  auto *mq = reinterpret_cast<MockQp *>(qp);
  std::lock_guard<std::mutex> lk(g().mu);
  for (; wr; wr = wr->next) {
    uint64_t laddr = 0;
    uint32_t llen = 0;
    if (wr->num_sge > 0) {
      laddr = wr->sg_list[0].addr;
      llen = wr->sg_list[0].length;
    }
    switch (wr->opcode) {
      case IBV_WR_SEND:
      case IBV_WR_SEND_WITH_IMM: {
        auto it = g().qps.find(mq->dest);
        if (it == g().qps.end()) {
          push_wc(mq->scq, make_wc(wr->wr_id, kWcFlushErr, 0, 0));
          break;
        }
        MockQp *peer = it->second;
        Inbound in;
        in.data.assign(reinterpret_cast<char *>(laddr),
                       reinterpret_cast<char *>(laddr) + llen);
        in.has_imm = wr->opcode == IBV_WR_SEND_WITH_IMM;
        in.imm = wr->imm_data;
        in.src_qpn = mq->qp.qp_num;
        in.src_wr_id = wr->wr_id;
        if (!peer->recvs.empty()) {
          PostedRecv r = peer->recvs.front();
          peer->recvs.pop_front();
          deliver(peer, r, in);
        } else {
          peer->inbound.push_back(std::move(in));  // RNR queue
        }
        break;
      }
      case IBV_WR_RDMA_WRITE:
      case IBV_WR_RDMA_READ: {
        auto it = g().mrs.find(wr->wr.rdma.rkey);
        bool write = wr->opcode == IBV_WR_RDMA_WRITE;
        int need = write ? IBV_ACCESS_REMOTE_WRITE : IBV_ACCESS_REMOTE_READ;
        uint64_t ra = wr->wr.rdma.remote_addr;
        if (it == g().mrs.end() || !(it->second->access & need) ||
            ra < reinterpret_cast<uint64_t>(it->second->mr.addr) ||
            ra + llen > reinterpret_cast<uint64_t>(it->second->mr.addr) +
                            it->second->mr.length) {
          push_wc(mq->scq, make_wc(wr->wr_id, kWcRemAccessErr,
                                   write ? 0 : 2, 0));
          break;
        }
        if (write)
          memcpy(reinterpret_cast<void *>(ra),
                 reinterpret_cast<void *>(laddr), llen);
        else
          memcpy(reinterpret_cast<void *>(laddr),
                 reinterpret_cast<void *>(ra), llen);
        push_wc(mq->scq,
                make_wc(wr->wr_id, IBV_WC_SUCCESS, write ? 0 : 2, llen));
        break;
      }
      default:
        if (bad) *bad = wr;
        return 95;  // EOPNOTSUPP
    }
  }
  return 0;
}

int mock_post_recv(ibv_qp *qp, ibv_recv_wr *wr, ibv_recv_wr **bad) {
  (void)bad;
  auto *mq = reinterpret_cast<MockQp *>(qp);
  std::lock_guard<std::mutex> lk(g().mu);
  for (; wr; wr = wr->next) {
    PostedRecv r{wr->wr_id,
                 wr->num_sge > 0 ? wr->sg_list[0].addr : 0,
                 wr->num_sge > 0 ? wr->sg_list[0].length : 0};
    if (!mq->inbound.empty()) {
      Inbound in = std::move(mq->inbound.front());
      mq->inbound.pop_front();
      deliver(mq, r, in);
    } else {
      mq->recvs.push_back(r);
    }
  }
  return 0;
}

int mock_poll_cq(ibv_cq *cq, int num, ibv_wc *out) {
  auto *mc = reinterpret_cast<MockCq *>(cq);
  std::lock_guard<std::mutex> lk(g().mu);
  int n = 0;
  while (n < num && !mc->wcs.empty()) {
    out[n++] = mc->wcs.front();
    mc->wcs.pop_front();
  }
  return n;
}

// The fake device list: one device, identity carried in the pointer.
int g_device_token;

}  // namespace

extern "C" {

struct ibv_device **ibv_get_device_list(int *num) {
  auto **list = static_cast<ibv_device **>(calloc(2, sizeof(void *)));
  list[0] = reinterpret_cast<ibv_device *>(&g_device_token);
  list[1] = nullptr;
  if (num) *num = 1;
  return list;
}

void ibv_free_device_list(struct ibv_device **list) { free(list); }

const char *ibv_get_device_name(struct ibv_device *dev) {
  (void)dev;
  return "mock0";
}

struct ibv_context *ibv_open_device(struct ibv_device *dev) {
  (void)dev;
  auto *ctx = static_cast<ibv_context *>(calloc(1, sizeof(ibv_context)));
  ctx->ops.poll_cq = mock_poll_cq;
  ctx->ops.post_send = mock_post_send;
  ctx->ops.post_recv = mock_post_recv;
  return ctx;
}

int ibv_close_device(struct ibv_context *ctx) {
  free(ctx);
  return 0;
}

struct ibv_pd *ibv_alloc_pd(struct ibv_context *ctx) {
  auto *pd = static_cast<ibv_pd *>(calloc(1, sizeof(ibv_pd)));
  pd->context = ctx;
  return pd;
}

int ibv_dealloc_pd(struct ibv_pd *pd) {
  free(pd);
  return 0;
}

struct ibv_mr *ibv_reg_mr(struct ibv_pd *pd, void *addr, size_t len,
                          int access) {
  auto *m = new MockMr();
  memset(&m->mr, 0, sizeof(m->mr));
  m->mr.pd = pd;
  m->mr.addr = addr;
  m->mr.length = len;
  m->access = access;
  std::lock_guard<std::mutex> lk(g().mu);
  m->mr.lkey = m->mr.rkey = g().next_key++;
  g().mrs[m->mr.rkey] = m;
  return &m->mr;
}

int ibv_dereg_mr(struct ibv_mr *mr) {
  auto *m = reinterpret_cast<MockMr *>(mr);
  std::lock_guard<std::mutex> lk(g().mu);
  g().mrs.erase(mr->rkey);
  delete m;
  return 0;
}

struct ibv_cq *ibv_create_cq(struct ibv_context *ctx, int cqe, void *arg,
                             struct ibv_comp_channel *ch, int vec) {
  (void)cqe;
  (void)arg;
  (void)ch;
  (void)vec;
  auto *c = new MockCq();
  memset(&c->cq, 0, sizeof(c->cq));
  c->cq.context = ctx;
  std::lock_guard<std::mutex> lk(g().mu);
  g().live_cqs.insert(c);
  return &c->cq;
}

int ibv_destroy_cq(struct ibv_cq *cq) {
  auto *c = reinterpret_cast<MockCq *>(cq);
  std::lock_guard<std::mutex> lk(g().mu);
  g().live_cqs.erase(c);
  delete c;
  return 0;
}

struct ibv_qp *ibv_create_qp(struct ibv_pd *pd,
                             struct ibv_qp_init_attr *attr) {
  auto *q = new MockQp();
  memset(&q->qp, 0, sizeof(q->qp));
  q->qp.context = pd->context;
  q->qp.pd = pd;
  q->scq = reinterpret_cast<MockCq *>(attr->send_cq);
  q->rcq = reinterpret_cast<MockCq *>(attr->recv_cq);
  std::lock_guard<std::mutex> lk(g().mu);
  q->qp.qp_num = g().next_qpn++;
  g().qps[q->qp.qp_num] = q;
  return &q->qp;
}

int ibv_modify_qp(struct ibv_qp *qp, struct ibv_qp_attr *attr, int mask) {
  auto *q = reinterpret_cast<MockQp *>(qp);
  std::lock_guard<std::mutex> lk(g().mu);
  if (mask & IBV_QP_DEST_QPN) q->dest = attr->dest_qp_num;
  if (mask & IBV_QP_STATE) q->qp.state = attr->qp_state;
  return 0;
}

int ibv_destroy_qp(struct ibv_qp *qp) {
  auto *q = reinterpret_cast<MockQp *>(qp);
  std::lock_guard<std::mutex> lk(g().mu);
  g().qps.erase(q->qp.qp_num);
  // RC flush semantics: posted recvs die with the QP.
  for (const PostedRecv &r : q->recvs)
    push_wc(q->rcq, make_wc(r.wr_id, kWcFlushErr, kWcOpRecv, 0));
  delete q;
  return 0;
}

int ibv_query_port(struct ibv_context *ctx, uint8_t port,
                   struct ibv_port_attr *attr) {
  (void)ctx;
  (void)port;
  memset(attr, 0, sizeof(*attr));
  attr->state = IBV_PORT_ACTIVE;
  attr->active_mtu = IBV_MTU_4096;
  attr->max_mtu = IBV_MTU_4096;
  attr->link_layer = IBV_LINK_LAYER_INFINIBAND;
  std::lock_guard<std::mutex> lk(g().mu);
  attr->lid = g().next_lid++;
  return 0;
}

int ibv_query_gid(struct ibv_context *ctx, uint8_t port, int index,
                  union ibv_gid *gid) {
  (void)ctx;
  (void)port;
  (void)index;
  memset(gid, 0, sizeof(*gid));
  return 0;
}

}  // extern "C"
