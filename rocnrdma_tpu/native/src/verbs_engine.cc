// Real InfiniBand backend: RC queue pairs over libibverbs, resolved at
// runtime via dlopen so the framework runs (and CI passes) on machines
// without rdma-core headers or HCAs.
//
// This is the layer the reference delegated to OFED + perftest
// (README.md:64 "IB Verbs interface must be used"): device open, PD,
// MR registration — including dma-buf registration via
// ibv_reg_dmabuf_mr, the modern path SURVEY.md §7 prescribes in place
// of the reference's peer_memory_client bounce through the kernel —
// RC QP bring-up with a TCP rendezvous, and one-sided WRITE/READ.
//
// MR revocation here is an actual dereg (the effect the reference's
// free_callback→invalidate_peer_memory chain, amdp2p.c:88-109, has on
// the NIC: the MTT entry dies and remote access faults).

#include <dlfcn.h>
#include <string.h>
#include <unistd.h>

#include <chrono>
#include <mutex>
#include <thread>
#include <unordered_map>

#include "common.h"
#include "verbs_abi.h"

namespace tdr {
namespace {

struct VerbsLib {
  void *handle = nullptr;
  fn_ibv_get_device_list get_device_list = nullptr;
  fn_ibv_free_device_list free_device_list = nullptr;
  fn_ibv_get_device_name get_device_name = nullptr;
  fn_ibv_open_device open_device = nullptr;
  fn_ibv_close_device close_device = nullptr;
  fn_ibv_alloc_pd alloc_pd = nullptr;
  fn_ibv_dealloc_pd dealloc_pd = nullptr;
  fn_ibv_reg_mr reg_mr = nullptr;
  fn_ibv_reg_dmabuf_mr reg_dmabuf_mr = nullptr;  // optional (rdma-core >= 34)
  fn_ibv_dereg_mr dereg_mr = nullptr;
  fn_ibv_create_cq create_cq = nullptr;
  fn_ibv_destroy_cq destroy_cq = nullptr;
  fn_ibv_create_qp create_qp = nullptr;
  fn_ibv_modify_qp modify_qp = nullptr;
  fn_ibv_destroy_qp destroy_qp = nullptr;
  fn_ibv_query_port query_port = nullptr;
  fn_ibv_query_gid query_gid = nullptr;
};

VerbsLib *load_verbs(std::string *err) {
  static std::mutex mu;
  static VerbsLib *lib = nullptr;
  static std::string load_err;
  std::lock_guard<std::mutex> g(mu);
  if (lib) return lib;
  if (!load_err.empty()) {
    *err = load_err;
    return nullptr;
  }
  void *h = dlopen("libibverbs.so.1", RTLD_NOW | RTLD_GLOBAL);
  if (!h) h = dlopen("libibverbs.so", RTLD_NOW | RTLD_GLOBAL);
  if (!h) {
    load_err = std::string("dlopen libibverbs: ") + dlerror();
    *err = load_err;
    return nullptr;
  }
  auto *l = new VerbsLib();
  l->handle = h;
  bool ok = true;
  auto sym = [&](const char *name, bool required) -> void * {
    void *p = dlsym(h, name);
    if (!p && required) {
      load_err = std::string("missing symbol: ") + name;
      ok = false;
    }
    return p;
  };
  l->get_device_list = (fn_ibv_get_device_list)sym("ibv_get_device_list", true);
  l->free_device_list =
      (fn_ibv_free_device_list)sym("ibv_free_device_list", true);
  l->get_device_name = (fn_ibv_get_device_name)sym("ibv_get_device_name", true);
  l->open_device = (fn_ibv_open_device)sym("ibv_open_device", true);
  l->close_device = (fn_ibv_close_device)sym("ibv_close_device", true);
  l->alloc_pd = (fn_ibv_alloc_pd)sym("ibv_alloc_pd", true);
  l->dealloc_pd = (fn_ibv_dealloc_pd)sym("ibv_dealloc_pd", true);
  l->reg_mr = (fn_ibv_reg_mr)sym("ibv_reg_mr", true);
  l->reg_dmabuf_mr = (fn_ibv_reg_dmabuf_mr)sym("ibv_reg_dmabuf_mr", false);
  l->dereg_mr = (fn_ibv_dereg_mr)sym("ibv_dereg_mr", true);
  l->create_cq = (fn_ibv_create_cq)sym("ibv_create_cq", true);
  l->destroy_cq = (fn_ibv_destroy_cq)sym("ibv_destroy_cq", true);
  l->create_qp = (fn_ibv_create_qp)sym("ibv_create_qp", true);
  l->modify_qp = (fn_ibv_modify_qp)sym("ibv_modify_qp", true);
  l->destroy_qp = (fn_ibv_destroy_qp)sym("ibv_destroy_qp", true);
  l->query_port = (fn_ibv_query_port)sym("ibv_query_port", true);
  l->query_gid = (fn_ibv_query_gid)sym("ibv_query_gid", true);
  if (!ok) {
    delete l;
    *err = load_err;
    return nullptr;
  }
  lib = l;
  return lib;
}

// ibv_wc_status values we map specially (rdma-core numbering).
constexpr int kIbvWcWrFlushErr = 5;
constexpr int kIbvWcRemAccessErr = 10;

int map_status(int ibv_status) {
  switch (ibv_status) {
    case IBV_WC_SUCCESS:
      return TDR_WC_SUCCESS;
    case kIbvWcWrFlushErr:
      return TDR_WC_FLUSH_ERR;
    case kIbvWcRemAccessErr:
      return TDR_WC_REM_ACCESS_ERR;
    default:
      return TDR_WC_GENERAL_ERR;
  }
}

int map_access(int tdr_access) {
  int a = IBV_ACCESS_LOCAL_WRITE;
  if (tdr_access & TDR_ACCESS_REMOTE_WRITE) a |= IBV_ACCESS_REMOTE_WRITE;
  if (tdr_access & TDR_ACCESS_REMOTE_READ) a |= IBV_ACCESS_REMOTE_READ;
  return a;
}

class VerbsEngine;

class VerbsMr : public Mr {
 public:
  VerbsLib *lib = nullptr;
  ibv_mr *mr = nullptr;
  std::mutex mu;
  int invalidate() override {
    std::lock_guard<std::mutex> g(mu);
    valid.store(false, std::memory_order_release);
    if (mr) {
      lib->dereg_mr(mr);
      mr = nullptr;
    }
    return 0;
  }
  ~VerbsMr() override { invalidate(); }
};

// Exchanged over the TCP rendezvous during bring-up, both directions.
#pragma pack(push, 1)
struct ConnInfo {
  uint32_t qpn;
  uint32_t psn;
  uint16_t lid;
  uint8_t gid[16];
  uint8_t mtu;
  uint8_t link_layer;
};
#pragma pack(pop)

class VerbsQp : public Qp {
 public:
  VerbsQp(VerbsLib *lib, ibv_context *ctx, ibv_pd *pd)
      : lib_(lib), ctx_(ctx), pd_(pd) {}

  bool setup(int sock, uint8_t port_num, int gid_index, std::string *err) {
    sock_ = sock;
    cq_ = lib_->create_cq(ctx_, 1024, nullptr, nullptr, 0);
    if (!cq_) {
      *err = "ibv_create_cq failed";
      return false;
    }
    ibv_qp_init_attr ia;
    memset(&ia, 0, sizeof(ia));
    ia.send_cq = cq_;
    ia.recv_cq = cq_;
    ia.cap.max_send_wr = 512;
    ia.cap.max_recv_wr = 512;
    ia.cap.max_send_sge = 1;
    ia.cap.max_recv_sge = 1;
    ia.qp_type = IBV_QPT_RC;
    qp_ = lib_->create_qp(pd_, &ia);
    if (!qp_) {
      *err = "ibv_create_qp failed";
      return false;
    }

    ibv_port_attr pattr;
    memset(&pattr, 0, sizeof(pattr));
    if (lib_->query_port(ctx_, port_num, &pattr) != 0) {
      *err = "ibv_query_port failed";
      return false;
    }
    union ibv_gid gid;
    memset(&gid, 0, sizeof(gid));
    lib_->query_gid(ctx_, port_num, gid_index, &gid);

    ConnInfo mine;
    memset(&mine, 0, sizeof(mine));
    mine.qpn = qp_->qp_num;
    mine.psn = static_cast<uint32_t>(
                   reinterpret_cast<uintptr_t>(this) ^
                   static_cast<uintptr_t>(
                       std::chrono::steady_clock::now().time_since_epoch()
                           .count())) &
               0xffffff;
    mine.lid = pattr.lid;
    memcpy(mine.gid, gid.raw, 16);
    mine.mtu = static_cast<uint8_t>(pattr.active_mtu);
    mine.link_layer = pattr.link_layer;
    if (!write_full(sock_, &mine, sizeof(mine)) ||
        !read_full(sock_, &peer_, sizeof(peer_))) {
      *err = "rendezvous exchange failed";
      return false;
    }

    // INIT
    ibv_qp_attr a;
    memset(&a, 0, sizeof(a));
    a.qp_state = IBV_QPS_INIT;
    a.pkey_index = 0;
    a.port_num = port_num;
    a.qp_access_flags =
        IBV_ACCESS_LOCAL_WRITE | IBV_ACCESS_REMOTE_WRITE | IBV_ACCESS_REMOTE_READ;
    if (lib_->modify_qp(qp_, &a,
                        IBV_QP_STATE | IBV_QP_PKEY_INDEX | IBV_QP_PORT |
                            IBV_QP_ACCESS_FLAGS) != 0) {
      *err = "modify_qp INIT failed";
      return false;
    }
    // RTR
    memset(&a, 0, sizeof(a));
    a.qp_state = IBV_QPS_RTR;
    a.path_mtu = peer_.mtu < static_cast<uint8_t>(pattr.active_mtu)
                     ? peer_.mtu
                     : pattr.active_mtu;
    a.dest_qp_num = peer_.qpn;
    a.rq_psn = peer_.psn;
    a.max_dest_rd_atomic = 16;
    a.min_rnr_timer = 12;
    a.ah_attr.dlid = peer_.lid;
    a.ah_attr.sl = 0;
    a.ah_attr.src_path_bits = 0;
    a.ah_attr.port_num = port_num;
    if (peer_.link_layer == IBV_LINK_LAYER_ETHERNET || peer_.lid == 0) {
      a.ah_attr.is_global = 1;
      memcpy(a.ah_attr.grh.dgid.raw, peer_.gid, 16);
      a.ah_attr.grh.sgid_index = static_cast<uint8_t>(gid_index);
      a.ah_attr.grh.hop_limit = 64;
    }
    if (lib_->modify_qp(qp_, &a,
                        IBV_QP_STATE | IBV_QP_AV | IBV_QP_PATH_MTU |
                            IBV_QP_DEST_QPN | IBV_QP_RQ_PSN |
                            IBV_QP_MAX_DEST_RD_ATOMIC |
                            IBV_QP_MIN_RNR_TIMER) != 0) {
      *err = "modify_qp RTR failed";
      return false;
    }
    // RTS
    memset(&a, 0, sizeof(a));
    a.qp_state = IBV_QPS_RTS;
    a.timeout = 14;
    a.retry_cnt = 7;
    a.rnr_retry = 7;
    a.sq_psn = mine.psn;
    a.max_rd_atomic = 16;
    if (lib_->modify_qp(qp_, &a,
                        IBV_QP_STATE | IBV_QP_TIMEOUT | IBV_QP_RETRY_CNT |
                            IBV_QP_RNR_RETRY | IBV_QP_SQ_PSN |
                            IBV_QP_MAX_QP_RD_ATOMIC) != 0) {
      *err = "modify_qp RTS failed";
      return false;
    }
    // Barrier: both sides fully in RTS before any data flows.
    char tok = 1, peer_tok = 0;
    if (!write_full(sock_, &tok, 1) || !read_full(sock_, &peer_tok, 1)) {
      *err = "rendezvous barrier failed";
      return false;
    }
    return true;
  }

  int post_write(Mr *lmr, size_t loff, uint64_t raddr, uint32_t rkey,
                 size_t len, uint64_t wr_id) override {
    return post_one(lmr, loff, len, wr_id, IBV_WR_RDMA_WRITE, TDR_OP_WRITE,
                    raddr, rkey);
  }
  int post_read(Mr *lmr, size_t loff, uint64_t raddr, uint32_t rkey,
                size_t len, uint64_t wr_id) override {
    return post_one(lmr, loff, len, wr_id, IBV_WR_RDMA_READ, TDR_OP_READ,
                    raddr, rkey);
  }
  int post_send(Mr *lmr, size_t loff, size_t len, uint64_t wr_id) override {
    return post_one(lmr, loff, len, wr_id, IBV_WR_SEND, TDR_OP_SEND, 0, 0);
  }

  int post_recv(Mr *lmr, size_t loff, size_t maxlen, uint64_t wr_id) override {
    auto *vmr = static_cast<VerbsMr *>(lmr);
    std::lock_guard<std::mutex> g(vmr->mu);
    if (!vmr->mr) {
      set_error("post_recv: MR invalidated");
      return -1;
    }
    ibv_sge sge;
    sge.addr = reinterpret_cast<uint64_t>(vmr->mr->addr) + loff;
    sge.length = static_cast<uint32_t>(maxlen);
    sge.lkey = vmr->mr->lkey;
    ibv_recv_wr wr;
    memset(&wr, 0, sizeof(wr));
    wr.wr_id = stash(wr_id, TDR_OP_RECV);
    wr.sg_list = &sge;
    wr.num_sge = 1;
    ibv_recv_wr *bad = nullptr;
    if (qp_->context->ops.post_recv(qp_, &wr, &bad) != 0) {
      unstash(wr.wr_id);
      set_error("ibv_post_recv failed");
      return -1;
    }
    return 0;
  }

  int poll(tdr_wc *out, int max, int timeout_ms) override {
    auto deadline =
        std::chrono::steady_clock::now() + std::chrono::milliseconds(timeout_ms);
    ibv_wc wcs[64];
    for (;;) {
      int want = max < 64 ? max : 64;
      int n = qp_->context->ops.poll_cq(cq_, want, wcs);
      if (n < 0) {
        set_error("ibv_poll_cq failed");
        return -1;
      }
      if (n > 0) {
        for (int i = 0; i < n; i++) {
          auto meta = unstash(wcs[i].wr_id);
          out[i].wr_id = meta.first;
          out[i].status = map_status(wcs[i].status);
          out[i].opcode = meta.second;
          out[i].len = wcs[i].byte_len;
        }
        return n;
      }
      if (timeout_ms == 0) return 0;
      if (timeout_ms > 0 && std::chrono::steady_clock::now() >= deadline)
        return 0;
      std::this_thread::yield();
    }
  }

  int close_qp() override {
    if (qp_) {
      lib_->destroy_qp(qp_);
      qp_ = nullptr;
    }
    if (cq_) {
      lib_->destroy_cq(cq_);
      cq_ = nullptr;
    }
    if (sock_ >= 0) {
      ::close(sock_);
      sock_ = -1;
    }
    return 0;
  }

  ~VerbsQp() override { close_qp(); }

 private:
  int post_one(Mr *lmr, size_t loff, size_t len, uint64_t wr_id, int ibv_op,
               int tdr_op, uint64_t raddr, uint32_t rkey) {
    auto *vmr = static_cast<VerbsMr *>(lmr);
    std::lock_guard<std::mutex> g(vmr->mu);
    if (!vmr->mr) {
      set_error("post: MR invalidated");
      return -1;
    }
    ibv_sge sge;
    sge.addr = reinterpret_cast<uint64_t>(vmr->mr->addr) + loff;
    sge.length = static_cast<uint32_t>(len);
    sge.lkey = vmr->mr->lkey;
    ibv_send_wr wr;
    memset(&wr, 0, sizeof(wr));
    wr.wr_id = stash(wr_id, tdr_op);
    wr.sg_list = &sge;
    wr.num_sge = 1;
    wr.opcode = ibv_op;
    wr.send_flags = IBV_SEND_SIGNALED;
    wr.wr.rdma.remote_addr = raddr;
    wr.wr.rdma.rkey = rkey;
    ibv_send_wr *bad = nullptr;
    if (qp_->context->ops.post_send(qp_, &wr, &bad) != 0) {
      unstash(wr.wr_id);
      set_error("ibv_post_send failed");
      return -1;
    }
    return 0;
  }

  // wr_id indirection: completions (esp. error completions, whose
  // ibv opcode field is undefined) are mapped back to the user's wr_id
  // and the op they were posted as.
  uint64_t stash(uint64_t user, int opcode) {
    std::lock_guard<std::mutex> g(mu_);
    uint64_t cookie = next_cookie_++;
    inflight_[cookie] = {user, opcode};
    return cookie;
  }
  std::pair<uint64_t, int> unstash(uint64_t cookie) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = inflight_.find(cookie);
    if (it == inflight_.end()) return {cookie, TDR_OP_WRITE};
    auto v = it->second;
    inflight_.erase(it);
    return v;
  }

  VerbsLib *lib_;
  ibv_context *ctx_;
  ibv_pd *pd_;
  ibv_cq *cq_ = nullptr;
  ibv_qp *qp_ = nullptr;
  int sock_ = -1;
  ConnInfo peer_{};
  std::mutex mu_;
  std::unordered_map<uint64_t, std::pair<uint64_t, int>> inflight_;
  uint64_t next_cookie_ = 1;
};

class VerbsEngine : public Engine {
 public:
  VerbsEngine(VerbsLib *lib, ibv_context *ctx, ibv_pd *pd, std::string dev,
              uint8_t port, int gid_index)
      : lib_(lib),
        ctx_(ctx),
        pd_(pd),
        dev_(std::move(dev)),
        port_(port),
        gid_index_(gid_index) {}

  ~VerbsEngine() override {
    if (pd_) lib_->dealloc_pd(pd_);
    if (ctx_) lib_->close_device(ctx_);
  }

  int kind() const override { return TDR_ENGINE_VERBS; }
  const char *name() const override { return dev_.c_str(); }

  Mr *reg_mr(void *addr, size_t len, int access) override {
    ibv_mr *m = lib_->reg_mr(pd_, addr, len, map_access(access));
    if (!m) {
      set_error("ibv_reg_mr failed");
      return nullptr;
    }
    return wrap(m, access);
  }

  Mr *reg_dmabuf_mr(int fd, size_t offset, size_t len, uint64_t iova,
                    int access) override {
    if (!lib_->reg_dmabuf_mr) {
      set_error("ibv_reg_dmabuf_mr not available (rdma-core too old)");
      return nullptr;
    }
    ibv_mr *m =
        lib_->reg_dmabuf_mr(pd_, offset, len, iova, fd, map_access(access));
    if (!m) {
      set_error("ibv_reg_dmabuf_mr failed");
      return nullptr;
    }
    return wrap(m, access);
  }

  int dereg_mr(Mr *mr) override {
    delete static_cast<VerbsMr *>(mr);  // dtor deregs if still live
    return 0;
  }

  Qp *listen(const char *bind_host, int port) override {
    std::string err;
    int fd = tcp_listen_accept(bind_host, port, &err);
    if (fd < 0) {
      set_error("listen: " + err);
      return nullptr;
    }
    return bring_up(fd);
  }

  Qp *connect(const char *host, int port, int timeout_ms) override {
    std::string err;
    int fd = tcp_connect_retry(host, port, timeout_ms, &err);
    if (fd < 0) {
      set_error("connect: " + err);
      return nullptr;
    }
    return bring_up(fd);
  }

 private:
  Mr *wrap(ibv_mr *m, int access) {
    auto *mr = new VerbsMr();
    mr->engine = this;
    mr->lib = lib_;
    mr->mr = m;
    mr->addr = reinterpret_cast<uint64_t>(m->addr);
    mr->len = m->length;
    mr->lkey = m->lkey;
    mr->rkey = m->rkey;
    mr->access = access;
    return mr;
  }

  Qp *bring_up(int fd) {
    auto *qp = new VerbsQp(lib_, ctx_, pd_);
    std::string err;
    if (!qp->setup(fd, port_, gid_index_, &err)) {
      set_error("verbs bring-up: " + err);
      // setup() stored fd as sock_; ~VerbsQp closes it exactly once.
      delete qp;
      return nullptr;
    }
    return qp;
  }

  VerbsLib *lib_;
  ibv_context *ctx_;
  ibv_pd *pd_;
  std::string dev_;
  uint8_t port_;
  int gid_index_;
};

}  // namespace

Engine *create_verbs_engine(const std::string &device, std::string *err) {
  VerbsLib *lib = load_verbs(err);
  if (!lib) return nullptr;
  int num = 0;
  ibv_device **list = lib->get_device_list(&num);
  if (!list || num == 0) {
    if (list) lib->free_device_list(list);
    *err = "no RDMA devices present";
    return nullptr;
  }
  ibv_device *chosen = nullptr;
  std::string chosen_name;
  for (int i = 0; i < num; i++) {
    const char *n = lib->get_device_name(list[i]);
    if (device.empty() || device == n) {
      chosen = list[i];
      chosen_name = n ? n : "?";
      break;
    }
  }
  if (!chosen) {
    lib->free_device_list(list);
    *err = "device not found: " + device;
    return nullptr;
  }
  ibv_context *ctx = lib->open_device(chosen);
  lib->free_device_list(list);
  if (!ctx) {
    *err = "ibv_open_device failed";
    return nullptr;
  }
  ibv_pd *pd = lib->alloc_pd(ctx);
  if (!pd) {
    lib->close_device(ctx);
    *err = "ibv_alloc_pd failed";
    return nullptr;
  }
  const char *gid_env = getenv("TDR_GID_INDEX");
  int gid_index = gid_env ? atoi(gid_env) : 0;
  const char *port_env = getenv("TDR_IB_PORT");
  uint8_t port = port_env ? static_cast<uint8_t>(atoi(port_env)) : 1;
  return new VerbsEngine(lib, ctx, pd, chosen_name, port, gid_index);
}

}  // namespace tdr
