/* tdr.h — C API of the TPU-Direct-RDMA native engine (libtdr).
 *
 * Role in the stack: the userspace half of what the reference split
 * between OFED ib_core and the amdp2p bridge (amdp2p.c). Where the
 * reference's public surface is a callback table polled by the kernel
 * (the 7-entry peer_memory_client ops, amdp2p.c:363-371), this engine
 * exposes the registration + RC queue-pair surface directly to the
 * framework: register memory (host pointer or dma-buf fd), bring up a
 * reliable connection, post one-sided WRITE/READ and two-sided
 * SEND/RECV, poll completions.
 *
 * Invariant preserved from the reference (SURVEY.md §3.3): all mapping
 * work is front-loaded into registration; posting a transfer performs
 * no per-byte software work beyond handing the NIC (or the emulated
 * progress engine) a descriptor.
 *
 * Two backends, selected at runtime:
 *   - "verbs": real InfiniBand via dlopen(libibverbs.so.1), including
 *     ibv_reg_dmabuf_mr for accelerator HBM (SURVEY.md §7 design
 *     stance: dma-buf is the idiomatic modern path).
 *   - "emu":   hardware-free emulation over TCP with a progress thread
 *     standing in for the HCA — the "fake L2 backend" SURVEY.md §4
 *     calls out as the reference's biggest testing gap.
 */
#ifndef TDR_H_
#define TDR_H_

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct tdr_engine tdr_engine;
typedef struct tdr_mr tdr_mr;
typedef struct tdr_qp tdr_qp;

enum {
  TDR_ENGINE_EMU = 0,
  TDR_ENGINE_VERBS = 1,
};

/* Completion statuses (subset of ibv_wc_status semantics). */
enum {
  TDR_WC_SUCCESS = 0,
  TDR_WC_REM_ACCESS_ERR = 1, /* bad, out-of-range, or revoked rkey */
  TDR_WC_LOC_ACCESS_ERR = 2, /* local MR invalid / recv too small */
  TDR_WC_FLUSH_ERR = 3,      /* QP torn down with the op in flight */
  TDR_WC_GENERAL_ERR = 4,
  /* Payload-integrity verification failed at land time and the
   * per-chunk retransmit budget is exhausted (or the frame carried a
   * stale-incarnation seal). Retryable: the elastic layer rebuilds the
   * world, exactly as it does for FLUSH/GENERAL. */
  TDR_WC_INTEGRITY_ERR = 5,
};

/* MR access flags (ibv_access_flags semantics). */
enum {
  TDR_ACCESS_LOCAL = 0,
  TDR_ACCESS_REMOTE_WRITE = 1 << 0,
  TDR_ACCESS_REMOTE_READ = 1 << 1,
};

/* Work-completion opcodes. */
enum {
  TDR_OP_WRITE = 0,
  TDR_OP_READ = 1,
  TDR_OP_SEND = 2,
  TDR_OP_RECV = 3,
};

typedef struct {
  uint64_t wr_id;
  int32_t status; /* TDR_WC_* */
  int32_t opcode; /* TDR_OP_* */
  uint64_t len;   /* payload bytes (meaningful for RECV) */
} tdr_wc;

/* Last error message for the calling thread ("" if none). */
const char *tdr_last_error(void);

/* Number of workers in the process-wide parallel copy/reduce pool
 * (the emulated NIC's DMA-engine array; TDR_COPY_THREADS overrides). */
size_t tdr_copy_pool_workers(void);

/* Workers in the fold-offload pool (TDR_FOLD_THREADS): the threads
 * that run the ring's scratch-window folds off the poll loop. 0 means
 * folds run inline on the polling thread (1-core hosts, or the knob
 * set to 0). */
size_t tdr_fold_pool_workers(void);

/* Effective progress-shard count for a ring with `channels` channels
 * (the sharded progress engine, TDR_PROGRESS_SHARDS): how many
 * dedicated poll threads a striped collective will run, each owning a
 * disjoint channel group. 0 = the legacy single-poll loop (the
 * schedule's calling thread owns all polling; TDR_PROGRESS_SHARDS=0
 * forces it). Default: one shard per channel, capped at the host's
 * usable cores — a core-starved host gains nothing from shards that
 * only preempt each other. Per-PROCESS execution strategy: never
 * negotiated, never part of the schedule digest (any mix of shard
 * counts across ranks is wire-compatible and bitwise-identical). */
int tdr_progress_shards(int channels);

/* Cumulative bytes moved via the streaming (non-temporal) vs cached
 * (memcpy) copy tiers since process start — which path carried the
 * traffic (bench/diagnostics). */
void tdr_copy_counters(uint64_t *nt_bytes, uint64_t *plain_bytes);

/* ------------------------------------------------------------------ *
 * Deterministic fault injection — the TDR_FAULT_PLAN registry.
 *
 * TDR_FAULT_PLAN holds comma-separated clauses of the form
 * site[:match...]:action (grammar in README.md "Failure semantics"),
 * e.g. "send:chunk=3:once=general_err,conn:drop_after=2". Sites:
 * send (emu SEND-class posts: the WR completes with the injected
 * status instead of transmitting), conn (emu posts: the QP's socket
 * drops after N posts), land (the landing-time window; generalizes
 * TDR_FAULT_LANDING_DELAY_MS), ring (tdr_ring_allreduce entry: the
 * collective call fails before posting). Status actions are valid at
 * send/ring only, drop_after at conn only, corrupt=NBYTES at
 * send/land only (flip NBYTES payload bytes AFTER sealing on send /
 * BEFORE verification on land — sealed connections only; the source
 * buffer is never touched, so retransmissions can be clean),
 * stall_ms anywhere; clauses whose action the site cannot apply are
 * rejected at parse time so a hit counter never reports an injection
 * that did not happen.
 *
 * Per-clause hit counters are exported so tests assert the fault
 * ACTUALLY fired — a green test whose fault never armed is a lie.
 * Counters are process-wide; reset re-parses the environment.
 * ------------------------------------------------------------------ */
int tdr_fault_plan_clauses(void);
uint64_t tdr_fault_plan_hits(int idx);  /* times clause idx fired   */
uint64_t tdr_fault_plan_seen(int idx);  /* site arrivals it matched */
void tdr_fault_plan_reset(void);

/* ------------------------------------------------------------------ *
 * Sealed chunks — end-to-end payload integrity on the emu transport.
 *
 * When both ends of a QP negotiate FEAT_SEAL (default on; TDR_NO_SEAL
 * opts a rank out at the handshake), every payload-bearing frame
 * (SEND/recv_reduce/foldback and RDMA_WRITE landings) carries a seal:
 * CRC32C over the payload plus a (generation, step, chunk-seq) tag,
 * verified at land time BEFORE the chunk is folded into any
 * accumulator. A verification failure NAKs the chunk seq back to the
 * sender, which re-posts it from the still-live source buffer (the
 * pending op holds an inflight MR ref until the final ack); the
 * per-chunk retransmit budget is TDR_SEAL_RETRY (default 3), and
 * exhausting it completes BOTH sides' WRs with TDR_WC_INTEGRITY_ERR —
 * retryable, so the elastic layer escalates to RingWorld.rebuild().
 * ------------------------------------------------------------------ */

/* CRC32C (Castagnoli, reflected 0x82F63B78): hardware (SSE4.2) when
 * compiled in, software slicing otherwise. Incremental: pass the
 * previous return value as seed to continue a running checksum. */
uint32_t tdr_crc32c(const void *data, size_t len, uint32_t seed);

/* Process-wide integrity counters: out[0]=frames sealed at send,
 * out[1]=landings verified ok, out[2]=verification failures,
 * out[3]=retransmissions performed. */
void tdr_seal_counters(uint64_t out[4]);
void tdr_seal_counters_reset(void);

/* The per-chunk retransmit budget as the ENGINE parses it
 * (TDR_SEAL_RETRY, default 3) — the schedule digest records this
 * value, so the Python layer must not re-parse the env and risk
 * certifying a budget the transport is not using. */
int tdr_seal_retry_budget(void);

/* Stamp the engine's seal context: gen_plus1 = ring incarnation + 1
 * (0 = unset, checks skipped) and the training step. Outbound seals
 * carry both; a landing whose seal names a DIFFERENT non-zero
 * incarnation than the local engine's is a stale-incarnation ghost
 * write and fails verification even when its bytes are intact. */
void tdr_seal_context(tdr_engine *e, uint64_t gen_plus1, uint64_t step);

/* Whether this QP negotiated sealing with its peer (emu only; the
 * verbs backend relies on the wire's ICRC and advertises 0). */
int tdr_qp_has_seal(tdr_qp *qp);

/* Whether the negotiated seal's CRC covers the PAYLOAD bytes. True on
 * the TCP stream tier; on the CMA tier (same-host kernel-memcpy
 * "wire" — no payload bit-flip failure mode, the ICRC rationale) the
 * default is tag-only sealing (generation fence + chunk seq +
 * steering fields stay covered) and this returns 0 unless BOTH ends
 * set TDR_SEAL_CMA=1 (FEAT_SEAL_CMA_FULL). */
int tdr_qp_has_seal_payload(tdr_qp *qp);

/* Whether this QP negotiated FEAT_COLL_ID (emu only): frames carry
 * the posting rank's collective trace id in an 8-byte header
 * extension, so the receiver's telemetry events tag with the SAME id
 * the sender stamped (retransmits keep it; tag-only CMA seals carry
 * it too). Advertised only when TDR_TELEMETRY was on at handshake
 * time — with the feature off the wire format is byte-identical to
 * the pre-trace-id framing. */
int tdr_qp_has_coll_id(tdr_qp *qp);

/* 1 when FEAT_WIRE_Q8 was negotiated on this QP: both ends accept the
 * int8 quantized ring schedule (tdr_ring_allreduce_q8). The quantized
 * pieces are ordinary sealed SEND payloads ([f32 scale][int8 bytes]) —
 * no frame-format change, so with the feature off the wire is
 * byte-identical to the legacy framing; the bit gates the SCHEDULE and
 * lets the health ladder query per-link int8 capability before
 * engaging its rung below bf16. TDR_NO_WIRE_Q8 suppresses the
 * advertisement. */
int tdr_qp_has_wire_q8(tdr_qp *qp);

/* Hung-peer probe: send a zero-byte PING (sealed with a tag-only CRC
 * on sealed connections) and wait up to timeout_ms for the peer's
 * progress engine to PONG it back. Returns 1 = peer alive, 0 = no
 * pong within the timeout (peer hung/wedged), -1 = connection down,
 * -2 = uninformative (backend has no probe, or FEAT_PROBE was not
 * negotiated — with it off, frames stay byte-identical to the legacy
 * wire format; TDR_NO_PROBE=1 disables the advertisement). */
int tdr_qp_probe(tdr_qp *qp, int timeout_ms);

/* Stamp the QP's link identity (channel lane, local rank, peer rank)
 * so netem fault riders can scope to one link and stall/probe
 * telemetry names the edge. The ring layer calls this at channel
 * bring-up; -1 = unknown. Purely observational. */
void tdr_qp_set_link(tdr_qp *qp, int lane, int rank, int peer);

/* ------------------------------------------------------------------ *
 * Flight recorder — the engine-side telemetry subsystem.
 *
 * When TDR_TELEMETRY is set (and not "0"), every stage of the chunk
 * lifecycle on both backends (post → wire tx/rx → land → seal
 * verify/NAK/retransmit → fold → completion, plus copy-pool
 * enqueue/run and ring-collective begin/end) records a fixed-size
 * timestamped event into a bounded process-wide ring, and log2-bucket
 * latency/bandwidth histograms accumulate alongside. When the knob is
 * off, every event site costs exactly one predicted branch — no
 * clock reads, no stores — and tdr_tel_recorded()/tdr_tel_dropped()
 * stay 0 (the bench smoke asserts this).
 *
 * Clock domain: CLOCK_MONOTONIC nanoseconds — the same clock Python's
 * time.monotonic() reads on Linux, so native events and the Python
 * tracer's ring merge into one timeline without translation
 * (tdr_tel_now_ns anchors the correspondence).
 *
 * Ring capacity: TDR_TELEMETRY_RING events (default 65536, clamped to
 * [1024, 4Mi]). When full, the OLDEST event is overwritten (flight-
 * recorder semantics: the recent past survives a long soak) and the
 * dropped counter counts the overwrite.
 * ------------------------------------------------------------------ */

/* Event types (tdr_tel_event.type). */
enum {
  TDR_TEL_NONE = 0,
  TDR_TEL_POST_SEND = 1,   /* id=wr_id, arg=bytes                     */
  TDR_TEL_POST_RECV = 2,   /* id=wr_id, arg=maxlen                    */
  TDR_TEL_POST_WRITE = 3,  /* id=wr_id, arg=bytes                     */
  TDR_TEL_POST_READ = 4,   /* id=wr_id, arg=bytes                     */
  TDR_TEL_WIRE_TX = 5,     /* frame leaves the wire/desc path:
                              id=frame seq, arg=bytes                 */
  TDR_TEL_WIRE_RX = 6,     /* frame header arrived: id=seq, arg=bytes */
  TDR_TEL_LAND = 7,        /* payload materialized at its target      */
  TDR_TEL_VERIFY_OK = 8,   /* seal verification passed: id=seq        */
  TDR_TEL_VERIFY_FAIL = 9, /* seal verification failed: id=seq        */
  TDR_TEL_NAK = 10,        /* receiver NAKs chunk seq (arg=attempt)   */
  TDR_TEL_RETX = 11,       /* sender re-posts chunk seq (arg=bytes)   */
  TDR_TEL_FOLD = 12,       /* payload folded into an accumulator      */
  TDR_TEL_WC = 13,         /* completion delivered: id=wr_id,
                              arg=TDR_WC_* status                     */
  TDR_TEL_COPY_ENQ = 14,   /* copy-pool job submitted: arg=work units */
  TDR_TEL_COPY_RUN = 15,   /* copy-pool job finished: arg=duration us */
  TDR_TEL_RING_BEGIN = 16, /* collective entry: id=call seq, arg=bytes*/
  TDR_TEL_RING_END = 17,   /* collective exit: arg=0 ok / 1 failed    */
  TDR_TEL_FOLD_OFF = 18,   /* scratch fold handed to the fold pool:
                              id=chunk index, arg=bytes (the matching
                              FOLD event fires when the worker runs
                              it — the gap between the two is queue
                              wait, fold-pool pressure made visible) */
  TDR_TEL_SHARD = 19,      /* progress-shard drain batch: qp=the
                              shard thread's track id, id=shard
                              ordinal, arg=completions consumed.
                              Emitted with engine=0 (process-level,
                              like the copy pool's events): batch
                              boundaries ride thread timing, so they
                              must not perturb per-engine replay
                              shapes. */
  TDR_TEL_FAULT = 20,      /* netem rider fired on an outbound frame
                              (delay/jitter sleep, throttle pacing
                              wait, duplicate, or reorder hold):
                              id=frame seq, arg=bytes. Emitted once
                              per frame however many riders matched;
                              the per-clause hit counters carry the
                              breakdown. */
};

/* Histograms. Recorded at log-linear ("log2 × 8") resolution: 8
 * linear sub-buckets per power-of-two octave (values 0..15 exact),
 * bounding the relative quantization error at 12.5% — percentile
 * estimates are real numbers, not octave edges (the BENCH_r06
 * saturation: every latency percentile read 8191/32767/65535).
 * tdr_tel_hist_read folds the fine rows back into the legacy
 * 64-octave view; tdr_tel_hist_read_fine exposes the fine rows. */
enum {
  TDR_HIST_CHUNK_LAT_US = 0, /* post → completion latency, us    */
  TDR_HIST_CHUNK_BYTES = 1,  /* completed op payload sizes       */
  TDR_HIST_COPY_BYTES = 2,   /* copy-pool memcpy sizes           */
  TDR_HIST_RING_LAT_US = 3,  /* whole-collective latency, us     */
  TDR_HIST_RING_MBPS = 4,    /* whole-collective bandwidth, MB/s */
  TDR_HIST_COUNT = 5,
};

/* Fine rows: 16 exact small-value buckets + 8 sub-buckets for each of
 * the 60 octaves above them (indices 16..495), padded to 512. */
#define TDR_HIST_FINE_BUCKETS 512

typedef struct {
  uint64_t ts_ns;  /* CLOCK_MONOTONIC */
  uint16_t type;   /* TDR_TEL_* */
  uint16_t engine; /* engine track id (tdr_tel_engine_id) */
  uint32_t qp;     /* qp track id (tdr_tel_qp_id), 0 = none */
  uint64_t id;     /* wr_id / frame seq / call seq */
  uint64_t arg;    /* bytes / status / attempt (per type) */
  /* Collective trace id (0 = none): the per-world monotonically
   * increasing id of the collective this event belongs to, stamped by
   * the posting rank (tdr_ring_set_coll) and CARRIED IN THE FRAME
   * HEADER to the peer when both ends negotiated FEAT_COLL_ID — so
   * two ranks' wire_rx/land/verify/fold/wc events for one collective
   * join by key across a merged fleet timeline. Ids with bit 63 set
   * were auto-assigned by the ring (caller never set one). */
  uint64_t coll;
} tdr_tel_event;

int tdr_tel_enabled(void);
/* Re-read TDR_TELEMETRY / TDR_TELEMETRY_RING and clear the ring,
 * histograms, and recorded/dropped counts (tests toggle the env then
 * call this, like tdr_fault_plan_reset). */
void tdr_tel_reset(void);
uint64_t tdr_tel_now_ns(void);
/* Remove up to `max` events from the ring, oldest first. */
int tdr_tel_drain(tdr_tel_event *out, int max);
uint64_t tdr_tel_recorded(void); /* events recorded since reset */
uint64_t tdr_tel_dropped(void);  /* events overwritten unread   */
const char *tdr_tel_event_name(int type);
int tdr_tel_hist_count(void);
const char *tdr_tel_hist_name(int which);
void tdr_tel_hist_read(int which, uint64_t out[64]);
/* Fine (log2 × 8) histogram rows: bucket count, the inclusive upper
 * edge of a fine bucket (the conservative percentile estimate — the
 * Python side calls this instead of re-deriving the edge math), and
 * the row itself (fills min(max, TDR_HIST_FINE_BUCKETS), returns the
 * number written). */
int tdr_tel_hist_fine_buckets(void);
uint64_t tdr_tel_hist_fine_upper(int idx);
int tdr_tel_hist_read_fine(int which, uint64_t *out, int max);
/* Stable per-process track ids (assigned at open/bring-up whether or
 * not telemetry is enabled — they also label exported timelines). */
int tdr_tel_engine_id(const tdr_engine *e);
int tdr_tel_qp_id(const tdr_qp *qp);

/* Unified native counter registry: one call reads every engine-side
 * counter — the seal/integrity ladder, fault-plan aggregates, copy
 * tiers, and the telemetry ring's own accounting — under stable
 * dotted names, replacing per-subsystem polling (whose multi-call
 * windows could double-count deltas). Counters that share a producer
 * (fault seen/hits; the copy tiers) are gathered in one pass so a
 * snapshot never shows impossible relations (hits > seen); counters
 * from DIFFERENT subsystems are individually-atomic monotonic reads,
 * not a global freeze. */
int tdr_counter_count(void);
const char *tdr_counter_name(int idx);
/* Fill out[0..min(max, count)) in registry order; returns the number
 * written. */
int tdr_counters_read(uint64_t *out, int max);

/* spec: "emu", "verbs", "verbs:<device>", or "auto" (verbs, else emu). */
tdr_engine *tdr_engine_open(const char *spec);
void tdr_engine_close(tdr_engine *e);
int tdr_engine_kind(const tdr_engine *e);
const char *tdr_engine_name(const tdr_engine *e);

/* ------------------------------------------------------------------ *
 * Per-engine QP accounting — multi-tenant engines (one engine hosting
 * several concurrent named worlds) get a hard cap on live QPs, checked
 * at bring-up: when the limit is set (> 0; 0 = unlimited) and reached,
 * tdr_listen/tdr_connect fail fast with a budget error BEFORE touching
 * the network, so an over-budget world dies at bring-up instead of
 * starving a co-tenant world of connections mid-soak. Accounting is
 * backend-independent (enforced at the C API boundary); the count
 * covers every live QP on the engine regardless of which world owns
 * it. Budget errors are non-retryable: rebuilding cannot create QP
 * headroom.
 * ------------------------------------------------------------------ */
void tdr_engine_set_qp_limit(tdr_engine *e, int limit);
int tdr_engine_qp_limit(const tdr_engine *e);
int tdr_engine_qp_live(const tdr_engine *e);

/* Registration. Mirrors the reference's acquire+get_pages+dma_map
 * front-loading (amdp2p.c:112-264) collapsed into one call; dereg
 * mirrors put_pages+release (amdp2p.c:283-313, 345-360). */
tdr_mr *tdr_reg_mr(tdr_engine *e, void *addr, size_t len, int access);
tdr_mr *tdr_reg_dmabuf_mr(tdr_engine *e, int fd, size_t offset, size_t len,
                          uint64_t iova, int access);
int tdr_dereg_mr(tdr_mr *mr);
uint32_t tdr_mr_lkey(const tdr_mr *mr);
uint32_t tdr_mr_rkey(const tdr_mr *mr);
uint64_t tdr_mr_addr(const tdr_mr *mr);
uint64_t tdr_mr_len(const tdr_mr *mr);

/* Revocation: the free-while-registered flow (amdp2p.c:88-109). After
 * this, remote access to the MR completes with TDR_WC_REM_ACCESS_ERR
 * and local posts fail; dereg remains safe (idempotent teardown, the
 * free_callback_called handshake of amdp2p.c:299-302). */
int tdr_mr_invalidate(tdr_mr *mr);

/* Whether the CPU can fold into this MR's memory (false for verbs
 * dma-buf MRs — no CPU mapping). Ring allreduces over non-foldable
 * MRs fail up front with a clear error. */
int tdr_mr_cpu_foldable(const tdr_mr *mr);

/* Connection bring-up over an out-of-band TCP rendezvous (the role
 * perftest's TCP port plays). Blocking; one QP per call.
 * tdr_listen_timeout bounds the accept wait (-1 = forever) so an
 * elastic rendezvous whose peer never arrives returns instead of
 * stranding a thread in accept on the port the next attempt needs. */
tdr_qp *tdr_listen(tdr_engine *e, const char *bind_host, int port);
tdr_qp *tdr_listen_timeout(tdr_engine *e, const char *bind_host, int port,
                           int timeout_ms);
tdr_qp *tdr_connect(tdr_engine *e, const char *host, int port,
                    int timeout_ms);

/* Connection flags for the tiered bring-up variants below. */
enum {
  /* Refuse the CMA fast path for this connection even when the probe
   * would succeed: the QP negotiates the STREAM tier (socket payloads,
   * full payload seals). The hierarchical inter-host ring uses this so
   * a two-host topology EMULATED on one machine (host-key override)
   * still exercises real stream-tier framing — payload CRCs, NAK
   * retransmit, corrupt riders — on the tier that models the slow
   * inter-host links. One side forcing is enough: it reports its probe
   * as failed, so both ends agree on the tier (the handshake's
   * both-directions-verified rule). */
  TDR_CONN_FORCE_STREAM = 1 << 0,
};
tdr_qp *tdr_listen_tier(tdr_engine *e, const char *bind_host, int port,
                        int timeout_ms, int flags);
tdr_qp *tdr_connect_tier(tdr_engine *e, const char *host, int port,
                         int timeout_ms, int flags);
int tdr_qp_close(tdr_qp *qp);

/* Work posting. Returns 0 on success, -1 on immediate local failure.
 * Completion (incl. remote status) arrives via tdr_poll. */
int tdr_post_write(tdr_qp *qp, tdr_mr *lmr, size_t loff, uint64_t raddr,
                   uint32_t rkey, size_t len, uint64_t wr_id);
int tdr_post_read(tdr_qp *qp, tdr_mr *lmr, size_t loff, uint64_t raddr,
                  uint32_t rkey, size_t len, uint64_t wr_id);
int tdr_post_send(tdr_qp *qp, tdr_mr *lmr, size_t loff, size_t len,
                  uint64_t wr_id);
int tdr_post_recv(tdr_qp *qp, tdr_mr *lmr, size_t loff, size_t maxlen,
                  uint64_t wr_id);

/* Fused reduce-on-receive (the SHARP-style in-transport reduction):
 * like tdr_post_recv, but the inbound SEND payload is folded into the
 * buffer (dst op= src, with TDR_DT_ / TDR_RED_ semantics) by the
 * progress engine — no scratch buffer or second pass. Capability-gated:
 * tdr_qp_has_recv_reduce() returns 1 on engines that support it (emu);
 * on others the post fails and callers fall back to recv + reduce. */
int tdr_post_recv_reduce(tdr_qp *qp, tdr_mr *lmr, size_t loff, size_t maxlen,
                         int dtype, int red_op, uint64_t wr_id);
int tdr_qp_has_recv_reduce(tdr_qp *qp);

/* Fused fold-and-write-back send (the other half of an in-transport
 * allreduce exchange): like tdr_post_send, but the peer — having
 * matched this message to a tdr_post_recv_reduce — folds the payload
 * into its buffer AND writes the folded result back IN PLACE over
 * this send's source region, all in one pass while the data is hot.
 * The send completion fires only after the write-back has landed, so
 * for a symmetric exchange no separate return transfer (all-gather
 * phase) is needed at all. Capability-gated like recv_reduce. */
int tdr_post_send_foldback(tdr_qp *qp, tdr_mr *lmr, size_t loff, size_t len,
                           uint64_t wr_id);
int tdr_qp_has_send_foldback(tdr_qp *qp);

/* Whether BOTH ends of this QP negotiated participation in the world-2
 * fused exchange schedule (TDR_NO_FUSED2 opts a rank out at the
 * handshake, degrading the whole connection to the compatible
 * rightward schedules instead of a per-rank wire mismatch). */
int tdr_qp_has_fused2(tdr_qp *qp);

/* Max recv_reduce postings this QP wants in flight (bounded staging
 * engines — verbs — return their slot budget; 0 = unbounded). The
 * ring layer sizes its recv window to this. */
size_t tdr_qp_rr_window(tdr_qp *qp);

/* Poll up to `max` completions; waits up to timeout_ms (0 = non-block,
 * -1 = forever). Returns count, or -1 on error. */
int tdr_poll(tdr_qp *qp, tdr_wc *wc, int max, int timeout_ms);

/* ------------------------------------------------------------------ *
 * Ring allreduce — the cross-slice collective consumer (the layer the
 * reference left to MPI/NCCL userspace, SURVEY.md §2 "Distributed
 * communication backend inventory"). Classic reduce-scatter +
 * all-gather over the neighbor QPs; per-rank traffic is
 * 2*(world-1)/world of the buffer, the textbook bus-bandwidth-optimal
 * schedule.
 * ------------------------------------------------------------------ */

typedef struct tdr_ring tdr_ring;

enum {
  TDR_DT_F32 = 0,
  TDR_DT_F64 = 1,
  TDR_DT_I32 = 2,
  TDR_DT_I64 = 3,
  TDR_DT_BF16 = 4, /* accumulated in f32 */
  TDR_DT_U8 = 5,   /* byte transport (alltoall/all_gather/broadcast);
                      reducing collectives reject it */
  TDR_DT_I8 = 6,   /* int8 wire compression: quantized payload of the
                      scale-carrying q8 schedule. Plain reducing
                      collectives reject it (a scale-less int8 sum
                      overflows); use tdr_ring_allreduce_q8. */
};

enum { TDR_RED_SUM = 0, TDR_RED_MAX = 1, TDR_RED_MIN = 2 };

/* left/right: QPs to the ring neighbors (the same QP for world == 2).
 * The ring borrows the QPs; it does not close them. */
tdr_ring *tdr_ring_create(tdr_engine *e, tdr_qp *left, tdr_qp *right,
                          int rank, int world);
/* Multi-channel ring: `channels` independent QPs per neighbor, chunk
 * i of every striped schedule riding channel i % channels — the wire
 * transfer, seal verification, and fold of consecutive chunks proceed
 * in parallel on independent progress engines. lefts[c] on this rank
 * must be connected to rights[c] on the left neighbor (the Python
 * bootstrap brings channels up in index order, which guarantees it).
 * Every channel must have negotiated identical capabilities
 * (reduce-on-receive, foldback, fused2, seal) — creation fails
 * otherwise, because a schedule striped across capability-divergent
 * channels would desynchronize mid-collective. Completion ordering,
 * verify-before-fold, NAK/retransmit budgets, and generation fencing
 * all hold PER CHANNEL (each channel is its own QP: seal state and
 * retransmit bookkeeping are channel-local by construction).
 * channels == 1 is exactly tdr_ring_create. */
tdr_ring *tdr_ring_create_channels(tdr_engine *e, tdr_qp *const *lefts,
                                   tdr_qp *const *rights, int channels,
                                   int rank, int world);
/* Channel count of a ring (1 for tdr_ring_create rings). */
int tdr_ring_channels(const tdr_ring *r);
/* EFFECTIVE ring chunk size in bytes (TDR_RING_CHUNK override or the
 * built-in default): the value schedule digests must hash — the raw
 * env string hides a changed built-in default from the digest. */
size_t tdr_ring_chunk_bytes(void);
/* Stamp the collective trace id for the NEXT collective on this ring
 * (blocking call or async start): the id lands in every telemetry
 * event of that collective and — when FEAT_COLL_ID is negotiated —
 * rides the frame header to the peer. Sticky until replaced; the
 * caller (the world layer) sets a fresh per-world monotonic id before
 * every collective. Rings whose caller never sets one auto-assign
 * ids with bit 63 set, so caller-assigned and auto ids never
 * collide. Purely observational: never negotiated, never part of the
 * schedule digest, and results are unaffected. */
void tdr_ring_set_coll(tdr_ring *r, uint64_t coll_id);
int tdr_ring_allreduce(tdr_ring *r, void *data, size_t count, int dtype,
                       int red_op);
/* The rest of the MPI-app collective surface, sharing the
 * allreduce's segment layout and ownership convention:
 * reduce_scatter is its phase 1 — on return this rank's OWNED range
 * (the fully-reduced segment (rank+1) % world) is reported via
 * own_off/own_len (byte offset/length into data; either may be
 * NULL); all_gather is its phase 2 and assumes that same ownership;
 * broadcast streams root's nbytes down the ring, store-and-forward
 * per chunk. allreduce ≡ reduce_scatter; all_gather. */
int tdr_ring_reduce_scatter(tdr_ring *r, void *data, size_t count,
                            int dtype, int red_op, size_t *own_off,
                            size_t *own_len);
int tdr_ring_all_gather(tdr_ring *r, void *data, size_t count, int dtype);
int tdr_ring_broadcast(tdr_ring *r, void *data, size_t nbytes, int root);
/* In-place MPI_Alltoall: ``data`` = world equal segments, segment j
 * FOR rank j on entry, FROM rank j on return. count must divide by
 * world. Bundle-shrink ring schedule, w(w-1)/2 segments per link. */
int tdr_ring_alltoall(tdr_ring *r, void *data, size_t count, int dtype);
/* Root-reduce: converging fold toward root (one N-byte pass per
 * link, chunk-pipelined through the fused recv_reduce op). In-place
 * and DESTRUCTIVE on non-root ranks: their buffers end holding the
 * partial sums that passed through them; only root holds the full
 * reduction. */
int tdr_ring_reduce(tdr_ring *r, void *data, size_t count, int dtype,
                    int red_op, int root);
/* int8 wire-compressed allreduce (FEAT_WIRE_Q8 on every channel QP,
 * else fails fast): `q8` holds count int8 elements quantized with the
 * symmetric per-bucket `scale_in` (true value = q[i] * scale_in, the
 * caller computed scale_in = absmax/127 and keeps the error-feedback
 * residual). Runs the textbook RS+AG ring but each wire piece is
 * [f32 running scale][int8 segment] inside an ordinary sealed SEND
 * payload, and the fold REQUANTIZES under the summed scale
 * (q := round((s_l*q_l + s_f*q_f)/(s_l+s_f))) so magnitudes never
 * clip no matter the world size. The all-gather circulates the
 * reduced [scale][q8] pieces verbatim, so every rank dequantizes
 * IDENTICAL bits: f32_out[i] = q[i] * scale_of_segment, bitwise equal
 * across ranks. `q8` is scratch (destroyed); f32_out receives the
 * count-element f32 result and may be any host buffer (never posted
 * to the wire). Wire bytes ~= half of the bf16 schedule's for the
 * same count (+4 bytes of scale per piece). */
int tdr_ring_allreduce_q8(tdr_ring *r, void *q8, size_t count,
                          float scale_in, float *f32_out);
/* Front-load registration for a caller-stable buffer; allreduces on it
 * post work requests only. Unregistered buffers are registered per
 * call (safe for arbitrary/recycled addresses, slower). */
int tdr_ring_register(tdr_ring *r, void *base, size_t len);
int tdr_ring_unregister(tdr_ring *r, void *base);
/* Adopt an externally-owned MR (typically a dma-buf MR over device
 * memory, tdr_reg_dmabuf_mr with iova = the device VA) as the data MR
 * for allreduces whose data pointer equals `base`. The ring NEVER
 * deregisters an adopted MR — the caller keeps ownership and must
 * tdr_ring_unregister(base) before invalidating/deregistering it.
 * This is the zero-copy collective path: the ring posts directly
 * against pinned device memory, no host staging. */
int tdr_ring_adopt_mr(tdr_ring *r, void *base, tdr_mr *mr);
void tdr_ring_destroy(tdr_ring *r);

/* ------------------------------------------------------------------ *
 * Nonblocking ring collectives — handle-based allreduce.
 *
 * tdr_ring_start posts an allreduce onto the ring's async driver (one
 * dedicated thread per ring, spawned lazily at the first start and
 * joined at destroy) and returns immediately with a handle. Ops
 * execute STRICTLY in submission order — submission order is the SPMD
 * contract: every rank must start the same ops in the same order, and
 * the driver serializes them on the ring exactly as back-to-back
 * blocking calls would, so a mixed async/blocking fleet stays
 * wire-compatible and results are bitwise identical to the blocking
 * API. While an op is in flight the CALLER's thread never parks on
 * the progress machinery (the shard threads own polling; the driver
 * thread owns posting/consuming), which is what lets a training step
 * overlap its backward pass with the wire.
 *
 * Failure is HANDLE-SCOPED: a failed op records its error on the
 * handle; tdr_ring_wait/tdr_ring_test surface it into the calling
 * thread's tdr_last_error slot with the same status labels as the
 * blocking API, so the existing retryable/fatal taxonomy (and the
 * elastic rebuild ladder above it) applies unchanged. After any async
 * failure the driver fails subsequent queued ops fast ("aborted after
 * earlier failure") instead of posting into a broken ring — the
 * caller's recovery is a world rebuild, which replaces the ring.
 *
 * The data buffer must stay alive and untouched until the handle
 * completes. Do not run OTHER collectives on the ring between start
 * and wait unless every rank interleaves them identically (they would
 * serialize correctly but a cross-rank order divergence desyncs the
 * wire, exactly as with blocking calls from two threads).
 *
 * tdr_ring_op_free on a still-pending handle blocks until the op
 * completes (every op terminates: the stall deadline bounds a wedged
 * collective), then releases it.
 * ------------------------------------------------------------------ */
typedef struct tdr_ring_op tdr_ring_op;
tdr_ring_op *tdr_ring_start(tdr_ring *r, void *data, size_t count,
                            int dtype, int red_op);
/* Nonblocking standalone phases on the same async driver — the
 * hierarchical schedule's building blocks (intra-host reduce-scatter
 * and all-gather overlap the inter-host ring through these). Same
 * submission-order/SPMD contract, handle surface, and failure
 * taxonomy as tdr_ring_start; results are bitwise the blocking
 * phases'. The reduce-scatter handle reports no ownership outparams —
 * callers read the (pure, layout-deterministic) segment bounds via
 * tdr_ring_owned_segment below. */
tdr_ring_op *tdr_ring_start_reduce_scatter(tdr_ring *r, void *data,
                                           size_t count, int dtype,
                                           int red_op);
tdr_ring_op *tdr_ring_start_all_gather(tdr_ring *r, void *data,
                                       size_t count, int dtype);
/* Nonblocking tdr_ring_allreduce_q8 — same driver, submission-order,
 * and failure contract as tdr_ring_start. Both `q8` and `f32_out`
 * must stay alive and untouched until the handle completes. */
tdr_ring_op *tdr_ring_start_q8(tdr_ring *r, void *q8, size_t count,
                               float scale_in, float *f32_out);
/* The BYTE offset/length of the segment this rank owns after a
 * reduce-scatter of `count` elements of `dtype` — the same
 * (rank+1) % world convention and remainder layout the collectives
 * use, exposed so async callers never re-derive the segment math. */
int tdr_ring_owned_segment(tdr_ring *r, size_t count, int dtype,
                           size_t *own_off, size_t *own_len);
/* 1 = done ok, 0 = still in flight, -1 = failed (error in
 * tdr_last_error and tdr_ring_op_error). */
int tdr_ring_test(tdr_ring_op *op);
/* Block until the op completes (timeout_ms < 0 = forever). 0 = done
 * ok; -1 = failed or timed out (tdr_last_error distinguishes; a
 * timeout leaves the op in flight and wait may be called again). */
int tdr_ring_wait(tdr_ring_op *op, int timeout_ms);
/* The op's recorded error ("" while pending or on success). */
const char *tdr_ring_op_error(tdr_ring_op *op);
/* 1 once the op completed (ok or failed). Unlike tdr_ring_test this
 * NEVER writes the calling thread's error slot — safe from finalizer
 * contexts that must not clobber an error another call is reading. */
int tdr_ring_op_done(tdr_ring_op *op);
void tdr_ring_op_free(tdr_ring_op *op);

/* Which schedule the LAST tdr_ring_allreduce on this ring ran —
 * introspection for tests/benches asserting that the negotiated
 * capabilities actually selected the fused paths. */
enum {
  TDR_SCHED_NONE = 0,     /* no allreduce yet */
  TDR_SCHED_GENERIC = 1,  /* two-phase pipeline (scratch fold) */
  TDR_SCHED_FUSED2 = 2,   /* world-2 fused exchange */
  TDR_SCHED_FUSED2_FB = 3,/* world-2 fused exchange with foldback */
  TDR_SCHED_WAVEFRONT = 4,/* world>2 flattened wavefront */
  TDR_SCHED_Q8 = 5,       /* int8 scale-carrying RS+AG (allreduce_q8) */
};
int tdr_ring_last_schedule(const tdr_ring *r);

#ifdef __cplusplus
}
#endif

#endif /* TDR_H_ */
