"""DP trainer — the end-to-end consumer (BASELINE.md config 4).

Training topology mirrors the multi-slice JAX setup the baseline
names (Llama-3 DP across 2 slices of v5e-8):

- **Intra-slice**: one jitted train step over the slice's mesh
  (dp × tp), shardings from ``parallel.mesh``; XLA's ICI collectives
  handle everything inside the slice.
- **Cross-slice**: gradient allreduce between slices rides this
  framework's transport (``CrossSliceAllReduce`` → ring over RDMA),
  replacing XLA's host-staged DCN path — the reason this framework
  exists (SURVEY.md §5 "Distributed communication backend").

When a cross-slice hook is installed the step splits into
grad-compute and apply so the sync sits between them; without it the
whole step is one fused jit.
"""

from __future__ import annotations

import contextlib
import dataclasses
import functools
import math
import os
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import optax
from jax.experimental import io_callback
from jax.sharding import NamedSharding, PartitionSpec as P

from rocnrdma_tpu.models.llama import (
    Llama, LlamaConfig, cross_entropy_loss, make_model, resolve_pallas)
from rocnrdma_tpu.ops.sharding import pallas_sharding
from rocnrdma_tpu.parallel.mesh import (
    batch_spec, make_mesh, param_shardings, replicated)
from rocnrdma_tpu.utils.trace import trace


def loss_fn(model: Llama, params, tokens) -> jnp.ndarray:
    """Next-token cross entropy on (B, S) int32 tokens."""
    logits = model.apply(params, tokens[:, :-1])
    return cross_entropy_loss(logits, tokens[:, 1:])


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _grad_tap(cb, idx, tree):
    """Identity on a parameter subtree whose BACKWARD rule delivers
    the subtree's concrete cotangent — the layer's gradients — to a
    host collector (``cb(idx, grads)``) via ordered io_callback, the
    moment XLA's backward pass finishes accumulating it. The forward
    value and the cotangent pass through UNCHANGED, so the jitted
    step's outputs are bitwise those of the untapped program; the
    ``ordered=True`` token chain makes the delivery order the
    program's backward order — identical on every rank, which is what
    keeps the per-layer allreduce submission order SPMD without any
    cross-rank coordination."""
    return tree


def _grad_tap_fwd(cb, idx, tree):
    return tree, None


def _grad_tap_bwd(cb, idx, _res, ct):
    io_callback(lambda g: cb(idx, g), None, ct, ordered=True)
    return (ct,)


_grad_tap.defvjp(_grad_tap_fwd, _grad_tap_bwd)


@dataclasses.dataclass
class ElasticPolicy:
    """Opt-in auto-resume for transient transport failures.

    When a ``step()`` raises a RETRYABLE ``TransportError`` (peer
    death, connection drop, stall — the taxonomy lives on the
    exception), the trainer drops the transport world, rebuilds it
    (``RingWorld.rebuild``: re-rendezvous with backoff under a new
    generation), restores params/optimizer/step from the last
    checkpoint, and re-runs the step — so a SIGKILLed-and-restarted
    rank rejoins and training converges to the same params as an
    uninterrupted run. Fatal errors (access violations, schedule
    mismatches) re-raise unchanged.

    ``checkpoint_path``: where this rank saves/restores its state
    (each rank uses its own path; DP keeps ranks in lockstep, so the
    contents agree). ``save_every``: checkpoint cadence in steps.
    With 1 (the default) the failed step re-runs exactly in place;
    with larger values a mid-interval failure restores a checkpoint
    OLDER than the current step, and since ``step()`` cannot replay
    the caller's intervening batches it raises instead of silently
    desynchronizing — the caller must then drive its data loop from
    ``trainer.global_step``. ``max_resumes``: resume budget PER STEP
    before the error propagates. ``rebuild``: kwargs forwarded to
    ``RingWorld.rebuild`` (retry budget, backoff, per-attempt
    deadline).

    ``quarantine_nonfinite``: the last rung below the elastic ladder.
    A step whose all-reduced gradients VERIFY (the transport seal
    caught no corruption) but come back non-finite is retried ONCE
    from the pre-step state — params/optimizer are untouched because
    apply never ran, so the retry recomputes and re-syncs the same
    batch in place. Only if the retry is ALSO non-finite does the
    elastic path engage (rebuild → restore → re-run), on the theory
    that a deterministic non-finite loss would have been non-finite
    the first time: a once-only non-finite is transport-shaped, not
    data-shaped."""

    checkpoint_path: str
    save_every: int = 1
    max_resumes: int = 4
    rebuild: Dict[str, Any] = dataclasses.field(default_factory=dict)
    quarantine_nonfinite: bool = True


class _NonFiniteGrads(RuntimeError):
    """Internal: the all-reduced gradients verified but are non-finite
    (NaN/inf). Raised from the post-sync check so ``step()`` can run
    the quarantine retry before the elastic ladder engages."""


class Trainer:
    def __new__(cls, *args, **kwargs):
        # Front door for sequence parallelism: Trainer(cfg,
        # seq_parallel=ring_world) constructs the layerwise seq-
        # parallel runner instead (parallel/seq_parallel.py) — the
        # sequence axis is partitioned across the transport ring, not
        # the jit-internal mesh, so it is a different orchestration.
        if cls is Trainer and kwargs.get("seq_parallel") is not None:
            from rocnrdma_tpu.parallel.seq_parallel import SeqParallelTrainer

            kw = dict(kwargs)
            world = kw.pop("seq_parallel")
            if len(args) > 1:
                # Positional mesh_shape (the two_slice_dp.py spelling)
                # would otherwise land in SeqParallelTrainer's world
                # slot with a baffling TypeError.
                raise ValueError(
                    "mesh_shape does not apply to the seq_parallel "
                    "trainer (one device per ring rank)")
            for unsupported in ("mesh_shape", "devices", "cross_slice_sync",
                                "elastic"):
                if kw.pop(unsupported, None) is not None:
                    raise ValueError(
                        f"{unsupported} does not apply to the "
                        "seq_parallel trainer (one device per ring rank)")
            return SeqParallelTrainer(*args, world=world, **kw)
        return super().__new__(cls)

    def __init__(
        self,
        config: "LlamaConfig | str",
        mesh_shape: Optional[Dict[str, int]] = None,
        learning_rate: float = 3e-4,
        weight_decay: float = 0.1,
        cross_slice_sync: Optional[Callable[[Any], Any]] = None,
        devices=None,
        seed: int = 0,
        seq_parallel=None,  # None = disabled; non-None handled by __new__
        elastic: Optional[ElasticPolicy] = None,
        **model_overrides,
    ):
        if seq_parallel is not None:
            # Unreachable via Trainer(...) (__new__ intercepts), so
            # this only fires for subclasses, where silently dropping
            # the flag would hand back a plain DP trainer.
            raise ValueError(
                "seq_parallel requires the Trainer base class "
                "(__new__ dispatches to SeqParallelTrainer; subclasses "
                "are not intercepted)")
        if "sp_mode" in model_overrides:
            raise ValueError(
                "sp_mode selects the seq-parallel attention strategy "
                "and requires seq_parallel=<RingWorld>")
        self.model = make_model(config, **model_overrides)
        self.cfg = self.model.cfg
        self.mesh = make_mesh(mesh_shape or {"dp": 1, "tp": 1}, devices)
        # GSPMD has no partitioning rule for pallas_call, so on a
        # multi-device mesh the Pallas kernels can only run inside a
        # shard_map manual region (ops/sharding.py): batch on dp,
        # heads on tp. When the geometry shards cleanly, trace every
        # step under that context; otherwise pin the auto flags to
        # the XLA path, which GSPMD shards natively.
        self._trace_ctx = contextlib.nullcontext
        if self.mesh.devices.size > 1:
            tp = self.mesh.shape.get("tp", 1)
            # Per-kernel shardability: rmsnorm shard_maps over rows and
            # only needs the dp axis; attention additionally needs the
            # heads (incl. GQA kv heads) to divide tp.
            rms_ok = "dp" in self.mesh.shape
            attn_ok = (rms_ok and "tp" in self.mesh.shape
                       and self.cfg.n_heads % tp == 0
                       and self.cfg.n_kv_heads % tp == 0)
            # Explicitly-requested Pallas that cannot shard must fail
            # loudly, not leave a bare pallas_call for GSPMD (no
            # partitioning rule → replicated operands or a compile
            # error on TPU) or silently degrade.
            if self.cfg.use_pallas_attention and not attn_ok:
                raise ValueError(
                    f"use_pallas_attention=True on a "
                    f"{self.mesh.devices.size}-device mesh, but "
                    f"n_heads={self.cfg.n_heads}/n_kv_heads="
                    f"{self.cfg.n_kv_heads} don't divide tp={tp} (or "
                    "the mesh lacks dp/tp axes); set the flag to None "
                    "(auto) or fix the mesh")
            if self.cfg.use_pallas_rmsnorm and not rms_ok:
                raise ValueError(
                    "use_pallas_rmsnorm=True on a multi-device mesh "
                    "without a dp axis; set the flag to None (auto) "
                    "or add a dp axis")
            pins = {}
            if self.cfg.use_pallas_attention is None and not attn_ok:
                pins["use_pallas_attention"] = False
            if self.cfg.use_pallas_rmsnorm is None and not rms_ok:
                pins["use_pallas_rmsnorm"] = False
            if pins:
                self.model = make_model(self.cfg, **pins)
                self.cfg = self.model.cfg
            if ((resolve_pallas(self.cfg.use_pallas_attention) and attn_ok)
                    or (resolve_pallas(self.cfg.use_pallas_rmsnorm,
                                       tpu_default=False)
                        and rms_ok)):
                self._trace_ctx = lambda: pallas_sharding(
                    self.mesh, batch_axis="dp", head_axis="tp")
        self.tx = optax.adamw(learning_rate, weight_decay=weight_decay)
        self.cross_slice_sync = cross_slice_sync
        if elastic is not None and cross_slice_sync is None:
            raise ValueError(
                "elastic= recovers the cross-slice transport world and "
                "requires cross_slice_sync")
        self.elastic = elastic
        # Optimizer steps completed (and, with an elastic policy, the
        # step number of the last checkpoint when save_every == 1).
        self.global_step = 0
        # Stamp the first cross-slice sync (and the first after every
        # resume) with the step number: all ranks proving they are at
        # the SAME step before any gradient is averaged is what makes
        # recovery exact rather than silently mixing batches.
        self._stamp_sync = cross_slice_sync is not None

        rng = jax.random.PRNGKey(seed)
        with self.mesh, self._trace_ctx():
            abstract = jax.eval_shape(
                lambda r: self.model.init(
                    r, jnp.zeros((1, 8), dtype=jnp.int32)), rng)
            self._pshard = param_shardings(self.mesh, abstract)
            init_fn = jax.jit(
                lambda r: self.model.init(
                    r, jnp.zeros((1, 8), dtype=jnp.int32)),
                out_shardings=self._pshard)
            self.params = init_fn(rng)
            opt_abstract = jax.eval_shape(self.tx.init, abstract)
            self._oshard = jax.tree_util.tree_map(
                lambda _: replicated(self.mesh), opt_abstract,
                is_leaf=lambda x: hasattr(x, "shape"))
            self.opt_state = jax.jit(
                self.tx.init, out_shardings=self._oshard)(self.params)

        data_sharding = NamedSharding(self.mesh, batch_spec())

        def grads_of(params, tokens):
            loss, grads = jax.value_and_grad(
                lambda p: loss_fn(self.model, p, tokens))(params)
            return loss, grads

        def apply(params, opt_state, grads):
            updates, new_opt = self.tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), new_opt

        def full_step(params, opt_state, tokens):
            loss, grads = grads_of(params, tokens)
            new_params, new_opt = apply(params, opt_state, grads)
            return new_params, new_opt, loss

        with self.mesh:
            self._jit_grads = jax.jit(
                grads_of,
                in_shardings=(self._pshard, data_sharding),
                out_shardings=(replicated(self.mesh), self._pshard))
            self._jit_apply = jax.jit(
                apply,
                in_shardings=(self._pshard, self._oshard, self._pshard),
                out_shardings=(self._pshard, self._oshard))
            self._jit_full = jax.jit(
                full_step,
                in_shardings=(self._pshard, self._oshard, data_sharding),
                out_shardings=(self._pshard, self._oshard,
                               replicated(self.mesh)))
        self._data_sharding = data_sharding

        # Per-layer backward overlap (cross_slice_sync with
        # per_layer=True): tap every top-level parameter subtree
        # (embed, layer_i, final_norm, lm_head) with _grad_tap so the
        # backward pass DELIVERS each layer's gradients to the pending
        # sync as it produces them — bucket k's allreduce rides the
        # wire while layer k-1's grads are still being computed. The
        # bucket plan is a pure function of the abstract param tree,
        # so every rank derives the identical plan (and the sync layer
        # hashes it into the schedule digest before any wire work).
        self._per_layer = bool(getattr(cross_slice_sync, "per_layer",
                                       False)
                               and hasattr(cross_slice_sync,
                                           "start_layered"))
        self._pending_layers = None
        if self._per_layer:
            inner = abstract["params"]
            keys = sorted(inner)  # the dict flatten order jax uses
            self.layer_plan = [
                (k, [(int(math.prod(leaf.shape)), str(leaf.dtype))
                     for leaf in jax.tree_util.tree_leaves(inner[k])])
                for k in keys]

            def tapped_grads(params, tokens):
                def tapped_loss(p):
                    tp = {k: _grad_tap(self._deliver_bucket, i,
                                       p["params"][k])
                          for i, k in enumerate(keys)}
                    q = dict(p)
                    q["params"] = tp
                    return loss_fn(self.model, q, tokens)

                return jax.value_and_grad(tapped_loss)(params)

            with self.mesh:
                self._jit_grads = jax.jit(
                    tapped_grads,
                    in_shardings=(self._pshard, data_sharding),
                    out_shardings=(replicated(self.mesh), self._pshard))

    def shard_batch(self, tokens):
        return jax.device_put(tokens, self._data_sharding)

    def _deliver_bucket(self, idx: int, grads_subtree) -> None:
        """Target of the per-layer gradient taps: forward bucket
        ``idx``'s concrete host gradients to the step's pending sync.
        Runs inside the XLA callback machinery, so it must never
        raise — push() records failures and finish() re-raises them."""
        pending = self._pending_layers
        if pending is None:
            return  # tap fired outside a layered step (e.g. warmup)
        try:
            pending.push(idx, jax.tree_util.tree_leaves(grads_subtree))
        except BaseException:  # noqa: BLE001 — surfaced at finish()
            pass

    def _step_once(self, tokens) -> float:
        """One optimizer step; returns the (pre-update) loss."""
        tokens = self.shard_batch(tokens)
        step_no = self.global_step + 1
        # _trace_ctx matters only on the first call (trace time); it is
        # a no-op for steady-state dispatch of the compiled step.
        # The phase spans (grads / sync / apply) are the top bars of
        # the flight-recorder timeline: under trainer.sync sit the
        # xslice.sync and world.allreduce spans, and under those the
        # native chunk events down to individual retransmits.
        with self.mesh, self._trace_ctx():
            if self.cross_slice_sync is None:
                with trace.span("trainer.fused_step", step=step_no):
                    self.params, self.opt_state, loss = self._jit_full(
                        self.params, self.opt_state, tokens)
            else:
                if self._stamp_sync:
                    stamp = getattr(self.cross_slice_sync,
                                    "set_step_token", None)
                    if stamp is not None:
                        stamp(self.global_step)
                    self._stamp_sync = False
                # Backward-overlap: a sync layer exposing start()
                # (CrossSliceAllReduce(overlap=True)) launches each
                # gradient bucket's allreduce INSIDE the grads span —
                # as its leaves' D2H copies land — so the wire hides
                # behind the backward pass, and the sync span shrinks
                # to waiting the last handles + scatter. With
                # per_layer=True the launches move INSIDE the jitted
                # backward itself (the gradient taps deliver each
                # layer's grads as XLA produces them), so the wire
                # rides under trainer.backward — the nested span that
                # splits the flight recorder's overlap_fraction into
                # compute-overlapped (inside backward) vs
                # staging-overlapped (inside grads, outside backward).
                overlap = (getattr(self.cross_slice_sync, "overlap",
                                   False)
                           and hasattr(self.cross_slice_sync, "start"))
                per_layer = self._per_layer
                pending = None
                with trace.span("trainer.grads", step=step_no):
                    if per_layer:
                        pending = self.cross_slice_sync.start_layered(
                            self.layer_plan)
                        self._pending_layers = pending
                        try:
                            with trace.span("trainer.backward",
                                            step=step_no):
                                loss, grads = self._jit_grads(
                                    self.params, tokens)
                                # The backward span must close only
                                # when the program (and so every tap
                                # delivery) actually finished — async
                                # dispatch would otherwise close it at
                                # submit time.
                                jax.block_until_ready(loss)
                        finally:
                            self._pending_layers = None
                    else:
                        with trace.span("trainer.backward",
                                        step=step_no):
                            loss, grads = self._jit_grads(self.params,
                                                          tokens)
                        if overlap:
                            pending = self.cross_slice_sync.start(grads)
                # The cross-slice hop: grads averaged across slices
                # over the RDMA transport (staged fallback accounts
                # its bytes), then applied locally.
                with trace.span("trainer.sync", step=step_no):
                    if per_layer:
                        grads = pending.finish(grads)
                    elif pending is not None:
                        grads = pending.finish()
                    else:
                        grads = self.cross_slice_sync(grads)
                # Quarantine check BEFORE apply: gradients that passed
                # the transport's integrity seal but came back
                # non-finite would poison params on apply — with the
                # elastic policy armed, surface them while the
                # pre-step state is still intact (step() retries once
                # in place, then escalates).
                if (self.elastic is not None
                        and self.elastic.quarantine_nonfinite
                        and not self._grads_finite(grads)):
                    raise _NonFiniteGrads(
                        f"all-reduced gradients contain non-finite "
                        f"values at step {step_no}")
                with trace.span("trainer.apply", step=step_no):
                    self.params, self.opt_state = self._jit_apply(
                        self.params, self.opt_state, grads)
        return float(loss)

    @staticmethod
    def _grads_finite(grads) -> bool:
        import numpy as np

        for leaf in jax.tree_util.tree_leaves(grads):
            try:
                if isinstance(leaf, np.ndarray):
                    ok = bool(np.all(np.isfinite(leaf)))
                else:
                    # Device leaf: reduce ON DEVICE and transfer one
                    # scalar — this runs every elastic step, so it
                    # must never copy the gradient itself to host.
                    ok = bool(jnp.all(jnp.isfinite(leaf)))
            except TypeError:
                continue  # non-float dtype with no isfinite: trivially ok
            if not ok:
                return False
        return True

    def _resume(self, exc: BaseException, attempt: int) -> None:
        """The detect→recover bridge: rebuild the transport world under
        a new generation, drop the sync layer's ring-bound caches, and
        restore the last checkpoint so the failed step re-runs from a
        consistent (params, opt_state, step) snapshot."""
        trace.event("trainer.resume", step=self.global_step + 1,
                    attempt=attempt, error=str(exc)[:160])
        world = getattr(self.cross_slice_sync, "world", None)
        if world is not None:
            old_size = getattr(world, "world", None)
            kw = dict(self.elastic.rebuild)
            kw.setdefault("reason", str(exc)[:400])
            world.rebuild(**kw)
            new_size = getattr(world, "world", None)
            if old_size is not None and new_size != old_size:
                # A world RESIZE rode the rebuild: the coordinator cut
                # a view at a different size (shrink-to-survivors or
                # grow-on-join). The data-parallel batch shard
                # rebalances by construction — every sync scales by
                # the CURRENT world size — but the change is a
                # training-semantics event (global batch moved), so it
                # is counted and stamped for the postmortem timeline.
                trace.add("trainer.resize", 1)
                trace.event("trainer.resize",
                            step=self.global_step + 1,
                            old_size=old_size, new_size=new_size)
        reset = getattr(self.cross_slice_sync, "reset_transport_cache", None)
        if reset is not None:
            reset()
        from rocnrdma_tpu.parallel.checkpoint import (checkpoint_file,
                                                      restore_checkpoint)

        entry_step = self.global_step
        path = self.elastic.checkpoint_path
        if os.path.exists(checkpoint_file(path)):
            restore_checkpoint(path, self)  # also sets self.global_step
        # else: failure before the first checkpoint — params/opt_state
        # are still the pre-step values (apply never ran), retry as-is.
        if self.global_step != entry_step:
            # The checkpoint rewound PAST the step being retried
            # (save_every > 1 with intervening uncheckpointed steps):
            # re-running only the current batch would silently skip
            # the rolled-back ones. step() cannot replay batches it
            # never saw — surface it and let the caller drive its data
            # loop from trainer.global_step.
            raise RuntimeError(
                f"elastic resume restored step {self.global_step} but "
                f"the failed step was {entry_step + 1}: the "
                f"intervening steps were never checkpointed "
                f"(save_every={self.elastic.save_every}); re-feed "
                "batches from trainer.global_step (or use "
                "save_every=1 for exact in-place replay)")
        # The retried sync re-proves step agreement across ranks.
        self._stamp_sync = True

    def step(self, tokens) -> float:
        """One optimizer step; returns the (pre-update) loss. With an
        ``elastic=`` policy, retryable transport failures mid-step
        trigger rebuild→restore→re-run (bounded by ``max_resumes``),
        and verified-but-non-finite gradients are quarantined: retried
        once in place from the pre-step state (apply never ran) before
        the elastic ladder engages. Successful steps checkpoint every
        ``save_every`` steps."""
        if self.elastic is None:
            loss = self._step_once(tokens)
        else:
            from rocnrdma_tpu.transport.engine import TransportError

            resumes = 0
            quarantined = False
            while True:
                try:
                    loss = self._step_once(tokens)
                    break
                except _NonFiniteGrads as e:
                    if not quarantined:
                        # First non-finite on this step: retry in
                        # place. Params/opt_state are the pre-step
                        # values (apply never ran), so the re-run
                        # recomputes and re-syncs the same batch.
                        quarantined = True
                        trace.event("trainer.quarantine",
                                    step=self.global_step + 1)
                        continue
                    # The retry was ALSO non-finite: escalate to the
                    # elastic path (rebuild → restore → re-run).
                    if resumes >= self.elastic.max_resumes:
                        raise TransportError(str(e), retryable=True)
                    resumes += 1
                    self._resume(e, resumes)
                    quarantined = False
                except TransportError as e:
                    if (not getattr(e, "retryable", False)
                            or resumes >= self.elastic.max_resumes):
                        raise
                    resumes += 1
                    self._resume(e, resumes)
                    quarantined = False
        self.global_step += 1
        if (self.elastic is not None and self.elastic.save_every > 0
                and self.global_step % self.elastic.save_every == 0):
            from rocnrdma_tpu.parallel.checkpoint import save_checkpoint

            save_checkpoint(self.elastic.checkpoint_path, self,
                            self.global_step)
        trace.event("trainer.step", loss=float(loss), step=self.global_step)
        return float(loss)
