"""Device mesh + sharding rules — the intra-slice parallelism story.

Per SURVEY.md §2's parallelism inventory, intra-slice parallelism is
delegated to XLA/pjit over ICI: we pick a mesh, annotate shardings
(data-parallel batch on ``dp``, tensor-parallel heads/ffn/vocab on
``tp``), and let XLA insert the collectives. The framework's own
transport only owns the cross-slice (DCN) hop — see
``parallel.trainer`` and ``collectives.jax_shim``.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(shape: Dict[str, int],
              devices: Optional[Sequence] = None) -> Mesh:
    """Mesh from axis-name → size, e.g. {"dp": 2, "tp": 4}."""
    devs = list(devices) if devices is not None else list(jax.devices())
    total = int(np.prod(list(shape.values())))
    if total > len(devs):
        raise ValueError(f"mesh {shape} needs {total} devices, "
                         f"have {len(devs)}")
    arr = np.array(devs[:total]).reshape(tuple(shape.values()))
    return Mesh(arr, tuple(shape.keys()))


def batch_spec() -> P:
    """Tokens (B, S): batch on dp."""
    return P("dp", None)


def param_spec(path: str) -> P:
    """Tensor-parallel partitioning for Llama params by param path.

    Column-parallel (shard the output features): wq/wk/wv, w_gate,
    w_up, lm_head. Row-parallel (shard the input features): wo,
    w_down. Embedding shards the vocab axis. Norms replicate. XLA
    derives the matching all-reduces from these placements.
    """
    if "embed" in path:
        return P("tp", None)
    if any(k in path for k in ("wq", "wk", "wv")):
        return P(None, "tp")
    if "wo" in path:
        return P("tp", None)
    if any(k in path for k in ("w_gate", "w_up")):
        return P(None, "tp")
    if "w_down" in path:
        return P("tp", None)
    if "lm_head" in path:
        return P(None, "tp")
    return P()  # norms and anything residual: replicated


def param_shardings(mesh: Mesh, params):
    """Pytree of NamedShardings matching param_spec by tree path."""

    def one(path_parts, leaf):
        path = "/".join(str(p) for p in path_parts)
        spec = param_spec(path)
        # Fall back to replication when a spec doesn't divide evenly
        # (tiny test configs with odd head counts).
        try:
            for axis_name, dim in zip(spec, range(leaf.ndim)):
                if axis_name is None:
                    continue
                if leaf.shape[dim] % mesh.shape[axis_name] != 0:
                    return NamedSharding(mesh, P())
        except Exception:
            return NamedSharding(mesh, P())
        return NamedSharding(mesh, spec)

    import jax.tree_util as jtu

    return jtu.tree_map_with_path(
        lambda kp, leaf: one([getattr(k, "key", getattr(k, "idx", k))
                              for k in kp], leaf),
        params)


def replicated(mesh: Mesh):
    return NamedSharding(mesh, P())
