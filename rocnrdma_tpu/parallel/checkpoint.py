"""Checkpoint/resume for the trainer.

The reference has no checkpointing (SURVEY.md §5: "absent — N/A for a
transport driver"); the training consumer this framework adds needs
it. Format: one ``.npz`` of path-flattened leaves (params + optimizer
state) plus metadata — dependency-free and stable across optax's
nested-tuple state structures. Restore is sharding-aware: leaves are
``device_put`` back onto the trainer's mesh placements, so a dp×tp
job resumes with placement intact.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Tuple

import jax
import numpy as np

from rocnrdma_tpu.utils.trace import trace

_FORMAT_VERSION = 1


def checkpoint_file(path: str) -> str:
    """The on-disk file a checkpoint ``path`` resolves to — the one
    normalization save/restore/existence checks must share."""
    return path if path.endswith(".npz") else path + ".npz"


def _flatten_with_paths(tree) -> List[Tuple[str, Any]]:
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for keypath, leaf in flat:
        path = "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                        for k in keypath)
        out.append((path, leaf))
    return out


def _extended_dtype(name: str):
    """Resolve ml_dtypes extended dtypes (bfloat16, fp8 families) that
    plain numpy can't name."""
    import ml_dtypes

    return np.dtype(getattr(ml_dtypes, name))


def _encode_leaf(arr: np.ndarray):
    """npz can't round-trip ml_dtypes leaves (they save as raw void and
    refuse to cast back); store them bit-exact as unsigned ints plus a
    dtype tag."""
    try:
        builtin = np.dtype(arr.dtype.char) == arr.dtype and \
            arr.dtype.kind != "V"
    except TypeError:
        builtin = False
    if not builtin:
        width = {1: np.uint8, 2: np.uint16, 4: np.uint32}[arr.dtype.itemsize]
        return arr.view(width), arr.dtype.name
    return arr, None


def save_checkpoint(path: str, trainer, step: int) -> None:
    """Write params + optimizer state + step to ``path`` (.npz)."""
    arrays: Dict[str, np.ndarray] = {}
    for prefix, tree in (("params", trainer.params),
                         ("opt", trainer.opt_state)):
        for leaf_path, leaf in _flatten_with_paths(tree):
            enc, tag = _encode_leaf(np.asarray(leaf))
            key = f"{prefix}/{leaf_path}"
            arrays[key] = enc
            if tag is not None:
                arrays[f"__dtype__/{key}"] = np.frombuffer(
                    tag.encode(), dtype=np.uint8)
    arrays["__meta__/step"] = np.asarray(step, dtype=np.int64)
    arrays["__meta__/config"] = np.frombuffer(
        trainer.cfg.name.encode(), dtype=np.uint8)
    arrays["__meta__/version"] = np.asarray(_FORMAT_VERSION)
    path = checkpoint_file(path)
    tmp = path + ".tmp.npz"
    np.savez(tmp, **arrays)
    os.replace(tmp, path)  # atomic publish — no torn checkpoints
    trace.event("ckpt.save", path=path, step=step)


def restore_checkpoint(path: str, trainer) -> int:
    """Restore in place onto the trainer's shardings; returns step."""
    path = checkpoint_file(path)
    with np.load(path) as z:
        cfg_name = bytes(z["__meta__/config"]).decode()
        if cfg_name != trainer.cfg.name:
            raise ValueError(
                f"checkpoint is for config {cfg_name!r}, trainer is "
                f"{trainer.cfg.name!r}")
        step = int(z["__meta__/step"])

        def rebuild(prefix: str, template):
            flat = _flatten_with_paths(template)
            leaves = []
            for leaf_path, old_leaf in flat:
                key = f"{prefix}/{leaf_path}"
                if key not in z:
                    raise ValueError(f"checkpoint missing leaf {key}")
                arr = z[key]
                tag_key = f"__dtype__/{key}"
                if tag_key in z:
                    arr = arr.view(_extended_dtype(
                        bytes(z[tag_key]).decode()))
                if hasattr(old_leaf, "sharding"):
                    arr = jax.device_put(
                        arr.astype(old_leaf.dtype), old_leaf.sharding)
                leaves.append(arr)
            treedef = jax.tree_util.tree_structure(template)
            return jax.tree_util.tree_unflatten(treedef, leaves)

        trainer.params = rebuild("params", trainer.params)
        trainer.opt_state = rebuild("opt", trainer.opt_state)
    if hasattr(trainer, "global_step"):
        # Keep the trainer's step counter (the elastic policy's
        # checkpoint cadence and resume point) in sync with the
        # restored state.
        trainer.global_step = step
    trace.event("ckpt.restore", path=path, step=step)
    return step
