"""Sequence-parallel training — ring attention wired into the trainer.

Long-context training where the SEQUENCE is the partitioned axis: each
rank (slice) holds a contiguous token shard of every batch row, and
attention reaches the rest of the sequence through the transport-
rotated K/V ring (``collectives/ring_attention.py``) — the SURVEY §5
"L5 consumer" role: the model consumes the RDMA fabric the way the
reference's MPI apps consumed its peer-mapped buffers
(/root/reference/README.md:62-69).

Architecture: the transformer block exposes its attention-split halves
(``Block.qkv`` / ``Block.post``, models/llama.py) — everything except
the attention contraction is position-local, so those halves run as
ordinary jitted computations on the local shard, while the contraction
itself runs as the host-orchestrated ring: per layer,

    x ─jit→ qkv ─(ring: rotate K/V, merge by global lse)→ out ─jit→ post

The step's backward is stitched from the same pieces, exactly: each
jitted half contributes its ``jax.vjp`` pullback, and the attention
middle uses :meth:`RingAttention.backward`, whose global-lse pair
gradients + homecoming accumulator are parity-tested against the full
``jax.vjp`` (tests/test_ring_attention.py). Parameter gradients then
average across ranks over the SAME transport (``CrossSliceAllReduce``),
because every rank's tokens contribute to every rank's dK/dV: with
L = (1/W)·Σ_r ℓ_r and each rank seeding its backward with dℓ_r/dout_r,
the mean-allreduce of per-rank parameter grads is algebraically
dL/dθ (each rank's local chains carry Σ_j ∂ℓ_j/∂θ|through-rank-r).

Attention strategy (``sp_mode``): "ring" (default) rotates K/V
through :class:`RingAttention`; "ulysses" reshards heads<->sequence
through :class:`~rocnrdma_tpu.collectives.ulysses.UlyssesAttention`
(two all-to-alls per layer-call instead of W-1 rotations; requires
head counts divisible by the world). Both produce exact gradients —
the training parity tests run the same contract over each.

Replication contract: parameters and optimizer state are identical on
every rank (same init seed, same averaged gradients, same update
math), so ranks stay bit-synchronized without a parameter server.

Activation rematerialization: with ``remat=True`` (the production
setting at sizes that matter, same flag as the DP trainer) the jitted
halves are ``jax.checkpoint``-ed, so each layer's pullback residual
shrinks to the half's inputs and the internals recompute during the
backward sweep. Not yet done here: intra-rank tensor parallelism —
the seq axis composes with the jit-internal dp/tp mesh of
``parallel/trainer.py`` in the usual grid fashion but this runner
drives one device per rank.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from rocnrdma_tpu.collectives.jax_shim import CrossSliceAllReduce
from rocnrdma_tpu.collectives.ring_attention import RingAttention
from rocnrdma_tpu.collectives.world import RingWorld
from rocnrdma_tpu.models.llama import (
    Block, LlamaConfig, RMSNorm, cross_entropy_loss, make_model,
    rope_freqs)
from rocnrdma_tpu.utils.trace import trace


class SeqParallelTrainer:
    """Trains a Llama model with the sequence sharded across a
    :class:`RingWorld` — ``Trainer(config, seq_parallel=world)`` is the
    front-door spelling.

    ``step(inputs, targets)`` takes this rank's contiguous
    (B, S_local) token shard (inputs and next-token targets already
    split by the caller, the same split on every rank) and returns the
    GLOBAL mean loss. All ranks must call ``step`` collectively.
    """

    def __init__(self, config: "LlamaConfig | str", world: RingWorld,
                 learning_rate: float = 3e-4, weight_decay: float = 0.1,
                 seed: int = 0, interpret: Optional[bool] = None,
                 optimizer=None, sp_mode: str = "ring",
                 **model_overrides):
        if sp_mode not in ("ring", "ulysses"):
            raise ValueError(
                f"sp_mode={sp_mode!r}: must be 'ring' or 'ulysses'")
        self.sp_mode = sp_mode
        self.model = make_model(config, **model_overrides)
        self.cfg = cfg = self.model.cfg
        self.world = world
        if sp_mode == "ulysses":
            # Ulysses scatters the HEAD axis; both head counts must
            # divide the world (checked here so every rank fails fast
            # at construction, not mid-ring).
            for what, n in (("n_heads", cfg.n_heads),
                            ("n_kv_heads", cfg.n_kv_heads)):
                if n % world.world != 0:
                    raise ValueError(
                        f"sp_mode='ulysses': {what}={n} must divide by "
                        f"world={world.world} (use sp_mode='ring' for "
                        "head counts the world does not divide)")
        # cfg.remat (the production setting for sizes that matter):
        # wrap the jitted block halves in jax.checkpoint, so each
        # layer's vjp residual shrinks to the half's INPUTS — the
        # internal activations (rmsnorm intermediates, pre-rope q/k,
        # the MLP's d_ff-wide hidden) are recomputed during the
        # pullback instead of held across the whole backward sweep.
        self._remat = bool(cfg.remat)
        if interpret is None:
            interpret = cfg.pallas_interpret
        if sp_mode == "ulysses":
            from rocnrdma_tpu.collectives.ulysses import UlyssesAttention
            self.attn = UlyssesAttention(world, interpret=interpret)
        else:
            self.attn = RingAttention(world, interpret=interpret)
        self._xs = CrossSliceAllReduce(world, mean=True)
        # ``optimizer``: any optax GradientTransformation; the default
        # matches the DP trainer. (The parity tests inject plain SGD —
        # adaptive optimizers amplify fp-reordering-scale gradient
        # differences through the 1/(sqrt(v)+eps) normalization, which
        # makes bit-level param comparison meaningless, not wrong.)
        self.tx = optimizer if optimizer is not None else optax.adamw(
            learning_rate, weight_decay=weight_decay)

        # Identical params on every rank: same seed, same init graph.
        self.params = self.model.init(
            jax.random.PRNGKey(seed), jnp.zeros((1, 8), dtype=jnp.int32))
        self.opt_state = self.tx.init(self.params)

        # Jitted local segments. One compile each (shapes repeat across
        # layers and steps); the block instance is shared so every
        # layer reuses the same executables with its own param subtree.
        block = Block(cfg)
        embed = nn.Embed(cfg.vocab_size, cfg.d_model, dtype=cfg.dtype,
                         param_dtype=cfg.dtype)
        norm = RMSNorm(cfg)
        head = nn.Dense(cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                        param_dtype=cfg.dtype)
        self._embed = jax.jit(
            lambda ep, t: embed.apply({"params": ep}, t))
        self._qkv = jax.jit(
            lambda lp, x, fr: block.apply({"params": lp}, x, fr,
                                          method=Block.qkv))
        self._post = jax.jit(
            lambda lp, x, o: block.apply({"params": lp}, x, o,
                                         method=Block.post))

        def logits_fn(fp, hp, x):
            xn = norm.apply({"params": fp}, x)
            return head.apply({"params": hp}, xn).astype(jnp.float32)

        self._logits = jax.jit(logits_fn)
        self._head_loss = jax.jit(
            lambda fp, hp, x, targets: cross_entropy_loss(
                logits_fn(fp, hp, x), targets))
        self._apply = jax.jit(
            lambda g, o, p: self.tx.update(g, o, p))
        self._freqs = rope_freqs(cfg.head_dim, cfg.max_seq_len,
                                 cfg.rope_theta)

    # Attention-strategy adapter: both long-context strategies take the
    # same sequence-sharded (q, k, v) and produce this rank's out/grads;
    # ring carries an (out, lse) residual into backward, ulysses
    # rematerializes and needs none.
    def _attn_forward(self, q, k, v):
        if self.sp_mode == "ulysses":
            return self.attn.forward(q, k, v, causal=True), None
        return self.attn.forward(q, k, v, causal=True)

    def _attn_backward(self, q, k, v, out, lse, dout):
        if self.sp_mode == "ulysses":
            return self.attn.backward(q, k, v, dout, causal=True)
        return self.attn.backward(q, k, v, out, lse, dout, causal=True)

    # --------------------------------------------------------- forward

    def _freqs_shard(self, s_local: int):
        off = self.world.rank * s_local
        # Checked against the GLOBAL length so every rank raises (an
        # off+s_local check fires only on the last ranks, leaving the
        # rest to stall in the ring until the transport timeout).
        if self.world.world * s_local > self.cfg.max_seq_len:
            raise ValueError(
                f"global sequence {self.world.world * s_local} exceeds "
                f"max_seq_len={self.cfg.max_seq_len}")
        return jax.lax.dynamic_slice_in_dim(self._freqs, off, s_local)

    def forward(self, params, inputs):
        """Logits for this rank's shard (no loss) — the inference
        spelling of the seq-parallel forward, used by the parity
        tests."""
        p = params["params"]
        fr = self._freqs_shard(inputs.shape[1])
        x = self._embed(p["embed"], inputs)
        for i in range(self.cfg.n_layers):
            lp = p[f"layer_{i}"]
            q, k, v = self._qkv(lp, x, fr)
            out, _ = self._attn_forward(q, k, v)
            x = self._post(lp, x, out)
        return self._logits(p["final_norm"], p["lm_head"], x)

    # ------------------------------------------------ forward+backward

    def forward_backward(self, params, inputs, targets):
        """(local_loss, grads): exact gradients of this rank's local
        mean loss chains — see the module docstring for why the
        mean-allreduce of these across ranks is the global-loss
        gradient. Residual memory is one pullback per layer — inputs
        only under remat, full half-internals otherwise."""
        p = params["params"]
        fr = self._freqs_shard(inputs.shape[1])
        x, pull_embed = jax.vjp(
            lambda ep: self._embed(ep, inputs), p["embed"])
        # Under remat, differentiate through checkpointed halves: the
        # pullback then holds only the half's inputs and re-runs its
        # forward on demand (jit'd on first use, cached thereafter).
        if self._remat:
            qkv_fn = jax.checkpoint(
                lambda lp_, x_, fr_: self._qkv(lp_, x_, fr_))
            post_fn = jax.checkpoint(
                lambda lp_, x_, o_: self._post(lp_, x_, o_))
        else:
            qkv_fn = lambda lp_, x_, fr_: self._qkv(lp_, x_, fr_)
            post_fn = lambda lp_, x_, o_: self._post(lp_, x_, o_)
        pulls = []
        residuals = []
        for i in range(self.cfg.n_layers):
            lp = p[f"layer_{i}"]
            (q, k, v), pull_qkv = jax.vjp(
                lambda lp_, x_: qkv_fn(lp_, x_, fr), lp, x)
            out, lse = self._attn_forward(q, k, v)
            x, pull_post = jax.vjp(post_fn, lp, x, out)
            pulls.append((pull_qkv, pull_post))
            residuals.append((q, k, v, out, lse))
        loss, pull_head = jax.vjp(
            lambda fp, hp, x_: self._head_loss(fp, hp, x_, targets),
            p["final_norm"], p["lm_head"], x)

        g_final, g_head, dx = pull_head(jnp.ones((), jnp.float32))
        grads = {"final_norm": g_final, "lm_head": g_head}
        add = lambda a, b: jax.tree_util.tree_map(jnp.add, a, b)
        for i in reversed(range(self.cfg.n_layers)):
            pull_qkv, pull_post = pulls[i]
            q, k, v, out, lse = residuals[i]
            g_post, dx, dout = pull_post(dx)
            dq, dk, dv = self._attn_backward(q, k, v, out, lse, dout)
            g_qkv, dx2 = pull_qkv((dq, dk, dv))
            dx = add(dx, dx2)
            grads[f"layer_{i}"] = add(g_post, g_qkv)
        (grads["embed"],) = pull_embed(dx)
        return loss, {"params": grads}

    # ------------------------------------------------------------ step

    def step(self, inputs, targets) -> float:
        """One collective optimizer step on this rank's shard; returns
        the global mean loss. Parameter gradients average across ranks
        over the transport (the same ring the K/V rotation used)."""
        inputs = jnp.asarray(inputs)
        targets = jnp.asarray(targets)
        loss, grads = self.forward_backward(self.params, inputs, targets)
        grads = self._xs(grads)
        updates, self.opt_state = self._apply(
            grads, self.opt_state, self.params)
        self.params = optax.apply_updates(self.params, updates)
        # Global loss: mean of the per-rank local means (equal shards).
        box = np.array([float(loss)], dtype=np.float64)
        self.world.allreduce(box)
        gloss = float(box[0]) / self.world.world
        trace.event("seq_parallel.step", rank=self.world.rank,
                    world=self.world.world, loss=gloss)
        return gloss

    def close(self) -> None:
        self.attn.close()
        self._xs.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
