"""Paged numpy Llama decode — the serving-side model consumer.

The training model (:mod:`rocnrdma_tpu.models.llama`) is flax/jax; the
serving decode path is a faithful **numpy port of the same math**
operating on flat f32 weight *pages* — one page per transformer layer
plus an embedding page and a head page — because pages are what the
streaming pager delivers. Keeping the hot loop in numpy does two
things: the -san smoke can run it with no jaxlib in the process (the
MLIR pybind trips ASan's ``__cxa_throw`` interceptor), and every
matmul releases the GIL so the ring's async driver streams page k+1
underneath layer k's compute — the overlap the subsystem exists to
produce, measurable on a 1-core host.

Math parity: RMSNorm, split-half RoPE, GQA with f32 accumulation,
stable softmax, SwiGLU, f32 logits — mirroring the flax modules
line-for-line. Greedy tokens match ``llama.generate(temperature=0)``
(asserted in tests); the bitwise contract the smoke pins is
streamed-pages vs local-pages on THIS port, where identity is
structural (the wire moves exact bytes).

This module never imports jax. ``pack_llama_params`` accepts the
*already materialized* numpy param tree (the caller device_gets it),
so full mode and LITE mode share every line below the packing seam.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .pager import PageSet

__all__ = [
    "ServeConfig", "page_names", "pack_pages", "pack_llama_params",
    "toy_param_tree", "unpack_embed", "unpack_layer", "unpack_head",
    "PagedDecoder", "JitPagedDecoder",
]


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """The architecture facts decode needs — a jax-free mirror of
    ``LlamaConfig`` (constructible from one via :meth:`from_llama`)."""

    vocab_size: int
    d_model: int
    n_layers: int
    n_heads: int
    n_kv_heads: int
    d_ff: int
    max_seq_len: int = 128
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @classmethod
    def from_llama(cls, cfg: Any) -> "ServeConfig":
        return cls(vocab_size=cfg.vocab_size, d_model=cfg.d_model,
                   n_layers=cfg.n_layers, n_heads=cfg.n_heads,
                   n_kv_heads=cfg.n_kv_heads, d_ff=cfg.d_ff,
                   max_seq_len=cfg.max_seq_len,
                   rope_theta=cfg.rope_theta, norm_eps=cfg.norm_eps)


# ------------------------------------------------------------- page layout
#
# Page k of a ServeConfig model:
#   page 0                 : embedding        [vocab, d_model]
#   page 1 .. n_layers     : one layer each   [attn_norm | wq | wk | wv |
#                                              wo | mlp_norm | w_gate |
#                                              w_up | w_down], flat f32
#   page n_layers + 1      : head             [final_norm | lm_head]
#
# The layout is a pure function of the config — every rank derives the
# identical page sizes (the pager's SPMD schedule needs nothing else).

def _layer_fields(cfg: ServeConfig) -> List[Tuple[str, Tuple[int, ...]]]:
    d, hd = cfg.d_model, cfg.head_dim
    return [
        ("attn_norm", (d,)),
        ("wq", (d, cfg.n_heads * hd)),
        ("wk", (d, cfg.n_kv_heads * hd)),
        ("wv", (d, cfg.n_kv_heads * hd)),
        ("wo", (cfg.n_heads * hd, d)),
        ("mlp_norm", (d,)),
        ("w_gate", (d, cfg.d_ff)),
        ("w_up", (d, cfg.d_ff)),
        ("w_down", (cfg.d_ff, d)),
    ]


def page_names(cfg: ServeConfig) -> List[str]:
    return (["embed"] + [f"layer_{i}" for i in range(cfg.n_layers)]
            + ["head"])


def _pack(fields: Sequence[Tuple[str, Tuple[int, ...]]],
          tensors: Dict[str, np.ndarray]) -> np.ndarray:
    parts = []
    for name, shape in fields:
        t = np.ascontiguousarray(tensors[name], dtype=np.float32)
        if tuple(t.shape) != tuple(shape):
            raise ValueError(f"{name}: shape {t.shape} != {shape}")
        parts.append(t.reshape(-1))
    return np.concatenate(parts) if parts else np.zeros(0, np.float32)


def _unpack(fields: Sequence[Tuple[str, Tuple[int, ...]]],
            page: np.ndarray) -> Dict[str, np.ndarray]:
    out: Dict[str, np.ndarray] = {}
    off = 0
    for name, shape in fields:
        n = int(np.prod(shape))
        out[name] = page[off:off + n].reshape(shape)
        off += n
    return out


def pack_pages(cfg: ServeConfig, tree: Dict[str, Any]) -> PageSet:
    """``tree`` is the nested numpy param dict (flax naming, see
    :func:`pack_llama_params` / :func:`toy_param_tree`)."""
    pages = [_pack([("embed", (cfg.vocab_size, cfg.d_model))],
                   {"embed": tree["embed"]})]
    for i in range(cfg.n_layers):
        pages.append(_pack(_layer_fields(cfg), tree[f"layer_{i}"]))
    pages.append(_pack(
        [("final_norm", (cfg.d_model,)),
         ("lm_head", (cfg.d_model, cfg.vocab_size))],
        {"final_norm": tree["final_norm"], "lm_head": tree["lm_head"]}))
    return PageSet(pages, page_names(cfg))


def unpack_embed(cfg: ServeConfig, page: np.ndarray) -> np.ndarray:
    return page[:cfg.vocab_size * cfg.d_model].reshape(
        cfg.vocab_size, cfg.d_model)


def unpack_layer(cfg: ServeConfig, page: np.ndarray) -> Dict[str, np.ndarray]:
    return _unpack(_layer_fields(cfg), page)


def unpack_head(cfg: ServeConfig, page: np.ndarray
                ) -> Tuple[np.ndarray, np.ndarray]:
    d = cfg.d_model
    return (page[:d],
            page[d:d + d * cfg.vocab_size].reshape(d, cfg.vocab_size))


def pack_llama_params(cfg: ServeConfig, params: Dict[str, Any]) -> PageSet:
    """Flatten a (materialized-to-numpy) flax ``init_params`` tree into
    pages. ``params`` is the ``{"params": {...}}`` tree with numpy (or
    numpy-convertible) leaves — the caller device_gets; this module
    stays jax-free."""
    p = params["params"] if "params" in params else params
    tree: Dict[str, Any] = {
        "embed": np.asarray(p["embed"]["embedding"]),
        "final_norm": np.asarray(p["final_norm"]["weight"]),
        "lm_head": np.asarray(p["lm_head"]["kernel"]),
    }
    for i in range(cfg.n_layers):
        lp = p[f"layer_{i}"]
        tree[f"layer_{i}"] = {
            "attn_norm": np.asarray(lp["attn_norm"]["weight"]),
            "wq": np.asarray(lp["attn"]["wq"]["kernel"]),
            "wk": np.asarray(lp["attn"]["wk"]["kernel"]),
            "wv": np.asarray(lp["attn"]["wv"]["kernel"]),
            "wo": np.asarray(lp["attn"]["wo"]["kernel"]),
            "mlp_norm": np.asarray(lp["mlp_norm"]["weight"]),
            "w_gate": np.asarray(lp["mlp"]["w_gate"]["kernel"]),
            "w_up": np.asarray(lp["mlp"]["w_up"]["kernel"]),
            "w_down": np.asarray(lp["mlp"]["w_down"]["kernel"]),
        }
    return pack_pages(cfg, tree)


def toy_param_tree(cfg: ServeConfig, seed: int = 7) -> Dict[str, Any]:
    """Deterministic small random params (numpy RNG — identical on
    every rank for a given seed): the LITE/-san path and the unit
    tests, no jax in the process."""
    rng = np.random.default_rng(seed)

    def w(*shape):
        scale = 1.0 / np.sqrt(shape[0])
        return (rng.standard_normal(shape) * scale).astype(np.float32)

    tree: Dict[str, Any] = {
        "embed": w(cfg.vocab_size, cfg.d_model),
        "final_norm": np.ones(cfg.d_model, np.float32),
        "lm_head": w(cfg.d_model, cfg.vocab_size),
    }
    for i in range(cfg.n_layers):
        tree[f"layer_{i}"] = {
            "attn_norm": np.ones(cfg.d_model, np.float32),
            "wq": w(cfg.d_model, cfg.n_heads * cfg.head_dim),
            "wk": w(cfg.d_model, cfg.n_kv_heads * cfg.head_dim),
            "wv": w(cfg.d_model, cfg.n_kv_heads * cfg.head_dim),
            "wo": w(cfg.n_heads * cfg.head_dim, cfg.d_model),
            "mlp_norm": np.ones(cfg.d_model, np.float32),
            "w_gate": w(cfg.d_model, cfg.d_ff),
            "w_up": w(cfg.d_model, cfg.d_ff),
            "w_down": w(cfg.d_ff, cfg.d_model),
        }
    return tree


# ---------------------------------------------------------------- decoder

def _rmsnorm(x: np.ndarray, w: np.ndarray, eps: float) -> np.ndarray:
    ms = np.mean(x * x, axis=-1, keepdims=True)
    return x * (1.0 / np.sqrt(ms + eps)) * w


def _silu(x: np.ndarray) -> np.ndarray:
    return x * (1.0 / (1.0 + np.exp(-x)))


def _softmax(x: np.ndarray) -> np.ndarray:
    m = np.max(x, axis=-1, keepdims=True)
    e = np.exp(x - m)
    return e / np.sum(e, axis=-1, keepdims=True)


class PagedDecoder:
    """Stateless per-page math; the batcher owns page acquisition and
    per-request KV caches, this class owns the numbers.

    KV caches are per-request arrays of shape
    ``(n_kv_heads, max_seq_len, head_dim)`` f32 (``new_cache()``)."""

    def __init__(self, cfg: ServeConfig) -> None:
        self.cfg = cfg
        hd = cfg.head_dim
        inv = 1.0 / (cfg.rope_theta ** (
            np.arange(0, hd, 2, dtype=np.float32) / hd))
        t = np.arange(cfg.max_seq_len, dtype=np.float32)
        freqs = np.outer(t, inv)                    # (S, hd/2)
        self._cos = np.cos(freqs)
        self._sin = np.sin(freqs)

    def new_cache(self) -> Dict[str, np.ndarray]:
        cfg = self.cfg
        shape = (cfg.n_kv_heads, cfg.max_seq_len, cfg.head_dim)
        return {"k": np.zeros(shape, np.float32),
                "v": np.zeros(shape, np.float32)}

    def _rope(self, x: np.ndarray, pos: int) -> np.ndarray:
        # x: (H, s, hd) — split-half rotation, f32 throughout.
        s = x.shape[1]
        cos = self._cos[pos:pos + s][None]          # (1, s, hd/2)
        sin = self._sin[pos:pos + s][None]
        half = x.shape[-1] // 2
        x1, x2 = x[..., :half], x[..., half:]
        return np.concatenate(
            [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)

    def embed(self, embed_page: np.ndarray, tokens: np.ndarray
              ) -> np.ndarray:
        emb = unpack_embed(self.cfg, embed_page)
        return emb[np.asarray(tokens, dtype=np.int64)]   # (s, D)

    def layer(self, layer_page: np.ndarray, x: np.ndarray,
              cache: Dict[str, np.ndarray], pos: int) -> np.ndarray:
        """One transformer block over ``x`` (s, D) at absolute
        position ``pos``, writing K/V into ``cache`` — the flax
        decode branch, in numpy."""
        cfg = self.cfg
        w = unpack_layer(cfg, layer_page)
        s = x.shape[0]
        hd = cfg.head_dim

        h = _rmsnorm(x, w["attn_norm"], cfg.norm_eps)
        q = (h @ w["wq"]).reshape(s, cfg.n_heads, hd).transpose(1, 0, 2)
        k = (h @ w["wk"]).reshape(s, cfg.n_kv_heads, hd).transpose(1, 0, 2)
        v = (h @ w["wv"]).reshape(s, cfg.n_kv_heads, hd).transpose(1, 0, 2)
        q = self._rope(q, pos)
        k = self._rope(k, pos)
        cache["k"][:, pos:pos + s] = k
        cache["v"][:, pos:pos + s] = v
        k_all, v_all = cache["k"], cache["v"]

        rep = cfg.n_heads // cfg.n_kv_heads
        qg = q.reshape(cfg.n_kv_heads, rep, s, hd)
        scores = np.einsum("grqd,gkd->grqk", qg, k_all) / np.sqrt(
            np.float32(hd))
        q_pos = pos + np.arange(s)
        visible = np.arange(cfg.max_seq_len)[None, :] <= q_pos[:, None]
        scores = np.where(visible[None, None], scores, -np.inf)
        probs = _softmax(scores)
        o = np.einsum("grqk,gkd->grqd", probs, v_all)
        o = o.reshape(cfg.n_heads, s, hd).transpose(1, 0, 2).reshape(
            s, cfg.n_heads * hd)
        x = x + o @ w["wo"]

        h = _rmsnorm(x, w["mlp_norm"], cfg.norm_eps)
        x = x + (_silu(h @ w["w_gate"]) * (h @ w["w_up"])) @ w["w_down"]
        return x

    def head(self, head_page: np.ndarray, x: np.ndarray) -> np.ndarray:
        """Final norm + lm_head → f32 logits (s, vocab)."""
        fn, lm = unpack_head(self.cfg, head_page)
        return _rmsnorm(x, fn, self.cfg.norm_eps) @ lm

    # KV seam: the batcher's join streaming reads/writes per-request
    # caches through these two methods only, so a decoder subclass may
    # hold caches in a different container (jax arrays, below) without
    # the batcher knowing.

    def dump_kv(self, cache: Dict[str, np.ndarray], p: int) -> np.ndarray:
        """Flatten the first ``p`` positions of K then V (the KV-join
        wire payload). Works on any array type with ``__array__``."""
        return np.concatenate([np.asarray(cache["k"][:, :p]).ravel(),
                               np.asarray(cache["v"][:, :p]).ravel()])

    def load_kv(self, cache: Dict[str, np.ndarray], k: np.ndarray,
                v: np.ndarray, p: int) -> None:
        """Write received prefill K/V into the first ``p`` positions."""
        cache["k"][:, :p] = k
        cache["v"][:, :p] = v


class JitPagedDecoder(PagedDecoder):
    """Opt-in jax-jitted paged decode (ROADMAP item 2 residual (b)).

    Same page layout, same math as the numpy decoder — the layer step
    is one ``jax.jit`` call with the per-request K/V cache buffers
    DONATED (``donate_argnums``): XLA reuses the cache storage for the
    updated cache output instead of allocating a fresh
    ``(n_kv_heads, max_seq_len, head_dim)`` pair per layer per token,
    which is what closes the gap to ``models/llama.py``'s scan decode.
    ``layer()`` rebinds ``cache["k"]/["v"]`` to the donated outputs, so
    the batcher's cache-dict contract is unchanged.

    jax is imported INSIDE ``__init__`` — the module stays importable
    with no jaxlib in the process (the -san/LITE contract at the top
    of this file), and only this class pays the dependency. ``pos``
    rides as a traced scalar (``dynamic_slice``/``dynamic_update_slice``
    under the mask), so the jit caches exactly one executable per
    sequence length (prefill s, then s=1), not one per position.
    Greedy tokens match the numpy port (asserted in the serve smoke);
    logits may differ in final-ulp summation order, which greedy
    argmax on real models does not observe."""

    def __init__(self, cfg: ServeConfig) -> None:
        super().__init__(cfg)
        import jax
        import jax.numpy as jnp

        self._jnp = jnp
        cos = jnp.asarray(self._cos)
        sin = jnp.asarray(self._sin)
        eps = cfg.norm_eps
        hd = cfg.head_dim
        rep = cfg.n_heads // cfg.n_kv_heads

        def rms(x, w):
            ms = jnp.mean(x * x, axis=-1, keepdims=True)
            return x * (1.0 / jnp.sqrt(ms + eps)) * w

        def rope(x, pos, s):
            c = jax.lax.dynamic_slice_in_dim(cos, pos, s, axis=0)[None]
            sn = jax.lax.dynamic_slice_in_dim(sin, pos, s, axis=0)[None]
            half = x.shape[-1] // 2
            x1, x2 = x[..., :half], x[..., half:]
            return jnp.concatenate(
                [x1 * c - x2 * sn, x1 * sn + x2 * c], axis=-1)

        def embed_fn(page, tokens):
            return unpack_embed(cfg, page)[tokens]

        def layer_fn(page, x, k_cache, v_cache, pos):
            w = unpack_layer(cfg, page)
            s = x.shape[0]
            h = rms(x, w["attn_norm"])
            q = (h @ w["wq"]).reshape(
                s, cfg.n_heads, hd).transpose(1, 0, 2)
            k = (h @ w["wk"]).reshape(
                s, cfg.n_kv_heads, hd).transpose(1, 0, 2)
            v = (h @ w["wv"]).reshape(
                s, cfg.n_kv_heads, hd).transpose(1, 0, 2)
            q = rope(q, pos, s)
            k = rope(k, pos, s)
            k_cache = jax.lax.dynamic_update_slice_in_dim(
                k_cache, k, pos, axis=1)
            v_cache = jax.lax.dynamic_update_slice_in_dim(
                v_cache, v, pos, axis=1)
            qg = q.reshape(cfg.n_kv_heads, rep, s, hd)
            scores = jnp.einsum("grqd,gkd->grqk", qg,
                                k_cache) / jnp.sqrt(jnp.float32(hd))
            q_pos = pos + jnp.arange(s)
            visible = (jnp.arange(cfg.max_seq_len)[None, :]
                       <= q_pos[:, None])
            scores = jnp.where(visible[None, None], scores, -jnp.inf)
            m = jnp.max(scores, axis=-1, keepdims=True)
            e = jnp.exp(scores - m)
            probs = e / jnp.sum(e, axis=-1, keepdims=True)
            o = jnp.einsum("grqk,gkd->grqd", probs, v_cache)
            o = o.reshape(cfg.n_heads, s, hd).transpose(
                1, 0, 2).reshape(s, cfg.n_heads * hd)
            x = x + o @ w["wo"]
            h = rms(x, w["mlp_norm"])
            g = h @ w["w_gate"]
            x = x + ((g * (1.0 / (1.0 + jnp.exp(-g))))
                     * (h @ w["w_up"])) @ w["w_down"]
            return x, k_cache, v_cache

        def head_fn(page, x):
            fn, lm = unpack_head(cfg, page)
            return rms(x, fn) @ lm

        self._embed_jit = jax.jit(embed_fn)
        self._layer_jit = jax.jit(layer_fn, donate_argnums=(2, 3))
        self._head_jit = jax.jit(head_fn)

    def new_cache(self) -> Dict[str, Any]:
        jnp = self._jnp
        cfg = self.cfg
        shape = (cfg.n_kv_heads, cfg.max_seq_len, cfg.head_dim)
        return {"k": jnp.zeros(shape, jnp.float32),
                "v": jnp.zeros(shape, jnp.float32)}

    def embed(self, embed_page: np.ndarray, tokens: np.ndarray):
        return self._embed_jit(embed_page,
                               np.asarray(tokens, dtype=np.int32))

    def layer(self, layer_page: np.ndarray, x, cache: Dict[str, Any],
              pos: int):
        x, cache["k"], cache["v"] = self._layer_jit(
            layer_page, x, cache["k"], cache["v"], pos)
        return x

    def head(self, head_page: np.ndarray, x) -> np.ndarray:
        return np.asarray(self._head_jit(head_page, x))

    def load_kv(self, cache: Dict[str, Any], k: np.ndarray,
                v: np.ndarray, p: int) -> None:
        jnp = self._jnp
        cache["k"] = cache["k"].at[:, :p].set(jnp.asarray(k))
        cache["v"] = cache["v"].at[:, :p].set(jnp.asarray(v))
