"""Generic streaming transfer engine: credit-gated producer/consumer
channels over the zero-copy collective path.

This is the staged-pipeline + copy-pool machinery from
``collectives/jax_shim.py`` extracted into its own subsystem (ROADMAP
item 2, after "The DMA Streaming Framework"'s buffer-orchestration
model): a transfer is an explicit *produce* step (fill a registered
scratch window), a *launch* step (submit a nonblocking collective or a
worker-pool future), and a *consume* step (read the landed bytes), with
**credit-based depth** bounding how many transfers are in flight — or
pinned in scratch — at once. The trainer's bucketed overlap sync and
the serving weight/KV pager are both clients of the same engine, so the
submission-order contract from the async driver (ops complete in the
order submitted; results bitwise the blocking calls') holds for both.

Depth comes from ``TDR_STREAM_DEPTH`` (default 3 — the historical
staged-pipeline depth). ``depth=0`` means unbounded: credits are still
accounted (``in_flight``/``high_water``) but never block, which is what
the trainer's bucketed launch wants (its natural bound is the bucket
plan; the census still proves no handle leaks).

The engine spawns **no threads**: launches ride the ring's existing
async driver or a caller-owned executor, so the flat-thread-census
invariant the smokes pin is free.

Serving collective ids
----------------------

FEAT_COLL_ID carries 8 bytes on the wire. Serving streams stamp a
structured id so ``tdr_explain`` can decompose decode streams per
request: bit 62 set (bit 63 — the ring's auto-assign marker — clear)
marks a serving-stream collective; bits 40..61 hold the request id
(0 = batch-level weight traffic shared by all requests); bits 0..39
a per-stream sequence. Ids are seeded through the same one-shot
``_seed_coll`` hook the hierarchical tiers use, and admission/evict
decisions are deterministic, so the SPMD same-id-same-collective
contract survives.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Any, Callable, Deque, Dict, Iterable, List, Optional, Tuple

from ..utils.trace import trace

__all__ = [
    "stream_depth", "CreditGate", "Inflight", "TransferEngine",
    "STREAM_BIT", "make_stream_coll", "is_stream_coll",
    "stream_coll_request", "stream_coll_seq",
]


def stream_depth(default: int = 3) -> int:
    """Credit depth for streaming transfers (``TDR_STREAM_DEPTH``).

    The default of 3 is the staged pipeline's historical depth: one
    window landing, one on the wire, one being produced. Values < 1
    are clamped to 1 (a depth-0 *engine* is constructed explicitly,
    not through the env knob — an unbounded default would let a
    misconfigured server pin every page in scratch at once)."""
    env = os.environ.get("TDR_STREAM_DEPTH", "")
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            pass
    return default


# --------------------------------------------------------------- coll ids

STREAM_BIT = 1 << 62
_REQ_SHIFT = 40
_REQ_MASK = (1 << 22) - 1
_SEQ_MASK = (1 << _REQ_SHIFT) - 1


def make_stream_coll(request_id: int, seq: int) -> int:
    """Serving-stream collective id: bit 62 | request<<40 | seq.

    ``request_id`` 0 is batch-level traffic (weight pages shared by
    every active request); nonzero ids attribute KV/join streams to
    one request. Bit 63 stays clear so the id never collides with the
    ring's auto-assigned namespace."""
    return STREAM_BIT | ((int(request_id) & _REQ_MASK) << _REQ_SHIFT) \
        | (int(seq) & _SEQ_MASK)


def is_stream_coll(coll: int) -> bool:
    return bool(coll & STREAM_BIT) and not bool(coll >> 63)


def stream_coll_request(coll: int) -> int:
    return (coll >> _REQ_SHIFT) & _REQ_MASK


def stream_coll_seq(coll: int) -> int:
    return coll & _SEQ_MASK


# ----------------------------------------------------------------- credits

class CreditGate:
    """Counting gate for in-flight transfer credits.

    ``acquire`` blocks while ``in_flight >= depth`` (depth 0 =
    unbounded, accounting only). ``release`` refunds one credit; the
    refund is what keeps the gate honest across the NAK/retransmit
    ladder — a retransmitted page completes through the same handle,
    so its credit is refunded exactly once, on settlement, never on
    the NAK itself (the wire slot is still occupied while the
    retransmit runs)."""

    def __init__(self, depth: int, name: str = "stream") -> None:
        self.depth = max(0, int(depth))
        self.name = name
        self._cv = threading.Condition()
        self._in_flight = 0
        self._high_water = 0
        self._acquired = 0
        self._released = 0

    @property
    def in_flight(self) -> int:
        with self._cv:
            return self._in_flight

    @property
    def high_water(self) -> int:
        with self._cv:
            return self._high_water

    def acquire(self, timeout_s: Optional[float] = None) -> bool:
        deadline = None if timeout_s is None else time.monotonic() + timeout_s
        with self._cv:
            while self.depth and self._in_flight >= self.depth:
                left = None if deadline is None \
                    else deadline - time.monotonic()
                if left is not None and left <= 0:
                    return False
                trace.add(f"serve.credit_stall.{self.name}", 1)
                self._cv.wait(0.05 if left is None else min(left, 0.05))
            self._in_flight += 1
            self._acquired += 1
            if self._in_flight > self._high_water:
                self._high_water = self._in_flight
            return True

    def release(self) -> None:
        with self._cv:
            if self._in_flight <= 0:
                raise RuntimeError(
                    f"credit underflow on gate {self.name!r}: "
                    "release without matching acquire")
            self._in_flight -= 1
            self._released += 1
            self._cv.notify_all()

    def stats(self) -> Dict[str, int]:
        with self._cv:
            return {"depth": self.depth, "in_flight": self._in_flight,
                    "high_water": self._high_water,
                    "acquired": self._acquired,
                    "released": self._released}


# ---------------------------------------------------------------- inflight

class Inflight:
    """A launched transfer holding one credit.

    Proxies the underlying :class:`CollectiveHandle` (``wait``/``test``/
    ``done``/``coll``) and refunds its credit exactly once when the
    transfer settles — on successful completion OR on the error path
    (a failed transfer must not strand its credit, or a NAK storm
    starves the stream). ``release_on_settle=False`` defers the refund
    to an explicit :meth:`release` — for pagers whose credit maps to a
    scratch *window* that stays pinned after the wire work lands,
    until the consumer is done reading it."""

    def __init__(self, engine: "TransferEngine", handle: Any, tag: Any = None,
                 release_on_settle: bool = True) -> None:
        self._engine = engine
        self._handle = handle
        self.tag = tag
        self._release_on_settle = release_on_settle
        self._released = False
        self._settled = False

    @property
    def coll(self) -> int:
        return int(getattr(self._handle, "coll", 0))

    @property
    def handle(self) -> Any:
        return self._handle

    @property
    def done(self) -> bool:
        return bool(getattr(self._handle, "done", False))

    def _settle(self) -> None:
        if not self._settled:
            self._settled = True
            self._engine._settled(self)
            if self._release_on_settle:
                self.release()

    def release(self) -> None:
        """Refund this transfer's credit (idempotent)."""
        if not self._released:
            self._released = True
            self._engine.gate.release()

    def test(self) -> bool:
        """True once the transfer completed OK; raises on failure.
        Either way the credit is refunded when the transfer settles."""
        try:
            ok = self._handle.test()
        except BaseException:
            self._settle()
            raise
        if ok:
            self._settle()
        return ok

    def wait(self, timeout_ms: int = -1) -> None:
        """Block until completion; raises the transport's classified
        error on failure. A positive expired timeout raises retryable
        and leaves the transfer (and its credit) live — retry wait."""
        try:
            self._handle.wait(timeout_ms)
        except BaseException as e:
            if "still in flight" in str(e):
                raise  # not settled: the transfer is still running
            self._settle()
            raise
        self._settle()


class _LocalDone:
    """Loopback stand-in for a CollectiveHandle: a produce-only
    transfer with no wire leg (world=None pagers, unit tests). Settles
    immediately."""

    coll = 0
    done = True

    def test(self) -> bool:
        return True

    def wait(self, timeout_ms: int = -1) -> None:
        return None


# ------------------------------------------------------------------ engine

class TransferEngine:
    """Credit-gated producer/consumer transfer channels.

    One engine instance per client (the trainer's cross-slice sync,
    a weight pager, a KV stream): each owns a :class:`CreditGate` and
    an in-flight registry, shares the underlying ring's async driver,
    and spawns no threads. ``submit`` is the async-handle channel;
    ``pipeline`` is the executor-future channel (the staged-pipeline
    loop, verbatim semantics).
    """

    def __init__(self, depth: Optional[int] = None, name: str = "stream",
                 yield_after_launch: bool = False) -> None:
        if depth is None:
            depth = stream_depth()
        self.name = name
        self.gate = CreditGate(depth, name=name)
        self._yield = yield_after_launch
        self._lock = threading.Lock()
        self._live: Dict[int, Inflight] = {}
        self._submitted = 0
        self._closed = False

    # -- accounting ------------------------------------------------

    def _settled(self, inf: Inflight) -> None:
        with self._lock:
            self._live.pop(id(inf), None)

    @property
    def live(self) -> int:
        """Transfers submitted and not yet settled (the engine-level
        leak census; teardown drains this to zero)."""
        with self._lock:
            return len(self._live)

    def stats(self) -> Dict[str, Any]:
        s = self.gate.stats()
        with self._lock:
            s.update(name=self.name, submitted=self._submitted,
                     live=len(self._live))
        return s

    # -- async-handle channel --------------------------------------

    def submit(self, launch: Callable[[], Any],
               produce: Optional[Callable[[], None]] = None,
               tag: Any = None, release_on_settle: bool = True,
               yield_cpu: Optional[bool] = None) -> Inflight:
        """Acquire a credit, run ``produce()`` (fill scratch), then
        ``launch()`` (returns an async CollectiveHandle — or None for
        a produce-only loopback transfer) and track the result.

        ``yield_cpu`` (default: the engine's ``yield_after_launch``)
        re-enacts the bucketed launch's ``time.sleep(0)``: drop the
        GIL right after submission so the driver thread gets on the
        wire before the next produce step competes for cycles."""
        if self._closed:
            raise RuntimeError(f"TransferEngine {self.name!r} is closed")
        self.gate.acquire()
        try:
            if produce is not None:
                produce()
            handle = launch()
        except BaseException:
            self.gate.release()
            raise
        if handle is None:
            handle = _LocalDone()
        inf = Inflight(self, handle, tag=tag,
                       release_on_settle=release_on_settle)
        with self._lock:
            self._submitted += 1
            self._live[id(inf)] = inf
        if isinstance(handle, _LocalDone):
            inf._settle()
        if (self._yield if yield_cpu is None else yield_cpu):
            time.sleep(0)
        return inf

    # -- executor-future channel -----------------------------------

    def pipeline(self, items: Iterable[Any],
                 produce: Callable[[Any, int], None],
                 launch: Callable[[Any, int], Any],
                 consume: Callable[[Any, Any, int], None],
                 depth: Optional[int] = None) -> None:
        """The staged-pipeline deque loop over ``items``: for each item
        run ``produce(item, k)``, submit ``launch(item, k)`` (returns a
        concurrent Future), and ``consume(result, item, k)`` strictly
        in submission order once the future lands — consuming early
        whenever the head is already done, and always when the window
        is full. ``depth`` defaults to the engine's credit depth (the
        gate bounds produce-side scratch occupancy: produce for item
        k+depth never starts before item k was consumed).

        On any failure every launched future is drained before the
        error propagates — no worker is left writing into scratch that
        the caller is about to reuse (the staged pipeline's own error
        contract, kept verbatim)."""
        if depth is None:
            depth = self.gate.depth or stream_depth()
        depth = max(1, int(depth))
        pending: Deque[Tuple[Any, Any, int]] = collections.deque()

        def _consume_head() -> None:
            fut, item, k = pending.popleft()
            try:
                res = fut.result()
                consume(res, item, k)
            finally:
                self.gate.release()

        try:
            for k, item in enumerate(items):
                self.gate.acquire()
                try:
                    produce(item, k)
                    fut = launch(item, k)
                except BaseException:
                    self.gate.release()
                    raise
                with self._lock:
                    self._submitted += 1
                pending.append((fut, item, k))
                while len(pending) >= depth or (pending and pending[0][0].done()):
                    _consume_head()
            while pending:
                _consume_head()
        except BaseException:
            while pending:
                fut = pending.popleft()[0]
                try:
                    fut.result()
                except BaseException:
                    pass
                self.gate.release()
            raise

    # -- teardown --------------------------------------------------

    def drain(self, timeout_ms: int = 30000) -> None:
        """Wait every live transfer to settlement (errors swallowed —
        drain is the teardown path; the caller already has its
        primary error if there is one). Credits end refunded."""
        with self._lock:
            live = list(self._live.values())
        for inf in live:
            try:
                inf.wait(timeout_ms)
            except BaseException:
                pass
            inf.release()

    def close(self) -> None:
        """Drain and refuse further submits. Idempotent; the flat
        thread census is free (the engine never spawned any)."""
        if self._closed:
            return
        self.drain()
        self._closed = True
