"""Weight/KV page streaming over the sealed zero-copy path.

The serving memory model: model weights are flat f32 **pages** (one
page per transformer layer, plus the embedding and head pages — see
:mod:`.model`), sharded across ranks by ``RingWorld.owned_slice``.
Each rank keeps only its own shard resident; a page needed for compute
is streamed just-in-time into a registered scratch *window* with
``all_gather_async`` — the PR 8 async driver, so fetch k+1 rides the
wire while layer k's matmuls run. Pages arrive sealed like any other
collective frame (CRC32C + generation/step/chunk-seq); a corrupt rider
on a streamed page walks the NAK/retransmit ladder and the consumer
never sees the bad bytes.

Credits ARE windows here: the :class:`~.stream.TransferEngine` gate is
sized to the scratch window count (``TDR_STREAM_DEPTH``), a fetch holds
its credit from submission until the consumer calls :meth:`release`
(the page may be pinned in scratch well after the wire work landed),
and the high-water mark proves the engine never exceeded depth.

KV-cache pages use the same engine with the zero-fill broadcast trick:
the home rank fills the window with the page payload, every other rank
zeroes it, and the ring ``allreduce_async`` sum reconstructs the home
rank's bytes on every rank — async, sealed, credit-gated, and
request-taggable, without needing a broadcast on the async driver.
(IEEE caveat: ``x + 0.0`` is value- but not sign-of-zero-preserving
for ``-0.0``; KV payloads only feed dot products and softmax, where
the two zeros are indistinguishable.)
"""

from __future__ import annotations

import collections
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

import numpy as np

from ..utils.trace import trace
from .stream import TransferEngine, make_stream_coll, stream_depth

__all__ = ["PageSet", "WeightStreamer", "KVStream"]


class PageSet:
    """Named flat-f32 pages (the streamable unit).

    ``pages`` is a list of 1-D ``float32`` arrays; ``names`` labels
    them for telemetry. The set is immutable after construction — the
    streamer registers windows sized to the largest page once."""

    def __init__(self, pages: List[np.ndarray],
                 names: Optional[List[str]] = None) -> None:
        self.pages = [np.ascontiguousarray(p, dtype=np.float32).reshape(-1)
                      for p in pages]
        self.names = list(names) if names is not None else \
            [f"page{i}" for i in range(len(self.pages))]
        if len(self.names) != len(self.pages):
            raise ValueError("names/pages length mismatch")
        self.max_elems = max((int(p.size) for p in self.pages), default=0)

    def __len__(self) -> int:
        return len(self.pages)

    def nbytes(self) -> int:
        return sum(int(p.nbytes) for p in self.pages)


class WeightStreamer:
    """Streams weight pages ahead of compute, double(+)-buffered.

    Strict-FIFO contract: :meth:`prefetch` order must equal
    :meth:`acquire` order (the page schedule is deterministic on every
    rank — the SPMD contract the async driver already imposes). A page
    is valid from ``acquire`` until :meth:`release`; releasing returns
    the scratch window AND the transfer credit.

    ``world=None`` is loopback mode: pages are served from the local
    copy with no wire leg — the sequential baseline and unit tests run
    the identical consumer code with zero transport.
    """

    def __init__(self, world: Any, pages: PageSet,
                 depth: Optional[int] = None, name: str = "weights",
                 seal_step: Optional[Callable[[], int]] = None) -> None:
        self.world = world
        self.pages = pages
        self.depth = stream_depth() if depth is None else max(1, int(depth))
        self.name = name
        self.engine = TransferEngine(depth=self.depth, name=name,
                                     yield_after_launch=True)
        # Scratch windows, ring-registered ONCE (front-loaded
        # registration — steady-state fetches post work requests only).
        self._windows: List[np.ndarray] = [
            np.zeros(max(1, pages.max_elems), dtype=np.float32)
            for _ in range(self.depth)]
        self._free: Deque[int] = collections.deque(range(self.depth))
        # (page_idx, Inflight, window_idx) in flight, FIFO.
        self._inflight: Deque[Tuple[int, Any, int]] = collections.deque()
        # Acquired-and-not-yet-released pages: (window_idx, Inflight).
        self._held: List[Tuple[int, Any]] = []
        self._registered = False
        # Local shards: in wire mode each rank persists only its owned
        # slice of every page (plus the slice bounds); loopback keeps
        # whole pages.
        self._shards: List[Tuple[slice, np.ndarray]] = []
        if world is not None:
            # Front-load the window MRs once (best-effort — an
            # unregistered buffer still works, registered per call).
            ring = getattr(world, "ring", None)
            if ring is not None:
                try:
                    for w in self._windows:
                        ring.register_buffer(w)
                    self._registered = True
                except Exception:
                    pass
            for p in pages.pages:
                sl = world.owned_slice(p)
                self._shards.append((sl, p[sl].copy()))
        else:
            for p in pages.pages:
                self._shards.append((slice(0, p.size), p))
        self.fetched_pages = 0
        self.fetched_bytes = 0

    # -- fetch ------------------------------------------------------

    def prefetch(self, page_idx: int, coll: int = 0) -> None:
        """Start streaming page ``page_idx`` into the next free
        window. Blocks while all windows are pinned (credit gate) —
        which only happens when the consumer is ``depth`` pages
        behind, i.e. the stream is already fully ahead."""
        pg = self.pages.pages[page_idx]
        n = int(pg.size)

        state = {}

        def produce() -> None:
            # Pick the window under the credit we now hold. The gate
            # guarantees a free one exists: credits == windows.
            wi = self._free.popleft()
            state["wi"] = wi
            win = self._windows[wi]
            sl, shard = self._shards[page_idx]
            if self.world is None:
                win[:n] = pg
                return
            win[:n] = 0.0
            win[sl] = shard

        def launch():
            if self.world is None:
                return None
            if coll:
                self.world._seed_coll(coll)
            return self.world.all_gather_async(self._windows[state["wi"]][:n])

        try:
            inf = self.engine.submit(launch, produce=produce,
                                     tag=("page", page_idx),
                                     release_on_settle=False)
        except BaseException:
            if "wi" in state:
                self._free.append(state["wi"])
            raise
        self._inflight.append((page_idx, inf, state["wi"]))
        self.fetched_pages += 1
        self.fetched_bytes += n * 4
        trace.add(f"serve.pages.{self.name}", 1)

    def acquire(self, page_idx: int) -> np.ndarray:
        """Wait the oldest in-flight fetch (must be ``page_idx`` — the
        FIFO contract) and return the landed page view. The window
        stays pinned until :meth:`release`."""
        if not self._inflight:
            raise RuntimeError(f"acquire({page_idx}) with empty stream "
                               f"on {self.name!r} — prefetch first")
        idx, inf, wi = self._inflight[0]
        if idx != page_idx:
            raise RuntimeError(
                f"stream {self.name!r} is FIFO: acquire({page_idx}) but "
                f"head of stream is page {idx}")
        self._inflight.popleft()
        try:
            with trace.span("serve.page_wait", page=page_idx,
                            page_name=self.pages.names[page_idx]):
                inf.wait()
        except BaseException:
            # Failed fetch: the window is garbage — recycle it and
            # refund the credit so the NAK/heal retry can restream.
            self._free.append(wi)
            inf.release()
            raise
        n = int(self.pages.pages[page_idx].size)
        self._held.append((wi, inf))
        return self._windows[wi][:n]

    def release(self, view: np.ndarray) -> None:
        """Return an acquired page's window and credit (matched to
        the held window the view aliases)."""
        for j, (wi, inf) in enumerate(self._held):
            if np.shares_memory(self._windows[wi], view):
                self._held.pop(j)
                self._free.append(wi)
                inf.release()
                return
        raise RuntimeError(
            f"release on {self.name!r}: view aliases no held window")

    # -- teardown ---------------------------------------------------

    def close(self) -> None:
        """Drain in-flight fetches, drop held windows, refund every
        credit, release the ring registrations. Flat thread census —
        the streamer never spawned a thread."""
        while self._inflight:
            _, inf, wi = self._inflight.popleft()
            try:
                inf.wait()
            except BaseException:
                pass
            inf.release()
            self._free.append(wi)
        while self._held:
            wi, inf = self._held.pop()
            self._free.append(wi)
            inf.release()
        self.engine.close()
        if self._registered and self.world is not None:
            ring = getattr(self.world, "ring", None)
            if ring is not None:
                for w in self._windows:
                    try:
                        ring.unregister_buffer(w)
                    except Exception:
                        pass
            self._registered = False

    def stats(self) -> Dict[str, Any]:
        s = self.engine.stats()
        s.update(pages=self.fetched_pages, bytes=self.fetched_bytes,
                 windows=self.depth)
        return s


class KVStream:
    """Streams KV-cache pages between ranks on request join.

    One instance per batcher; uses its own credit-gated engine and a
    single registered window (KV joins are boundary events, not a
    steady stream — depth 1 keeps the scratch footprint at one page).

    ``broadcast(payload, home, request_id, seq)``: home rank supplies
    ``payload`` (flat f32); every rank returns a copy of home's bytes.
    Rides allreduce-of-(payload | zeros) — see the module docstring —
    so the page is sealed, NAK/retransmit-healable, and carries the
    request-tagged collective id for tdr_explain attribution."""

    def __init__(self, world: Any, max_elems: int,
                 name: str = "kv") -> None:
        self.world = world
        self.name = name
        self.engine = TransferEngine(depth=1, name=name)
        self._win = np.zeros(max(1, int(max_elems)), dtype=np.float32)
        self._registered = False
        if world is not None:
            ring = getattr(world, "ring", None)
            if ring is not None:
                try:
                    ring.register_buffer(self._win)
                    self._registered = True
                except Exception:
                    pass

    def broadcast(self, payload: Optional[np.ndarray], home: int,
                  request_id: int, seq: int, n: Optional[int] = None) -> np.ndarray:
        """All ranks call collectively. ``payload`` is required on the
        home rank (ignored elsewhere); non-home callers pass ``n`` =
        page elements (home's payload length is part of the
        deterministic schedule)."""
        if self.world is None:
            assert payload is not None
            return np.array(payload, dtype=np.float32).reshape(-1).copy()
        rank = self.world.rank
        if rank == home:
            assert payload is not None
            flat = np.asarray(payload, dtype=np.float32).reshape(-1)
            n = int(flat.size)
        else:
            if n is None:
                raise ValueError("non-home broadcast needs n")
            n = int(n)
        if n > self._win.size:
            raise ValueError(f"KV page {n} elems exceeds window "
                             f"{self._win.size}")

        def produce() -> None:
            if rank == home:
                self._win[:n] = flat
            else:
                self._win[:n] = 0.0

        coll = make_stream_coll(request_id, seq)

        def launch():
            self.world._seed_coll(coll)
            return self.world.allreduce_async(self._win[:n])

        with trace.span("serve.kv_stream", req=request_id,
                        bytes=n * 4, coll=coll):
            inf = self.engine.submit(launch, produce=produce,
                                     tag=("kv", request_id, seq))
            inf.wait()
        trace.add("serve.kv_pages", 1)
        return self._win[:n].copy()

    def close(self) -> None:
        self.engine.close()
        if self._registered and self.world is not None:
            ring = getattr(self.world, "ring", None)
            if ring is not None:
                try:
                    ring.unregister_buffer(self._win)
                except Exception:
                    pass
            self._registered = False
