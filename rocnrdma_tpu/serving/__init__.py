"""Serving data path: streaming transfer engine, weight/KV-page
prefetch over the zero-copy path, and continuous batching.

Import-light by design: :mod:`collectives.jax_shim` imports
``serving.stream`` for the shared transfer engine, so this package
init must not pull jax, models, or the transport — the heavy
submodules (:mod:`.pager`, :mod:`.model`, :mod:`.batcher`) load
lazily on first attribute access.
"""

from __future__ import annotations

from .stream import (  # noqa: F401
    CreditGate, Inflight, TransferEngine, stream_depth,
    STREAM_BIT, make_stream_coll, is_stream_coll,
    stream_coll_request, stream_coll_seq,
)

__all__ = [
    "CreditGate", "Inflight", "TransferEngine", "stream_depth",
    "STREAM_BIT", "make_stream_coll", "is_stream_coll",
    "stream_coll_request", "stream_coll_seq",
    "stream", "pager", "model", "batcher",
]

_LAZY = ("pager", "model", "batcher", "stream")


def __getattr__(name: str):
    if name in _LAZY:
        import importlib
        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
