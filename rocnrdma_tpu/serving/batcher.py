"""Continuous batching over the paged decode path.

The serving loop: a request queue with **join/evict at token
boundaries**. Every decode step is one page cycle — embed page, layer
pages, head page — streamed by the :class:`~.pager.WeightStreamer`
continuously across steps (the fetch pointer runs ahead of the compute
pointer by the credit depth, so layer k+1 is on the wire while layer
k's matmuls run, including across the step boundary). Joining requests
prefill on their **home rank** only (``id % world``) during the same
page cycle the active slots decode under — weight traffic is batch
traffic, paid once per step however many requests ride it — and the
prefill KV pages then stream to the other ranks over the sealed path
(:class:`~.pager.KVStream`), tagged with the request's collective id
so ``tdr_explain`` can attribute decode-stream stragglers per request.

SPMD contract: every rank runs the same batcher against the same
submit/evict sequence; admissions and evictions happen at deterministic
boundaries, so the collective schedule (weight gathers + KV broadcasts)
is identical fleet-wide — the same contract the trainer's bucket plan
carries, inherited rather than re-invented.

SLO accounting: ``serve.requests`` / ``serve.tokens`` counters and the
``token_lat_us`` fine histogram (rendered by the coordinator as
``tdr_serve_requests_total`` / ``tdr_serve_tokens_total`` /
``tdr_token_lat_us{quantile=}``) ride the ordinary heartbeat — no new
wire protocol.
"""

from __future__ import annotations

import collections
import time
from typing import Any, Deque, Dict, List, Optional

import numpy as np

from ..utils.trace import trace
from .model import JitPagedDecoder, PagedDecoder, ServeConfig
from .pager import KVStream, PageSet, WeightStreamer
from .stream import make_stream_coll

__all__ = ["Request", "ContinuousBatcher"]


class Request:
    """One decode request. ``id`` must be unique and identical on all
    ranks (it keys the home-rank assignment and the wire-carried
    attribution id — 22 bits, so < 4M live ids)."""

    def __init__(self, req_id: int, prompt, max_new_tokens: int) -> None:
        self.id = int(req_id)
        self.prompt = np.asarray(prompt, dtype=np.int32).reshape(-1)
        if self.prompt.size == 0:
            raise ValueError("empty prompt")
        self.max_new_tokens = int(max_new_tokens)
        self.tokens: List[int] = []
        self.joined_step = -1
        self.done = False
        self.evicted = False
        self.t_submit = time.monotonic()
        self.t_first: Optional[float] = None
        self.t_done: Optional[float] = None


class _Slot:
    def __init__(self, req: Request, cache: Dict[str, Dict[str, np.ndarray]],
                 pos: int) -> None:
        self.req = req
        self.cache = cache          # {"layer_i": {"k","v"}}
        self.pos = pos              # next cache write position
        self.x: Optional[np.ndarray] = None  # per-cycle activation
        self.kv_seq = 0             # per-request stream sequence


class ContinuousBatcher:
    """Continuous-batching decode over streamed weight pages.

    ``world=None`` runs loopback (single process, no transport): the
    sequential baseline and the unit tests. ``prefetch=False`` fetches
    each page on demand and waits it immediately — the non-overlapped
    baseline the bench compares against; tokens are bitwise identical
    either way (the page bytes are, and the math doesn't move).
    """

    def __init__(self, world: Any, pages: PageSet, cfg: ServeConfig,
                 max_slots: int = 4, depth: Optional[int] = None,
                 prefetch: bool = True, jit_decode: bool = False) -> None:
        self.world = world
        self.cfg = cfg
        # jit_decode is opt-in (it imports jax): the jitted paged step
        # with donated cache buffers — same tokens, faster matmuls.
        # Default stays the numpy port (the -san/LITE contract).
        self.decoder = (JitPagedDecoder(cfg) if jit_decode
                        else PagedDecoder(cfg))
        self.prefetch = bool(prefetch)
        self.streamer = WeightStreamer(world, pages, depth=depth,
                                       name="weights")
        kv_elems = (2 * cfg.n_kv_heads * cfg.max_seq_len * cfg.head_dim)
        self.kv = KVStream(world, max_elems=max(kv_elems, 8), name="kv")
        self.max_slots = int(max_slots)
        self.slots: List[Optional[_Slot]] = [None] * self.max_slots
        self.queue: Deque[Request] = collections.deque()
        self.finished: Dict[int, Request] = {}
        self._evict_asap: set = set()
        self.step_no = 0
        # Weight-page stream pointers: the page ORDER repeats every
        # step, so the fetch stream is just the cycled sequence.
        self._order = list(range(len(pages)))
        self._fetch_ptr = 0
        self._acq_ptr = 0
        # Wall-clock per produced token (µs), for the local p99 gate;
        # the histogram twin rides the heartbeat.
        self.token_lat_us: List[float] = []

    # ------------------------------------------------------ admission

    def submit(self, req: Request) -> None:
        """Enqueue (all ranks, identically — the SPMD contract)."""
        self.queue.append(req)
        trace.event("serve.submit", req=req.id,
                    prompt=int(req.prompt.size))

    def evict(self, req_id: int) -> None:
        """Mark a request for eviction at the next token boundary
        (all ranks, identically)."""
        self._evict_asap.add(int(req_id))

    def home_rank(self, req: Request) -> int:
        if self.world is None:
            return 0
        return req.id % self.world.world

    @property
    def active(self) -> int:
        return sum(1 for s in self.slots if s is not None)

    # ----------------------------------------------------- page stream

    def _prefetch_one(self) -> None:
        idx = self._order[self._fetch_ptr % len(self._order)]
        self._fetch_ptr += 1
        self.streamer.prefetch(idx, coll=make_stream_coll(0, self._fetch_ptr))

    def _top_up(self) -> None:
        """Fill the window budget with fetches ahead of compute —
        never blocking: only submit while a credit is demonstrably
        free (single-threaded, so the check is race-free)."""
        if not self.prefetch:
            return
        while (self.streamer.engine.gate.in_flight < self.streamer.depth
               and self._fetch_ptr - self._acq_ptr < 2 * len(self._order)):
            self._prefetch_one()

    def _acquire_next(self, expect: int) -> np.ndarray:
        if not self.prefetch:
            # On-demand baseline: fetch exactly the needed page, wait.
            self._prefetch_one()
        else:
            self._top_up()
        idx = self._order[self._acq_ptr % len(self._order)]
        assert idx == expect, f"page stream out of order: {idx} != {expect}"
        self._acq_ptr += 1
        view = self.streamer.acquire(idx)
        # Re-arm the stream while this page computes: the next fetch
        # rides the wire underneath the matmuls below.
        self._top_up()
        return view

    # ------------------------------------------------------------ step

    def step(self) -> bool:
        """One token boundary + page cycle. Returns False when there
        was nothing to do (empty queue, empty slots)."""
        # Boundary: evictions first (freeing slots), then admissions.
        for i, slot in enumerate(self.slots):
            if slot is None:
                continue
            r = slot.req
            if r.id in self._evict_asap or len(r.tokens) >= r.max_new_tokens:
                r.done = True
                r.evicted = r.id in self._evict_asap and \
                    len(r.tokens) < r.max_new_tokens
                r.t_done = time.monotonic()
                self._evict_asap.discard(r.id)
                self.finished[r.id] = r
                self.slots[i] = None
                trace.event("serve.evict", req=r.id,
                            tokens=len(r.tokens),
                            evicted=bool(r.evicted))
        newly: List[_Slot] = []
        for i in range(self.max_slots):
            if self.slots[i] is None and self.queue:
                req = self.queue.popleft()
                if req.id in self._evict_asap:
                    self._evict_asap.discard(req.id)
                    req.done = req.evicted = True
                    self.finished[req.id] = req
                    continue
                cache = {f"layer_{j}": self.decoder.new_cache()
                         for j in range(self.cfg.n_layers)}
                slot = _Slot(req, cache, pos=0)
                req.joined_step = self.step_no
                self.slots[i] = slot
                newly.append(slot)
                trace.add("serve.requests", 1)
                trace.event("serve.join", req=req.id, slot=i,
                            home=self.home_rank(req),
                            prompt=int(req.prompt.size))
        live = [s for s in self.slots if s is not None]
        if not live:
            return False

        self.step_no += 1
        if self.world is not None:
            self.world.set_seal_step(self.step_no)
        rank = 0 if self.world is None else self.world.rank
        t0 = time.monotonic()

        # ---- page cycle: embed → layers → head -------------------
        # Joining slots prefill under the same pages the active slots
        # decode under (home rank computes; the other ranks hold the
        # pages for their own active-slot decode only).
        cfg, dec = self.cfg, self.decoder
        page = self._acquire_next(0)
        with trace.span("serve.compute", phase="embed", rank=rank):
            for s in live:
                if s in newly:
                    if self.home_rank(s.req) == rank:
                        s.x = dec.embed(page, s.req.prompt)
                else:
                    s.x = dec.embed(page, np.array([s.req.tokens[-1]]))
        self.streamer.release(page)

        for li in range(cfg.n_layers):
            page = self._acquire_next(1 + li)
            with trace.span("serve.compute", phase="layer", layer=li,
                            rank=rank):
                for s in live:
                    if s.x is None:
                        continue  # joining slot on a non-home rank
                    s.x = dec.layer(page, s.x, s.cache[f"layer_{li}"],
                                    s.pos)
            self.streamer.release(page)

        page = self._acquire_next(len(self._order) - 1)
        with trace.span("serve.compute", phase="head", rank=rank):
            for s in live:
                if s.x is None:
                    continue
                logits = dec.head(page, s.x)
                tok = int(np.argmax(logits[-1]))
                s.req.tokens.append(tok)
                if s.req.t_first is None:
                    s.req.t_first = time.monotonic()
                s.x = None
        self.streamer.release(page)

        # ---- KV join streaming (boundary events, request-tagged) --
        for s in newly:
            self._stream_join(s, rank)

        # Advance positions; account the step's tokens.
        produced = 0
        for s in live:
            s.pos += s.req.prompt.size if s in newly else 1
            produced += 1
        dt_us = (time.monotonic() - t0) * 1e6 / max(1, produced)
        for _ in range(produced):
            self.token_lat_us.append(dt_us)
            trace.hist("token_lat_us", int(dt_us))
        trace.add("serve.tokens", produced)
        return True

    def _stream_join(self, slot: _Slot, rank: int) -> None:
        """Ship the joining request's prefill KV (and its first token)
        from its home rank to every rank, one sealed page per layer
        plus a meta page — every page carries the request-tagged
        collective id (bit 62 | req<<40 | seq)."""
        req, cfg = slot.req, self.cfg
        home = self.home_rank(req)
        p = int(req.prompt.size)
        kvn = cfg.n_kv_heads * p * cfg.head_dim
        with trace.span("serve.request_join", req=req.id, home=home,
                        rank=rank):
            for li in range(cfg.n_layers):
                c = slot.cache[f"layer_{li}"]
                payload = None
                if rank == home:
                    payload = self.decoder.dump_kv(c, p)
                slot.kv_seq += 1
                got = self.kv.broadcast(payload, home, req.id,
                                        slot.kv_seq, n=2 * kvn)
                if rank != home:
                    self.decoder.load_kv(
                        c,
                        got[:kvn].reshape(cfg.n_kv_heads, p,
                                          cfg.head_dim),
                        got[kvn:].reshape(cfg.n_kv_heads, p,
                                          cfg.head_dim), p)
            meta = None
            if rank == home:
                meta = np.array([float(req.tokens[-1])], np.float32)
            slot.kv_seq += 1
            got = self.kv.broadcast(meta, home, req.id, slot.kv_seq, n=1)
            if rank != home:
                tok = int(got[0])
                req.tokens.append(tok)
                if req.t_first is None:
                    req.t_first = time.monotonic()

    # ------------------------------------------------------------- run

    def run(self, max_steps: int = 10000) -> int:
        """Drive steps until idle; returns steps executed."""
        n = 0
        while n < max_steps and (self.queue or self.active):
            if not self.step():
                break
            n += 1
        return n

    def close(self) -> None:
        """Drain the streams and free the windows (flat thread
        census; every credit refunded)."""
        self.streamer.close()
        self.kv.close()

    def stats(self) -> Dict[str, Any]:
        return {
            "steps": self.step_no,
            "active": self.active,
            "queued": len(self.queue),
            "finished": len(self.finished),
            "weights": self.streamer.stats(),
            "kv": self.kv.engine.stats(),
        }
