"""ctypes bindings to the native transport engine (libtdr.so).

This is the Python face of the userspace half of the stack: MR
registration, RC queue pairs, one-sided WRITE/READ, two-sided
SEND/RECV, completions, MR revocation, and the native ring allreduce.
The library is built on demand from ``rocnrdma_tpu/native`` (no
build-time dependencies — the verbs backend dlopens libibverbs at
runtime; machines without NICs get the emulated backend).
"""

from __future__ import annotations

import ctypes
import os
import re
import subprocess
import threading
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

from rocnrdma_tpu.utils.trace import trace

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), os.pardir, "native")
# TDR_NATIVE_LIB points at an alternative artifact (the sanitized
# libtdr_san.so built by `make sanitize`); default is the on-demand
# production build.
_LIB_PATH = os.environ.get("TDR_NATIVE_LIB") or os.path.abspath(
    os.path.join(_NATIVE_DIR, "libtdr.so"))
_build_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None

# Engine kinds
ENGINE_EMU = 0
ENGINE_VERBS = 1

# Completion statuses
WC_SUCCESS = 0
WC_REM_ACCESS_ERR = 1
WC_LOC_ACCESS_ERR = 2
WC_FLUSH_ERR = 3
WC_GENERAL_ERR = 4
# Seal verification failed at land time and the per-chunk retransmit
# budget is exhausted (or a stale-incarnation frame was fenced).
WC_INTEGRITY_ERR = 5

# Access flags
ACCESS_LOCAL = 0
ACCESS_REMOTE_WRITE = 1
ACCESS_REMOTE_READ = 2

# Opcodes
OP_WRITE, OP_READ, OP_SEND, OP_RECV = 0, 1, 2, 3

# Datatypes / reduce ops for the ring
DT_F32, DT_F64, DT_I32, DT_I64, DT_BF16, DT_U8 = 0, 1, 2, 3, 4, 5
DT_I8 = 6  # int8 wire compression; reduces only via allreduce_q8
RED_SUM, RED_MAX, RED_MIN = 0, 1, 2

# Ring schedules (tdr_ring_last_schedule)
SCHED_NONE, SCHED_GENERIC, SCHED_FUSED2, SCHED_FUSED2_FB, SCHED_WAVEFRONT = \
    0, 1, 2, 3, 4
SCHED_Q8 = 5

# Connection flags (tdr_listen_tier/tdr_connect_tier).
_CONN_FORCE_STREAM = 1

_NUMPY_DTYPE_MAP = {
    "float32": DT_F32,
    "float64": DT_F64,
    "int32": DT_I32,
    "int64": DT_I64,
    "bfloat16": DT_BF16,
    # Byte transport only (alltoall / all_gather / broadcast); the
    # reducing collectives reject it engine-side (no fold semantics).
    "uint8": DT_U8,
    # Quantized wire payload: reduces only through the scale-carrying
    # q8 schedule (Ring.allreduce_q8); plain reducing collectives
    # reject it engine-side (a scale-less int8 sum overflows).
    "int8": DT_I8,
}


class Wc(ctypes.Structure):
    _fields_ = [
        ("wr_id", ctypes.c_uint64),
        ("status", ctypes.c_int32),
        ("opcode", ctypes.c_int32),
        ("len", ctypes.c_uint64),
    ]


class TelEventC(ctypes.Structure):
    """Mirror of the native tdr_tel_event (40 bytes, fixed layout)."""

    _fields_ = [
        ("ts_ns", ctypes.c_uint64),
        ("type", ctypes.c_uint16),
        ("engine", ctypes.c_uint16),
        ("qp", ctypes.c_uint32),
        ("id", ctypes.c_uint64),
        ("arg", ctypes.c_uint64),
        # Collective trace id (0 = none; bit 63 = ring auto-assigned).
        ("coll", ctypes.c_uint64),
    ]


def _build_library() -> None:
    # TUNE=native is safe here: build-on-demand always runs on the
    # machine that will execute the library (the repo ships no .so).
    subprocess.run(
        ["make", "-s", "-C", os.path.abspath(_NATIVE_DIR), "TUNE=native"],
        check=True,
        capture_output=True,
    )


def _load() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    with _build_lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_LIB_PATH):
            _build_library()
        lib = ctypes.CDLL(_LIB_PATH)
        _declare(lib)
        _lib = lib
    return _lib


def _declare(lib: ctypes.CDLL) -> None:
    P = ctypes.c_void_p
    lib.tdr_last_error.restype = ctypes.c_char_p
    lib.tdr_copy_pool_workers.restype = ctypes.c_size_t
    lib.tdr_copy_counters.argtypes = [ctypes.POINTER(ctypes.c_uint64),
                                      ctypes.POINTER(ctypes.c_uint64)]
    lib.tdr_engine_open.restype = P
    lib.tdr_engine_open.argtypes = [ctypes.c_char_p]
    lib.tdr_engine_close.argtypes = [P]
    lib.tdr_engine_kind.restype = ctypes.c_int
    lib.tdr_engine_kind.argtypes = [P]
    lib.tdr_engine_name.restype = ctypes.c_char_p
    lib.tdr_engine_name.argtypes = [P]
    lib.tdr_engine_set_qp_limit.restype = None
    lib.tdr_engine_set_qp_limit.argtypes = [P, ctypes.c_int]
    lib.tdr_engine_qp_limit.restype = ctypes.c_int
    lib.tdr_engine_qp_limit.argtypes = [P]
    lib.tdr_engine_qp_live.restype = ctypes.c_int
    lib.tdr_engine_qp_live.argtypes = [P]
    lib.tdr_reg_mr.restype = P
    lib.tdr_reg_mr.argtypes = [P, P, ctypes.c_size_t, ctypes.c_int]
    lib.tdr_reg_dmabuf_mr.restype = P
    lib.tdr_reg_dmabuf_mr.argtypes = [
        P, ctypes.c_int, ctypes.c_size_t, ctypes.c_size_t,
        ctypes.c_uint64, ctypes.c_int,
    ]
    lib.tdr_dereg_mr.argtypes = [P]
    for fn in ("tdr_mr_lkey", "tdr_mr_rkey"):
        getattr(lib, fn).restype = ctypes.c_uint32
        getattr(lib, fn).argtypes = [P]
    for fn in ("tdr_mr_addr", "tdr_mr_len"):
        getattr(lib, fn).restype = ctypes.c_uint64
        getattr(lib, fn).argtypes = [P]
    lib.tdr_mr_invalidate.argtypes = [P]
    lib.tdr_listen.restype = P
    lib.tdr_listen.argtypes = [P, ctypes.c_char_p, ctypes.c_int]
    lib.tdr_listen_timeout.restype = P
    lib.tdr_listen_timeout.argtypes = [P, ctypes.c_char_p, ctypes.c_int,
                                       ctypes.c_int]
    lib.tdr_listen_tier.restype = P
    lib.tdr_listen_tier.argtypes = [P, ctypes.c_char_p, ctypes.c_int,
                                    ctypes.c_int, ctypes.c_int]
    lib.tdr_connect_tier.restype = P
    lib.tdr_connect_tier.argtypes = [P, ctypes.c_char_p, ctypes.c_int,
                                     ctypes.c_int, ctypes.c_int]
    lib.tdr_fault_plan_clauses.restype = ctypes.c_int
    lib.tdr_fault_plan_hits.restype = ctypes.c_uint64
    lib.tdr_fault_plan_hits.argtypes = [ctypes.c_int]
    lib.tdr_fault_plan_seen.restype = ctypes.c_uint64
    lib.tdr_fault_plan_seen.argtypes = [ctypes.c_int]
    lib.tdr_fault_plan_reset.restype = None
    lib.tdr_crc32c.restype = ctypes.c_uint32
    lib.tdr_crc32c.argtypes = [P, ctypes.c_size_t, ctypes.c_uint32]
    lib.tdr_seal_counters.restype = None
    lib.tdr_seal_counters.argtypes = [ctypes.POINTER(ctypes.c_uint64)]
    lib.tdr_seal_counters_reset.restype = None
    lib.tdr_seal_retry_budget.restype = ctypes.c_int
    lib.tdr_seal_context.restype = None
    lib.tdr_seal_context.argtypes = [P, ctypes.c_uint64, ctypes.c_uint64]
    lib.tdr_qp_has_seal.restype = ctypes.c_int
    lib.tdr_qp_has_seal.argtypes = [P]
    lib.tdr_connect.restype = P
    lib.tdr_connect.argtypes = [P, ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
    lib.tdr_qp_close.argtypes = [P]
    lib.tdr_post_write.argtypes = [
        P, P, ctypes.c_size_t, ctypes.c_uint64, ctypes.c_uint32,
        ctypes.c_size_t, ctypes.c_uint64,
    ]
    lib.tdr_post_read.argtypes = lib.tdr_post_write.argtypes
    lib.tdr_post_send.argtypes = [
        P, P, ctypes.c_size_t, ctypes.c_size_t, ctypes.c_uint64,
    ]
    lib.tdr_post_recv.argtypes = lib.tdr_post_send.argtypes
    lib.tdr_post_recv_reduce.argtypes = [
        P, P, ctypes.c_size_t, ctypes.c_size_t, ctypes.c_int, ctypes.c_int,
        ctypes.c_uint64,
    ]
    lib.tdr_qp_has_recv_reduce.restype = ctypes.c_int
    lib.tdr_qp_has_recv_reduce.argtypes = [P]
    lib.tdr_post_send_foldback.argtypes = lib.tdr_post_send.argtypes
    lib.tdr_qp_has_send_foldback.restype = ctypes.c_int
    lib.tdr_qp_has_send_foldback.argtypes = [P]
    lib.tdr_poll.restype = ctypes.c_int
    lib.tdr_poll.argtypes = [P, ctypes.POINTER(Wc), ctypes.c_int, ctypes.c_int]
    lib.tdr_ring_create.restype = P
    lib.tdr_ring_create.argtypes = [P, P, P, ctypes.c_int, ctypes.c_int]
    lib.tdr_ring_create_channels.restype = P
    lib.tdr_ring_create_channels.argtypes = [
        P, ctypes.POINTER(P), ctypes.POINTER(P), ctypes.c_int,
        ctypes.c_int, ctypes.c_int,
    ]
    lib.tdr_ring_channels.restype = ctypes.c_int
    lib.tdr_ring_channels.argtypes = [P]
    lib.tdr_ring_chunk_bytes.restype = ctypes.c_size_t
    lib.tdr_ring_chunk_bytes.argtypes = []
    lib.tdr_ring_set_coll.restype = None
    lib.tdr_ring_set_coll.argtypes = [P, ctypes.c_uint64]
    lib.tdr_fold_pool_workers.restype = ctypes.c_size_t
    lib.tdr_qp_has_seal_payload.restype = ctypes.c_int
    lib.tdr_qp_has_seal_payload.argtypes = [P]
    lib.tdr_qp_has_coll_id.restype = ctypes.c_int
    lib.tdr_qp_has_coll_id.argtypes = [P]
    lib.tdr_qp_has_wire_q8.restype = ctypes.c_int
    lib.tdr_qp_has_wire_q8.argtypes = [P]
    lib.tdr_qp_probe.restype = ctypes.c_int
    lib.tdr_qp_probe.argtypes = [P, ctypes.c_int]
    lib.tdr_qp_set_link.restype = None
    lib.tdr_qp_set_link.argtypes = [P, ctypes.c_int, ctypes.c_int,
                                    ctypes.c_int]
    lib.tdr_ring_register.restype = ctypes.c_int
    lib.tdr_ring_register.argtypes = [P, P, ctypes.c_size_t]
    lib.tdr_ring_unregister.restype = ctypes.c_int
    lib.tdr_ring_unregister.argtypes = [P, P]
    lib.tdr_ring_adopt_mr.restype = ctypes.c_int
    lib.tdr_ring_adopt_mr.argtypes = [P, P, P]
    lib.tdr_qp_has_fused2.restype = ctypes.c_int
    lib.tdr_qp_has_fused2.argtypes = [P]
    lib.tdr_qp_rr_window.restype = ctypes.c_size_t
    lib.tdr_qp_rr_window.argtypes = [P]
    lib.tdr_ring_last_schedule.restype = ctypes.c_int
    lib.tdr_ring_last_schedule.argtypes = [P]
    lib.tdr_ring_allreduce.restype = ctypes.c_int
    lib.tdr_ring_allreduce.argtypes = [
        P, P, ctypes.c_size_t, ctypes.c_int, ctypes.c_int,
    ]
    lib.tdr_ring_start.restype = P
    lib.tdr_ring_start.argtypes = [
        P, P, ctypes.c_size_t, ctypes.c_int, ctypes.c_int,
    ]
    lib.tdr_ring_start_reduce_scatter.restype = P
    lib.tdr_ring_start_reduce_scatter.argtypes = \
        lib.tdr_ring_start.argtypes
    lib.tdr_ring_start_all_gather.restype = P
    lib.tdr_ring_start_all_gather.argtypes = [
        P, P, ctypes.c_size_t, ctypes.c_int,
    ]
    lib.tdr_ring_allreduce_q8.restype = ctypes.c_int
    lib.tdr_ring_allreduce_q8.argtypes = [
        P, P, ctypes.c_size_t, ctypes.c_float, P,
    ]
    lib.tdr_ring_start_q8.restype = P
    lib.tdr_ring_start_q8.argtypes = [
        P, P, ctypes.c_size_t, ctypes.c_float, P,
    ]
    lib.tdr_ring_owned_segment.restype = ctypes.c_int
    lib.tdr_ring_owned_segment.argtypes = [
        P, ctypes.c_size_t, ctypes.c_int,
        ctypes.POINTER(ctypes.c_size_t), ctypes.POINTER(ctypes.c_size_t),
    ]
    lib.tdr_ring_test.restype = ctypes.c_int
    lib.tdr_ring_test.argtypes = [P]
    lib.tdr_ring_wait.restype = ctypes.c_int
    lib.tdr_ring_wait.argtypes = [P, ctypes.c_int]
    lib.tdr_ring_op_error.restype = ctypes.c_char_p
    lib.tdr_ring_op_error.argtypes = [P]
    lib.tdr_ring_op_done.restype = ctypes.c_int
    lib.tdr_ring_op_done.argtypes = [P]
    lib.tdr_ring_op_free.restype = None
    lib.tdr_ring_op_free.argtypes = [P]
    lib.tdr_ring_reduce_scatter.restype = ctypes.c_int
    lib.tdr_ring_reduce_scatter.argtypes = [
        P, P, ctypes.c_size_t, ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_size_t), ctypes.POINTER(ctypes.c_size_t),
    ]
    lib.tdr_ring_all_gather.restype = ctypes.c_int
    lib.tdr_ring_all_gather.argtypes = [
        P, P, ctypes.c_size_t, ctypes.c_int,
    ]
    lib.tdr_ring_broadcast.restype = ctypes.c_int
    lib.tdr_ring_broadcast.argtypes = [
        P, P, ctypes.c_size_t, ctypes.c_int,
    ]
    lib.tdr_ring_reduce.restype = ctypes.c_int
    lib.tdr_ring_reduce.argtypes = [
        P, P, ctypes.c_size_t, ctypes.c_int, ctypes.c_int, ctypes.c_int,
    ]
    lib.tdr_ring_alltoall.restype = ctypes.c_int
    lib.tdr_ring_alltoall.argtypes = [
        P, P, ctypes.c_size_t, ctypes.c_int,
    ]
    lib.tdr_ring_destroy.argtypes = [P]
    # Flight recorder (telemetry.cc): event ring, histograms, and the
    # unified counter registry.
    lib.tdr_tel_enabled.restype = ctypes.c_int
    lib.tdr_tel_reset.restype = None
    lib.tdr_tel_now_ns.restype = ctypes.c_uint64
    lib.tdr_tel_drain.restype = ctypes.c_int
    lib.tdr_tel_drain.argtypes = [ctypes.POINTER(TelEventC), ctypes.c_int]
    lib.tdr_tel_recorded.restype = ctypes.c_uint64
    lib.tdr_tel_dropped.restype = ctypes.c_uint64
    lib.tdr_tel_event_name.restype = ctypes.c_char_p
    lib.tdr_tel_event_name.argtypes = [ctypes.c_int]
    lib.tdr_tel_hist_count.restype = ctypes.c_int
    lib.tdr_tel_hist_name.restype = ctypes.c_char_p
    lib.tdr_tel_hist_name.argtypes = [ctypes.c_int]
    lib.tdr_tel_hist_read.restype = None
    lib.tdr_tel_hist_read.argtypes = [ctypes.c_int,
                                      ctypes.POINTER(ctypes.c_uint64)]
    lib.tdr_tel_hist_fine_buckets.restype = ctypes.c_int
    lib.tdr_tel_hist_fine_upper.restype = ctypes.c_uint64
    lib.tdr_tel_hist_fine_upper.argtypes = [ctypes.c_int]
    lib.tdr_tel_hist_read_fine.restype = ctypes.c_int
    lib.tdr_tel_hist_read_fine.argtypes = [
        ctypes.c_int, ctypes.POINTER(ctypes.c_uint64), ctypes.c_int,
    ]
    lib.tdr_progress_shards.restype = ctypes.c_int
    lib.tdr_progress_shards.argtypes = [ctypes.c_int]
    lib.tdr_tel_engine_id.restype = ctypes.c_int
    lib.tdr_tel_engine_id.argtypes = [P]
    lib.tdr_tel_qp_id.restype = ctypes.c_int
    lib.tdr_tel_qp_id.argtypes = [P]
    lib.tdr_counter_count.restype = ctypes.c_int
    lib.tdr_counter_name.restype = ctypes.c_char_p
    lib.tdr_counter_name.argtypes = [ctypes.c_int]
    lib.tdr_counters_read.restype = ctypes.c_int
    lib.tdr_counters_read.argtypes = [ctypes.POINTER(ctypes.c_uint64),
                                      ctypes.c_int]


# Completion statuses that signal a TRANSIENT condition — a peer died
# or a connection dropped (flush), a wedge/injected fault (general),
# or detected-and-uncorrectable payload corruption (integrity — the
# per-chunk retransmit budget already failed to heal it, so the next
# rung of the ladder is a world rebuild): the world can be rebuilt and
# the operation retried. Access errors (REM/LOC) are
# lifetime/programming bugs; retrying cannot fix them.
_RETRYABLE_STATUSES = frozenset({WC_FLUSH_ERR, WC_GENERAL_ERR,
                                 WC_INTEGRITY_ERR})
_WC_STATUS_RE = re.compile(r"status (\d+)")
# Message markers for error paths that carry no WC status: stalls and
# connection loss are transient; everything unrecognized is fatal by
# default (recovery must be opted into by evidence, not guessed).
_RETRYABLE_MARKERS = (
    "timeout",            # poll/accept/connect deadlines — a wedge
    "connection down",    # post against a dead QP
    "fault injected",     # TDR_FAULT_PLAN transient
    "stale ring generation",  # fenced previous-incarnation traffic
    "never connected",    # rendezvous peer missing
    "ring destroyed",     # teardown raced a pending async handle
    "deadline exceeded",  # hard per-collective deadline
    "peer hung",          # probe sent, no pong — wedged peer process
)


def _classify_retryable(message: str, status: Optional[int]) -> bool:
    if status is not None:
        return status in _RETRYABLE_STATUSES
    low = message.lower()
    return any(marker in low for marker in _RETRYABLE_MARKERS)


class TransportError(RuntimeError):
    """Transport failure with an error taxonomy.

    ``status`` is the WC status the failure surfaced with (parsed from
    the native message when not passed explicitly); ``retryable`` says
    whether the condition is transient — peer death, connection drop,
    stall deadline, injected fault — i.e. whether tearing the world
    down and rebuilding it (``RingWorld.rebuild``) can succeed. Access
    errors, schedule mismatches, and misuse are fatal: ``retryable``
    is False and the elastic layer re-raises them.
    """

    def __init__(self, message: str, status: Optional[int] = None,
                 retryable: Optional[bool] = None):
        super().__init__(message)
        text = str(message)
        if status is None:
            m = _WC_STATUS_RE.search(text)
            if m:
                status = int(m.group(1))
        self.status = status
        self.retryable = (_classify_retryable(text, status)
                          if retryable is None else bool(retryable))

    @property
    def kind(self) -> str:
        """Coarse failure class: ``"integrity"`` for detected payload
        corruption / stale-incarnation fences (retryable via the
        elastic ladder), ``"hung"`` for a peer that stopped answering
        probes while its connection stayed up (distinct from a
        conn-drop: the process exists but is wedged — postmortems
        should look at the PEER, not the wire), ``"transport"`` for
        everything else."""
        low = str(self).lower()
        if self.status == WC_INTEGRITY_ERR or "integrity" in low:
            return "integrity"
        if "peer hung" in low:
            return "hung"
        return "transport"


def copy_pool_workers() -> int:
    """Worker count of the native parallel copy/reduce pool (the
    emulated NIC's DMA-engine array; TDR_COPY_THREADS overrides)."""
    return int(_load().tdr_copy_pool_workers())


def fold_pool_workers() -> int:
    """Worker count of the fold-offload pool (TDR_FOLD_THREADS): the
    threads that run the ring's scratch-window folds off the poll
    loop. 0 = folds run inline (1-core hosts or the knob set to 0)."""
    return int(_load().tdr_fold_pool_workers())


def ring_chunk_bytes() -> int:
    """EFFECTIVE ring chunk size in bytes (TDR_RING_CHUNK override or
    the native built-in default) — the value schedule digests hash:
    the raw env string would hide a changed built-in default."""
    return int(_load().tdr_ring_chunk_bytes())


def ring_channels_default() -> int:
    """The channel count RingWorld uses when TDR_RING_CHANNELS is
    unset (clamped to [1, 16])."""
    env = os.environ.get("TDR_RING_CHANNELS", "")
    try:
        v = int(env) if env else 4
    except ValueError:
        v = 4
    return max(1, min(v, 16))


def progress_shards(channels: Optional[int] = None) -> int:
    """Resolved progress-shard count for a ring with ``channels``
    channels, as the NATIVE layer parses TDR_PROGRESS_SHARDS (the
    schedule digest never carries this — progress sharding is
    per-process execution strategy). 0 = the legacy single-poll loop
    (forced by TDR_PROGRESS_SHARDS=0, and the default on 1-core
    hosts); otherwise one dedicated poll thread per channel group."""
    ch = ring_channels_default() if channels is None else int(channels)
    return int(_load().tdr_progress_shards(ch))


def copy_counters() -> Tuple[int, int]:
    """(nt_bytes, plain_bytes) moved via the streaming vs cached copy
    tiers since process start — which path carried the traffic."""
    nt = ctypes.c_uint64()
    plain = ctypes.c_uint64()
    _load().tdr_copy_counters(ctypes.byref(nt), ctypes.byref(plain))
    return int(nt.value), int(plain.value)


# ------------------------------------------------------------------
# Flight recorder (native telemetry.cc): raw ctypes surface. The
# ergonomic API — merged native+Python timelines, Perfetto export,
# histogram percentiles — lives in rocnrdma_tpu.telemetry.

def telemetry_enabled() -> bool:
    """Whether the native flight recorder is recording (TDR_TELEMETRY
    as the engine parsed it — one branch per event site when off)."""
    return bool(_load().tdr_tel_enabled())


def telemetry_reset() -> None:
    """Re-read TDR_TELEMETRY / TDR_TELEMETRY_RING and clear the native
    ring, histograms, and recorded/dropped counts (set the env, then
    call this — the tdr_fault_plan_reset idiom)."""
    _load().tdr_tel_reset()


def telemetry_now_ns() -> int:
    """The recorder's clock (CLOCK_MONOTONIC ns) — the same clock
    Python's time.monotonic() reads on Linux, anchoring the merged
    timeline's single clock domain."""
    return int(_load().tdr_tel_now_ns())


def telemetry_recorded() -> int:
    return int(_load().tdr_tel_recorded())


def telemetry_dropped() -> int:
    return int(_load().tdr_tel_dropped())


def telemetry_event_name(ev_type: int) -> str:
    return _load().tdr_tel_event_name(ev_type).decode()


def telemetry_drain(max_events: int = 65536) -> List[TelEventC]:
    """Remove up to ``max_events`` events from the native ring, oldest
    first (raw structs; rocnrdma_tpu.telemetry wraps them)."""
    lib = _load()
    out: List[TelEventC] = []
    batch = 4096
    while len(out) < max_events:
        want = min(batch, max_events - len(out))
        arr = (TelEventC * want)()
        n = lib.tdr_tel_drain(arr, want)
        out.extend(arr[i] for i in range(n))
        if n < want:
            break
    return out


def telemetry_histograms() -> dict:
    """All native histograms in the legacy 64-octave view: name -> 64
    bucket counts (bucket b counts values in [2^(b-1), 2^b); bucket 0
    zeros). Derived by folding the fine rows — percentile consumers
    should use ``telemetry_histograms_fine`` for sub-octave
    resolution."""
    lib = _load()
    out = {}
    for i in range(int(lib.tdr_tel_hist_count())):
        buckets = (ctypes.c_uint64 * 64)()
        lib.tdr_tel_hist_read(i, buckets)
        out[lib.tdr_tel_hist_name(i).decode()] = [int(v) for v in buckets]
    return out


def telemetry_hist_fine_buckets() -> int:
    """Length of a fine (log2 × 8) histogram row."""
    return int(_load().tdr_tel_hist_fine_buckets())


def telemetry_hist_fine_upper(idx: int) -> int:
    """Inclusive upper edge of fine bucket ``idx`` — read from the
    native layer so Python percentile estimates can never drift from
    the recorder's bucket assignment."""
    return int(_load().tdr_tel_hist_fine_upper(idx))


def telemetry_histograms_fine() -> dict:
    """All native histograms at fine (log2 × 8) resolution: name ->
    TDR_HIST_FINE_BUCKETS counts, 8 linear sub-buckets per octave
    (values 0..15 exact) — relative quantization error <= 12.5%, so
    percentile estimates are real numbers, not octave edges."""
    lib = _load()
    n = int(lib.tdr_tel_hist_fine_buckets())
    out = {}
    for i in range(int(lib.tdr_tel_hist_count())):
        buckets = (ctypes.c_uint64 * n)()
        lib.tdr_tel_hist_read_fine(i, buckets, n)
        out[lib.tdr_tel_hist_name(i).decode()] = [int(v) for v in buckets]
    return out


_counter_names: List[str] = []


def native_counters() -> dict:
    """One snapshot of the unified native counter registry
    (integrity.*, fault.*, copy.*, telemetry.*) — a single native
    call, so delta accounting has no multi-call double-count window.
    Counters sharing a producer (fault seen/hits, copy tiers) are
    read in one pass natively; cross-subsystem counters are
    individually-atomic monotonic reads."""
    lib = _load()
    global _counter_names
    if not _counter_names:
        _counter_names = [lib.tdr_counter_name(i).decode()
                          for i in range(int(lib.tdr_counter_count()))]
    arr = (ctypes.c_uint64 * len(_counter_names))()
    n = lib.tdr_counters_read(arr, len(_counter_names))
    return {name: int(arr[i]) for i, name in enumerate(_counter_names[:n])}


# ------------------------------------------------------------------
# Fault-plan introspection (TDR_FAULT_PLAN, native fault.cc): tests and
# the recovery layer read per-clause hit counters so an injected fault
# is OBSERVABLE — asserted, traced, never assumed.

_fault_hits_noted = [0]


def fault_plan_clauses() -> int:
    """Number of parsed TDR_FAULT_PLAN clauses (0 = no plan)."""
    return int(_load().tdr_fault_plan_clauses())


def fault_plan_hits(idx: int) -> int:
    """Times clause ``idx`` fired (injected its action)."""
    return int(_load().tdr_fault_plan_hits(idx))


def fault_plan_seen(idx: int) -> int:
    """Site arrivals clause ``idx`` matched (fired or not)."""
    return int(_load().tdr_fault_plan_seen(idx))


def fault_plan_reset() -> None:
    """Re-parse TDR_FAULT_PLAN from the environment, zeroing every
    counter (tests set the env var, then call this)."""
    _load().tdr_fault_plan_reset()
    _fault_hits_noted[0] = 0


def note_fault_injections() -> int:
    """Emit a ``fault.injected`` trace event for hits since the last
    call (the recovery path calls this so injected faults show up in
    the same observable stream as ``world.rebuild``/``trainer.resume``).
    Returns the number of new hits. Reads the native counter registry
    — one snapshot, not a per-clause poll loop."""
    with _note_lock:
        total = native_counters()["fault.hits"]
        new = total - _fault_hits_noted[0]
        if new > 0:
            _fault_hits_noted[0] = total
            trace.event("fault.injected", hits=new, total=total)
        return max(new, 0)


# ------------------------------------------------------------------
# Sealed-chunk integrity introspection: CRC32C for tests, the native
# sealed/verified/failed/retransmitted counters, and their bridge into
# the tracer's ``integrity.*`` namespace.

def crc32c(data: bytes, seed: int = 0) -> int:
    """CRC32C (Castagnoli) of ``data``; pass the previous return value
    as ``seed`` to extend a running checksum."""
    return int(_load().tdr_crc32c(data, len(data), seed))


_SEAL_COUNTER_NAMES = ("sealed", "verified", "failed", "retransmitted")


def seal_counters() -> dict:
    """Process-wide integrity counters: frames sealed at send,
    landings verified ok, verification failures, retransmissions."""
    arr = (ctypes.c_uint64 * 4)()
    _load().tdr_seal_counters(arr)
    return dict(zip(_SEAL_COUNTER_NAMES, (int(v) for v in arr)))


def seal_counters_reset() -> None:
    _load().tdr_seal_counters_reset()
    with _note_lock:
        _integrity_noted.clear()
        _integrity_noted.update({k: 0 for k in _SEAL_COUNTER_NAMES})


def seal_retry_budget() -> int:
    """The per-chunk retransmit budget AS THE ENGINE PARSES IT
    (TDR_SEAL_RETRY, default 3) — the one source of truth the schedule
    digest records."""
    return int(_load().tdr_seal_retry_budget())


_integrity_noted = {k: 0 for k in _SEAL_COUNTER_NAMES}
# Serializes the delta accounting of note_integrity and
# note_fault_injections: the old poll-then-add bridge could run the
# native read and the noted-state update in two racing callers and
# double-count a window of increments into the tracer.
_note_lock = threading.Lock()


def note_integrity() -> dict:
    """Fold native integrity-counter deltas since the last call into
    the tracer as ``integrity.sealed`` / ``integrity.verified`` /
    ``integrity.failed`` / ``integrity.retransmitted`` — the recovery
    path and tests observe the whole detect→retransmit ladder in the
    same stream as ``world.rebuild``/``trainer.resume``. Returns the
    deltas. Reads the unified native counter registry: one snapshot
    call under one lock, so concurrent callers cannot double-count
    (the poll-bridge race this replaced)."""
    with _note_lock:
        snap = native_counters()
        deltas = {}
        for k in _SEAL_COUNTER_NAMES:
            v = snap[f"integrity.{k}"]
            d = v - _integrity_noted.get(k, 0)
            if d > 0:
                trace.add(f"integrity.{k}", d)
            deltas[k] = max(d, 0)
            _integrity_noted[k] = v
        return deltas


def _check(cond, what: str):
    if not cond:
        err = _load().tdr_last_error().decode()
        # The native layer already labels its errors; avoid doubling
        # the prefix when it does.
        if err and err.split(":")[0] in what:
            raise TransportError(err)
        raise TransportError(f"{what}: {err or 'unknown error'}")


def _live(handle, what: str):
    if not handle:
        raise TransportError(f"{what}: object already closed")
    return handle


@dataclass(frozen=True)
class Completion:
    wr_id: int
    status: int
    opcode: int
    length: int

    @property
    def ok(self) -> bool:
        return self.status == WC_SUCCESS


class MemoryRegion:
    """A registered memory region. Mirrors the lifetime the reference
    front-loads into ``ibv_reg_mr`` (SURVEY.md §3.2): after creation,
    transfers touching it involve no registration-layer software."""

    def __init__(self, engine: "Engine", handle: int):
        self._engine = engine
        self._h = handle

    @property
    def lkey(self) -> int:
        return _load().tdr_mr_lkey(_live(self._h, "mr.lkey"))

    @property
    def rkey(self) -> int:
        return _load().tdr_mr_rkey(_live(self._h, "mr.rkey"))

    @property
    def addr(self) -> int:
        return _load().tdr_mr_addr(_live(self._h, "mr.addr"))

    @property
    def length(self) -> int:
        return _load().tdr_mr_len(_live(self._h, "mr.length"))

    def invalidate(self) -> None:
        """Revoke remote access (the free-while-registered flow,
        amdp2p.c:88-109). Safe to call multiple times; dereg after
        invalidate is also safe (amdp2p.c:299-302 semantics)."""
        if self._h:
            rkey = self.rkey
            _load().tdr_mr_invalidate(self._h)
            trace.event("mr.invalidate", rkey=rkey)

    def deregister(self) -> None:
        if self._h:
            h, self._h = self._h, None
            _load().tdr_dereg_mr(h)
            trace.event("mr.dereg")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.deregister()


class QueuePair:
    def __init__(self, engine: "Engine", handle: int):
        self._engine = engine
        self._h = handle

    def post_write(self, mr: MemoryRegion, loff: int, raddr: int, rkey: int,
                   length: int, wr_id: int = 0) -> None:
        rc = _load().tdr_post_write(_live(self._h, "post_write"),
                                    _live(mr._h, "post_write mr"), loff,
                                    raddr, rkey, length, wr_id)
        _check(rc == 0, "post_write")

    def post_read(self, mr: MemoryRegion, loff: int, raddr: int, rkey: int,
                  length: int, wr_id: int = 0) -> None:
        rc = _load().tdr_post_read(_live(self._h, "post_read"),
                                   _live(mr._h, "post_read mr"), loff,
                                   raddr, rkey, length, wr_id)
        _check(rc == 0, "post_read")

    def post_send(self, mr: MemoryRegion, loff: int, length: int,
                  wr_id: int = 0) -> None:
        rc = _load().tdr_post_send(_live(self._h, "post_send"),
                                   _live(mr._h, "post_send mr"), loff,
                                   length, wr_id)
        _check(rc == 0, "post_send")

    def post_recv(self, mr: MemoryRegion, loff: int, maxlen: int,
                  wr_id: int = 0) -> None:
        rc = _load().tdr_post_recv(_live(self._h, "post_recv"),
                                   _live(mr._h, "post_recv mr"), loff,
                                   maxlen, wr_id)
        _check(rc == 0, "post_recv")

    def post_recv_reduce(self, mr: MemoryRegion, loff: int, maxlen: int,
                         dtype: int, red_op: int = RED_SUM,
                         wr_id: int = 0) -> None:
        """Fused reduce-on-receive: the inbound SEND payload is folded
        into the buffer (dst op= src) by the progress engine —
        capability-gated (``has_recv_reduce``)."""
        rc = _load().tdr_post_recv_reduce(
            _live(self._h, "post_recv_reduce"),
            _live(mr._h, "post_recv_reduce mr"), loff, maxlen, dtype,
            red_op, wr_id)
        _check(rc == 0, "post_recv_reduce")

    def post_send_foldback(self, mr: MemoryRegion, loff: int, length: int,
                           wr_id: int = 0) -> None:
        """Fold-and-write-back send: the peer folds this payload into
        its matched reduce-recv buffer and the folded result lands
        back in place over [loff, loff+length); the send completion
        means the exchange is finished on both sides."""
        rc = _load().tdr_post_send_foldback(
            _live(self._h, "post_send_foldback"),
            _live(mr._h, "post_send_foldback mr"), loff, length, wr_id)
        _check(rc == 0, "post_send_foldback")

    @property
    def has_recv_reduce(self) -> bool:
        return bool(_load().tdr_qp_has_recv_reduce(
            _live(self._h, "has_recv_reduce")))

    @property
    def has_send_foldback(self) -> bool:
        return bool(_load().tdr_qp_has_send_foldback(
            _live(self._h, "has_send_foldback")))

    @property
    def has_fused2(self) -> bool:
        """Both ends negotiated the world-2 fused exchange schedule."""
        return bool(_load().tdr_qp_has_fused2(
            _live(self._h, "has_fused2")))

    @property
    def has_seal(self) -> bool:
        """Both ends negotiated sealed payload framing (CRC32C +
        incarnation tag, NAK-driven chunk retransmit). Emu-only; the
        verbs wire carries its own ICRC."""
        return bool(_load().tdr_qp_has_seal(_live(self._h, "has_seal")))

    @property
    def has_seal_payload(self) -> bool:
        """Whether the negotiated seal's CRC covers the PAYLOAD bytes:
        always on the TCP stream tier; on the CMA tier only when both
        ends set TDR_SEAL_CMA=1 (the default there is tag-only — the
        kernel-memcpy \"wire\" has no payload bit-flip failure mode,
        the same rationale as the verbs backend's ICRC stance)."""
        return bool(_load().tdr_qp_has_seal_payload(
            _live(self._h, "has_seal_payload")))

    @property
    def has_coll_id(self) -> bool:
        """Both ends negotiated wire-carried collective trace ids
        (FEAT_COLL_ID): frame headers carry the posting rank's coll id
        so the peer's telemetry events join by key. Advertised only
        when TDR_TELEMETRY was on at handshake time — with the feature
        off, frames are byte-identical to the pre-trace-id format."""
        return bool(_load().tdr_qp_has_coll_id(
            _live(self._h, "has_coll_id")))

    @property
    def has_wire_q8(self) -> bool:
        """Both ends negotiated int8 wire compression (FEAT_WIRE_Q8):
        the ring may run the quantized scale-carrying schedule
        (``Ring.allreduce_q8``) over this link. The compressed pieces
        are ordinary sealed SEND payloads — frames are byte-identical
        with the feature off; the bit gates the SCHEDULE and lets the
        health ladder query per-link int8 capability. TDR_NO_WIRE_Q8
        suppresses the advertisement."""
        return bool(_load().tdr_qp_has_wire_q8(
            _live(self._h, "has_wire_q8")))

    @property
    def telemetry_id(self) -> int:
        """Flight-recorder track id of this QP (bring-up ordinal;
        names the per-QP timeline in Perfetto exports)."""
        return int(_load().tdr_tel_qp_id(_live(self._h, "telemetry_id")))

    def probe(self, timeout_ms: int = 1000) -> int:
        """Hung-peer probe: PING the peer's progress thread and wait
        for the echoed PONG. Returns 1 (peer alive — it drained its
        socket even if the collective is stalled), 0 (no pong inside
        the window — peer hung), -1 (connection down), or -2 (probing
        not negotiated: legacy peer or TDR_NO_PROBE — wire frames stay
        byte-identical with the feature off)."""
        return int(_load().tdr_qp_probe(_live(self._h, "probe"),
                                        int(timeout_ms)))

    def set_link(self, lane: int, rank: int, peer: int) -> None:
        """Stamp link identity (channel lane, local rank, peer rank)
        onto this QP: netem fault riders scope by these labels and
        stall attribution reports them. Ring bring-up stamps them
        natively; this is for QPs used outside a ring."""
        _load().tdr_qp_set_link(_live(self._h, "set_link"),
                                int(lane), int(rank), int(peer))

    def poll(self, max_wc: int = 16, timeout_ms: int = -1) -> List[Completion]:
        arr = (Wc * max_wc)()
        n = _load().tdr_poll(_live(self._h, "poll"), arr, max_wc, timeout_ms)
        _check(n >= 0, "poll")
        return [
            Completion(arr[i].wr_id, arr[i].status, arr[i].opcode, arr[i].len)
            for i in range(n)
        ]

    def wait(self, wr_id: int, timeout_ms: int = 10000) -> Completion:
        """Poll until the completion for wr_id arrives; other
        completions raise (protocol error in simple callers)."""
        got = self.poll(max_wc=1, timeout_ms=timeout_ms)
        if not got:
            raise TransportError(f"timeout waiting for wr_id={wr_id}")
        if got[0].wr_id != wr_id:
            raise TransportError(
                f"unexpected completion wr_id={got[0].wr_id}, want {wr_id}")
        return got[0]

    def close(self) -> None:
        if self._h:
            h, self._h = self._h, None
            _load().tdr_qp_close(h)
            trace.event("qp.close")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class RingOp:
    """Handle for one nonblocking ring collective (``allreduce_async``).

    Holds a reference to the data buffer (the native op posts against
    it until completion). Failure is handle-scoped: ``wait``/``test``
    raise a :class:`TransportError` carrying the same status labels as
    the blocking API, so the retryable/fatal taxonomy — and the
    elastic rebuild ladder above it — applies unchanged."""

    def __init__(self, handle: int, array):
        self._h = handle
        self._array = array  # keep the buffer alive until completion

    @property
    def done(self) -> bool:
        """Completed (ok or failed) and released."""
        return self._h is None

    def test(self) -> bool:
        """True when the op completed OK (and releases the handle);
        False while still in flight; raises on a failed op."""
        if self._h is None:
            return True
        rc = _load().tdr_ring_test(self._h)
        if rc == 0:
            return False
        self._finish(rc)
        return True

    def wait(self, timeout_ms: int = -1) -> None:
        """Block until the op completes (forever by default — the
        collective's own stall deadline bounds a wedged ring). A
        positive timeout that expires raises a retryable timeout error
        and leaves the handle live (wait again or let close() reap)."""
        if self._h is None:
            return
        rc = _load().tdr_ring_wait(self._h, int(timeout_ms))
        if rc != 0:
            # Distinguish a wait TIMEOUT from an op FAILURE — and
            # re-check the op, not the error string: the collective
            # may have completed (either way) between the native wait
            # expiring and now, and reporting a completed-ok op as
            # failed would tear down a world whose peers all
            # succeeded.
            t = _load().tdr_ring_test(self._h)
            if t == 0:
                raise TransportError(
                    "timeout waiting for async collective "
                    "(still in flight)")
            rc = 0 if t > 0 else -1
        self._finish(rc)

    def _finish(self, rc: int) -> None:
        """Consume the completed op: free the native handle and raise
        the recorded, taxonomy-classified error on failure."""
        err = ""
        if rc != 0:
            err = _load().tdr_ring_op_error(self._h).decode() or \
                _load().tdr_last_error().decode() or "async collective failed"
        h, self._h = self._h, None
        self._array = None
        _load().tdr_ring_op_free(h)
        if rc != 0:
            raise TransportError(err)

    def __del__(self):
        # Backstop only: free a COMPLETED but never-consumed op.
        # A pending op is deliberately leaked here (op_free would
        # block GC until the collective terminates); ring destroy
        # fails pending ops promptly and close paths wait handles.
        # tdr_ring_op_done, NOT tdr_ring_test: a finalizer runs at an
        # arbitrary GC point and must never write the thread-local
        # error slot another native call is about to read.
        h = getattr(self, "_h", None)
        if h is not None and _load().tdr_ring_op_done(h):
            self._h = None
            _load().tdr_ring_op_free(h)


class Ring:
    """Native ring-allreduce context over neighbor QPs.

    ``left``/``right`` may each be a single QueuePair (the classic
    single-QP ring) or a sequence of QueuePairs — one per channel —
    in which case the striped schedules route chunk i over channel
    i % channels (``lefts[c]`` here must be connected to ``rights[c]``
    on the left neighbor; RingWorld's bootstrap guarantees it by
    bringing channels up in index order)."""

    def __init__(self, engine: "Engine", left, right, rank: int,
                 world: int):
        lefts = list(left) if isinstance(left, (list, tuple)) else [left]
        rights = (list(right) if isinstance(right, (list, tuple))
                  else [right])
        if len(lefts) != len(rights) or not lefts:
            raise TransportError("ring_create: mismatched channel lists")
        n = len(lefts)
        P = ctypes.c_void_p
        la = (P * n)(*[_live(q._h, "ring_create left") for q in lefts])
        ra = (P * n)(*[_live(q._h, "ring_create right") for q in rights])
        self._h = _load().tdr_ring_create_channels(engine._h, la, ra, n,
                                                   rank, world)
        _check(self._h, "ring_create")
        self.rank, self.world = rank, world

    @property
    def channels(self) -> int:
        """Channel count (independent QPs per neighbor) of this ring."""
        return int(_load().tdr_ring_channels(_live(self._h, "channels")))

    def set_coll(self, coll_id: int) -> None:
        """Stamp the collective trace id for the NEXT collective on
        this ring (blocking call or async start). The id tags every
        native telemetry event of that collective and rides the frame
        header to the peer when FEAT_COLL_ID was negotiated, making
        two ranks' events joinable by key in a merged fleet timeline.
        Observational only — never negotiated, never in the digest."""
        _load().tdr_ring_set_coll(_live(self._h, "ring_set_coll"),
                                  int(coll_id))

    def register_buffer(self, array) -> None:
        """Front-load MR registration for a buffer the caller promises
        stable for the ring's lifetime; subsequent allreduces on it do
        no registration work (the reference's zero-software-hot-path
        invariant). Unregistered buffers still work — registered per
        call."""
        rc = _load().tdr_ring_register(
            _live(self._h, "ring_register"), array.ctypes.data,
            array.nbytes)
        _check(rc == 0, "ring_register")

    def unregister_buffer(self, array) -> None:
        """Drop the front-loaded MR for a buffer registered with
        ``register_buffer`` (call before freeing the buffer)."""
        rc = _load().tdr_ring_unregister(
            _live(self._h, "ring_unregister"), array.ctypes.data)
        _check(rc == 0, "ring_unregister")

    def adopt_mr(self, addr: int, mr: MemoryRegion) -> None:
        """Adopt a caller-owned MR (typically a dma-buf MR over device
        memory with iova == addr) as the data MR for allreduces on
        ``addr`` — the zero-copy collective path. The ring never
        deregisters an adopted MR; call ``drop_buffer(addr)`` before
        invalidating or deregistering it."""
        rc = _load().tdr_ring_adopt_mr(
            _live(self._h, "ring_adopt_mr"), addr,
            _live(mr._h, "ring_adopt_mr mr"))
        _check(rc == 0, "ring_adopt_mr")

    def drop_buffer(self, addr: int) -> None:
        """Drop the cached MR for ``addr`` (registered or adopted) by
        raw address. Adopted MRs stay alive — ownership is the
        caller's."""
        rc = _load().tdr_ring_unregister(
            _live(self._h, "ring_unregister"), addr)
        _check(rc == 0, "ring_unregister")

    def allreduce(self, array, op: int = RED_SUM) -> None:
        """In-place allreduce of a C-contiguous numpy array (ctypes
        releases the GIL for the duration, so per-rank threads overlap)."""
        ptr, dt = self._array_args(array, "allreduce")
        rc = _load().tdr_ring_allreduce(_live(self._h, "ring_allreduce"),
                                        ptr, array.size, dt, op)
        _check(rc == 0, "ring_allreduce")

    def allreduce_async(self, array, op: int = RED_SUM) -> "RingOp":
        """Nonblocking allreduce: posts onto the ring's async driver
        and returns a :class:`RingOp` immediately. Ops execute strictly
        in submission order (the SPMD contract — every rank must start
        the same ops in the same order), bitwise-identical to the
        blocking call. The array must stay alive and untouched until
        the handle completes."""
        ptr, dt = self._array_args(array, "allreduce_async")
        h = _load().tdr_ring_start(_live(self._h, "ring_start"),
                                   ptr, array.size, dt, op)
        _check(h, "ring_start")
        return RingOp(h, array)

    def allreduce_q8(self, q8, scale: float, out) -> None:
        """int8 wire-compressed allreduce: ``q8`` (C-contiguous int8,
        scratch — destroyed) holds this rank's bucket quantized with
        the symmetric per-bucket ``scale`` (true value = q * scale;
        the caller computed scale = absmax/127 and keeps the
        error-feedback residual); ``out`` (float32, same element
        count) receives the dequantized sum, bitwise identical on
        every rank. Wire pieces are [f32 running scale][int8 segment]
        inside ordinary sealed payloads; requires FEAT_WIRE_Q8 on
        every channel QP (fails fast otherwise)."""
        ptr, _ = self._array_args(q8, "allreduce_q8")
        optr, _ = self._array_args(out, "allreduce_q8 out")
        if str(q8.dtype) != "int8" or str(out.dtype) != "float32":
            raise TransportError(
                "allreduce_q8 needs int8 q8 + float32 out")
        if out.size != q8.size:
            raise TransportError("allreduce_q8: q8/out size mismatch")
        rc = _load().tdr_ring_allreduce_q8(
            _live(self._h, "ring_allreduce_q8"), ptr, q8.size,
            float(scale), optr)
        _check(rc == 0, "ring_allreduce_q8")

    def allreduce_q8_async(self, q8, scale: float, out) -> "RingOp":
        """Nonblocking ``allreduce_q8`` on the same async driver (and
        submission-order SPMD contract) as ``allreduce_async``. BOTH
        buffers must stay alive and untouched until the handle
        completes (the RingOp pins them)."""
        ptr, _ = self._array_args(q8, "allreduce_q8_async")
        optr, _ = self._array_args(out, "allreduce_q8_async out")
        if str(q8.dtype) != "int8" or str(out.dtype) != "float32":
            raise TransportError(
                "allreduce_q8 needs int8 q8 + float32 out")
        if out.size != q8.size:
            raise TransportError("allreduce_q8: q8/out size mismatch")
        h = _load().tdr_ring_start_q8(
            _live(self._h, "ring_start_q8"), ptr, q8.size, float(scale),
            optr)
        _check(h, "ring_start_q8")
        return RingOp(h, (q8, out))

    def reduce_scatter_async(self, array, op: int = RED_SUM) -> "RingOp":
        """Nonblocking reduce-scatter on the same async driver (and
        under the same submission-order SPMD contract) as
        ``allreduce_async``. The ownership layout is the blocking
        call's; read the owned slice with ``owned_slice``."""
        ptr, dt = self._array_args(array, "reduce_scatter_async")
        h = _load().tdr_ring_start_reduce_scatter(
            _live(self._h, "ring_start_reduce_scatter"), ptr, array.size,
            dt, op)
        _check(h, "ring_start_reduce_scatter")
        return RingOp(h, array)

    def all_gather_async(self, array) -> "RingOp":
        """Nonblocking all-gather (the reduce-scatter's phase-2 twin)
        on the async driver; assumes the ownership layout
        ``reduce_scatter`` leaves."""
        ptr, dt = self._array_args(array, "all_gather_async")
        h = _load().tdr_ring_start_all_gather(
            _live(self._h, "ring_start_all_gather"), ptr, array.size, dt)
        _check(h, "ring_start_all_gather")
        return RingOp(h, array)

    def owned_slice(self, array) -> slice:
        """The flat-element slice this rank owns after a reduce-scatter
        of ``array`` — the native layout math (segment (rank+1) % world
        with remainder distribution), so async callers never re-derive
        it in Python."""
        _, dt = self._array_args(array, "owned_slice")
        off = ctypes.c_size_t()
        length = ctypes.c_size_t()
        rc = _load().tdr_ring_owned_segment(
            _live(self._h, "ring_owned_segment"), array.size, dt,
            ctypes.byref(off), ctypes.byref(length))
        _check(rc == 0, "ring_owned_segment")
        isz = array.itemsize
        return slice(off.value // isz, (off.value + length.value) // isz)

    def _array_args(self, array, what: str, need_dtype: bool = True):
        import numpy as np

        dt = _NUMPY_DTYPE_MAP.get(str(array.dtype))
        if dt is None and need_dtype:
            raise TransportError(f"unsupported dtype {array.dtype}")
        if not isinstance(array, np.ndarray) or \
                not array.flags["C_CONTIGUOUS"]:
            raise TransportError(f"{what} requires a C-contiguous "
                                 "numpy array")
        return array.ctypes.data, dt

    def reduce_scatter(self, array, op: int = RED_SUM) -> slice:
        """In-place ring reduce-scatter (the allreduce's phase 1).
        Returns the FLAT-element slice this rank owns afterwards — the
        fully-reduced segment (rank+1) % world; the rest of the buffer
        holds partial sums. The slice indexes ``array.reshape(-1)``
        (segmentation ignores dimensionality, exactly like allreduce's
        reduction does); apply it to the flat view, not to axis 0 of a
        multi-dimensional array. ``all_gather`` on the same buffer
        completes the allreduce."""
        ptr, dt = self._array_args(array, "reduce_scatter")
        own_off = ctypes.c_size_t()
        own_len = ctypes.c_size_t()
        rc = _load().tdr_ring_reduce_scatter(
            _live(self._h, "ring_reduce_scatter"), ptr, array.size, dt,
            op, ctypes.byref(own_off), ctypes.byref(own_len))
        _check(rc == 0, "ring_reduce_scatter")
        isz = array.itemsize
        return slice(own_off.value // isz,
                     (own_off.value + own_len.value) // isz)

    def all_gather(self, array) -> None:
        """In-place ring all-gather (the allreduce's phase 2):
        circulates each rank's owned segment — the (rank+1) % world
        layout ``reduce_scatter`` leaves — until every rank holds the
        full buffer."""
        ptr, dt = self._array_args(array, "all_gather")
        rc = _load().tdr_ring_all_gather(
            _live(self._h, "ring_all_gather"), ptr, array.size, dt)
        _check(rc == 0, "ring_all_gather")

    def all_to_all(self, array) -> None:
        """In-place MPI_Alltoall: ``array.reshape(-1)`` is ``world``
        equal segments — segment j is FOR rank j on entry and FROM
        rank j on return (this rank's own segment is untouched).
        ``array.size`` must divide evenly by the world size. Ring
        bundle-shrink schedule: w(w-1)/2 segments cross each link,
        the store-and-forward optimum for a ring topology."""
        ptr, dt = self._array_args(array, "all_to_all")
        rc = _load().tdr_ring_alltoall(
            _live(self._h, "ring_alltoall"), ptr, array.size, dt)
        _check(rc == 0, "ring_alltoall")

    def reduce(self, array, root: int, op: int = RED_SUM) -> None:
        """Root-reduce: after the call ROOT's buffer holds the
        reduction over all ranks. In-place and DESTRUCTIVE on
        non-root ranks (their buffers end holding the partial sums
        that passed through them on the way to root); one buffer-pass
        per link, folds riding the fused reduce-on-receive op."""
        ptr, dt = self._array_args(array, "reduce")
        rc = _load().tdr_ring_reduce(
            _live(self._h, "ring_reduce"), ptr, array.size, dt, op, root)
        _check(rc == 0, "ring_reduce")

    def broadcast(self, array, root: int) -> None:
        """Ring broadcast: root's buffer contents stream to every
        rank, store-and-forward per chunk (bandwidth-optimal for
        large messages; latency grows by world-1 chunks)."""
        # Byte-oriented: any dtype broadcasts (no folds happen).
        ptr, _ = self._array_args(array, "broadcast", need_dtype=False)
        rc = _load().tdr_ring_broadcast(
            _live(self._h, "ring_broadcast"), ptr, array.nbytes, root)
        _check(rc == 0, "ring_broadcast")

    @property
    def last_schedule(self) -> int:
        """Which SCHED_* the last allreduce on this ring ran — lets
        tests assert that negotiated capabilities actually selected
        the fused paths (not just that results are correct)."""
        return int(_load().tdr_ring_last_schedule(
            _live(self._h, "last_schedule")))

    def destroy(self) -> None:
        if self._h:
            _load().tdr_ring_destroy(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.destroy()


class Engine:
    """An open transport engine ("verbs" on real HCAs, "emu" anywhere)."""

    def __init__(self, spec: str = "auto"):
        self._h = _load().tdr_engine_open(spec.encode())
        _check(self._h, f"engine_open({spec})")
        # Worlds currently hosted on this engine (RingWorld attaches at
        # bootstrap, detaches at close). Multi-tenancy gates the
        # engine-wide seal-context stamp: the incarnation fence is only
        # meaningful while ONE world owns the engine — with co-tenant
        # worlds at different generations the stamp is cleared and
        # stale-world fencing falls back to the schedule-digest
        # generation check (per world, per collective). A WeakSet, so
        # an abandoned world (never closed — e.g. discarded after a
        # non-retryable rebuild failure) stops counting once collected
        # instead of permanently disabling the fence for its successor.
        import weakref

        self._worlds: "weakref.WeakSet" = weakref.WeakSet()
        trace.event("engine.open", kind=self.kind, backend=self.name)

    def attach_world(self, world) -> None:
        self._worlds.add(world)

    def detach_world(self, world) -> None:
        self._worlds.discard(world)

    @property
    def world_count(self) -> int:
        """Number of RingWorlds currently attached to this engine."""
        return len(self._worlds)

    def set_qp_limit(self, limit: int) -> None:
        """Cap live QPs on this engine (0 = unlimited). When the cap is
        reached, listen/connect fail fast with a non-retryable budget
        error — bring-up-time enforcement for engines shared by
        concurrent worlds."""
        _load().tdr_engine_set_qp_limit(_live(self._h, "set_qp_limit"),
                                        int(limit))

    @property
    def qp_limit(self) -> int:
        return int(_load().tdr_engine_qp_limit(
            _live(self._h, "qp_limit")))

    @property
    def qp_live(self) -> int:
        """Live QPs on this engine right now (all worlds combined)."""
        return int(_load().tdr_engine_qp_live(_live(self._h, "qp_live")))

    @property
    def kind(self) -> int:
        return _load().tdr_engine_kind(_live(self._h, "engine.kind"))

    @property
    def name(self) -> str:
        return _load().tdr_engine_name(_live(self._h, "engine.name")).decode()

    @property
    def telemetry_id(self) -> int:
        """Flight-recorder track id of this engine (open ordinal;
        names the per-rank/engine timeline in Perfetto exports)."""
        return int(_load().tdr_tel_engine_id(
            _live(self._h, "telemetry_id")))

    def reg_mr(self, buf, access: int = ACCESS_REMOTE_WRITE | ACCESS_REMOTE_READ
               ) -> MemoryRegion:
        """Register memory. ``buf`` is a numpy array, bytearray, or an
        (addr, len) tuple for pre-resolved device memory."""
        import numpy as np

        if isinstance(buf, tuple):
            addr, length = buf
        elif isinstance(buf, np.ndarray):
            addr, length = buf.ctypes.data, buf.nbytes
        elif isinstance(buf, (bytearray, memoryview)):
            c = (ctypes.c_char * len(buf)).from_buffer(buf)
            addr, length = ctypes.addressof(c), len(buf)
        else:
            raise TransportError(f"cannot register {type(buf)}")
        h = _load().tdr_reg_mr(_live(self._h, "reg_mr"), addr, length,
                               access)
        _check(h, "reg_mr")
        trace.event("mr.reg", bytes=length)
        return MemoryRegion(self, h)

    def reg_dmabuf_mr(self, fd: int, offset: int, length: int, iova: int = 0,
                      access: int = ACCESS_REMOTE_WRITE | ACCESS_REMOTE_READ
                      ) -> MemoryRegion:
        """Register device memory behind a dma-buf fd — the modern
        equivalent of the reference's whole pin+map pipeline
        (amdp2p.c:169-264), performed by the kernel's dma-buf machinery
        instead of a custom peer-memory client."""
        h = _load().tdr_reg_dmabuf_mr(_live(self._h, "reg_dmabuf_mr"), fd,
                                      offset, length, iova, access)
        _check(h, "reg_dmabuf_mr")
        trace.event("mr.reg_dmabuf", bytes=length)
        return MemoryRegion(self, h)

    def set_seal_context(self, generation: int, step: int = 0) -> None:
        """Stamp this engine's seal context: outbound seals carry the
        ring incarnation (+1 on the wire, 0 meaning unset) and the
        training step; a landing sealed by a DIFFERENT live
        incarnation is fenced as a stale-world ghost write. RingWorld
        calls this after every bootstrap/rebuild generation
        agreement."""
        _load().tdr_seal_context(_live(self._h, "seal_context"),
                                 int(generation) + 1, int(step))

    def clear_seal_context(self) -> None:
        """Unset the seal context (wire gen 0 = fence skipped).
        RingWorld clears it at every bootstrap entry: generation
        RECONCILIATION frames must not be fenced by a stamp retained
        from a previous incarnation, or an asymmetrically-failed
        rebuild (one rank stamped, its neighbor did not) would reject
        the very frames that re-sync the ranks — on every retry."""
        _load().tdr_seal_context(_live(self._h, "seal_context"), 0, 0)

    def listen(self, host: str = "127.0.0.1", port: int = 0,
               timeout_ms: int = -1,
               force_stream: bool = False) -> QueuePair:
        """Accept one connection (blocking). ``timeout_ms`` bounds the
        accept wait (-1 = forever): elastic rendezvous must be able to
        give up and release the port for the next attempt.
        ``force_stream`` pins the connection to the stream tier (no
        CMA fast path — full payload seals; the emulated inter-host
        link of a hierarchical topology)."""
        h = _load().tdr_listen_tier(_live(self._h, "listen"),
                                    host.encode(), port, timeout_ms,
                                    _CONN_FORCE_STREAM if force_stream
                                    else 0)
        _check(h, "listen")
        return QueuePair(self, h)

    def connect(self, host: str = "127.0.0.1", port: int = 0,
                timeout_ms: int = 10000,
                force_stream: bool = False) -> QueuePair:
        h = _load().tdr_connect_tier(_live(self._h, "connect"),
                                     host.encode(), port, timeout_ms,
                                     _CONN_FORCE_STREAM if force_stream
                                     else 0)
        _check(h, "connect")
        return QueuePair(self, h)

    def close(self) -> None:
        if self._h:
            _load().tdr_engine_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def loopback_pair(engine: Engine, port: int,
                  engine2: Optional[Engine] = None
                  ) -> Tuple[QueuePair, QueuePair]:
    """Bring up a connected QP pair on localhost (test/bench helper)."""
    result: List[Optional[QueuePair]] = [None]

    def _serve():
        result[0] = engine.listen("127.0.0.1", port)

    t = threading.Thread(target=_serve)
    t.start()
    client = (engine2 or engine).connect("127.0.0.1", port)
    t.join()
    assert result[0] is not None
    return result[0], client
