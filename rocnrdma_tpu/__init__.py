"""rocnrdma_tpu — a TPU-native zero-copy RDMA framework.

Re-imagines the capabilities of AMD's ``amdp2p`` PeerDirect bridge
(reference: rocmarchive/ROCnRDMA, ``amdp2p.c``) for TPU hardware:

- ``hbm``: pin-lifecycle layer over accelerator memory, mirroring the
  semantics of the reference's ``peer_memory_client`` callbacks
  (``amdp2p.c:363-371``) and their revocation handshake
  (``amdp2p.c:88-109``), re-based on dma-buf export instead of the AMD
  KFD RDMA interface.
- ``transport``: Python bindings to the native C++ engine (``native/``)
  providing MR registration, RC-style queue pairs, one-sided RDMA
  WRITE/READ and two-sided SEND/RECV with completions. Backends: real
  InfiniBand verbs (dlopen'd libibverbs, incl. ``ibv_reg_dmabuf_mr``)
  and a hardware-free emulated backend for CI.
- ``collectives``: cross-slice (DCN) ring allreduce over the transport,
  replacing XLA's host-staged DCN copy, plus staging-byte accounting.
- ``telemetry``: the flight recorder — engine-side chunk-lifecycle
  event ring (native ``telemetry.cc``), log2 latency/bandwidth
  histograms, the unified counter registry, and Chrome/Perfetto
  export merging native and Python-tier timelines on one clock.
- ``parallel`` / ``models`` / ``ops``: the JAX consumer stack — device
  meshes, a Llama model family, Pallas TPU kernels, and a DP trainer
  whose cross-slice gradient allreduce rides the zero-copy path.

The reference is a transport layer with zero software on the per-message
hot path (all work front-loaded into registration, ``amdp2p.c:219-264``);
that invariant is preserved here: after ``register``, data movement is
NIC hardware (or, in the emulated backend, the progress engine) only.
"""

__version__ = "0.1.0"

from rocnrdma_tpu.utils.trace import trace  # noqa: F401
