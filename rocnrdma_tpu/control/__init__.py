"""Control plane: named rendezvous with leases and arbitrated rejoin.

``coordinator`` is the single-process rendezvous/coordination service
(runnable via ``tools/tdr_rendezvous.py``); ``client`` is the member
side RingWorld embeds. The legacy pairwise bootstrap keeps working
with no coordinator — this package is the arbitrated upgrade path.
"""

from rocnrdma_tpu.control.client import ControlClient, ControlError
from rocnrdma_tpu.control.coordinator import Coordinator

__all__ = ["Coordinator", "ControlClient", "ControlError"]
