"""Member-side control-plane client (stdlib-only).

One request per connection (the coordinator closes after answering),
so a parked rendezvous call never blocks heartbeats — the background
``Heartbeat`` thread opens its own connections. All methods return the
coordinator's response dict; ``ok`` is False on arbitration refusals
(stale incarnation, rendezvous timeout) — the member decides whether
that means re-join or give up. Transport-level failures raise
``ControlError``.
"""

from __future__ import annotations

import json
import socket
import threading
from typing import Any, Callable, Dict, Optional

from rocnrdma_tpu.utils.trace import trace


class ControlError(RuntimeError):
    """The coordinator was unreachable or spoke garbage (distinct from
    an ok=False arbitration answer, which is a protocol-level verdict
    the member must interpret)."""


class ControlClient:
    def __init__(self, address: str, timeout_s: float = 120.0):
        host, _, port = address.rpartition(":")
        if not host or not port.isdigit():
            raise ValueError(f"control address must be host:port, "
                             f"got {address!r}")
        self.host = host
        self.port = int(port)
        self.timeout_s = float(timeout_s)

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    # ------------------------------------------------------------ wire

    def request(self, op: str, timeout_s: Optional[float] = None,
                **fields: Any) -> Dict[str, Any]:
        budget = self.timeout_s if timeout_s is None else float(timeout_s)
        # The budget rides IN the payload: the coordinator parks
        # join/sync for the CALLER's budget, not its own default —
        # otherwise an aborted-and-retried sync leaves an orphaned
        # handler parked on the same member for the server default,
        # racing the retry for the released view.
        req = dict(fields, op=op, timeout_s=budget)
        try:
            with socket.create_connection(
                    (self.host, self.port), timeout=budget + 10.0) as s:
                f = s.makefile("rwb")
                f.write((json.dumps(req) + "\n").encode())
                f.flush()
                line = f.readline()
            if not line:
                raise ControlError(
                    f"coordinator {self.address} closed the connection")
            return json.loads(line.decode())
        except (OSError, ValueError) as e:
            raise ControlError(
                f"coordinator {self.address} unreachable for "
                f"{op}: {e}") from e

    # ------------------------------------------------------ operations

    def join(self, world: str, size: int, rank: int = -1,
             host: str = "127.0.0.1",
             host_key: Optional[str] = None,
             timeout_s: Optional[float] = None) -> Dict[str, Any]:
        """``host_key`` is the member's TOPOLOGY key (which physical
        host it sits on) — distinct from ``host``, the address peers
        dial, and deliberately NOT defaulted from it: inferring
        locality from connect addresses would silently flip collective
        algorithms under NAT or multi-homed hosts (the resolve_topology
        design rule). A member with no explicit key reports none, and
        the coordinator releases a keyless view the member side
        ignores. The coordinator releases every slot's key in the view
        (``host_keys``), which is how arbitrated worlds agree on the
        hierarchical grouping without a per-rank env."""
        budget = self.timeout_s if timeout_s is None else float(timeout_s)
        return self.request("join", timeout_s=budget, world=world,
                            size=int(size), rank=int(rank), host=host,
                            host_key=host_key)

    def sync(self, world: str, rank: int, incarnation: int,
             timeout_s: Optional[float] = None) -> Dict[str, Any]:
        budget = self.timeout_s if timeout_s is None else float(timeout_s)
        return self.request("sync", timeout_s=budget, world=world,
                            rank=int(rank), incarnation=int(incarnation))

    def report(self, world: str, rank: int, incarnation: int,
               generation: int, error: str = "") -> Dict[str, Any]:
        return self.request("report", world=world, rank=int(rank),
                            incarnation=int(incarnation),
                            generation=int(generation),
                            error=str(error)[:400])

    def heartbeat(self, world: str, rank: int, incarnation: int,
                  generation: int,
                  counters: Optional[Dict[str, int]] = None,
                  hists: Optional[Dict[str, Dict[int, int]]] = None
                  ) -> Dict[str, Any]:
        return self.request("heartbeat", timeout_s=15.0, world=world,
                            rank=int(rank), incarnation=int(incarnation),
                            generation=int(generation),
                            counters=counters, hists=hists)

    def leave(self, world: str, rank: int,
              incarnation: int) -> Dict[str, Any]:
        return self.request("leave", timeout_s=15.0, world=world,
                            rank=int(rank), incarnation=int(incarnation))

    def metrics(self) -> str:
        """Scrape the coordinator's /metrics endpoint (the same HTTP
        text a Prometheus scraper would read)."""
        with socket.create_connection((self.host, self.port),
                                      timeout=15.0) as s:
            s.sendall(b"GET /metrics HTTP/1.0\r\n\r\n")
            chunks = []
            while True:
                b = s.recv(65536)
                if not b:
                    break
                chunks.append(b)
        blob = b"".join(chunks)
        head, _, body = blob.partition(b"\r\n\r\n")
        if not head.startswith(b"HTTP/1.0 200"):
            raise ControlError(
                f"/metrics scrape failed: {head.splitlines()[:1]}")
        return body.decode()

    # ------------------------------------------------------- heartbeat

    def start_heartbeat(self, world: str, rank: int,
                        state_fn: Callable[[], tuple],
                        interval_s: float,
                        counters_fn: Optional[Callable[[], Dict]] = None,
                        hists_fn: Optional[Callable[[], Dict]] = None
                        ) -> "Heartbeat":
        """Renew this member's lease from a daemon thread every
        ``interval_s``, pushing counter/histogram snapshots for the
        coordinator's /metrics aggregation. ``state_fn`` returns the
        member's CURRENT (incarnation, generation) — it changes across
        rejoins, so the thread reads it per beat."""
        return Heartbeat(self, world, rank, state_fn, interval_s,
                         counters_fn, hists_fn)


class Heartbeat:
    def __init__(self, client: ControlClient, world: str, rank: int,
                 state_fn: Callable[[], tuple], interval_s: float,
                 counters_fn: Optional[Callable[[], Dict]] = None,
                 hists_fn: Optional[Callable[[], Dict]] = None):
        self._client = client
        self._world = world
        self._rank = rank
        self._state_fn = state_fn
        self._interval = max(0.05, float(interval_s))
        self._counters_fn = counters_fn
        self._hists_fn = hists_fn
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"tdr-ctl-hb-{world}-{rank}")
        self._thread.start()

    def beat(self) -> bool:
        """One synchronous beat (also used as the final flush before
        leave, so /metrics reflects the member's last snapshots).
        Returns False when ``state_fn`` reports the member object is
        GONE (garbage-collected) — the thread must exit and the lease
        age out at the coordinator."""
        state = self._state_fn()
        if state is None:
            return False
        inc, gen = state
        if inc is None:
            return True  # between incarnations: nothing to renew
        counters = self._counters_fn() if self._counters_fn else None
        hists = self._hists_fn() if self._hists_fn else None
        resp = self._client.heartbeat(self._world, self._rank, inc, gen,
                                      counters=counters, hists=hists)
        if not resp.get("ok"):
            trace.event("ctl.heartbeat_refused", world=self._world,
                        rank=self._rank,
                        error=str(resp.get("error", ""))[:80])
        return True

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                if not self.beat():
                    return  # member collected: stop renewing its lease
            except ControlError:
                # The coordinator being briefly unreachable must never
                # take the member down; the lease ages, and the member
                # rejoins through the normal arbitration path if it
                # expires meanwhile.
                pass
            except Exception:
                pass  # diagnostics must never kill the workload

    def stop(self, flush: bool = False) -> None:
        self._stop.set()
        if flush:
            try:
                self.beat()
            except Exception:
                pass
        self._thread.join(timeout=5)
