"""Member-side control-plane client (stdlib-only).

One request per connection (the coordinator closes after answering),
so a parked rendezvous call never blocks heartbeats — the background
``Heartbeat`` thread opens its own connections. All methods return the
coordinator's response dict; ``ok`` is False on arbitration refusals
(stale incarnation, rendezvous timeout) — the member decides whether
that means re-join or give up. Transport-level failures raise
``ControlError``.
"""

from __future__ import annotations

import json
import socket
import threading
import time
from typing import Any, Callable, Dict, Optional

from rocnrdma_tpu.utils.trace import trace


class ControlError(RuntimeError):
    """The coordinator was unreachable or spoke garbage (distinct from
    an ok=False arbitration answer, which is a protocol-level verdict
    the member must interpret)."""


class ClockSync:
    """NTP-style offset estimate against the coordinator's
    CLOCK_MONOTONIC, min-RTT filtered.

    Each heartbeat is a four-timestamp exchange: the member stamps t0
    at send, the coordinator echoes its receive (t1) and send (t2)
    instants, the member stamps t3 at the reply. Then

        offset = ((t1 - t0) + (t2 - t3)) / 2   (coordinator - member)
        rtt    = (t3 - t0) - (t2 - t1)

    and |true_offset - offset| <= rtt / 2 — the asymmetry bound, so
    the sample taken at the SMALLEST rtt carries the tightest bound.
    The filter keeps exactly that sample (a new sample replaces the
    estimate only when its rtt is <= the kept one's): the estimate's
    error bound is monotonically non-increasing, and congestion
    spikes — which inflate rtt and offset together — can never drag
    the estimate around. Same-host ranks share the kernel clock, so
    the estimate converges toward 0 there; the machinery is what makes
    multi-host merges honest."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.offset_ns: int = 0
        self.rtt_ns: Optional[int] = None  # None until the 1st sample
        self.samples: int = 0

    def sample(self, t0: int, t1: int, t2: int, t3: int) -> bool:
        """Feed one exchange; True when it became the new estimate."""
        rtt = (t3 - t0) - (t2 - t1)
        if rtt < 0:  # clock misbehavior / garbled echo: discard
            return False
        offset = ((t1 - t0) + (t2 - t3)) // 2
        with self._lock:
            self.samples += 1
            if self.rtt_ns is not None and rtt > self.rtt_ns:
                return False
            self.rtt_ns = rtt
            self.offset_ns = offset
            return True

    def state(self) -> Dict[str, int]:
        with self._lock:
            return {
                "clock_offset_ns": int(self.offset_ns),
                "clock_rtt_ns": int(self.rtt_ns or 0),
                "clock_samples": int(self.samples),
            }


class ControlClient:
    def __init__(self, address: str, timeout_s: float = 120.0):
        host, _, port = address.rpartition(":")
        if not host or not port.isdigit():
            raise ValueError(f"control address must be host:port, "
                             f"got {address!r}")
        self.host = host
        self.port = int(port)
        self.timeout_s = float(timeout_s)

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    # ------------------------------------------------------------ wire

    def request(self, op: str, timeout_s: Optional[float] = None,
                **fields: Any) -> Dict[str, Any]:
        budget = self.timeout_s if timeout_s is None else float(timeout_s)
        # The budget rides IN the payload: the coordinator parks
        # join/sync for the CALLER's budget, not its own default —
        # otherwise an aborted-and-retried sync leaves an orphaned
        # handler parked on the same member for the server default,
        # racing the retry for the released view.
        req = dict(fields, op=op, timeout_s=budget)
        try:
            with socket.create_connection(
                    (self.host, self.port), timeout=budget + 10.0) as s:
                f = s.makefile("rwb")
                f.write((json.dumps(req) + "\n").encode())
                f.flush()
                line = f.readline()
            if not line:
                raise ControlError(
                    f"coordinator {self.address} closed the connection")
            return json.loads(line.decode())
        except (OSError, ValueError) as e:
            raise ControlError(
                f"coordinator {self.address} unreachable for "
                f"{op}: {e}") from e

    # ------------------------------------------------------ operations

    def join(self, world: str, size: int, rank: int = -1,
             host: str = "127.0.0.1",
             host_key: Optional[str] = None,
             timeout_s: Optional[float] = None,
             resizable: bool = False, max_size: int = 0,
             weight: float = 1.0) -> Dict[str, Any]:
        """``host_key`` is the member's TOPOLOGY key (which physical
        host it sits on) — distinct from ``host``, the address peers
        dial, and deliberately NOT defaulted from it: inferring
        locality from connect addresses would silently flip collective
        algorithms under NAT or multi-homed hosts (the resolve_topology
        design rule). A member with no explicit key reports none, and
        the coordinator releases a keyless view the member side
        ignores. The coordinator releases every slot's key in the view
        (``host_keys``), which is how arbitrated worlds agree on the
        hierarchical grouping without a per-rank env.

        ``resizable`` opts the world (sticky, first join wins) into
        coordinator-arbitrated RESIZE: shrink-to-survivors on a lease
        expiry/leave, grow-on-join when full (``max_size`` caps the
        growth; 0 = unbounded). ``weight`` is the world's fair-share
        weight when the coordinator divides its engine QP pool."""
        budget = self.timeout_s if timeout_s is None else float(timeout_s)
        return self.request("join", timeout_s=budget, world=world,
                            size=int(size), rank=int(rank), host=host,
                            host_key=host_key,
                            resizable=bool(resizable),
                            max_size=int(max_size),
                            weight=float(weight))

    def sync(self, world: str, rank: int, incarnation: int,
             timeout_s: Optional[float] = None) -> Dict[str, Any]:
        budget = self.timeout_s if timeout_s is None else float(timeout_s)
        return self.request("sync", timeout_s=budget, world=world,
                            rank=int(rank), incarnation=int(incarnation))

    def report(self, world: str, rank: int, incarnation: int,
               generation: int, error: str = "") -> Dict[str, Any]:
        return self.request("report", world=world, rank=int(rank),
                            incarnation=int(incarnation),
                            generation=int(generation),
                            error=str(error)[:400])

    def heartbeat(self, world: str, rank: int, incarnation: int,
                  generation: int,
                  counters: Optional[Dict[str, int]] = None,
                  hists: Optional[Dict[str, Dict[int, int]]] = None,
                  **extra: Any) -> Dict[str, Any]:
        """``extra`` carries the observability riders: ``t0_ns`` (the
        clock-sync exchange), ``clock_offset_ns``/``clock_rtt_ns``
        (the member's current min-RTT estimate, served on /metrics as
        ``tdr_clock_offset_us``), and ``postmortems`` (bundles this
        member has written, summed into
        ``tdr_postmortems_total{world=}``)."""
        return self.request("heartbeat", timeout_s=15.0, world=world,
                            rank=int(rank), incarnation=int(incarnation),
                            generation=int(generation),
                            counters=counters, hists=hists, **extra)

    def collect_trace(self, world: str, timeout_s: float = 30.0,
                      max_events: int = 65536) -> Dict[str, Any]:
        """Pull one bounded flight-recorder segment from EVERY live
        rank of ``world``: the coordinator flags the request, each
        member's next heartbeat drains and pushes its segment, and the
        call parks until all ranks reported (or the timeout). The
        result's ``segments`` map feeds ``telemetry.merge_fleet`` and
        ``tools/tdr_explain.py``."""
        return self.request("collect_trace", timeout_s=timeout_s,
                            world=world, max_events=int(max_events))

    def leave(self, world: str, rank: int,
              incarnation: int) -> Dict[str, Any]:
        return self.request("leave", timeout_s=15.0, world=world,
                            rank=int(rank), incarnation=int(incarnation))

    def metrics(self) -> str:
        """Scrape the coordinator's /metrics endpoint (the same HTTP
        text a Prometheus scraper would read)."""
        with socket.create_connection((self.host, self.port),
                                      timeout=15.0) as s:
            s.sendall(b"GET /metrics HTTP/1.0\r\n\r\n")
            chunks = []
            while True:
                b = s.recv(65536)
                if not b:
                    break
                chunks.append(b)
        blob = b"".join(chunks)
        head, _, body = blob.partition(b"\r\n\r\n")
        if not head.startswith(b"HTTP/1.0 200"):
            raise ControlError(
                f"/metrics scrape failed: {head.splitlines()[:1]}")
        return body.decode()

    # ------------------------------------------------------- heartbeat

    def start_heartbeat(self, world: str, rank: int,
                        state_fn: Callable[[], tuple],
                        interval_s: float,
                        counters_fn: Optional[Callable[[], Dict]] = None,
                        hists_fn: Optional[Callable[[], Dict]] = None,
                        trace_fn: Optional[Callable[[int], Dict]] = None,
                        postmortems_fn: Optional[Callable[[], int]] = None,
                        notify_fn: Optional[Callable[[Dict], None]] = None,
                        extras_fn: Optional[Callable[[], Dict]] = None
                        ) -> "Heartbeat":
        """Renew this member's lease from a daemon thread every
        ``interval_s``, pushing counter/histogram snapshots for the
        coordinator's /metrics aggregation. ``state_fn`` returns the
        member's CURRENT (incarnation, generation) or (incarnation,
        generation, rank) — incarnation AND rank change across
        rejoins/RESIZEs, so the thread reads it per beat (a 2-tuple
        keeps the construction-time rank). ``trace_fn(max_events)``
        serves ``collect_trace`` pulls (returns {"events": wire list,
        "dropped": int}); ``postmortems_fn`` reports bundles written;
        ``notify_fn(resp)`` sees every accepted heartbeat response
        (how a member learns ``resize_pending``); ``extras_fn()``
        returns additional scalar riders merged into every beat (how a
        member pushes its bring-up ``qp_reserved``)."""
        return Heartbeat(self, world, rank, state_fn, interval_s,
                         counters_fn, hists_fn, trace_fn, postmortems_fn,
                         notify_fn, extras_fn)


class Heartbeat:
    def __init__(self, client: ControlClient, world: str, rank: int,
                 state_fn: Callable[[], tuple], interval_s: float,
                 counters_fn: Optional[Callable[[], Dict]] = None,
                 hists_fn: Optional[Callable[[], Dict]] = None,
                 trace_fn: Optional[Callable[[int], Dict]] = None,
                 postmortems_fn: Optional[Callable[[], int]] = None,
                 notify_fn: Optional[Callable[[Dict], None]] = None,
                 extras_fn: Optional[Callable[[], Dict]] = None):
        self._client = client
        self._world = world
        self._rank = rank
        self._state_fn = state_fn
        self._notify_fn = notify_fn
        self._extras_fn = extras_fn
        # (incarnation, rank) the coordinator declared superseded: a
        # member that left, was lease-expired, or was resized out must
        # STOP pushing counters under that identity — the coordinator
        # rejects the pushes, and retrying them forever is the
        # heartbeat-after-leave leak. Beats resume the moment state_fn
        # reports a different identity (a rejoin's new incarnation, or
        # a RESIZE's new rank for the same incarnation).
        self._dead_key: Optional[tuple] = None
        self._interval = max(0.05, float(interval_s))
        self._counters_fn = counters_fn
        self._hists_fn = hists_fn
        self._trace_fn = trace_fn
        self._postmortems_fn = postmortems_fn
        # Clock-offset estimate vs the coordinator, fed by every beat
        # and pushed back so /metrics serves tdr_clock_offset_us.
        self.clock = ClockSync()
        # collect_trace requests already answered (one push per id),
        # and drained-but-unacknowledged payloads awaiting a retry —
        # the ring drain is DESTRUCTIVE, so a failed push must resend
        # the captured window, never re-drain an emptied ring.
        self._pushed_traces: set = set()
        self._trace_payloads: Dict[int, Dict[str, Any]] = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"tdr-ctl-hb-{world}-{rank}")
        self._thread.start()

    def beat(self) -> bool:
        """One synchronous beat (also used as the final flush before
        leave, so /metrics reflects the member's last snapshots).
        Returns False when ``state_fn`` reports the member object is
        GONE (garbage-collected) — the thread must exit and the lease
        age out at the coordinator."""
        state = self._state_fn()
        if state is None:
            return False
        if len(state) >= 3:
            inc, gen, rank = state[0], state[1], state[2]
        else:
            inc, gen, rank = state[0], state[1], self._rank
        if inc is None:
            return True  # between incarnations: nothing to renew
        if (inc, rank) == self._dead_key:
            return True  # superseded identity: push nothing under it
        counters = self._counters_fn() if self._counters_fn else None
        hists = self._hists_fn() if self._hists_fn else None
        extra: Dict[str, Any] = self.clock.state()
        if self._postmortems_fn is not None:
            try:
                extra["postmortems"] = int(self._postmortems_fn())
            except Exception:
                pass
        if self._extras_fn is not None:
            try:
                extra.update(self._extras_fn() or {})
            except Exception:
                pass  # a rider hook must never cost the lease renewal
        t0 = time.monotonic_ns()
        resp = self._client.heartbeat(self._world, rank, inc, gen,
                                      counters=counters, hists=hists,
                                      t0_ns=t0, **extra)
        t3 = time.monotonic_ns()
        try:
            if int(resp.get("t0_ns", -1)) == t0:
                self.clock.sample(t0, int(resp["t1_ns"]),
                                  int(resp["t2_ns"]), t3)
        except (KeyError, TypeError, ValueError):
            pass  # pre-clock coordinator: estimate just stays at 0
        if not resp.get("ok"):
            if resp.get("error") == "superseded":
                # The coordinator owns membership: this identity is
                # dead there (left / lease-expired / resized out).
                # Stop pushing under it — the next rejoin or RESIZE
                # view changes what state_fn returns and beats resume.
                self._dead_key = (inc, rank)
            trace.event("ctl.heartbeat_refused", world=self._world,
                        rank=rank,
                        error=str(resp.get("error", ""))[:80])
            return True
        if self._notify_fn is not None:
            try:
                self._notify_fn(resp)
            except Exception:
                pass  # a member-side hook must never kill the lease
        collect = resp.get("collect")
        if isinstance(collect, dict) and self._trace_fn is not None:
            self._push_trace(collect, inc, gen, rank)
        return True

    def _push_trace(self, collect: Dict[str, Any], inc: int,
                    gen: int, rank: Optional[int] = None) -> None:
        """Serve one collect_trace pull: drain a bounded local segment
        and push it under the request id. The drain runs ONCE per id
        (it is destructive); the push retries on ANY failure —
        transport loss or a coordinator refusal (e.g. this member was
        superseded mid-push) — resending the CACHED window on the next
        beat, because the flag stays up at the coordinator until this
        rank's segment lands. Only success or a stale-id verdict (a
        newer collect superseded the request) retires the id."""
        try:
            trace_id = int(collect.get("id", 0))
            max_events = int(collect.get("max_events", 65536))
        except (TypeError, ValueError):
            return
        if not trace_id or trace_id in self._pushed_traces:
            return
        payload = self._trace_payloads.get(trace_id)
        if payload is None:
            try:
                seg = self._trace_fn(max_events) or {}
            except Exception:
                seg = {"events": [], "dropped": 0,
                       "error": "trace_fn failed"}
            payload = dict(seg)
            payload.update(self.clock.state())
            # Bound the retry cache: requests the coordinator timed
            # out never re-flag, so their payloads would otherwise
            # pin event windows forever.
            while len(self._trace_payloads) >= 4:
                self._trace_payloads.pop(
                    min(self._trace_payloads), None)
            self._trace_payloads[trace_id] = payload
        if rank is None:
            rank = self._rank
        try:
            resp = self._client.request(
                "trace_push", world=self._world, rank=int(rank),
                incarnation=int(inc), generation=int(gen),
                trace_id=trace_id, segment=payload)
        except ControlError:
            return  # payload stays cached; the next beat retries
        if resp.get("ok") or resp.get("error") == "stale trace id":
            self._pushed_traces.add(trace_id)
            self._trace_payloads.pop(trace_id, None)
            if resp.get("ok"):
                trace.event("ctl.trace_push", world=self._world,
                            rank=int(rank), trace_id=trace_id,
                            events=len(payload.get("events") or []))
        # Any other refusal (superseded member mid-rebuild): keep the
        # cache, retry under the next incarnation's heartbeat.

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                if not self.beat():
                    return  # member collected: stop renewing its lease
            except ControlError:
                # The coordinator being briefly unreachable must never
                # take the member down; the lease ages, and the member
                # rejoins through the normal arbitration path if it
                # expires meanwhile.
                pass
            except Exception:
                pass  # diagnostics must never kill the workload

    def stop(self, flush: bool = False) -> None:
        self._stop.set()
        if flush:
            try:
                self.beat()
            except Exception:
                pass
        self._thread.join(timeout=5)
