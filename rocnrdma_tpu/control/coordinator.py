"""Rendezvous coordinator: named worlds, leases, arbitrated rejoin.

A small single-process TCP service (stdlib-only) that owns the
lifecycle state the pairwise bootstrap used to infer per rank: it
names worlds, hands out ring positions, base ports, and generation
numbers, and arbitrates elastic rejoin. "The DMA Streaming Framework"
discipline applied to membership: ONE owner of lifecycle state instead
of N peers independently guessing the next generation.

**Model.** A *world* is a named, multi-tenant resource: fixed size,
a base port carved from the coordinator's port pool (so two jobs
sharing a NIC never fight for listen ports), a monotonic generation,
and one member slot per ring position. Members hold *leases* renewed
by heartbeats; a member that misses its lease is declared dead by the
coordinator — never by a peer's guess — which bumps the generation.
Generation bumps happen in exactly three places, all here: a lease
expiry, a membership change (rejoin/supersede/leave) after the world
first became ready, and a member's failure report. Ranks NEVER bump
locally on the arbitrated path.

**Rendezvous barrier.** ``join`` (new/restarted member) and ``sync``
(surviving member re-rendezvousing during rebuild) both park the
caller at the world's barrier. When every slot is filled by a live
member and all of them are parked, the coordinator atomically builds
ONE membership view — generation, epoch, base port, peer hosts — and
answers every parked member with it. Two ranks can therefore never
act on different views of the same incarnation; the epoch is the view
counter and is stamped (with the generation) into the schedule digest
by the member side.

**Wire protocol.** One JSON object per line, one request per
connection; the response is one JSON line. The same port also answers
``GET /metrics`` (and ``GET /healthz``) with a Prometheus-style text
exposition: coordinator state (generation, members, rebuilds, lease
expiries) plus the member-pushed native counter registry and log2
histograms (heartbeats carry snapshots), rendered as ``tdr_*`` series
with per-world labels — chunk p99, retransmit rate, NAK count, and
rebuild count become scrapeable SLOs.

In-process caveat: multi-rank test harnesses run many members in one
process, which share one process-wide native registry — summed
counter series over-count by the member multiplier there. Production
members are one process each, where the sum is exact.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from typing import Any, Dict, List, Optional

from rocnrdma_tpu.telemetry.recorder import hist_percentile
from rocnrdma_tpu.utils.trace import trace

# Histograms surfaced as quantile series on /metrics (names match the
# native recorder's tdr_tel_hist_name table).
_QUANTILES = (50, 90, 99)

# Protocol / metrics contract version (served on /metrics so scrapers
# can pin the names they parse).
PROTOCOL_VERSION = 1


class _Member:
    __slots__ = ("rank", "incarnation", "host", "host_key",
                 "lease_deadline", "alive", "waiting", "pending_view",
                 "counters", "hists", "wait_token", "clock_offset_ns",
                 "clock_rtt_ns", "postmortems", "qp_reserved", "hb_last",
                 "link_health", "degraded_total")

    def __init__(self, rank: int, incarnation: int, host: str,
                 lease_deadline: float, host_key: Optional[str] = None):
        self.rank = rank
        self.incarnation = incarnation
        self.host = host
        # Topology key (which physical host this member sits on) —
        # reported at join, released to every member in the view so
        # the hierarchical grouping is a coordinator decision, not a
        # per-rank env guess. None when the member reported none — the
        # dial address is deliberately NOT a fallback (locality
        # inferred from connect addresses flips algorithms under
        # NAT/multi-homing); a view with any keyless slot is ignored
        # by the member-side topology resolution.
        self.host_key = None if host_key is None else str(host_key)
        self.lease_deadline = lease_deadline
        self.alive = True
        self.waiting = False
        self.pending_view: Optional[Dict[str, Any]] = None
        self.counters: Dict[str, int] = {}
        self.hists: Dict[str, Dict[int, int]] = {}
        # Park token: a re-issued sync for this member bumps it, so an
        # ORPHANED handler (client gave up and retried; its connection
        # is dead) stops waiting instead of racing the live retry for
        # the released view.
        self.wait_token = 0
        # Heartbeat-pushed observability riders: the member's min-RTT
        # clock-offset estimate vs this coordinator (what fleet trace
        # merges align timestamps with; served as
        # tdr_clock_offset_us{world=,rank=}) and the postmortem
        # bundles it has written (summed into
        # tdr_postmortems_total{world=}).
        self.clock_offset_ns = 0
        self.clock_rtt_ns = 0
        self.postmortems = 0
        # QP appetite this member reserved at bring-up (flat ring +
        # hierarchical tier rings; heartbeat-pushed, served as
        # tdr_ctl_qp_reserved{world=}).
        self.qp_reserved = 0
        # Last accepted heartbeat instant (monotonic) — the per-member
        # state behind the optional heartbeat rate limit.
        self.hb_last = 0.0
        # Degradation-ladder riders: this member's per-link health
        # snapshot ({link: {peer, score, degraded, faults}}) and its
        # rung-engagement tally — the slow-rank quarantine report,
        # served as tdr_link_health{world=,rank=,peer=} and summed
        # into tdr_degraded_total{world=}.
        self.link_health: Dict[str, Dict[str, Any]] = {}
        self.degraded_total = 0


class _World:
    __slots__ = ("name", "size", "base_port", "qp_budget", "generation",
                 "epoch", "members", "ever_ready", "rebuilds",
                 "lease_expiries", "joins", "trace_req", "trace_seq",
                 "resizable", "max_size", "resizes", "weight",
                 "qp_share", "admission_rejects", "hb_throttled",
                 "grow_hold_until")

    def __init__(self, name: str, size: int, base_port: int,
                 qp_budget: int):
        self.name = name
        self.size = size
        self.base_port = base_port
        self.qp_budget = qp_budget
        self.generation = 0
        self.epoch = 0  # view counter: bumps once per barrier release
        self.members: Dict[int, _Member] = {}
        self.ever_ready = False
        self.rebuilds = 0
        self.lease_expiries = 0
        self.joins = 0
        # ---- Elastic membership (RESIZE) ----
        # Sticky opt-in (first join's ``resizable`` field): a lease
        # expiry or leave after first-ready cuts a world_size-1 view
        # to the survivors instead of waiting for a rejoin, and a
        # joiner on a full world parks for a world_size+1 view.
        self.resizable = False
        self.max_size = 0  # grow ceiling (0 = unbounded)
        self.resizes = 0
        # Batch admission: deadline (monotonic) until which a
        # pure-growth RESIZE is held open so near-simultaneous grow
        # joiners coalesce into ONE size+N cut. 0 = no hold pending.
        self.grow_hold_until = 0.0
        # ---- Admission control ----
        self.weight = 1.0   # fair-share weight (first join's ``weight``)
        self.qp_share = qp_budget  # computed fair share (gauge)
        self.admission_rejects = 0
        self.hb_throttled = 0
        # Pending collect_trace pull: {"id", "max_events", "segments":
        # {rank: segment}} — heartbeats see the flag and push; the
        # parked collector wakes when every live rank reported.
        self.trace_req: Optional[Dict[str, Any]] = None
        self.trace_seq = 0

    def alive_members(self) -> List[_Member]:
        return [m for m in self.members.values() if m.alive]


class Coordinator:
    """The rendezvous service. ``start()`` binds and serves from
    daemon threads; ``stop()`` tears down. Thread-per-connection —
    parked rendezvous calls hold their connection, everything else is
    one short request."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 lease_ms: int = 5000, port_base: int = 36000,
                 port_stride: int = 64, qp_budget: int = 0,
                 qp_fair: bool = False, qp_floor: int = 0,
                 snapshot_dir: Optional[str] = None,
                 snapshot_interval_s: float = 2.0,
                 restore: bool = False,
                 hb_min_interval_ms: int = 0,
                 scrape_min_interval_ms: int = 0,
                 max_worlds: int = 0,
                 grow_hold_ms: int = 250):
        self.host = host
        self.lease_ms = int(lease_ms)
        self.port_base = int(port_base)
        self.port_stride = int(port_stride)
        self.qp_budget = int(qp_budget)
        # Admission control: with qp_fair, ``qp_budget`` is the TOTAL
        # engine pool divided across named worlds by weight (floored
        # at qp_floor); without it, every world gets the full budget
        # (the pre-fair-share per-world semantics, default).
        self.qp_fair = bool(qp_fair)
        self.qp_floor = int(qp_floor)
        self.max_worlds = int(max_worlds)
        # Batch admission: how long a pure-growth RESIZE is held open
        # so a burst of grow joiners lands in ONE size+N view change
        # instead of N back-to-back rebuild-equivalent cuts. 0 keeps
        # the immediate-cut behavior.
        self._grow_hold_s = max(0.0, int(grow_hold_ms) / 1000.0)
        self._hb_min_s = max(0.0, int(hb_min_interval_ms) / 1000.0)
        self._scrape_min_s = max(0.0,
                                 int(scrape_min_interval_ms) / 1000.0)
        self._last_scrape = 0.0
        self._scrape_throttled = 0
        # Coordinator redundancy: periodic full-state snapshots to
        # snapshot_dir (TDR_CTL_SNAPSHOT_DIR env fallback); restore=True
        # resumes arbitration from the latest one — members re-attach
        # via heartbeat re-registration, no full re-rendezvous.
        if snapshot_dir is None:
            snapshot_dir = os.environ.get("TDR_CTL_SNAPSHOT_DIR") or None
        self.snapshot_dir = snapshot_dir
        self.snapshot_interval_s = max(0.1, float(snapshot_interval_s))
        self._last_snapshot = 0.0  # wall time of the last dump
        self.failovers = 0
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._worlds: Dict[str, _World] = {}
        self._next_inc = 1
        snap = self._load_snapshot(snapshot_dir) if restore else None
        if snap is not None and port == 0:
            # A restored coordinator must come back at the address the
            # fleet already dials: adopt the snapshot's port unless the
            # caller pinned one explicitly.
            port = int(snap.get("port", 0))
        self._stop = threading.Event()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.port = self._sock.getsockname()[1]
        self._threads: List[threading.Thread] = []
        if snap is not None:
            self._restore_state(snap)

    # ------------------------------------------------------- lifecycle

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self) -> "Coordinator":
        workers = [(self._serve, "tdr-ctl-accept"),
                   (self._sweep, "tdr-ctl-sweeper")]
        if self.snapshot_dir:
            workers.append((self._snapshots, "tdr-ctl-snapshot"))
        for target, name in workers:
            t = threading.Thread(target=target, daemon=True, name=name)
            t.start()
            self._threads.append(t)
        trace.event("ctl.coordinator", address=self.address,
                    lease_ms=self.lease_ms, failovers=self.failovers)
        return self

    def stop(self) -> None:
        self._stop.set()
        # A blocked accept() pins the listen socket past close() (the
        # in-flight syscall holds the file open), so the port would
        # stay bound and a restore/standby rebind on the SAME address
        # would EADDRINUSE. Poke the listener with a throwaway
        # connection so the accept thread observes the stop flag.
        try:
            with socket.create_connection((self.host, self.port),
                                          timeout=1):
                pass
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        with self._cv:
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout=5)
        if self.snapshot_dir:
            # Final dump so a clean shutdown leaves a restorable image
            # (a SIGKILL relies on the last periodic one instead).
            try:
                self.snapshot_now()
            except OSError:
                pass

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # --------------------------------------------------------- serving

    def _serve(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # socket closed by stop()
            t = threading.Thread(target=self._handle, args=(conn,),
                                 daemon=True, name="tdr-ctl-conn")
            t.start()

    def _handle(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(300)
            f = conn.makefile("rwb")
            line = f.readline()
            if not line:
                return
            if line.startswith(b"GET "):
                self._handle_http(f, line)
                return
            try:
                req = json.loads(line.decode())
                resp = self._dispatch(req)
            except Exception as e:  # malformed request must not kill us
                resp = {"ok": False, "error": f"bad request: {e}"}
            f.write((json.dumps(resp) + "\n").encode())
            f.flush()
        except (OSError, ValueError):
            pass  # client went away; its member state ages out by lease
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _handle_http(self, f, request_line: bytes) -> None:
        path = request_line.split()[1].decode() if len(
            request_line.split()) > 1 else "/"
        while True:  # drain headers
            h = f.readline()
            if not h or h in (b"\r\n", b"\n"):
                break
        if path.startswith("/metrics"):
            now = time.monotonic()
            with self._lock:
                throttled = (self._scrape_min_s > 0.0 and
                             now - self._last_scrape < self._scrape_min_s)
                if throttled:
                    self._scrape_throttled += 1
                else:
                    self._last_scrape = now
            if throttled:
                # Admission control on the scrape path: a hot scraper
                # gets a deterministic backoff, not the render cost.
                body = (f"retry after "
                        f"{self._scrape_min_s:.3f}s\n").encode()
                status = "429 Too Many Requests"
            else:
                body = self.render_metrics().encode()
                status = "200 OK"
        elif path.startswith("/healthz"):
            body = b"ok\n"
            status = "200 OK"
        else:
            body = b"not found\n"
            status = "404 Not Found"
        f.write((f"HTTP/1.0 {status}\r\n"
                 "Content-Type: text/plain; version=0.0.4; "
                 "charset=utf-8\r\n"
                 f"Content-Length: {len(body)}\r\n"
                 "Connection: close\r\n\r\n").encode())
        f.write(body)
        f.flush()

    def _dispatch(self, req: Dict[str, Any]) -> Dict[str, Any]:
        op = req.get("op")
        handler = {
            "join": self._op_join,
            "sync": self._op_sync,
            "report": self._op_report,
            "heartbeat": self._op_heartbeat,
            "leave": self._op_leave,
            "collect_trace": self._op_collect_trace,
            "trace_push": self._op_trace_push,
        }.get(op)
        if handler is None:
            return {"ok": False, "error": f"unknown op: {op}"}
        return handler(req)

    # ----------------------------------------------------- arbitration

    def _alloc_inc(self) -> int:
        """Monotonic incarnation numbers (under the lock). A plain
        counter rather than itertools so snapshots can persist it — a
        restored coordinator must never re-issue a live incarnation."""
        v = self._next_inc
        self._next_inc += 1
        return v

    def _get_world(self, name: str, size: int,
                   req: Optional[Dict[str, Any]] = None) -> _World:
        w = self._worlds.get(name)
        if w is None:
            base = self.port_base + len(self._worlds) * self.port_stride
            w = _World(name, size, base, self.qp_budget)
            if req is not None:
                # Sticky world-scoped knobs, set by the FIRST join:
                # elastic opt-in, grow ceiling, fair-share weight.
                w.resizable = bool(req.get("resizable"))
                try:
                    w.max_size = max(0, int(req.get("max_size") or 0))
                except (TypeError, ValueError):
                    pass
                try:
                    w.weight = max(0.0, float(req.get("weight", 1.0)))
                except (TypeError, ValueError):
                    pass
            self._worlds[name] = w
            self._recompute_shares()
            trace.event("ctl.world", world=name, size=size, base_port=base,
                        resizable=int(w.resizable))
        return w

    def _recompute_shares(self) -> None:
        """Weighted fair-share division of the engine QP pool across
        named worlds, with per-world floors. Without qp_fair every
        world's share IS the per-world budget (stricter-wins at the
        member keeps working unchanged); with it the total divides by
        weight, and a new world's arrival re-divides — existing worlds
        adopt their new share at the next view they park for."""
        if not self.qp_fair or not self.qp_budget or not self._worlds:
            for w in self._worlds.values():
                w.qp_share = w.qp_budget
            return
        total_weight = sum(w.weight for w in self._worlds.values()) or 1.0
        for w in self._worlds.values():
            share = int(self.qp_budget * w.weight / total_weight)
            w.qp_share = max(self.qp_floor, share)

    def _apply_resize(self, w: _World) -> None:
        """Cut the RESIZE: repack the parked survivors (and any parked
        grow joiners) into contiguous ranks 0..n-1 ordered by their old
        rank, drop dead members entirely (their superseded pushes are
        rejected from here on, never re-adopted), and bump the
        generation — the new size is a membership decision like any
        other. Callers release the view immediately after, so the
        resize and its first view are one atomic arbitration step."""
        alive = sorted(w.alive_members(), key=lambda m: m.rank)
        old_size, old_ranks = w.size, [m.rank for m in alive]
        w.members = {}
        for i, m in enumerate(alive):
            m.rank = i
            w.members[i] = m
        w.size = len(alive)
        w.resizes += 1
        w.generation += 1
        trace.add("ctl.resize", 1)
        trace.event("ctl.resize", world=w.name, old_size=old_size,
                    new_size=w.size, old_ranks=old_ranks,
                    generation=w.generation, resizes=w.resizes)

    def _membership_changed(self, w: _World, why: str) -> None:
        """A slot's occupancy changed. Before the world ever became
        ready this is just the initial fill; afterwards it is a
        membership decision and bumps the generation (the ONLY place
        generations move besides failure reports)."""
        if w.ever_ready:
            w.generation += 1
            trace.event("ctl.generation", world=w.name,
                        generation=w.generation, why=why)

    def _maybe_release(self, w: _World) -> None:
        """Release the rendezvous barrier: every slot filled by a live
        member and all of them parked -> build ONE view and hand it to
        every one of them atomically (under the lock), so no two
        members can ever act on different views."""
        alive = w.alive_members()
        if not alive or not all(m.waiting for m in alive):
            return
        if {m.rank for m in alive} != set(range(w.size)):
            # Membership does not match the nominal shape: dead slots
            # (shrink candidates) or parked joiners beyond the size
            # (grow candidates). A resizable, once-ready world cuts a
            # RESIZE view to exactly the parked survivors; any other
            # world keeps waiting for the missing slots to rejoin.
            if not (w.resizable and w.ever_ready and len(alive) >= 2):
                return
            if w.grow_hold_until and \
                    set(range(w.size)) <= {m.rank for m in alive}:
                # Pure growth (every nominal slot alive, the only
                # mismatch is parked grow joiners): hold the RESIZE
                # open for the coalescing window so near-simultaneous
                # joiners land in one size+N cut — the sweeper calls
                # back when the hold expires. A shrink (dead nominal
                # slot) never waits.
                if time.monotonic() < w.grow_hold_until:
                    return
                w.grow_hold_until = 0.0
            self._apply_resize(w)
            alive = w.alive_members()
        w.epoch += 1
        if w.ever_ready:
            # Every re-release after the world first became ready IS a
            # completed rebuild — the SLO counts finished recoveries,
            # whatever initiated them (failure report, lease expiry,
            # supersede). Reports only move the generation.
            w.rebuilds += 1
        w.ever_ready = True
        now = time.monotonic()
        view = {
            "ok": True,
            "generation": w.generation,
            "epoch": w.epoch,
            "base_port": w.base_port,
            "world_size": w.size,
            "resizes": w.resizes,
            "lease_ms": self.lease_ms,
            "qp_budget": w.qp_share if self.qp_fair else w.qp_budget,
            "peers": [w.members[r].host for r in range(w.size)],
            # One topology key per slot (join-reported; None for
            # members that reported none): the member side feeds these
            # to the hierarchical grouping when no explicit
            # topology/TDR_TOPOLOGY overrides — and only when EVERY
            # slot carries a key.
            "host_keys": [w.members[r].host_key for r in range(w.size)],
        }
        for m in alive:
            m.waiting = False
            m.pending_view = dict(view, rank=m.rank,
                                  incarnation=m.incarnation)
            m.lease_deadline = now + self.lease_ms / 1000.0
        trace.event("ctl.release", world=w.name, generation=w.generation,
                    epoch=w.epoch)
        self._cv.notify_all()

    def _await_view(self, w: _World, m: _Member,
                    timeout_s: float) -> Dict[str, Any]:
        token = m.wait_token
        deadline = time.monotonic() + timeout_s
        while m.pending_view is None:
            if not m.alive:
                return {"ok": False, "error": "superseded",
                        "generation": w.generation}
            if m.wait_token != token:
                # A newer sync for this member took over the park;
                # this handler's client is gone. Don't touch
                # waiting/pending_view — they belong to the newcomer.
                return {"ok": False, "error": "superseded wait",
                        "generation": w.generation}
            left = deadline - time.monotonic()
            if left <= 0:
                m.waiting = False
                return {"ok": False, "error": "rendezvous timeout",
                        "generation": w.generation}
            self._cv.wait(min(left, 0.25))
        if m.wait_token != token:
            return {"ok": False, "error": "superseded wait",
                    "generation": w.generation}
        view, m.pending_view = m.pending_view, None
        return view

    def _member(self, req: Dict[str, Any]):
        """Resolve (world, member) for ops that address an existing
        incarnation; returns (None, error_resp) when stale."""
        w = self._worlds.get(req.get("world"))
        if w is None:
            return None, {"ok": False, "error": "unknown world"}
        m = w.members.get(int(req.get("rank", -1)))
        if m is None or not m.alive or \
                m.incarnation != int(req.get("incarnation", -1)):
            return None, {"ok": False, "error": "superseded",
                          "generation": w.generation}
        return (w, m), None

    # -------------------------------------------------------- handlers

    def _op_join(self, req: Dict[str, Any]) -> Dict[str, Any]:
        name = str(req["world"])
        size = int(req["size"])
        rank = int(req.get("rank", -1))
        host = str(req.get("host", "127.0.0.1"))
        timeout_s = min(max(float(req.get("timeout_s", 60.0)), 0.0), 600.0)
        if size < 2:
            return {"ok": False, "error": "world size must be >= 2"}
        with self._cv:
            if (self.max_worlds and req.get("world") not in self._worlds
                    and len(self._worlds) >= self.max_worlds):
                return self._admission_reject(None, "fleet full: world "
                                              "quota exhausted")
            w = self._get_world(name, size, req)
            if size != w.size and not w.resizable:
                return {"ok": False,
                        "error": f"world {name} has size {w.size}, "
                                 f"not {size}"}
            grow = False
            if rank < 0:
                free = [r for r in range(w.size)
                        if r not in w.members or not w.members[r].alive]
                if free:
                    rank = free[0]
                elif w.resizable and w.ever_ready and \
                        (not w.max_size or
                         len(w.alive_members()) < w.max_size):
                    # Grow-on-join: the world is full of live members,
                    # so this joiner parks on the slot past the end —
                    # the RESIZE to world_size+1 cuts at the next
                    # collective boundary, when every current member
                    # has parked too.
                    rank = max(w.members, default=w.size - 1) + 1
                    grow = True
                    if self._grow_hold_s > 0.0 and \
                            w.grow_hold_until < time.monotonic():
                        # First grow joiner of a burst opens the
                        # coalescing window; later ones ride it (never
                        # extend — a steady trickle must not starve
                        # the cut).
                        w.grow_hold_until = \
                            time.monotonic() + self._grow_hold_s
                else:
                    # Admission backpressure: a full fleet is a
                    # RETRYABLE condition with a deterministic
                    # retry-after, not a hard failure.
                    return self._admission_reject(w, "fleet full")
            if rank >= w.size and not grow:
                return {"ok": False,
                        "error": f"rank {rank} out of range for size "
                                 f"{w.size}"}
            prev = w.members.get(rank)
            if prev is not None and prev.alive:
                # A restarted rank racing its own lingering lease: the
                # NEW incarnation supersedes — the old one is dead by
                # definition (one process per slot).
                prev.alive = False
                self._membership_changed(w, "superseded")
            elif w.ever_ready:
                self._membership_changed(w, "grow" if grow else "rejoin")
            m = _Member(rank, self._alloc_inc(), host,
                        time.monotonic() + self.lease_ms / 1000.0,
                        host_key=req.get("host_key"))
            m.waiting = True
            w.members[rank] = m
            w.joins += 1
            trace.event("ctl.join", world=name, rank=rank,
                        incarnation=m.incarnation,
                        generation=w.generation, grow=int(grow))
            self._maybe_release(w)
            return self._await_view(w, m, timeout_s)

    def _admission_reject(self, w: Optional[_World],
                          why: str) -> Dict[str, Any]:
        """The backpressure verdict: retryable, with a retry-after
        that is a deterministic function of the lease and how many
        rejects this world has already absorbed (so a thundering herd
        spreads itself without coordination)."""
        if w is not None:
            w.admission_rejects += 1
            rejects = w.admission_rejects
        else:
            rejects = 1
        retry_after = round(
            (self.lease_ms / 1000.0) * (1 + (rejects - 1) % 3), 3)
        trace.add("ctl.admission_reject", 1)
        trace.event("ctl.admission_reject", world=w.name if w else "",
                    why=why, retry_after_s=retry_after)
        return {"ok": False, "error": why, "retryable": True,
                "retry_after_s": retry_after}

    def _op_sync(self, req: Dict[str, Any]) -> Dict[str, Any]:
        timeout_s = min(max(float(req.get("timeout_s", 60.0)), 0.0), 600.0)
        with self._cv:
            resolved, err = self._member(req)
            if err:
                return err
            w, m = resolved
            m.lease_deadline = time.monotonic() + self.lease_ms / 1000.0
            m.wait_token += 1  # supersede any orphaned park (see above)
            m.waiting = True
            m.pending_view = None
            trace.event("ctl.sync", world=w.name, rank=m.rank,
                        generation=w.generation)
            self._maybe_release(w)
            return self._await_view(w, m, timeout_s)

    def _op_report(self, req: Dict[str, Any]) -> Dict[str, Any]:
        with self._cv:
            resolved, err = self._member(req)
            if err:
                return err
            w, m = resolved
            # Idempotent per incident: the bump is keyed on the
            # reporter's believed generation — the FIRST report of an
            # incident moves the world forward; later reporters (same
            # incident, same believed generation, now stale) just
            # learn the new generation.
            if int(req.get("generation", -1)) == w.generation:
                w.generation += 1
                trace.event("ctl.report", world=w.name, rank=m.rank,
                            generation=w.generation,
                            error=str(req.get("error", ""))[:120])
                self._cv.notify_all()
            return {"ok": True, "generation": w.generation,
                    "rebuilds": w.rebuilds}

    def _op_heartbeat(self, req: Dict[str, Any]) -> Dict[str, Any]:
        # Clock-sync receive instant, stamped BEFORE the lock: the
        # member's offset math treats t1 as "when the request reached
        # the coordinator", and queueing on _cv is server processing
        # time that belongs between t1 and t2, not before t1.
        t1 = time.monotonic_ns()
        with self._cv:
            resolved, err = self._member(req)
            if err:
                return err
            w, m = resolved
            now = time.monotonic()
            m.lease_deadline = now + self.lease_ms / 1000.0
            if self._hb_min_s > 0.0 and now - m.hb_last < self._hb_min_s:
                # Rate-limited: the lease still renews (dropping THAT
                # would turn a chatty member into a dead one), but the
                # counter/histogram/clock processing is shed.
                w.hb_throttled += 1
                return {"ok": True, "generation": w.generation,
                        "throttled": True}
            m.hb_last = now
            counters = req.get("counters")
            if isinstance(counters, dict):
                m.counters = {str(k): int(v) for k, v in counters.items()}
            hists = req.get("hists")
            if isinstance(hists, dict):
                m.hists = {
                    str(name): {int(b): int(c) for b, c in buckets.items()}
                    for name, buckets in hists.items()
                    if isinstance(buckets, dict)
                }
            # Observability riders: the member's current clock-offset
            # estimate and postmortem tally (gauges on /metrics).
            for attr, key in (("clock_offset_ns", "clock_offset_ns"),
                              ("clock_rtt_ns", "clock_rtt_ns"),
                              ("postmortems", "postmortems"),
                              ("qp_reserved", "qp_reserved")):
                v = req.get(key)
                if v is not None:
                    try:
                        setattr(m, attr, int(v))
                    except (TypeError, ValueError):
                        pass
            # Degradation-ladder riders: the member's per-link health
            # snapshot and rung-engagement tally (the quarantine
            # report behind tdr_link_health / tdr_degraded_total).
            lh = req.get("link_health")
            if isinstance(lh, dict):
                m.link_health = {str(k): dict(st)
                                 for k, st in lh.items()
                                 if isinstance(st, dict)}
            dt = req.get("degraded_total")
            if dt is not None:
                try:
                    m.degraded_total = int(dt)
                except (TypeError, ValueError):
                    pass
            resp = {"ok": True, "generation": w.generation,
                    "stale": int(req.get("generation", -1)) != w.generation}
            # RESIZE hint: membership no longer matches the nominal
            # shape (a grow joiner is parked, or a slot died on a
            # resizable world) — the member should fail its next
            # collective retryably and park, so the coordinator can
            # cut the new-size view at a collective boundary.
            if w.resizable and {mm.rank for mm in w.alive_members()} \
                    != set(range(w.size)):
                resp["resize_pending"] = True
            # Pending trace pull this member has not served yet: flag
            # it so the member's heartbeat thread drains and pushes.
            tr = w.trace_req
            if tr is not None and m.rank not in tr["segments"]:
                resp["collect"] = {"id": tr["id"],
                                   "max_events": tr["max_events"]}
            # Clock-sync echo: t0 back verbatim (the member matches it
            # against the beat it stamped), our receive and send
            # instants alongside.
            t0 = req.get("t0_ns")
            if t0 is not None:
                resp["t0_ns"] = t0
                resp["t1_ns"] = t1
                resp["t2_ns"] = time.monotonic_ns()
            return resp

    def _op_trace_push(self, req: Dict[str, Any]) -> Dict[str, Any]:
        """A member's answer to a collect flag: one bounded event
        segment. Stored under the request id; the parked collector
        wakes when every live rank has pushed."""
        with self._cv:
            resolved, err = self._member(req)
            if err:
                return err
            w, m = resolved
            tr = w.trace_req
            if tr is None or int(req.get("trace_id", -1)) != tr["id"]:
                return {"ok": False, "error": "stale trace id"}
            seg = req.get("segment")
            if not isinstance(seg, dict):
                return {"ok": False, "error": "bad segment"}
            seg = dict(seg)
            seg["rank"] = m.rank
            seg["incarnation"] = m.incarnation
            tr["segments"][m.rank] = seg
            trace.event("ctl.trace_push", world=w.name, rank=m.rank,
                        trace_id=tr["id"],
                        events=len(seg.get("events") or []))
            self._cv.notify_all()
            return {"ok": True}

    def _op_collect_trace(self, req: Dict[str, Any]) -> Dict[str, Any]:
        """Operator op: pull one flight-recorder segment from every
        live rank of a world. Parks until all ranks pushed or the
        caller's budget expires; a timeout returns ok=False WITH
        whatever arrived (partial visibility beats none during an
        incident)."""
        name = req.get("world")
        timeout_s = min(max(float(req.get("timeout_s", 30.0)), 0.0), 600.0)
        max_events = max(1, min(int(req.get("max_events", 65536)),
                                1 << 20))
        deadline = time.monotonic() + timeout_s
        with self._cv:
            w = self._worlds.get(name)
            if w is None:
                return {"ok": False, "error": "unknown world"}
            if w.trace_req is not None:
                return {"ok": False,
                        "error": "trace collection already in progress"}
            w.trace_seq += 1
            tr = {"id": w.trace_seq, "max_events": max_events,
                  "segments": {}}
            w.trace_req = tr
            trace.event("ctl.collect_trace", world=w.name,
                        trace_id=tr["id"], max_events=max_events)
            try:
                while True:
                    alive = {m.rank for m in w.alive_members()}
                    if alive and alive <= set(tr["segments"]):
                        break
                    left = deadline - time.monotonic()
                    if left <= 0:
                        return {
                            "ok": False, "error": "collect timeout",
                            "generation": w.generation,
                            "world_size": w.size,
                            "segments": {str(r): s for r, s in
                                         sorted(tr["segments"].items())},
                        }
                    self._cv.wait(min(left, 0.25))
            finally:
                w.trace_req = None
            return {
                "ok": True,
                "generation": w.generation,
                "world_size": w.size,
                "segments": {str(r): s for r, s in
                             sorted(tr["segments"].items())},
            }

    def _op_leave(self, req: Dict[str, Any]) -> Dict[str, Any]:
        with self._cv:
            resolved, err = self._member(req)
            if err:
                return err
            w, m = resolved
            m.alive = False
            trace.event("ctl.leave", world=w.name, rank=m.rank)
            self._membership_changed(w, "leave")
            # Survivors may ALREADY be parked (they saw the leaver's
            # QPs close before the leave arrived): a resizable world
            # must cut its shrink view now, not wait for a rejoin.
            self._maybe_release(w)
            self._cv.notify_all()
            return {"ok": True, "generation": w.generation}

    # ---------------------------------------------------------- leases

    def _sweep(self) -> None:
        interval = max(0.05, self.lease_ms / 4000.0)
        while not self._stop.wait(interval):
            now = time.monotonic()
            with self._cv:
                for w in self._worlds.values():
                    for m in w.alive_members():
                        # A parked member IS live: its rendezvous
                        # connection is open, and during initial join
                        # its heartbeat thread has not started yet.
                        if m.waiting or m.lease_deadline > now:
                            continue
                        m.alive = False
                        w.lease_expiries += 1
                        trace.event("ctl.lease_expired", world=w.name,
                                    rank=m.rank,
                                    incarnation=m.incarnation)
                        self._membership_changed(w, "lease")
                        # The expiry may complete a shrink: survivors
                        # parked waiting for this verdict get their
                        # world_size-1 view here instead of timing out.
                        self._maybe_release(w)
                        self._cv.notify_all()
                    if w.grow_hold_until and now >= w.grow_hold_until:
                        # A batch-admission hold ran out with everyone
                        # parked: cut the coalesced grow view now.
                        self._maybe_release(w)
                        self._cv.notify_all()

    # ------------------------------------------------------- snapshots

    SNAPSHOT_FILE = "coordinator.json"

    @classmethod
    def _load_snapshot(cls, snapshot_dir: Optional[str]
                       ) -> Optional[Dict[str, Any]]:
        if not snapshot_dir:
            return None
        path = os.path.join(snapshot_dir, cls.SNAPSHOT_FILE)
        try:
            with open(path) as f:
                snap = json.load(f)
        except (OSError, ValueError):
            return None
        if snap.get("format") != "tdr-ctl-snapshot-v1":
            return None
        return snap

    def _snapshot_state(self) -> Dict[str, Any]:
        """The full arbitration state, JSON-shaped (caller holds the
        lock). Contract: restoring this dict yields a coordinator that
        resumes arbitration — same worlds, generations, incarnations,
        port arena, budgets, counters — with every lease restarted."""
        worlds = {}
        for name, w in self._worlds.items():
            worlds[name] = {
                "size": w.size, "base_port": w.base_port,
                "qp_budget": w.qp_budget, "generation": w.generation,
                "epoch": w.epoch, "ever_ready": w.ever_ready,
                "rebuilds": w.rebuilds,
                "lease_expiries": w.lease_expiries, "joins": w.joins,
                "trace_seq": w.trace_seq, "resizable": w.resizable,
                "max_size": w.max_size, "resizes": w.resizes,
                "weight": w.weight, "qp_share": w.qp_share,
                "admission_rejects": w.admission_rejects,
                "hb_throttled": w.hb_throttled,
                "members": [{
                    "rank": m.rank, "incarnation": m.incarnation,
                    "host": m.host, "host_key": m.host_key,
                    "alive": m.alive, "counters": m.counters,
                    "hists": {h: {str(b): c for b, c in bk.items()}
                              for h, bk in m.hists.items()},
                    "clock_offset_ns": m.clock_offset_ns,
                    "clock_rtt_ns": m.clock_rtt_ns,
                    "postmortems": m.postmortems,
                    "qp_reserved": m.qp_reserved,
                    "link_health": m.link_health,
                    "degraded_total": m.degraded_total,
                } for m in w.members.values()],
            }
        return {
            "format": "tdr-ctl-snapshot-v1",
            "port": self.port, "lease_ms": self.lease_ms,
            "port_base": self.port_base,
            "port_stride": self.port_stride,
            "qp_budget": self.qp_budget, "qp_fair": self.qp_fair,
            "qp_floor": self.qp_floor, "next_inc": self._next_inc,
            "failovers": self.failovers, "wall_time": time.time(),
            "worlds": worlds,
        }

    def snapshot_now(self) -> Optional[str]:
        """Write one snapshot atomically (tmp + rename); returns the
        path, or None without a snapshot_dir."""
        if not self.snapshot_dir:
            return None
        with self._lock:
            snap = self._snapshot_state()
        os.makedirs(self.snapshot_dir, exist_ok=True)
        path = os.path.join(self.snapshot_dir, self.SNAPSHOT_FILE)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(snap, f)
        os.replace(tmp, path)
        self._last_snapshot = time.time()
        return path

    def _snapshots(self) -> None:
        while not self._stop.wait(self.snapshot_interval_s):
            try:
                self.snapshot_now()
            except OSError:
                pass  # a full disk must not take arbitration down

    def _restore_state(self, snap: Dict[str, Any]) -> None:
        """Resume arbitration from a snapshot: worlds come back with
        their generations/epochs/counters intact, every member's lease
        restarts at a full TTL (members re-attach by simply continuing
        to heartbeat — the incarnations they hold still resolve), and
        nobody is parked (members mid-rendezvous when the old
        coordinator died will retry their sync/join). A restore IS a
        failover: the counter bumps and is served on /metrics."""
        self.lease_ms = int(snap.get("lease_ms", self.lease_ms))
        self.port_base = int(snap.get("port_base", self.port_base))
        self.port_stride = int(snap.get("port_stride", self.port_stride))
        self.qp_budget = int(snap.get("qp_budget", self.qp_budget))
        self.qp_fair = bool(snap.get("qp_fair", self.qp_fair))
        self.qp_floor = int(snap.get("qp_floor", self.qp_floor))
        self._next_inc = max(self._next_inc,
                             int(snap.get("next_inc", 1)))
        self.failovers = int(snap.get("failovers", 0)) + 1
        now = time.monotonic()
        lease = self.lease_ms / 1000.0
        for name, wd in (snap.get("worlds") or {}).items():
            w = _World(str(name), int(wd["size"]),
                       int(wd["base_port"]), int(wd.get("qp_budget", 0)))
            w.generation = int(wd.get("generation", 0))
            w.epoch = int(wd.get("epoch", 0))
            w.ever_ready = bool(wd.get("ever_ready"))
            w.rebuilds = int(wd.get("rebuilds", 0))
            w.lease_expiries = int(wd.get("lease_expiries", 0))
            w.joins = int(wd.get("joins", 0))
            w.trace_seq = int(wd.get("trace_seq", 0))
            w.resizable = bool(wd.get("resizable"))
            w.max_size = int(wd.get("max_size", 0))
            w.resizes = int(wd.get("resizes", 0))
            w.weight = float(wd.get("weight", 1.0))
            w.qp_share = int(wd.get("qp_share", w.qp_budget))
            w.admission_rejects = int(wd.get("admission_rejects", 0))
            w.hb_throttled = int(wd.get("hb_throttled", 0))
            for md in wd.get("members") or []:
                m = _Member(int(md["rank"]), int(md["incarnation"]),
                            str(md.get("host", "127.0.0.1")),
                            now + lease, host_key=md.get("host_key"))
                m.alive = bool(md.get("alive", True))
                m.counters = {str(k): int(v) for k, v in
                              (md.get("counters") or {}).items()}
                m.hists = {str(h): {int(b): int(c)
                                    for b, c in bk.items()}
                           for h, bk in (md.get("hists") or {}).items()}
                m.clock_offset_ns = int(md.get("clock_offset_ns", 0))
                m.clock_rtt_ns = int(md.get("clock_rtt_ns", 0))
                m.postmortems = int(md.get("postmortems", 0))
                m.qp_reserved = int(md.get("qp_reserved", 0))
                lh = md.get("link_health")
                if isinstance(lh, dict):
                    m.link_health = {str(k): dict(st)
                                     for k, st in lh.items()
                                     if isinstance(st, dict)}
                m.degraded_total = int(md.get("degraded_total", 0))
                w.members[m.rank] = m
            self._worlds[w.name] = w
        trace.add("ctl.failover", 1)
        trace.event("ctl.restore", worlds=len(self._worlds),
                    failovers=self.failovers,
                    next_inc=self._next_inc)

    # --------------------------------------------------------- metrics

    @staticmethod
    def _metric_name(counter: str) -> str:
        safe = "".join(c if c.isalnum() else "_" for c in counter)
        return f"tdr_{safe}_total"

    def render_metrics(self) -> str:
        """The Prometheus-style text exposition. Contract-pinned names
        (tests/test_control.py): ``tdr_ctl_generation``,
        ``tdr_ctl_members``, ``tdr_ctl_rebuilds_total``,
        ``tdr_ctl_lease_expiries_total``, ``tdr_retransmit_rate``, the
        ``tdr_<registry counter>_total`` family (dots -> underscores,
        e.g. ``tdr_integrity_retransmitted_total``) — served both as
        the per-world aggregate (``{world=}``, label shape unchanged)
        and per member (``{world=,rank=}``) — and the histogram
        quantile series ``tdr_<hist>{...,quantile="0.99"}`` (e.g.
        ``tdr_chunk_lat_us``). Fleet-tracing additions (also
        contract-pinned): ``tdr_postmortems_total{world=}`` (black-box
        bundles written across the world) and
        ``tdr_clock_offset_us{world=,rank=}`` /
        ``tdr_clock_rtt_us{world=,rank=}`` (each member's min-RTT
        clock estimate vs this coordinator); note
        ``tdr_telemetry_dropped_total{world=,rank=}`` already rides
        the registry family — a nonzero value taints event-derived
        fractions for that rank's windows."""
        with self._lock:
            lines = [
                f"# tdr coordinator metrics v{PROTOCOL_VERSION}",
                "# TYPE tdr_ctl_worlds gauge",
                f"tdr_ctl_worlds {len(self._worlds)}",
                "# TYPE tdr_ctl_failovers_total counter",
                f"tdr_ctl_failovers_total {self.failovers}",
                "# TYPE tdr_ctl_scrape_throttled_total counter",
                f"tdr_ctl_scrape_throttled_total "
                f"{self._scrape_throttled}",
            ]
            if self.snapshot_dir:
                age = (time.time() - self._last_snapshot
                       if self._last_snapshot else -1.0)
                lines.append("# TYPE tdr_ctl_snapshot_age_s gauge")
                lines.append(f"tdr_ctl_snapshot_age_s {age:.3f}")
            lines.append("# TYPE tdr_ctl_generation gauge")
            lines.append("# TYPE tdr_ctl_members gauge")
            lines.append("# TYPE tdr_ctl_rebuilds_total counter")
            lines.append("# TYPE tdr_ctl_lease_expiries_total counter")
            for name in sorted(self._worlds):
                w = self._worlds[name]
                lab = f'{{world="{name}"}}'
                lines += [
                    f"tdr_ctl_generation{lab} {w.generation}",
                    f"tdr_ctl_epoch{lab} {w.epoch}",
                    f"tdr_ctl_size{lab} {w.size}",
                    f"tdr_ctl_members{lab} {len(w.alive_members())}",
                    f"tdr_ctl_base_port{lab} {w.base_port}",
                    f"tdr_ctl_rebuilds_total{lab} {w.rebuilds}",
                    f"tdr_ctl_lease_expiries_total{lab} "
                    f"{w.lease_expiries}",
                    f"tdr_ctl_joins_total{lab} {w.joins}",
                    f"tdr_ctl_resizes_total{lab} {w.resizes}",
                    f"tdr_ctl_resizable{lab} {int(w.resizable)}",
                    # Fair-share gauges: this world's computed slice
                    # of the engine QP pool vs the appetite its live
                    # members actually reserved at bring-up.
                    f"tdr_ctl_qp_share{lab} {w.qp_share}",
                    f"tdr_ctl_qp_reserved{lab} "
                    f"{sum(m.qp_reserved for m in w.alive_members())}",
                    f"tdr_ctl_admission_rejects_total{lab} "
                    f"{w.admission_rejects}",
                    f"tdr_ctl_hb_throttled_total{lab} {w.hb_throttled}",
                    # Black-box postmortems written across the world's
                    # slots (heartbeat-pushed; slots keep serving their
                    # current occupant's tally like every other series).
                    f"tdr_postmortems_total{lab} "
                    f"{sum(m.postmortems for m in w.members.values())}",
                ]
                # Per-member clock offsets vs this coordinator (µs;
                # min-RTT filtered on the member side) — the numbers a
                # fleet trace merge aligns timestamps with, and the
                # live skew readout tdr_top --connect renders.
                for m in sorted(w.members.values(), key=lambda m: m.rank):
                    rlab = f'{{world="{name}",rank="{m.rank}"}}'
                    lines.append(f"tdr_clock_offset_us{rlab} "
                                 f"{m.clock_offset_ns / 1000.0:.6g}")
                    lines.append(f"tdr_clock_rtt_us{rlab} "
                                 f"{m.clock_rtt_ns / 1000.0:.6g}")
                # Slow-rank quarantine report (heartbeat-pushed ladder
                # scores): WHICH link each member's degradation ladder
                # is acting on, plus the world's rung-engagement tally.
                # Contract-pinned names (tests/test_control.py):
                # tdr_link_health{world=,rank=,peer=} and
                # tdr_degraded_total{world=}.
                for m in sorted(w.members.values(), key=lambda m: m.rank):
                    for link in sorted(m.link_health):
                        st = m.link_health[link]
                        try:
                            score = float(st.get("score", 1.0))
                            peer = int(st.get("peer", -1))
                        except (TypeError, ValueError):
                            continue
                        lines.append(
                            f'tdr_link_health{{world="{name}",'
                            f'rank="{m.rank}",peer="{peer}",'
                            f'link="{link}"}} {score:.6g}')
                lines.append(
                    f"tdr_degraded_total{lab} "
                    f"{sum(m.degraded_total for m in w.members.values())}")
                # Member-pushed counter registry, summed over each
                # slot's CURRENT occupant — dead or departed members
                # keep serving their last snapshot (a scraper must not
                # see the world's history vanish because a rank died;
                # exact when members are one process each).
                agg: Dict[str, int] = {}
                hists: Dict[str, List[int]] = {}
                for m in w.members.values():
                    for k, v in m.counters.items():
                        agg[k] = agg.get(k, 0) + v
                    for hname, buckets in m.hists.items():
                        row = hists.setdefault(hname, [0] * 64)
                        for b, c in buckets.items():
                            # Native rows are 64 octave buckets;
                            # python-tier fine (log2×8) rows carry
                            # indices past 64 (plus a {64: 0} marker
                            # so hist_percentile reads fine edges) —
                            # grow the row to fit, capped well above
                            # any real fine index (2^64 ns ≈ bucket
                            # 488) so a corrupt push can't balloon it.
                            if not 0 <= b < 512:
                                continue
                            if b >= len(row):
                                row.extend([0] * (b + 1 - len(row)))
                            row[b] += c
                for k in sorted(agg):
                    lines.append(f"{self._metric_name(k)}{lab} {agg[k]}")
                # Per-member series: the same registry counters, one
                # series per ring slot with a rank label — a scraper
                # can tell WHICH member's retransmit ladder is moving
                # without losing the aggregate (whose label shape and
                # values above are unchanged, contract-pinned). Slots
                # keep serving their current occupant's last snapshot,
                # exactly like the aggregate.
                for m in sorted(w.members.values(), key=lambda m: m.rank):
                    if not m.counters:
                        continue
                    rlab = f'{{world="{name}",rank="{m.rank}"}}'
                    for k in sorted(m.counters):
                        lines.append(
                            f"{self._metric_name(k)}{rlab} "
                            f"{m.counters[k]}")
                sealed = agg.get("integrity.sealed", 0)
                retx = agg.get("integrity.retransmitted", 0)
                rate = (retx / sealed) if sealed else 0.0
                lines.append(f"tdr_retransmit_rate{lab} {rate:.6g}")
                for hname in sorted(hists):
                    safe = "".join(c if c.isalnum() else "_"
                                   for c in hname)
                    for q in _QUANTILES:
                        v = hist_percentile(hists[hname], q)
                        lines.append(
                            f'tdr_{safe}{{world="{name}",'
                            f'quantile="0.{q}"}} {v}')
                    lines.append(
                        f"tdr_{safe}_count{lab} {sum(hists[hname])}")
            return "\n".join(lines) + "\n"


class Standby:
    """Warm standby for the coordinator: tails the snapshot directory,
    probes the active coordinator's ``/healthz``, and after
    ``fail_threshold`` consecutive probe failures promotes itself —
    restoring the latest snapshot and binding the SAME port the fleet
    already dials (the dead coordinator's socket is gone, so the bind
    succeeds exactly when takeover is legitimate). Members notice
    nothing but a missed heartbeat or two: their incarnations still
    resolve against the restored state.

    ``promoted`` is set once takeover completed; ``coordinator`` then
    holds the live replacement (the caller owns stopping it)."""

    def __init__(self, snapshot_dir: str, address: Optional[str] = None,
                 host: str = "127.0.0.1", probe_interval_s: float = 0.5,
                 fail_threshold: int = 3):
        self.snapshot_dir = snapshot_dir
        self.address = address  # None: probe the snapshot's port
        self.host = host
        self.probe_interval_s = max(0.05, float(probe_interval_s))
        self.fail_threshold = max(1, int(fail_threshold))
        self.coordinator: Optional[Coordinator] = None
        self.promoted = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _probe_target(self) -> Optional[tuple]:
        if self.address:
            host, _, port = self.address.rpartition(":")
            return (host, int(port))
        snap = Coordinator._load_snapshot(self.snapshot_dir)
        if snap is None:
            return None
        return (self.host, int(snap.get("port", 0)))

    def _healthy(self, target: tuple) -> bool:
        try:
            with socket.create_connection(target, timeout=2.0) as s:
                s.sendall(b"GET /healthz HTTP/1.0\r\n\r\n")
                return b"200" in s.recv(256)
        except OSError:
            return False

    def _watch(self) -> None:
        failures = 0
        while not self._stop.wait(self.probe_interval_s):
            target = self._probe_target()
            if target is None or not target[1]:
                continue  # no snapshot yet: nothing to guard
            if self._healthy(target):
                failures = 0
                continue
            failures += 1
            if failures < self.fail_threshold:
                continue
            try:
                self.coordinator = Coordinator(
                    host=self.host, port=0, restore=True,
                    snapshot_dir=self.snapshot_dir).start()
            except OSError:
                # Port still held (the old coordinator is wedged, not
                # dead, or another standby won the race): keep
                # probing — takeover is only legitimate once the bind
                # succeeds.
                failures = 0
                continue
            trace.add("ctl.failover", 1)
            trace.event("ctl.standby_takeover",
                        address=self.coordinator.address,
                        failovers=self.coordinator.failovers)
            self.promoted.set()
            return

    def start(self) -> "Standby":
        self._thread = threading.Thread(target=self._watch, daemon=True,
                                        name="tdr-ctl-standby")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self.coordinator is not None:
            self.coordinator.stop()
