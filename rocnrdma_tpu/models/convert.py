"""HuggingFace Llama checkpoint import.

The reference ships no models (SURVEY.md: it is a transport driver);
the Llama family here is the BASELINE config-4 consumer, and real
checkpoints are how a user actually runs it. This module maps a
`transformers` Llama state dict (LlamaForCausalLM layout) onto this
package's flax parameter tree.

Conventions that make the mapping a pure transpose job:
- torch ``nn.Linear.weight`` is (out, in); flax ``Dense`` kernel is
  (in, out) → transpose every projection.
- HF checkpoints use the rotate-half (GPT-NeoX-style) RoPE layout —
  the same convention ``models.llama.apply_rope`` implements — so no
  head-dim permutation is needed.
- Head ordering is head-major in both (row block h covers
  ``h*head_dim .. (h+1)*head_dim``).
- ``tie_word_embeddings`` checkpoints have no ``lm_head.weight``; the
  embedding matrix is reused.
"""

from __future__ import annotations

from typing import Any, Dict, Mapping, Tuple

import jax.numpy as jnp
import numpy as np

from rocnrdma_tpu.models.llama import Llama, LlamaConfig


def config_from_hf(hf_config: Any, name: str = "llama-hf",
                   **overrides) -> LlamaConfig:
    """LlamaConfig from a transformers LlamaConfig(-like) object."""
    derived_hd = hf_config.hidden_size // hf_config.num_attention_heads
    explicit_hd = getattr(hf_config, "head_dim", None) or derived_hd
    if explicit_hd != derived_hd:
        raise ValueError(
            f"unsupported checkpoint: explicit head_dim={explicit_hd} != "
            f"hidden_size/num_heads={derived_hd} (this architecture "
            "derives head_dim; width-pruned checkpoints need resizing)")
    cfg = LlamaConfig(
        name=name,
        vocab_size=hf_config.vocab_size,
        d_model=hf_config.hidden_size,
        n_layers=hf_config.num_hidden_layers,
        n_heads=hf_config.num_attention_heads,
        n_kv_heads=getattr(hf_config, "num_key_value_heads",
                           hf_config.num_attention_heads),
        d_ff=hf_config.intermediate_size,
        max_seq_len=hf_config.max_position_embeddings,
        rope_theta=float(getattr(hf_config, "rope_theta", 10000.0)),
        norm_eps=float(getattr(hf_config, "rms_norm_eps", 1e-5)),
    )
    if overrides:
        import dataclasses

        cfg = dataclasses.replace(cfg, **overrides)
    return cfg


def _np(t) -> np.ndarray:
    """torch tensor / array-like → numpy, via f32 so bf16/f16
    checkpoint tensors (which numpy cannot represent) convert."""
    if hasattr(t, "detach"):
        import torch

        return t.detach().to(torch.float32).cpu().numpy()
    return np.asarray(t)


def from_hf_state_dict(cfg: LlamaConfig,
                       state: Mapping[str, Any]) -> Dict[str, Any]:
    """Map an HF LlamaForCausalLM state dict to this package's flax
    params pytree (``{"params": ...}``), cast to ``cfg.dtype``."""

    def dense(key: str) -> Dict[str, jnp.ndarray]:
        w = _np(state[key])
        return {"kernel": jnp.asarray(w.T, dtype=cfg.dtype)}

    def norm(key: str) -> Dict[str, jnp.ndarray]:
        # Norm weights stay f32 (they are f32 params in the model).
        return {"weight": jnp.asarray(_np(state[key]), dtype=jnp.float32)}

    params: Dict[str, Any] = {
        "embed": {
            "embedding": jnp.asarray(
                _np(state["model.embed_tokens.weight"]), dtype=cfg.dtype)
        },
        "final_norm": norm("model.norm.weight"),
    }
    if "lm_head.weight" in state:
        params["lm_head"] = dense("lm_head.weight")
    else:  # tied embeddings
        params["lm_head"] = {
            "kernel": jnp.asarray(
                _np(state["model.embed_tokens.weight"]).T, dtype=cfg.dtype)
        }
    for i in range(cfg.n_layers):
        hf = f"model.layers.{i}"
        params[f"layer_{i}"] = {
            "attn": {
                "wq": dense(f"{hf}.self_attn.q_proj.weight"),
                "wk": dense(f"{hf}.self_attn.k_proj.weight"),
                "wv": dense(f"{hf}.self_attn.v_proj.weight"),
                "wo": dense(f"{hf}.self_attn.o_proj.weight"),
            },
            "attn_norm": norm(f"{hf}.input_layernorm.weight"),
            "mlp": {
                "w_gate": dense(f"{hf}.mlp.gate_proj.weight"),
                "w_up": dense(f"{hf}.mlp.up_proj.weight"),
                "w_down": dense(f"{hf}.mlp.down_proj.weight"),
            },
            "mlp_norm": norm(f"{hf}.post_attention_layernorm.weight"),
        }
    return {"params": params}


def from_hf_model(hf_model: Any, name: str = "llama-hf",
                  **overrides) -> Tuple[Llama, Dict[str, Any]]:
    """(model, params) from a live transformers LlamaForCausalLM."""
    cfg = config_from_hf(hf_model.config, name=name, **overrides)
    model = Llama(cfg)
    params = from_hf_state_dict(cfg, hf_model.state_dict())
    return model, params


def from_hf_pretrained(path_or_repo: str, name: str = "llama-hf",
                       **overrides) -> Tuple[Llama, Dict[str, Any]]:
    """(model, params) from a local HF checkpoint directory (or hub id
    where network access exists)."""
    from transformers import AutoModelForCausalLM

    hf = AutoModelForCausalLM.from_pretrained(path_or_repo)
    return from_hf_model(hf, name=name, **overrides)
