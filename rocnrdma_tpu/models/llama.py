"""Llama model family — the flagship consumer of the framework.

The reference repo ships no models (it is a transport driver); the
Llama-3-8B multi-slice DP training demo is mandated by BASELINE.md
config 4 as the end-to-end consumer whose cross-slice gradient
allreduce rides the RDMA path. The model is written TPU-first:

- bf16 params/activations by default (MXU-native), f32 logits for the
  loss;
- RoPE, GQA, SwiGLU per the Llama 3 architecture;
- attention and RMSNorm dispatch to the Pallas kernels in ``ops/``
  (XLA reference paths remain selectable and are used for training
  until the Pallas backward lands);
- no data-dependent Python control flow — the whole step jits and
  shards under pjit.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from rocnrdma_tpu.ops.attention import attention
from rocnrdma_tpu.ops.rmsnorm import rmsnorm


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    name: str
    vocab_size: int
    d_model: int
    n_layers: int
    n_heads: int
    n_kv_heads: int
    d_ff: int
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    use_pallas_attention: bool = False
    use_pallas_rmsnorm: bool = False
    pallas_interpret: bool = False

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    def param_count(self) -> int:
        emb = self.vocab_size * self.d_model
        attn = self.d_model * self.head_dim * (
            self.n_heads + 2 * self.n_kv_heads) + \
            self.n_heads * self.head_dim * self.d_model
        mlp = 3 * self.d_model * self.d_ff
        per_layer = attn + mlp + 2 * self.d_model
        return 2 * emb + self.n_layers * per_layer + self.d_model


# Llama-3-8B, the flagship (meta-llama/Meta-Llama-3-8B geometry).
LLAMA3_8B = LlamaConfig(
    name="llama3-8b", vocab_size=128256, d_model=4096, n_layers=32,
    n_heads=32, n_kv_heads=8, d_ff=14336, rope_theta=500000.0)

# ~1B proxy with the same architecture — fits a single v5e chip with
# optimizer state for single-chip runs and benches.
LLAMA3_1B = LlamaConfig(
    name="llama3-1b", vocab_size=32768, d_model=2048, n_layers=16,
    n_heads=16, n_kv_heads=8, d_ff=5632)

# Tiny config for tests and multi-chip dry runs.
LLAMA_TINY = LlamaConfig(
    name="llama-tiny", vocab_size=256, d_model=64, n_layers=2,
    n_heads=4, n_kv_heads=2, d_ff=128, max_seq_len=128,
    dtype=jnp.float32)

CONFIGS = {c.name: c for c in (LLAMA3_8B, LLAMA3_1B, LLAMA_TINY)}


def rope_freqs(head_dim: int, max_seq: int, theta: float) -> jnp.ndarray:
    inv = 1.0 / (theta ** (jnp.arange(0, head_dim, 2,
                                      dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_seq, dtype=jnp.float32)
    return jnp.outer(t, inv)  # (S, D/2)


def apply_rope(x: jnp.ndarray, freqs: jnp.ndarray) -> jnp.ndarray:
    """x: (B, H, S, D); freqs: (S, D/2)."""
    xf = x.astype(jnp.float32)
    x1, x2 = jnp.split(xf, 2, axis=-1)
    cos = jnp.cos(freqs)[None, None]
    sin = jnp.sin(freqs)[None, None]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1).astype(x.dtype)


class RMSNorm(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x):
        w = self.param("weight", nn.initializers.ones, (x.shape[-1],),
                       jnp.float32)
        return rmsnorm(x, w, self.cfg.norm_eps,
                       use_pallas=self.cfg.use_pallas_rmsnorm,
                       interpret=self.cfg.pallas_interpret)


class Attention(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, freqs):
        cfg = self.cfg
        b, s, _ = x.shape
        hd = cfg.head_dim
        dense = lambda feats, name: nn.Dense(
            feats, use_bias=False, dtype=cfg.dtype,
            param_dtype=cfg.dtype, name=name)
        q = dense(cfg.n_heads * hd, "wq")(x)
        k = dense(cfg.n_kv_heads * hd, "wk")(x)
        v = dense(cfg.n_kv_heads * hd, "wv")(x)
        q = q.reshape(b, s, cfg.n_heads, hd).transpose(0, 2, 1, 3)
        k = k.reshape(b, s, cfg.n_kv_heads, hd).transpose(0, 2, 1, 3)
        v = v.reshape(b, s, cfg.n_kv_heads, hd).transpose(0, 2, 1, 3)
        q = apply_rope(q, freqs[:s])
        k = apply_rope(k, freqs[:s])
        o = attention(q, k, v, causal=True,
                      use_pallas=cfg.use_pallas_attention,
                      interpret=cfg.pallas_interpret)
        o = o.transpose(0, 2, 1, 3).reshape(b, s, cfg.n_heads * hd)
        return dense(cfg.d_model, "wo")(o)


class MLP(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        dense = lambda feats, name: nn.Dense(
            feats, use_bias=False, dtype=cfg.dtype,
            param_dtype=cfg.dtype, name=name)
        gate = dense(cfg.d_ff, "w_gate")(x)
        up = dense(cfg.d_ff, "w_up")(x)
        return dense(cfg.d_model, "w_down")(nn.silu(gate) * up)


class Block(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, freqs):
        x = x + Attention(self.cfg, name="attn")(
            RMSNorm(self.cfg, name="attn_norm")(x), freqs)
        x = x + MLP(self.cfg, name="mlp")(
            RMSNorm(self.cfg, name="mlp_norm")(x))
        return x


class Llama(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, tokens: jnp.ndarray) -> jnp.ndarray:
        """tokens: (B, S) int32 → logits (B, S, vocab) f32."""
        cfg = self.cfg
        if tokens.shape[-1] > cfg.max_seq_len:
            raise ValueError(
                f"sequence length {tokens.shape[-1]} exceeds "
                f"{cfg.name}'s max_seq_len={cfg.max_seq_len}")
        emb = nn.Embed(cfg.vocab_size, cfg.d_model,
                       dtype=cfg.dtype, param_dtype=cfg.dtype,
                       name="embed")
        x = emb(tokens)
        freqs = rope_freqs(cfg.head_dim, cfg.max_seq_len, cfg.rope_theta)
        for i in range(cfg.n_layers):
            x = Block(cfg, name=f"layer_{i}")(x, freqs)
        x = RMSNorm(cfg, name="final_norm")(x)
        logits = nn.Dense(cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                          param_dtype=cfg.dtype, name="lm_head")(x)
        return logits.astype(jnp.float32)


def make_model(config: "LlamaConfig | str", **overrides) -> Llama:
    cfg = CONFIGS[config] if isinstance(config, str) else config
    if overrides:
        cfg = dataclasses.replace(cfg, **overrides)
    return Llama(cfg)


def init_params(model: Llama, rng, batch: int = 1, seq: int = 8):
    tokens = jnp.zeros((batch, seq), dtype=jnp.int32)
    return model.init(rng, tokens)


def cross_entropy_loss(logits: jnp.ndarray, targets: jnp.ndarray
                       ) -> jnp.ndarray:
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(nll)
