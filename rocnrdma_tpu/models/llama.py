"""Llama model family — the flagship consumer of the framework.

The reference repo ships no models (it is a transport driver); the
Llama-3-8B multi-slice DP training demo is mandated by BASELINE.md
config 4 as the end-to-end consumer whose cross-slice gradient
allreduce rides the RDMA path. The model is written TPU-first:

- bf16 params/activations by default (MXU-native), f32 logits for the
  loss;
- RoPE, GQA, SwiGLU per the Llama 3 architecture;
- attention and RMSNorm dispatch to the Pallas kernels in ``ops/``;
  the per-op flags default to ``None`` = **auto**: the fused kernels
  are the compute path whenever the default backend is TPU, and the
  XLA reference path is used elsewhere (CPU tests run the kernels in
  interpret mode for parity instead);
- no data-dependent Python control flow — the whole step jits and
  shards under pjit.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from rocnrdma_tpu.ops.attention import attention
from rocnrdma_tpu.ops.rmsnorm import rmsnorm


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    name: str
    vocab_size: int
    d_model: int
    n_layers: int
    n_heads: int
    n_kv_heads: int
    d_ff: int
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    # None = auto: per-pass measured default on TPU backends (flash
    # attention Pallas, rmsnorm XLA — see resolve_pallas), XLA
    # reference elsewhere.
    use_pallas_attention: Optional[bool] = None
    use_pallas_rmsnorm: Optional[bool] = None
    pallas_interpret: bool = False
    # Rematerialize each transformer block in the backward pass
    # (jax.checkpoint): activations are recomputed instead of stored,
    # trading ~1/3 more FLOPs for O(layers × S²) less HBM — without it
    # a 1B-model train step at seq 2048 exceeds a v5e chip's 16 GiB.
    # Applies to training forwards only (decode has no backward).
    remat: bool = False
    # Remat recompute policy: "full" recomputes everything (minimum
    # memory); "dots" saves matmul outputs and recomputes only the
    # cheap elementwise work (jax.checkpoint_policies
    # .dots_with_no_batch_dims_saveable) — fewer backward FLOPs for
    # O(layers x tokens x d_ff) more HBM, the standard lever when the
    # chip has headroom and MFU is the target.
    remat_policy: str = "full"

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    def param_count(self) -> int:
        emb = self.vocab_size * self.d_model
        attn = self.d_model * self.head_dim * (
            self.n_heads + 2 * self.n_kv_heads) + \
            self.n_heads * self.head_dim * self.d_model
        mlp = 3 * self.d_model * self.d_ff
        per_layer = attn + mlp + 2 * self.d_model
        return 2 * emb + self.n_layers * per_layer + self.d_model


# Llama-3-8B, the flagship (meta-llama/Meta-Llama-3-8B geometry).
LLAMA3_8B = LlamaConfig(
    name="llama3-8b", vocab_size=128256, d_model=4096, n_layers=32,
    n_heads=32, n_kv_heads=8, d_ff=14336, rope_theta=500000.0)

# ~1B proxy with the same architecture — fits a single v5e chip with
# optimizer state for single-chip runs and benches.
LLAMA3_1B = LlamaConfig(
    name="llama3-1b", vocab_size=32768, d_model=2048, n_layers=16,
    n_heads=16, n_kv_heads=8, d_ff=5632)

# Tiny config for tests and multi-chip dry runs.
LLAMA_TINY = LlamaConfig(
    name="llama-tiny", vocab_size=256, d_model=64, n_layers=2,
    n_heads=4, n_kv_heads=2, d_ff=128, max_seq_len=128,
    dtype=jnp.float32)

CONFIGS = {c.name: c for c in (LLAMA3_8B, LLAMA3_1B, LLAMA_TINY)}


def _tpu_backend() -> bool:
    """True when the default backend drives TPU devices — including
    tunneled PJRT plugins whose platform name is NOT "tpu" (the axon
    tunnel reports platform "axon" but device_kind "TPU v5 lite";
    matching on the platform string alone silently disabled the auto
    default on the one environment it was built for)."""
    try:
        if jax.default_backend() == "tpu":
            return True
        devs = jax.devices()
        return bool(devs) and "tpu" in str(
            getattr(devs[0], "device_kind", "")).lower()
    except Exception:  # backend init failure → safe XLA path
        return False


def resolve_pallas(flag: "Optional[bool]", tpu_default: bool = True) -> bool:
    """Resolve a tri-state Pallas flag: explicit True/False wins;
    ``None`` (auto) selects the fused kernels only on TPU backends —
    on CPU the Pallas TPU lowering is unavailable (interpret mode is
    test-only) — and, there, per ``tpu_default``: the default is
    per-PASS, set by on-chip measurement, not globally (VERDICT r04
    weak-2: flipping everything to Pallas was ahead of the evidence).
    v5e, llama3-1b shapes (TPU_RESULTS_r05_extra.json): flash
    attention Pallas 7223 µs vs XLA 10541 µs → default ON; rmsnorm
    Pallas 544 µs vs XLA 437 µs → default OFF."""
    if flag is not None:
        return flag
    return tpu_default and _tpu_backend()


def rope_freqs(head_dim: int, max_seq: int, theta: float) -> jnp.ndarray:
    inv = 1.0 / (theta ** (jnp.arange(0, head_dim, 2,
                                      dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_seq, dtype=jnp.float32)
    return jnp.outer(t, inv)  # (S, D/2)


def apply_rope(x: jnp.ndarray, freqs: jnp.ndarray) -> jnp.ndarray:
    """x: (B, H, S, D); freqs: (S, D/2)."""
    xf = x.astype(jnp.float32)
    x1, x2 = jnp.split(xf, 2, axis=-1)
    cos = jnp.cos(freqs)[None, None]
    sin = jnp.sin(freqs)[None, None]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1).astype(x.dtype)


class RMSNorm(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x):
        w = self.param("weight", nn.initializers.ones, (x.shape[-1],),
                       jnp.float32)
        return rmsnorm(x, w, self.cfg.norm_eps,
                       use_pallas=resolve_pallas(self.cfg.use_pallas_rmsnorm,
                                                 tpu_default=False),
                       interpret=self.cfg.pallas_interpret)


class Attention(nn.Module):
    """Attention sub-block, ``setup()``-style so the projections are
    addressable as methods: the sequence-parallel path
    (``parallel/seq_parallel.py``) drives :meth:`qkv` →
    transport-rotated ring attention → :meth:`out_proj` layerwise,
    against the SAME parameters and math the fused ``__call__`` uses."""

    cfg: LlamaConfig

    def setup(self):
        cfg = self.cfg
        dense = lambda feats: nn.Dense(
            feats, use_bias=False, dtype=cfg.dtype, param_dtype=cfg.dtype)
        hd = cfg.head_dim
        self.wq = dense(cfg.n_heads * hd)
        self.wk = dense(cfg.n_kv_heads * hd)
        self.wv = dense(cfg.n_kv_heads * hd)
        self.wo = dense(cfg.d_model)

    def qkv(self, x, freqs):
        """(B, S, D) normed input → roped (q, k, v) in (B, H, S, hd) /
        (B, KVH, S, hd) layout. ``freqs`` must already be sliced to
        x's absolute positions — the seq-parallel caller passes its
        shard's slice, the local path passes ``freqs[:s]``."""
        cfg = self.cfg
        b, s, _ = x.shape
        hd = cfg.head_dim
        q = self.wq(x).reshape(b, s, cfg.n_heads, hd).transpose(0, 2, 1, 3)
        k = self.wk(x).reshape(b, s, cfg.n_kv_heads, hd).transpose(
            0, 2, 1, 3)
        v = self.wv(x).reshape(b, s, cfg.n_kv_heads, hd).transpose(
            0, 2, 1, 3)
        return apply_rope(q, freqs), apply_rope(k, freqs), v

    def out_proj(self, o):
        """(B, H, S, hd) attention output → (B, S, D) projection."""
        b, _, s, _ = o.shape
        o = o.transpose(0, 2, 1, 3).reshape(
            b, s, self.cfg.n_heads * self.cfg.head_dim)
        return self.wo(o)

    def __call__(self, x, freqs, cache=None, pos=None):
        """Training/no-cache: x is the full (B, S, D) sequence, causal
        attention, returns (out, None). Decode: ``cache`` holds per-
        layer K/V of shape (B, n_kv, max_seq, hd) and ``pos`` is the
        absolute position of x's first token; K/V are written at pos
        and attention runs over the cache with a static-shape mask —
        the standard jit-friendly incremental decode."""
        cfg = self.cfg
        b, s, _ = x.shape
        hd = cfg.head_dim
        if cache is None:
            q, k, v = self.qkv(x, freqs[:s])
            o = attention(q, k, v, causal=True,
                          use_pallas=resolve_pallas(cfg.use_pallas_attention),
                          interpret=cfg.pallas_interpret)
            return self.out_proj(o), None

        q = self.wq(x).reshape(b, s, cfg.n_heads, hd).transpose(0, 2, 1, 3)
        k = self.wk(x).reshape(b, s, cfg.n_kv_heads, hd).transpose(
            0, 2, 1, 3)
        v = self.wv(x).reshape(b, s, cfg.n_kv_heads, hd).transpose(
            0, 2, 1, 3)
        fr = jax.lax.dynamic_slice_in_dim(freqs, pos, s)
        q = apply_rope(q, fr)
        k = apply_rope(k, fr)
        k_all = jax.lax.dynamic_update_slice(
            cache["k"], k.astype(cache["k"].dtype), (0, 0, pos, 0))
        v_all = jax.lax.dynamic_update_slice(
            cache["v"], v.astype(cache["v"].dtype), (0, 0, pos, 0))
        # Grouped-query attention against the cache without ever
        # materializing a head-repeated (or f32-widened) copy of it:
        # fold the group axis into the query tensor and let the einsum
        # accumulate in f32 (preferred_element_type), as the training
        # kernels do.
        rep = cfg.n_heads // cfg.n_kv_heads
        qg = q.reshape(b, cfg.n_kv_heads, rep, s, hd)
        scores = jnp.einsum(
            "bgrqd,bgkd->bgrqk", qg, k_all,
            preferred_element_type=jnp.float32) / (hd ** 0.5)
        q_pos = pos + jnp.arange(s)
        visible = jnp.arange(cache["k"].shape[2])[None, :] <= q_pos[:, None]
        scores = jnp.where(visible[None, None, None], scores, -jnp.inf)
        probs = jax.nn.softmax(scores, axis=-1)
        o = jnp.einsum("bgrqk,bgkd->bgrqd", probs.astype(cfg.dtype), v_all,
                       preferred_element_type=jnp.float32)
        o = o.astype(cfg.dtype).reshape(b, cfg.n_heads, s, hd)
        return self.out_proj(o), {"k": k_all, "v": v_all}


class MLP(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        dense = lambda feats, name: nn.Dense(
            feats, use_bias=False, dtype=cfg.dtype,
            param_dtype=cfg.dtype, name=name)
        gate = dense(cfg.d_ff, "w_gate")(x)
        up = dense(cfg.d_ff, "w_up")(x)
        return dense(cfg.d_model, "w_down")(nn.silu(gate) * up)


class Block(nn.Module):
    """Transformer block. ``setup()``-style: besides the fused
    ``__call__``, exposes the attention-split halves the
    sequence-parallel runner drives — :meth:`qkv` (norm + projections +
    rope, everything before the attention contraction) and :meth:`post`
    (output projection + residuals + MLP, everything after). The
    fused path and the split path share every parameter and every op,
    so parity between them is structural, not coincidental."""

    cfg: LlamaConfig

    def setup(self):
        self.attn_norm = RMSNorm(self.cfg)
        self.attn = Attention(self.cfg)
        self.mlp_norm = RMSNorm(self.cfg)
        self.mlp = MLP(self.cfg)

    def qkv(self, x, freqs):
        """Pre-attention half for the seq-parallel runner: ``freqs``
        sliced to x's absolute positions."""
        return self.attn.qkv(self.attn_norm(x), freqs)

    def post(self, x, o):
        """Post-attention half: ``o`` is the (B, H, S_local, hd)
        attention output for this rank's queries."""
        y = x + self.attn.out_proj(o)
        return y + self.mlp(self.mlp_norm(y))

    def __call__(self, x, freqs, cache=None, pos=None):
        attn_out, new_cache = self.attn(self.attn_norm(x), freqs, cache,
                                        pos)
        x = x + attn_out
        x = x + self.mlp(self.mlp_norm(x))
        return x, new_cache


class Llama(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, tokens: jnp.ndarray, cache=None, pos=None):
        """tokens: (B, S) int32 → logits (B, S, vocab) f32.

        With ``cache`` (from :func:`init_cache`) and ``pos``, runs in
        incremental-decode mode and returns ``(logits, new_cache)``;
        without, plain causal forward returning logits only."""
        cfg = self.cfg
        if tokens.shape[-1] > cfg.max_seq_len:
            raise ValueError(
                f"sequence length {tokens.shape[-1]} exceeds "
                f"{cfg.name}'s max_seq_len={cfg.max_seq_len}")
        emb = nn.Embed(cfg.vocab_size, cfg.d_model,
                       dtype=cfg.dtype, param_dtype=cfg.dtype,
                       name="embed")
        x = emb(tokens)
        freqs = rope_freqs(cfg.head_dim, cfg.max_seq_len, cfg.rope_theta)
        new_cache = {} if cache is not None else None
        block_cls = Block
        if cfg.remat and cache is None:
            if cfg.remat_policy == "dots":
                block_cls = nn.remat(
                    Block, policy=jax.checkpoint_policies
                    .dots_with_no_batch_dims_saveable)
            elif cfg.remat_policy == "full":
                block_cls = nn.remat(Block)
            else:
                raise ValueError(
                    f"remat_policy={cfg.remat_policy!r}: must be "
                    "'full' or 'dots'")
        for i in range(cfg.n_layers):
            layer_cache = cache[f"layer_{i}"] if cache is not None else None
            x, lc = block_cls(cfg, name=f"layer_{i}")(x, freqs, layer_cache,
                                                      pos)
            if new_cache is not None:
                new_cache[f"layer_{i}"] = lc
        x = RMSNorm(cfg, name="final_norm")(x)
        logits = nn.Dense(cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                          param_dtype=cfg.dtype, name="lm_head")(x)
        logits = logits.astype(jnp.float32)
        if cache is not None:
            return logits, new_cache
        return logits


def make_model(config: "LlamaConfig | str", **overrides) -> Llama:
    cfg = CONFIGS[config] if isinstance(config, str) else config
    if (overrides.get("remat_policy", cfg.remat_policy)
            not in ("full", "dots")):
        # Fail at the config site, not trace time deep inside jit.
        raise ValueError(
            f"remat_policy="
            f"{overrides.get('remat_policy', cfg.remat_policy)!r}: "
            "must be 'full' or 'dots'")
    if overrides:
        cfg = dataclasses.replace(cfg, **overrides)
    return Llama(cfg)


def init_params(model: Llama, rng, batch: int = 1, seq: int = 8):
    tokens = jnp.zeros((batch, seq), dtype=jnp.int32)
    return model.init(rng, tokens)


def cross_entropy_loss(logits: jnp.ndarray, targets: jnp.ndarray
                       ) -> jnp.ndarray:
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(nll)


def init_cache(cfg: LlamaConfig, batch: int, max_seq: Optional[int] = None):
    """Zeroed KV cache pytree: per layer, K/V of shape
    (B, n_kv_heads, max_seq, head_dim) in the model dtype. Static
    shapes — decode steps jit once and reuse the executable."""
    s = max_seq or cfg.max_seq_len
    shape = (batch, cfg.n_kv_heads, s, cfg.head_dim)
    return {
        f"layer_{i}": {
            "k": jnp.zeros(shape, dtype=cfg.dtype),
            "v": jnp.zeros(shape, dtype=cfg.dtype),
        }
        for i in range(cfg.n_layers)
    }


def generate(model: Llama, params, prompt: jnp.ndarray,
             max_new_tokens: int, temperature: float = 0.0,
             rng=None) -> jnp.ndarray:
    """Autoregressive generation with an incremental KV cache.

    prompt: (B, P) int32. Returns (B, max_new_tokens) int32. Greedy at
    temperature 0, else categorical sampling. The whole loop — prefill
    + lax.scan over decode steps — is one jitted computation with
    static shapes; repeated calls with the same (P, max_new_tokens)
    reuse the compiled executable.
    """
    cfg = model.cfg
    b, p = prompt.shape
    total = p + max_new_tokens
    if total > cfg.max_seq_len:
        raise ValueError(f"prompt+new = {total} exceeds "
                         f"max_seq_len={cfg.max_seq_len}")
    if max_new_tokens <= 0:
        return jnp.zeros((b, 0), dtype=jnp.int32)
    if rng is None:
        rng = jax.random.PRNGKey(0)

    def pick(logits_last, key):
        if temperature <= 0.0:
            return jnp.argmax(logits_last, axis=-1).astype(jnp.int32)
        return jax.random.categorical(
            key, logits_last / temperature, axis=-1).astype(jnp.int32)

    # Memoize the jitted loop per (config, shapes, temperature) so
    # repeated generate() calls reuse the compiled executable.
    memo_key = (cfg, b, p, max_new_tokens, float(temperature))
    cached = _GEN_CACHE.get(memo_key)
    if cached is not None:
        return cached(params, prompt, rng)

    def run(params, prompt, rng):
        # Cache sized to the smallest multiple of 128 covering the
        # sequence (MXU/lane-friendly, bounds the masked-attention
        # wastage for short prompts).
        cache_len = min(cfg.max_seq_len, ((total + 127) // 128) * 128)
        cache = init_cache(cfg, b, cache_len)
        logits, cache = model.apply(params, prompt, cache=cache, pos=0)
        rng, key = jax.random.split(rng)
        first = pick(logits[:, -1], key)

        def step(carry, _):
            cache, tok, pos, rng = carry
            logits, cache = model.apply(params, tok[:, None], cache=cache,
                                        pos=pos)
            rng, key = jax.random.split(rng)
            nxt = pick(logits[:, -1], key)
            return (cache, nxt, pos + 1, rng), nxt

        if max_new_tokens == 1:
            return first[:, None]
        (_, _, _, _), rest = jax.lax.scan(
            step, (cache, first, jnp.asarray(p, jnp.int32), rng), None,
            length=max_new_tokens - 1)
        return jnp.concatenate([first[:, None], rest.T], axis=1)

    jitted = jax.jit(run)
    # Bounded FIFO: one executable per distinct shape tuple, evicted
    # oldest-first so a serving loop with varying prompt lengths does
    # not accumulate compiled programs without limit.
    if len(_GEN_CACHE) >= _GEN_CACHE_MAX:
        _GEN_CACHE.pop(next(iter(_GEN_CACHE)))
    _GEN_CACHE[memo_key] = jitted
    return jitted(params, prompt, rng)


_GEN_CACHE: dict = {}
_GEN_CACHE_MAX = 32
