/* SPDX-License-Identifier: MIT */
/* UAPI of /dev/tpup2ptest — direct exercise of the dma-buf pin layer.
 *
 * The hardware-free mirror of the reference's kernel test harness UAPI
 * (include/amdp2ptest.h: 4 ioctls + mmap). Differences by design:
 * ioctls returning data are _IOWR (the reference's IS_GPU_ADDRESS was
 * _IOW and named a nonexistent struct in its size field — SURVEY.md §2
 * component 3), and the pin handle is explicit instead of keyed by
 * (va,size) so double-pins are unambiguous.
 */
#ifndef TPUP2PTEST_UAPI_H
#define TPUP2PTEST_UAPI_H

#include <linux/ioctl.h>
#include <linux/types.h>

#define TPUP2PTEST_DEV_PATH "/dev/tpup2ptest"
#define TPUP2PTEST_IOC_MAGIC 't'

/* Is this VA range claimed as device memory? (role of
 * AMDRDMA_IOCTL_IS_GPU_ADDRESS, tests/amdp2ptest.c:141-165) */
struct tpup2ptest_query_param {
	__u64 va;	/* in */
	__u64 len;	/* in */
	__u32 is_device;/* out */
	__u32 _pad;
};

/* Pin a claimed range (role of AMDRDMA_IOCTL_GET_PAGES). */
struct tpup2ptest_pin_param {
	__u64 va;	/* in */
	__u64 len;	/* in */
	__u64 handle;	/* out: pin handle */
	__u64 nents;	/* out: sg entries mapped */
};

/* Unpin by handle (role of AMDRDMA_IOCTL_PUT_PAGES). */
struct tpup2ptest_unpin_param {
	__u64 handle;	/* in */
};

/* Page size of the pinned range (role of AMDRDMA_IOCTL_GET_PAGE_SIZE). */
struct tpup2ptest_page_size_param {
	__u64 va;	 /* in */
	__u64 page_size; /* out */
};

#define TPUP2PTEST_IOC_QUERY \
	_IOWR(TPUP2PTEST_IOC_MAGIC, 1, struct tpup2ptest_query_param)
#define TPUP2PTEST_IOC_PIN \
	_IOWR(TPUP2PTEST_IOC_MAGIC, 2, struct tpup2ptest_pin_param)
#define TPUP2PTEST_IOC_UNPIN \
	_IOW(TPUP2PTEST_IOC_MAGIC, 3, struct tpup2ptest_unpin_param)
#define TPUP2PTEST_IOC_PAGE_SIZE \
	_IOWR(TPUP2PTEST_IOC_MAGIC, 4, struct tpup2ptest_page_size_param)

#endif /* TPUP2PTEST_UAPI_H */
