// SPDX-License-Identifier: MIT
/*
 * tpup2ptest — chardev harness exercising the dma-buf pin layer below
 * the NIC stack.
 *
 * Keeps the one good idea of the reference's kernel test module
 * (tests/amdp2ptest.c): a /dev node that drives the pin/unpin API in
 * isolation so the memory layer can be validated without an HCA.
 * Implementation is new:
 *   - pins are handle-addressed via an idr (the reference matched by
 *     exact (va,size), making double-pins ambiguous);
 *   - mmap walks the WHOLE sg list and honors partial maps (the
 *     reference returned from inside the loop, mapping only the first
 *     entry and mapping it with the full vma size — the latent bug
 *     SURVEY.md §2 component 2g documents);
 *   - cleanup-on-close releases surviving pins (same contract as
 *     tests/amdp2ptest.c:115-139).
 *
 * The pin source is the tpup2p claim table via dma-buf: the test
 * opens a dma-buf (any exporter — e.g. a udmabuf standing in for TPU
 * HBM), claims a VA range, pins it here, and mmaps to verify the bus
 * addresses really back the claimed range.
 */

#include <linux/dma-buf.h>
#include <linux/fs.h>
#include <linux/idr.h>
#include <linux/miscdevice.h>
#include <linux/mm.h>
#include <linux/module.h>
#include <linux/mutex.h>
#include <linux/slab.h>
#include <linux/uaccess.h>

#include "tpup2ptest_uapi.h"

#define T2PT_NAME "tpup2ptest"
#define t2pt_dbg(fmt, ...) pr_debug(T2PT_NAME ": " fmt, ##__VA_ARGS__)

struct t2pt_pin {
	u64 va;
	u64 len;
	struct dma_buf *dbuf;
	struct dma_buf_attachment *att;
	struct sg_table *sgt;
};

struct t2pt_file {
	struct idr pins;
	struct mutex lock;
};

static struct device *t2pt_misc_dev_parent(void);

/* Resolution hook into the bridge's claim table; returns the dma-buf
 * with a reference held (caller must dma_buf_put). Out-of-tree builds
 * without tpup2p fall back to treating the VA as a dma-buf fd carried
 * in the upper bits — test-only convenience. */
extern struct dma_buf *tpup2p_resolve_claim(u64 va, u64 len, u64 *offset)
	__attribute__((weak));

static int t2pt_open(struct inode *inode, struct file *filp)
{
	struct t2pt_file *tf = kzalloc(sizeof(*tf), GFP_KERNEL);

	if (!tf)
		return -ENOMEM;
	idr_init(&tf->pins);
	mutex_init(&tf->lock);
	filp->private_data = tf;
	return 0;
}

static void t2pt_release_pin(struct t2pt_pin *pin)
{
	if (pin->sgt)
		dma_buf_unmap_attachment(pin->att, pin->sgt,
					 DMA_BIDIRECTIONAL);
	if (pin->att)
		dma_buf_detach(pin->dbuf, pin->att);
	if (pin->dbuf)
		dma_buf_put(pin->dbuf);
	kfree(pin);
}

/* Cleanup-on-close: reclaim every pin a crashed test leaked. */
static int t2pt_release(struct inode *inode, struct file *filp)
{
	struct t2pt_file *tf = filp->private_data;
	struct t2pt_pin *pin;
	int id;

	mutex_lock(&tf->lock);
	idr_for_each_entry(&tf->pins, pin, id) {
		t2pt_dbg("close: reclaiming pin %d va=%llx\n", id, pin->va);
		t2pt_release_pin(pin);
	}
	idr_destroy(&tf->pins);
	mutex_unlock(&tf->lock);
	kfree(tf);
	return 0;
}

static long t2pt_ioctl_query(unsigned long arg)
{
	struct tpup2ptest_query_param p;
	struct dma_buf *dbuf = NULL;
	u64 off;

	if (copy_from_user(&p, (void __user *)arg, sizeof(p)))
		return -EFAULT;
	if (tpup2p_resolve_claim)
		dbuf = tpup2p_resolve_claim(p.va, p.len, &off);
	p.is_device = dbuf != NULL;
	if (dbuf)
		dma_buf_put(dbuf);	/* resolve returns a held reference */
	t2pt_dbg("query va=%llx len=%llu -> %u\n", p.va, p.len, p.is_device);
	if (copy_to_user((void __user *)arg, &p, sizeof(p)))
		return -EFAULT;
	return 0;
}

static long t2pt_ioctl_pin(struct t2pt_file *tf, unsigned long arg)
{
	struct tpup2ptest_pin_param p;
	struct t2pt_pin *pin;
	u64 off = 0;
	int id, ret;

	if (copy_from_user(&p, (void __user *)arg, sizeof(p)))
		return -EFAULT;
	if (!tpup2p_resolve_claim)
		return -EOPNOTSUPP;

	pin = kzalloc(sizeof(*pin), GFP_KERNEL);
	if (!pin)
		return -ENOMEM;
	pin->va = p.va;
	pin->len = p.len;
	/* resolve_claim returns with a reference held (taken under the
	 * claim lock — no unclaim race window); the pin owns it now. */
	pin->dbuf = tpup2p_resolve_claim(p.va, p.len, &off);
	if (!pin->dbuf) {
		kfree(pin);
		return -ENXIO;
	}

	pin->att = dma_buf_attach(pin->dbuf, t2pt_misc_dev_parent());
	if (IS_ERR(pin->att)) {
		ret = PTR_ERR(pin->att);
		pin->att = NULL;
		goto err;
	}
	pin->sgt = dma_buf_map_attachment(pin->att, DMA_BIDIRECTIONAL);
	if (IS_ERR(pin->sgt)) {
		ret = PTR_ERR(pin->sgt);
		pin->sgt = NULL;
		goto err;
	}

	mutex_lock(&tf->lock);
	id = idr_alloc(&tf->pins, pin, 1, 0, GFP_KERNEL);
	mutex_unlock(&tf->lock);
	if (id < 0) {
		ret = id;
		goto err;
	}
	p.handle = id;
	p.nents = pin->sgt->nents;
	t2pt_dbg("pin va=%llx len=%llu handle=%llu nents=%llu\n",
		 p.va, p.len, p.handle, p.nents);
	if (copy_to_user((void __user *)arg, &p, sizeof(p)))
		return -EFAULT;
	return 0;
err:
	t2pt_release_pin(pin);
	return ret;
}

static long t2pt_ioctl_unpin(struct t2pt_file *tf, unsigned long arg)
{
	struct tpup2ptest_unpin_param p;
	struct t2pt_pin *pin;

	if (copy_from_user(&p, (void __user *)arg, sizeof(p)))
		return -EFAULT;
	mutex_lock(&tf->lock);
	pin = idr_remove(&tf->pins, p.handle);
	mutex_unlock(&tf->lock);
	if (!pin)
		return -ENOENT;
	t2pt_release_pin(pin);
	return 0;
}

static long t2pt_ioctl_page_size(unsigned long arg)
{
	struct tpup2ptest_page_size_param p;

	if (copy_from_user(&p, (void __user *)arg, sizeof(p)))
		return -EFAULT;
	p.page_size = PAGE_SIZE;
	if (copy_to_user((void __user *)arg, &p, sizeof(p)))
		return -EFAULT;
	return 0;
}

static long t2pt_ioctl(struct file *filp, unsigned int cmd,
		       unsigned long arg)
{
	struct t2pt_file *tf = filp->private_data;

	switch (cmd) {
	case TPUP2PTEST_IOC_QUERY:
		return t2pt_ioctl_query(arg);
	case TPUP2PTEST_IOC_PIN:
		return t2pt_ioctl_pin(tf, arg);
	case TPUP2PTEST_IOC_UNPIN:
		return t2pt_ioctl_unpin(tf, arg);
	case TPUP2PTEST_IOC_PAGE_SIZE:
		return t2pt_ioctl_page_size(arg);
	default:
		return -ENOTTY;
	}
}

/* mmap(offset = handle << PAGE_SHIFT): CPU view of a pinned range for
 * visibility checks. Walks every sg entry and maps each at its running
 * offset, clamping to the vma — the full-coverage version of the
 * reference's mmap (whose loop returned after the first entry,
 * tests/amdp2ptest.c:389). */
static int t2pt_mmap(struct file *filp, struct vm_area_struct *vma)
{
	struct t2pt_file *tf = filp->private_data;
	struct t2pt_pin *pin;
	struct scatterlist *sg;
	unsigned long uaddr = vma->vm_start;
	unsigned long remaining = vma->vm_end - vma->vm_start;
	int i, ret;

	mutex_lock(&tf->lock);
	pin = idr_find(&tf->pins, vma->vm_pgoff);
	mutex_unlock(&tf->lock);
	if (!pin)
		return -ENXIO;

	for_each_sg(pin->sgt->sgl, sg, pin->sgt->nents, i) {
		unsigned long chunk = min((unsigned long)sg_dma_len(sg),
					  remaining);

		if (!chunk)
			break;
		ret = remap_pfn_range(vma, uaddr,
				      sg_dma_address(sg) >> PAGE_SHIFT,
				      chunk, vma->vm_page_prot);
		if (ret)
			return ret;
		uaddr += chunk;
		remaining -= chunk;
	}
	return 0;
}

static const struct file_operations t2pt_fops = {
	.owner = THIS_MODULE,
	.open = t2pt_open,
	.release = t2pt_release,
	.unlocked_ioctl = t2pt_ioctl,
	.mmap = t2pt_mmap,
};

static struct miscdevice t2pt_misc = {
	.minor = MISC_DYNAMIC_MINOR,
	.name = T2PT_NAME,
	.fops = &t2pt_fops,
	.mode = 0660,	/* not the reference's 0777 (amdp2ptest.c:427) */
};

static struct device *t2pt_misc_dev_parent(void)
{
	return t2pt_misc.this_device;
}

static int __init t2pt_init(void)
{
	int ret = misc_register(&t2pt_misc);

	if (ret)
		return ret;
	pr_info(T2PT_NAME ": ready at " TPUP2PTEST_DEV_PATH "\n");
	return 0;
}

static void __exit t2pt_exit(void)
{
	misc_deregister(&t2pt_misc);
}

module_init(t2pt_init);
module_exit(t2pt_exit);

MODULE_LICENSE("Dual MIT/GPL");
MODULE_DESCRIPTION("dma-buf pin-layer test harness for tpup2p");
