// SPDX-License-Identifier: MIT
/*
 * tpup2p — peer-memory bridge from legacy OFED PeerDirect stacks to
 * dma-buf-exported TPU HBM.
 *
 * Functional mirror of the role AMD's amdp2p bridge played for KFD
 * memory (reference: rocmarchive/ROCnRDMA, amdp2p.c), re-based on the
 * kernel's dma-buf machinery:
 *
 *   amdp2p (reference)                  tpup2p (this module)
 *   ------------------------------     --------------------------------
 *   is_gpu_address() query to KFD      VA-range table fed by userspace
 *     (amdp2p.c:127)                     ioctl (tpup2p_claim/unclaim)
 *   get_pages() pins via KFD           dma_buf_get + attach; pages stay
 *     (amdp2p.c:200-205)                 exporter-owned
 *   dma_map() copies prebuilt sg       dma_buf_map_attachment builds a
 *     list, no IOMMU work               properly IOMMU-mapped sg table
 *     (amdp2p.c:222-240, 258)           (the fix for that caveat)
 *   free_callback → invalidate         move_notify → invalidate
 *     (amdp2p.c:88-109)                  (dynamic attachment)
 *   free_callback_called flag          ctx->revoked under ctx->lock
 *     (amdp2p.c:299-302)
 *
 * Userspace flow: the runtime (rocnrdma_tpu.hbm) obtains a dma-buf fd
 * for a HBM region from the TPU driver, then tells this bridge which
 * VA range the fd backs via TPUP2P_IOC_CLAIM on /dev/tpup2p. A later
 * ibv_reg_mr() over that VA range is claimed by acquire(), pinned via
 * the dma-buf attach path, and revoked through ib_core's invalidate
 * callback if the exporter moves/frees the buffer while registered.
 */

#include <linux/cdev.h>
#include <linux/dma-buf.h>
#include <linux/dma-resv.h>
#include <linux/fs.h>
#include <linux/miscdevice.h>
#include <linux/module.h>
#include <linux/mutex.h>
#include <linux/rbtree.h>
#include <linux/sched.h>
#include <linux/slab.h>
#include <linux/uaccess.h>

#include "peer_mem_compat.h"
#include "tpup2p_uapi.h"

#define TPUP2P_NAME "tpup2p"
#define TPUP2P_VERSION "1.0"

#define t2p_dbg(fmt, ...) pr_debug(TPUP2P_NAME ": " fmt, ##__VA_ARGS__)
#define t2p_err(fmt, ...) pr_err(TPUP2P_NAME ": " fmt, ##__VA_ARGS__)

/* ------------------------------------------------------------------ *
 * VA-range claim table (role of KFD's is_gpu_address): which VA
 * ranges of which process are backed by which dma-buf fd.
 * ------------------------------------------------------------------ */

struct t2p_claim {
	struct rb_node node;
	u64 va;
	u64 len;
	pid_t tgid;
	/* fd the claim was made through; claims die with it (the per-fd
	 * cleanup discipline of the reference's test module,
	 * tests/amdp2ptest.c:115-139, applied to the bridge itself) */
	struct file *owner;
	/* dma-buf reference held from claim to unclaim */
	struct dma_buf *dbuf;
	u64 dbuf_offset;
};

static struct rb_root t2p_claims = RB_ROOT;
static DEFINE_MUTEX(t2p_claims_lock);

static struct t2p_claim *t2p_claim_find(u64 va, u64 len, pid_t tgid)
{
	struct rb_node *n = t2p_claims.rb_node;

	while (n) {
		struct t2p_claim *c = rb_entry(n, struct t2p_claim, node);

		if (va < c->va)
			n = n->rb_left;
		else if (va >= c->va + c->len)
			n = n->rb_right;
		else
			return (c->tgid == tgid &&
				va + len <= c->va + c->len) ? c : NULL;
	}
	return NULL;
}

static int t2p_claim_insert(struct t2p_claim *nc)
{
	struct rb_node **p = &t2p_claims.rb_node, *parent = NULL;

	while (*p) {
		struct t2p_claim *c = rb_entry(*p, struct t2p_claim, node);

		parent = *p;
		if (nc->va + nc->len <= c->va)
			p = &(*p)->rb_left;
		else if (nc->va >= c->va + c->len)
			p = &(*p)->rb_right;
		else
			return -EEXIST;	/* overlapping claim */
	}
	rb_link_node(&nc->node, parent, p);
	rb_insert_color(&nc->node, &t2p_claims);
	return 0;
}

/* ------------------------------------------------------------------ *
 * Per-registration context (role of struct amd_mem_context)
 * ------------------------------------------------------------------ */

struct t2p_ctx {
	u64 va;
	u64 len;
	pid_t tgid;
	struct dma_buf *dbuf;
	u64 dbuf_offset;
	struct dma_buf_attachment *att;
	struct sg_table *sgt;
	u64 core_context;	/* ib_core cookie for invalidation */
	struct mutex lock;
	bool revoked;		/* exporter moved/freed while registered */
	bool mapped;
};

static void *t2p_invalidate_handle;
static invalidate_peer_memory t2p_invalidate_cb;

/* Claim-table lookup for sibling modules (tpup2ptest). Returns the
 * dma-buf backing [va, va+len) for the calling process with a
 * reference held (taken under the claims lock, so a racing unclaim
 * cannot free it first), or NULL. The caller owns the reference and
 * must dma_buf_put() it. */
struct dma_buf *tpup2p_resolve_claim(u64 va, u64 len, u64 *offset)
{
	struct t2p_claim *c;
	struct dma_buf *dbuf = NULL;

	mutex_lock(&t2p_claims_lock);
	c = t2p_claim_find(va, len, task_tgid_nr(current));
	if (c) {
		dbuf = c->dbuf;
		get_dma_buf(dbuf);
		*offset = c->dbuf_offset + (va - c->va);
	}
	mutex_unlock(&t2p_claims_lock);
	return dbuf;
}
EXPORT_SYMBOL_GPL(tpup2p_resolve_claim);

/* Exporter-initiated revocation: dynamic dma-buf attachments get a
 * move_notify when the backing storage is about to move or vanish —
 * the same moment KFD fired the reference's free_callback. Invalidate
 * upward first, then flag the context so put_pages after the fact is
 * a no-op. */
static void t2p_move_notify(struct dma_buf_attachment *att)
{
	struct t2p_ctx *ctx = att->importer_priv;

	t2p_dbg("move_notify va=%llx len=%llu\n", ctx->va, ctx->len);
	if (t2p_invalidate_cb && ctx->core_context)
		t2p_invalidate_cb(t2p_invalidate_handle, ctx->core_context);
	mutex_lock(&ctx->lock);
	/* Dynamic-importer contract: tear down our mapping before the
	 * exporter moves the storage (the caller holds the resv lock,
	 * so the locked unmap variant is correct here). */
	if (ctx->mapped && ctx->sgt) {
		dma_buf_unmap_attachment(ctx->att, ctx->sgt,
					 DMA_BIDIRECTIONAL);
		ctx->sgt = NULL;
		ctx->mapped = false;
	}
	ctx->revoked = true;
	mutex_unlock(&ctx->lock);
}

static const struct dma_buf_attach_ops t2p_attach_ops = {
	.allow_peer2peer = true,
	.move_notify = t2p_move_notify,
};

/* ------------------------------------------------------------------ *
 * peer_memory_client ops
 * ------------------------------------------------------------------ */

static int t2p_acquire(unsigned long addr, size_t size,
		       void *peer_mem_private_data, char *peer_mem_name,
		       void **client_context)
{
	struct t2p_claim *claim;
	struct t2p_ctx *ctx;
	pid_t tgid = task_tgid_nr(current);

	mutex_lock(&t2p_claims_lock);
	claim = t2p_claim_find(addr, size, tgid);
	if (!claim) {
		mutex_unlock(&t2p_claims_lock);
		return 0;	/* not ours */
	}

	ctx = kzalloc(sizeof(*ctx), GFP_KERNEL);
	if (!ctx) {
		mutex_unlock(&t2p_claims_lock);
		return 0;	/* claim refused on alloc failure */
	}
	ctx->va = addr;
	ctx->len = size;
	ctx->tgid = tgid;
	get_dma_buf(claim->dbuf);
	ctx->dbuf = claim->dbuf;
	ctx->dbuf_offset = claim->dbuf_offset + (addr - claim->va);
	mutex_init(&ctx->lock);
	mutex_unlock(&t2p_claims_lock);

	__module_get(THIS_MODULE);
	*client_context = ctx;
	t2p_dbg("acquire va=%lx len=%zu tgid=%d\n", addr, size, tgid);
	return 1;
}

static int t2p_get_pages(unsigned long addr, size_t size, int write,
			 int force, struct sg_table *sg_head,
			 void *client_context, u64 core_context)
{
	struct t2p_ctx *ctx = client_context;

	if (addr != ctx->va || size != ctx->len)
		return -EINVAL;

	/* The attachment needs the DMA device, which the peer-memory
	 * contract only supplies at dma_map time — so only the ib_core
	 * cookie is recorded here. (dma_buf_dynamic_attach rejects a
	 * NULL device.) */
	ctx->core_context = core_context;
	return 0;
}

static int t2p_dma_map(struct sg_table *sg_head, void *client_context,
		       struct device *dma_device, int dmasync, int *nmap)
{
	struct t2p_ctx *ctx = client_context;
	struct sg_table *sgt;

	ctx->att = dma_buf_dynamic_attach(ctx->dbuf, dma_device,
					  &t2p_attach_ops, ctx);
	if (IS_ERR(ctx->att)) {
		int ret = PTR_ERR(ctx->att);

		ctx->att = NULL;
		t2p_err("dynamic attach failed: %d\n", ret);
		return ret;
	}

	dma_resv_lock(ctx->dbuf->resv, NULL);
	sgt = dma_buf_map_attachment(ctx->att, DMA_BIDIRECTIONAL);
	dma_resv_unlock(ctx->dbuf->resv);
	if (IS_ERR(sgt)) {
		dma_buf_detach(ctx->dbuf, ctx->att);
		ctx->att = NULL;
		return PTR_ERR(sgt);
	}

	ctx->sgt = sgt;
	ctx->mapped = true;
	*sg_head = *sgt;
	*nmap = sgt->nents;
	t2p_dbg("dma_map va=%llx nents=%d\n", ctx->va, sgt->nents);
	return 0;
}

static int t2p_dma_unmap(struct sg_table *sg_head, void *client_context,
			 struct device *dma_device)
{
	struct t2p_ctx *ctx = client_context;

	mutex_lock(&ctx->lock);
	if (ctx->mapped && ctx->att && ctx->sgt) {
		dma_resv_lock(ctx->dbuf->resv, NULL);
		dma_buf_unmap_attachment(ctx->att, ctx->sgt,
					 DMA_BIDIRECTIONAL);
		dma_resv_unlock(ctx->dbuf->resv);
		ctx->sgt = NULL;
		ctx->mapped = false;
	}
	mutex_unlock(&ctx->lock);
	return 0;
}

static void t2p_put_pages(struct sg_table *sg_head, void *client_context)
{
	struct t2p_ctx *ctx = client_context;

	mutex_lock(&ctx->lock);
	/* The MAPPING must not be unmapped twice after revocation
	 * (move_notify already tore it down — the double-free the
	 * reference guards with free_callback_called, amdp2p.c:299-302)
	 * — but the ATTACHMENT is ours in every path: leaving it on the
	 * dma-buf's attachment list with importer_priv pointing at a
	 * soon-freed ctx would make the exporter's next walk a
	 * use-after-free. */
	if (ctx->mapped && ctx->sgt && !ctx->revoked) {
		dma_resv_lock(ctx->dbuf->resv, NULL);
		dma_buf_unmap_attachment(ctx->att, ctx->sgt,
					 DMA_BIDIRECTIONAL);
		dma_resv_unlock(ctx->dbuf->resv);
		ctx->sgt = NULL;
		ctx->mapped = false;
	}
	if (ctx->att) {
		dma_buf_detach(ctx->dbuf, ctx->att);
		ctx->att = NULL;
	}
	mutex_unlock(&ctx->lock);
}

static unsigned long t2p_get_page_size(void *client_context)
{
	/* dma-buf exporters are page-granular; PAGE_SIZE matches the
	 * reference's fallback (amdp2p.c:339). */
	return PAGE_SIZE;
}

static void t2p_release(void *client_context)
{
	struct t2p_ctx *ctx = client_context;

	dma_buf_put(ctx->dbuf);
	kfree(ctx);
	module_put(THIS_MODULE);
}

static const struct peer_memory_client t2p_client = {
	.name = TPUP2P_NAME,
	.version = TPUP2P_VERSION,
	.acquire = t2p_acquire,
	.get_pages = t2p_get_pages,
	.dma_map = t2p_dma_map,
	.dma_unmap = t2p_dma_unmap,
	.put_pages = t2p_put_pages,
	.get_page_size = t2p_get_page_size,
	.release = t2p_release,
};

/* ------------------------------------------------------------------ *
 * /dev/tpup2p — claim-management ioctls from the userspace runtime
 * ------------------------------------------------------------------ */

static long t2p_ioctl_claim(struct file *filp, unsigned long arg)
{
	struct tpup2p_claim_param p;
	struct t2p_claim *c;
	int ret;

	if (copy_from_user(&p, (void __user *)arg, sizeof(p)))
		return -EFAULT;

	c = kzalloc(sizeof(*c), GFP_KERNEL);
	if (!c)
		return -ENOMEM;
	c->va = p.va;
	c->len = p.len;
	c->tgid = task_tgid_nr(current);
	c->owner = filp;
	c->dbuf_offset = p.dmabuf_offset;
	c->dbuf = dma_buf_get(p.dmabuf_fd);
	if (IS_ERR(c->dbuf)) {
		ret = PTR_ERR(c->dbuf);
		kfree(c);
		return ret;
	}

	mutex_lock(&t2p_claims_lock);
	ret = t2p_claim_insert(c);
	mutex_unlock(&t2p_claims_lock);
	if (ret) {
		dma_buf_put(c->dbuf);
		kfree(c);
	}
	return ret;
}

static long t2p_ioctl_unclaim(unsigned long arg)
{
	struct tpup2p_unclaim_param p;
	struct t2p_claim *c;

	if (copy_from_user(&p, (void __user *)arg, sizeof(p)))
		return -EFAULT;

	mutex_lock(&t2p_claims_lock);
	c = t2p_claim_find(p.va, 1, task_tgid_nr(current));
	if (c)
		rb_erase(&c->node, &t2p_claims);
	mutex_unlock(&t2p_claims_lock);
	if (!c)
		return -ENOENT;
	dma_buf_put(c->dbuf);
	kfree(c);
	return 0;
}

/* Drop every claim owned by `filp` (NULL = all claims, the module-exit
 * sweep). Dead-process claims cannot outlive their fd — the leak (and
 * the tgid-reuse aliasing window) the reference's per-fd cleanup list
 * closes for pins (tests/amdp2ptest.c:115-139), closed for claims. */
static void t2p_reap_claims(struct file *filp)
{
	struct rb_node *n, *next;

	mutex_lock(&t2p_claims_lock);
	for (n = rb_first(&t2p_claims); n; n = next) {
		struct t2p_claim *c = rb_entry(n, struct t2p_claim, node);

		next = rb_next(n);
		if (filp && c->owner != filp)
			continue;
		rb_erase(&c->node, &t2p_claims);
		dma_buf_put(c->dbuf);
		kfree(c);
	}
	mutex_unlock(&t2p_claims_lock);
}

static int t2p_chardev_release(struct inode *inode, struct file *filp)
{
	t2p_reap_claims(filp);
	return 0;
}

static long t2p_ioctl(struct file *filp, unsigned int cmd, unsigned long arg)
{
	switch (cmd) {
	case TPUP2P_IOC_CLAIM:
		return t2p_ioctl_claim(filp, arg);
	case TPUP2P_IOC_UNCLAIM:
		return t2p_ioctl_unclaim(arg);
	default:
		return -ENOTTY;
	}
}

static const struct file_operations t2p_fops = {
	.owner = THIS_MODULE,
	.unlocked_ioctl = t2p_ioctl,
	.release = t2p_chardev_release,
};

static struct miscdevice t2p_misc = {
	.minor = MISC_DYNAMIC_MINOR,
	.name = TPUP2P_NAME,
	.fops = &t2p_fops,
	.mode = 0660,
};

static int __init tpup2p_init(void)
{
	int ret;

	ret = misc_register(&t2p_misc);
	if (ret)
		return ret;

	t2p_invalidate_handle = ib_register_peer_memory_client(
		&t2p_client, &t2p_invalidate_cb);
	if (!t2p_invalidate_handle) {
		misc_deregister(&t2p_misc);
		t2p_err("peer-memory registration failed\n");
		return -ENODEV;
	}
	pr_info(TPUP2P_NAME ": registered (dma-buf peer-memory bridge)\n");
	return 0;
}

static void __exit tpup2p_exit(void)
{
	ib_unregister_peer_memory_client(t2p_invalidate_handle);
	misc_deregister(&t2p_misc);
	t2p_reap_claims(NULL);	/* drop any claims that outlived their fd */
}

module_init(tpup2p_init);
module_exit(tpup2p_exit);

MODULE_LICENSE("Dual MIT/GPL");
MODULE_DESCRIPTION("TPU HBM peer-memory bridge over dma-buf");
