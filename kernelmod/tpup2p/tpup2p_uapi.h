/* SPDX-License-Identifier: MIT */
/* UAPI of /dev/tpup2p — VA-range claims for the peer-memory bridge.
 *
 * Role of the reference's UAPI header (include/amdp2ptest.h) for the
 * bridge side; both of that header's latent bugs are avoided here
 * (SURVEY.md §2 component 3): every ioctl that returns data is _IOWR,
 * and the size fields name the real param structs.
 */
#ifndef TPUP2P_UAPI_H
#define TPUP2P_UAPI_H

#include <linux/ioctl.h>
#include <linux/types.h>

#define TPUP2P_DEV_PATH "/dev/tpup2p"
#define TPUP2P_IOC_MAGIC 'T'

struct tpup2p_claim_param {
	__u64 va;	    /* userspace VA the dma-buf backs */
	__u64 len;
	__s32 dmabuf_fd;    /* from the TPU driver's HBM export */
	__u32 _pad;
	__u64 dmabuf_offset;
};

struct tpup2p_unclaim_param {
	__u64 va;
};

#define TPUP2P_IOC_CLAIM \
	_IOW(TPUP2P_IOC_MAGIC, 1, struct tpup2p_claim_param)
#define TPUP2P_IOC_UNCLAIM \
	_IOW(TPUP2P_IOC_MAGIC, 2, struct tpup2p_unclaim_param)

#endif /* TPUP2P_UAPI_H */
