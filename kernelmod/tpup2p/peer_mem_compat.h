/* Local declaration of the Mellanox OFED peer-memory client ABI.
 *
 * The real header (rdma/peer_mem.h) ships only with MLNX_OFED; the
 * reference repo had the same problem and solved it by requiring OFED
 * at build time (Makefile:17-18 links Module.symvers). We declare the
 * contract locally instead so the bridge at least compiles against
 * plain kernel headers for CI-style syntax checking; linking still
 * requires the OFED tree (see Makefile).
 *
 * ABI shape per the upstream peer-memory patches: a client registers a
 * named ops table; ib_core polls acquire() across clients at
 * ibv_reg_mr time, then drives get_pages/dma_map, and hands back an
 * invalidation callback for asynchronous revocation.
 */
#ifndef TPUP2P_PEER_MEM_COMPAT_H
#define TPUP2P_PEER_MEM_COMPAT_H

#include <linux/scatterlist.h>
#include <linux/types.h>

#define IB_PEER_MEMORY_NAME_MAX 64
#define IB_PEER_MEMORY_VER_MAX 16

struct peer_memory_client {
	char name[IB_PEER_MEMORY_NAME_MAX];
	char version[IB_PEER_MEMORY_VER_MAX];
	int (*acquire)(unsigned long addr, size_t size,
		       void *peer_mem_private_data,
		       char *peer_mem_name, void **client_context);
	int (*get_pages)(unsigned long addr, size_t size, int write,
			 int force, struct sg_table *sg_head,
			 void *client_context, u64 core_context);
	int (*dma_map)(struct sg_table *sg_head, void *client_context,
		       struct device *dma_device, int dmasync, int *nmap);
	int (*dma_unmap)(struct sg_table *sg_head, void *client_context,
			 struct device *dma_device);
	void (*put_pages)(struct sg_table *sg_head, void *client_context);
	unsigned long (*get_page_size)(void *client_context);
	void (*release)(void *client_context);
	void *(*get_context_private_data)(u64 peer_id);
	void (*put_context_private_data)(void *context);
};

typedef int (*invalidate_peer_memory)(void *reg_handle, u64 core_context);

void *ib_register_peer_memory_client(const struct peer_memory_client *client,
				     invalidate_peer_memory *invalidate_cb);
void ib_unregister_peer_memory_client(void *reg_handle);

#endif /* TPUP2P_PEER_MEM_COMPAT_H */
