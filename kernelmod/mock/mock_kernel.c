/* SPDX-License-Identifier: MIT */
/* Implementation of the userspace kernel mock (see include/mock/). */

#include <mock/mock_kernel.h>

#include "../tpup2p/peer_mem_compat.h"

/* ------------------------------------------------------------------ *
 * logging
 * ------------------------------------------------------------------ */
void mock_log(const char *lvl, const char *fmt, ...)
{
	va_list ap;

	if (!getenv("MOCK_KERNEL_VERBOSE"))
		return;
	fprintf(stderr, "[mock:%s] ", lvl);
	va_start(ap, fmt);
	vfprintf(stderr, fmt, ap);
	va_end(ap);
}

/* ------------------------------------------------------------------ *
 * slab
 * ------------------------------------------------------------------ */
int mock_kzalloc_live;
int mock_fail_next_kzalloc;

void *mock_kzalloc(size_t n)
{
	if (mock_fail_next_kzalloc > 0) {
		mock_fail_next_kzalloc--;
		return NULL;
	}
	mock_kzalloc_live++;
	return calloc(1, n);
}

void mock_kfree(void *p)
{
	if (!p)
		return;
	mock_kzalloc_live--;
	free(p);
}

/* ------------------------------------------------------------------ *
 * pids
 * ------------------------------------------------------------------ */
static pid_t mock_tgid_override;

pid_t mock_task_tgid_nr(void)
{
	return mock_tgid_override ? mock_tgid_override : getpid();
}

void mock_set_tgid(pid_t tgid)
{
	mock_tgid_override = tgid;
}

/* ------------------------------------------------------------------ *
 * module
 * ------------------------------------------------------------------ */
struct module mock_module;
int mock_module_refs;

static void (*mock_exit_fns[8])(void);
static int mock_exit_count;

void mock_register_exit(void (*fn)(void))
{
	if (mock_exit_count < 8)
		mock_exit_fns[mock_exit_count++] = fn;
}

void mock_run_module_exits(void)
{
	/* Reverse registration order, as rmmod unwinds a dependency
	 * stack (test module before the bridge it links against). */
	while (mock_exit_count > 0)
		mock_exit_fns[--mock_exit_count]();
}

/* ------------------------------------------------------------------ *
 * rbtree (plain BST with parent pointers; API-compatible)
 * ------------------------------------------------------------------ */
static struct rb_node *rb_leftmost(struct rb_node *n)
{
	while (n && n->rb_left)
		n = n->rb_left;
	return n;
}

struct rb_node *rb_first(const struct rb_root *root)
{
	return rb_leftmost(root->rb_node);
}

struct rb_node *rb_next(const struct rb_node *node)
{
	struct rb_node *n = (struct rb_node *)node;

	if (n->rb_right)
		return rb_leftmost(n->rb_right);
	while (n->rb_parent && n == n->rb_parent->rb_right)
		n = n->rb_parent;
	return n->rb_parent;
}

static void rb_replace_child(struct rb_root *root, struct rb_node *parent,
			     struct rb_node *old, struct rb_node *new)
{
	if (!parent)
		root->rb_node = new;
	else if (parent->rb_left == old)
		parent->rb_left = new;
	else
		parent->rb_right = new;
	if (new)
		new->rb_parent = parent;
}

void rb_erase(struct rb_node *node, struct rb_root *root)
{
	if (!node->rb_left) {
		rb_replace_child(root, node->rb_parent, node, node->rb_right);
	} else if (!node->rb_right) {
		rb_replace_child(root, node->rb_parent, node, node->rb_left);
	} else {
		/* Two children: splice in the in-order successor. */
		struct rb_node *succ = rb_leftmost(node->rb_right);

		if (succ->rb_parent != node) {
			rb_replace_child(root, succ->rb_parent, succ,
					 succ->rb_right);
			succ->rb_right = node->rb_right;
			succ->rb_right->rb_parent = succ;
		}
		succ->rb_left = node->rb_left;
		succ->rb_left->rb_parent = succ;
		rb_replace_child(root, node->rb_parent, node, succ);
	}
	node->rb_left = node->rb_right = node->rb_parent = NULL;
}

/* ------------------------------------------------------------------ *
 * miscdevice + VFS-lite
 * ------------------------------------------------------------------ */
#define MOCK_MAX_MISC 8
static struct miscdevice *mock_miscs[MOCK_MAX_MISC];
static struct device mock_misc_parent_devs[MOCK_MAX_MISC];

int misc_register(struct miscdevice *misc)
{
	for (int i = 0; i < MOCK_MAX_MISC; i++) {
		if (!mock_miscs[i]) {
			mock_miscs[i] = misc;
			mock_misc_parent_devs[i].name = misc->name;
			misc->this_device = &mock_misc_parent_devs[i];
			return 0;
		}
	}
	return -ENOMEM;
}

void misc_deregister(struct miscdevice *misc)
{
	for (int i = 0; i < MOCK_MAX_MISC; i++)
		if (mock_miscs[i] == misc)
			mock_miscs[i] = NULL;
	misc->this_device = NULL;
}

struct miscdevice *mock_misc_find(const char *name)
{
	for (int i = 0; i < MOCK_MAX_MISC; i++)
		if (mock_miscs[i] && strcmp(mock_miscs[i]->name, name) == 0)
			return mock_miscs[i];
	return NULL;
}

struct file *mock_dev_open(const char *name)
{
	struct miscdevice *misc = mock_misc_find(name);
	struct file *filp;
	static struct inode dummy_inode;

	if (!misc)
		return NULL;
	filp = calloc(1, sizeof(*filp));
	filp->f_op = misc->fops;
	if (misc->fops->open && misc->fops->open(&dummy_inode, filp)) {
		free(filp);
		return NULL;
	}
	return filp;
}

int mock_dev_close(struct file *filp)
{
	static struct inode dummy_inode;
	int ret = 0;

	if (filp->f_op->release)
		ret = filp->f_op->release(&dummy_inode, filp);
	free(filp);
	return ret;
}

long mock_dev_ioctl(struct file *filp, unsigned int cmd, void *arg)
{
	if (!filp->f_op->unlocked_ioctl)
		return -ENOTTY;
	return filp->f_op->unlocked_ioctl(filp, cmd, (unsigned long)arg);
}

/* ------------------------------------------------------------------ *
 * idr
 * ------------------------------------------------------------------ */
void idr_init(struct idr *idr)
{
	idr->slots = NULL;
	idr->cap = 0;
}

int idr_alloc(struct idr *idr, void *ptr, int start, int end, gfp_t gfp)
{
	int id;

	(void)gfp;
	if (start < 0)
		return -EINVAL;
	for (id = start; end <= 0 || id < end; id++) {
		if (id >= idr->cap) {
			int ncap = id + 8;
			void **n = realloc(idr->slots,
					   ncap * sizeof(void *));

			if (!n)
				return -ENOMEM;
			memset(n + idr->cap, 0,
			       (ncap - idr->cap) * sizeof(void *));
			idr->slots = n;
			idr->cap = ncap;
		}
		if (!idr->slots[id]) {
			idr->slots[id] = ptr;
			return id;
		}
	}
	return -ENOSPC;
}

void *idr_remove(struct idr *idr, unsigned long id)
{
	void *p;

	if ((int)id >= idr->cap)
		return NULL;
	p = idr->slots[id];
	idr->slots[id] = NULL;
	return p;
}

void *idr_find(const struct idr *idr, unsigned long id)
{
	if ((int)id >= idr->cap)
		return NULL;
	return idr->slots[id];
}

void idr_destroy(struct idr *idr)
{
	free(idr->slots);
	idr->slots = NULL;
	idr->cap = 0;
}

/* ------------------------------------------------------------------ *
 * mm
 * ------------------------------------------------------------------ */
#define MOCK_MAX_SEGMENTS 128
static struct mock_map_segment mock_segments[MOCK_MAX_SEGMENTS];
static int mock_segment_count;

int remap_pfn_range(struct vm_area_struct *vma, unsigned long addr,
		    unsigned long pfn, unsigned long size, pgprot_t prot)
{
	(void)vma;
	(void)prot;
	if (mock_segment_count >= MOCK_MAX_SEGMENTS)
		return -ENOMEM;
	mock_segments[mock_segment_count++] =
		(struct mock_map_segment){ addr, pfn, size };
	return 0;
}

void mock_mmap_reset(void)
{
	mock_segment_count = 0;
}

int mock_mmap_segment_count(void)
{
	return mock_segment_count;
}

const struct mock_map_segment *mock_mmap_segment(int i)
{
	return &mock_segments[i];
}

/* ------------------------------------------------------------------ *
 * dma-buf exporter
 * ------------------------------------------------------------------ */
#define MOCK_MAX_DMABUF 16
static struct dma_buf *mock_bufs[MOCK_MAX_DMABUF];
static int mock_next_fd = 100;
static int mock_live_attachments;
static int mock_live_mappings;

int mock_dmabuf_create(size_t size)
{
	struct dma_buf *d = calloc(1, sizeof(*d));

	d->backing = calloc(1, size);
	d->size = size;
	d->refcount = 1; /* the fd's own reference */
	d->fd = mock_next_fd++;
	mutex_init(&d->resv_storage.lock);
	d->resv = &d->resv_storage;
	for (int i = 0; i < MOCK_MAX_DMABUF; i++) {
		if (!mock_bufs[i]) {
			mock_bufs[i] = d;
			return d->fd;
		}
	}
	free(d->backing);
	free(d);
	return -1;
}

static struct dma_buf *mock_find_buf(int fd)
{
	for (int i = 0; i < MOCK_MAX_DMABUF; i++)
		if (mock_bufs[i] && mock_bufs[i]->fd == fd)
			return mock_bufs[i];
	return NULL;
}

void *mock_dmabuf_mem(int fd)
{
	struct dma_buf *d = mock_find_buf(fd);

	return d ? d->backing : NULL;
}

struct dma_buf *dma_buf_get(int fd)
{
	struct dma_buf *d = mock_find_buf(fd);

	if (!d)
		return ERR_PTR(-EBADF);
	d->refcount++;
	return d;
}

void get_dma_buf(struct dma_buf *dmabuf)
{
	dmabuf->refcount++;
}

void dma_buf_put(struct dma_buf *dmabuf)
{
	if (--dmabuf->refcount > 0)
		return;
	for (int i = 0; i < MOCK_MAX_DMABUF; i++)
		if (mock_bufs[i] == dmabuf)
			mock_bufs[i] = NULL;
	free(dmabuf->backing);
	free(dmabuf);
}

void mock_dmabuf_fd_close(int fd)
{
	struct dma_buf *d = mock_find_buf(fd);

	if (d)
		dma_buf_put(d);
}

static struct dma_buf_attachment *
mock_attach(struct dma_buf *dmabuf, struct device *dev,
	    const struct dma_buf_attach_ops *ops, void *priv)
{
	struct dma_buf_attachment *att = calloc(1, sizeof(*att));

	att->dmabuf = dmabuf;
	att->dev = dev;
	att->importer_ops = ops;
	att->importer_priv = priv;
	att->next = dmabuf->attachments;
	dmabuf->attachments = att;
	mock_live_attachments++;
	return att;
}

struct dma_buf_attachment *dma_buf_attach(struct dma_buf *dmabuf,
					  struct device *dev)
{
	if (!dev)
		return ERR_PTR(-EINVAL);
	return mock_attach(dmabuf, dev, NULL, NULL);
}

struct dma_buf_attachment *
dma_buf_dynamic_attach(struct dma_buf *dmabuf, struct device *dev,
		       const struct dma_buf_attach_ops *ops, void *priv)
{
	if (!dev)
		return ERR_PTR(-EINVAL);
	if (ops && !ops->move_notify)
		return ERR_PTR(-EINVAL); /* dynamic importers must handle moves */
	return mock_attach(dmabuf, dev, ops, priv);
}

void dma_buf_detach(struct dma_buf *dmabuf, struct dma_buf_attachment *att)
{
	struct dma_buf_attachment **p = &dmabuf->attachments;

	if (att->sgt) {
		fprintf(stderr, "mock: BUG: detach with live mapping\n");
		exit(1);
	}
	while (*p && *p != att)
		p = &(*p)->next;
	if (*p)
		*p = att->next;
	mock_live_attachments--;
	free(att);
}

struct sg_table *dma_buf_map_attachment(struct dma_buf_attachment *att,
					enum dma_data_direction dir)
{
	struct dma_buf *d = att->dmabuf;
	unsigned int nents = (d->size + PAGE_SIZE - 1) / PAGE_SIZE;
	struct sg_table *sgt;

	(void)dir;
	if (att->sgt)
		return ERR_PTR(-EBUSY); /* one mapping per attachment */
	sgt = calloc(1, sizeof(*sgt));
	sgt->sgl = calloc(nents, sizeof(struct scatterlist));
	sgt->nents = sgt->orig_nents = nents;
	for (unsigned int i = 0; i < nents; i++) {
		size_t off = (size_t)i * PAGE_SIZE;

		sgt->sgl[i].dma_address = (u64)(uintptr_t)d->backing + off;
		sgt->sgl[i].dma_len =
			(unsigned int)min(PAGE_SIZE, d->size - off);
	}
	att->sgt = sgt;
	mock_live_mappings++;
	return sgt;
}

void dma_buf_unmap_attachment(struct dma_buf_attachment *att,
			      struct sg_table *sgt,
			      enum dma_data_direction dir)
{
	(void)dir;
	if (att->sgt != sgt) {
		fprintf(stderr, "mock: BUG: unmap of foreign/stale sg_table\n");
		exit(1);
	}
	att->sgt = NULL;
	mock_live_mappings--;
	free(sgt->sgl);
	free(sgt);
}

void mock_dmabuf_move(int fd)
{
	struct dma_buf *d = mock_find_buf(fd);
	struct dma_buf_attachment *att, *next;

	if (!d)
		return;
	/* Exporters fire move_notify holding the resv lock; importers'
	 * callbacks may unmap (locked variant) but not detach. */
	mutex_lock(&d->resv->lock);
	for (att = d->attachments; att; att = next) {
		next = att->next;
		if (att->importer_ops && att->importer_ops->move_notify)
			att->importer_ops->move_notify(att);
	}
	mutex_unlock(&d->resv->lock);
}

int mock_dmabuf_live_bufs(void)
{
	int n = 0;

	for (int i = 0; i < MOCK_MAX_DMABUF; i++)
		if (mock_bufs[i])
			n++;
	return n;
}

int mock_dmabuf_live_attachments(void)
{
	return mock_live_attachments;
}

int mock_dmabuf_live_mappings(void)
{
	return mock_live_mappings;
}

/* ------------------------------------------------------------------ *
 * peer-memory registration (ib_core's role)
 * ------------------------------------------------------------------ */
static const struct peer_memory_client *mock_registered_client;
static int mock_invalidations;
static u64 mock_last_core_context;

static int mock_invalidate(void *reg_handle, u64 core_context)
{
	(void)reg_handle;
	mock_invalidations++;
	mock_last_core_context = core_context;
	return 0;
}

void *ib_register_peer_memory_client(const struct peer_memory_client *client,
				     invalidate_peer_memory *invalidate_cb)
{
	if (mock_registered_client)
		return NULL; /* one client in this mock */
	mock_registered_client = client;
	*invalidate_cb = mock_invalidate;
	return (void *)&mock_registered_client;
}

void ib_unregister_peer_memory_client(void *reg_handle)
{
	(void)reg_handle;
	mock_registered_client = NULL;
}

const struct peer_memory_client *mock_peer_client(void)
{
	return mock_registered_client;
}

int mock_invalidate_count(void)
{
	return mock_invalidations;
}

u64 mock_last_invalidated_core_context(void)
{
	return mock_last_core_context;
}
