/* SPDX-License-Identifier: MIT */
/*
 * Mock-kernel harness: drives the UNMODIFIED tpup2p.c / tpup2ptest.c
 * module code through the full peer-memory lifecycle in a plain
 * process.
 *
 * Coverage mirrors SURVEY.md §3's call stacks, which the reference
 * could only exercise on real Fiji+ConnectX hardware:
 *   §3.1 module load/registration      → constructors + mock ib_core
 *   §3.2 ibv_reg_mr claim→pin→map      → claim ioctl + client ops calls
 *   §3.4 free-while-registered revoke  → mock_dmabuf_move → move_notify
 *   §3.5 deregistration                → dma_unmap/put_pages/release
 *   §3.6 chardev harness + mmap        → tpup2ptest ioctls + fops->mmap
 * plus the leak/refcount invariants (module refs, dma-buf refs,
 * attachment and mapping balance, kzalloc balance) that only crash a
 * real kernel long after the bug.
 */

#include <mock/mock_kernel.h>

#include "../tpup2p/peer_mem_compat.h"
#include "../tpup2p/tpup2p_uapi.h"
#include "../tpup2ptest/tpup2ptest_uapi.h"

static int failures;

#define CHECK(cond)                                                       \
	do {                                                              \
		if (!(cond)) {                                            \
			fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__,     \
				__LINE__, #cond);                         \
			failures++;                                       \
		}                                                         \
	} while (0)

#define CHECK_EQ(a, b)                                                     \
	do {                                                               \
		long long va_ = (long long)(a), vb_ = (long long)(b);      \
		if (va_ != vb_) {                                          \
			fprintf(stderr,                                    \
				"FAIL %s:%d: %s == %lld, want %s == %lld\n", \
				__FILE__, __LINE__, #a, va_, #b, vb_);     \
			failures++;                                        \
		}                                                          \
	} while (0)

enum { BUF_SIZE = 16 * 4096 };
static const u64 kVa = 0x500000000000ull;

/* §3.2: the ib_core side of ibv_reg_mr, replayed against the client
 * ops exactly as OFED drives them. Returns the client context. */
static void *do_register(const struct peer_memory_client *pc, u64 va,
			 size_t len, u64 core_context, struct sg_table *sg,
			 int *nmap)
{
	void *ctx = NULL;
	static struct device nic_dev = { "mock-hca" };
	int ret = pc->acquire((unsigned long)va, len, NULL, NULL, &ctx);

	CHECK_EQ(ret, 1);
	CHECK(ctx != NULL);
	ret = pc->get_pages((unsigned long)va, len, 1, 0, sg, ctx,
			    core_context);
	CHECK_EQ(ret, 0);
	CHECK_EQ(pc->get_page_size(ctx), PAGE_SIZE);
	ret = pc->dma_map(sg, ctx, &nic_dev, 0, nmap);
	CHECK_EQ(ret, 0);
	return ctx;
}

static void do_deregister(const struct peer_memory_client *pc, void *ctx,
			  struct sg_table *sg)
{
	static struct device nic_dev = { "mock-hca" };

	pc->dma_unmap(sg, ctx, &nic_dev);
	pc->put_pages(sg, ctx);
	pc->release(ctx);
}

static void test_bridge_lifecycle(struct file *bridge, int fd)
{
	const struct peer_memory_client *pc = mock_peer_client();
	struct tpup2p_claim_param cp = { kVa, BUF_SIZE, fd, 0, 0 };
	struct sg_table sg;
	int nmap = 0;
	void *ctx;
	char *mem;

	CHECK(pc != NULL);
	CHECK_EQ(mock_dev_ioctl(bridge, TPUP2P_IOC_CLAIM, &cp), 0);

	/* overlapping claim rejected */
	struct tpup2p_claim_param overlap = { kVa + 4096, 4096, fd, 0, 0 };
	CHECK_EQ(mock_dev_ioctl(bridge, TPUP2P_IOC_CLAIM, &overlap), -EEXIST);

	/* bad fd propagates the dma_buf_get error */
	struct tpup2p_claim_param badfd = { kVa + (64u << 20), 4096, 9999, 0,
					    0 };
	CHECK_EQ(mock_dev_ioctl(bridge, TPUP2P_IOC_CLAIM, &badfd), -EBADF);

	/* unclaimed VA is "not ours" (acquire → 0, amdp2p.c:133-136) */
	void *nctx = (void *)0xdead;
	CHECK_EQ(pc->acquire(0x1000, 4096, NULL, NULL, &nctx), 0);

	/* another process's VA is not ours either (tgid scoping) */
	mock_set_tgid(1);
	CHECK_EQ(pc->acquire((unsigned long)kVa, BUF_SIZE, NULL, NULL, &nctx),
		 0);
	mock_set_tgid(0);

	/* alloc failure → claim refused, not an error (amdp2p.c:140-144) */
	mock_fail_next_kzalloc = 1;
	CHECK_EQ(pc->acquire((unsigned long)kVa, BUF_SIZE, NULL, NULL, &nctx),
		 0);

	/* the real registration */
	int refs0 = mock_module_refs;
	ctx = do_register(pc, kVa, BUF_SIZE, 42, &sg, &nmap);
	CHECK_EQ(mock_module_refs, refs0 + 1);
	CHECK_EQ(nmap, BUF_SIZE / PAGE_SIZE);
	CHECK_EQ(sg.nents, BUF_SIZE / PAGE_SIZE);

	/* bus addresses really back the dma-buf: write through the sg
	 * list, read via the exporter's memory */
	mem = mock_dmabuf_mem(fd);
	for (unsigned int i = 0; i < sg.nents; i++) {
		char *bus = (char *)(uintptr_t)sg_dma_address(&sg.sgl[i]);

		CHECK(bus == mem + (size_t)i * PAGE_SIZE);
		memset(bus, 0x30 + (int)(i % 10), sg_dma_len(&sg.sgl[i]));
	}
	CHECK_EQ(mem[0], 0x30);
	CHECK_EQ(mem[PAGE_SIZE], 0x31);

	/* clean §3.5 teardown */
	do_deregister(pc, ctx, &sg);
	CHECK_EQ(mock_module_refs, refs0);
	CHECK_EQ(mock_dmabuf_live_mappings(), 0);
	CHECK_EQ(mock_dmabuf_live_attachments(), 0);

	/* §3.4 revocation: exporter moves the buffer while registered */
	int inv0 = mock_invalidate_count();
	ctx = do_register(pc, kVa, BUF_SIZE, 43, &sg, &nmap);
	mock_dmabuf_move(fd);
	CHECK_EQ(mock_invalidate_count(), inv0 + 1);
	CHECK_EQ(mock_last_invalidated_core_context(), 43);
	CHECK_EQ(mock_dmabuf_live_mappings(), 0); /* move tore the map down */
	/* ib_core still runs the dereg path afterwards; it must not
	 * double-unmap (the amdp2p.c:299-302 guard) and must still drop
	 * the attachment */
	do_deregister(pc, ctx, &sg);
	CHECK_EQ(mock_dmabuf_live_attachments(), 0);
	CHECK_EQ(mock_module_refs, refs0);

	/* unclaim; then the range is nobody's */
	struct tpup2p_unclaim_param up = { kVa };
	CHECK_EQ(mock_dev_ioctl(bridge, TPUP2P_IOC_UNCLAIM, &up), 0);
	CHECK_EQ(mock_dev_ioctl(bridge, TPUP2P_IOC_UNCLAIM, &up), -ENOENT);
	CHECK_EQ(pc->acquire((unsigned long)kVa, BUF_SIZE, NULL, NULL, &nctx),
		 0);
}

static void test_chardev_harness(struct file *bridge, int fd)
{
	struct file *tf = mock_dev_open(TPUP2PTEST_DEV_PATH + 5);
	struct tpup2p_claim_param cp = { kVa, BUF_SIZE, fd, 0, 0 };

	CHECK(tf != NULL);
	CHECK_EQ(mock_dev_ioctl(bridge, TPUP2P_IOC_CLAIM, &cp), 0);

	/* QUERY: claimed vs unclaimed (§3.6 is_gpu_address analogue) */
	struct tpup2ptest_query_param q = { kVa, BUF_SIZE, 0, 0 };
	CHECK_EQ(mock_dev_ioctl(tf, TPUP2PTEST_IOC_QUERY, &q), 0);
	CHECK_EQ(q.is_device, 1);
	q = (struct tpup2ptest_query_param){ 0x1000, 4096, 7, 0 };
	CHECK_EQ(mock_dev_ioctl(tf, TPUP2PTEST_IOC_QUERY, &q), 0);
	CHECK_EQ(q.is_device, 0);

	/* PAGE_SIZE */
	struct tpup2ptest_page_size_param ps = { kVa, 0 };
	CHECK_EQ(mock_dev_ioctl(tf, TPUP2PTEST_IOC_PAGE_SIZE, &ps), 0);
	CHECK_EQ(ps.page_size, PAGE_SIZE);

	/* PIN; and a second pin of the same range must coexist (the
	 * double-get_pages semantics the reference made testable,
	 * tests/amdp2ptest.c:296-299 — here unambiguous via handles) */
	struct tpup2ptest_pin_param p1 = { kVa, BUF_SIZE, 0, 0 };
	struct tpup2ptest_pin_param p2 = { kVa, BUF_SIZE, 0, 0 };
	CHECK_EQ(mock_dev_ioctl(tf, TPUP2PTEST_IOC_PIN, &p1), 0);
	CHECK_EQ(mock_dev_ioctl(tf, TPUP2PTEST_IOC_PIN, &p2), 0);
	CHECK_EQ(p1.nents, BUF_SIZE / PAGE_SIZE);
	CHECK(p1.handle != p2.handle);
	CHECK_EQ(mock_dmabuf_live_mappings(), 2);

	/* pin of an unclaimed range */
	struct tpup2ptest_pin_param pbad = { 0x2000, 4096, 0, 0 };
	CHECK_EQ(mock_dev_ioctl(tf, TPUP2PTEST_IOC_PIN, &pbad), -ENXIO);

	/* mmap walks the WHOLE sg list (the reference bug mapped only
	 * the first entry, tests/amdp2ptest.c:389) */
	struct vm_area_struct vma = { 0x10000000,
				      0x10000000 + BUF_SIZE,
				      (unsigned long)p1.handle, 0 };
	mock_mmap_reset();
	CHECK_EQ(tf->f_op->mmap((struct file *)tf, &vma), 0);
	CHECK_EQ(mock_mmap_segment_count(), BUF_SIZE / PAGE_SIZE);
	unsigned long covered = 0;
	unsigned long expect_uaddr = vma.vm_start;
	char *mem = mock_dmabuf_mem(fd);
	for (int i = 0; i < mock_mmap_segment_count(); i++) {
		const struct mock_map_segment *s = mock_mmap_segment(i);

		CHECK_EQ(s->uaddr, expect_uaddr);
		CHECK_EQ(s->pfn,
			 ((unsigned long)(uintptr_t)mem +
			  (unsigned long)i * PAGE_SIZE) >> PAGE_SHIFT);
		expect_uaddr += s->size;
		covered += s->size;
	}
	CHECK_EQ(covered, BUF_SIZE);

	/* partial mmap clamps to the vma */
	struct vm_area_struct small = { 0x20000000, 0x20000000 + 2 * PAGE_SIZE,
					(unsigned long)p2.handle, 0 };
	mock_mmap_reset();
	CHECK_EQ(tf->f_op->mmap((struct file *)tf, &small), 0);
	covered = 0;
	for (int i = 0; i < mock_mmap_segment_count(); i++)
		covered += mock_mmap_segment(i)->size;
	CHECK_EQ(covered, 2 * PAGE_SIZE);

	/* mmap of an unknown handle */
	struct vm_area_struct bad = { 0x30000000, 0x30001000, 77, 0 };
	CHECK_EQ(tf->f_op->mmap((struct file *)tf, &bad), -ENXIO);

	/* UNPIN once; a second unpin of the same handle fails */
	struct tpup2ptest_unpin_param u = { p1.handle };
	CHECK_EQ(mock_dev_ioctl(tf, TPUP2PTEST_IOC_UNPIN, &u), 0);
	CHECK_EQ(mock_dev_ioctl(tf, TPUP2PTEST_IOC_UNPIN, &u), -ENOENT);
	CHECK_EQ(mock_dmabuf_live_mappings(), 1);

	/* close with p2 still pinned: cleanup-on-close reclaims it
	 * (tests/amdp2ptest.c:115-139 contract) */
	CHECK_EQ(mock_dev_close(tf), 0);
	CHECK_EQ(mock_dmabuf_live_mappings(), 0);
	CHECK_EQ(mock_dmabuf_live_attachments(), 0);

	struct tpup2p_unclaim_param up = { kVa };
	CHECK_EQ(mock_dev_ioctl(bridge, TPUP2P_IOC_UNCLAIM, &up), 0);
}

static void test_claims_die_with_fd(int fd)
{
	struct file *bridge = mock_dev_open("tpup2p");
	struct tpup2p_claim_param cp = { kVa, BUF_SIZE, fd, 0, 0 };
	const struct peer_memory_client *pc = mock_peer_client();
	void *nctx;

	CHECK(bridge != NULL);
	CHECK_EQ(mock_dev_ioctl(bridge, TPUP2P_IOC_CLAIM, &cp), 0);
	/* leak the claim; close must reap it */
	CHECK_EQ(mock_dev_close(bridge), 0);
	CHECK_EQ(pc->acquire((unsigned long)kVa, BUF_SIZE, NULL, NULL, &nctx),
		 0);
}

int main(void)
{
	struct file *bridge;
	int fd;

	/* module_init constructors already ran: both devices exist and
	 * the peer-memory client is registered (§3.1). */
	CHECK(mock_misc_find("tpup2p") != NULL);
	CHECK(mock_misc_find("tpup2ptest") != NULL);
	CHECK(mock_peer_client() != NULL);

	bridge = mock_dev_open("tpup2p");
	CHECK(bridge != NULL);
	fd = mock_dmabuf_create(BUF_SIZE);
	CHECK(fd > 0);

	test_bridge_lifecycle(bridge, fd);
	test_chardev_harness(bridge, fd);
	CHECK_EQ(mock_dev_close(bridge), 0);
	test_claims_die_with_fd(fd);

	/* module exit: devices unregister, stray claims reaped */
	mock_run_module_exits();
	CHECK(mock_misc_find("tpup2p") == NULL);
	CHECK(mock_misc_find("tpup2ptest") == NULL);
	CHECK(mock_peer_client() == NULL);

	/* global leak invariants */
	mock_dmabuf_fd_close(fd);
	CHECK_EQ(mock_dmabuf_live_bufs(), 0);
	CHECK_EQ(mock_dmabuf_live_attachments(), 0);
	CHECK_EQ(mock_dmabuf_live_mappings(), 0);
	CHECK_EQ(mock_module_refs, 0);
	CHECK_EQ(mock_kzalloc_live, 0);

	if (failures) {
		fprintf(stderr, "HARNESS FAIL: %d check(s)\n", failures);
		return 1;
	}
	printf("MOCK-KERNEL HARNESS PASS\n");
	return 0;
}
