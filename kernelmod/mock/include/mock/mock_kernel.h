/* SPDX-License-Identifier: MIT */
/*
 * Userspace mock of the kernel APIs consumed by tpup2p/tpup2ptest.
 *
 * SURVEY.md §4's central lesson is that the reference's kernel code was
 * untestable without a Fiji GPU + ConnectX HCA; this mock closes that
 * gap for our kernel modules: the UNMODIFIED module sources compile
 * against these headers into an ordinary process, where a harness
 * (harness.c) drives the full claim → acquire → pin → map → revoke →
 * teardown lifecycle and asserts on leak counters the real kernel
 * would only reveal as crashes.
 *
 * Only the symbols the two modules actually use are provided. Where
 * kernel semantics matter to the code under test (ERR_PTR encoding,
 * dma-buf refcounts and move_notify, per-fd release, idr identity,
 * copy_{from,to}_user failure paths, kzalloc failure injection) the
 * mock honors them; everything else is the simplest thing that links.
 */
#ifndef MOCK_KERNEL_H
#define MOCK_KERNEL_H

#include <errno.h>
#include <pthread.h>
#include <stdarg.h>
#include <stdbool.h>
#include <stddef.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/types.h>
#include <unistd.h>

#ifdef __cplusplus
#error "mock kernel headers are C only (kernel modules are C)"
#endif

/* ------------------------------------------------------------------ *
 * types
 * ------------------------------------------------------------------ */
typedef uint8_t u8;
typedef uint16_t u16;
typedef uint32_t u32;
/* unsigned long long, as in the kernel, so %llu/%llx formats match */
typedef unsigned long long u64;
typedef int32_t s32;
typedef long long s64;
typedef unsigned long long __u64;
typedef uint32_t __u32;
typedef int32_t __s32;
typedef unsigned int gfp_t;
typedef unsigned long pgprot_t;

#define GFP_KERNEL 0u
#define __user
#define __init
#define __exit

#define PAGE_SHIFT 12
#define PAGE_SIZE (1UL << PAGE_SHIFT)

#ifndef offsetof
#define offsetof(type, member) __builtin_offsetof(type, member)
#endif
#define container_of(ptr, type, member) \
	((type *)((char *)(ptr) - offsetof(type, member)))

#define min(a, b) ((a) < (b) ? (a) : (b))

/* ------------------------------------------------------------------ *
 * printk
 * ------------------------------------------------------------------ */
void mock_log(const char *lvl, const char *fmt, ...)
	__attribute__((format(printf, 2, 3)));
#define pr_debug(...) mock_log("debug", __VA_ARGS__)
#define pr_info(...) mock_log("info", __VA_ARGS__)
#define pr_warn(...) mock_log("warn", __VA_ARGS__)
#define pr_err(...) mock_log("err", __VA_ARGS__)

/* ------------------------------------------------------------------ *
 * ERR_PTR
 * ------------------------------------------------------------------ */
static inline void *ERR_PTR(long error) { return (void *)error; }
static inline long PTR_ERR(const void *ptr) { return (long)ptr; }
static inline bool IS_ERR(const void *ptr)
{
	return (unsigned long)ptr >= (unsigned long)-4095;
}

/* ------------------------------------------------------------------ *
 * slab — with a live-allocation counter and failure injection so the
 * harness can assert leak-freedom and exercise alloc-failure paths
 * (the reference treats kzalloc failure in acquire as "not mine",
 * amdp2p.c:140-144; tpup2p keeps that contract).
 * ------------------------------------------------------------------ */
extern int mock_kzalloc_live;
extern int mock_fail_next_kzalloc;
void *mock_kzalloc(size_t n);
void mock_kfree(void *p);
#define kzalloc(n, flags) mock_kzalloc(n)
#define kfree(p) mock_kfree(p)

/* ------------------------------------------------------------------ *
 * mutex
 * ------------------------------------------------------------------ */
struct mutex {
	pthread_mutex_t m;
};
#define DEFINE_MUTEX(name) struct mutex name = { PTHREAD_MUTEX_INITIALIZER }
static inline void mutex_init(struct mutex *mu)
{
	pthread_mutex_init(&mu->m, NULL);
}
static inline void mutex_lock(struct mutex *mu)
{
	pthread_mutex_lock(&mu->m);
}
static inline void mutex_unlock(struct mutex *mu)
{
	pthread_mutex_unlock(&mu->m);
}

/* ------------------------------------------------------------------ *
 * current / pids — harness can impersonate another process to test
 * the tgid scoping of the claim table.
 * ------------------------------------------------------------------ */
#define current ((void *)0)
pid_t mock_task_tgid_nr(void);
void mock_set_tgid(pid_t tgid); /* 0 = real getpid() */
#define task_tgid_nr(task) mock_task_tgid_nr()

/* ------------------------------------------------------------------ *
 * rbtree — same API, plain BST internals (balance is a perf property
 * the code under test never observes)
 * ------------------------------------------------------------------ */
struct rb_node {
	struct rb_node *rb_left;
	struct rb_node *rb_right;
	struct rb_node *rb_parent;
};
struct rb_root {
	struct rb_node *rb_node;
};
#define RB_ROOT ((struct rb_root){ NULL })
#define rb_entry(ptr, type, member) container_of(ptr, type, member)

static inline void rb_link_node(struct rb_node *node, struct rb_node *parent,
				struct rb_node **rb_link)
{
	node->rb_left = NULL;
	node->rb_right = NULL;
	node->rb_parent = parent;
	*rb_link = node;
}
static inline void rb_insert_color(struct rb_node *node, struct rb_root *root)
{
	(void)node;
	(void)root;
}
void rb_erase(struct rb_node *node, struct rb_root *root);
struct rb_node *rb_first(const struct rb_root *root);
struct rb_node *rb_next(const struct rb_node *node);

/* ------------------------------------------------------------------ *
 * scatterlist — flat array form; for_each_sg walks the array
 * ------------------------------------------------------------------ */
struct scatterlist {
	u64 dma_address;
	unsigned int dma_len;
};
struct sg_table {
	struct scatterlist *sgl;
	unsigned int nents;
	unsigned int orig_nents;
};
#define sg_dma_address(sg) ((sg)->dma_address)
#define sg_dma_len(sg) ((sg)->dma_len)
#define for_each_sg(sglist, sg, nents, i) \
	for ((i) = 0, (sg) = (sglist); (i) < (int)(nents); (i)++, (sg)++)

/* ------------------------------------------------------------------ *
 * device / module
 * ------------------------------------------------------------------ */
struct device {
	const char *name;
};
struct module {
	int dummy;
};
extern struct module mock_module;
extern int mock_module_refs;
#define THIS_MODULE (&mock_module)
#define __module_get(mod) (void)(mock_module_refs++)
#define module_put(mod) (void)(mock_module_refs--)

#define MODULE_LICENSE(x)
#define MODULE_DESCRIPTION(x)
#define MODULE_AUTHOR(x)
#define MODULE_VERSION(x)
#define EXPORT_SYMBOL_GPL(sym)
#define EXPORT_SYMBOL(sym)

/* module_init runs at process start (constructor); module_exit is
 * recorded so the harness can invoke the teardown path explicitly and
 * assert on the post-exit state. */
void mock_register_exit(void (*fn)(void));
void mock_run_module_exits(void);
#define module_init(fn)                                                   \
	static void __attribute__((constructor(201))) mock_ctor_##fn(void) \
	{                                                                  \
		if (fn()) {                                                \
			fprintf(stderr, "mock: module_init %s failed\n",   \
				#fn);                                      \
			exit(1);                                           \
		}                                                          \
	}
#define module_exit(fn)                                                      \
	static void __attribute__((constructor(202))) mock_exitreg_##fn(void) \
	{                                                                    \
		mock_register_exit(fn);                                      \
	}

/* ------------------------------------------------------------------ *
 * chardev surface: file_operations + miscdevice + uaccess
 * ------------------------------------------------------------------ */
struct inode {
	int unused;
};
struct file;
struct vm_area_struct;
struct file_operations {
	struct module *owner;
	int (*open)(struct inode *, struct file *);
	int (*release)(struct inode *, struct file *);
	long (*unlocked_ioctl)(struct file *, unsigned int, unsigned long);
	int (*mmap)(struct file *, struct vm_area_struct *);
};
struct file {
	void *private_data;
	const struct file_operations *f_op;
};

#define MISC_DYNAMIC_MINOR 255
struct miscdevice {
	int minor;
	const char *name;
	const struct file_operations *fops;
	unsigned short mode;
	struct device *this_device;
};
int misc_register(struct miscdevice *misc);
void misc_deregister(struct miscdevice *misc);

/* Harness-side chardev access (the role the VFS plays in-kernel). */
struct miscdevice *mock_misc_find(const char *name);
struct file *mock_dev_open(const char *name);
int mock_dev_close(struct file *filp);
long mock_dev_ioctl(struct file *filp, unsigned int cmd, void *arg);

static inline unsigned long copy_from_user(void *to, const void __user *from,
					   unsigned long n)
{
	if (!from)
		return n; /* EFAULT path */
	memcpy(to, from, n);
	return 0;
}
static inline unsigned long copy_to_user(void __user *to, const void *from,
					 unsigned long n)
{
	if (!to)
		return n;
	memcpy(to, from, n);
	return 0;
}

/* ------------------------------------------------------------------ *
 * idr
 * ------------------------------------------------------------------ */
struct idr {
	void **slots;
	int cap;
};
void idr_init(struct idr *idr);
int idr_alloc(struct idr *idr, void *ptr, int start, int end, gfp_t gfp);
void *idr_remove(struct idr *idr, unsigned long id);
void *idr_find(const struct idr *idr, unsigned long id);
void idr_destroy(struct idr *idr);
#define idr_for_each_entry(idr, entry, id)              \
	for ((id) = 0; (id) < (idr)->cap; (id)++)       \
		if (((entry) = (idr)->slots[id]) != NULL)

/* ------------------------------------------------------------------ *
 * mm: vma + remap_pfn_range. Mappings are recorded for the harness to
 * verify sg-walk coverage (the reference's mmap bug — first entry
 * only, tests/amdp2ptest.c:389 — is exactly what this catches).
 * ------------------------------------------------------------------ */
struct vm_area_struct {
	unsigned long vm_start;
	unsigned long vm_end;
	unsigned long vm_pgoff;
	pgprot_t vm_page_prot;
};
int remap_pfn_range(struct vm_area_struct *vma, unsigned long addr,
		    unsigned long pfn, unsigned long size, pgprot_t prot);

struct mock_map_segment {
	unsigned long uaddr;
	unsigned long pfn;
	unsigned long size;
};
void mock_mmap_reset(void);
int mock_mmap_segment_count(void);
const struct mock_map_segment *mock_mmap_segment(int i);

/* ------------------------------------------------------------------ *
 * dma-buf — mock exporter with refcounts, page-granular sg tables over
 * a malloc'd backing store, and harness-triggered move_notify
 * ------------------------------------------------------------------ */
enum dma_data_direction {
	DMA_BIDIRECTIONAL = 0,
	DMA_TO_DEVICE = 1,
	DMA_FROM_DEVICE = 2,
};

struct dma_resv {
	struct mutex lock;
};
static inline void dma_resv_lock(struct dma_resv *resv, void *ctx)
{
	(void)ctx;
	mutex_lock(&resv->lock);
}
static inline void dma_resv_unlock(struct dma_resv *resv)
{
	mutex_unlock(&resv->lock);
}

struct dma_buf;
struct dma_buf_attachment;
struct dma_buf_attach_ops {
	bool allow_peer2peer;
	void (*move_notify)(struct dma_buf_attachment *attach);
};
struct dma_buf_attachment {
	struct dma_buf *dmabuf;
	struct device *dev;
	void *importer_priv;
	const struct dma_buf_attach_ops *importer_ops;
	struct sg_table *sgt; /* live mapping, if any */
	struct dma_buf_attachment *next;
};
struct dma_buf {
	void *backing;
	size_t size;
	int refcount;
	int fd;
	struct dma_resv resv_storage;
	struct dma_resv *resv;
	struct dma_buf_attachment *attachments;
};

struct dma_buf *dma_buf_get(int fd);
void get_dma_buf(struct dma_buf *dmabuf);
void dma_buf_put(struct dma_buf *dmabuf);
struct dma_buf_attachment *dma_buf_attach(struct dma_buf *dmabuf,
					  struct device *dev);
struct dma_buf_attachment *
dma_buf_dynamic_attach(struct dma_buf *dmabuf, struct device *dev,
		       const struct dma_buf_attach_ops *ops, void *priv);
void dma_buf_detach(struct dma_buf *dmabuf, struct dma_buf_attachment *att);
struct sg_table *dma_buf_map_attachment(struct dma_buf_attachment *att,
					enum dma_data_direction dir);
void dma_buf_unmap_attachment(struct dma_buf_attachment *att,
			      struct sg_table *sgt,
			      enum dma_data_direction dir);

/* Harness-side exporter controls. */
int mock_dmabuf_create(size_t size); /* returns an "fd" */
void *mock_dmabuf_mem(int fd);
void mock_dmabuf_fd_close(int fd); /* drop the fd's own reference */
void mock_dmabuf_move(int fd);     /* fire move_notify on dynamic attachments */
int mock_dmabuf_live_bufs(void);
int mock_dmabuf_live_attachments(void);
int mock_dmabuf_live_mappings(void);

/* ------------------------------------------------------------------ *
 * peer-memory registration (role of OFED ib_core)
 * ------------------------------------------------------------------ */
struct peer_memory_client;
const struct peer_memory_client *mock_peer_client(void);
int mock_invalidate_count(void);
u64 mock_last_invalidated_core_context(void);

#endif /* MOCK_KERNEL_H */
