/* SPDX-License-Identifier: MIT */
/* mock stub: kernel ioctl encoding (matches asm-generic/ioctl.h) */
#ifndef MOCK_LINUX_IOCTL_H
#define MOCK_LINUX_IOCTL_H
#define _IOC_NRBITS 8
#define _IOC_TYPEBITS 8
#define _IOC_SIZEBITS 14
#define _IOC_NRSHIFT 0
#define _IOC_TYPESHIFT (_IOC_NRSHIFT + _IOC_NRBITS)
#define _IOC_SIZESHIFT (_IOC_TYPESHIFT + _IOC_TYPEBITS)
#define _IOC_DIRSHIFT (_IOC_SIZESHIFT + _IOC_SIZEBITS)
#define _IOC_NONE 0U
#define _IOC_WRITE 1U
#define _IOC_READ 2U
#define _IOC(dir, type, nr, size)                                     \
	(((dir) << _IOC_DIRSHIFT) | ((type) << _IOC_TYPESHIFT) |      \
	 ((nr) << _IOC_NRSHIFT) | ((size) << _IOC_SIZESHIFT))
#define _IO(type, nr) _IOC(_IOC_NONE, (type), (nr), 0)
#define _IOW(type, nr, sz) _IOC(_IOC_WRITE, (type), (nr), sizeof(sz))
#define _IOR(type, nr, sz) _IOC(_IOC_READ, (type), (nr), sizeof(sz))
#define _IOWR(type, nr, sz)                                           \
	_IOC(_IOC_READ | _IOC_WRITE, (type), (nr), sizeof(sz))
#endif
