/* SPDX-License-Identifier: MIT */
/* mock stub — see mock/mock_kernel.h */
#include <mock/mock_kernel.h>
