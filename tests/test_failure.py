"""Failure-path tests: rank death mid-collective.

The reference's only failure path is process-death revocation
(SURVEY.md §3.4); a framework that also OWNS the collective layer must
additionally guarantee that a peer crashing mid-allreduce surfaces as
an error on the survivors — RC flush semantics — never as a hang.
These tests SIGKILL a rank at different points and assert the
survivor errors out promptly with TransportError.
"""

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(script: str, timeout=120) -> subprocess.CompletedProcess:
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # These tests assert "peer death surfaces PROMPTLY" — the ring
    # stall deadline is part of what's under test, so the subprocess
    # must not inherit the suite-wide 120 s contention allowance from
    # conftest (the dead-peer path usually flushes in ms via TCP
    # close, but when the teardown races bootstrap the deadline is
    # the backstop, and it must fire well inside this harness
    # timeout).
    env["TDR_RING_TIMEOUT_MS"] = "20000"
    return subprocess.run([sys.executable, "-c", script], env=env,
                          capture_output=True, text=True, timeout=timeout)


def test_rank_killed_mid_allreduce_surfaces_error():
    """Child rank is SIGKILLed while a large allreduce is in flight;
    the surviving rank must raise TransportError (flush/completion
    error), not hang. Exercised on the stream tier so payloads are
    actually mid-wire when the peer dies."""
    proc = _run("""
import os, signal, socket, sys, time
import numpy as np

os.environ["TDR_NO_CMA"] = "1"          # keep payloads on the wire
os.environ["TDR_RING_CHUNK"] = "65536"  # many chunks -> long transfer

s = socket.socket(); s.bind(("127.0.0.1", 0))
base = s.getsockname()[1]; s.close()
count = (64 << 20) // 4

pid = os.fork()
rank = 1 if pid == 0 else 0
from rocnrdma_tpu.collectives.world import RingWorld
from rocnrdma_tpu.transport.engine import Engine, TransportError

w = RingWorld(Engine("emu"), rank, 2, base + 100)
buf = np.full(count, float(rank + 1), dtype=np.float32)
if pid == 0:
    # Child: start the allreduce; the parent will kill us mid-flight.
    try:
        w.allreduce(buf)
    except Exception:
        pass
    os._exit(0)

# Parent: give the exchange a moment to get onto the wire, then kill.
killer_fired = []
import threading
def killer():
    time.sleep(0.3)
    os.kill(pid, signal.SIGKILL)
    killer_fired.append(True)
t = threading.Thread(target=killer); t.start()
t0 = time.monotonic()
try:
    w.allreduce(buf)
    # Tiny race window: the whole allreduce beat the killer. Accept
    # only if the kill genuinely came too late.
    t.join()
    print("COMPLETED-BEFORE-KILL")
except TransportError as e:
    elapsed = time.monotonic() - t0
    assert elapsed < 60, f"took {elapsed}s - effectively hung"
    print("SURVIVOR-ERRORED", e.kind, str(e)[:60])
t.join()
os.waitpid(pid, 0)
""")
    assert proc.returncode == 0, (
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}")
    assert ("SURVIVOR-ERRORED" in proc.stdout
            or "COMPLETED-BEFORE-KILL" in proc.stdout)
    # A killed peer is a CONNECTION loss, never a "hung" verdict — the
    # taxonomy keeps dead-process and wedged-process postmortems apart.
    assert "SURVIVOR-ERRORED hung" not in proc.stdout, proc.stdout


def test_rank_killed_before_collective_flushes_bootstrap():
    """Peer dies right after connecting, before any collective: posts
    against the dead QP flush rather than hang."""
    proc = _run("""
import os, signal, socket, time
import numpy as np

s = socket.socket(); s.bind(("127.0.0.1", 0))
base = s.getsockname()[1]; s.close()

pid = os.fork()
rank = 1 if pid == 0 else 0
from rocnrdma_tpu.collectives.world import RingWorld
from rocnrdma_tpu.transport.engine import Engine, TransportError

w = RingWorld(Engine("emu"), rank, 2, base + 100)
if pid == 0:
    os._exit(0)   # die immediately, QPs up but idle
os.waitpid(pid, 0)
buf = np.ones(1 << 20, dtype=np.float32)
try:
    w.allreduce(buf)
    raise SystemExit("allreduce against a dead peer succeeded?!")
except TransportError:
    print("FLUSHED")
""")
    assert proc.returncode == 0, (
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}")
    assert "FLUSHED" in proc.stdout


_HUNG_PEER_SCRIPT = """
import os, signal, socket, time
import numpy as np

s = socket.socket(); s.bind(("127.0.0.1", 0))
base = s.getsockname()[1]; s.close()
rfd, wfd = os.pipe()

pid = os.fork()
rank = 1 if pid == 0 else 0
%(child_env)s
from rocnrdma_tpu.collectives.world import RingWorld
from rocnrdma_tpu.transport.engine import Engine, TransportError

w = RingWorld(Engine("emu"), rank, 2, base + 100)
if pid == 0:
    # Child: bootstrap done (features negotiated, QPs live) — report
    # ready, then idle. The parent freezes us BEFORE we ever enter a
    # collective, so no data is mid-wire: the survivor's stall is a
    # pure silent-peer stall, not a flush.
    os.close(rfd); os.write(wfd, b"r"); os.close(wfd)
    time.sleep(120)
    os._exit(0)

os.close(wfd)
assert os.read(rfd, 1) == b"r"
os.close(rfd)
os.kill(pid, signal.SIGSTOP)   # wedge the peer: alive but frozen
time.sleep(0.2)                # let the STOP land

# Small buffer: the PING must never queue behind bulk data in the
# peer's (frozen, finite) socket buffers.
os.environ["TDR_RING_TIMEOUT_MS"] = "5000"
buf = np.ones((64 << 10) // 4, dtype=np.float32)
try:
    try:
        w.allreduce(buf)
        print("UNEXPECTED-COMPLETION")
    except TransportError as e:
        print("STALLED", e.kind)
        print("MSG", str(e)[:200])
finally:
    os.kill(pid, signal.SIGCONT)
    os.kill(pid, signal.SIGKILL)
    os.waitpid(pid, 0)
"""


def test_hung_peer_classified_distinctly_from_conn_drop():
    """A SIGSTOPped peer — process alive, connection up, zero progress
    — must classify as `kind == "hung"` via the zero-byte probe (PING
    delivered, PONG never comes), which is exactly what a kill/crash
    can never produce. Postmortems for the two diverge completely:
    hung says "look at the PEER's stacks", conn-drop says "the process
    died"."""
    proc = _run(_HUNG_PEER_SCRIPT % {"child_env": ""})
    assert proc.returncode == 0, (
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}")
    assert "STALLED hung" in proc.stdout, proc.stdout
    assert "peer hung (probe unanswered)" in proc.stdout, proc.stdout


def test_no_probe_peer_keeps_legacy_stall_message():
    """Feature gate: the child disables FEAT_PROBE at its handshake
    (TDR_NO_PROBE=1 post-fork, pre-import), so the pair never
    negotiates probing and the survivor's stall surfaces EXACTLY as it
    did before this feature existed — no verdict suffix, no "hung"
    classification — proving probe frames are invisible to legacy
    peers."""
    proc = _run(_HUNG_PEER_SCRIPT % {
        "child_env": 'if pid == 0: os.environ["TDR_NO_PROBE"] = "1"'})
    assert proc.returncode == 0, (
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}")
    assert "STALLED transport" in proc.stdout, proc.stdout
    for verdict in ("peer hung", "peer alive", "peer connection down"):
        assert verdict not in proc.stdout, proc.stdout
