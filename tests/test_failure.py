"""Failure-path tests: rank death mid-collective.

The reference's only failure path is process-death revocation
(SURVEY.md §3.4); a framework that also OWNS the collective layer must
additionally guarantee that a peer crashing mid-allreduce surfaces as
an error on the survivors — RC flush semantics — never as a hang.
These tests SIGKILL a rank at different points and assert the
survivor errors out promptly with TransportError.
"""

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(script: str, timeout=120) -> subprocess.CompletedProcess:
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # These tests assert "peer death surfaces PROMPTLY" — the ring
    # stall deadline is part of what's under test, so the subprocess
    # must not inherit the suite-wide 120 s contention allowance from
    # conftest (the dead-peer path usually flushes in ms via TCP
    # close, but when the teardown races bootstrap the deadline is
    # the backstop, and it must fire well inside this harness
    # timeout).
    env["TDR_RING_TIMEOUT_MS"] = "20000"
    return subprocess.run([sys.executable, "-c", script], env=env,
                          capture_output=True, text=True, timeout=timeout)


def test_rank_killed_mid_allreduce_surfaces_error():
    """Child rank is SIGKILLed while a large allreduce is in flight;
    the surviving rank must raise TransportError (flush/completion
    error), not hang. Exercised on the stream tier so payloads are
    actually mid-wire when the peer dies."""
    proc = _run("""
import os, signal, socket, sys, time
import numpy as np

os.environ["TDR_NO_CMA"] = "1"          # keep payloads on the wire
os.environ["TDR_RING_CHUNK"] = "65536"  # many chunks -> long transfer

s = socket.socket(); s.bind(("127.0.0.1", 0))
base = s.getsockname()[1]; s.close()
count = (64 << 20) // 4

pid = os.fork()
rank = 1 if pid == 0 else 0
from rocnrdma_tpu.collectives.world import RingWorld
from rocnrdma_tpu.transport.engine import Engine, TransportError

w = RingWorld(Engine("emu"), rank, 2, base + 100)
buf = np.full(count, float(rank + 1), dtype=np.float32)
if pid == 0:
    # Child: start the allreduce; the parent will kill us mid-flight.
    try:
        w.allreduce(buf)
    except Exception:
        pass
    os._exit(0)

# Parent: give the exchange a moment to get onto the wire, then kill.
killer_fired = []
import threading
def killer():
    time.sleep(0.3)
    os.kill(pid, signal.SIGKILL)
    killer_fired.append(True)
t = threading.Thread(target=killer); t.start()
t0 = time.monotonic()
try:
    w.allreduce(buf)
    # Tiny race window: the whole allreduce beat the killer. Accept
    # only if the kill genuinely came too late.
    t.join()
    print("COMPLETED-BEFORE-KILL")
except TransportError as e:
    elapsed = time.monotonic() - t0
    assert elapsed < 60, f"took {elapsed}s - effectively hung"
    print("SURVIVOR-ERRORED", str(e)[:60])
t.join()
os.waitpid(pid, 0)
""")
    assert proc.returncode == 0, (
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}")
    assert ("SURVIVOR-ERRORED" in proc.stdout
            or "COMPLETED-BEFORE-KILL" in proc.stdout)


def test_rank_killed_before_collective_flushes_bootstrap():
    """Peer dies right after connecting, before any collective: posts
    against the dead QP flush rather than hang."""
    proc = _run("""
import os, signal, socket, time
import numpy as np

s = socket.socket(); s.bind(("127.0.0.1", 0))
base = s.getsockname()[1]; s.close()

pid = os.fork()
rank = 1 if pid == 0 else 0
from rocnrdma_tpu.collectives.world import RingWorld
from rocnrdma_tpu.transport.engine import Engine, TransportError

w = RingWorld(Engine("emu"), rank, 2, base + 100)
if pid == 0:
    os._exit(0)   # die immediately, QPs up but idle
os.waitpid(pid, 0)
buf = np.ones(1 << 20, dtype=np.float32)
try:
    w.allreduce(buf)
    raise SystemExit("allreduce against a dead peer succeeded?!")
except TransportError:
    print("FLUSHED")
""")
    assert proc.returncode == 0, (
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}")
    assert "FLUSHED" in proc.stdout
