"""Serving data path (ISSUE 18): streaming transfer engine, weight/KV
page prefetch over the zero-copy path, continuous batching.

Pins the contracts that make the serving subsystem safe to grow on:

- **credit accounting**: the engine never holds more than
  ``TDR_STREAM_DEPTH`` transfers in flight (high-water mark proved,
  not assumed); a failed launch/fetch refunds its credit; teardown
  drains to a balanced gate with a FLAT thread census (the engine
  spawns no threads — it rides the PR 8 async driver);
- **pager FIFO**: prefetch order is acquire order, out-of-order
  acquires raise instead of silently serving the wrong page;
- **sealed KV streaming**: a corrupt rider on a streamed KV page at
  world 2 fails seal verification, NAKs, retransmits, and the
  consumer sees bitwise the home rank's bytes;
- **continuous batching**: mid-stream join (home-rank prefill + KV
  page streaming) and mid-stream evict at token boundaries produce
  tokens bitwise identical to a sequential loopback run, with and
  without prefetch, at world 1 and 2;
- **numpy/flax parity**: the paged numpy decoder greedy-decodes the
  same tokens ``llama.generate`` does (the port's contract);
- **SLO metrics**: serve.* counters and the token_lat_us fine
  histogram ride the ordinary heartbeat and render on /metrics under
  the contract-pinned names (``tdr_serve_requests_total`` /
  ``tdr_serve_tokens_total`` / ``tdr_token_lat_us{quantile=}``);
- **attribution**: request-tagged stream collective ids decompose in
  tdr_explain per request id.
"""

import os
import sys
import threading

import numpy as np
import pytest

from rocnrdma_tpu.collectives.world import local_worlds
from rocnrdma_tpu.serving.batcher import ContinuousBatcher, Request
from rocnrdma_tpu.serving.model import (PagedDecoder, ServeConfig,
                                        pack_pages, page_names,
                                        toy_param_tree)
from rocnrdma_tpu.serving.pager import KVStream, PageSet, WeightStreamer
from rocnrdma_tpu.serving.stream import (CreditGate, TransferEngine,
                                         is_stream_coll,
                                         make_stream_coll, stream_coll_request,
                                         stream_coll_seq, stream_depth)
from rocnrdma_tpu.transport.engine import (fault_plan_reset, seal_counters,
                                           seal_counters_reset,
                                           telemetry_reset)
from rocnrdma_tpu.utils.trace import trace

from test_transport import free_port

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")
if TOOLS not in sys.path:
    sys.path.insert(0, TOOLS)


@pytest.fixture(autouse=True)
def _serving_env():
    keys = ("TDR_TELEMETRY", "TDR_FAULT_PLAN", "TDR_SEAL_CMA",
            "TDR_STREAM_DEPTH")
    saved = {k: os.environ.get(k) for k in keys}
    trace.reset()
    yield
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    telemetry_reset()
    fault_plan_reset()
    seal_counters_reset()


def _task_count() -> int:
    return len(os.listdir("/proc/self/task"))


def _toy(seed=7, **over):
    cfg = ServeConfig(vocab_size=64, d_model=32, n_layers=2, n_heads=4,
                      n_kv_heads=2, d_ff=64, max_seq_len=32,
                      rope_theta=10000.0, **over)
    return cfg, pack_pages(cfg, toy_param_tree(cfg, seed=seed))


# ------------------------------------------------------ coll-id encoding


def test_stream_coll_encoding_roundtrip():
    """Request/seq round-trip; bit 63 stays clear (the ring's
    auto-assign bit must never collide with serving ids)."""
    for req, seq in ((0, 1), (7, 3), ((1 << 22) - 1, (1 << 40) - 1)):
        c = make_stream_coll(req, seq)
        assert is_stream_coll(c)
        assert c >> 63 == 0
        assert stream_coll_request(c) == req
        assert stream_coll_seq(c) == seq
    assert not is_stream_coll(0)
    assert not is_stream_coll(1 << 63)
    assert not is_stream_coll((1 << 63) | (1 << 62))


# ------------------------------------------------------ credit accounting


def test_credit_gate_depth_and_underflow():
    g = CreditGate(2, name="t")
    assert g.acquire() and g.acquire()
    assert g.in_flight == 2 and g.high_water == 2
    assert not g.acquire(timeout_s=0.02)  # full — bounded, not broken
    g.release()
    assert g.acquire(timeout_s=1.0)
    g.release()
    g.release()
    with pytest.raises(RuntimeError):
        g.release()  # refunding a credit never acquired is a bug


def test_engine_failed_launch_refunds_credit():
    eng = TransferEngine(depth=2, name="t")
    with pytest.raises(ValueError):
        eng.submit(lambda: (_ for _ in ()).throw(ValueError("boom")))
    s = eng.stats()
    assert s["in_flight"] == 0 and s["acquired"] == s["released"]
    eng.close()


def test_streamer_honors_stream_depth_env(monkeypatch):
    """Loopback pager over many pages: the high-water mark never
    exceeds TDR_STREAM_DEPTH, every credit is refunded, teardown is
    thread-flat (the engine spawns none)."""
    monkeypatch.setenv("TDR_STREAM_DEPTH", "2")
    assert stream_depth() == 2
    cfg, pages = _toy()
    before = _task_count()
    st = WeightStreamer(None, pages, name="t")
    assert st.depth == 2
    order = list(range(len(pages))) * 3
    fetched = 0
    for _ in range(st.depth):
        st.prefetch(order[fetched] if fetched < len(order) else 0)
        fetched += 1
    for k, idx in enumerate(order):
        view = st.acquire(idx)
        np.testing.assert_array_equal(view, pages.pages[idx])
        st.release(view)
        if fetched < len(order):
            st.prefetch(order[fetched])
            fetched += 1
    s = st.stats()
    assert s["high_water"] <= 2, s
    assert s["pages"] == len(order)
    st.close()
    s = st.stats()
    assert s["acquired"] == s["released"] and s["in_flight"] == 0, s
    assert _task_count() == before


def test_streamer_fifo_contract():
    cfg, pages = _toy()
    st = WeightStreamer(None, pages, depth=2)
    st.prefetch(0)
    st.prefetch(1)
    with pytest.raises(RuntimeError, match="FIFO"):
        st.acquire(1)  # head of stream is page 0
    v = st.acquire(0)
    st.release(v)
    with pytest.raises(RuntimeError, match="aliases no held window"):
        st.release(np.zeros(4, np.float32))
    st.close()


def test_streamer_teardown_mid_stream_drains():
    """close() with fetches in flight AND pages held: every window
    and credit comes back, no thread leaks."""
    cfg, pages = _toy()
    before = _task_count()
    st = WeightStreamer(None, pages, depth=3)
    st.prefetch(0)
    st.prefetch(1)
    st.prefetch(2)
    _held = st.acquire(0)  # held, never released by the caller
    st.close()
    s = st.stats()
    assert s["acquired"] == s["released"] and s["in_flight"] == 0, s
    assert len(st._free) == st.depth
    assert _task_count() == before


def test_world2_credit_refund_under_retransmit(monkeypatch):
    """NAK/retransmit on a streamed weight page: the heal is invisible
    to the credit ledger — the gate balances, high-water stays within
    depth, and the landed page is bitwise right."""
    monkeypatch.setenv("TDR_SEAL_CMA", "1")
    monkeypatch.setenv("TDR_RING_CHUNK", str(16 << 10))
    monkeypatch.setenv("TDR_FAULT_PLAN", "send:chunk=0:nth=1:corrupt=3")
    fault_plan_reset()
    seal_counters_reset()
    cfg, pages = _toy()
    worlds = local_worlds(2, free_port())
    try:
        sts = [WeightStreamer(w, pages, depth=2) for w in worlds]
        outs = [[] for _ in range(2)]

        def run(r):
            st = sts[r]
            for idx in list(range(len(pages))) * 2:
                st.prefetch(idx)
                view = st.acquire(idx)
                outs[r].append(view.copy())
                st.release(view)

        ts = [threading.Thread(target=run, args=(r,)) for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        c = seal_counters()
        assert c["failed"] >= 1 and c["retransmitted"] >= 1, c
        for r in range(2):
            for got, idx in zip(outs[r], list(range(len(pages))) * 2):
                np.testing.assert_array_equal(got, pages.pages[idx])
            s = sts[r].stats()
            assert s["acquired"] == s["released"], s
            assert s["high_water"] <= 2, s
            sts[r].close()
    finally:
        monkeypatch.delenv("TDR_FAULT_PLAN")
        fault_plan_reset()
        for w in worlds:
            w.close()
    seal_counters_reset()


def test_world2_kv_page_corrupt_rider_heals(monkeypatch):
    """A corrupt rider on a streamed KV page walks the NAK/retransmit
    ladder and every rank still receives the home rank's exact bytes,
    under the request-tagged collective id."""
    monkeypatch.setenv("TDR_SEAL_CMA", "1")
    monkeypatch.setenv("TDR_RING_CHUNK", str(16 << 10))
    monkeypatch.setenv("TDR_FAULT_PLAN", "send:chunk=0:nth=1:corrupt=3")
    fault_plan_reset()
    seal_counters_reset()
    rng = np.random.default_rng(3)
    payload = rng.standard_normal(6144).astype(np.float32)
    worlds = local_worlds(2, free_port())
    try:
        kvs = [KVStream(w, max_elems=payload.size) for w in worlds]
        got = [None, None]

        def run(r):
            got[r] = kvs[r].broadcast(payload if r == 0 else None,
                                      home=0, request_id=9, seq=1,
                                      n=payload.size)

        ts = [threading.Thread(target=run, args=(r,)) for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        c = seal_counters()
        assert c["failed"] >= 1 and c["retransmitted"] >= 1, c
        np.testing.assert_array_equal(got[0], payload)
        np.testing.assert_array_equal(got[1], payload)
        for kv in kvs:
            s = kv.engine.stats()
            assert s["acquired"] == s["released"], s
            kv.close()
    finally:
        monkeypatch.delenv("TDR_FAULT_PLAN")
        fault_plan_reset()
        for w in worlds:
            w.close()
    seal_counters_reset()


# --------------------------------------------------- continuous batching


def _scenario(b):
    """Join/evict churn: R1+R2 decode, R3 queues while full, R1 is
    evicted mid-stream, the freed slot admits R3 mid-stream."""
    b.submit(Request(1, [3, 7, 11], 8))
    b.submit(Request(2, [9, 2], 6))
    for _ in range(3):
        b.step()
    b.submit(Request(3, [5, 1], 4))
    b.evict(1)
    b.run()
    return {rid: r.tokens for rid, r in sorted(b.finished.items())}


def test_batcher_join_evict_loopback_prefetch_parity():
    """Loopback: the scenario evicts R1 mid-stream, admits R3
    mid-stream, and prefetch on/off produce bitwise the same tokens
    (the page bytes are identical; only the timing moves)."""
    cfg, pages = _toy()
    outs = {}
    for prefetch in (False, True):
        b = ContinuousBatcher(None, pages, cfg, max_slots=2,
                              prefetch=prefetch)
        outs[prefetch] = _scenario(b)
        b.close()
        assert b.finished[1].evicted
        assert 0 < len(b.finished[1].tokens) < 8
        assert not b.finished[2].evicted
        assert len(b.finished[2].tokens) == 6
        assert b.finished[3].joined_step > 0
        assert len(b.finished[3].tokens) == 4
        s = b.streamer.stats()
        assert s["acquired"] == s["released"], s
    assert outs[False] == outs[True]


def test_batcher_world2_lockstep_bitwise_vs_loopback():
    """World-2 streamed decode (weights gathered per page, KV joins
    broadcast over the sealed path) produces tokens bitwise identical
    on both ranks AND to the sequential loopback baseline."""
    cfg, pages = _toy()
    base = ContinuousBatcher(None, pages, cfg, max_slots=2,
                             prefetch=False)
    want = _scenario(base)
    base.close()

    worlds = local_worlds(2, free_port())
    try:
        bs = [ContinuousBatcher(w, pages, cfg, max_slots=2) for w in worlds]
        got = [None, None]
        errs = [None, None]

        def run(r):
            try:
                got[r] = _scenario(bs[r])
            except BaseException as e:  # noqa: BLE001 — surfaced below
                errs[r] = e

        ts = [threading.Thread(target=run, args=(r,)) for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        for e in errs:
            if e is not None:
                raise e
        assert got[0] == got[1] == want
        for b in bs:
            b.close()
        assert [w.pending_async for w in worlds] == [0, 0]
    finally:
        for w in worlds:
            w.close()


def test_batcher_requeued_eviction_before_admission():
    """Evicting a request that is still QUEUED finishes it with zero
    tokens at the next boundary instead of admitting it."""
    cfg, pages = _toy()
    b = ContinuousBatcher(None, pages, cfg, max_slots=1)
    b.submit(Request(1, [4], 3))
    b.submit(Request(2, [5], 3))
    b.evict(2)
    b.run()
    b.close()
    assert b.finished[2].evicted and b.finished[2].tokens == []
    assert len(b.finished[1].tokens) == 3


# ------------------------------------------------------------ the model


def test_paged_decoder_matches_flax_llama():
    """The numpy paged port greedy-decodes EXACTLY llama.generate's
    tokens on llama-tiny (f32 end to end, same masking/RoPE/GQA)."""
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp

    from rocnrdma_tpu.models import llama
    from rocnrdma_tpu.serving.model import pack_llama_params

    lcfg = llama.LLAMA_TINY
    model = llama.make_model(lcfg)
    params = llama.init_params(model, jax.random.PRNGKey(0))
    cfg = ServeConfig.from_llama(lcfg)
    pages = pack_llama_params(
        cfg, jax.tree_util.tree_map(np.asarray, params))
    assert page_names(cfg)[0] == "embed"
    assert len(pages) == cfg.n_layers + 2

    prompt = [5, 9, 42, 7]
    want = np.asarray(llama.generate(
        model, params, jnp.array([prompt], dtype=jnp.int32), 8,
        temperature=0.0))[0].tolist()
    b = ContinuousBatcher(None, pages, cfg, max_slots=1, prefetch=False)
    b.submit(Request(1, prompt, 8))
    b.run()
    b.close()
    assert b.finished[1].tokens == want


def test_page_layout_roundtrip():
    """pack_pages → unpack views reproduce the parameter tree
    bitwise, and the page count/naming is the serving contract."""
    from rocnrdma_tpu.serving.model import (unpack_embed, unpack_head,
                                            unpack_layer)

    cfg, pages = _toy(seed=13)
    tree = toy_param_tree(cfg, seed=13)
    np.testing.assert_array_equal(unpack_embed(cfg, pages.pages[0]),
                                  tree["embed"])
    for li in range(cfg.n_layers):
        lay = unpack_layer(cfg, pages.pages[1 + li])
        for k, v in tree[f"layer_{li}"].items():
            np.testing.assert_array_equal(lay[k], v)
    final_norm, lm_head = unpack_head(cfg, pages.pages[-1])
    np.testing.assert_array_equal(final_norm, tree["final_norm"])
    np.testing.assert_array_equal(lm_head, tree["lm_head"])
    assert page_names(cfg) == ["embed", "layer_0", "layer_1", "head"]


# ------------------------------------------------------------ SLO metrics


def test_serve_counters_and_hist_ride_heartbeat_to_metrics():
    """The serving SLO series render on /metrics under the
    contract-pinned names: tdr_serve_requests_total{world=},
    tdr_serve_tokens_total, and tdr_token_lat_us{quantile=} computed
    from the FINE (log2×8) histogram rows the heartbeat pushes —
    through the real coordinator wire (join → heartbeat → scrape),
    with the payload shaped exactly as the world's heartbeat hooks
    ship it (serve.* counters + fine rows carrying the {64:0}
    marker)."""
    from rocnrdma_tpu.control.client import ControlClient
    from rocnrdma_tpu.control.coordinator import Coordinator

    cfg, pages = _toy()
    b = ContinuousBatcher(None, pages, cfg, max_slots=2)
    b.submit(Request(1, [3, 7], 5))
    b.submit(Request(2, [4], 5))
    b.run()
    b.close()
    toks = trace.counter("serve.tokens")
    assert toks == len(b.finished[1].tokens) + len(b.finished[2].tokens)

    co = Coordinator(port=0, lease_ms=5000, port_base=free_port()).start()
    try:
        client = ControlClient(co.address)
        views = [None, None]

        def j(r):
            views[r] = client.join("serve", 2, rank=r)

        ts = [threading.Thread(target=j, args=(r,)) for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        hists = {name: {**{64: 0}, **row}
                 for name, row in trace.hists().items()}
        client.heartbeat("serve", 0, views[0]["incarnation"],
                         views[0]["generation"],
                         counters=trace.counters_prefixed("serve."),
                         hists=hists)
        body = client.metrics()
    finally:
        co.stop()
    assert 'tdr_serve_requests_total{world="serve"} 2' in body
    assert f'tdr_serve_tokens_total{{world="serve"}} {toks}' in body
    # Quantiles come from FINE bucket edges — real numbers for the
    # pinned quantile labels, not octave saturation.
    for q in ("0.50", "0.90", "0.99"):
        line = [ln for ln in body.splitlines()
                if ln.startswith(f'tdr_token_lat_us{{world="serve",'
                                 f'quantile="{q}"}}')]
        assert line, f"quantile {q} not served:\n{body}"
        assert float(line[0].rsplit(" ", 1)[1]) > 0
    assert f'tdr_token_lat_us_count{{world="serve"}} {toks}' in body


def test_fine_hist_rows_read_fine_edges_not_octave_edges():
    """trace.hist buckets mirror the native fine layout, and a row
    reconstructed the coordinator's way (grow-to-fit + the {64:0}
    marker) yields sub-octave percentile estimates — the BENCH_r06
    saturated-percentile defect, pinned for serving latencies."""
    from rocnrdma_tpu.telemetry.recorder import (bucket_upper,
                                                 fine_bucket_upper,
                                                 hist_percentile)

    trace.reset()
    # 1100 lives in octave 11 (1024..2047), first sub-bucket:
    # fine upper edge 1151, octave upper edge 2047.
    for _ in range(4):
        trace.hist("token_lat_us", 1100)
    row = trace.hists()["token_lat_us"]
    (bkt,) = row.keys()
    assert row[bkt] == 4
    assert fine_bucket_upper(bkt) == 1151
    grown = [0] * 64
    # Marker FIRST (the worker merges with setdefault): bucket 64 may
    # legitimately hold counts — 1100's fine bucket IS 64.
    for b, c in {**{64: 0}, **row}.items():
        if b >= len(grown):
            grown.extend([0] * (b + 1 - len(grown)))
        grown[b] += c
    assert hist_percentile(grown, 50) == 1151
    assert hist_percentile(grown, 50) != bucket_upper(11)  # 2047
    # Small values index themselves exactly.
    trace.hist("small_us", 7)
    assert trace.hists()["small_us"] == {7: 1}


# ------------------------------------------------------------ attribution


def test_tdr_explain_attributes_stream_requests():
    """Request-tagged stream collectives decompose per request id in
    tdr_explain: the serving section counts transfers/bytes per
    request, and request 0 (shared weight pages) stays separate."""
    from rocnrdma_tpu.telemetry.recorder import TelEvent, events_to_wire
    from tdr_explain import analyze_segments, render_text

    MS = 1_000_000

    def seg(rank, colls):
        evs = []
        for i, coll in enumerate(colls):
            t = (10 * i + rank)
            evs += [
                TelEvent(ts_ns=t * MS, name="ring_begin", engine=rank + 1,
                         id=i + 1, arg=4096, coll=coll),
                TelEvent(ts_ns=(t + 1) * MS, name="wire_tx",
                         engine=rank + 1, qp=1, id=i + 1, arg=4096,
                         coll=coll),
                TelEvent(ts_ns=(t + 5) * MS, name="ring_end",
                         engine=rank + 1, id=i + 1, arg=0, coll=coll),
            ]
        return {"events": events_to_wire(evs), "clock_offset_ns": 0,
                "dropped": 0}

    colls = [make_stream_coll(0, 1), make_stream_coll(7, 1),
             make_stream_coll(7, 2), 5]
    a = analyze_segments({"0": seg(0, colls), "1": seg(1, colls)})
    serving = a["serving"]
    assert serving["7"]["transfers"] == 2
    assert serving["7"]["tx_bytes"] == 2 * 2 * 4096  # both ranks tx'd
    assert serving["0"]["transfers"] == 1
    assert "5" not in serving  # plain collective, not a stream
    for c in a["collectives"]:
        if is_stream_coll(c["coll"]):
            assert c["request"] == stream_coll_request(c["coll"])
            assert c["stream_seq"] == stream_coll_seq(c["coll"])
        else:
            assert "request" not in c
    text = render_text(a)
    assert "serving streams" in text
    assert "req 7" in text
