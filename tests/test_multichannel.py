"""Multi-channel ring tests (TDR_RING_CHANNELS, TDR_PROGRESS_SHARDS).

The striped schedules route chunk i over channel i % channels, so the
wire transfer, seal verification, and fold of consecutive chunks run
on independent QPs/progress engines; the SHARDED progress engine
(TDR_PROGRESS_SHARDS) moves completion polling onto dedicated shard
threads so no channel's progress waits behind a blocking poll owed to
another. These tests pin the properties that make that safe: bitwise
parity with the single-QP schedule at every channel count AND every
shard count (0 = the legacy single-poll loop), channel-local seal
NAK/retransmit under deterministic corruption — sharded included —
survival of a mid-soak connection drop via rebuild with no leaked
shard threads, the flight-recorder proof that offloaded folds overlap
wire activity, and the schedule digest growing the channel count —
with channels=1 reproducing the legacy single-QP digest byte-for-byte
(progress sharding never touches the digest: it is per-process
execution strategy).
"""

import os
import threading

import numpy as np
import pytest

from rocnrdma_tpu.collectives.world import RingWorld, local_worlds
from rocnrdma_tpu.transport.engine import (TransportError,
                                           fault_plan_reset,
                                           native_counters,
                                           seal_counters,
                                           seal_counters_reset)

from test_transport import free_port


def _task_count() -> int:
    """Native thread count of this process (shard-leak detector)."""
    return len(os.listdir("/proc/self/task"))


def _allreduce_all(worlds, bufs):
    errs = [None] * len(worlds)

    def run(r):
        try:
            worlds[r].allreduce(bufs[r])
        except TransportError as e:
            errs[r] = e

    ts = [threading.Thread(target=run, args=(r,))
          for r in range(len(worlds))]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    return errs


def _inputs(world, count):
    # % 977 keeps every value and every partial sum exactly
    # representable in f32, so "bitwise" parity is about the transport,
    # not about summation-order rounding.
    return [(np.arange(count, dtype=np.float32) % 977) * (r + 1)
            for r in range(world)]


def test_channels_default_and_property(monkeypatch):
    monkeypatch.setenv("TDR_RING_CHANNELS", "2")
    worlds = local_worlds(2, free_port())
    try:
        for w in worlds:
            assert w.channels == 2
            assert w.ring.channels == 2
            assert len(w.left_qps) == 2 and len(w.right_qps) == 2
            assert w.left_qp is w.left_qps[0]
    finally:
        for w in worlds:
            w.close()


def test_channels_auto_applies_host_cap(monkeypatch):
    """channels="auto" resolves via the cores-vs-local-ranks heuristic
    instead of blindly taking TDR_RING_CHANNELS, and the world still
    allreduces correctly at the resolved count. A bogus string raises
    up front."""
    from rocnrdma_tpu.collectives.world import auto_channel_cap

    monkeypatch.setenv("TDR_RING_CHANNELS", "8")
    expected = auto_channel_cap(["127.0.0.1"] * 2, 0)
    assert 1 <= expected <= 8
    worlds = local_worlds(2, free_port(), channels="auto")
    try:
        for w in worlds:
            assert w.channels == expected
            assert w.ring.channels == expected
        bufs = _inputs(2, 4096)
        assert all(e is None for e in _allreduce_all(worlds, bufs))
        expect = sum(_inputs(2, 4096), np.zeros(4096, dtype=np.float32))
        for b in bufs:
            assert b.tobytes() == expect.tobytes()
    finally:
        for w in worlds:
            w.close()
    with pytest.raises(ValueError):
        RingWorld(worlds[0].engine, 0, 2, free_port(), channels="fastest")


@pytest.mark.parametrize("world", [2, 4])
def test_parity_bitwise_vs_single_channel(world, monkeypatch):
    """channels in {1, 2, 4} produce byte-identical allreduce results
    on the same inputs — channels=1 being the pre-multichannel
    single-QP path (tdr_ring_create's exact schedule)."""
    count = (2 << 20) // 4
    monkeypatch.setenv("TDR_RING_CHUNK", str(128 << 10))  # many chunks
    results = {}
    for ch in (1, 2, 4):
        monkeypatch.setenv("TDR_RING_CHANNELS", str(ch))
        worlds = local_worlds(world, free_port())
        bufs = _inputs(world, count)
        try:
            errs = _allreduce_all(worlds, bufs)
            assert all(e is None for e in errs), errs
            results[ch] = [b.tobytes() for b in bufs]
        finally:
            for w in worlds:
                w.close()
    for ch in (2, 4):
        assert results[ch] == results[1], f"channels={ch} diverged"


def test_corrupt_rider_stays_channel_local(monkeypatch):
    """A deterministic send-site corruption on chunk 0 under full CMA
    sealing NAKs and retransmits on chunk 0's channel ONLY (per-QP
    seal state — the flight recorder's NAK/RETX events all carry one
    qp track id), and the result still heals bitwise."""
    from rocnrdma_tpu import telemetry

    monkeypatch.setenv("TDR_RING_CHANNELS", "4")
    monkeypatch.setenv("TDR_RING_CHUNK", str(64 << 10))
    monkeypatch.setenv("TDR_SEAL_CMA", "1")  # payload CRC on CMA
    count = (1 << 20) // 4
    # Clean reference first (same env, no fault).
    worlds = local_worlds(2, free_port())
    clean = _inputs(2, count)
    try:
        assert all(e is None for e in _allreduce_all(worlds, clean))
    finally:
        for w in worlds:
            w.close()

    monkeypatch.setenv("TDR_FAULT_PLAN", "send:chunk=0:nth=1:corrupt=3")
    fault_plan_reset()
    seal_counters_reset()
    telemetry.enable()
    try:
        worlds = local_worlds(2, free_port())
        faulty = _inputs(2, count)
        try:
            assert all(e is None for e in _allreduce_all(worlds, faulty))
        finally:
            for w in worlds:
                w.close()
        for c, f in zip(clean, faulty):
            assert c.tobytes() == f.tobytes()
        c = seal_counters()
        assert c["failed"] >= 1 and c["retransmitted"] >= 1, c
        events = telemetry.drain()
        naks = {e.qp for e in events if e.name == "nak"}
        retx = {e.qp for e in events if e.name == "retx"}
        assert retx, "no retransmission recorded"
        # chunk 0 lives on channel 0 of one QP pair: every NAK came
        # from one receiver QP, every retransmit from one sender QP.
        assert len(naks) == 1 and len(retx) == 1, (naks, retx)
    finally:
        telemetry.disable()
        monkeypatch.delenv("TDR_FAULT_PLAN", raising=False)
        fault_plan_reset()
        seal_counters_reset()


def test_drop_rider_mid_soak_rebuilds(monkeypatch):
    """A connection drop mid-soak on a multi-channel ring surfaces a
    retryable error (one dead channel flushes the collective, never
    wedges it); rebuild() brings all channels back and the next
    allreduce is bitwise correct under the bumped generation."""
    monkeypatch.setenv("TDR_RING_CHANNELS", "4")
    monkeypatch.setenv("TDR_RING_TIMEOUT_MS", "30000")
    count = (256 << 10) // 4
    worlds = local_worlds(2, free_port())
    try:
        good = _inputs(2, count)
        assert all(e is None for e in _allreduce_all(worlds, good))

        monkeypatch.setenv("TDR_FAULT_PLAN", "conn:drop_after=3")
        fault_plan_reset()
        errs = []
        for _ in range(8):  # soak until the drop clause fires
            bufs = _inputs(2, count)
            errs = _allreduce_all(worlds, bufs)
            if any(e is not None for e in errs):
                break
        assert any(e is not None for e in errs), \
            "drop rider never surfaced"
        assert all(e is None or e.retryable for e in errs), errs

        monkeypatch.delenv("TDR_FAULT_PLAN")
        fault_plan_reset()
        ts = [threading.Thread(
            target=lambda r=r: worlds[r].rebuild(
                max_attempts=8, backoff_s=0.05, timeout_ms=10000))
            for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert [w.generation for w in worlds] == [1, 1]
        assert all(len(w.left_qps) == 4 for w in worlds)
        bufs = _inputs(2, count)
        expect = sum(_inputs(2, count),
                     np.zeros(count, dtype=np.float32))
        assert all(e is None for e in _allreduce_all(worlds, bufs))
        for b in bufs:
            assert b.tobytes() == expect.tobytes()
    finally:
        monkeypatch.delenv("TDR_FAULT_PLAN", raising=False)
        fault_plan_reset()
        for w in worlds:
            w.close()


def test_channels_one_reproduces_legacy_digest(monkeypatch):
    """The schedule digest grows the channel count ONLY when it
    differs from 1: a channels=1 ring emits the legacy single-QP
    digest string byte-for-byte (no ``chan=`` term), and channels=4
    emits a different digest carrying ``chan=4`` — mismatched worlds
    fail fast instead of striping against each other."""
    from rocnrdma_tpu.collectives.jax_shim import CrossSliceAllReduce

    captured = {}
    orig = RingWorld.check_schedule

    def spy(self, digest, describe=""):
        captured.setdefault(self.channels, {})[self.rank] = (digest,
                                                             describe)
        return orig(self, digest, describe)

    monkeypatch.setattr(RingWorld, "check_schedule", spy)

    for ch in (1, 4):
        monkeypatch.setenv("TDR_RING_CHANNELS", str(ch))
        worlds = local_worlds(2, free_port())
        shims = [CrossSliceAllReduce(w) for w in worlds]
        trees = [[np.ones(256, dtype=np.float32)] for _ in range(2)]

        def run(r):
            shims[r](trees[r])

        ts = [threading.Thread(target=run, args=(r,)) for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        for s in shims:
            s.close()
        for w in worlds:
            w.close()

    one = captured[1][0]
    four = captured[4][0]
    assert "chan=" not in one[1], one[1]  # the legacy digest string
    assert "chan=4" in four[1], four[1]
    assert one[0] != four[0]
    # Both ranks of each world agreed (the sync would have failed
    # otherwise — this just pins that the digest is rank-invariant).
    assert captured[1][0][0] == captured[1][1][0]
    assert captured[4][0][0] == captured[4][1][0]


def test_windowed_fold_offload_parity(monkeypatch):
    """The windowed-scratch schedule (TDR_NO_RECV_REDUCE — engines
    without reduce-on-receive) with folds offloaded to the fold pool
    is bitwise identical to the inline-fold path (TDR_FOLD_THREADS=0),
    across channel counts; the offload demonstrably ran."""
    from rocnrdma_tpu.transport.engine import native_counters

    monkeypatch.setenv("TDR_NO_RECV_REDUCE", "1")
    monkeypatch.setenv("TDR_RING_CHUNK", str(64 << 10))
    count = (1 << 20) // 4
    results = {}
    for label, fold_env in (("offload", None), ("inline", "0")):
        if fold_env is None:
            monkeypatch.delenv("TDR_FOLD_THREADS", raising=False)
        else:
            monkeypatch.setenv("TDR_FOLD_THREADS", fold_env)
        for ch in (1, 2):
            monkeypatch.setenv("TDR_RING_CHANNELS", str(ch))
            before = native_counters()["fold.jobs"]
            worlds = local_worlds(3, free_port())
            bufs = _inputs(3, count)
            try:
                assert all(e is None
                           for e in _allreduce_all(worlds, bufs))
                assert worlds[0].ring.last_schedule == 1  # generic
                results[(label, ch)] = [b.tobytes() for b in bufs]
            finally:
                for w in worlds:
                    w.close()
            if label == "offload":
                # The pool was already sized at first use; if it has
                # workers, the windowed folds must have gone through
                # it (fold.jobs is process-wide and monotonic).
                from rocnrdma_tpu.transport.engine import \
                    fold_pool_workers
                if fold_pool_workers() > 0:
                    assert native_counters()["fold.jobs"] > before
    baseline = results[("inline", 1)]
    for key, val in results.items():
        assert val == baseline, f"{key} diverged from inline/1-channel"


@pytest.mark.parametrize("world", [2, 4])
def test_progress_shards_bitwise_parity(world, monkeypatch):
    """TDR_PROGRESS_SHARDS in {0, 1, 2, channels} produces
    byte-identical allreduce results on the same inputs at channels=4
    — 0 being the legacy single-poll loop and 1 the single-shard
    engine, whose results must be indistinguishable from it (the
    acceptance pin: shards are execution strategy, never schedule).
    The progress.wc counter proves which engine actually ran."""
    count = (1 << 20) // 4
    monkeypatch.setenv("TDR_RING_CHANNELS", "4")
    monkeypatch.setenv("TDR_RING_CHUNK", str(64 << 10))  # many chunks
    results = {}
    for shards in (0, 1, 2, 4):
        monkeypatch.setenv("TDR_PROGRESS_SHARDS", str(shards))
        before = native_counters()["progress.wc"]
        worlds = local_worlds(world, free_port())
        bufs = _inputs(world, count)
        try:
            errs = _allreduce_all(worlds, bufs)
            assert all(e is None for e in errs), errs
            results[shards] = [b.tobytes() for b in bufs]
        finally:
            for w in worlds:
                w.close()
        consumed = native_counters()["progress.wc"] - before
        if shards == 0:
            assert consumed == 0, \
                "legacy mode must not consume completions on shards"
        else:
            assert consumed > 0, \
                f"shards={shards} never consumed a completion"
    for shards in (1, 2, 4):
        assert results[shards] == results[0], \
            f"shards={shards} diverged from the legacy single-poll loop"


def test_corrupt_rider_channel_local_under_shards(monkeypatch):
    """The corrupt-rider contract holds under SHARDED progress: a
    deterministic send-site corruption on chunk 0 with full CMA
    sealing NAKs/retransmits on chunk 0's channel ONLY (per-QP seal
    state survives the move of polling onto shard threads) and the
    result heals bitwise."""
    from rocnrdma_tpu import telemetry

    monkeypatch.setenv("TDR_RING_CHANNELS", "4")
    monkeypatch.setenv("TDR_PROGRESS_SHARDS", "2")
    monkeypatch.setenv("TDR_RING_CHUNK", str(64 << 10))
    monkeypatch.setenv("TDR_SEAL_CMA", "1")  # payload CRC on CMA
    count = (1 << 20) // 4
    worlds = local_worlds(2, free_port())
    clean = _inputs(2, count)
    try:
        assert all(e is None for e in _allreduce_all(worlds, clean))
    finally:
        for w in worlds:
            w.close()

    monkeypatch.setenv("TDR_FAULT_PLAN", "send:chunk=0:nth=1:corrupt=3")
    fault_plan_reset()
    seal_counters_reset()
    telemetry.enable()
    try:
        before_wc = native_counters()["progress.wc"]
        worlds = local_worlds(2, free_port())
        faulty = _inputs(2, count)
        try:
            assert all(e is None for e in _allreduce_all(worlds, faulty))
        finally:
            for w in worlds:
                w.close()
        for c, f in zip(clean, faulty):
            assert c.tobytes() == f.tobytes()
        assert native_counters()["progress.wc"] > before_wc, \
            "sharded progress engine never engaged"
        c = seal_counters()
        assert c["failed"] >= 1 and c["retransmitted"] >= 1, c
        events = telemetry.drain()
        naks = {e.qp for e in events if e.name == "nak"}
        retx = {e.qp for e in events if e.name == "retx"}
        assert retx, "no retransmission recorded"
        assert len(naks) == 1 and len(retx) == 1, (naks, retx)
    finally:
        telemetry.disable()
        monkeypatch.delenv("TDR_FAULT_PLAN", raising=False)
        fault_plan_reset()
        seal_counters_reset()


def test_shard_threads_join_across_drop_and_rebuild(monkeypatch):
    """A conn-drop mid-soak under SHARDED progress surfaces retryable
    and rebuild() restarts cleanly — and the shard threads are
    per-collective (spawn/join inside the call), so the process's
    native thread count is flat across the whole soak+rebuild cycle:
    no leaked shard thread survives an errored collective or a
    rebuild."""
    monkeypatch.setenv("TDR_RING_CHANNELS", "4")
    monkeypatch.setenv("TDR_PROGRESS_SHARDS", "2")
    monkeypatch.setenv("TDR_RING_CHUNK", str(32 << 10))
    monkeypatch.setenv("TDR_RING_TIMEOUT_MS", "30000")
    count = (256 << 10) // 4
    worlds = local_worlds(2, free_port())
    try:
        good = _inputs(2, count)
        assert all(e is None for e in _allreduce_all(worlds, good))
        # Steady-state thread census AFTER the first collective: the
        # engine progress threads and any lazily-built pools exist by
        # now; only leaked shard threads could grow it from here.
        tasks0 = _task_count()

        monkeypatch.setenv("TDR_FAULT_PLAN", "conn:drop_after=3")
        fault_plan_reset()
        errs = []
        for _ in range(8):  # soak until the drop clause fires
            bufs = _inputs(2, count)
            errs = _allreduce_all(worlds, bufs)
            if any(e is not None for e in errs):
                break
        assert any(e is not None for e in errs), \
            "drop rider never surfaced"
        # EVERY failing rank classifies the drop as retryable. The
        # other rank racing the first rank's teardown used to observe
        # LOC_ACCESS_ERR here (per-call-registered buffers: the
        # failing rank's exit deregistered its data MR while peer
        # frames were still in flight on the surviving channels) —
        # the native layer now defers the per-call MR teardown until
        # the owed in-flight landings drain (quiesce_before_dereg),
        # so the transient drop surfaces as transient on BOTH sides.
        assert any(e is not None and e.retryable for e in errs), errs
        assert all(e is None or e.retryable for e in errs), errs

        monkeypatch.delenv("TDR_FAULT_PLAN")
        fault_plan_reset()
        ts = [threading.Thread(
            target=lambda r=r: worlds[r].rebuild(
                max_attempts=8, backoff_s=0.05, timeout_ms=10000))
            for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert [w.generation for w in worlds] == [1, 1]
        assert all(len(w.left_qps) == 4 for w in worlds)
        for _ in range(3):
            bufs = _inputs(2, count)
            expect = sum(_inputs(2, count),
                         np.zeros(count, dtype=np.float32))
            assert all(e is None for e in _allreduce_all(worlds, bufs))
            for b in bufs:
                assert b.tobytes() == expect.tobytes()
        # Rebuild replaced the per-QP progress threads 1:1 and every
        # shard thread joined at its collective's exit — the census
        # must settle back to the baseline (transient entries for
        # just-exited python/helper threads are given time to reap; a
        # LEAKED shard thread never exits, so it would hold the count
        # up past the deadline).
        import time as _time

        deadline = _time.time() + 5
        while _task_count() > tasks0 and _time.time() < deadline:
            _time.sleep(0.05)
        assert _task_count() <= tasks0, \
            (f"native threads grew {tasks0} -> {_task_count()} across "
             "drop+rebuild: leaked shard threads")
    finally:
        monkeypatch.delenv("TDR_FAULT_PLAN", raising=False)
        fault_plan_reset()
        for w in worlds:
            w.close()


_OVERLAP_SCRIPT = """
import socket, threading
import numpy as np
from rocnrdma_tpu import telemetry
from rocnrdma_tpu.collectives.world import local_worlds
from rocnrdma_tpu.transport.engine import TransportError

s = socket.socket(); s.bind(("127.0.0.1", 0))
port = s.getsockname()[1]; s.close()
telemetry.enable()
# Sized so the fold gate ENGAGES: 16 chunks per phase against an
# 8-slot scratch window (4 channels x 2) — posting chunk i+8 requires
# chunk i folded, so wire traffic and folds are forced to interleave
# and the overlap below is a property of the machinery, not of lucky
# thread timing.
count = (32 << 20) // 4
worlds = local_worlds(2, port)
bufs = [(np.arange(count, dtype=np.float32) % 977) * (r + 1)
        for r in range(2)]
expect = (np.arange(count, dtype=np.float32) % 977) * 3
overlapped = 0
spans_total = 0
events = []
for attempt in range(3):
    bufs = [(np.arange(count, dtype=np.float32) % 977) * (r + 1)
            for r in range(2)]
    ts = [threading.Thread(target=worlds[r].allreduce, args=(bufs[r],))
          for r in range(2)]
    [t.start() for t in ts]; [t.join() for t in ts]
    for b in bufs:
        assert b.tobytes() == expect.tobytes(), "result diverged"
    assert worlds[0].ring.last_schedule == 1  # generic/windowed
    events = telemetry.drain()
    offs = [e for e in events if e.name == "fold_off"]
    folds = [e for e in events if e.name == "fold"]
    assert offs and folds, "fold offload never engaged"
    # Pair each enqueue with the first later execution of the same
    # chunk id: that interval is the fold span (queue wait + fold).
    spans = []
    for off in offs:
        cands = [f for f in folds
                 if f.id == off.id and f.ts_ns >= off.ts_ns]
        if cands:
            spans.append((off.ts_ns, min(c.ts_ns for c in cands)))
    assert spans, "no fold_off/fold pairs matched"
    wire_ts = [e.ts_ns for e in events
               if e.name in ("wire_tx", "wire_rx")]
    overlapped += sum(1 for (a, b) in spans
                      if any(a <= t <= b for t in wire_ts))
    spans_total += len(spans)
    # Lane split: chunk completions ride QP lanes; fold/fold_off ride
    # helper-thread lanes disjoint from them.
    qp_lanes = {e.qp for e in events
                if e.name in ("post_recv", "wc") and e.qp}
    fold_lanes = {e.qp for e in offs + folds}
    assert fold_lanes and not (fold_lanes & qp_lanes), \
        (qp_lanes, fold_lanes)
    shard_lanes = {e.qp for e in events if e.name == "shard"}
    assert shard_lanes, "no shard-thread lanes recorded"
    if overlapped:
        break
for w in worlds:
    w.close()
assert overlapped > 0, \
    "no fold span overlapped any wire event: folds serialized"
print("OVERLAP_OK spans=%d overlapped=%d" % (spans_total, overlapped))
"""


def test_fold_spans_on_shard_threads_overlap_wire():
    """Flight-recorder proof of the tentpole's overlap claim: with
    sharded progress and fold offload on the striped windowed
    schedule, FOLD_OFF→FOLD spans (enqueue on a shard thread →
    execution on a fold worker) OVERLAP wire_tx/wire_rx events of the
    same collective — folds run while the wire moves, instead of the
    poll loop serializing them (BENCH_r06's occupancy-0.0 defect).
    Also pins the lane split: fold events ride helper-thread tracks,
    never the QP lanes the chunks complete on. Runs in a SUBPROCESS:
    the fold pool is a process-wide singleton sized at first use, so
    the forced TDR_FOLD_THREADS can only take effect in a fresh
    process."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.update({
        "TDR_RING_CHANNELS": "4",
        "TDR_PROGRESS_SHARDS": "2",
        "TDR_FOLD_THREADS": "2",
        "TDR_NO_RECV_REDUCE": "1",  # windowed → fold pool
        "TDR_RING_CHUNK": str(1 << 20),
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": repo + os.pathsep + env.get("PYTHONPATH", ""),
    })
    env.pop("TDR_TELEMETRY", None)  # script enables it itself
    run = subprocess.run([sys.executable, "-c", _OVERLAP_SCRIPT],
                         capture_output=True, text=True, timeout=120,
                         env=env)
    out = run.stdout + run.stderr
    assert run.returncode == 0, out[-3000:]
    assert "OVERLAP_OK" in out, out[-3000:]
