"""Multi-channel ring tests (TDR_RING_CHANNELS).

The striped schedules route chunk i over channel i % channels, so the
wire transfer, seal verification, and fold of consecutive chunks run
on independent QPs/progress engines. These tests pin the properties
that make that safe: bitwise parity with the single-QP schedule at
every channel count, channel-local seal NAK/retransmit under
deterministic corruption, survival of a mid-soak connection drop via
rebuild, and the schedule digest growing the channel count — with
channels=1 reproducing the legacy single-QP digest byte-for-byte.
"""

import threading

import numpy as np
import pytest

from rocnrdma_tpu.collectives.world import RingWorld, local_worlds
from rocnrdma_tpu.transport.engine import (TransportError,
                                           fault_plan_reset,
                                           seal_counters,
                                           seal_counters_reset)

from test_transport import free_port


def _allreduce_all(worlds, bufs):
    errs = [None] * len(worlds)

    def run(r):
        try:
            worlds[r].allreduce(bufs[r])
        except TransportError as e:
            errs[r] = e

    ts = [threading.Thread(target=run, args=(r,))
          for r in range(len(worlds))]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    return errs


def _inputs(world, count):
    # % 977 keeps every value and every partial sum exactly
    # representable in f32, so "bitwise" parity is about the transport,
    # not about summation-order rounding.
    return [(np.arange(count, dtype=np.float32) % 977) * (r + 1)
            for r in range(world)]


def test_channels_default_and_property(monkeypatch):
    monkeypatch.setenv("TDR_RING_CHANNELS", "2")
    worlds = local_worlds(2, free_port())
    try:
        for w in worlds:
            assert w.channels == 2
            assert w.ring.channels == 2
            assert len(w.left_qps) == 2 and len(w.right_qps) == 2
            assert w.left_qp is w.left_qps[0]
    finally:
        for w in worlds:
            w.close()


@pytest.mark.parametrize("world", [2, 4])
def test_parity_bitwise_vs_single_channel(world, monkeypatch):
    """channels in {1, 2, 4} produce byte-identical allreduce results
    on the same inputs — channels=1 being the pre-multichannel
    single-QP path (tdr_ring_create's exact schedule)."""
    count = (2 << 20) // 4
    monkeypatch.setenv("TDR_RING_CHUNK", str(128 << 10))  # many chunks
    results = {}
    for ch in (1, 2, 4):
        monkeypatch.setenv("TDR_RING_CHANNELS", str(ch))
        worlds = local_worlds(world, free_port())
        bufs = _inputs(world, count)
        try:
            errs = _allreduce_all(worlds, bufs)
            assert all(e is None for e in errs), errs
            results[ch] = [b.tobytes() for b in bufs]
        finally:
            for w in worlds:
                w.close()
    for ch in (2, 4):
        assert results[ch] == results[1], f"channels={ch} diverged"


def test_corrupt_rider_stays_channel_local(monkeypatch):
    """A deterministic send-site corruption on chunk 0 under full CMA
    sealing NAKs and retransmits on chunk 0's channel ONLY (per-QP
    seal state — the flight recorder's NAK/RETX events all carry one
    qp track id), and the result still heals bitwise."""
    from rocnrdma_tpu import telemetry

    monkeypatch.setenv("TDR_RING_CHANNELS", "4")
    monkeypatch.setenv("TDR_RING_CHUNK", str(64 << 10))
    monkeypatch.setenv("TDR_SEAL_CMA", "1")  # payload CRC on CMA
    count = (1 << 20) // 4
    # Clean reference first (same env, no fault).
    worlds = local_worlds(2, free_port())
    clean = _inputs(2, count)
    try:
        assert all(e is None for e in _allreduce_all(worlds, clean))
    finally:
        for w in worlds:
            w.close()

    monkeypatch.setenv("TDR_FAULT_PLAN", "send:chunk=0:nth=1:corrupt=3")
    fault_plan_reset()
    seal_counters_reset()
    telemetry.enable()
    try:
        worlds = local_worlds(2, free_port())
        faulty = _inputs(2, count)
        try:
            assert all(e is None for e in _allreduce_all(worlds, faulty))
        finally:
            for w in worlds:
                w.close()
        for c, f in zip(clean, faulty):
            assert c.tobytes() == f.tobytes()
        c = seal_counters()
        assert c["failed"] >= 1 and c["retransmitted"] >= 1, c
        events = telemetry.drain()
        naks = {e.qp for e in events if e.name == "nak"}
        retx = {e.qp for e in events if e.name == "retx"}
        assert retx, "no retransmission recorded"
        # chunk 0 lives on channel 0 of one QP pair: every NAK came
        # from one receiver QP, every retransmit from one sender QP.
        assert len(naks) == 1 and len(retx) == 1, (naks, retx)
    finally:
        telemetry.disable()
        monkeypatch.delenv("TDR_FAULT_PLAN", raising=False)
        fault_plan_reset()
        seal_counters_reset()


def test_drop_rider_mid_soak_rebuilds(monkeypatch):
    """A connection drop mid-soak on a multi-channel ring surfaces a
    retryable error (one dead channel flushes the collective, never
    wedges it); rebuild() brings all channels back and the next
    allreduce is bitwise correct under the bumped generation."""
    monkeypatch.setenv("TDR_RING_CHANNELS", "4")
    monkeypatch.setenv("TDR_RING_TIMEOUT_MS", "30000")
    count = (256 << 10) // 4
    worlds = local_worlds(2, free_port())
    try:
        good = _inputs(2, count)
        assert all(e is None for e in _allreduce_all(worlds, good))

        monkeypatch.setenv("TDR_FAULT_PLAN", "conn:drop_after=3")
        fault_plan_reset()
        errs = []
        for _ in range(8):  # soak until the drop clause fires
            bufs = _inputs(2, count)
            errs = _allreduce_all(worlds, bufs)
            if any(e is not None for e in errs):
                break
        assert any(e is not None for e in errs), \
            "drop rider never surfaced"
        assert all(e is None or e.retryable for e in errs), errs

        monkeypatch.delenv("TDR_FAULT_PLAN")
        fault_plan_reset()
        ts = [threading.Thread(
            target=lambda r=r: worlds[r].rebuild(
                max_attempts=8, backoff_s=0.05, timeout_ms=10000))
            for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert [w.generation for w in worlds] == [1, 1]
        assert all(len(w.left_qps) == 4 for w in worlds)
        bufs = _inputs(2, count)
        expect = sum(_inputs(2, count),
                     np.zeros(count, dtype=np.float32))
        assert all(e is None for e in _allreduce_all(worlds, bufs))
        for b in bufs:
            assert b.tobytes() == expect.tobytes()
    finally:
        monkeypatch.delenv("TDR_FAULT_PLAN", raising=False)
        fault_plan_reset()
        for w in worlds:
            w.close()


def test_channels_one_reproduces_legacy_digest(monkeypatch):
    """The schedule digest grows the channel count ONLY when it
    differs from 1: a channels=1 ring emits the legacy single-QP
    digest string byte-for-byte (no ``chan=`` term), and channels=4
    emits a different digest carrying ``chan=4`` — mismatched worlds
    fail fast instead of striping against each other."""
    from rocnrdma_tpu.collectives.jax_shim import CrossSliceAllReduce

    captured = {}
    orig = RingWorld.check_schedule

    def spy(self, digest, describe=""):
        captured.setdefault(self.channels, {})[self.rank] = (digest,
                                                             describe)
        return orig(self, digest, describe)

    monkeypatch.setattr(RingWorld, "check_schedule", spy)

    for ch in (1, 4):
        monkeypatch.setenv("TDR_RING_CHANNELS", str(ch))
        worlds = local_worlds(2, free_port())
        shims = [CrossSliceAllReduce(w) for w in worlds]
        trees = [[np.ones(256, dtype=np.float32)] for _ in range(2)]

        def run(r):
            shims[r](trees[r])

        ts = [threading.Thread(target=run, args=(r,)) for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        for s in shims:
            s.close()
        for w in worlds:
            w.close()

    one = captured[1][0]
    four = captured[4][0]
    assert "chan=" not in one[1], one[1]  # the legacy digest string
    assert "chan=4" in four[1], four[1]
    assert one[0] != four[0]
    # Both ranks of each world agreed (the sync would have failed
    # otherwise — this just pins that the digest is rank-invariant).
    assert captured[1][0][0] == captured[1][1][0]
    assert captured[4][0][0] == captured[4][1][0]


def test_windowed_fold_offload_parity(monkeypatch):
    """The windowed-scratch schedule (TDR_NO_RECV_REDUCE — engines
    without reduce-on-receive) with folds offloaded to the fold pool
    is bitwise identical to the inline-fold path (TDR_FOLD_THREADS=0),
    across channel counts; the offload demonstrably ran."""
    from rocnrdma_tpu.transport.engine import native_counters

    monkeypatch.setenv("TDR_NO_RECV_REDUCE", "1")
    monkeypatch.setenv("TDR_RING_CHUNK", str(64 << 10))
    count = (1 << 20) // 4
    results = {}
    for label, fold_env in (("offload", None), ("inline", "0")):
        if fold_env is None:
            monkeypatch.delenv("TDR_FOLD_THREADS", raising=False)
        else:
            monkeypatch.setenv("TDR_FOLD_THREADS", fold_env)
        for ch in (1, 2):
            monkeypatch.setenv("TDR_RING_CHANNELS", str(ch))
            before = native_counters()["fold.jobs"]
            worlds = local_worlds(3, free_port())
            bufs = _inputs(3, count)
            try:
                assert all(e is None
                           for e in _allreduce_all(worlds, bufs))
                assert worlds[0].ring.last_schedule == 1  # generic
                results[(label, ch)] = [b.tobytes() for b in bufs]
            finally:
                for w in worlds:
                    w.close()
            if label == "offload":
                # The pool was already sized at first use; if it has
                # workers, the windowed folds must have gone through
                # it (fold.jobs is process-wide and monotonic).
                from rocnrdma_tpu.transport.engine import \
                    fold_pool_workers
                if fold_pool_workers() > 0:
                    assert native_counters()["fold.jobs"] > before
    baseline = results[("inline", 1)]
    for key, val in results.items():
        assert val == baseline, f"{key} diverged from inline/1-channel"
