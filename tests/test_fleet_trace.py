"""Fleet-scope tracing tests (ISSUE 13).

Pins the cross-rank observability contracts:
- wire-carried collective ids: posting-side events and the PEER's
  wire_rx/land/verify/wc events carry the SAME ``coll`` (negotiated
  FEAT_COLL_ID; off — and wire-format-neutral — without telemetry);
- a corrupt-rider NAK/retransmit keeps the ORIGINAL coll id on the
  retransmitted frame's events;
- the NTP-style clock-offset estimate is bounded by the measured RTT
  and monotone under the min-RTT filter;
- a TWO-PROCESS world-2 collect_trace merge joins one collective's
  send-side and land-side events across ranks by id;
- postmortem bundles are written per rank on rebuild and merge via
  tdr_explain; /metrics serves the new contract names;
- overlap_fraction refuses to report an untainted number over a
  window that overlapped telemetry drops;
- Perfetto tier-ring lanes label tier=intra|inter.
"""

import json
import os
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from rocnrdma_tpu import telemetry
from rocnrdma_tpu.collectives.world import local_worlds
from rocnrdma_tpu.telemetry.recorder import TelEvent, events_from_wire
from rocnrdma_tpu.transport.engine import (TransportError,
                                           fault_plan_reset,
                                           telemetry_reset)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")
if TOOLS not in sys.path:
    sys.path.insert(0, TOOLS)


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture(autouse=True)
def _trace_env():
    keys = ("TDR_TELEMETRY", "TDR_TELEMETRY_RING", "TDR_FAULT_PLAN",
            "TDR_SEAL_CMA", "TDR_POSTMORTEM_DIR")
    saved = {k: os.environ.get(k) for k in keys}
    yield
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    telemetry_reset()
    fault_plan_reset()


def _run_world2(iters=2, **world_kwargs):
    """World-2 in-process soak; returns the drained merged timeline."""
    worlds = local_worlds(2, free_port(), **world_kwargs)
    try:
        assert worlds[0].left_qp.has_coll_id
        bufs = [np.ones(1 << 12, dtype=np.float32) for _ in range(2)]
        for _ in range(iters):
            ts = [threading.Thread(target=worlds[r].allreduce,
                                   args=(bufs[r],)) for r in range(2)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
        return telemetry.timeline(), worlds[0].engine.telemetry_id, \
            worlds[1].engine.telemetry_id
    finally:
        for w in worlds:
            w.close()


# ------------------------------------------------------ coll-id plumbing

def test_coll_id_joins_ranks_in_one_collective():
    """The posting rank's events and the PEER's landing-side events
    for one collective carry the same wire-carried coll id — the
    first time two ranks' flight recorders are joinable by key."""
    telemetry.enable()
    events, eng0, eng1 = _run_world2()
    native = [e for e in events if e.source == "native" and e.coll]
    assert native, "no coll-tagged events recorded"
    # Pick a collective that engine0's ring ran; its wire_tx events
    # must pair with wire_rx/land/wc events ON THE OTHER ENGINE with
    # the same id (the frame carried it).
    begins = [e for e in native if e.name == "ring_begin"
              and e.engine == eng0]
    assert begins
    coll = begins[0].coll
    assert coll  # world.py stamped it (not the native auto id)
    assert not (coll >> 63), "expected a caller-stamped id"
    peer = [e for e in native if e.coll == coll and e.engine == eng1]
    peer_names = {e.name for e in peer}
    assert {"wire_rx", "wc"} <= peer_names, peer_names
    assert "land" in peer_names or "fold" in peer_names, peer_names
    # Posting side carries it too.
    mine = {e.name for e in native
            if e.coll == coll and e.engine == eng0}
    assert "wire_tx" in mine and "ring_end" in mine


def test_coll_seq_is_per_world_monotonic():
    """Both ranks assign the same per-world monotonic sequence (the
    SPMD order IS the key agreement): collective k on rank 0 and
    collective k on rank 1 share one id."""
    telemetry.enable()
    events, eng0, eng1 = _run_world2(iters=3)
    for eng in (eng0, eng1):
        seq = [e.coll for e in events
               if e.source == "native" and e.name == "ring_begin"
               and e.engine == eng and not (e.coll >> 63)]
        assert seq == sorted(seq)
        assert len(set(seq)) == len(seq)
    c0 = {e.coll for e in events if e.source == "native"
          and e.name == "ring_begin" and e.engine == eng0}
    c1 = {e.coll for e in events if e.source == "native"
          and e.name == "ring_begin" and e.engine == eng1}
    assert c0 == c1  # same collectives, same ids, both rings


def test_no_coll_wire_without_telemetry():
    """Telemetry off => FEAT_COLL_ID is not advertised: the handshake
    resolves to the legacy wire format (frames byte-identical to the
    pre-trace-id framing) and nothing records."""
    os.environ["TDR_TELEMETRY"] = "0"
    telemetry_reset()
    worlds = local_worlds(2, free_port())
    try:
        assert not worlds[0].left_qp.has_coll_id
        assert not worlds[1].right_qp.has_coll_id
        buf = [np.ones(512, dtype=np.float32) for _ in range(2)]
        ts = [threading.Thread(target=worlds[r].allreduce,
                               args=(buf[r],)) for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert (buf[0] == 2).all()
        assert not telemetry.drain(100)
    finally:
        for w in worlds:
            w.close()


def test_corrupt_rider_retx_keeps_coll_id():
    """A corrupt-rider NAK/retransmit cycle keeps the ORIGINAL coll
    id: the verify_fail, nak, retx, and the healed verify_ok all tag
    with the collective the first transmission belonged to."""
    # send-site corruption flips the WIRE copy of one sealed frame
    # mid-collective (nth=7 clears the bootstrap generation-exchange
    # sends, whose frames predate any collective id): the land-side
    # verify fails, NAKs, and the sender retransmits clean.
    os.environ["TDR_SEAL_CMA"] = "1"  # full payload CRC on CMA tier
    os.environ["TDR_FAULT_PLAN"] = "send:nth=7:corrupt=2"
    fault_plan_reset()
    telemetry.enable()
    events, _, _ = _run_world2(iters=3)
    native = [e for e in events if e.source == "native"]
    retx = [e for e in native if e.name == "retx"]
    assert retx, "corrupt rider never armed (no retransmission)"
    for r in retx:
        assert r.coll, "retransmission lost its coll id"
        fails = [e for e in native if e.name == "verify_fail"
                 and e.id == r.id]
        naks = [e for e in native if e.name == "nak" and e.id == r.id]
        assert fails and naks
        assert all(e.coll == r.coll for e in fails + naks)
        heals = [e for e in native if e.name == "verify_ok"
                 and e.id == r.id and e.ts_ns > r.ts_ns]
        assert heals and all(e.coll == r.coll for e in heals)


# ------------------------------------------------------------ clock sync

def test_clock_sync_min_rtt_filter_bounds_and_monotone():
    from rocnrdma_tpu.control.client import ClockSync

    cs = ClockSync()
    # Symmetric exchange, true offset 1000ns, rtt 400ns.
    assert cs.sample(0, 1200, 1300, 500) is True
    assert cs.rtt_ns == 400
    assert abs(cs.offset_ns - 1000) <= cs.rtt_ns // 2
    # Worse RTT: discarded, estimate unchanged (monotone filter).
    assert cs.sample(0, 9000, 9100, 5000) is False
    assert cs.offset_ns == 1000 and cs.rtt_ns == 400
    # Better RTT: adopted; the bound tightens.
    assert cs.sample(0, 1050, 1060, 110) is True
    assert cs.rtt_ns == 100
    assert abs(cs.offset_ns - 1000) <= 50
    # Negative RTT (garbled echo): discarded before it even counts.
    assert cs.sample(0, 500, 5000, 100) is False
    assert cs.samples == 3

    # Property: rtt_ns never increases over an arbitrary stream.
    rng = np.random.default_rng(7)
    cs2 = ClockSync()
    last = None
    for _ in range(200):
        t0 = int(rng.integers(0, 1 << 30))
        d1 = int(rng.integers(1, 10000))
        srv = int(rng.integers(1, 5000))
        d2 = int(rng.integers(1, 10000))
        cs2.sample(t0, t0 + d1, t0 + d1 + srv, t0 + d1 + srv + d2)
        if last is not None:
            assert cs2.rtt_ns <= last
        last = cs2.rtt_ns


def test_clock_offset_live_is_rtt_bounded():
    """A real heartbeat exchange against a live coordinator yields an
    estimate bounded by its measured RTT (same host: the true offset
    is ~0, so |estimate| <= rtt/2 <= rtt)."""
    from rocnrdma_tpu.control.client import ControlClient
    from rocnrdma_tpu.control.coordinator import Coordinator

    coord = Coordinator(port=0, port_base=free_port()).start()
    try:
        worlds = local_worlds(2, None, controller=coord.address,
                              world_name="clock")
        try:
            for w in worlds:
                for _ in range(3):
                    assert w._hb.beat()
                st = w._hb.clock.state()
                assert st["clock_samples"] >= 3
                assert st["clock_rtt_ns"] > 0
                assert abs(st["clock_offset_ns"]) <= st["clock_rtt_ns"]
            # The pushed estimates serve on /metrics under the pinned
            # names.
            m = ControlClient(coord.address).metrics()
            assert 'tdr_clock_offset_us{world="clock",rank="0"}' in m
            assert 'tdr_clock_rtt_us{world="clock",rank="1"}' in m
            assert 'tdr_postmortems_total{world="clock"}' in m
            # telemetry.dropped rides the registry family per rank —
            # the taint signal a scraper watches.
            assert 'tdr_telemetry_dropped_total{world="clock"}' in m
            assert ('tdr_telemetry_dropped_total{world="clock",'
                    'rank="0"}') in m
        finally:
            for w in worlds:
                w.close()
    finally:
        coord.stop()


# ------------------------------------------------- two-process merge

_RANK_SCRIPT = r"""
import sys, time
import numpy as np
from rocnrdma_tpu.collectives.world import RingWorld
from rocnrdma_tpu.transport.engine import Engine

rank, coord = int(sys.argv[1]), sys.argv[2]
eng = Engine("emu")
w = RingWorld(eng, rank, 2, controller=coord, world_name="merge2",
              timeout_ms=20000)
buf = np.zeros(1 << 13, dtype=np.float32)
for i in range(400):
    buf[:] = rank + 1
    w.allreduce(buf)
    assert (buf == 3).all()
    time.sleep(0.02)
w.close(); eng.close()
"""


def test_two_process_collect_trace_joins_by_coll():
    """World-2, one PROCESS per rank (separate native rings — the
    real fleet shape): a mid-soak collect_trace returns both ranks'
    segments, and the same collective's send-side events on rank 0
    join its land-side events on rank 1 by the wire-carried id."""
    from rocnrdma_tpu.control.client import ControlClient
    from rocnrdma_tpu.control.coordinator import Coordinator
    from rocnrdma_tpu.telemetry.perfetto import merge_fleet

    coord = Coordinator(port=0, lease_ms=4000,
                        port_base=free_port()).start()
    env = dict(os.environ, TDR_TELEMETRY="1", JAX_PLATFORMS="cpu")
    procs = [subprocess.Popen(
        [sys.executable, "-c", _RANK_SCRIPT, str(r), coord.address],
        env=env, cwd=REPO) for r in range(2)]
    try:
        time.sleep(4.0)
        resp = ControlClient(coord.address).collect_trace(
            "merge2", timeout_s=30.0)
        assert resp.get("ok"), resp.get("error")
        segments = resp["segments"]
        assert sorted(segments) == ["0", "1"]
    finally:
        rcs = []
        for p in procs:
            try:
                rcs.append(p.wait(timeout=90))
            except subprocess.TimeoutExpired:
                p.kill()
                rcs.append(-9)
        coord.stop()
    assert rcs == [0, 0]

    per_rank = {int(r): events_from_wire(s["events"])
                for r, s in segments.items()}
    send0 = {e.coll for e in per_rank[0]
             if e.source == "native" and e.coll
             and e.name in ("post_send", "wire_tx")}
    land1 = {e.coll for e in per_rank[1]
             if e.source == "native" and e.coll
             and e.name in ("wire_rx", "land", "wc")}
    joined = send0 & land1
    assert len(joined) >= 3, (len(send0), len(land1))
    # Clock estimates rode the segments.
    for s in segments.values():
        assert int(s.get("clock_rtt_ns", 0)) > 0
    # And the merge is a valid fleet-shaped Perfetto doc.
    doc = json.loads(json.dumps(merge_fleet(segments)))
    pids = {e["pid"] // 1000 for e in doc["traceEvents"]}
    assert {1, 2} <= pids
    names = {e["name"] for e in doc["traceEvents"]}
    assert "ring_begin" in names and "wire_rx" in names

    # tdr_explain consumes the same segments.
    from tdr_explain import analyze_segments

    analysis = analyze_segments(segments)
    assert analysis["joinable_collectives"] >= 3
    assert analysis["straggler"]["rank"] in (0, 1)
    assert not analysis["tainted_ranks"]


# ------------------------------------------------------- postmortems

def test_postmortem_bundles_write_and_merge(tmp_path):
    """A TransportError→rebuild dumps one bundle per rank keyed by
    (world, generation); tdr_explain --postmortem merges them."""
    os.environ["TDR_POSTMORTEM_DIR"] = str(tmp_path)
    telemetry.enable()
    worlds = local_worlds(2, free_port(), world_name="pmworld")
    try:
        bufs = [np.ones(1 << 12, dtype=np.float32) for _ in range(2)]
        ts = [threading.Thread(target=worlds[r].allreduce,
                               args=(bufs[r],)) for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        # Kill rank 1's transport: its next collective is retryable,
        # and BOTH ranks walk the rebuild ladder.
        worlds[1]._teardown()
        with pytest.raises(TransportError) as ei:
            worlds[1].allreduce(bufs[1])
        assert ei.value.retryable
        errs = [None, None]

        def rb(r):
            try:
                worlds[r].rebuild(reason="test incident")
            except BaseException as e:  # pragma: no cover
                errs[r] = e

        ts = [threading.Thread(target=rb, args=(r,)) for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert errs == [None, None]
        assert worlds[0]._postmortems == 1
    finally:
        for w in worlds:
            w.close()

    inc_dir = tmp_path / "pmworld" / "incident-g0"
    bundles = sorted(p.name for p in inc_dir.iterdir())
    assert bundles == ["rank0.json", "rank1.json"]
    b0 = json.loads((inc_dir / "rank0.json").read_text())
    assert b0["format"] == "tdr-postmortem-v1"
    assert b0["world"] == "pmworld" and b0["rank"] == 0
    assert b0["generation"] == 0
    assert b0["error"] == "test incident"
    assert "integrity.sealed" in b0["counters"]
    assert isinstance(b0["events"], list) and b0["events"]

    from tdr_explain import explain_postmortem

    merged = explain_postmortem(str(inc_dir))
    inc = merged["incident"]
    assert inc["world"] == "pmworld"
    assert sorted(inc["ranks"]) == ["0", "1"]
    assert inc["ranks"]["1"]["error"] == "test incident"


def test_postmortem_noop_without_dir(tmp_path):
    """No TDR_POSTMORTEM_DIR: rebuild writes nothing and counts
    nothing (the knob gates the whole feature)."""
    os.environ.pop("TDR_POSTMORTEM_DIR", None)
    worlds = local_worlds(2, free_port())
    try:
        for w in worlds:
            w._write_postmortem("x")
            assert w._postmortems == 0
    finally:
        for w in worlds:
            w.close()


# ----------------------------------------------------- taint + lanes

def test_overlap_fraction_taints_on_drops():
    from rocnrdma_tpu.telemetry import recorder

    recorder._warned_tainted = False
    with pytest.warns(RuntimeWarning, match="dropped 5 events"):
        r = telemetry.overlap_fraction(events=[], dropped=5)
    assert r["tainted"] is True and r["dropped"] == 5
    r = telemetry.overlap_fraction(events=[], dropped=0)
    assert r["tainted"] is False and r["dropped"] == 0


def test_perfetto_tier_lane_labels():
    """Hier tier-ring QP lanes label with tier=intra|inter and the
    tier world's name (satellite: a hier trace must be readable
    without guessing which qpN is the delegate ring)."""
    from rocnrdma_tpu.telemetry.perfetto import export_trace

    events = [
        TelEvent(ts_ns=1000, name="world.up", source="python",
                 fields={"world_name": "w.intra", "rank": 0, "world": 2,
                         "tel_left": [21], "tel_right": [22]}),
        TelEvent(ts_ns=1001, name="world.up", source="python",
                 fields={"world_name": "w.x0", "rank": 0, "world": 2,
                         "tel_left": [31], "tel_right": [32]}),
        TelEvent(ts_ns=2000, name="post_send", engine=1, qp=22, id=1,
                 arg=64, coll=7),
        TelEvent(ts_ns=2100, name="post_send", engine=1, qp=32, id=1,
                 arg=64, coll=7),
    ]
    doc = export_trace(events=events, include_python=True)
    thread_names = {ev["tid"]: ev["args"]["name"]
                    for ev in doc["traceEvents"]
                    if ev.get("ph") == "M"
                    and ev.get("name") == "thread_name"
                    and ev.get("pid") == 1}
    assert "tier=intra" in thread_names[22]
    assert "w.intra" in thread_names[22]
    assert "tier=inter" in thread_names[32]
    assert "w.x0" in thread_names[32]
    # coll rides into the instant's args (the join key in the UI).
    insts = [ev for ev in doc["traceEvents"]
             if ev.get("name") == "post_send"]
    assert all(ev["args"]["coll"] == 7 for ev in insts)


def test_tdr_top_fleet_view_renders_metrics():
    """tdr_top --connect's parser + frame over a synthetic /metrics
    exposition: per-world header, per-rank clock offsets, and the
    taint flag on nonzero drops."""
    import tdr_top

    text = "\n".join([
        "# tdr coordinator metrics v1",
        'tdr_ctl_generation{world="train"} 3',
        'tdr_ctl_epoch{world="train"} 5',
        'tdr_ctl_size{world="train"} 2',
        'tdr_ctl_members{world="train"} 2',
        'tdr_ctl_rebuilds_total{world="train"} 1',
        'tdr_postmortems_total{world="train"} 4',
        'tdr_retransmit_rate{world="train"} 0.0125',
        'tdr_chunk_lat_us{world="train",quantile="0.99"} 1234',
        'tdr_clock_offset_us{world="train",rank="0"} -12.5',
        'tdr_clock_offset_us{world="train",rank="1"} 40',
        'tdr_clock_rtt_us{world="train",rank="0"} 300',
        'tdr_clock_rtt_us{world="train",rank="1"} 500',
        'tdr_telemetry_dropped_total{world="train",rank="1"} 9',
        'tdr_ctl_worlds 1',
        'tdr_ctl_failovers_total 2',
        'tdr_ctl_snapshot_age_s 0.75',
        'tdr_ctl_resizes_total{world="train"} 6',
        'tdr_ctl_qp_share{world="train"} 18',
        'tdr_ctl_qp_reserved{world="train"} 12',
        'tdr_ctl_admission_rejects_total{world="train"} 3',
        'tdr_ctl_hb_throttled_total{world="train"} 7',
    ])
    frame = tdr_top.render_fleet(text)
    assert "fleet: worlds=1 failovers=2 snapshot_age=0.8s" in frame
    assert "world train: gen=3 epoch=5 members=2/2" in frame
    assert "rebuilds=1 resizes=6 postmortems=4" in frame
    assert ("qp_share=18 qp_reserved=12 admission_rejects=3 "
            "hb_throttled=7") in frame
    assert "retransmit_rate=0.0125" in frame and "chunk_p99_us=1234" in frame
    assert "rank 0: clock_offset=-12.5us (rtt 300.0us) dropped=0" in frame
    assert "rank 1: clock_offset=+40.0us" in frame
    assert "dropped=9  TAINTED" in frame


def test_explain_synthetic_straggler_and_phases():
    """analyze_segments on a hand-built two-rank segment pair: the
    late-arriving rank is the straggler, phase decomposition sums to
    the observed span, and the tx->rx lane match yields a link."""
    from rocnrdma_tpu.telemetry.recorder import events_to_wire
    from tdr_explain import analyze_segments

    MS = 1_000_000

    def world_up(rank, left, right):
        return TelEvent(ts_ns=0, name="world.up", source="python",
                        fields={"world_name": "syn", "rank": rank,
                                "world": 2, "tel_left": [left],
                                "tel_right": [right]})

    # rank 0 lanes: left 11 / right 12; rank 1: left 21 / right 22.
    # Connection pairing: r0.right(12) -> r1.left(21).
    r0 = [
        world_up(0, 11, 12),
        TelEvent(ts_ns=1 * MS, name="ring_begin", engine=1, id=1,
                 arg=4096, coll=5),
        TelEvent(ts_ns=2 * MS, name="post_send", engine=1, qp=12,
                 id=1, arg=4096, coll=5),
        TelEvent(ts_ns=3 * MS, name="wire_tx", engine=1, qp=12, id=1,
                 arg=4096, coll=5),
        TelEvent(ts_ns=9 * MS, name="wc", engine=1, qp=12, id=1,
                 arg=0, coll=5),
        TelEvent(ts_ns=10 * MS, name="ring_end", engine=1, id=1,
                 arg=0, coll=5),
    ]
    r1 = [
        world_up(1, 21, 22),
        TelEvent(ts_ns=6 * MS, name="ring_begin", engine=2, id=1,
                 arg=4096, coll=5),
        TelEvent(ts_ns=7 * MS, name="wire_rx", engine=2, qp=21, id=1,
                 arg=4096, coll=5),
        TelEvent(ts_ns=8 * MS, name="land", engine=2, qp=21, id=1,
                 arg=4096, coll=5),
        TelEvent(ts_ns=10 * MS, name="ring_end", engine=2, id=1,
                 arg=0, coll=5),
    ]
    segments = {
        "0": {"events": events_to_wire(r0), "clock_offset_ns": 0,
              "dropped": 0},
        "1": {"events": events_to_wire(r1), "clock_offset_ns": 0,
              "dropped": 7},
    }
    a = analyze_segments(segments)
    assert a["joinable_collectives"] == 1
    assert a["straggler"]["rank"] == 1  # arrived 5ms late
    c = a["collectives"][0]
    assert c["straggler"] == 1
    # Phase decomposition sums to each rank's begin->end span.
    d0 = c["ranks"]["0"]
    assert d0["wall_s"] == pytest.approx(9e-3)
    assert sum(d0["phases_s"].values()) == pytest.approx(9e-3)
    assert d0["phases_s"]["post"] == pytest.approx(1e-3)
    # The link r0->r1 was matched by (lane pair, seq) and carries the
    # 4 KiB frame over tx(3ms)->rx(7ms).
    assert len(a["links"]) == 1
    ln = a["links"][0]
    assert (ln["src"], ln["dst"]) == (0, 1)
    assert ln["bytes"] == 4096
    assert ln["seconds"] == pytest.approx(4e-3)
    # The dropped ring taints rank 1.
    assert a["tainted_ranks"] == {"1": 7}


def test_explain_attributes_straggler_to_degraded_link():
    """The straggler readout must say WHY when the ladder knows: a
    rank straggling behind a peer's degraded delegate link is a link
    problem, not a compute problem. The python tracer's
    health.degrade events replay into ``degraded_links`` (a heal
    retires its degrade), the straggler line carries the
    behind-degraded-link label, and the quarantine lines name
    link/peer/rung/score."""
    from rocnrdma_tpu.telemetry.recorder import events_to_wire
    from tdr_explain import analyze_segments, render_text

    MS = 1_000_000

    def ring(rank, engine, begin_ms, end_ms):
        return [TelEvent(ts_ns=begin_ms * MS, name="ring_begin",
                         engine=engine, id=1, arg=4096, coll=5),
                TelEvent(ts_ns=end_ms * MS, name="ring_end",
                         engine=engine, id=1, arg=0, coll=5)]

    def health_ev(ms, name, link, peer, rung, score):
        return TelEvent(ts_ns=ms * MS, name=name, source="python",
                        fields={"world_name": "syn", "link": link,
                                "peer": peer, "rung": rung,
                                "score": score})

    # Rank 0 reports its delegate link to peer 1 degraded; a second
    # link degrades and HEALS inside the window (must not survive the
    # replay). Rank 1 — the sick link's far end — straggles.
    r0 = ring(0, 1, 1, 10) + [
        health_ev(2, "health.degrade", "inter:r0", 1, "fallback", 0.31),
        health_ev(3, "health.degrade", "inter:r9", 3, "wire_down", 0.7),
        health_ev(4, "health.heal", "inter:r9", 3, "wire_down", 0.92),
    ]
    r1 = ring(1, 2, 6, 10)
    segments = {
        "0": {"events": events_to_wire(r0), "clock_offset_ns": 0,
              "dropped": 0},
        "1": {"events": events_to_wire(r1), "clock_offset_ns": 0,
              "dropped": 0},
    }
    a = analyze_segments(segments)
    assert a["straggler"]["rank"] == 1
    assert a["degraded_links"] == {
        "0": {"inter:r0": {"peer": 1, "rung": "fallback",
                           "score": 0.31}}}
    text = render_text(a)
    assert ("straggler: rank 1" in text and
            "[behind degraded link inter:r0 reported by r0 "
            "(rung fallback)]" in text), text
    assert ("degraded: r0 link inter:r0 -> peer r1 "
            "rung=fallback score=0.31") in text, text
