"""Control plane: coordinator arbitration, leases, budgets, /metrics.

Covers the arbitrated rendezvous path end to end on the emulated
engine — join/rank assignment, idempotent failure reports, lease
expiry, arbitrated RingWorld rebuild with coordinator-owned
generations — plus the two bring-up-time budget ladders (native
engine QP cap, per-world budget), the EADDRINUSE fast-retry,
deterministic rebuild jitter, and the /metrics contract: stable
names, counters monotone across a forced rebuild, registry values
matching ``tdr_counters_read`` snapshots.
"""

import socket
import threading
import time

import numpy as np
import pytest

from rocnrdma_tpu.collectives.world import (RingWorld, local_worlds,
                                            rebuild_jitter_seed)
from rocnrdma_tpu.control.client import ControlClient
from rocnrdma_tpu.control.coordinator import Coordinator
from rocnrdma_tpu.transport.engine import (Engine, TransportError,
                                           loopback_pair, native_counters)
from rocnrdma_tpu.utils.trace import trace


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture()
def coord():
    c = Coordinator(port=0, lease_ms=1500, port_base=_free_port()).start()
    yield c
    c.stop()


def _join_all(client, world, size, **kw):
    out = [None] * size
    errs = [None] * size

    def j(r):
        try:
            out[r] = client.join(world, size, rank=r, **kw)
        except BaseException as e:
            errs[r] = e

    ts = [threading.Thread(target=j, args=(r,)) for r in range(size)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    for e in errs:
        if e is not None:
            raise e
    return out


# ------------------------------------------------------- coordinator


def test_join_assigns_ranks_and_one_view(coord):
    client = ControlClient(coord.address)
    views = _join_all(client, "w", 3)
    assert all(v["ok"] for v in views)
    assert [v["rank"] for v in views] == [0, 1, 2]
    # One release, one view: same generation/epoch/base_port on all.
    assert len({v["generation"] for v in views}) == 1
    assert len({v["epoch"] for v in views}) == 1
    assert len({v["base_port"] for v in views}) == 1
    assert len({v["incarnation"] for v in views}) == 3


def test_worlds_get_disjoint_port_ranges(coord):
    client = ControlClient(coord.address)
    va = _join_all(client, "a", 2)
    vb = _join_all(client, "b", 2)
    assert va[0]["base_port"] != vb[0]["base_port"]
    assert abs(va[0]["base_port"] - vb[0]["base_port"]) >= 2


def test_report_bumps_generation_once_per_incident(coord):
    client = ControlClient(coord.address)
    views = _join_all(client, "w", 2)
    gen = views[0]["generation"]
    # Both ranks report the SAME incident (same believed generation):
    # exactly one bump — the arbitration core. Rebuilds count finished
    # recoveries (barrier re-releases), not reports, so it stays 0
    # until the ranks actually re-rendezvous.
    r0 = client.report("w", 0, views[0]["incarnation"], gen, "boom")
    r1 = client.report("w", 1, views[1]["incarnation"], gen, "boom")
    assert r0["generation"] == gen + 1
    assert r1["generation"] == gen + 1
    assert r1["rebuilds"] == 0


def test_lease_expiry_declares_dead_and_bumps():
    coord = Coordinator(port=0, lease_ms=300,
                        port_base=_free_port()).start()
    try:
        client = ControlClient(coord.address)
        views = _join_all(client, "w", 2)
        gen = views[0]["generation"]
        deadline = time.monotonic() + 5.0
        # Nobody heartbeats: the sweeper must declare both dead.
        while time.monotonic() < deadline:
            body = client.metrics()
            if 'tdr_ctl_members{world="w"} 0' in body:
                break
            time.sleep(0.1)
        body = client.metrics()
        assert 'tdr_ctl_members{world="w"} 0' in body
        exp = [ln for ln in body.splitlines()
               if ln.startswith('tdr_ctl_lease_expiries_total{world="w"}')]
        assert exp and int(exp[0].split()[-1]) >= 2
        # Each death was a membership decision: the generation moved.
        gl = [ln for ln in body.splitlines()
              if ln.startswith('tdr_ctl_generation{world="w"}')]
        assert gl and int(gl[0].split()[-1]) > gen
        # A stale incarnation is refused — it must rejoin.
        resp = client.sync("w", 0, views[0]["incarnation"], timeout_s=2)
        assert not resp["ok"] and resp["error"] == "superseded"
    finally:
        coord.stop()


def test_heartbeat_renews_lease():
    coord = Coordinator(port=0, lease_ms=400,
                        port_base=_free_port()).start()
    try:
        client = ControlClient(coord.address)
        views = _join_all(client, "w", 2)
        for _ in range(6):
            for v in views:
                r = client.heartbeat("w", v["rank"], v["incarnation"],
                                     v["generation"])
                assert r["ok"] and not r["stale"]
            time.sleep(0.15)
        assert 'tdr_ctl_members{world="w"} 2' in client.metrics()
    finally:
        coord.stop()


# ------------------------------------------------ arbitrated RingWorld


def test_arbitrated_world_rebuild_coordinator_owns_generation(coord):
    engines = [Engine("emu") for _ in range(2)]
    worlds = local_worlds(2, engines=engines, controller=coord.address,
                          world_name="ring", channels=1,
                          timeout_ms=15000)
    try:
        w0, w1 = worlds
        assert w0.generation == w1.generation == 0
        assert w0._ctl_epoch == w1._ctl_epoch == 1
        assert w0.control_stamp == "ctl=ring:g0:e1"
        bufs = [np.arange(16, dtype=np.float32) * (r + 1)
                for r in range(2)]
        errs = [None, None]

        def ar(r):
            try:
                worlds[r].allreduce(bufs[r])
            except BaseException as e:
                errs[r] = e

        ts = [threading.Thread(target=ar, args=(r,)) for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert errs == [None, None]
        np.testing.assert_array_equal(
            bufs[0], np.arange(16, dtype=np.float32) * 3)

        def rb(r):
            try:
                worlds[r].rebuild(max_attempts=6, backoff_s=0.05,
                                  timeout_ms=10000)
            except BaseException as e:
                errs[r] = e

        # Delta-count the arbitration events (the tracer is a process
        # singleton; absolute values would couple this test to
        # whatever ran before it).
        report0 = trace.counter("ctl.report")
        rebuild0 = trace.counter("ctl.rebuild")
        ts = [threading.Thread(target=rb, args=(r,)) for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert errs == [None, None]
        # ONE incident -> ONE coordinator bump, adopted by both ranks
        # (no rank-local generation arithmetic on this path), and the
        # rebuild is observable as ctl.* arbitration events.
        assert w0.generation == w1.generation == 1
        assert w0._ctl_epoch == w1._ctl_epoch == 2
        assert trace.counter("ctl.report") - report0 >= 1
        assert trace.counter("ctl.rebuild") - rebuild0 == 2
        ts = [threading.Thread(target=ar, args=(r,)) for r in range(2)]
        bufs[0][:] = 1.0
        bufs[1][:] = 2.0
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert errs == [None, None]
        np.testing.assert_array_equal(
            bufs[0], np.full(16, 3.0, dtype=np.float32))
    finally:
        for w in worlds:
            w.close()
        for e in engines:
            e.close()


def test_rank_auto_assignment_adopted_by_ringworld(coord):
    """rank=-1 asks the coordinator for the lowest free slot; the
    RingWorld must ADOPT the assigned position (ports, neighbors, and
    peer indexing all key off it)."""
    engines = [Engine("emu") for _ in range(2)]
    worlds = [None, None]
    errs = [None, None]

    def boot(i):
        try:
            worlds[i] = RingWorld(engines[i], -1, 2,
                                  controller=coord.address,
                                  world_name="auto", channels=1,
                                  timeout_ms=15000)
        except BaseException as e:
            errs[i] = e

    ts = [threading.Thread(target=boot, args=(i,)) for i in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    try:
        assert errs == [None, None], errs
        assert sorted(w.rank for w in worlds) == [0, 1]
        bufs = [np.full(16, 5, dtype=np.int32) for _ in range(2)]
        ts = [threading.Thread(target=worlds[i].allreduce,
                               args=(bufs[i],)) for i in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        np.testing.assert_array_equal(bufs[0],
                                      np.full(16, 10, dtype=np.int32))
    finally:
        for w in worlds:
            if w is not None:
                w.close()
        for e in engines:
            e.close()


def test_concurrent_worlds_share_engines(coord):
    """One engine pair hosting two named worlds: both rings reduce
    correctly (the multi-tenant path clears the engine-wide seal stamp
    instead of letting the worlds fence each other)."""
    engines = [Engine("emu") for _ in range(2)]
    wa = local_worlds(2, engines=engines, controller=coord.address,
                      world_name="tenant-a", channels=1,
                      timeout_ms=15000)
    wb = local_worlds(2, engines=engines, controller=coord.address,
                      world_name="tenant-b", channels=1,
                      timeout_ms=15000)
    try:
        assert engines[0].world_count == 2
        outs = {}
        errs = []

        def ar(worlds, r, tag, val):
            try:
                buf = np.full(32, val, dtype=np.int32)
                worlds[r].allreduce(buf)
                outs[(tag, r)] = buf
            except BaseException as e:
                errs.append(e)

        ts = [threading.Thread(target=ar, args=(wa, r, "a", r + 1))
              for r in range(2)]
        ts += [threading.Thread(target=ar, args=(wb, r, "b", 10 * (r + 1)))
               for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errs
        np.testing.assert_array_equal(outs[("a", 0)],
                                      np.full(32, 3, dtype=np.int32))
        np.testing.assert_array_equal(outs[("b", 1)],
                                      np.full(32, 30, dtype=np.int32))
    finally:
        for w in wa + wb:
            w.close()
        for e in engines:
            e.close()


# ------------------------------------------------------------ budgets


def test_native_qp_limit_enforced_at_bringup():
    eng = Engine("emu")
    try:
        eng.set_qp_limit(2)
        assert eng.qp_limit == 2
        port = _free_port()
        a, b = loopback_pair(eng, port)
        assert eng.qp_live == 2
        with pytest.raises(TransportError) as ei:
            eng.connect("127.0.0.1", _free_port(), timeout_ms=500)
        assert "qp budget exhausted" in str(ei.value)
        # Budget exhaustion is a configuration condition: rebuilding
        # cannot create headroom, so it must not be retryable.
        assert not ei.value.retryable
        a.close()
        b.close()
        assert eng.qp_live == 0
        # Headroom restored: bring-up works again.
        a, b = loopback_pair(eng, _free_port())
        a.close()
        b.close()
    finally:
        eng.close()


def test_world_qp_budget_enforced_at_bringup():
    eng = Engine("emu")
    try:
        with pytest.raises(TransportError) as ei:
            RingWorld(eng, 0, 2, _free_port(), channels=2, qp_budget=2,
                      timeout_ms=2000)
        assert "qp_budget" in str(ei.value)
        assert not ei.value.retryable
        # The refusal happened before any connection was attempted.
        assert eng.qp_live == 0
    finally:
        eng.close()


# -------------------------------------------------- bring-up details


def test_eaddrinuse_is_fast_retry_not_full_backoff():
    """A lingering listener from a torn-down incarnation blocks the
    accept port briefly; ``RingWorld._listen`` must ride it out INSIDE
    one attempt (50 ms fast retry against the attempt's own deadline)
    instead of failing the bootstrap and burning a backoff attempt."""
    port = _free_port()
    squatter = socket.socket()
    squatter.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    squatter.bind(("127.0.0.1", port))
    squatter.listen(1)

    eng = Engine("emu")
    eng2 = Engine("emu")
    # A bare RingWorld shell: _listen only needs .engine (building a
    # full world here would drag the squatter into the peer's dial).
    shell = RingWorld.__new__(RingWorld)
    shell.engine = eng
    try:
        # The native listener fails EADDRINUSE immediately while the
        # port is held — the condition the helper exists to absorb.
        with pytest.raises(TransportError) as ei:
            eng.listen("127.0.0.1", port, 100)
        assert "address already in use" in str(ei.value).lower()

        result = [None]
        errs = []

        def serve():
            try:
                result[0] = shell._listen("127.0.0.1", port, 10000)
            except BaseException as e:
                errs.append(e)

        t = threading.Thread(target=serve)
        t0 = time.monotonic()
        t.start()
        time.sleep(0.4)
        squatter.close()  # the lingering incarnation finally lets go
        time.sleep(0.2)
        client = eng2.connect("127.0.0.1", port, timeout_ms=8000)
        t.join(timeout=10)
        elapsed = time.monotonic() - t0
        assert not errs, errs
        assert result[0] is not None
        # Converged promptly after release — fast retry, not a failed
        # attempt plus exponential backoff.
        assert elapsed < 5.0, elapsed
        client.close()
        result[0].close()
    finally:
        squatter.close()
        eng.close()
        eng2.close()


def test_rebuild_jitter_is_deterministic(monkeypatch):
    import random as _random

    monkeypatch.setenv("TDR_REBUILD_SEED", "7")
    assert rebuild_jitter_seed() == 7
    # The jitter stream is a pure function of (seed, rank, generation)
    # — replaying a soak failure under TDR_FAULT_PLAN sleeps the same.
    a = _random.Random("7:1:3")
    b = _random.Random("7:1:3")
    c = _random.Random("7:2:3")
    seq_a = [a.random() for _ in range(4)]
    assert seq_a == [b.random() for _ in range(4)]
    assert seq_a != [c.random() for _ in range(4)]


# ------------------------------------------------------------ metrics


PINNED_NAMES = (
    "tdr_ctl_worlds",
    'tdr_ctl_generation{world="w"}',
    'tdr_ctl_members{world="w"}',
    'tdr_ctl_rebuilds_total{world="w"}',
    'tdr_ctl_lease_expiries_total{world="w"}',
    'tdr_retransmit_rate{world="w"}',
    'tdr_integrity_sealed_total{world="w"}',
    'tdr_integrity_retransmitted_total{world="w"}',
)


def _metric_value(body: str, prefix: str) -> float:
    for ln in body.splitlines():
        if ln.startswith(prefix + " ") or ln.startswith(prefix):
            if ln.split("}")[0] + "}" == prefix or \
                    ln.split()[0] == prefix:
                return float(ln.split()[-1])
    raise AssertionError(f"{prefix} not served:\n{body}")


def test_metrics_contract_names_and_monotonicity(coord):
    client = ControlClient(coord.address)
    views = _join_all(client, "w", 2)
    snap = native_counters()
    client.heartbeat("w", 0, views[0]["incarnation"],
                     views[0]["generation"], counters=snap,
                     hists={"chunk_lat_us": {4: 7, 9: 2}})
    body = client.metrics()
    for name in PINNED_NAMES:
        assert name in body, f"contract name {name} missing:\n{body}"
    # Histogram quantile series with the pinned label shape.
    assert 'tdr_chunk_lat_us{world="w",quantile="0.99"}' in body
    gen0 = _metric_value(body, 'tdr_ctl_generation{world="w"}')
    rb0 = _metric_value(body, 'tdr_ctl_rebuilds_total{world="w"}')
    # Force a rebuild: counters must be MONOTONE across it.
    client.report("w", 0, views[0]["incarnation"],
                  views[0]["generation"], "forced")
    errs = []
    out = []

    def s(r):
        try:
            out.append(client.sync("w", r, views[r]["incarnation"],
                                   timeout_s=10))
        except BaseException as e:
            errs.append(e)

    ts = [threading.Thread(target=s, args=(r,)) for r in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errs and all(v["ok"] for v in out)
    body2 = client.metrics()
    assert _metric_value(body2, 'tdr_ctl_generation{world="w"}') > gen0
    assert _metric_value(body2,
                         'tdr_ctl_rebuilds_total{world="w"}') == rb0 + 1


def test_metrics_match_native_registry_snapshot(coord):
    """The /metrics values for registry counters are EXACTLY the
    tdr_counters_read snapshot the member pushed (single member, so
    the per-world sum is the identity)."""
    client = ControlClient(coord.address)
    views = _join_all(client, "solo", 2)
    snap = native_counters()
    client.heartbeat("solo", 0, views[0]["incarnation"],
                     views[0]["generation"], counters=snap)
    body = client.metrics()
    for name in ("integrity.sealed", "integrity.verified",
                 "integrity.failed", "integrity.retransmitted",
                 "fault.hits", "telemetry.recorded"):
        served = _metric_value(
            body,
            f'tdr_{name.replace(".", "_")}_total{{world="solo"}}')
        assert served == snap[name], name


def test_metrics_per_member_rank_labels(coord):
    """Member-pushed registry series are ALSO served per ring slot
    with a ``rank=`` label: each rank's series carries exactly its own
    pushed snapshot (attribution — which member's retransmit ladder is
    moving), while the aggregate ``{world=}`` series keeps its
    contract-pinned label shape and value (the sum over slots)."""
    client = ControlClient(coord.address)
    views = _join_all(client, "ranked", 2)
    base = {"integrity.sealed": 100, "integrity.retransmitted": 4}
    other = {"integrity.sealed": 23, "integrity.retransmitted": 1}
    client.heartbeat("ranked", 0, views[0]["incarnation"],
                     views[0]["generation"], counters=base)
    client.heartbeat("ranked", 1, views[1]["incarnation"],
                     views[1]["generation"], counters=other)
    body = client.metrics()
    for name, pushed in (("integrity.sealed", (100, 23)),
                         ("integrity.retransmitted", (4, 1))):
        metric = f"tdr_{name.replace('.', '_')}_total"
        for rank, want in enumerate(pushed):
            served = _metric_value(
                body, f'{metric}{{world="ranked",rank="{rank}"}}')
            assert served == want, (name, rank)
        # Aggregate series: unchanged label shape, sum over slots.
        agg = _metric_value(body, f'{metric}{{world="ranked"}}')
        assert agg == sum(pushed), name


def test_healthz_and_unknown_path():
    coord = Coordinator(port=0, port_base=_free_port()).start()
    try:
        with socket.create_connection(("127.0.0.1", coord.port),
                                      timeout=5) as s:
            s.sendall(b"GET /healthz HTTP/1.0\r\n\r\n")
            assert s.recv(4096).startswith(b"HTTP/1.0 200")
        with socket.create_connection(("127.0.0.1", coord.port),
                                      timeout=5) as s:
            s.sendall(b"GET /nope HTTP/1.0\r\n\r\n")
            assert s.recv(4096).startswith(b"HTTP/1.0 404")
    finally:
        coord.stop()
