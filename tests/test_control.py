"""Control plane: coordinator arbitration, leases, budgets, /metrics.

Covers the arbitrated rendezvous path end to end on the emulated
engine — join/rank assignment, idempotent failure reports, lease
expiry, arbitrated RingWorld rebuild with coordinator-owned
generations — plus the two bring-up-time budget ladders (native
engine QP cap, per-world budget), the EADDRINUSE fast-retry,
deterministic rebuild jitter, and the /metrics contract: stable
names, counters monotone across a forced rebuild, registry values
matching ``tdr_counters_read`` snapshots.
"""

import socket
import threading
import time

import numpy as np
import pytest

from rocnrdma_tpu.collectives.world import (RingWorld, local_worlds,
                                            rebuild_jitter_seed)
from rocnrdma_tpu.control.client import ControlClient
from rocnrdma_tpu.control.coordinator import Coordinator
from rocnrdma_tpu.transport.engine import (Engine, TransportError,
                                           loopback_pair, native_counters)
from rocnrdma_tpu.utils.trace import trace


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture()
def coord():
    c = Coordinator(port=0, lease_ms=1500, port_base=_free_port()).start()
    yield c
    c.stop()


def _join_all(client, world, size, **kw):
    out = [None] * size
    errs = [None] * size

    def j(r):
        try:
            out[r] = client.join(world, size, rank=r, **kw)
        except BaseException as e:
            errs[r] = e

    ts = [threading.Thread(target=j, args=(r,)) for r in range(size)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    for e in errs:
        if e is not None:
            raise e
    return out


# ------------------------------------------------------- coordinator


def test_join_assigns_ranks_and_one_view(coord):
    client = ControlClient(coord.address)
    views = _join_all(client, "w", 3)
    assert all(v["ok"] for v in views)
    assert [v["rank"] for v in views] == [0, 1, 2]
    # One release, one view: same generation/epoch/base_port on all.
    assert len({v["generation"] for v in views}) == 1
    assert len({v["epoch"] for v in views}) == 1
    assert len({v["base_port"] for v in views}) == 1
    assert len({v["incarnation"] for v in views}) == 3


def test_worlds_get_disjoint_port_ranges(coord):
    client = ControlClient(coord.address)
    va = _join_all(client, "a", 2)
    vb = _join_all(client, "b", 2)
    assert va[0]["base_port"] != vb[0]["base_port"]
    assert abs(va[0]["base_port"] - vb[0]["base_port"]) >= 2


def test_report_bumps_generation_once_per_incident(coord):
    client = ControlClient(coord.address)
    views = _join_all(client, "w", 2)
    gen = views[0]["generation"]
    # Both ranks report the SAME incident (same believed generation):
    # exactly one bump — the arbitration core. Rebuilds count finished
    # recoveries (barrier re-releases), not reports, so it stays 0
    # until the ranks actually re-rendezvous.
    r0 = client.report("w", 0, views[0]["incarnation"], gen, "boom")
    r1 = client.report("w", 1, views[1]["incarnation"], gen, "boom")
    assert r0["generation"] == gen + 1
    assert r1["generation"] == gen + 1
    assert r1["rebuilds"] == 0


def test_lease_expiry_declares_dead_and_bumps():
    coord = Coordinator(port=0, lease_ms=300,
                        port_base=_free_port()).start()
    try:
        client = ControlClient(coord.address)
        views = _join_all(client, "w", 2)
        gen = views[0]["generation"]
        deadline = time.monotonic() + 5.0
        # Nobody heartbeats: the sweeper must declare both dead.
        while time.monotonic() < deadline:
            body = client.metrics()
            if 'tdr_ctl_members{world="w"} 0' in body:
                break
            time.sleep(0.1)
        body = client.metrics()
        assert 'tdr_ctl_members{world="w"} 0' in body
        exp = [ln for ln in body.splitlines()
               if ln.startswith('tdr_ctl_lease_expiries_total{world="w"}')]
        assert exp and int(exp[0].split()[-1]) >= 2
        # Each death was a membership decision: the generation moved.
        gl = [ln for ln in body.splitlines()
              if ln.startswith('tdr_ctl_generation{world="w"}')]
        assert gl and int(gl[0].split()[-1]) > gen
        # A stale incarnation is refused — it must rejoin.
        resp = client.sync("w", 0, views[0]["incarnation"], timeout_s=2)
        assert not resp["ok"] and resp["error"] == "superseded"
    finally:
        coord.stop()


def test_heartbeat_renews_lease():
    coord = Coordinator(port=0, lease_ms=400,
                        port_base=_free_port()).start()
    try:
        client = ControlClient(coord.address)
        views = _join_all(client, "w", 2)
        for _ in range(6):
            for v in views:
                r = client.heartbeat("w", v["rank"], v["incarnation"],
                                     v["generation"])
                assert r["ok"] and not r["stale"]
            time.sleep(0.15)
        assert 'tdr_ctl_members{world="w"} 2' in client.metrics()
    finally:
        coord.stop()


# ------------------------------------------------ arbitrated RingWorld


def test_arbitrated_world_rebuild_coordinator_owns_generation(coord):
    engines = [Engine("emu") for _ in range(2)]
    worlds = local_worlds(2, engines=engines, controller=coord.address,
                          world_name="ring", channels=1,
                          timeout_ms=15000)
    try:
        w0, w1 = worlds
        assert w0.generation == w1.generation == 0
        assert w0._ctl_epoch == w1._ctl_epoch == 1
        assert w0.control_stamp == "ctl=ring:g0:e1"
        bufs = [np.arange(16, dtype=np.float32) * (r + 1)
                for r in range(2)]
        errs = [None, None]

        def ar(r):
            try:
                worlds[r].allreduce(bufs[r])
            except BaseException as e:
                errs[r] = e

        ts = [threading.Thread(target=ar, args=(r,)) for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert errs == [None, None]
        np.testing.assert_array_equal(
            bufs[0], np.arange(16, dtype=np.float32) * 3)

        def rb(r):
            try:
                worlds[r].rebuild(max_attempts=6, backoff_s=0.05,
                                  timeout_ms=10000)
            except BaseException as e:
                errs[r] = e

        # Delta-count the arbitration events (the tracer is a process
        # singleton; absolute values would couple this test to
        # whatever ran before it).
        report0 = trace.counter("ctl.report")
        rebuild0 = trace.counter("ctl.rebuild")
        ts = [threading.Thread(target=rb, args=(r,)) for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert errs == [None, None]
        # ONE incident -> ONE coordinator bump, adopted by both ranks
        # (no rank-local generation arithmetic on this path), and the
        # rebuild is observable as ctl.* arbitration events.
        assert w0.generation == w1.generation == 1
        assert w0._ctl_epoch == w1._ctl_epoch == 2
        assert trace.counter("ctl.report") - report0 >= 1
        assert trace.counter("ctl.rebuild") - rebuild0 == 2
        ts = [threading.Thread(target=ar, args=(r,)) for r in range(2)]
        bufs[0][:] = 1.0
        bufs[1][:] = 2.0
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert errs == [None, None]
        np.testing.assert_array_equal(
            bufs[0], np.full(16, 3.0, dtype=np.float32))
    finally:
        for w in worlds:
            w.close()
        for e in engines:
            e.close()


def test_rank_auto_assignment_adopted_by_ringworld(coord):
    """rank=-1 asks the coordinator for the lowest free slot; the
    RingWorld must ADOPT the assigned position (ports, neighbors, and
    peer indexing all key off it)."""
    engines = [Engine("emu") for _ in range(2)]
    worlds = [None, None]
    errs = [None, None]

    def boot(i):
        try:
            worlds[i] = RingWorld(engines[i], -1, 2,
                                  controller=coord.address,
                                  world_name="auto", channels=1,
                                  timeout_ms=15000)
        except BaseException as e:
            errs[i] = e

    ts = [threading.Thread(target=boot, args=(i,)) for i in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    try:
        assert errs == [None, None], errs
        assert sorted(w.rank for w in worlds) == [0, 1]
        bufs = [np.full(16, 5, dtype=np.int32) for _ in range(2)]
        ts = [threading.Thread(target=worlds[i].allreduce,
                               args=(bufs[i],)) for i in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        np.testing.assert_array_equal(bufs[0],
                                      np.full(16, 10, dtype=np.int32))
    finally:
        for w in worlds:
            if w is not None:
                w.close()
        for e in engines:
            e.close()


def test_concurrent_worlds_share_engines(coord):
    """One engine pair hosting two named worlds: both rings reduce
    correctly (the multi-tenant path clears the engine-wide seal stamp
    instead of letting the worlds fence each other)."""
    engines = [Engine("emu") for _ in range(2)]
    wa = local_worlds(2, engines=engines, controller=coord.address,
                      world_name="tenant-a", channels=1,
                      timeout_ms=15000)
    wb = local_worlds(2, engines=engines, controller=coord.address,
                      world_name="tenant-b", channels=1,
                      timeout_ms=15000)
    try:
        assert engines[0].world_count == 2
        outs = {}
        errs = []

        def ar(worlds, r, tag, val):
            try:
                buf = np.full(32, val, dtype=np.int32)
                worlds[r].allreduce(buf)
                outs[(tag, r)] = buf
            except BaseException as e:
                errs.append(e)

        ts = [threading.Thread(target=ar, args=(wa, r, "a", r + 1))
              for r in range(2)]
        ts += [threading.Thread(target=ar, args=(wb, r, "b", 10 * (r + 1)))
               for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errs
        np.testing.assert_array_equal(outs[("a", 0)],
                                      np.full(32, 3, dtype=np.int32))
        np.testing.assert_array_equal(outs[("b", 1)],
                                      np.full(32, 30, dtype=np.int32))
    finally:
        for w in wa + wb:
            w.close()
        for e in engines:
            e.close()


# ------------------------------------------------------------ budgets


def test_native_qp_limit_enforced_at_bringup():
    eng = Engine("emu")
    try:
        eng.set_qp_limit(2)
        assert eng.qp_limit == 2
        port = _free_port()
        a, b = loopback_pair(eng, port)
        assert eng.qp_live == 2
        with pytest.raises(TransportError) as ei:
            eng.connect("127.0.0.1", _free_port(), timeout_ms=500)
        assert "qp budget exhausted" in str(ei.value)
        # Budget exhaustion is a configuration condition: rebuilding
        # cannot create headroom, so it must not be retryable.
        assert not ei.value.retryable
        a.close()
        b.close()
        assert eng.qp_live == 0
        # Headroom restored: bring-up works again.
        a, b = loopback_pair(eng, _free_port())
        a.close()
        b.close()
    finally:
        eng.close()


def test_world_qp_budget_enforced_at_bringup():
    eng = Engine("emu")
    try:
        with pytest.raises(TransportError) as ei:
            RingWorld(eng, 0, 2, _free_port(), channels=2, qp_budget=2,
                      timeout_ms=2000)
        assert "qp_budget" in str(ei.value)
        assert not ei.value.retryable
        # The refusal happened before any connection was attempted.
        assert eng.qp_live == 0
    finally:
        eng.close()


# -------------------------------------------------- bring-up details


def test_eaddrinuse_is_fast_retry_not_full_backoff():
    """A lingering listener from a torn-down incarnation blocks the
    accept port briefly; ``RingWorld._listen`` must ride it out INSIDE
    one attempt (50 ms fast retry against the attempt's own deadline)
    instead of failing the bootstrap and burning a backoff attempt."""
    port = _free_port()
    squatter = socket.socket()
    squatter.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    squatter.bind(("127.0.0.1", port))
    squatter.listen(1)

    eng = Engine("emu")
    eng2 = Engine("emu")
    # A bare RingWorld shell: _listen only needs .engine (building a
    # full world here would drag the squatter into the peer's dial).
    shell = RingWorld.__new__(RingWorld)
    shell.engine = eng
    try:
        # The native listener fails EADDRINUSE immediately while the
        # port is held — the condition the helper exists to absorb.
        with pytest.raises(TransportError) as ei:
            eng.listen("127.0.0.1", port, 100)
        assert "address already in use" in str(ei.value).lower()

        result = [None]
        errs = []

        def serve():
            try:
                result[0] = shell._listen("127.0.0.1", port, 10000)
            except BaseException as e:
                errs.append(e)

        t = threading.Thread(target=serve)
        t0 = time.monotonic()
        t.start()
        time.sleep(0.4)
        squatter.close()  # the lingering incarnation finally lets go
        time.sleep(0.2)
        client = eng2.connect("127.0.0.1", port, timeout_ms=8000)
        t.join(timeout=10)
        elapsed = time.monotonic() - t0
        assert not errs, errs
        assert result[0] is not None
        # Converged promptly after release — fast retry, not a failed
        # attempt plus exponential backoff.
        assert elapsed < 5.0, elapsed
        client.close()
        result[0].close()
    finally:
        squatter.close()
        eng.close()
        eng2.close()


def test_rebuild_jitter_is_deterministic(monkeypatch):
    import random as _random

    monkeypatch.setenv("TDR_REBUILD_SEED", "7")
    assert rebuild_jitter_seed() == 7
    # The jitter stream is a pure function of (seed, rank, generation)
    # — replaying a soak failure under TDR_FAULT_PLAN sleeps the same.
    a = _random.Random("7:1:3")
    b = _random.Random("7:1:3")
    c = _random.Random("7:2:3")
    seq_a = [a.random() for _ in range(4)]
    assert seq_a == [b.random() for _ in range(4)]
    assert seq_a != [c.random() for _ in range(4)]


# ------------------------------------------------------------ metrics


PINNED_NAMES = (
    "tdr_ctl_worlds",
    'tdr_ctl_generation{world="w"}',
    'tdr_ctl_members{world="w"}',
    'tdr_ctl_rebuilds_total{world="w"}',
    'tdr_ctl_lease_expiries_total{world="w"}',
    'tdr_retransmit_rate{world="w"}',
    'tdr_integrity_sealed_total{world="w"}',
    'tdr_integrity_retransmitted_total{world="w"}',
)


def _metric_value(body: str, prefix: str) -> float:
    for ln in body.splitlines():
        if ln.startswith(prefix + " ") or ln.startswith(prefix):
            if ln.split("}")[0] + "}" == prefix or \
                    ln.split()[0] == prefix:
                return float(ln.split()[-1])
    raise AssertionError(f"{prefix} not served:\n{body}")


def test_metrics_contract_names_and_monotonicity(coord):
    client = ControlClient(coord.address)
    views = _join_all(client, "w", 2)
    snap = native_counters()
    client.heartbeat("w", 0, views[0]["incarnation"],
                     views[0]["generation"], counters=snap,
                     hists={"chunk_lat_us": {4: 7, 9: 2}})
    body = client.metrics()
    for name in PINNED_NAMES:
        assert name in body, f"contract name {name} missing:\n{body}"
    # Histogram quantile series with the pinned label shape.
    assert 'tdr_chunk_lat_us{world="w",quantile="0.99"}' in body
    gen0 = _metric_value(body, 'tdr_ctl_generation{world="w"}')
    rb0 = _metric_value(body, 'tdr_ctl_rebuilds_total{world="w"}')
    # Force a rebuild: counters must be MONOTONE across it.
    client.report("w", 0, views[0]["incarnation"],
                  views[0]["generation"], "forced")
    errs = []
    out = []

    def s(r):
        try:
            out.append(client.sync("w", r, views[r]["incarnation"],
                                   timeout_s=10))
        except BaseException as e:
            errs.append(e)

    ts = [threading.Thread(target=s, args=(r,)) for r in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errs and all(v["ok"] for v in out)
    body2 = client.metrics()
    assert _metric_value(body2, 'tdr_ctl_generation{world="w"}') > gen0
    assert _metric_value(body2,
                         'tdr_ctl_rebuilds_total{world="w"}') == rb0 + 1


def test_metrics_match_native_registry_snapshot(coord):
    """The /metrics values for registry counters are EXACTLY the
    tdr_counters_read snapshot the member pushed (single member, so
    the per-world sum is the identity)."""
    client = ControlClient(coord.address)
    views = _join_all(client, "solo", 2)
    snap = native_counters()
    client.heartbeat("solo", 0, views[0]["incarnation"],
                     views[0]["generation"], counters=snap)
    body = client.metrics()
    for name in ("integrity.sealed", "integrity.verified",
                 "integrity.failed", "integrity.retransmitted",
                 "fault.hits", "telemetry.recorded"):
        served = _metric_value(
            body,
            f'tdr_{name.replace(".", "_")}_total{{world="solo"}}')
        assert served == snap[name], name


def test_metrics_per_member_rank_labels(coord):
    """Member-pushed registry series are ALSO served per ring slot
    with a ``rank=`` label: each rank's series carries exactly its own
    pushed snapshot (attribution — which member's retransmit ladder is
    moving), while the aggregate ``{world=}`` series keeps its
    contract-pinned label shape and value (the sum over slots)."""
    client = ControlClient(coord.address)
    views = _join_all(client, "ranked", 2)
    base = {"integrity.sealed": 100, "integrity.retransmitted": 4}
    other = {"integrity.sealed": 23, "integrity.retransmitted": 1}
    client.heartbeat("ranked", 0, views[0]["incarnation"],
                     views[0]["generation"], counters=base)
    client.heartbeat("ranked", 1, views[1]["incarnation"],
                     views[1]["generation"], counters=other)
    body = client.metrics()
    for name, pushed in (("integrity.sealed", (100, 23)),
                         ("integrity.retransmitted", (4, 1))):
        metric = f"tdr_{name.replace('.', '_')}_total"
        for rank, want in enumerate(pushed):
            served = _metric_value(
                body, f'{metric}{{world="ranked",rank="{rank}"}}')
            assert served == want, (name, rank)
        # Aggregate series: unchanged label shape, sum over slots.
        agg = _metric_value(body, f'{metric}{{world="ranked"}}')
        assert agg == sum(pushed), name


def test_link_health_metrics_contract(coord):
    """Quarantine reporting: the ladder state a member pushes with its
    heartbeat is served under the CONTRACT-PINNED names and label
    shapes — tdr_link_health{world=,rank=,peer=,link=} per member per
    link, tdr_degraded_total{world=} as the fleet-wide rung tally, and
    the probe counters bridged from the native registry."""
    for k in ("probe.sent", "probe.pong", "probe.timeout"):
        assert k in native_counters(), k  # the bridge exports them
    client = ControlClient(coord.address)
    views = _join_all(client, "w", 2)
    client.heartbeat(
        "w", 0, views[0]["incarnation"], views[0]["generation"],
        counters={"probe.sent": 5, "probe.pong": 4, "probe.timeout": 1},
        link_health={
            "inter:r0": {"peer": 1, "score": 0.42, "degraded": 1,
                         "faults": 2},
            "intra:r0": {"peer": -1, "score": 0.97, "degraded": 0,
                         "faults": 0},
        },
        degraded_total=2)
    client.heartbeat(
        "w", 1, views[1]["incarnation"], views[1]["generation"],
        link_health={"inter:r1": {"peer": 0, "score": 0.9,
                                  "degraded": 0, "faults": 0}},
        degraded_total=1)
    body = client.metrics()
    assert _metric_value(
        body,
        'tdr_link_health{world="w",rank="0",peer="1",link="inter:r0"}'
    ) == pytest.approx(0.42)
    assert _metric_value(
        body,
        'tdr_link_health{world="w",rank="0",peer="-1",link="intra:r0"}'
    ) == pytest.approx(0.97)
    assert _metric_value(
        body,
        'tdr_link_health{world="w",rank="1",peer="0",link="inter:r1"}'
    ) == pytest.approx(0.9)
    # The world tally is the SUM of the members' rung engagements.
    assert _metric_value(body, 'tdr_degraded_total{world="w"}') == 3.0
    assert _metric_value(body,
                         'tdr_probe_sent_total{world="w"}') == 5.0
    assert _metric_value(body,
                         'tdr_probe_pong_total{world="w"}') == 4.0
    assert _metric_value(body,
                         'tdr_probe_timeout_total{world="w"}') == 1.0


def test_grow_admissions_coalesce_into_one_resize():
    """Batch admission: two joiners landing inside the grow-hold
    window ride ONE resize (one generation bump, one repack, one
    rebuild-equivalent disruption) instead of two back-to-back."""
    c = Coordinator(port=0, lease_ms=1500, port_base=_free_port(),
                    grow_hold_ms=300).start()
    try:
        client = ControlClient(c.address)
        views = _join_all(client, "w", 2, resizable=True)
        jr = [None, None]

        def j(i):
            jr[i] = client.join("w", 2, rank=-1, resizable=True,
                                timeout_s=15)

        jts = [threading.Thread(target=j, args=(i,)) for i in range(2)]
        for t in jts:
            t.start()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            with c._cv:
                if len(c._worlds["w"].members) == 4:
                    break
            time.sleep(0.02)
        out = [None, None]

        def s(r):
            out[r] = client.sync("w", r, views[r]["incarnation"],
                                 timeout_s=10)

        ts = [threading.Thread(target=s, args=(r,)) for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        for t in jts:
            t.join()
        assert all(v["ok"] for v in out)
        assert all(r["ok"] for r in jr)
        assert sorted(r["rank"] for r in jr) == [2, 3]
        assert all(v["world_size"] == 4 for v in out + jr)
        assert out[0]["resizes"] == 1  # ONE resize for both admissions
    finally:
        c.stop()


def test_healthz_and_unknown_path():
    coord = Coordinator(port=0, port_base=_free_port()).start()
    try:
        with socket.create_connection(("127.0.0.1", coord.port),
                                      timeout=5) as s:
            s.sendall(b"GET /healthz HTTP/1.0\r\n\r\n")
            assert s.recv(4096).startswith(b"HTTP/1.0 200")
        with socket.create_connection(("127.0.0.1", coord.port),
                                      timeout=5) as s:
            s.sendall(b"GET /nope HTTP/1.0\r\n\r\n")
            assert s.recv(4096).startswith(b"HTTP/1.0 404")
    finally:
        coord.stop()


# ------------------------------------------ elastic fleet (PR 16)


def test_snapshot_restore_roundtrip_equality(tmp_path):
    """Snapshot -> restore is state-equal: same port, worlds,
    generations, incarnations, resize/rebuild counters; the only
    deltas are the failover count (+1 — a restore IS a failover) and
    the leases (restarted at a full TTL). Old incarnations re-attach
    by simply continuing to heartbeat."""
    import json
    import os

    snapdir = str(tmp_path)
    c1 = Coordinator(port=0, lease_ms=1500, port_base=_free_port(),
                     snapshot_dir=snapdir).start()
    client = ControlClient(c1.address)
    views = _join_all(client, "w", 2, resizable=True)
    client.report("w", 0, views[0]["incarnation"],
                  views[0]["generation"], "boom")
    c1.stop()  # writes the final snapshot
    with open(os.path.join(snapdir, Coordinator.SNAPSHOT_FILE)) as f:
        snap = json.load(f)
    assert snap["format"] == "tdr-ctl-snapshot-v1"

    c2 = Coordinator(port=0, restore=True, snapshot_dir=snapdir).start()
    try:
        # port=0 + restore adopts the snapshot's port: the fleet keeps
        # dialing the address it already knows.
        assert c2.address == c1.address
        path = c2.snapshot_now()
        with open(path) as f:
            snap2 = json.load(f)
        assert snap2["failovers"] == snap["failovers"] + 1
        assert snap2["next_inc"] >= snap["next_inc"]
        volatile = ("wall_time", "failovers")
        a = {k: v for k, v in snap.items() if k not in volatile}
        b = {k: v for k, v in snap2.items() if k not in volatile}
        assert a == b  # worlds, members, counters: bit-identical
        # Members never re-rendezvous: the incarnation each holds
        # still resolves, so a plain heartbeat renews the lease.
        c2client = ControlClient(c2.address)
        for v in views:
            hb = c2client.heartbeat("w", v["rank"], v["incarnation"],
                                    v["generation"])
            assert hb["ok"]
    finally:
        c2.stop()


def test_shrink_then_grow_generation_monotone():
    """World-3 shrinks to 2 (leave), then grows back to 3 (join on the
    full world): each RESIZE repacks ranks contiguously, bumps the
    resize count, and moves generation/epoch strictly forward — the
    digest inputs never run backwards."""
    c = Coordinator(port=0, lease_ms=1500, port_base=_free_port()).start()
    try:
        client = ControlClient(c.address)
        views = _join_all(client, "w", 3, resizable=True)
        gen0 = views[0]["generation"]

        # Rank 2 leaves; the survivors park -> world_size-1 view.
        client.leave("w", 2, views[2]["incarnation"])
        out = [None, None]

        def s(r, inc):
            out[r] = client.sync("w", r, inc, timeout_s=10)

        ts = [threading.Thread(target=s, args=(r, views[r]["incarnation"]))
              for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert all(v["ok"] for v in out)
        assert out[0]["world_size"] == 2
        assert out[0]["resizes"] == 1
        assert sorted(v["rank"] for v in out) == [0, 1]
        gen1 = out[0]["generation"]
        assert gen1 > gen0

        # A joiner on the now-FULL world parks; the incumbents park at
        # their next boundary -> world_size+1 view.
        jr = [None]

        def j():
            jr[0] = client.join("w", 2, rank=-1, resizable=True,
                                timeout_s=15)

        jt = threading.Thread(target=j)
        jt.start()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            with c._cv:
                if len(c._worlds["w"].members) == 3:
                    break
            time.sleep(0.02)
        ts = [threading.Thread(target=s, args=(r, out[r]["incarnation"]))
              for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        jt.join()
        assert jr[0]["ok"] and jr[0]["rank"] == 2
        assert jr[0]["world_size"] == 3
        assert all(v["ok"] and v["world_size"] == 3 for v in out)
        assert out[0]["resizes"] == 2
        assert out[0]["generation"] > gen1
        assert out[0]["epoch"] > views[0]["epoch"]
    finally:
        c.stop()


def test_admission_backpressure_retryable_with_retry_after():
    """A join the fleet cannot admit is RETRYABLE backpressure with a
    deterministic retry-after, not a hard failure: both the full
    non-resizable world and the --max-worlds quota say so."""
    c = Coordinator(port=0, lease_ms=1500, port_base=_free_port(),
                    max_worlds=1).start()
    try:
        client = ControlClient(c.address)
        _join_all(client, "w", 2)
        r = client.join("w", 2, rank=-1, timeout_s=5)
        assert not r["ok"] and r["retryable"]
        assert r["error"] == "fleet full"
        assert r["retry_after_s"] == pytest.approx(1.5)
        # Deterministic spread: the second reject backs off longer.
        r2 = client.join("w", 2, rank=-1, timeout_s=5)
        assert r2["retry_after_s"] == pytest.approx(3.0)
        # World quota: same verdict shape for a brand-new world.
        q = client.join("other", 2, rank=0, timeout_s=5)
        assert not q["ok"] and q["retryable"]
        assert "quota" in q["error"]
        body = client.metrics()
        assert _metric_value(
            body, 'tdr_ctl_admission_rejects_total{world="w"}') == 2.0
    finally:
        c.stop()


def test_fair_share_division_with_floor():
    """--qp-fair divides the engine QP pool across worlds by join-time
    weight with a per-world floor; the share rides the view's
    qp_budget so members adopt it at the next rendezvous."""
    c = Coordinator(port=0, lease_ms=1500, port_base=_free_port(),
                    qp_budget=90, qp_fair=True, qp_floor=5).start()
    try:
        client = ControlClient(c.address)
        va = _join_all(client, "a", 2, resizable=True)
        assert va[0]["qp_budget"] == 90  # alone: the whole pool
        vb = _join_all(client, "b", 2, resizable=True, weight=2.0)
        assert vb[0]["qp_budget"] == 60  # 90 * 2/(1+2)
        body = client.metrics()
        assert _metric_value(body, 'tdr_ctl_qp_share{world="a"}') == 30.0
        assert _metric_value(body, 'tdr_ctl_qp_share{world="b"}') == 60.0
        # The floor beats the proportional share for a featherweight.
        _join_all(client, "tiny", 2, resizable=True, weight=0.01)
        body = client.metrics()
        assert _metric_value(
            body, 'tdr_ctl_qp_share{world="tiny"}') == 5.0
    finally:
        c.stop()


def test_heartbeat_after_leave_stops_and_is_rejected(coord):
    """The heartbeat-after-leave fix, both sides: the coordinator
    refuses (never re-adopts) a push under a superseded identity, and
    the member-side Heartbeat stops sending under that identity until
    state_fn reports a different (incarnation, rank)."""
    client = ControlClient(coord.address)
    views = _join_all(client, "w", 2)
    inc1 = views[1]["incarnation"]
    client.leave("w", 1, inc1)
    # Coordinator side: the old identity is dead, not re-adoptable.
    r = client.heartbeat("w", 1, inc1, views[1]["generation"],
                         counters={"integrity.sealed": 7})
    assert not r["ok"] and r["error"] == "superseded"
    body = client.metrics()
    assert 'tdr_integrity_sealed_total{world="w",rank="1"}' not in body

    # Member side: after one refusal the thread goes quiet under the
    # dead identity...
    state = {"v": (inc1, views[1]["generation"], 1)}
    sent = []
    real_hb = client.heartbeat
    client.heartbeat = lambda *a, **kw: sent.append(a) or real_hb(*a, **kw)
    hb = client.start_heartbeat("w", 1, lambda: state["v"],
                                interval_s=3600)
    try:
        assert hb.beat() and hb._dead_key == (inc1, 1)
        n = len(sent)
        assert hb.beat() and len(sent) == n  # no wire push: superseded
        # ...and resumes the moment the identity changes (a RESIZE
        # moves the rank under the same incarnation).
        state["v"] = (inc1, views[1]["generation"], 0)
        hb.beat()
        assert len(sent) == n + 1
    finally:
        client.heartbeat = real_hb
        hb.stop()


def test_metrics_scrape_rate_limit_429():
    """A hot scraper gets 429 backpressure with a deterministic
    retry-after, not the render cost: the first scrape in the window
    is served, the second refused and counted."""
    from rocnrdma_tpu.control.client import ControlError

    c = Coordinator(port=0, port_base=_free_port(),
                    scrape_min_interval_ms=30000).start()
    try:
        client = ControlClient(c.address)
        body = client.metrics()  # first scrape in the window is served
        assert "tdr_ctl_scrape_throttled_total 0" in body
        with pytest.raises(ControlError, match="429"):
            client.metrics()
        # The refusal is counted; the next successful scrape serves it.
        assert c._scrape_throttled == 1
    finally:
        c.stop()


def test_heartbeat_rate_limit_sheds_payload_keeps_lease():
    """Per-world heartbeat rate limit: an over-eager beater still
    renews its lease (liveness is cheap) but the telemetry payload is
    shed and the shed counted — sealed stays at the first push."""
    c = Coordinator(port=0, lease_ms=1500, port_base=_free_port(),
                    hb_min_interval_ms=60000).start()
    try:
        client = ControlClient(c.address)
        views = _join_all(client, "w", 2)
        inc = views[0]["incarnation"]
        gen = views[0]["generation"]
        r1 = client.heartbeat("w", 0, inc, gen,
                              counters={"integrity.sealed": 1})
        assert r1["ok"] and not r1.get("throttled")
        r2 = client.heartbeat("w", 0, inc, gen,
                              counters={"integrity.sealed": 2})
        assert r2["ok"] and r2["throttled"]  # lease renewed, payload shed
        body = client.metrics()
        assert _metric_value(
            body, 'tdr_ctl_hb_throttled_total{world="w"}') == 1.0
        assert _metric_value(
            body, 'tdr_integrity_sealed_total{world="w"}') == 1.0
    finally:
        c.stop()


def test_standby_promotes_on_primary_death(tmp_path):
    """Warm standby: tails snapshots, probes the primary's /healthz,
    and after the primary dies promotes itself on the SAME port with
    the restored state (failovers bumped)."""
    from rocnrdma_tpu.control.coordinator import Standby

    snapdir = str(tmp_path)
    c1 = Coordinator(port=0, lease_ms=1500, port_base=_free_port(),
                     snapshot_dir=snapdir,
                     snapshot_interval_s=0.1).start()
    client = ControlClient(c1.address)
    views = _join_all(client, "w", 2, resizable=True)
    c1.snapshot_now()
    sb = Standby(snapdir, address=c1.address, probe_interval_s=0.1,
                 fail_threshold=2).start()
    try:
        time.sleep(0.5)
        assert not sb.promoted.is_set()  # healthy primary: no takeover
        c1.stop()
        assert sb.promoted.wait(10)
        assert sb.coordinator is not None
        assert sb.coordinator.address == c1.address
        hb = client.heartbeat("w", 0, views[0]["incarnation"],
                              views[0]["generation"])
        assert hb["ok"]
        body = client.metrics()
        assert _metric_value(body, "tdr_ctl_failovers_total") >= 1.0
    finally:
        sb.stop()
