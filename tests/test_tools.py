"""Evidence-integrity machinery of the TPU measurement tools.

The banked-results files ARE the round's hardware evidence; the merge
logic that builds them across flaky-tunnel windows must never lose
banked keys, never let a clean selective run disguise an incomplete
bank, and always attribute what actually executed (VERDICT r04
missing-1 discipline).
"""

import contextlib
import importlib.util
import io
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tpu_extra():
    spec = importlib.util.spec_from_file_location(
        "tpu_extra", os.path.join(REPO, "tools", "tpu_extra.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


te = _load_tpu_extra()


def test_merge_keeps_banked_keys_and_new_keys_win():
    prev = {"ts": "t1", "attn_h16kv8s2048d128_us": {"pallas": 7000},
            "rmsnorm_parity_maxerr": 0.01, "_steps": 4,
            "sections_completed": ["entry", "ops"],
            "sections_requested": ["entry", "ops", "train"]}
    new = {"ts": "t2", "llama3_1b_train_mfu_pallas": 0.45,
           "attn_h16kv8s2048d128_us": {"pallas": 6900}, "_steps": 2,
           "sections_completed": ["train"],
           "sections_requested": ["train"]}
    m = te.merge_bank(prev, new)
    assert m["rmsnorm_parity_maxerr"] == 0.01  # banked key survives
    assert m["attn_h16kv8s2048d128_us"] == {"pallas": 6900}  # new wins
    assert m["llama3_1b_train_mfu_pallas"] == 0.45
    assert m["_steps"] == 6
    assert m["sections_completed"] == ["entry", "ops", "train"]
    assert m["_runs"] == ["t1", "t2"]


def test_merge_partial_reflects_newest_run_only():
    prev = {"ts": "t1", "partial": "timeout after 1200s", "_steps": 4}
    clean = {"ts": "t2", "llama3_1b_decode": {"tokens_per_s_64new": 400},
             "_steps": 1}
    m = te.merge_bank(prev, clean)
    assert "partial" not in m  # the newest run completed
    m2 = te.merge_bank(m, {"ts": "t3", "partial": "died", "_steps": 0})
    assert m2["partial"] == "died"


def test_annotate_missing_marks_incomplete_banks():
    """A clean selective run must not make an incomplete bank look
    whole: completeness comes from which section keys EXIST, not from
    the newest run's exit status."""
    bank = {"entry_auto_pallas_compiles": True,
            "attn_h16kv8s2048d128_us": {"pallas": 7000},
            "llama3_1b_decode": {"tokens_per_s_64new": 400}}
    te.annotate_missing(bank)
    # ops needs BOTH op timings (the 04:16Z window banked attention
    # but a meaningless 0.0-us rmsnorm).
    assert bank["missing_sections"] == ["longseq", "ops", "train"]

    bank["rmsnorm_b8s2048d2048_us"] = {"pallas": 17, "xla": 20}
    te.annotate_missing(bank)
    assert bank["missing_sections"] == ["longseq", "train"]

    # train needs BOTH A/B sides: a bank holding only the pallas half
    # (e.g. the xla run was fence-broken and discarded) stays
    # incomplete so a later window re-measures the discarded half.
    bank.update({"llama3_1b_train_mfu_pallas": 0.4,
                 "long_seq_attention": {}})
    te.annotate_missing(bank)
    assert bank["missing_sections"] == ["train"]

    bank["llama3_1b_train_mfu_xla"] = 0.37
    te.annotate_missing(bank)
    assert "missing_sections" not in bank  # and stale markers clear


def test_requested_vs_completed_stay_separate():
    """A timed-out run that REQUESTED five sections but finished one
    must not claim the other four as covered."""
    partial_run = {"ts": "t1", "partial": "timeout", "_steps": 1,
                   "sections_requested": ["decode", "entry", "ops"],
                   "sections_completed": ["entry"],
                   "entry_auto_pallas_compiles": True}
    m = te.merge_bank({}, partial_run)
    assert m["sections_completed"] == ["entry"]
    assert m["sections_requested"] == ["decode", "entry", "ops"]
    te.annotate_missing(m)
    assert "ops" in m["missing_sections"]


def test_collective_cli_runs_every_op():
    """The collective benchmark CLI (the perftest/MPI-analogue role)
    runs every primitive in-process and reports the op it ran with a
    finite bandwidth."""
    from test_transport import free_port

    from rocnrdma_tpu.tools import allreduce as cli

    for op in ("allreduce", "alltoall", "reduce_scatter", "all_gather",
               "broadcast", "reduce"):
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = cli.main(["--world", "2", "--bytes", "1M", "--iters",
                           "1", "--op", op, "--json",
                           "--port", str(free_port())])
        assert rc == 0
        out = json.loads(buf.getvalue().strip().splitlines()[-1])
        assert out["op"] == op and out["bus_GBps"] > 0


def test_perf_cli_lat_and_qd_modes():
    """tdr_perf covers both perftest roles: --lat (ib_write_lat:
    serial round trips with a min/p50/p99/max distribution) and the
    default bw mode with --qd outstanding writes (ib_write_bw's
    tx-depth)."""
    from test_transport import free_port

    from rocnrdma_tpu.tools import perf as cli

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = cli.main(["--loopback", "--op", "write", "--sizes", "4K",
                       "--iters", "24", "--lat", "--json",
                       "--port", str(free_port())])
    assert rc == 0
    out = json.loads(buf.getvalue().strip().splitlines()[-1])
    rec = out["sweep"][0]
    assert (rec["lat_us_min"] <= rec["lat_us_p50"]
            <= rec["lat_us_p99"] <= rec["lat_us_max"])
    assert out["min_lat_us"] == rec["lat_us_min"]

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = cli.main(["--loopback", "--op", "write", "--sizes", "64K",
                       "--iters", "24", "--qd", "8", "--json",
                       "--port", str(free_port())])
    assert rc == 0
    out = json.loads(buf.getvalue().strip().splitlines()[-1])
    assert out["peak_GBps"] > 0
    assert out["sweep"][0]["lat_us"] > 0


def test_bench_snippet_compiles_and_is_section_complete():
    """The in-subprocess BENCH script must stay syntactically valid
    (percent-formatting included) and gate every section it reports."""
    src = te.BENCH % {"repo": REPO}
    compile(src, "<bench>", "exec")
    for section in te.SECTION_KEYS:
        assert f'"{section}" in _SECT' in src
