"""Pallas kernel tests (interpret mode on CPU; same code compiles on TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rocnrdma_tpu.ops.attention import (
    attention_reference, flash_attention)
from rocnrdma_tpu.ops.rmsnorm import rmsnorm, rmsnorm_reference


def test_rmsnorm_matches_reference():
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 32, 128),
                          dtype=jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(1), (128,)) + 1.0
    got = rmsnorm(x, w, use_pallas=True, interpret=True)
    want = rmsnorm_reference(x, w)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_rmsnorm_block_rows_knob(monkeypatch):
    """block_rows (arg or TDR_RMSNORM_BLOCK env) changes the grid, not
    the math: a block that does NOT divide the row count exercises the
    masked-tail path in both passes."""
    x = jax.random.normal(jax.random.PRNGKey(2), (10, 64))
    w = jnp.ones((64,)) * 0.5
    want = rmsnorm_reference(x, w)
    gx_r, gw_r = jax.grad(
        lambda x, w: jnp.sum(rmsnorm_reference(x, w) ** 2),
        argnums=(0, 1))(x, w)
    for br in (4, 7, 16):
        got = rmsnorm(x, w, use_pallas=True, interpret=True, block_rows=br)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)
        gx_p, gw_p = jax.grad(
            lambda x, w, br=br: jnp.sum(rmsnorm(
                x, w, use_pallas=True, interpret=True,
                block_rows=br) ** 2), argnums=(0, 1))(x, w)
        np.testing.assert_allclose(np.asarray(gx_p), np.asarray(gx_r),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(gw_p), np.asarray(gw_r),
                                   rtol=1e-4, atol=1e-4)
    monkeypatch.setenv("TDR_RMSNORM_BLOCK", "7")
    got = rmsnorm(x, w, use_pallas=True, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    monkeypatch.setenv("TDR_RMSNORM_BLOCK", "0")
    with pytest.raises(ValueError, match="TDR_RMSNORM_BLOCK"):
        rmsnorm(x, w, use_pallas=True, interpret=True)


def test_rmsnorm_grad():
    x = jax.random.normal(jax.random.PRNGKey(0), (8, 64))
    w = jnp.ones((64,)) * 1.5

    def f_pallas(x, w):
        return jnp.sum(rmsnorm(x, w, 1e-5, True, True) ** 2)

    def f_ref(x, w):
        return jnp.sum(rmsnorm_reference(x, w, 1e-5) ** 2)

    gx_p, gw_p = jax.grad(f_pallas, argnums=(0, 1))(x, w)
    gx_r, gw_r = jax.grad(f_ref, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gx_p), np.asarray(gx_r),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(gw_p), np.asarray(gw_r),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("seq,block", [(64, 32), (100, 32), (128, 128)])
def test_flash_attention_matches_reference(seq, block):
    b, h, d = 2, 4, 64
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (b, h, seq, d), dtype=jnp.float32)
    k = jax.random.normal(ks[1], (b, h, seq, d), dtype=jnp.float32)
    v = jax.random.normal(ks[2], (b, h, seq, d), dtype=jnp.float32)
    got = flash_attention(q, k, v, True, None, block, block, True)
    want = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_flash_attention_gqa():
    """Grouped KV heads: 8 q heads read 2 kv heads via the index map."""
    b, h, kvh, seq, d = 1, 8, 2, 64, 32
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(ks[0], (b, h, seq, d))
    k = jax.random.normal(ks[1], (b, kvh, seq, d))
    v = jax.random.normal(ks[2], (b, kvh, seq, d))
    got = flash_attention(q, k, v, True, None, 32, 32, True)
    want = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_flash_attention_noncausal():
    b, h, seq, d = 1, 2, 64, 32
    ks = jax.random.split(jax.random.PRNGKey(2), 3)
    q = jax.random.normal(ks[0], (b, h, seq, d))
    k = jax.random.normal(ks[1], (b, h, seq, d))
    v = jax.random.normal(ks[2], (b, h, seq, d))
    got = flash_attention(q, k, v, False, None, 32, 32, True)
    want = attention_reference(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_flash_attention_grad_flows():
    b, h, seq, d = 1, 2, 32, 16
    ks = jax.random.split(jax.random.PRNGKey(3), 3)
    q = jax.random.normal(ks[0], (b, h, seq, d))
    k = jax.random.normal(ks[1], (b, h, seq, d))
    v = jax.random.normal(ks[2], (b, h, seq, d))

    def f(q, k, v):
        return jnp.sum(flash_attention(q, k, v, True, None, 32, 32, True))

    def f_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=True))

    g = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-4, atol=1e-4)


def test_pallas_auto_default_resolution():
    """Model configs default use_pallas_* to None = auto: Pallas is the
    compute path exactly when the default backend is TPU (VERDICT r03
    missing-item 4 — the kernels must not be opt-in demo code). The
    suite runs on CPU, so auto must resolve to the XLA path here, and
    explicit flags must always win over auto."""
    from rocnrdma_tpu.models.llama import (
        CONFIGS, make_model, resolve_pallas)

    for cfg in CONFIGS.values():
        assert cfg.use_pallas_attention is None
        assert cfg.use_pallas_rmsnorm is None
    assert resolve_pallas(True) is True
    assert resolve_pallas(False) is False
    assert resolve_pallas(None) is False  # this suite is CPU-pinned
    # Per-pass TPU defaults (measured, TPU_RESULTS_r05_extra.json):
    # the tpu_default knob only matters on TPU backends, but explicit
    # flags must override it everywhere.
    assert resolve_pallas(None, tpu_default=False) is False
    assert resolve_pallas(True, tpu_default=False) is True

    m = make_model("llama-tiny", use_pallas_attention=True,
                   use_pallas_rmsnorm=False)
    assert m.cfg.use_pallas_attention is True
    assert m.cfg.use_pallas_rmsnorm is False


def test_pallas_auto_default_is_per_pass_on_tpu(monkeypatch):
    """On a TPU backend the auto default is per-PASS, from on-chip
    measurement (TPU_RESULTS_r05_extra.json: flash attention beats XLA
    7223 vs 10541 us, rmsnorm loses 544 vs 437): attention -> Pallas,
    rmsnorm -> XLA. Also covers tunneled PJRT platforms whose platform
    string is not "tpu" but whose devices are TPU chips (the axon
    case, where matching on backend name alone disabled the kernels on
    the one environment they target)."""
    from rocnrdma_tpu.models import llama

    monkeypatch.setattr(llama, "_tpu_backend", lambda: True)
    assert llama.resolve_pallas(None) is True  # attention default
    assert llama.resolve_pallas(None, tpu_default=False) is False
    assert llama.resolve_pallas(False) is False  # explicit still wins
    assert llama.resolve_pallas(True, tpu_default=False) is True

    # The tunneled-platform detection itself: device_kind carries
    # "TPU" even when the platform name does not.
    class FakeDev:
        device_kind = "TPU v5 lite"

    monkeypatch.undo()
    import jax

    monkeypatch.setattr(jax, "default_backend", lambda: "axon")
    monkeypatch.setattr(jax, "devices", lambda: [FakeDev()])
    assert llama._tpu_backend() is True
    monkeypatch.setattr(jax, "devices", lambda: [])
    assert llama._tpu_backend() is False


def test_flash_attention_pallas_backward_parity():
    """The hand-written Pallas backward (dK/dV and dQ kernels driven
    by saved lse + delta = rowsum(dO∘O)) must match grads of the XLA
    reference — including GQA group-summing and a sequence length
    that pads to the block size."""
    ks = jax.random.split(jax.random.PRNGKey(3), 4)
    q = jax.random.normal(ks[0], (2, 4, 96, 32))
    k = jax.random.normal(ks[1], (2, 2, 96, 32))
    v = jax.random.normal(ks[2], (2, 2, 96, 32))
    g = jax.random.normal(ks[3], (2, 4, 96, 32))

    def f_p(q, k, v):
        return jnp.vdot(flash_attention(q, k, v, True, None, 64, 64,
                                        True), g)

    def f_r(q, k, v):
        return jnp.vdot(attention_reference(q, k, v, causal=True), g)

    gp = jax.grad(f_p, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(f_r, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-3)


def test_flash_attention_remat_backward_knob(monkeypatch):
    """TDR_FLASH_BWD=remat falls back to the rematerializing XLA
    backward; grads must agree with the Pallas backward."""
    ks = jax.random.split(jax.random.PRNGKey(4), 4)
    q = jax.random.normal(ks[0], (1, 2, 64, 16))
    k = jax.random.normal(ks[1], (1, 2, 64, 16))
    v = jax.random.normal(ks[2], (1, 2, 64, 16))
    g = jax.random.normal(ks[3], (1, 2, 64, 16))

    def f(q, k, v):
        return jnp.vdot(flash_attention(q, k, v, True, None, 64, 64,
                                        True), g)

    g_pallas = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    monkeypatch.setenv("TDR_FLASH_BWD", "remat")
    g_remat = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_pallas, g_remat):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-3)


def test_flash_backward_knob_and_block_validation(monkeypatch):
    """The TDR_FLASH_BWD knob is actually read (bogus values raise at
    backward trace time), and non-dividing block sizes raise instead
    of silently dropping the sequence tail."""
    ks = jax.random.split(jax.random.PRNGKey(5), 2)
    q = jax.random.normal(ks[0], (1, 1, 64, 16))
    g = jax.random.normal(ks[1], (1, 1, 64, 16))

    monkeypatch.setenv("TDR_FLASH_BWD", "bogus")
    with pytest.raises(ValueError, match="TDR_FLASH_BWD"):
        jax.grad(lambda q_: jnp.vdot(
            flash_attention(q_, q, q, True, None, 64, 64, True), g))(q)
    monkeypatch.delenv("TDR_FLASH_BWD")

    with pytest.raises(ValueError, match="divide"):
        flash_attention(q, q, q, True, None, 48, 64, True)


def test_flash_attention_causal_fetch_skip_parity():
    """Causal fetch-skip: above-diagonal kv blocks (and, in the dK/dV
    kernel, below-diagonal q blocks) re-map their fetch to the last
    contributing block so Mosaic's pipeline elides the HBM copy; the
    compute for those blocks is separately predicated off. Parity must
    hold at multi-block sizes where the clamps actually engage —
    including uneven block_q/block_k ratios, where the diagonal-block
    arithmetic differs in each kernel."""
    for bq, bk in ((64, 64), (64, 32), (32, 64)):
        ks = jax.random.split(jax.random.PRNGKey(bq + bk), 4)
        q = jax.random.normal(ks[0], (1, 2, 256, 32))
        k = jax.random.normal(ks[1], (1, 2, 256, 32))
        v = jax.random.normal(ks[2], (1, 2, 256, 32))
        g = jax.random.normal(ks[3], (1, 2, 256, 32))
        fo = flash_attention(q, k, v, True, None, bq, bk, True)
        ro = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(fo), np.asarray(ro),
                                   rtol=2e-3, atol=2e-3)
        gp = jax.grad(lambda q_, k_, v_: jnp.vdot(
            flash_attention(q_, k_, v_, True, None, bq, bk, True), g),
            argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda q_, k_, v_: jnp.vdot(
            attention_reference(q_, k_, v_, causal=True), g),
            argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gp, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-3, atol=2e-3)


def test_rmsnorm_pallas_backward_parity(monkeypatch):
    """The fused rmsnorm backward kernel (row-local dx, dw accumulated
    across the sequential row-block grid) must match the XLA backward
    formulas — multi-block rows so the dw accumulation is exercised,
    and 3-D input so the reshape plumbing is covered."""
    ks = jax.random.split(jax.random.PRNGKey(6), 3)
    x = jax.random.normal(ks[0], (4, 160, 128))  # 640 rows > _BLOCK_ROWS
    w = jax.random.normal(ks[1], (128,)) + 1.0
    g = jax.random.normal(ks[2], (4, 160, 128))

    def f(x, w):
        return jnp.vdot(rmsnorm(x, w, use_pallas=True, interpret=True), g)

    # Pin the knob: an ambient TDR_RMSNORM_BWD=xla would make the
    # "pallas" side take the XLA path and the parity check vacuous.
    monkeypatch.setenv("TDR_RMSNORM_BWD", "pallas")
    gx_p, gw_p = jax.grad(f, argnums=(0, 1))(x, w)
    monkeypatch.setenv("TDR_RMSNORM_BWD", "xla")
    gx_x, gw_x = jax.grad(f, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gx_p), np.asarray(gx_x),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gw_p), np.asarray(gw_x),
                               rtol=1e-4, atol=1e-4)

    monkeypatch.setenv("TDR_RMSNORM_BWD", "bogus")
    with pytest.raises(ValueError, match="TDR_RMSNORM_BWD"):
        jax.grad(f)(x, w)
