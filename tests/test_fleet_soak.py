"""Elastic-fleet soak: the autoscaling acceptance gate, pinned.

``tools/fleet_soak.py`` drives >=12 named worlds against one
coordinator through join/leave/flap churn, a shrink and a grow RESIZE,
a coordinator SIGKILL with snapshot-restore mid-soak, and the three
admission-control probes. The slow test here runs the whole soak and
asserts its verdict — bitwise parity on every completed collective,
zero leaked heartbeat threads, post-recovery resize/failover counters
on /metrics, monotone generations, weighted fair share. The fast
tests pin the soak's own tooling (metric parsing, the subprocess
coordinator's health endpoint) so a broken harness can't silently
pass the gate.
"""

import os
import signal
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")
if TOOLS not in sys.path:
    sys.path.insert(0, TOOLS)

import fleet_soak  # noqa: E402


def test_metric_helpers_parse_exposition():
    """The soak's verdict reads /metrics through these two helpers;
    they must sum label blocks and pick exact worlds, not prefixes."""
    text = "\n".join([
        "# comment",
        'tdr_ctl_resizes_total{world="a"} 2',
        'tdr_ctl_resizes_total{world="ab"} 3',
        "tdr_ctl_failovers_total 1",
        "garbage line",
        'tdr_ctl_qp_share{world="a"} nope',
    ])
    assert fleet_soak.metric_sum(text, "tdr_ctl_resizes_total{") == 5.0
    assert fleet_soak.metric_sum(text, "tdr_ctl_failovers_total") == 1.0
    # Exact world match: "a" must not swallow "ab".
    assert fleet_soak.metric_world(
        text, "tdr_ctl_resizes_total", "a") == 2.0
    assert fleet_soak.metric_world(
        text, "tdr_ctl_resizes_total", "ab") == 3.0
    # Unparseable value degrades to 0, never raises mid-verdict.
    assert fleet_soak.metric_world(text, "tdr_ctl_qp_share", "a") == 0.0


def test_subprocess_coordinator_health_and_kill(tmp_path):
    """The soak's coordinator child comes up healthy, dies to SIGKILL
    (the mid-soak failover injection), and a --restore respawn on the
    same port comes back healthy from the snapshot dir."""
    port = fleet_soak._free_port()
    proc = fleet_soak.spawn_coordinator(
        port, fleet_soak._free_port(), str(tmp_path),
        lease_ms=2000, qp_budget=64)
    try:
        assert fleet_soak.wait_health(port, timeout_s=30)
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=10)
        proc = fleet_soak.spawn_coordinator(
            port, fleet_soak._free_port(), str(tmp_path),
            lease_ms=2000, qp_budget=64, restore=True)
        assert fleet_soak.wait_health(port, timeout_s=30)
    finally:
        proc.kill()
        proc.wait(timeout=10)


@pytest.mark.slow
def test_fleet_soak_verdict_ok(tmp_path):
    """The full autoscaling soak, verdict-gated: every acceptance bit
    the ISSUE names must hold in one run."""
    import json

    verdict = fleet_soak.run_fleet(rounds=6, lease_ms=2500,
                                   snapshot_dir=str(tmp_path))
    # Full verdict on stdout: pytest truncates dict reprs in assertion
    # messages, and a failed soak needs every gate visible.
    print(json.dumps(verdict, indent=1, default=str))
    assert verdict["ok"], verdict
    assert verdict["errors"] == {}
    assert verdict["parity"] is True
    assert verdict["resizes_served_on_metrics"] >= 2
    assert verdict["failovers_served_on_metrics"] >= 1
    assert verdict["generations_monotone"] is True
    assert verdict["fair_share"]["ok"] is True
    assert verdict["hb_threads_leaked"] == 0
    assert verdict["worlds_served"] >= 12
    assert verdict["pinned_names_scraped"] is True
