"""Fold-and-write-back exchange (post_send_foldback / post_recv_reduce).

The fused op behind the world-2 allreduce fast path: the receiver
folds the inbound payload into its buffer and the folded result lands
back in place over the sender's source, so one posted op replaces the
whole all-gather return phase. These tests pin down the op's contract
at the engine level and the ring-level equivalence of every schedule
(generic two-phase, fused two-stream, fused foldback) across tiers
(same-process CMA and the TCP stream tier).
"""

import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest


from rocnrdma_tpu.collectives.world import local_worlds
from rocnrdma_tpu.transport.engine import (
    DT_F32, Engine, RED_SUM, WC_LOC_ACCESS_ERR, loopback_pair)

PORT = 23100


def _run_ring_script(script: str, env: dict):
    """Run a fork-based two-rank ring script in a subprocess. These
    scripts allocate their ring port by bind-release-reuse(+100),
    which can collide with another listener under a busy full-suite
    run; retry once ONLY on that signature (bind failure) — any other
    failure is a real regression and must surface first time."""
    for _attempt in (0, 1):
        proc = subprocess.run([sys.executable, "-c", script], env=env,
                              capture_output=True, text=True, timeout=120)
        if proc.returncode == 0 or \
                "Address already in use" not in (proc.stderr or ""):
            break
    return proc


def _pair(engine, port):
    return loopback_pair(engine, port)


@pytest.fixture
def loop():
    e = Engine("emu")
    a, b = _pair(e, PORT + (os.getpid() % 500))
    yield e, a, b
    a.close()
    b.close()
    e.close()


def test_foldback_exchange_both_sides_identical(loop):
    e, a, b = loop
    x = np.arange(1000, dtype=np.float32)
    y = np.arange(1000, dtype=np.float32) * 3.0
    want = x + y
    with e.reg_mr(x) as xmr, e.reg_mr(y) as ymr:
        b.post_recv_reduce(ymr, 0, y.nbytes, DT_F32, RED_SUM, wr_id=7)
        a.post_send_foldback(xmr, 0, x.nbytes, wr_id=8)
        assert b.wait(7, 10000).ok
        assert a.wait(8, 10000).ok
        np.testing.assert_array_equal(y, want)   # receiver folded
        np.testing.assert_array_equal(x, want)   # sender got it back


def test_foldback_before_recv_posted_defers_ack(loop):
    e, a, b = loop
    x = np.ones(512, dtype=np.float32)
    y = np.full(512, 2.0, dtype=np.float32)
    with e.reg_mr(x) as xmr, e.reg_mr(y) as ymr:
        a.post_send_foldback(xmr, 0, x.nbytes, wr_id=1)
        # The ack must wait for the fold: no completion until the
        # peer posts its reduce recv.
        time.sleep(0.2)
        assert a.poll(1, timeout_ms=0) == []
        b.post_recv_reduce(ymr, 0, y.nbytes, DT_F32, RED_SUM, wr_id=2)
        assert b.wait(2, 10000).ok
        assert a.wait(1, 10000).ok
        np.testing.assert_array_equal(x, np.full(512, 3.0, np.float32))
        np.testing.assert_array_equal(y, np.full(512, 3.0, np.float32))


def test_foldback_into_plain_recv_errors_both_sides(loop):
    e, a, b = loop
    x = np.ones(64, dtype=np.float32)
    y = np.zeros(64, dtype=np.float32)
    with e.reg_mr(x) as xmr, e.reg_mr(y) as ymr:
        b.post_recv(ymr, 0, y.nbytes, wr_id=1)   # NOT a reduce recv
        a.post_send_foldback(xmr, 0, x.nbytes, wr_id=2)
        wb = b.wait(1, 10000)
        wa = a.wait(2, 10000)
        assert wb.status == WC_LOC_ACCESS_ERR
        assert not wa.ok
        np.testing.assert_array_equal(y, np.zeros(64, np.float32))


def _ring_allreduce_result(env, port, count=100003, world=2):
    """Run a world-rank in-process allreduce under env overrides and
    return the per-rank buffers."""
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        worlds = local_worlds(world, port)
        rng = np.random.default_rng(42)
        bufs = [rng.standard_normal(count).astype(np.float32)
                for _ in range(world)]
        ts = [threading.Thread(target=worlds[r].allreduce, args=(bufs[r],))
              for r in range(world)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        for w in worlds:
            w.close()
        return bufs
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


@pytest.mark.parametrize("world", [2, 3])
def test_ring_schedules_agree(world):
    """Generic step-barrier, wavefront, fused-two, and foldback
    schedules all produce the same sums."""
    port = 23600 + world * 40
    generic = _ring_allreduce_result(
        {"TDR_NO_FUSED2": "1", "TDR_NO_WAVEFRONT": "1"}, port, world=world)
    wave = _ring_allreduce_result(
        {"TDR_NO_FUSED2": "1", "TDR_NO_WAVEFRONT": ""}, port + 10,
        world=world)
    variants = [generic, wave]
    if world == 2:  # FusedTwo engages only at world == 2
        variants.append(_ring_allreduce_result(
            {"TDR_NO_FUSED2": "", "TDR_NO_FOLDBACK": "1",
             "TDR_NO_WAVEFRONT": "1"}, port + 20, world=world))
        variants.append(_ring_allreduce_result({}, port + 30, world=world))
    else:
        # Wavefront with last-RS-step foldback (the last all-gather
        # step replaced by the write-back), both transport tiers.
        variants.append(_ring_allreduce_result(
            {"TDR_NO_WAVE_FB": "1"}, port + 20, world=world))
        variants.append(_ring_allreduce_result(
            {"TDR_NO_CMA": "1"}, port + 30, world=world))
    want = None
    for bufs in variants:
        for b in bufs[1:]:
            np.testing.assert_allclose(bufs[0], b, rtol=0, atol=0)
        if want is None:
            want = bufs[0]
        np.testing.assert_allclose(bufs[0], want, rtol=1e-5, atol=1e-6)


def test_ring_foldback_stream_tier():
    """Foldback over the TCP stream tier (CMA disabled): the folded
    result rides back on the ack payload. The buffer deliberately
    exceeds the socket buffers and the ring chunk so blocking payload
    writes interleave with inbound ack payloads on both connections."""
    bufs = _ring_allreduce_result({"TDR_NO_CMA": "1"}, 23630,
                                  count=6 * (1 << 20) + 13)
    np.testing.assert_allclose(bufs[0], bufs[1], rtol=0, atol=0)


def test_foldback_env_mismatch_negotiates_down():
    """A rank with TDR_NO_FOLDBACK set must not wedge a peer without
    it: the capability is negotiated in the QP handshake, so a
    mismatched pair degrades to the wire-compatible schedule and the
    allreduce still completes correctly on both ranks."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    script = """
import os
import socket

import numpy as np

s = socket.socket(); s.bind(("127.0.0.1", 0))
base = s.getsockname()[1]; s.close()
count = (4 << 20) // 4

pid = os.fork()
rank = 1 if pid == 0 else 0
if rank == 1:
    os.environ["TDR_NO_FOLDBACK"] = "1"   # only this rank opts out
from rocnrdma_tpu.collectives.world import RingWorld
from rocnrdma_tpu.transport.engine import Engine

w = RingWorld(Engine("emu"), rank, 2, base + 100)
buf = np.full(count, float(rank + 1), dtype=np.float32)
w.allreduce(buf)
ok = bool(np.all(buf == 3.0))
w.close()
if pid == 0:
    os._exit(0 if ok else 1)
assert ok
_, status = os.waitpid(pid, 0)
assert os.waitstatus_to_exitcode(status) == 0
print("OK")
"""
    proc = _run_ring_script(script, env)
    assert proc.returncode == 0, (
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}")


def test_fused2_env_mismatch_negotiates_down():
    """A rank with TDR_NO_FUSED2 set must not wedge a peer without it.
    FusedTwo's schedule is wire-incompatible with the rightward
    schedules (phase-2 reduced-B chunks ride the LEFT QP), so entry is
    gated on the negotiated FEAT_FUSED2 bit: a mismatched pair must
    degrade BOTH ranks to the compatible schedule and still produce
    the correct sum."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    script = """
import os
import socket

import numpy as np

s = socket.socket(); s.bind(("127.0.0.1", 0))
base = s.getsockname()[1]; s.close()
count = (4 << 20) // 4

pid = os.fork()
rank = 1 if pid == 0 else 0
if rank == 1:
    os.environ["TDR_NO_FUSED2"] = "1"   # only this rank opts out
from rocnrdma_tpu.collectives.world import RingWorld
from rocnrdma_tpu.transport.engine import Engine

w = RingWorld(Engine("emu"), rank, 2, base + 100)
assert not w.right_qp.has_fused2  # negotiated OFF for both ends
buf = np.full(count, float(rank + 1), dtype=np.float32)
w.allreduce(buf)
ok = bool(np.all(buf == 3.0))
w.close()
if pid == 0:
    os._exit(0 if ok else 1)
assert ok
_, status = os.waitpid(pid, 0)
assert os.waitstatus_to_exitcode(status) == 0
print("OK")
"""
    proc = _run_ring_script(script, env)
    assert proc.returncode == 0, (
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}")


def test_foldback_bf16_bit_identical(loop):
    e, a, b = loop
    import ml_dtypes

    x = (np.arange(333) % 7).astype(ml_dtypes.bfloat16)
    y = (np.arange(333) % 5).astype(ml_dtypes.bfloat16)
    from rocnrdma_tpu.transport.engine import DT_BF16

    with e.reg_mr(x) as xmr, e.reg_mr(y) as ymr:
        b.post_recv_reduce(ymr, 0, y.nbytes, DT_BF16, RED_SUM, wr_id=1)
        a.post_send_foldback(xmr, 0, x.nbytes, wr_id=2)
        assert b.wait(1, 10000).ok
        assert a.wait(2, 10000).ok
    # One rounding, both sides bit-identical.
    np.testing.assert_array_equal(x.view(np.uint16), y.view(np.uint16))
