"""Link-health scoring and the degradation ladder (degrade, don't die).

Units pin the registry's contract — goodput floor, EWMA-vs-own-peak
scoring, streak-gated soft engagement vs immediate hard (fault)
engagement, heal hysteresis, delegate-only schedule steering, frozen
per-collective schedule verdicts, and the TDR_NO_DEGRADE escape hatch.

The two world-8 tests are the PR's acceptance pins: a hier chaos soak
with netem riders (delay + reorder + throttle) scoped to the delegate
links' a->b direction must degrade hier->flat with bitwise parity and
ZERO rebuilds, while the same brownout under TDR_NO_DEGRADE=1 must
escalate deadline -> probe verdict -> retryable error -> rebuild.
"""

import numpy as np
import pytest

from rocnrdma_tpu.collectives import health
from rocnrdma_tpu.transport.engine import (TransportError,
                                           fault_plan_clauses,
                                           fault_plan_hits,
                                           fault_plan_reset)
from rocnrdma_tpu.utils.trace import trace
from test_hier import KEYS8, hier_worlds, run_all

W = "hw"
MB = 1 << 20


@pytest.fixture(autouse=True)
def clean_health():
    health.reset()
    yield
    health.reset()


def _feed(link, mbps, n=1, peer=1, world=W):
    """n goodput samples at `mbps` on `link` (2 MiB phases — above the
    default observation floor)."""
    for _ in range(n):
        health.observe(world, link, peer, 2 * MB, (2 * MB / 1e6) / mbps)


# ------------------------------------------------------------- units


def test_observe_floor_ignores_small_phases(monkeypatch):
    """Phases below TDR_HEALTH_MIN_BYTES measure latency and scheduler
    jitter, not link bandwidth — they must not move the score."""
    monkeypatch.setenv("TDR_HEALTH_MIN_BYTES", str(MB))
    health.observe(W, "inter:r0", 1, 4096, 10.0)  # 0.0004 MB/s "link"
    assert health.snapshot(W) == {}
    assert health.score(W, "inter:r0") == 1.0
    health.observe(W, "inter:r0", 1, 2 * MB, 0.02)
    assert "inter:r0" in health.snapshot(W)


def test_score_is_relative_to_own_peak(monkeypatch):
    """No absolute MB/s threshold: the score is EWMA/peak, and the
    peak only chases the EWMA up — a faster phase redefines healthy,
    a slower one never does."""
    monkeypatch.setenv("TDR_HEALTH_ALPHA", "1.0")
    monkeypatch.setenv("TDR_HEALTH_ENGAGE_STREAK", "64")
    _feed("inter:r0", 100, n=3)
    assert health.score(W, "inter:r0") == 1.0
    _feed("inter:r0", 25)
    assert health.score(W, "inter:r0") == pytest.approx(0.25)
    _feed("inter:r0", 200)  # new sustained best
    assert health.score(W, "inter:r0") == 1.0
    _feed("inter:r0", 100)  # the old "healthy" is now half speed
    assert health.score(W, "inter:r0") == pytest.approx(0.5)


def test_streak_gates_soft_engagement(monkeypatch):
    """Soft (goodput) evidence engages a rung only after
    TDR_HEALTH_ENGAGE_STREAK consecutive below-threshold samples — one
    slow phase is scheduler noise — and a good sample resets the run.
    Healing needs threshold + TDR_HEALTH_HEAL (hysteresis)."""
    monkeypatch.setenv("TDR_HEALTH_ALPHA", "1.0")
    monkeypatch.setenv("TDR_HEALTH_ENGAGE_STREAK", "3")
    monkeypatch.setenv("TDR_HEALTH_WIRE", "0.75")
    monkeypatch.setenv("TDR_HEALTH_FALLBACK", "0.25")
    _feed("inter:r0", 100, n=3)
    _feed("inter:r0", 60, n=2)  # score 0.6 < 0.75, streak 2/3
    assert not health.wire_downgrade(W)
    _feed("inter:r0", 100)      # good sample resets the streak
    _feed("inter:r0", 60, n=2)
    assert not health.wire_downgrade(W)
    _feed("inter:r0", 60)       # third consecutive: engage
    assert health.wire_downgrade(W)
    assert not health.fallback_active(W)  # 0.6 > fallback rung
    assert health.degraded_total(W) == 1
    assert health.snapshot(W)["inter:r0"]["degraded"] == 1
    _feed("inter:r0", 100)      # 1.0 > 0.75 + heal margin: disengage
    assert not health.wire_downgrade(W)
    assert health.degraded_total(W) == 1  # monotone incident count


def test_fallback_rung_and_degraded_links(monkeypatch):
    monkeypatch.setenv("TDR_HEALTH_ALPHA", "1.0")
    monkeypatch.setenv("TDR_HEALTH_ENGAGE_STREAK", "1")
    _feed("inter:r2", 100, n=3, peer=6)
    _feed("inter:r2", 10, peer=6)  # 0.1: below all three rungs at once
    assert health.wire_downgrade(W)
    assert health.wire_int8(W)
    assert health.fallback_active(W)
    assert health.degraded_links(W) == {"inter:r2": 6}
    assert health.degraded_total(W) == 3  # every rung counted


def test_wire_verdict_frozen_per_collective(monkeypatch):
    """The wire rung's schedule_verdict twin: one frozen
    'f32'|'bf16'|'int8' answer per (world, collective seq). The int8
    rung swaps the wire SCHEDULE (scale-carrying q8 pieces), so a
    live read racing an engage/heal would split the delegates across
    mismatched schedules into a deadlock; freezing makes every rank
    replay the first asker's answer. TDR_NO_WIRE_Q8 gates the int8
    answer down to bf16 (the rung is only offered when the q8
    schedule is negotiable)."""
    monkeypatch.setenv("TDR_HEALTH_ALPHA", "1.0")
    monkeypatch.setenv("TDR_HEALTH_ENGAGE_STREAK", "1")
    _feed("inter:r0", 100, n=3)
    _feed("inter:r0", 55)  # 0.55: bf16 + int8 rungs, not fallback
    assert health.wire_int8(W) and health.wire_downgrade(W)
    assert not health.fallback_active(W)
    assert health.wire_verdict(W, 7) == "int8"
    monkeypatch.setenv("TDR_NO_WIRE_Q8", "1")
    assert health.wire_verdict(W, 8) == "bf16"  # q8 not negotiable
    monkeypatch.delenv("TDR_NO_WIRE_Q8")
    _feed("inter:r0", 100)  # heal: disengage both rungs
    assert not health.wire_int8(W)
    assert health.wire_verdict(W, 7) == "int8"  # frozen replay
    assert health.wire_verdict(W, 9) == "f32"   # fresh seq, healed


def test_intra_links_never_steer_schedule(monkeypatch):
    """Both rungs mitigate the DELEGATE link (bf16 applies to the
    inter payload; flat rides the intra links too), so a collapsed
    intra score is reported but never engages a rung."""
    monkeypatch.setenv("TDR_HEALTH_ALPHA", "1.0")
    monkeypatch.setenv("TDR_HEALTH_ENGAGE_STREAK", "1")
    _feed("intra:r1", 100, n=3, peer=-1)
    _feed("intra:r1", 5, n=4, peer=-1)
    assert not health.wire_downgrade(W)
    assert not health.fallback_active(W)
    assert health.degraded_links(W) == {}
    assert health.degraded_total(W) == 0
    # ...but observability keeps the evidence.
    assert health.snapshot(W)["intra:r1"]["score"] < 0.1
    health.fault(W, "intra:r1", -1, kind="stall")  # hard intra evidence
    assert not health.fallback_active(W)


def test_fault_hard_engages_without_history():
    """Hard evidence (stall/deadline/hung verdicts) engages
    immediately — no streak, no goodput history required — so a
    post-rebuild world comes back already degraded."""
    health.fault(W, "inter:r3", 7, kind="hung")
    assert health.fallback_active(W)
    assert health.wire_downgrade(W)
    assert health.degraded_links(W) == {"inter:r3": 7}
    snap = health.snapshot(W)["inter:r3"]
    assert snap["faults"] == 1 and snap["score"] == 0.0


def test_no_degrade_disables_ladder(monkeypatch):
    """TDR_NO_DEGRADE=1: scoring continues (observability) but no
    query reports an engaged rung and every schedule verdict is
    'hier' — failures escalate to deadline/probe/rebuild instead."""
    monkeypatch.setenv("TDR_NO_DEGRADE", "1")
    assert not health.ladder_enabled()
    health.fault(W, "inter:r0", 1, kind="stall")
    assert not health.fallback_active(W)
    assert not health.wire_downgrade(W)
    assert health.schedule_verdict(W, 8) == "hier"
    # The evidence is still recorded for /metrics and tdr_explain.
    assert health.snapshot(W)["inter:r0"]["degraded"] == 1


def test_schedule_verdict_frozen_and_canary_cadence(monkeypatch):
    """One verdict per (world, collective seq), frozen at first ask —
    rung state flipping mid-window must never split ranks across
    hier/flat — with every TDR_HEALTH_PROBE_EVERY-th candidate a
    'canary' that re-rides the sick link so the score can heal."""
    monkeypatch.setenv("TDR_HEALTH_ALPHA", "1.0")
    monkeypatch.setenv("TDR_HEALTH_PROBE_EVERY", "4")
    health.fault(W, "inter:r0", 1, kind="stall")
    assert health.schedule_verdict(W, 4) == "canary"
    assert health.schedule_verdict(W, 5) == "flat"
    assert health.schedule_verdict(W, 7) == "flat"
    assert health.schedule_verdict(W, 8) == "canary"
    # Heal the link: new seqs return to hier, frozen seqs replay.
    _feed("inter:r0", 100, n=2)
    assert not health.fallback_active(W)
    assert health.schedule_verdict(W, 5) == "flat"   # frozen
    assert health.schedule_verdict(W, 9) == "hier"   # fresh


# ---------------------------------------- world-8 chaos (acceptance)


@pytest.fixture(scope="module")
def world8():
    worlds = hier_worlds(8, KEYS8)
    try:
        yield worlds
    finally:
        for w in worlds:
            try:
                w.close()
            except Exception:
                pass


# Netem brownout scoped to the delegate links' a->b direction: the
# inter tier rings are the only stream-tier, rank-0->peer-1 links in
# the process (intra rings and the in-process flat ring ride the CMA
# tier), so the flat fallback path is completely clean — exactly the
# one-sick-delegate-direction scenario the ladder exists for.
CHAOS_PLAN = ("send:tier=stream:rank=0:peer=1:delay=2000:1000,"
              "send:tier=stream:rank=0:peer=1:reorder=4,"
              "send:tier=stream:rank=0:peer=1:throttle=8")


def _chaos_env(monkeypatch):
    """Ladder tuning sized to the soak: the inter shard is 512 KiB
    (floor below it), thresholds under the 2-4x scheduler jitter of
    in-process phase timings, 2-sample streak so a short brownout
    engages, aggressive canary cadence."""
    monkeypatch.setenv("TDR_HEALTH_MIN_BYTES", "262144")
    monkeypatch.setenv("TDR_HEALTH_WIRE", "0.6")
    monkeypatch.setenv("TDR_HEALTH_FALLBACK", "0.4")
    monkeypatch.setenv("TDR_HEALTH_ENGAGE_STREAK", "2")
    monkeypatch.setenv("TDR_HEALTH_PROBE_EVERY", "4")
    # The soak's contract is BITWISE parity through the ladder walk,
    # and its data is not in the int8-exact regime (absmax != 127, so
    # scale != 1 and quantization is lossy) — the q8 rung would trade
    # exactness for wire bytes by design. Disable it via its
    # documented off-switch; the three-rung walk (including int8) is
    # pinned by the brownout smoke in the exact regime instead.
    monkeypatch.setenv("TDR_NO_WIRE_Q8", "1")
    monkeypatch.delenv("TDR_COLL_DEADLINE_MS", raising=False)
    monkeypatch.delenv("TDR_NO_DEGRADE", raising=False)


def _sweep(worlds, data, expect, n):
    for _ in range(n):
        bufs = [data[r].copy() for r in range(len(worlds))]
        run_all(worlds, lambda r: worlds[r].allreduce(bufs[r],
                                                      algo="hier"))
        for r in range(len(worlds)):
            assert bufs[r].tobytes() == expect.tobytes(), f"rank {r}"


def test_world8_chaos_soak_degrades_without_rebuild(world8, monkeypatch):
    """The PR's headline gate: a world-8 hier soak with the delegate
    direction browned out (delay+reorder+throttle) keeps BITWISE
    parity with zero rebuilds — the ladder reroutes (hier->flat, bf16
    wire rung on the way down) instead of escalating."""
    _chaos_env(monkeypatch)
    wname = world8[0].world_name
    count = (2 * MB) // 4  # 2 MiB f32 -> 512 KiB inter shard
    rng = np.random.default_rng(7)
    data = rng.integers(-64, 64, (8, count)).astype(np.float32)
    expect = data.sum(axis=0)
    rebuilds0 = trace.counter("world.rebuild")
    degraded0 = trace.counter("algo.degraded")

    _sweep(world8, data, expect, 3)  # baseline: peaks define healthy
    assert not health.fallback_active(wname)

    monkeypatch.setenv("TDR_FAULT_PLAN", CHAOS_PLAN)
    fault_plan_reset()
    try:
        for _ in range(14):
            _sweep(world8, data, expect, 1)
            if health.fallback_active(wname):
                break
        hits = sum(fault_plan_hits(i)
                   for i in range(fault_plan_clauses()))
        assert hits > 0                       # riders actually fired
        assert health.fallback_active(wname)  # the ladder engaged
        # Only delegate links may steer the schedule.
        assert health.degraded_links(wname)
        assert all(l.startswith("inter")
                   for l in health.degraded_links(wname))
        # Degraded traffic: the flat reroute (plus canaries) carries
        # the same bits.
        _sweep(world8, data, expect, 2)
        assert trace.counter("algo.degraded") > degraded0
    finally:
        monkeypatch.delenv("TDR_FAULT_PLAN", raising=False)
        fault_plan_reset()
    assert trace.counter("world.rebuild") == rebuilds0  # ZERO rebuilds


def test_world8_no_degrade_escalates_to_rebuild(world8, monkeypatch):
    """The same brownout with the ladder disabled must walk the
    escalation ladder instead: the per-collective deadline fires, the
    probe classifies the peers alive-but-slow, every rank surfaces a
    RETRYABLE error, and the world rebuilds and carries traffic."""
    _chaos_env(monkeypatch)
    monkeypatch.setenv("TDR_NO_DEGRADE", "1")
    monkeypatch.setenv("TDR_COLL_DEADLINE_MS", "400")
    count = MB // 4  # 1 MiB f32 -> 256 KiB inter shard
    rng = np.random.default_rng(11)
    data = rng.integers(-64, 64, (8, count)).astype(np.float32)
    expect = data.sum(axis=0)
    rebuilds0 = trace.counter("world.rebuild")
    gen0 = world8[0].generation

    # Every delegate frame pays 700 ms (the four inter rings brown out
    # in parallel, so the wall cost is ~one frame): no inter ring can
    # finish inside the 400 ms deadline, and nothing ever stalls
    # outright (the peers stay alive — a SLOW fleet, not a dead one).
    monkeypatch.setenv("TDR_FAULT_PLAN", "send:tier=stream:delay=700000")
    fault_plan_reset()
    errs = [None] * 8

    def run(r):
        try:
            world8[r].allreduce(data[r].copy(), algo="hier")
        except TransportError as e:
            errs[r] = e

    try:
        run_all(world8, run)
    finally:
        monkeypatch.delenv("TDR_FAULT_PLAN", raising=False)
        fault_plan_reset()
    assert all(e is not None for e in errs), errs
    assert all(e.retryable for e in errs), errs
    msgs = " | ".join(str(e) for e in errs)
    assert "deadline exceeded" in msgs, msgs
    # Probe verdict: alive-but-slow, NOT hung and NOT a conn drop.
    assert "peer alive (slow link)" in msgs, msgs
    assert all(e.kind != "hung" for e in errs), msgs

    # The escalation's last rung: rebuild, then clean parity.
    monkeypatch.delenv("TDR_COLL_DEADLINE_MS", raising=False)
    run_all(world8, lambda r: world8[r].rebuild(
        max_attempts=8, backoff_s=0.05, timeout_ms=15000))
    assert [w.generation for w in world8] == [gen0 + 1] * 8
    assert trace.counter("world.rebuild") >= rebuilds0 + 8
    _sweep(world8, data, expect, 1)
