"""Verbs-backend integration tests (SoftRoCE or real HCA).

SURVEY.md §4 prescribes SoftRoCE (`rdma_rxe`) integration testing so
the verbs engine is exercised without special hardware. These tests
run the same lifecycle the emu tests pin down — registration, QP
bring-up, one-sided WRITE/READ, SEND/RECV, revocation — against
``Engine("verbs")`` over whatever RDMA device is present (a SoftRoCE
device created with ``rdma link add rxe0 type rxe netdev <if>`` works).

They SKIP when no RDMA device exists (e.g. this CI container has no
NETLINK_RDMA support, so rxe cannot be created); on an HCA- or
rxe-equipped host they run automatically.
"""

import os
import threading

import numpy as np
import pytest

from rocnrdma_tpu.transport.engine import (
    Engine, TransportError, WC_REM_ACCESS_ERR, loopback_pair)


def _verbs_engine():
    try:
        return Engine("verbs")
    except TransportError:
        return None


requires_rdma = pytest.mark.skipif(
    _verbs_engine() is None,
    reason="no RDMA device (install an HCA or create a SoftRoCE rxe dev)")

PORT = 24500 + (os.getpid() % 500)


@requires_rdma
def test_verbs_write_read_roundtrip():
    e = Engine("verbs")
    a, b = loopback_pair(e, PORT)
    src = np.arange(1 << 16, dtype=np.uint8)
    dst = np.zeros(1 << 16, dtype=np.uint8)
    smr, dmr = e.reg_mr(src), e.reg_mr(dst)
    a.post_write(smr, 0, dmr.addr, dmr.rkey, src.nbytes, wr_id=1)
    assert a.wait(1, 30000).ok
    np.testing.assert_array_equal(src, dst)
    back = np.zeros(1 << 16, dtype=np.uint8)
    with e.reg_mr(back) as bmr:
        a.post_read(bmr, 0, dmr.addr, dmr.rkey, back.nbytes, wr_id=2)
        assert a.wait(2, 30000).ok
        np.testing.assert_array_equal(back, dst)
    smr.deregister(); dmr.deregister()
    a.close(); b.close(); e.close()


@requires_rdma
def test_verbs_send_recv():
    e = Engine("verbs")
    a, b = loopback_pair(e, PORT + 1)
    msg = np.frombuffer(b"verbs hello", dtype=np.uint8).copy()
    inbox = np.zeros(64, dtype=np.uint8)
    with e.reg_mr(msg) as smr, e.reg_mr(inbox) as rmr:
        b.post_recv(rmr, 0, 64, wr_id=1)
        a.post_send(smr, 0, msg.nbytes, wr_id=2)
        assert b.wait(1, 30000).ok
        assert a.wait(2, 30000).ok
        assert bytes(inbox[:msg.nbytes]) == b"verbs hello"
    a.close(); b.close(); e.close()


@requires_rdma
def test_verbs_revocation():
    e = Engine("verbs")
    a, b = loopback_pair(e, PORT + 2)
    src = np.ones(4096, dtype=np.uint8)
    dst = np.zeros(4096, dtype=np.uint8)
    smr, dmr = e.reg_mr(src), e.reg_mr(dst)
    dmr.invalidate()
    a.post_write(smr, 0, dmr.addr, dmr.rkey, 4096, wr_id=1)
    wc = a.wait(1, 30000)
    assert wc.status == WC_REM_ACCESS_ERR or not wc.ok
    smr.deregister(); dmr.deregister()
    a.close(); b.close(); e.close()


@requires_rdma
def test_verbs_ring_allreduce():
    from rocnrdma_tpu.collectives.world import local_worlds

    worlds = local_worlds(2, PORT + 10, spec="verbs")
    bufs = [np.full(1 << 18, float(r + 1), dtype=np.float32)
            for r in range(2)]
    ts = [threading.Thread(target=worlds[r].allreduce, args=(bufs[r],))
          for r in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    for r in range(2):
        np.testing.assert_array_equal(bufs[r], np.full(1 << 18, 3.0,
                                                       np.float32))
    for w in worlds:
        w.close()
