"""Ring attention over the transport: exact parity vs full attention.

Each rank holds contiguous Q/K/V sequence shards; K/V rotate around
the ring over the emulated RDMA transport; the merged per-rank outputs
must equal the reference attention computed on the full gathered
sequence (the lse merge is exact, so tolerances are float-level).
"""

import threading

import numpy as np
import pytest

from test_transport import free_port


def _run_ring(world_size: int, causal: bool, h: int = 2, kvh: int = 2,
              s_local: int = 32, d: int = 16, dtype=np.float32):
    import jax.numpy as jnp

    from rocnrdma_tpu.collectives.ring_attention import RingAttention
    from rocnrdma_tpu.collectives.world import local_worlds
    from rocnrdma_tpu.ops.attention import attention_reference

    rng = np.random.default_rng(world_size * 10 + causal)
    S = world_size * s_local
    q_full = rng.standard_normal((1, h, S, d)).astype(dtype)
    k_full = rng.standard_normal((1, kvh, S, d)).astype(dtype)
    v_full = rng.standard_normal((1, kvh, S, d)).astype(dtype)

    worlds = local_worlds(world_size, free_port() + 400)
    outs = [None] * world_size
    errs = []

    def run_rank(r):
        try:
            ra = RingAttention(worlds[r], interpret=True)
            sl = slice(r * s_local, (r + 1) * s_local)
            outs[r] = np.asarray(ra(q_full[:, :, sl], k_full[:, :, sl],
                                    v_full[:, :, sl], causal=causal))
            ra.close()
        except Exception as e:  # noqa: BLE001 — surfaced to the test
            errs.append((r, e))

    ts = [threading.Thread(target=run_rank, args=(r,))
          for r in range(world_size)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    for w in worlds:
        w.close()
    assert not errs, errs

    got = np.concatenate(outs, axis=2).astype(np.float32)
    want = np.asarray(attention_reference(
        jnp.asarray(q_full), jnp.asarray(k_full), jnp.asarray(v_full),
        causal=causal)).astype(np.float32)
    tol = 2e-2 if np.dtype(dtype).itemsize == 2 else 2e-3
    np.testing.assert_allclose(got, want, rtol=tol, atol=tol)


def test_ring_attention_world2_causal():
    _run_ring(2, causal=True)


def test_ring_attention_world2_causal_bf16():
    """The production model dtype: the uint8 pack / in-place view
    unpack of the staging buffers must round-trip ml_dtypes bfloat16
    exactly (tolerances widened for bf16 compute)."""
    import ml_dtypes

    _run_ring(2, causal=True, dtype=ml_dtypes.bfloat16)


def test_ring_attention_world2_full():
    _run_ring(2, causal=False)


def test_ring_attention_world3_causal_gqa():
    """3 ranks, GQA (kvh < h): two rotations, block-triangular causal
    handling (full past shards, causal diagonal, skipped future)."""
    _run_ring(3, causal=True, h=4, kvh=2)


def test_ring_attention_world3_full_mqa():
    _run_ring(3, causal=False, h=4, kvh=1)


def test_ring_attention_world4_causal():
    """4 ranks: three rotations with the prefetch schedule (two kv
    transfers in flight across the double buffer at peak)."""
    _run_ring(4, causal=True, h=4, kvh=2, s_local=16)


def test_ring_attention_serial_schedule_parity(monkeypatch):
    """TDR_RA_NO_OVERLAP=1 (strictly serial rotate-then-compute) must
    produce the identical result — the overlap is a schedule change,
    not a numerics change."""
    monkeypatch.setenv("TDR_RA_NO_OVERLAP", "1")
    _run_ring(3, causal=True, h=4, kvh=2)


def test_ring_attention_charges_staging_and_reports_wait():
    """Every host bounce of the rotation (D2H of K/V, H2D of received
    shards) is charged to collectives.staging, and the call reports
    how long it blocked in transport waits (the overlap bench's raw
    material)."""
    from rocnrdma_tpu.collectives import staging as staging_mod
    from rocnrdma_tpu.collectives.ring_attention import RingAttention
    from rocnrdma_tpu.collectives.world import local_worlds

    rng = np.random.default_rng(3)
    world_size, s_local, h, d = 2, 16, 2, 16
    q = rng.standard_normal((1, h, world_size * s_local, d)).astype(
        np.float32)
    worlds = local_worlds(world_size, free_port() + 950)
    ras = [RingAttention(worlds[r], interpret=True)
           for r in range(world_size)]
    staging_mod.staging.reset()
    before = staging_mod.staging.bytes
    outs = [None] * world_size
    errs = []

    def go(r):
        try:
            sl = slice(r * s_local, (r + 1) * s_local)
            # causal=False: every rank attends every remote shard, so
            # the expected bounce count below is exact, not rank-
            # dependent.
            outs[r] = ras[r](q[:, :, sl], q[:, :, sl], q[:, :, sl],
                             causal=False)
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    ts = [threading.Thread(target=go, args=(r,))
          for r in range(world_size)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errs, errs
    kv_bytes = 2 * s_local * h * d * 4
    # Per rank: >= one D2H of its own K/V + one H2D per attended
    # remote shard.
    assert staging_mod.staging.bytes - before >= world_size * 2 * kv_bytes
    for ra in ras:
        assert ra.last_total_s > 0
        assert 0 <= ra.last_wait_s <= ra.last_total_s
        ra.close()
    for w in worlds:
        w.close()


def test_returned_gradients_do_not_alias_rotation_buffers():
    """The arrays backward() returns must be SNAPSHOTS: jax's CPU
    backend zero-copy-aliases 64-byte-aligned numpy memory (alignment
    of np.empty varies per allocation — which made the original bug a
    load-dependent flake), and the next call on the same instance
    zeroes and rotates those very bytes. Regression: zero the
    registered buffers after backward returns but BEFORE materializing
    the gradients; aliased returns would read zeros."""
    from rocnrdma_tpu.collectives.ring_attention import RingAttention
    from rocnrdma_tpu.collectives.world import local_worlds

    rng = np.random.default_rng(9)
    world_size, s_local, h, d = 2, 16, 2, 16
    S = world_size * s_local
    q = rng.standard_normal((1, h, S, d)).astype(np.float32)
    do = rng.standard_normal((1, h, S, d)).astype(np.float32)
    worlds = local_worlds(world_size, free_port() + 970)
    ras = [RingAttention(worlds[r], interpret=True)
           for r in range(world_size)]
    grads = [None] * world_size
    errs = []

    def go(r):
        try:
            sl = slice(r * s_local, (r + 1) * s_local)
            qs, dos = q[:, :, sl], do[:, :, sl]
            out, lse = ras[r].forward(qs, qs, qs, causal=True)
            g = ras[r].backward(qs, qs, qs, out, lse, dos, causal=True)
            # Clobber the rotation buffers while the returned arrays
            # are still unmaterialized — the hazard window.
            for b in ras[r]._bufs:
                b[:] = 0
            grads[r] = tuple(np.asarray(x).copy() for x in g)
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    ts = [threading.Thread(target=go, args=(r,))
          for r in range(world_size)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errs, errs
    for r in range(world_size):
        # dk/dv of a real shard can't be all-zero; aliased returns
        # would have read the zeroed buffer.
        assert np.any(grads[r][1] != 0), "dk aliased the zeroed buffer"
        assert np.any(grads[r][2] != 0), "dv aliased the zeroed buffer"
    for ra in ras:
        ra.close()
    for w in worlds:
        w.close()


def test_ring_attention_posts_only_work_requests():
    """Front-loaded registration (the reference invariant): after the
    first call, a second call registers nothing new — the rotation
    posts work requests against the same MRs."""
    from rocnrdma_tpu.collectives.ring_attention import RingAttention
    from rocnrdma_tpu.collectives.world import local_worlds

    rng = np.random.default_rng(0)
    worlds = local_worlds(2, free_port() + 600)
    ras = [RingAttention(worlds[r], interpret=True) for r in range(2)]
    q = rng.standard_normal((1, 2, 2 * 16, 16)).astype(np.float32)

    def call_both():
        outs = [None, None]

        def go(r):
            sl = slice(r * 16, (r + 1) * 16)
            outs[r] = ras[r](q[:, :, sl], q[:, :, sl], q[:, :, sl])

        ts = [threading.Thread(target=go, args=(r,)) for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        return outs

    call_both()
    mrs_before = [ra._mrs for ra in ras]
    o2 = call_both()
    assert all(ra._mrs is m for ra, m in zip(ras, mrs_before))
    assert all(np.isfinite(np.asarray(o)).all() for o in o2)
    for ra in ras:
        ra.close()
    for w in worlds:
        w.close()


@pytest.mark.parametrize("causal,dtype_name",
                         [(True, "float32"), (False, "float32"),
                          (True, "bfloat16")])
def test_ring_attention_backward_matches_reference_vjp(causal, dtype_name):
    """backward(): per-rank (dq, dk, dv) gathered across the ring must
    equal jax.vjp of the reference attention on the full sequence —
    the global-lse pair-gradient identity plus the homecoming
    accumulation rotation, end to end over the transport."""
    import jax
    import jax.numpy as jnp

    from rocnrdma_tpu.collectives.ring_attention import RingAttention
    from rocnrdma_tpu.collectives.world import local_worlds
    from rocnrdma_tpu.ops.attention import attention_reference

    import ml_dtypes

    dtype = {"float32": np.float32,
             "bfloat16": ml_dtypes.bfloat16}[dtype_name]
    world_size, s_local, h, kvh, d = 3, 32, 4, 2, 16
    rng = np.random.default_rng(7 + causal)
    S = world_size * s_local
    q_full = rng.standard_normal((1, h, S, d)).astype(dtype)
    k_full = rng.standard_normal((1, kvh, S, d)).astype(dtype)
    v_full = rng.standard_normal((1, kvh, S, d)).astype(dtype)
    do_full = rng.standard_normal((1, h, S, d)).astype(dtype)

    worlds = local_worlds(world_size, free_port() + 800)
    grads = [None] * world_size
    errs = []

    def run_rank(r):
        try:
            ra = RingAttention(worlds[r], interpret=True)
            sl = slice(r * s_local, (r + 1) * s_local)
            q, k, v = (q_full[:, :, sl], k_full[:, :, sl],
                       v_full[:, :, sl])
            out, lse = ra.forward(q, k, v, causal=causal)
            grads[r] = tuple(
                np.asarray(g) for g in ra.backward(
                    q, k, v, out, lse, do_full[:, :, sl], causal=causal))
            ra.close()
        except Exception as e:  # noqa: BLE001
            errs.append((r, e))

    ts = [threading.Thread(target=run_rank, args=(r,))
          for r in range(world_size)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    for w in worlds:
        w.close()
    assert not errs, errs

    def ref(q, k, v):
        return attention_reference(q, k, v, causal=causal)

    _, vjp = jax.vjp(ref, jnp.asarray(q_full), jnp.asarray(k_full),
                     jnp.asarray(v_full))
    dq_ref, dk_ref, dv_ref = (np.asarray(g)
                              for g in vjp(jnp.asarray(do_full)))
    dq = np.concatenate([g[0] for g in grads], axis=2).astype(np.float32)
    dk = np.concatenate([g[1] for g in grads], axis=2).astype(np.float32)
    dv = np.concatenate([g[2] for g in grads], axis=2).astype(np.float32)
    tol = 4e-2 if dtype_name == "bfloat16" else 2e-3
    np.testing.assert_allclose(dq, dq_ref.astype(np.float32),
                               rtol=tol, atol=tol)
    np.testing.assert_allclose(dk, dk_ref.astype(np.float32),
                               rtol=tol, atol=tol)
    np.testing.assert_allclose(dv, dv_ref.astype(np.float32),
                               rtol=tol, atol=tol)
