"""Hierarchical topology-aware allreduce (ROADMAP item 1).

World 8 emulated as TWO HOSTS via the host-key override
(``TDR_TOPOLOGY=a,a,a,a,b,b,b,b``): the two-tier schedule — intra-host
reduce-scatter → inter-host delegate-ring allreduce over the owned
shard → intra-host all-gather — must be BITWISE the flat ring's result
on exactly-representable sums, blocking and async-chained, across
dtypes, bucket splits, and the bf16 wire; the schedule digest must
diverge when the topology or the algorithm selector changes (and stay
byte-identical for legacy flat worlds); sealing must hold PER TIER
(CMA intra rings tag-only, the forced-stream inter rings full payload
CRC); and the standalone async reduce-scatter/all-gather primitives
must compose, in submission order, to the allreduce bit-for-bit.
"""

import hashlib
import os
import threading

import numpy as np
import pytest

from rocnrdma_tpu.collectives.topology import (TopologyMap, algo_stamp,
                                               choose_algo,
                                               hier_min_bytes,
                                               parse_env_topology,
                                               resolve_topology)
from rocnrdma_tpu.collectives.world import (RingWorld, auto_channel_cap,
                                            local_worlds)
from rocnrdma_tpu.transport.engine import TransportError

KEYS8 = ["a", "a", "a", "a", "b", "b", "b", "b"]


def port_band(span: int, lo: int = 21000, hi: int = 29000) -> int:
    """Bind-probe a CONTIGUOUS free port band below the ephemeral
    range. A hierarchical world listens across base..base+span (flat
    ring + per-group intra arenas + per-local-index inter arenas);
    taking base from an ephemeral free_port() invites a later bind in
    the span to collide with kernel-assigned client ports — the
    classic "one rank's listener stolen → digest hop wedges for the
    full stall deadline" flake. Probing the whole span in a quiet
    range makes the collision a retried probe, not a 30 s timeout."""
    import random
    import socket

    rng = random.Random()
    for _ in range(128):
        base = rng.randrange(lo, hi - span)
        socks = []
        try:
            for p in range(base, base + span):
                s = socket.socket()
                s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                s.bind(("127.0.0.1", p))
                socks.append(s)
            return base
        except OSError:
            continue
        finally:
            for s in socks:
                s.close()
    raise RuntimeError(f"no free {span}-port band in [{lo}, {hi})")


def hier_worlds(n, keys, channels=1, tries=3, **kwargs):
    """Bring up an n-rank multi-host-emulated world on a probed port
    band. The topology rides the EXPLICIT ``topology=`` parameter —
    never the process-wide TDR_TOPOLOGY env, which a mid-bring-up
    failure would leak into every other test's (differently-sized)
    worlds — and explicit topology also survives rebuild()'s
    re-resolution. Transient bring-up failures retry on a fresh
    band."""
    last = None
    for _ in range(tries):
        # Flat ring n ports + intra arenas n*hosts + inter arenas
        # local*hosts = n*(2 + hosts) worst-case span; pad a bit.
        base = port_band(n * 4 + 8)
        try:
            return local_worlds(n, base, channels=channels,
                                topology=list(keys), **kwargs)
        except (TransportError, TimeoutError, OSError) as e:
            last = e
    raise last


def run_all(worlds, fn):
    """Run fn(rank) on one thread per rank; re-raise the first error."""
    errs = [None] * len(worlds)

    def body(r):
        try:
            fn(r)
        except BaseException as e:  # surfaced after join
            errs[r] = e

    ts = [threading.Thread(target=body, args=(r,))
          for r in range(len(worlds))]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    for e in errs:
        if e is not None:
            raise e


@pytest.fixture(scope="module")
def world8():
    """A world-8 two-host-emulated ring, channels=1 (the suite must
    pass on core-starved CI; channel scaling is bench.py's subject).
    Module-scoped: bring-up of 8 ranks plus 2+4 tier rings is the
    expensive part, and every test here runs the same SPMD sequence
    on it. Explicit topology= (not env — unleakable) on a probed
    port band (not an ephemeral base — uncollidable), per the
    hier_worlds rationale."""
    worlds = hier_worlds(8, KEYS8)
    try:
        yield worlds
    finally:
        for w in worlds:
            try:
                w.close()
            except Exception:
                pass


# ------------------------------------------------------------- units


def test_topology_map_groups_and_delegate_rings():
    t = TopologyMap(KEYS8, rank=5)
    assert t.n_hosts == 2 and t.local_size == 4 and t.uniform
    assert t.hierarchical
    assert t.group == [4, 5, 6, 7] and t.local_rank == 1
    assert t.host_index == 1
    # Delegate ring for local index 1: rank 1 of every host.
    assert t.delegate_ring() == [1, 5]
    # Every rank derives the same host order (first appearance).
    assert TopologyMap(KEYS8, rank=0).hosts == t.hosts == ["a", "b"]
    # Non-hierarchical shapes: one host, singleton groups, uneven.
    assert not TopologyMap(["a"] * 4, 0).hierarchical
    assert not TopologyMap(["a", "b", "c", "d"], 0).hierarchical
    uneven = TopologyMap(["a", "a", "a", "b"], 0)
    assert not uneven.uniform and not uneven.hierarchical
    # The stamp fingerprints the key list (digest divergence input).
    assert TopologyMap(KEYS8, 0).stamp() != \
        TopologyMap(["a"] * 2 + ["b"] * 2 + ["c"] * 4, 0).stamp()


def test_parse_env_topology_rejects_wrong_length(monkeypatch):
    monkeypatch.setenv("TDR_TOPOLOGY", "a,a,b")
    with pytest.raises(ValueError):
        parse_env_topology(4)
    monkeypatch.setenv("TDR_TOPOLOGY", ",".join(KEYS8))
    assert parse_env_topology(8) == KEYS8
    monkeypatch.delenv("TDR_TOPOLOGY")
    assert parse_env_topology(8) is None


def test_choose_algo_size_switch_and_overrides(monkeypatch):
    topo = TopologyMap(KEYS8, 0)
    monkeypatch.delenv("TDR_ALGO", raising=False)
    monkeypatch.delenv("TDR_HIER_MIN_BYTES", raising=False)
    thr = hier_min_bytes()
    assert choose_algo(thr - 1, topo) == "flat"
    assert choose_algo(thr, topo) == "hier"
    # Flat topology never goes hier, whatever the size or override.
    assert choose_algo(thr * 16, None) == "flat"
    monkeypatch.setenv("TDR_ALGO", "hier")
    assert choose_algo(1, topo) == "hier"
    assert choose_algo(1 << 30, None) == "flat"
    monkeypatch.setenv("TDR_ALGO", "flat")
    assert choose_algo(1 << 30, topo) == "flat"
    monkeypatch.setenv("TDR_ALGO", "staged")
    assert choose_algo(1, topo) == "staged"
    monkeypatch.setenv("TDR_ALGO", "bogus")
    with pytest.raises(ValueError):
        choose_algo(1, topo)
    # The threshold moves the switch (and the digest term with it).
    monkeypatch.setenv("TDR_ALGO", "auto")
    monkeypatch.setenv("TDR_HIER_MIN_BYTES", "64")
    assert choose_algo(64, topo) == "hier"
    assert "64" in algo_stamp(topo)


def test_auto_channel_cap_divides_across_live_rings(monkeypatch):
    monkeypatch.setattr(os, "sched_getaffinity", lambda pid: set(range(8)))
    monkeypatch.delenv("TDR_RING_CHANNELS", raising=False)
    peers = ["127.0.0.1"] * 4  # 4 local ranks
    assert auto_channel_cap(peers, 0) == 2          # 8 cores / 4 local
    # Two concurrently live rings (intra + delegate): the budget
    # splits instead of each ring claiming cores/local independently.
    assert auto_channel_cap(peers, 0, rings=2) == 1
    assert auto_channel_cap(["h1", "h2"], 0, rings=2) == 4


def test_resolve_topology_sources(monkeypatch):
    monkeypatch.delenv("TDR_TOPOLOGY", raising=False)
    # No source -> flat; peer ADDRESSES are deliberately not one.
    assert resolve_topology(4, 0) is None
    # Coordinator view keys engage when nothing overrides.
    t = resolve_topology(4, 2, view_keys=["a", "a", "b", "b"])
    assert t is not None and t.hierarchical and t.host_index == 1
    # Explicit beats env; env beats view.
    monkeypatch.setenv("TDR_TOPOLOGY", "a,b,a,b")
    t = resolve_topology(4, 0, view_keys=["a", "a", "b", "b"])
    assert t.group == [0, 2]
    t = resolve_topology(4, 0, explicit=["x", "x", "y", "y"])
    assert t.group == [0, 1]


# ------------------------------------------- world-8 bitwise parity


@pytest.mark.parametrize("dtype", ["float32", "float64", "int32"])
def test_world8_hier_flat_staged_bitwise_parity(world8, dtype):
    """The three algorithms agree bit-for-bit on exactly-representable
    sums (small integers: every partial sum exact in every order), so
    the hierarchical re-association is invisible where it must be."""
    rng = np.random.default_rng(3)
    data = rng.integers(-100, 100, (8, 4099)).astype(dtype)  # odd len:
    expect = data.sum(axis=0).astype(dtype)  # remainder segments too
    results = {}
    for algo in ("flat", "hier", "staged"):
        bufs = [data[r].copy() for r in range(8)]
        run_all(world8,
                lambda r: world8[r].allreduce(bufs[r], algo=algo))
        assert all(np.array_equal(b, expect) for b in bufs), algo
        results[algo] = bufs[0].tobytes()
    assert results["hier"] == results["flat"] == results["staged"]


def test_world8_hier_async_chain_parity_and_census(world8):
    """Three buckets per rank launched back-to-back as chained async
    hier handles (phase submissions ordered across handles), waited in
    order — bitwise the blocking flat result; the handle-leak census
    returns to zero on every world including the tiers."""
    rng = np.random.default_rng(5)
    data = rng.integers(-50, 50, (8, 3, 2048)).astype(np.float32)
    flat = [[data[r, k].copy() for k in range(3)] for r in range(8)]
    for k in range(3):
        run_all(world8, lambda r: world8[r].allreduce(flat[r][k],
                                                      algo="flat"))
    hier = [[data[r, k].copy() for k in range(3)] for r in range(8)]

    def launch(r):
        hs = [world8[r].allreduce_async(hier[r][k], algo="hier")
              for k in range(3)]
        for h in hs:
            h.wait()

    run_all(world8, launch)
    for r in range(8):
        for k in range(3):
            assert hier[r][k].tobytes() == flat[0][k].tobytes()
    for w in world8:
        assert w.pending_async == 0
        for tier in (w._tier_intra, w._tier_inter):
            assert tier is not None and tier.pending_async == 0


def test_per_tier_sealing_and_tier_shape(world8):
    """After any hierarchical collective: the intra ring negotiated
    the CMA tier (tag-only — has_seal_payload False), the inter
    delegate ring is PINNED to the stream tier (full payload seals)
    even though every rank here is CMA-reachable; ring shapes match
    the topology map."""
    bufs = [np.ones(1024, dtype=np.float32) for _ in range(8)]
    run_all(world8, lambda r: world8[r].allreduce(bufs[r], algo="hier"))
    for r, w in enumerate(world8):
        intra, inter = w._tier_intra, w._tier_inter
        assert intra is not None and inter is not None
        assert intra.world == 4 and inter.world == 2
        assert intra.left_qp.has_seal and not \
            intra.left_qp.has_seal_payload
        assert inter.left_qp.has_seal and inter.left_qp.has_seal_payload
        assert w.topology.hierarchical
        assert intra.rank == w.topology.local_rank
        assert inter.rank == w.topology.host_index


# ------------------------------- async RS/AG first-class primitives


def test_rs_ag_async_submission_order_composes_to_allreduce():
    """World-4 flat ring: reduce_scatter_async + all_gather_async
    queued back-to-back (submission order IS the contract — the AG
    executes after the RS on the per-ring driver) compose bitwise to
    the blocking allreduce; owned_slice matches what the blocking
    reduce_scatter returns."""
    worlds = local_worlds(4, port_band(8), channels=1, topology="flat")
    try:
        rng = np.random.default_rng(11)
        data = rng.integers(-100, 100, (4, 2051)).astype(np.float32)
        ar = [data[r].copy() for r in range(4)]
        run_all(worlds, lambda r: worlds[r].allreduce(ar[r]))

        own_blocking = [None] * 4
        rs = [data[r].copy() for r in range(4)]
        run_all(worlds, lambda r: own_blocking.__setitem__(
            r, worlds[r].reduce_scatter(rs[r])))
        for r in range(4):
            assert worlds[r].owned_slice(rs[r]) == own_blocking[r]

        comp = [data[r].copy() for r in range(4)]

        def chain(r):
            h1 = worlds[r].reduce_scatter_async(comp[r])
            h2 = worlds[r].all_gather_async(comp[r])
            h1.wait()
            h2.wait()

        run_all(worlds, chain)
        for r in range(4):
            assert comp[r].tobytes() == ar[0].tobytes()
        assert all(w.pending_async == 0 for w in worlds)
    finally:
        for w in worlds:
            w.close()


# --------------------------------------------------- digest behavior


def _describe(world):
    from rocnrdma_tpu.collectives.jax_shim import CrossSliceAllReduce

    shim = CrossSliceAllReduce(world)
    return shim._sched_describe([], [], [], {}, 16 << 20, wire=None)


def test_digest_diverges_on_topology_and_algo(monkeypatch):
    """topo=/algo= join the schedule digest exactly when the world is
    hierarchical: a changed key list or TDR_ALGO mode changes the
    digest (fail-fast at the first collective), while a flat legacy
    world's describe string carries neither term — byte-identical to
    pre-hier digests."""
    monkeypatch.delenv("TDR_ALGO", raising=False)
    monkeypatch.setenv("TDR_TOPOLOGY", "a,a,b,b")
    worlds = local_worlds(4, port_band(24), channels=1)
    try:
        base = _describe(worlds[0])
        assert "topo=h2x2" in base and "algo=auto" in base
        monkeypatch.setenv("TDR_ALGO", "hier")
        d_hier = _describe(worlds[0])
        assert "algo=hier" in d_hier and d_hier != base
        monkeypatch.setenv("TDR_ALGO", "auto")
        monkeypatch.setenv("TDR_HIER_MIN_BYTES", "4096")
        assert _describe(worlds[0]) != base  # threshold moves digest
        monkeypatch.delenv("TDR_HIER_MIN_BYTES")
        # A different topology (same shape class) -> different digest.
        worlds[0].topology = TopologyMap(["x", "x", "y", "y"], 0)
        assert _describe(worlds[0]) != base
        assert hashlib.sha256(base.encode()).digest() != \
            hashlib.sha256(_describe(worlds[0]).encode()).digest()
    finally:
        for w in worlds:
            w.close()
    monkeypatch.delenv("TDR_TOPOLOGY")
    worlds = local_worlds(2, port_band(4), channels=1)
    try:
        legacy = _describe(worlds[0])
        assert "topo=" not in legacy and "algo=" not in legacy
    finally:
        for w in worlds:
            w.close()


# ------------------------------------------ overlap + bf16 wire path


@pytest.mark.parametrize("bucket_bytes", [None, 2048])
def test_world8_overlap_bf16_hier_vs_flat_bitwise(world8, monkeypatch,
                                                  bucket_bytes):
    """The acceptance pin: CrossSliceAllReduce(overlap=True) with
    TDR_WIRE_DTYPE=bf16 produces BITWISE identical trees under
    TDR_ALGO=hier and TDR_ALGO=flat at world 8 — one big bucket and a
    multi-bucket split (the chained hier handles ride the bucketed
    launch path). Inputs are small integers: exact in bf16 at every
    fold order, so re-association cannot hide behind tolerance."""
    from rocnrdma_tpu.collectives.jax_shim import CrossSliceAllReduce

    rng = np.random.default_rng(9)
    trees = [[rng.integers(-4, 5, 1500).astype(np.float32),
              rng.integers(-4, 5, 700).astype(np.float32)]
             for _ in range(8)]
    outs = {}
    for algo in ("flat", "hier"):
        monkeypatch.setenv("TDR_ALGO", algo)
        shims = [CrossSliceAllReduce(world8[r], overlap=True,
                                     bucket_bytes=bucket_bytes,
                                     wire_dtype="bf16")
                 for r in range(8)]
        res = [None] * 8

        def sync(r):
            res[r] = shims[r]([a.copy() for a in trees[r]])

        run_all(world8, sync)
        for s in shims:
            s.close()
        outs[algo] = res
    for r in range(8):
        for a, b in zip(outs["flat"][r], outs["hier"][r]):
            assert np.asarray(a).tobytes() == np.asarray(b).tobytes()
    assert all(w.pending_async == 0 for w in world8)


# --------------------------------------------------------- elasticity


def test_hier_rebuild_rebuilds_both_tiers_bitwise():
    """Tear-down mid-life surfaces retryable; rebuild() brings the
    flat ring AND both tier rings back under the bumped generation,
    and the hierarchical result is bitwise the pre-rebuild one."""
    worlds = hier_worlds(4, ["a", "a", "b", "b"])
    try:
        rng = np.random.default_rng(13)
        data = rng.integers(-100, 100, (4, 4096)).astype(np.float32)
        bufs = [data[r].copy() for r in range(4)]
        run_all(worlds, lambda r: worlds[r].allreduce(bufs[r],
                                                      algo="hier"))
        gen0 = worlds[0].generation
        assert worlds[0]._tier_gen == gen0
        # A torn-down incarnation fails hier collectives RETRYABLE
        # (the elastic ladder's entry condition), not AttributeError.
        worlds[0]._teardown()
        with pytest.raises(TransportError) as ei:
            worlds[0].allreduce(data[0].copy(), algo="hier")
        assert ei.value.retryable
        assert worlds[0]._tier_intra is None  # tiers died with it
        run_all(worlds, lambda r: worlds[r].rebuild(
            max_attempts=6, backoff_s=0.05))
        bufs2 = [data[r].copy() for r in range(4)]
        run_all(worlds, lambda r: worlds[r].allreduce(bufs2[r],
                                                      algo="hier"))
        assert worlds[0].generation == gen0 + 1
        assert worlds[0]._tier_gen == gen0 + 1
        for r in range(4):
            assert bufs2[r].tobytes() == bufs[0].tobytes()
    finally:
        for w in worlds:
            try:
                w.close()
            except Exception:
                pass


def test_coordinator_view_carries_host_keys():
    """Arbitrated worlds agree on the grouping through the released
    view: members report host keys at join, every slot's key comes
    back in ``host_keys``, and the member side resolves the same
    TopologyMap from them with no TDR_TOPOLOGY env at all."""
    from rocnrdma_tpu.control.coordinator import Coordinator
    from rocnrdma_tpu.transport.engine import Engine

    prev = os.environ.pop("TDR_TOPOLOGY", None)
    coord = Coordinator(port=0, lease_ms=4000,
                        port_base=port_band(64)).start()
    engines = [Engine("emu") for _ in range(4)]
    worlds = [None] * 4
    errs = [None] * 4
    keys = ["hostA", "hostA", "hostB", "hostB"]
    try:
        def boot(r):
            try:
                worlds[r] = RingWorld(
                    engines[r], r, 4, None, controller=coord.address,
                    world_name="hier", timeout_ms=20000, channels=1,
                    topology=keys)
            except BaseException as e:
                errs[r] = e

        ts = [threading.Thread(target=boot, args=(r,)) for r in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        for e in errs:
            if e is not None:
                raise e
        for r, w in enumerate(worlds):
            assert w._ctl_host_keys == keys
            assert w.topology is not None and w.topology.hierarchical
            assert w.topology.group == ([0, 1] if r < 2 else [2, 3])
        # Parity through the arbitrated, view-derived topology.
        rng = np.random.default_rng(17)
        data = rng.integers(-100, 100, (4, 2048)).astype(np.float32)
        expect = data.sum(axis=0)
        bufs = [data[r].copy() for r in range(4)]
        run_all(worlds, lambda r: worlds[r].allreduce(bufs[r],
                                                      algo="hier"))
        assert all(np.array_equal(b, expect) for b in bufs)
    finally:
        for w in worlds:
            if w is not None:
                try:
                    w.close()
                except Exception:
                    pass
        for e in engines:
            e.close()
        coord.stop()
        if prev is not None:
            os.environ["TDR_TOPOLOGY"] = prev


def test_fallback_reason_unit_shapes():
    """The deterministic fallback note, shape by shape: nothing to
    fall back from (no topology), a carryable topology, the remainder
    case, and all-singleton groups."""
    from rocnrdma_tpu.collectives.topology import fallback_reason

    assert fallback_reason(None) == ""
    assert fallback_reason(TopologyMap(["a", "a", "b", "b"], 0)) == ""
    assert fallback_reason(
        TopologyMap(["a", "a", "b"], 0)) == "nonuniform:h2:2x1"
    assert fallback_reason(TopologyMap(["a", "b"], 0)) == "singleton:h2"


def test_nonuniform_fallback_warn_once_and_digest_note():
    """The remainder case end to end: a RESOLVED 2-host topology with
    uneven groups (the post-uneven-shrink shape) cannot carry hier.
    Bring-up warns once per world object (``algo.fallback``), the
    schedule digest carries the deterministic fallback note — two
    ranks disagreeing on WHY they fell back must not agree — and
    collectives run flat and bitwise-correct."""
    from rocnrdma_tpu.utils.trace import trace

    before = trace.counter("algo.fallback")
    worlds = hier_worlds(3, ["a", "a", "b"])
    try:
        # Every brought-up world object warned exactly once (bring-up
        # retries construct fresh objects, so >= not ==).
        after_boot = trace.counter("algo.fallback")
        assert after_boot >= before + 3
        assert all(w._fallback_warned for w in worlds)
        for w in worlds:
            assert w.topology_stamp == "topo=fallback:nonuniform:h2:2x1"
            assert not w.topology.hierarchical
        bufs = [np.full(64, r + 1, np.float32) for r in range(3)]
        run_all(worlds, lambda r: worlds[r].allreduce(bufs[r]))
        want = np.full(64, 6.0, np.float32)
        for b in bufs:
            assert b.tobytes() == want.tobytes()
        # Warn-ONCE: further collectives never re-count the fallback.
        run_all(worlds, lambda r: worlds[r].allreduce(bufs[r]))
        assert trace.counter("algo.fallback") == after_boot
    finally:
        for w in worlds:
            w.close()
