"""Verbs-backend fused capabilities, exercised hardware-free.

The UNMODIFIED verbs engine (``verbs_engine.cc``) runs against the
in-process mock libibverbs provider (``mock_ibverbs.cc``) by pointing
``TDR_VERBS_LIB`` at it — the userspace analogue of the mock-kernel
harness that runs the kernel modules without a kernel. This closes the
gap SURVEY.md §4 flags in the reference (hardware-only testing): the
product path — capability negotiation in the rendezvous, staged
reduce-on-receive, the foldback reply protocol, and fused-schedule
selection — is pinned down by CI on machines with no HCA, and the same
engine binary talks to real hardware unchanged.
"""

import os
import subprocess
import sys
import threading

import numpy as np
import pytest

from rocnrdma_tpu.transport.engine import (
    DT_F32, Engine, RED_SUM, SCHED_FUSED2, SCHED_FUSED2_FB, SCHED_GENERIC,
    SCHED_WAVEFRONT, WC_REM_ACCESS_ERR, loopback_pair)

_NATIVE = os.path.join(os.path.dirname(__file__), os.pardir,
                       "rocnrdma_tpu", "native")
_MOCK_LIB = os.path.abspath(os.path.join(_NATIVE, "libmockibverbs.so"))

_port_counter = [25600 + (os.getpid() % 400)]


def _port():
    _port_counter[0] += 7
    return _port_counter[0]


@pytest.fixture(scope="module", autouse=True)
def mock_verbs():
    """Build the mock provider and point the verbs backend at it for
    this module only (restored afterwards so Engine("auto") elsewhere
    keeps preferring real hardware)."""
    subprocess.run(["make", "-s", "-C", os.path.abspath(_NATIVE), "mock",
                    "TUNE=native"], check=True, capture_output=True)
    old = os.environ.get("TDR_VERBS_LIB")
    os.environ["TDR_VERBS_LIB"] = _MOCK_LIB
    yield
    if old is None:
        os.environ.pop("TDR_VERBS_LIB", None)
    else:
        os.environ["TDR_VERBS_LIB"] = old


def _engine():
    return Engine("verbs:mock0")


def test_mock_engine_identity():
    e = _engine()
    assert e.name == "mock0"
    e.close()


def test_capabilities_negotiated():
    e = _engine()
    a, b = loopback_pair(e, _port())
    for qp in (a, b):
        assert qp.has_recv_reduce
        assert qp.has_send_foldback
        assert qp.has_fused2
    a.close(); b.close(); e.close()


def test_opt_out_degrades_both_ends(monkeypatch):
    """TDR_NO_FOLDBACK on one side must degrade the CONNECTION (both
    ends), exactly like the emu Hello — negotiation, not local state."""
    monkeypatch.setenv("TDR_NO_FOLDBACK", "1")
    e = _engine()
    a, b = loopback_pair(e, _port())
    for qp in (a, b):
        assert qp.has_recv_reduce  # local capability, not negotiated
        assert not qp.has_send_foldback
        assert qp.has_fused2
    a.close(); b.close(); e.close()


def test_write_read_send_recv_roundtrip():
    e = _engine()
    a, b = loopback_pair(e, _port())
    src = np.arange(1 << 16, dtype=np.uint8)
    dst = np.zeros(1 << 16, dtype=np.uint8)
    smr, dmr = e.reg_mr(src), e.reg_mr(dst)
    a.post_write(smr, 0, dmr.addr, dmr.rkey, src.nbytes, wr_id=1)
    assert a.wait(1, 10000).ok
    np.testing.assert_array_equal(src, dst)
    back = np.zeros(1 << 16, dtype=np.uint8)
    with e.reg_mr(back) as bmr:
        a.post_read(bmr, 0, dmr.addr, dmr.rkey, back.nbytes, wr_id=2)
        assert a.wait(2, 10000).ok
        np.testing.assert_array_equal(back, dst)
    msg = np.frombuffer(b"mock verbs hello", dtype=np.uint8).copy()
    inbox = np.zeros(64, dtype=np.uint8)
    with e.reg_mr(msg) as mmr, e.reg_mr(inbox) as imr:
        b.post_recv(imr, 0, 64, wr_id=3)
        a.post_send(mmr, 0, msg.nbytes, wr_id=4)
        assert b.wait(3, 10000).ok
        assert a.wait(4, 10000).ok
        assert bytes(inbox[:msg.nbytes]) == b"mock verbs hello"
    smr.deregister(); dmr.deregister()
    a.close(); b.close(); e.close()


def test_revocation_faults_remote_access():
    """MR invalidation on verbs is a real dereg: the MTT entry dies and
    remote access faults — the observable effect of the reference's
    free_callback → invalidate_peer_memory chain (amdp2p.c:88-109)."""
    e = _engine()
    a, b = loopback_pair(e, _port())
    src = np.ones(4096, dtype=np.uint8)
    dst = np.zeros(4096, dtype=np.uint8)
    smr, dmr = e.reg_mr(src), e.reg_mr(dst)
    dmr.invalidate()
    a.post_write(smr, 0, dmr.addr, dmr.rkey, 4096, wr_id=1)
    wc = a.wait(1, 10000)
    assert wc.status == WC_REM_ACCESS_ERR
    smr.deregister(); dmr.deregister()
    a.close(); b.close(); e.close()


def test_recv_reduce_folds_into_destination():
    """The staged fold: payload lands in an engine slot, then dst op=
    payload at completion time — dst must hold old + sent."""
    e = _engine()
    a, b = loopback_pair(e, _port())
    payload = np.arange(4096, dtype=np.float32)
    acc = np.full(4096, 10.0, dtype=np.float32)
    with e.reg_mr(payload) as pmr, e.reg_mr(acc) as amr:
        b.post_recv_reduce(amr, 0, acc.nbytes, DT_F32, RED_SUM, wr_id=1)
        a.post_send(pmr, 0, payload.nbytes, wr_id=2)
        assert b.wait(1, 10000).ok
        assert a.wait(2, 10000).ok
        np.testing.assert_array_equal(acc, payload + 10.0)
        # The sender's buffer is untouched by a plain send.
        np.testing.assert_array_equal(payload,
                                      np.arange(4096, dtype=np.float32))
    a.close(); b.close(); e.close()


def test_send_foldback_exchange():
    """Foldback: the receiver folds and replies with the folded bytes,
    which land IN PLACE over the sender's source; the sender's
    completion means both sides hold the folded result (tdr.h
    contract, same as the emu backend)."""
    e = _engine()
    a, b = loopback_pair(e, _port())
    src = np.arange(2048, dtype=np.float32)
    acc = np.full(2048, 5.0, dtype=np.float32)
    want = src + 5.0
    with e.reg_mr(src) as smr, e.reg_mr(acc) as amr:
        b.post_recv_reduce(amr, 0, acc.nbytes, DT_F32, RED_SUM, wr_id=1)
        a.post_send_foldback(smr, 0, src.nbytes, wr_id=2)
        assert b.wait(1, 10000).ok
        assert a.wait(2, 10000).ok
        np.testing.assert_array_equal(acc, want)
        np.testing.assert_array_equal(src, want)
    a.close(); b.close(); e.close()


def test_recv_reduce_oversize_payload_errors():
    e = _engine()
    a, b = loopback_pair(e, _port())
    payload = np.ones(1024, dtype=np.float32)
    acc = np.zeros(16, dtype=np.float32)
    with e.reg_mr(payload) as pmr, e.reg_mr(acc) as amr:
        b.post_recv_reduce(amr, 0, acc.nbytes, DT_F32, RED_SUM, wr_id=1)
        a.post_send(pmr, 0, payload.nbytes, wr_id=2)
        wc = b.wait(1, 10000)
        assert not wc.ok
        np.testing.assert_array_equal(acc, np.zeros(16, np.float32))
    a.close(); b.close(); e.close()


def test_recv_reduce_invalidate_before_landing_fails_recv():
    """Free-while-registered between post and landing (amdp2p.c:88-109):
    the fold must FAIL the recv — never write through the dead MR —
    and dereg with the recv still outstanding must not crash."""
    e = _engine()
    a, b = loopback_pair(e, _port())
    payload = np.ones(1024, dtype=np.float32)
    acc = np.zeros(1024, dtype=np.float32)
    pmr = e.reg_mr(payload)
    amr = e.reg_mr(acc)
    b.post_recv_reduce(amr, 0, acc.nbytes, DT_F32, RED_SUM, wr_id=1)
    amr.invalidate()
    a.post_send(pmr, 0, payload.nbytes, wr_id=2)
    wc = b.wait(1, 10000)
    assert not wc.ok
    np.testing.assert_array_equal(acc, np.zeros(1024, np.float32))
    amr.deregister()  # refs drained at completion; immediate free path
    pmr.deregister()
    a.close(); b.close(); e.close()


def _ring_allreduce(world, port, dtype=np.float32, n=1 << 16):
    from rocnrdma_tpu.collectives.world import local_worlds

    worlds = local_worlds(world, port, spec="verbs:mock0")
    bufs = [np.full(n, float(r + 1), dtype=dtype) for r in range(world)]
    errs = [None] * world

    def run(r):
        try:
            worlds[r].allreduce(bufs[r])
        except BaseException as exc:  # surfaced after join
            errs[r] = exc

    ts = [threading.Thread(target=run, args=(r,)) for r in range(world)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    for exc in errs:
        if exc is not None:
            raise exc
    expect = np.full(n, sum(range(1, world + 1)), dtype=dtype)
    for r in range(world):
        np.testing.assert_array_equal(bufs[r], expect)
    scheds = [w.ring.last_schedule for w in worlds]
    for w in worlds:
        w.close()
    return scheds


def test_ring_world2_selects_fused2_foldback():
    """VERDICT round-3 'done' criterion: the FusedTwo schedule (with
    foldback) is selected on a verbs ring, not just on emu."""
    scheds = _ring_allreduce(2, _port())
    assert scheds == [SCHED_FUSED2_FB, SCHED_FUSED2_FB]


def test_ring_world2_no_foldback_degrades_to_fused2(monkeypatch):
    monkeypatch.setenv("TDR_NO_FOLDBACK", "1")
    scheds = _ring_allreduce(2, _port())
    assert scheds == [SCHED_FUSED2, SCHED_FUSED2]


def test_ring_world2_no_fused2_degrades(monkeypatch):
    monkeypatch.setenv("TDR_NO_FUSED2", "1")
    scheds = _ring_allreduce(2, _port())
    # Without the fused2 agreement the ring falls back to the wavefront
    # (reduce-on-receive still negotiable locally), never to a wire
    # mismatch.
    assert scheds == [SCHED_WAVEFRONT, SCHED_WAVEFRONT]


def test_ring_world2_generic_schedule(monkeypatch):
    monkeypatch.setenv("TDR_NO_FUSED2", "1")
    monkeypatch.setenv("TDR_NO_WAVEFRONT", "1")
    scheds = _ring_allreduce(2, _port())
    assert scheds == [SCHED_GENERIC, SCHED_GENERIC]


def test_ring_world3_wavefront():
    scheds = _ring_allreduce(3, _port())
    assert scheds == [SCHED_WAVEFRONT] * 3


def test_ring_world4_chunked_wavefront(monkeypatch):
    """Multi-chunk wavefront on verbs: chunk smaller than the segment
    so the staged-slot window recycles (slots < chunks in flight)."""
    monkeypatch.setenv("TDR_RING_CHUNK", "4096")
    monkeypatch.setenv("TDR_VERBS_RR_WINDOW", "2")
    scheds = _ring_allreduce(4, _port(), n=1 << 15)
    assert scheds == [SCHED_WAVEFRONT] * 4


def test_ring_bf16_parity():
    import ml_dtypes

    from rocnrdma_tpu.collectives.world import local_worlds

    world, port = 2, _port()
    worlds = local_worlds(world, port, spec="verbs:mock0")
    rng = np.random.default_rng(7)
    f32 = [rng.normal(size=4096).astype(np.float32) for _ in range(world)]
    bufs = [x.astype(ml_dtypes.bfloat16) for x in f32]
    ts = [threading.Thread(target=worlds[r].allreduce, args=(bufs[r],))
          for r in range(world)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    # bf16 accumulates in f32 with one rounding at the end (TPU
    # semantics) — both ranks must agree bit-for-bit.
    want = (bufs[0].astype(np.float32)).view(np.uint16)
    np.testing.assert_array_equal(bufs[0].view(np.uint16),
                                  bufs[1].view(np.uint16))
    exact = (f32[0].astype(ml_dtypes.bfloat16).astype(np.float32) +
             f32[1].astype(ml_dtypes.bfloat16).astype(np.float32)
             ).astype(ml_dtypes.bfloat16)
    np.testing.assert_array_equal(bufs[0].view(np.uint16),
                                  exact.view(np.uint16))
    del want
    for w in worlds:
        w.close()


def test_verbs_emu_cross_backend_parity():
    """The same 2-rank workload on emu and mock-verbs produces
    identical bits — schedule-independent correctness."""
    from rocnrdma_tpu.collectives.world import local_worlds

    rng = np.random.default_rng(11)
    data = [rng.normal(size=8192).astype(np.float32) for _ in range(2)]
    results = {}
    for spec in ("emu", "verbs:mock0"):
        worlds = local_worlds(2, _port(), spec=spec)
        bufs = [d.copy() for d in data]
        ts = [threading.Thread(target=worlds[r].allreduce, args=(bufs[r],))
              for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        results[spec] = bufs
        for w in worlds:
            w.close()
    np.testing.assert_array_equal(results["emu"][0],
                                  results["verbs:mock0"][0])
    np.testing.assert_array_equal(results["emu"][1],
                                  results["verbs:mock0"][1])


def test_ring_alltoall_world2_direct_exchange_over_mock_verbs():
    """The world=2 all-to-all fast path (ONE foreign segment each way,
    received directly into place, only the outgoing segment staged)
    on the UNMODIFIED verbs engine against the mock provider. The
    general bundle path is covered at world=3 below; this pins the
    direct-exchange branch, which posts against a per-call MR pinned
    over just the received segment."""
    from rocnrdma_tpu.collectives.world import local_worlds

    worlds = local_worlds(2, _port(), spec="verbs:mock0")
    seg = 4099  # prime: stresses offset math
    def fill(r):
        return np.concatenate(
            [1000.0 * r + 10 * j + np.arange(seg) % 7
             for j in range(2)]).astype(np.float32)
    bufs = [fill(r) for r in range(2)]
    errs = [None, None]

    def run(r):
        try:
            worlds[r].all_to_all(bufs[r])
        except BaseException as exc:  # surfaced after join
            errs[r] = exc

    ts = [threading.Thread(target=run, args=(r,)) for r in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    for exc in errs:
        if exc is not None:
            raise exc
    for r in range(2):
        want = np.concatenate(
            [1000.0 * j + 10 * r + np.arange(seg) % 7
             for j in range(2)]).astype(np.float32)
        np.testing.assert_array_equal(bufs[r], want)
    for w in worlds:
        w.close()


def test_ring_alltoall_world2_cached_full_buffer_mr_over_mock_verbs():
    """Same exchange with a PRE-REGISTERED full-buffer MR
    (Ring.register_buffer): the direct-exchange path must take the
    cached-MR branch — receiving at the segment's offset inside the
    full-buffer registration instead of pinning per call — and stay
    correct across repeated (steady-state) exchanges."""
    from rocnrdma_tpu.collectives.world import local_worlds

    worlds = local_worlds(2, _port(), spec="verbs:mock0")
    seg = 2048
    bufs = [np.zeros(2 * seg, dtype=np.float32) for _ in range(2)]
    for r in range(2):
        worlds[r].ring.register_buffer(bufs[r])  # front-loaded MR

    for round_no in range(2):  # steady-state reuse of the cached MR
        for r in range(2):
            for j in range(2):
                bufs[r][j * seg:(j + 1) * seg] = (
                    100.0 * r + 10 * j + round_no
                    + np.arange(seg) % 5)
        errs = [None, None]

        def run(r):
            try:
                worlds[r].all_to_all(bufs[r])
            except BaseException as exc:  # surfaced after join
                errs[r] = exc

        ts = [threading.Thread(target=run, args=(r,)) for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        for exc in errs:
            if exc is not None:
                raise exc
        for r in range(2):
            for j in range(2):
                want = (100.0 * j + 10 * r + round_no
                        + np.arange(seg) % 5).astype(np.float32)
                np.testing.assert_array_equal(
                    bufs[r][j * seg:(j + 1) * seg], want)
    for r in range(2):
        worlds[r].ring.unregister_buffer(bufs[r])
    for w in worlds:
        w.close()


def test_ring_alltoall_over_mock_verbs():
    """The all-to-all's ChainPump send/recv path is engine-agnostic:
    the same segment-transpose contract holds with the UNMODIFIED
    verbs engine talking to the mock provider (two-sided SEND/RECV
    bundles, no fused capabilities involved)."""
    from rocnrdma_tpu.collectives.world import local_worlds

    world = 3
    worlds = local_worlds(world, _port(), spec="verbs:mock0")
    seg = 4099  # prime: stresses offset math
    def fill(r):
        return np.concatenate(
            [1000.0 * r + 10 * j + np.arange(seg) % 5
             for j in range(world)]).astype(np.float32)
    bufs = [fill(r) for r in range(world)]
    errs = [None] * world

    def run(r):
        try:
            worlds[r].all_to_all(bufs[r])
        except BaseException as exc:  # surfaced after join
            errs[r] = exc

    ts = [threading.Thread(target=run, args=(r,)) for r in range(world)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    for exc in errs:
        if exc is not None:
            raise exc
    for r in range(world):
        want = np.concatenate(
            [1000.0 * j + 10 * r + np.arange(seg) % 5
             for j in range(world)]).astype(np.float32)
        np.testing.assert_array_equal(bufs[r], want)
    for w in worlds:
        w.close()
