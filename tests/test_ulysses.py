"""Ulysses sequence parallelism over the transport all-to-all.

The head<->sequence resharding (``collectives/ulysses.py``) rides
``tdr_ring_alltoall``; each rank's attention output and gradients for
its contiguous sequence shard must equal the reference computed on
the full gathered sequence — both resharding all-to-alls and the
local flash kernel are exact, so tolerances are float-level.
"""

import threading

import numpy as np
import pytest

from test_transport import free_port


def _run(world_size: int, causal: bool, with_grads: bool,
         h: int = 8, kvh: int = 4, s_local: int = 24, d: int = 16,
         dtype=np.float32):
    import jax
    import jax.numpy as jnp

    from rocnrdma_tpu.collectives.staging import staging
    from rocnrdma_tpu.collectives.ulysses import UlyssesAttention
    from rocnrdma_tpu.collectives.world import local_worlds
    from rocnrdma_tpu.ops.attention import attention_reference

    rng = np.random.default_rng(world_size * 100 + causal)
    S = world_size * s_local
    q_full = rng.standard_normal((1, h, S, d)).astype(dtype)
    k_full = rng.standard_normal((1, kvh, S, d)).astype(dtype)
    v_full = rng.standard_normal((1, kvh, S, d)).astype(dtype)
    do_full = rng.standard_normal((1, h, S, d)).astype(dtype)

    worlds = local_worlds(world_size, free_port() + 500)
    staging.reset()
    outs = [None] * world_size
    grads = [None] * world_size
    errs = []

    def run_rank(r):
        try:
            ua = UlyssesAttention(worlds[r], interpret=True)
            sl = slice(r * s_local, (r + 1) * s_local)
            q, k, v = (q_full[:, :, sl], k_full[:, :, sl],
                       v_full[:, :, sl])
            outs[r] = np.asarray(ua.forward(q, k, v, causal=causal))
            if with_grads:
                dq, dk, dv = ua.backward(q, k, v, do_full[:, :, sl],
                                         causal=causal)
                grads[r] = tuple(np.asarray(g) for g in (dq, dk, dv))
            ua.close()
        except Exception as e:  # noqa: BLE001 — surfaced to the test
            errs.append((r, e))

    ts = [threading.Thread(target=run_rank, args=(r,))
          for r in range(world_size)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    for w in worlds:
        w.close()
    assert not errs, errs
    assert staging.bytes > 0  # every host bounce is accounted

    got = np.concatenate(outs, axis=2).astype(np.float32)

    def ref(q, k, v):
        return attention_reference(q, k, v, causal=causal)

    want = np.asarray(ref(jnp.asarray(q_full), jnp.asarray(k_full),
                          jnp.asarray(v_full))).astype(np.float32)
    tol = 2e-2 if np.dtype(dtype).itemsize == 2 else 2e-3
    np.testing.assert_allclose(got, want, rtol=tol, atol=tol)

    if with_grads:
        _, pull = jax.vjp(ref, jnp.asarray(q_full), jnp.asarray(k_full),
                          jnp.asarray(v_full))
        wq, wk, wv = (np.asarray(g).astype(np.float32)
                      for g in pull(jnp.asarray(do_full)))
        gq = np.concatenate([g[0] for g in grads], axis=2)
        gk = np.concatenate([g[1] for g in grads], axis=2)
        gv = np.concatenate([g[2] for g in grads], axis=2)
        np.testing.assert_allclose(gq.astype(np.float32), wq,
                                   rtol=tol, atol=tol)
        np.testing.assert_allclose(gk.astype(np.float32), wk,
                                   rtol=tol, atol=tol)
        np.testing.assert_allclose(gv.astype(np.float32), wv,
                                   rtol=tol, atol=tol)


@pytest.mark.parametrize("world_size", [2, 4])
@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_forward_parity(world_size, causal):
    """Per-rank outputs equal full-sequence reference attention
    (GQA heads; head count divides the world)."""
    _run(world_size, causal, with_grads=False)


@pytest.mark.parametrize("world_size", [2, 4])
def test_ulysses_grads_match_full_vjp(world_size):
    """backward()'s resharded (dq, dk, dv) equal the jax.vjp of the
    full-sequence reference, causal."""
    _run(world_size, causal=True, with_grads=True)


def test_ulysses_bf16():
    """bf16 tensors ride the byte-semantics staging buffer."""
    import jax.numpy as jnp  # noqa: F401 — jax import guards the env

    import ml_dtypes

    _run(2, causal=True, with_grads=False, dtype=ml_dtypes.bfloat16)


def test_ulysses_rejects_indivisible_heads():
    from rocnrdma_tpu.collectives.ulysses import UlyssesAttention
    from rocnrdma_tpu.collectives.world import local_worlds

    worlds = local_worlds(2, free_port() + 600)
    try:
        ua = UlyssesAttention(worlds[0], interpret=True)
        q = np.zeros((1, 3, 8, 4), np.float32)  # 3 heads, world 2
        with pytest.raises(ValueError, match="divide"):
            ua.forward(q, q, q)
        ua.close()
    finally:
        for w in worlds:
            w.close()
