"""Sanitizer build gate (native/Makefile `make sanitize`).

Tier-1 carries only a cheap smoke that the target stamps the right
flags (.buildflags_san — no compilation); the slow tier rebuilds
libtdr_san.so under ASan+UBSan and runs a world-2 SEALED ring
allreduce under it, so the whole seal/NAK/retransmit machinery gets a
memory-error and UB sweep on every slow run.
"""

import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE = os.path.join(REPO, "rocnrdma_tpu", "native")


def test_sanitize_target_stamps_flags():
    """Cheap tier-1 smoke: the sanitize flag stamp carries the ASan +
    UBSan + frame-pointer flags the slow-tier build compiles with."""
    subprocess.run(["make", "-s", "-C", NATIVE, ".buildflags_san"],
                   check=True, capture_output=True)
    with open(os.path.join(NATIVE, ".buildflags_san")) as f:
        stamp = f.read()
    assert "-fsanitize=address,undefined" in stamp
    assert "-fno-omit-frame-pointer" in stamp


def _libasan_path():
    gcc = shutil.which("gcc")
    if not gcc:
        return None
    out = subprocess.run([gcc, "-print-file-name=libasan.so"],
                         capture_output=True, text=True)
    path = out.stdout.strip()
    return path if path and os.path.isabs(path) and os.path.exists(path) \
        else None


_SAN_SCRIPT = """
import os, socket, threading
import numpy as np
from rocnrdma_tpu import telemetry
from rocnrdma_tpu.collectives.world import local_worlds
from rocnrdma_tpu.transport.engine import native_counters
s = socket.socket(); s.bind(("127.0.0.1", 0))
port = s.getsockname()[1]; s.close()
worlds = local_worlds(2, port)
assert worlds[0].left_qp.has_seal, "seal must be on under the sanitizer"
bufs = [np.full(65536, float(r + 1), dtype=np.float32) for r in range(2)]
ts = [threading.Thread(target=worlds[r].allreduce, args=(bufs[r],))
      for r in range(2)]
[t.start() for t in ts]; [t.join() for t in ts]
for b in bufs:
    np.testing.assert_array_equal(b, np.full(65536, 3.0, np.float32))
# Second pass: the SHARDED progress engine over the windowed-scratch
# schedule (TDR_PROGRESS_SHARDS=2 is in the env; the 32 KiB ring
# chunk makes the runs big enough in chunks to engage the shards) —
# the per-channel locks, the one-condvar watermark hub, shard
# spawn/join, and the fold workers all get the ASan+UBSan sweep.
os.environ["TDR_NO_RECV_REDUCE"] = "1"
bufs = [np.full(65536, float(r + 1), dtype=np.float32) for r in range(2)]
ts = [threading.Thread(target=worlds[r].allreduce, args=(bufs[r],))
      for r in range(2)]
[t.start() for t in ts]; [t.join() for t in ts]
for b in bufs:
    np.testing.assert_array_equal(b, np.full(65536, 3.0, np.float32))
assert native_counters()["progress.wc"] > 0, \\
    "sharded progress engine never engaged under the sanitizer"
# Telemetry ran under ASan+UBSan too (TDR_TELEMETRY=1 in the env):
# the recorder must have captured the run, and drain + export must be
# clean under the sanitizer as well.
assert telemetry.enabled(), "telemetry must be on under the sanitizer"
events = telemetry.timeline()
assert any(e.name == "wc" for e in events), "no native events recorded"
telemetry.export_trace("/dev/null", events=events)
for w in worlds:
    w.close()
print("SAN_WORLD2_OK")
"""


@pytest.mark.slow
def test_sanitized_sealed_world2_allreduce():
    """Rebuild libtdr.so under ASan+UBSan and drive a world-2 sealed
    ring allreduce through it in a subprocess (ASan must be the first
    DSO, hence LD_PRELOAD). Any heap error aborts; any UBSan report
    fails the assertion on output."""
    libasan = _libasan_path()
    if libasan is None:
        pytest.skip("no gcc/libasan on this host")
    build = subprocess.run(["make", "-s", "-C", NATIVE, "sanitize"],
                           capture_output=True, text=True, timeout=600)
    assert build.returncode == 0, build.stderr[-2000:]
    env = dict(os.environ)
    env.update({
        "LD_PRELOAD": libasan,
        # abort_on_error surfaces ASan findings as a non-zero exit
        # even where the default exit path is swallowed; leak checking
        # is off (the CPython interpreter's arenas drown the signal).
        "ASAN_OPTIONS": "detect_leaks=0,abort_on_error=1",
        "UBSAN_OPTIONS": "print_stacktrace=1",
        "TDR_NATIVE_LIB": os.path.join(NATIVE, "libtdr_san.so"),
        "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
        "JAX_PLATFORMS": "cpu",
        # Run the flight recorder's event paths under the sanitizer
        # too — every emit/drain/histogram touch gets swept.
        "TDR_TELEMETRY": "1",
        # Force the sharded progress engine + fold workers (both
        # auto-degrade to 0 on the 1-core CI class) and a chunk size
        # small enough that the 256 KiB test buffer spans several
        # chunks per phase — the shard spawn/poll/join machinery must
        # actually run under the sanitizer, not gate itself off.
        "TDR_PROGRESS_SHARDS": "2",
        "TDR_FOLD_THREADS": "2",
        "TDR_RING_CHUNK": "32768",
    })
    run = subprocess.run([sys.executable, "-c", _SAN_SCRIPT],
                         capture_output=True, text=True, timeout=300,
                         env=env)
    out = run.stdout + run.stderr
    assert run.returncode == 0, out[-3000:]
    assert "SAN_WORLD2_OK" in out, out[-3000:]
    assert "runtime error" not in out, out[-3000:]   # UBSan reports
    assert "AddressSanitizer" not in out, out[-3000:]
