"""Llama model + sharded trainer tests (virtual 8-device CPU mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rocnrdma_tpu.models.llama import (
    CONFIGS, LLAMA3_8B, LLAMA_TINY, cross_entropy_loss, init_params,
    make_model)
from rocnrdma_tpu.parallel.trainer import Trainer


def test_model_forward_shapes():
    model = make_model("llama-tiny")
    params = init_params(model, jax.random.PRNGKey(0))
    tokens = jnp.ones((2, 16), dtype=jnp.int32)
    logits = model.apply(params, tokens)
    assert logits.shape == (2, 16, model.cfg.vocab_size)
    assert logits.dtype == jnp.float32


def test_model_is_causal():
    """Changing a future token must not change earlier logits."""
    model = make_model("llama-tiny")
    params = init_params(model, jax.random.PRNGKey(0))
    t1 = jnp.zeros((1, 16), dtype=jnp.int32)
    t2 = t1.at[0, 12].set(7)
    l1 = model.apply(params, t1)
    l2 = model.apply(params, t2)
    np.testing.assert_allclose(np.asarray(l1[0, :12]),
                               np.asarray(l2[0, :12]), atol=1e-5)
    assert not np.allclose(np.asarray(l1[0, 12:]), np.asarray(l2[0, 12:]))


def test_flagship_config_matches_llama3_8b():
    """The flagship geometry is Meta-Llama-3-8B (BASELINE.md config 4)."""
    assert LLAMA3_8B.d_model == 4096
    assert LLAMA3_8B.n_layers == 32
    assert LLAMA3_8B.n_heads == 32 and LLAMA3_8B.n_kv_heads == 8
    assert LLAMA3_8B.d_ff == 14336
    assert LLAMA3_8B.vocab_size == 128256
    # ~8.03B params
    assert 7.9e9 < LLAMA3_8B.param_count() < 8.2e9


def test_model_with_pallas_kernels_matches_xla():
    import dataclasses

    cfg = dataclasses.replace(
        LLAMA_TINY, use_pallas_attention=True, use_pallas_rmsnorm=True,
        pallas_interpret=True)
    model_p = make_model(cfg)
    model_x = make_model("llama-tiny")
    params = init_params(model_x, jax.random.PRNGKey(0))
    tokens = jnp.arange(32, dtype=jnp.int32).reshape(1, 32) % 256
    lp = model_p.apply(params, tokens)
    lx = model_x.apply(params, tokens)
    np.testing.assert_allclose(np.asarray(lp), np.asarray(lx),
                               rtol=2e-3, atol=2e-3)


def test_trainer_single_device_loss_decreases():
    tr = Trainer("llama-tiny", {"dp": 1, "tp": 1}, learning_rate=1e-2)
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 255, (4, 33)).astype(np.int32)
    losses = [tr.step(tokens) for _ in range(5)]
    assert losses[-1] < losses[0]


def test_trainer_dp_tp_mesh():
    """dp=2 × tp=4 over the virtual 8-device CPU mesh: the full
    sharded train step compiles and runs (XLA inserts the ICI
    collectives from the shardings)."""
    assert len(jax.devices()) >= 8
    tr = Trainer("llama-tiny", {"dp": 2, "tp": 4})
    rng = np.random.default_rng(1)
    tokens = rng.integers(0, 255, (8, 17)).astype(np.int32)
    l0 = tr.step(tokens)
    l1 = tr.step(tokens)
    assert np.isfinite(l0) and np.isfinite(l1)
    # params stay sharded per the spec
    wq = tr.params["params"]["layer_0"]["attn"]["wq"]["kernel"]
    assert not wq.sharding.is_fully_replicated


def test_trainer_dp_matches_single_device():
    """dp=2 must produce the same loss trajectory as dp=1 on the same
    global batch (data parallelism is a numerical no-op)."""
    rng = np.random.default_rng(2)
    tokens = rng.integers(0, 255, (4, 17)).astype(np.int32)
    tr1 = Trainer("llama-tiny", {"dp": 1, "tp": 1}, seed=3)
    tr2 = Trainer("llama-tiny", {"dp": 2, "tp": 1}, seed=3)
    for _ in range(3):
        l1 = tr1.step(tokens)
        l2 = tr2.step(tokens)
        assert abs(l1 - l2) < 1e-4, (l1, l2)


def test_two_slice_dp_training_over_transport():
    """The config-4 story in miniature: two 'slices' (each its own
    Trainer/mesh) training the same model, gradients averaged across
    slices via the RDMA-path ring allreduce each step. Both slices
    must stay bit-identical to each other and match a single trainer
    on the combined batch."""
    import threading

    from rocnrdma_tpu.collectives.jax_shim import CrossSliceAllReduce
    from rocnrdma_tpu.collectives.world import local_worlds

    from test_transport import free_port

    worlds = local_worlds(2, free_port() + 200)
    rng = np.random.default_rng(4)
    batches = [rng.integers(0, 255, (2, 17)).astype(np.int32)
               for _ in range(2)]

    trainers = [
        Trainer("llama-tiny", {"dp": 1, "tp": 1}, seed=5,
                cross_slice_sync=CrossSliceAllReduce(worlds[r], mean=True))
        for r in range(2)
    ]
    combined = Trainer("llama-tiny", {"dp": 1, "tp": 1}, seed=5)

    losses = [[], []]

    def run_slice(r):
        for _ in range(2):
            losses[r].append(trainers[r].step(batches[r]))

    ts = [threading.Thread(target=run_slice, args=(r,)) for r in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()

    ref_losses = [combined.step(np.concatenate(batches, axis=0))
                  for _ in range(2)]

    # Cross-slice mean of grads == grads of the combined batch, so the
    # trajectories agree (up to float reassociation).
    mean_slice_losses = [float(np.mean([losses[0][i], losses[1][i]]))
                         for i in range(2)]
    for got, want in zip(mean_slice_losses, ref_losses):
        assert abs(got - want) < 5e-3, (mean_slice_losses, ref_losses)

    # Slices stay in lockstep: identical params after sync'd steps.
    p0 = jax.tree_util.tree_leaves(trainers[0].params)
    p1 = jax.tree_util.tree_leaves(trainers[1].params)
    for a, b in zip(p0, p1):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-6)
    for w in worlds:
        w.close()


def test_checkpoint_save_restore(tmp_path):
    """Save → perturb → restore round-trips params, opt state, and step
    (checkpoint/resume is absent in the reference, SURVEY.md §5; the
    training consumer needs it)."""
    from rocnrdma_tpu.parallel.checkpoint import (
        restore_checkpoint, save_checkpoint)

    tr = Trainer("llama-tiny", {"dp": 1, "tp": 1}, seed=7)
    rng = np.random.default_rng(7)
    tokens = rng.integers(0, 255, (2, 17)).astype(np.int32)
    tr.step(tokens)
    saved = jax.tree_util.tree_map(np.asarray, tr.params)

    path = str(tmp_path / "ckpt")
    save_checkpoint(path, tr, step=1)

    tr.step(tokens)  # diverge
    step = restore_checkpoint(path, tr)
    assert step == 1
    for a, b in zip(jax.tree_util.tree_leaves(saved),
                    jax.tree_util.tree_leaves(tr.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # training continues after restore
    loss = tr.step(tokens)
    assert np.isfinite(loss)


def test_checkpoint_config_mismatch_rejected(tmp_path):
    from rocnrdma_tpu.parallel.checkpoint import (
        restore_checkpoint, save_checkpoint)
    import pytest as _pytest

    tr = Trainer("llama-tiny", {"dp": 1, "tp": 1})
    path = str(tmp_path / "ckpt")
    save_checkpoint(path, tr, step=0)
    tr.cfg = __import__("dataclasses").replace(tr.cfg, name="other")
    with _pytest.raises(ValueError):
        restore_checkpoint(path, tr)


def test_checkpoint_bf16_roundtrip(tmp_path):
    """bf16 (the flagship param dtype) must round-trip bit-exact
    through the npz format (extended dtypes are stored as uint views
    with a dtype tag)."""
    import dataclasses

    from rocnrdma_tpu.models.llama import LLAMA_TINY
    from rocnrdma_tpu.parallel.checkpoint import (
        restore_checkpoint, save_checkpoint)

    cfg = dataclasses.replace(LLAMA_TINY, dtype=jnp.bfloat16)
    tr = Trainer(cfg, {"dp": 1, "tp": 1}, seed=9)
    saved = jax.tree_util.tree_map(np.asarray, tr.params)
    path = str(tmp_path / "bf16ck")
    save_checkpoint(path, tr, step=3)
    # clobber, then restore
    tr.params = jax.tree_util.tree_map(lambda x: x * 0, tr.params)
    assert restore_checkpoint(path, tr) == 3
    for a, b in zip(jax.tree_util.tree_leaves(saved),
                    jax.tree_util.tree_leaves(tr.params)):
        av, bv = np.asarray(a), np.asarray(b)
        assert av.dtype == bv.dtype
        np.testing.assert_array_equal(
            av.view(np.uint16) if av.dtype.kind == "V" else av,
            bv.view(np.uint16) if bv.dtype.kind == "V" else bv)


def test_remat_loss_and_grad_parity():
    """cfg.remat wraps each Block in jax.checkpoint (nn.remat) for the
    training forward: activations are recomputed in the backward
    instead of stored. Rematerialization must be a pure memory/FLOPs
    trade — loss AND every gradient leaf must match the non-remat
    model exactly (same ops, same order, CPU is deterministic)."""
    import jax
    import jax.numpy as jnp

    from rocnrdma_tpu.models.llama import (
        cross_entropy_loss, init_params, make_model)

    tok = jnp.arange(32, dtype=jnp.int32).reshape(1, 32) % 256
    m0 = make_model("llama-tiny")
    m1 = make_model("llama-tiny", remat=True)
    params = init_params(m0, jax.random.PRNGKey(0))

    def loss_fn(model):
        return lambda p: cross_entropy_loss(
            model.apply(p, tok[:, :-1]), tok[:, 1:])

    l0, g0 = jax.value_and_grad(loss_fn(m0))(params)
    l1, g1 = jax.value_and_grad(loss_fn(m1))(params)
    # Bitwise-equal on today's CPU build; keep a hair of tolerance so
    # an XLA upgrade that reassociates a fusion differently between
    # the two HLO graphs doesn't hard-fail a parity test whose point
    # is "remat is a pure memory/FLOPs trade".
    assert abs(float(l0) - float(l1)) < 1e-6
    diffs = jax.tree_util.tree_map(
        lambda a, b: float(jnp.max(jnp.abs(a - b))), g0, g1)
    assert max(jax.tree_util.tree_leaves(diffs)) < 1e-5

    # The dots policy (save matmul outputs, recompute elementwise —
    # the MFU lever) is the same pure trade; bogus policies reject.
    m2 = make_model("llama-tiny", remat=True, remat_policy="dots")
    l2, g2 = jax.value_and_grad(loss_fn(m2))(params)
    assert abs(float(l0) - float(l2)) < 1e-6
    diffs2 = jax.tree_util.tree_map(
        lambda a, b: float(jnp.max(jnp.abs(a - b))), g0, g2)
    assert max(jax.tree_util.tree_leaves(diffs2)) < 1e-5
    import pytest
    with pytest.raises(ValueError, match="remat_policy"):
        make_model("llama-tiny", remat=True,
                   remat_policy="bogus").apply(params, tok[:, :-1])


@pytest.mark.parametrize("policy", ["full", "dots"])
def test_flagship_8b_train_step_traces_abstractly(policy):
    """The FULL Llama-3-8B training step — init, fwd, loss, grad,
    adamw update — traces end to end at the flagship geometry without
    materializing its ~16 GiB of parameters (jax.eval_shape: abstract
    values only). Catches geometry bugs (head split, GQA grouping,
    d_ff wiring) at the size that actually ships, which no executed
    test on this box could afford. remat=True is the production
    setting for this size (see LlamaConfig.remat); both recompute
    policies must trace."""
    import jax
    import jax.numpy as jnp
    import optax

    from rocnrdma_tpu.models.llama import (
        cross_entropy_loss, make_model)

    model = make_model("llama3-8b", remat=True, remat_policy=policy)
    tx = optax.adamw(1e-4)
    tokens = jax.ShapeDtypeStruct((2, 2049), jnp.int32)
    rng = jax.ShapeDtypeStruct((2,), jnp.uint32)

    def train_setup_and_step(rng, tokens):
        params = model.init(rng, jnp.zeros((1, 8), jnp.int32))
        opt = tx.init(params)

        def loss_fn(p):
            return cross_entropy_loss(
                model.apply(p, tokens[:, :-1]), tokens[:, 1:])

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt = tx.update(grads, opt, params)
        return optax.apply_updates(params, updates), opt, loss

    out_params, out_opt, loss = jax.eval_shape(
        train_setup_and_step, rng, tokens)
    assert loss.shape == () and loss.dtype == jnp.float32
    n = sum(int(jnp.prod(jnp.asarray(l.shape)))
            for l in jax.tree_util.tree_leaves(out_params))
    assert 7.9e9 < n < 8.2e9  # updated params keep the 8B geometry


def test_trainer_multi_device_pallas_via_shard_map():
    """On a multi-device mesh the Trainer no longer pins Pallas off:
    when the geometry shards cleanly (heads % tp == 0, kv_heads % tp
    == 0) it traces under ops.sharding.pallas_sharding, running the
    kernels as shard_map manual regions (batch on dp, heads on tp).
    Asserts (a) the Pallas kernel actually executes (call spy — the
    dispatcher must not silently fall back to the XLA reference),
    (b) training-loss parity with the XLA path on the same mesh."""
    import numpy as np

    from rocnrdma_tpu.ops import attention as attn_mod
    from rocnrdma_tpu.parallel.trainer import Trainer

    calls = {"flash": 0}
    real = attn_mod.flash_attention

    def spy(*a, **kw):
        calls["flash"] += 1
        return real(*a, **kw)

    attn_mod.flash_attention = spy
    try:
        tp_ = Trainer("llama-tiny", {"dp": 2, "tp": 2}, seed=0,
                      use_pallas_attention=True, use_pallas_rmsnorm=True,
                      pallas_interpret=True)
        batch = np.random.default_rng(0).integers(
            0, 255, (4, 17)).astype(np.int32)
        lp = [tp_.step(batch) for _ in range(2)]
    finally:
        attn_mod.flash_attention = real
    assert calls["flash"] > 0, "Pallas kernel never ran under the mesh"

    tx = Trainer("llama-tiny", {"dp": 2, "tp": 2}, seed=0)  # XLA path
    # Shardable geometry keeps auto flags un-pinned; on this CPU suite
    # they resolve to the XLA path at trace time.
    from rocnrdma_tpu.models.llama import resolve_pallas
    assert tx.cfg.use_pallas_attention is None
    assert resolve_pallas(tx.cfg.use_pallas_attention) is False
    lx = [tx.step(batch) for _ in range(2)]
    np.testing.assert_allclose(lp, lx, rtol=0, atol=5e-4)


def test_trainer_multi_device_pallas_pin_when_unshardable():
    """When the geometry does NOT divide the mesh (3 heads on tp=2),
    auto flags pin to the XLA path instead of handing GSPMD a bare
    pallas_call."""
    from rocnrdma_tpu.models.llama import LlamaConfig
    from rocnrdma_tpu.parallel.trainer import Trainer

    import jax.numpy as jnp

    cfg = LlamaConfig(name="odd", vocab_size=64, d_model=48, n_layers=1,
                      n_heads=3, n_kv_heads=3, d_ff=64, max_seq_len=32,
                      dtype=jnp.float32)
    import contextlib

    t = Trainer(cfg, {"dp": 2, "tp": 2})
    assert t.cfg.use_pallas_attention is False
    # rmsnorm only needs the dp axis, so its auto flag is NOT pinned
    # by the unshardable attention geometry (it resolves per backend).
    assert t.cfg.use_pallas_rmsnorm is None
    assert t._trace_ctx is contextlib.nullcontext  # CPU: auto -> off

    # EXPLICITLY-requested attention Pallas on an unshardable mesh
    # must fail loudly (a bare pallas_call must never reach GSPMD).
    with pytest.raises(ValueError, match="don't divide"):
        Trainer(cfg, {"dp": 2, "tp": 2}, use_pallas_attention=True,
                pallas_interpret=True)

    # ...but rmsnorm-only Pallas is fine on the same geometry: its
    # shard_map needs only dp, and unshardable attention stays XLA.
    t2 = Trainer(cfg, {"dp": 2, "tp": 2}, use_pallas_rmsnorm=True,
                 pallas_interpret=True)
    assert t2._trace_ctx is not contextlib.nullcontext
    import numpy as np
    l = t2.step(np.ones((4, 17), dtype=np.int32))
    assert np.isfinite(l)
