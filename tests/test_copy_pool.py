"""Parallel copy/reduce pool tests.

The emulated backend's CMA tier moves payloads through a process-wide
worker pool (``native/src/copy_pool.cc``) — the software stand-in for
an HCA's parallel DMA engines. The pool sizes itself from CPU affinity
at first use, so on a 1-core CI box it is inline-only; these tests
force a multi-worker pool via ``TDR_COPY_THREADS`` in a subprocess and
check bit-exactness of writes, sends, and reductions against numpy,
same-process and cross-process.
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_forced_pool(script: str) -> None:
    env = dict(os.environ)
    env["TDR_COPY_THREADS"] = "4"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-c", script],
        env=env,
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert proc.returncode == 0, (
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    )


def test_pooled_write_and_reduce_same_process():
    run_forced_pool(
        """
import socket

import numpy as np

from rocnrdma_tpu.transport.engine import (
    Engine, copy_pool_workers, loopback_pair)
from rocnrdma_tpu.collectives.world import local_worlds

assert copy_pool_workers() == 4, copy_pool_workers()

s = socket.socket(); s.bind(("127.0.0.1", 0))
port = s.getsockname()[1]; s.close()

# One-sided WRITE, large enough to be split into many pool slices.
n = 48 << 20
e = Engine("emu")
a, b = loopback_pair(e, port)
rng = np.random.default_rng(0)
src = rng.integers(0, 255, n, dtype=np.uint8)
dst = np.zeros(n, dtype=np.uint8)
smr, dmr = e.reg_mr(src), e.reg_mr(dst)
a.post_write(smr, 0, dmr.addr, dmr.rkey, n, wr_id=7)
assert a.wait(7).ok
assert np.array_equal(src, dst)
for m in (smr, dmr):
    m.deregister()
a.close(); b.close(); e.close()

# Ring allreduce: parallel fold must be bit-exact with numpy's.
count = (24 << 20) // 4
worlds = local_worlds(3, port + 500)
bufs = [np.random.default_rng(r).standard_normal(count).astype(np.float32)
        for r in range(3)]
want = bufs[0] + bufs[1] + bufs[2]
import threading
ts = [threading.Thread(target=worlds[r].allreduce, args=(bufs[r],))
      for r in range(3)]
for t in ts: t.start()
for t in ts: t.join()
for r in range(3):
    # All ranks bit-identical (same fold order along the ring); equal
    # to numpy only up to float associativity.
    np.testing.assert_array_equal(bufs[r], bufs[0])
    np.testing.assert_allclose(bufs[r], want, rtol=1e-5, atol=1e-6)
for w in worlds: w.close()
print("OK")
"""
    )


def test_pooled_cma_cross_process():
    # Parent serves rank 0, a forked child serves rank 1: the CMA tier
    # crosses a real process boundary, so the pool's parallel
    # process_vm_readv/writev slices are exercised.
    run_forced_pool(
        """
import os
import socket
import sys

import numpy as np

s = socket.socket(); s.bind(("127.0.0.1", 0))
base = s.getsockname()[1]; s.close()
count = (16 << 20) // 4

pid = os.fork()
rank = 1 if pid == 0 else 0
from rocnrdma_tpu.collectives.world import RingWorld
from rocnrdma_tpu.transport.engine import Engine

w = RingWorld(Engine("emu"), rank, 2, base + 100)
buf = np.full(count, float(rank + 1), dtype=np.float32)
w.allreduce(buf)
ok = bool(np.all(buf == 3.0))
w.close()
if pid == 0:
    os._exit(0 if ok else 1)
assert ok
_, status = os.waitpid(pid, 0)
assert os.waitstatus_to_exitcode(status) == 0
print("OK")
"""
    )
