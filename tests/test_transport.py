"""Transport engine lifecycle tests (emulated backend, no hardware).

Covers the registration → transfer → revocation lifecycle that the
reference could only exercise on a Fiji GPU + ConnectX HCA via dmesg
inspection (SURVEY.md §4): MR registration, one-sided WRITE/READ,
two-sided SEND/RECV, rkey enforcement, and invalidate-while-registered
— the amdp2p free_callback flow (amdp2p.c:88-109) made observable.
"""

import os
import socket
import threading

import numpy as np
import pytest

from rocnrdma_tpu.transport import engine as eng


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture()
def loop():
    """An emu engine with a connected loopback QP pair."""
    e = eng.Engine("emu")
    a, b = eng.loopback_pair(e, free_port())
    yield e, a, b
    a.close()
    b.close()
    e.close()


def test_engine_open_emu():
    with eng.Engine("emu") as e:
        assert e.kind == eng.ENGINE_EMU
        assert e.name == "emu"


def test_engine_auto_falls_back_without_devices():
    # No RDMA devices in CI: "auto" must degrade to the emulated
    # backend rather than fail (the reference hard-fails at build time
    # without OFED, Makefile:4-8 — a property we deliberately drop).
    with eng.Engine("auto") as e:
        assert e.kind in (eng.ENGINE_EMU, eng.ENGINE_VERBS)


def test_engine_verbs_reports_error_without_devices():
    with pytest.raises(eng.TransportError):
        eng.Engine("verbs")


def test_write_roundtrip(loop):
    e, a, b = loop
    src = np.arange(1024, dtype=np.uint8)
    dst = np.zeros(1024, dtype=np.uint8)
    with e.reg_mr(src) as smr, e.reg_mr(dst) as dmr:
        a.post_write(smr, 0, dmr.addr, dmr.rkey, 1024, wr_id=7)
        wc = a.wait(7)
        assert wc.ok and wc.opcode == eng.OP_WRITE
        np.testing.assert_array_equal(src, dst)


def test_write_partial_with_offsets(loop):
    e, a, b = loop
    src = np.arange(256, dtype=np.uint8)
    dst = np.zeros(256, dtype=np.uint8)
    with e.reg_mr(src) as smr, e.reg_mr(dst) as dmr:
        a.post_write(smr, 16, dmr.addr + 100, dmr.rkey, 32, wr_id=1)
        assert a.wait(1).ok
        np.testing.assert_array_equal(dst[100:132], src[16:48])
        assert dst[:100].sum() == 0 and dst[132:].sum() == 0


def test_read_roundtrip(loop):
    e, a, b = loop
    remote = np.arange(4096, dtype=np.uint8)
    local = np.zeros(4096, dtype=np.uint8)
    with e.reg_mr(remote) as rmr, e.reg_mr(local) as lmr:
        a.post_read(lmr, 0, rmr.addr, rmr.rkey, 4096, wr_id=3)
        wc = a.wait(3)
        assert wc.ok and wc.opcode == eng.OP_READ
        np.testing.assert_array_equal(local, remote)


def test_bad_rkey_fails_remotely(loop):
    e, a, b = loop
    src = np.ones(64, dtype=np.uint8)
    with e.reg_mr(src) as smr:
        a.post_write(smr, 0, 0xdead0000, 0xbad, 64, wr_id=9)
        wc = a.wait(9)
        assert wc.status == eng.WC_REM_ACCESS_ERR


def test_out_of_range_write_fails(loop):
    e, a, b = loop
    src = np.ones(64, dtype=np.uint8)
    dst = np.zeros(64, dtype=np.uint8)
    with e.reg_mr(src) as smr, e.reg_mr(dst) as dmr:
        a.post_write(smr, 0, dmr.addr + 32, dmr.rkey, 64, wr_id=2)
        assert a.wait(2).status == eng.WC_REM_ACCESS_ERR


def test_access_flags_enforced(loop):
    e, a, b = loop
    src = np.ones(64, dtype=np.uint8)
    dst = np.zeros(64, dtype=np.uint8)
    with e.reg_mr(src) as smr, \
            e.reg_mr(dst, access=eng.ACCESS_REMOTE_READ) as dmr:
        a.post_write(smr, 0, dmr.addr, dmr.rkey, 64, wr_id=4)
        assert a.wait(4).status == eng.WC_REM_ACCESS_ERR


def test_invalidate_revokes_remote_access(loop):
    """The free-while-registered race (amdp2p.c:88-109): once the MR is
    invalidated, in-flight-and-later remote access must fail, and
    deregistration afterwards must remain safe (the free_callback_called
    handshake, amdp2p.c:299-302)."""
    e, a, b = loop
    src = np.ones(64, dtype=np.uint8)
    dst = np.zeros(64, dtype=np.uint8)
    smr = e.reg_mr(src)
    dmr = e.reg_mr(dst)
    a.post_write(smr, 0, dmr.addr, dmr.rkey, 64, wr_id=1)
    assert a.wait(1).ok

    dmr.invalidate()
    a.post_write(smr, 0, dmr.addr, dmr.rkey, 64, wr_id=2)
    assert a.wait(2).status == eng.WC_REM_ACCESS_ERR

    # Local posts on an invalidated MR fail immediately.
    with pytest.raises(eng.TransportError):
        a.post_write(dmr, 0, dmr.addr, dmr.rkey, 64, wr_id=3)

    # Teardown after revocation: both orders are safe.
    dmr.deregister()
    smr.deregister()


def test_double_registration_same_range(loop):
    """The reference deliberately supports get_pages twice on one range
    (tests/amdp2ptest.c:296-299); two MRs over one buffer must coexist
    and die independently."""
    e, a, b = loop
    buf = np.zeros(128, dtype=np.uint8)
    src = np.ones(128, dtype=np.uint8)
    mr1 = e.reg_mr(buf)
    mr2 = e.reg_mr(buf)
    assert mr1.rkey != mr2.rkey
    with e.reg_mr(src) as smr:
        mr1.invalidate()
        a.post_write(smr, 0, mr1.addr, mr1.rkey, 128, wr_id=1)
        assert a.wait(1).status == eng.WC_REM_ACCESS_ERR
        # The second registration is untouched by the first's death.
        a.post_write(smr, 0, mr2.addr, mr2.rkey, 128, wr_id=2)
        assert a.wait(2).ok
    np.testing.assert_array_equal(buf, src)
    mr1.deregister()
    mr2.deregister()


def test_send_recv(loop):
    e, a, b = loop
    msg = np.frombuffer(b"tpu-direct-rdma", dtype=np.uint8).copy()
    inbox = np.zeros(64, dtype=np.uint8)
    with e.reg_mr(msg) as smr, e.reg_mr(inbox) as rmr:
        b.post_recv(rmr, 0, 64, wr_id=100)
        a.post_send(smr, 0, msg.nbytes, wr_id=5)
        assert a.wait(5).ok
        wc = b.wait(100)
        assert wc.ok and wc.opcode == eng.OP_RECV and wc.length == msg.nbytes
        assert bytes(inbox[:msg.nbytes]) == b"tpu-direct-rdma"


def test_send_before_recv_is_buffered(loop):
    e, a, b = loop
    msg = np.full(32, 7, dtype=np.uint8)
    inbox = np.zeros(32, dtype=np.uint8)
    with e.reg_mr(msg) as smr, e.reg_mr(inbox) as rmr:
        a.post_send(smr, 0, 32, wr_id=1)
        assert a.wait(1).ok  # acked even though no recv is posted yet
        b.post_recv(rmr, 0, 32, wr_id=2)
        wc = b.wait(2)
        assert wc.ok and wc.length == 32
        assert (inbox == 7).all()


def test_recv_too_small_errors(loop):
    e, a, b = loop
    msg = np.zeros(128, dtype=np.uint8)
    inbox = np.zeros(16, dtype=np.uint8)
    with e.reg_mr(msg) as smr, e.reg_mr(inbox) as rmr:
        b.post_recv(rmr, 0, 16, wr_id=1)
        a.post_send(smr, 0, 128, wr_id=2)
        assert a.wait(2).ok
        assert b.wait(1).status == eng.WC_LOC_ACCESS_ERR


def test_dmabuf_registration_and_visibility():
    """dma-buf-style registration: register exported "device" memory by
    fd, write into it remotely, then verify the contents through the
    CPU mapping — the same visibility check amdp2ptest's mmap path does
    (tests/amdp2ptest.c:336-395), without the 4KB-page and
    first-sg-entry-only limitations noted in SURVEY.md §2."""
    import mmap

    e = eng.Engine("emu")
    a, b = eng.loopback_pair(e, free_port())
    size = 1 << 16
    fd = os.memfd_create("fake-hbm", 0)
    try:
        os.ftruncate(fd, size)
        dmr = e.reg_dmabuf_mr(fd, 0, size)
        src = np.arange(size, dtype=np.uint8) % 251
        with e.reg_mr(src) as smr:
            a.post_write(smr, 0, dmr.addr, dmr.rkey, size, wr_id=1)
            assert a.wait(1).ok
        with mmap.mmap(fd, size) as view:
            got = np.frombuffer(view[:], dtype=np.uint8)
            np.testing.assert_array_equal(got, src)
        dmr.deregister()
    finally:
        os.close(fd)
        a.close()
        b.close()
        e.close()


def test_peer_close_flushes_pending(loop):
    e, a, b = loop
    inbox = np.zeros(64, dtype=np.uint8)
    with e.reg_mr(inbox) as rmr:
        a.post_recv(rmr, 0, 64, wr_id=42)
        b.close()
        wc = a.wait(42)
        assert wc.status == eng.WC_FLUSH_ERR


def test_concurrent_writers(loop):
    """Two threads hammering the same QP pair in both directions — the
    emulated progress engine must not deadlock (SURVEY.md §5 notes the
    reference's concurrency handling is entirely manual)."""
    e, a, b = loop
    n = 1 << 20
    src_a = np.ones(n, dtype=np.uint8)
    dst_a = np.zeros(n, dtype=np.uint8)
    src_b = np.full(n, 2, dtype=np.uint8)
    dst_b = np.zeros(n, dtype=np.uint8)
    mrs = [e.reg_mr(x) for x in (src_a, dst_a, src_b, dst_b)]
    sa, da, sb, db = mrs

    def pump(qp, smr, dmr_addr, dmr_rkey):
        for i in range(8):
            qp.post_write(smr, 0, dmr_addr, dmr_rkey, n, wr_id=i)
            assert qp.wait(i, timeout_ms=30000).ok

    t1 = threading.Thread(target=pump, args=(a, sa, db.addr, db.rkey))
    t2 = threading.Thread(target=pump, args=(b, sb, da.addr, da.rkey))
    t1.start(); t2.start(); t1.join(); t2.join()
    np.testing.assert_array_equal(dst_b, src_a)
    np.testing.assert_array_equal(dst_a, src_b)
    for m in mrs:
        m.deregister()


def test_use_after_close_raises_cleanly():
    """Closed handles must raise TransportError, not crash (guards in
    the bindings; the C ring also null-checks)."""
    e = eng.Engine("emu")
    a, b = eng.loopback_pair(e, free_port())
    buf = np.zeros(16, dtype=np.uint8)
    mr = e.reg_mr(buf)
    mr.deregister()
    with pytest.raises(eng.TransportError):
        _ = mr.rkey
    with pytest.raises(eng.TransportError):
        a.post_write(mr, 0, 0, 0, 16)
    a.close()
    with pytest.raises(eng.TransportError):
        a.poll(1, 0)
    b.close()
    e.close()
    with pytest.raises(eng.TransportError):
        e.reg_mr(buf)


def test_invalidate_racing_inflight_target(loop):
    """tdr_mr_invalidate while a post against the TARGET is in flight:
    the WR must complete — with SUCCESS (it won the race) or an access
    error (it lost) — never corrupt reclaimed memory or crash, and the
    access error must classify as FATAL (non-retryable taxonomy)."""
    e, a, b = loop
    n = 8 << 20
    src = np.ones(n, dtype=np.uint8)
    dst = np.zeros(n, dtype=np.uint8)
    smr = e.reg_mr(src)
    dmr = e.reg_mr(dst)
    a.post_write(smr, 0, dmr.addr, dmr.rkey, n, wr_id=1)
    # Revoke the landing target while the transfer may be mid-flight;
    # invalidate() quiesces (blocks out the in-progress landing) so
    # returning means no late write can touch the pages.
    dmr.invalidate()
    wc = a.wait(1, timeout_ms=30000)
    assert wc.status in (eng.WC_SUCCESS, eng.WC_REM_ACCESS_ERR)
    # Post-invalidate traffic deterministically errors, and the error
    # is fatal: a lifetime bug, not a rebuildable transient.
    a.post_write(smr, 0, dmr.addr, dmr.rkey, n, wr_id=2)
    wc = a.wait(2, timeout_ms=30000)
    assert wc.status == eng.WC_REM_ACCESS_ERR
    err = eng.TransportError("completion error status "
                             f"{wc.status} (rem_access_err)")
    assert not err.retryable
    dmr.deregister()
    smr.deregister()


def test_invalidate_racing_inflight_source(loop):
    """tdr_mr_invalidate on the SOURCE of an outstanding send: the
    pending op holds an inflight ref, so invalidate() blocks until the
    exchange completes — the payload that arrives is intact, never a
    torn read from reclaimed pages; later posts on the dead MR fail
    immediately."""
    e, a, b = loop
    n = 4 << 20
    msg = np.full(n, 3, dtype=np.uint8)
    inbox = np.zeros(n, dtype=np.uint8)
    smr = e.reg_mr(msg)
    rmr = e.reg_mr(inbox)
    b.post_recv(rmr, 0, n, wr_id=1)
    a.post_send(smr, 0, n, wr_id=2)
    smr.invalidate()  # blocks until the peer is done with the source
    assert a.wait(2, timeout_ms=30000).ok
    assert b.wait(1, timeout_ms=30000).ok
    assert (inbox == 3).all()
    with pytest.raises(eng.TransportError):
        a.post_send(smr, 0, n, wr_id=3)
    smr.deregister()
    rmr.deregister()


def test_dereg_waits_for_inflight_dma(loop):
    """dereg during a remote write must not free memory under the
    in-flight 'DMA' (ibv_dereg_mr semantics in the emu backend)."""
    e, a, b = loop
    n = 8 << 20
    src = np.ones(n, dtype=np.uint8)
    dst = np.zeros(n, dtype=np.uint8)
    smr = e.reg_mr(src)
    dmr = e.reg_mr(dst)
    a.post_write(smr, 0, dmr.addr, dmr.rkey, n, wr_id=1)
    # Deregister the target while the transfer may still be in flight;
    # the engine must serialize this against the payload landing.
    dmr.deregister()
    wc = a.wait(1, timeout_ms=30000)
    assert wc.status in (eng.WC_SUCCESS, eng.WC_REM_ACCESS_ERR)
    smr.deregister()
