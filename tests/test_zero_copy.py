"""Zero-copy collective path tests (BASELINE.md config 3).

The reference's entire value proposition is zero software on the hot
path after registration (amdp2p.c §3.3): after ``reg_mr`` on device
memory the NIC DMAs straight out of it — no host bounce. These tests
prove the TPU-side analogue end-to-end in the hardware-free world: a
pytree allreduce over ``FakeHBMExporter`` memory runs through
acquire→get_pages→export_dmabuf→reg_dmabuf_mr→ring with ZERO bytes
staged through host buffers (``staging.expect_zero``), and revocation
(free-while-registered, amdp2p.c:88-109) invalidates the MR instead of
leaving the collective reading reclaimed pages.
"""

import threading
import time

import numpy as np
import pytest

from rocnrdma_tpu.collectives.jax_shim import CrossSliceAllReduce
from rocnrdma_tpu.collectives.staging import staging
from rocnrdma_tpu.collectives.world import local_worlds
from rocnrdma_tpu.hbm.registry import (DeviceArena, FakeHBMExporter,
                                       HbmError, as_ndarray, device_ndarray)
from rocnrdma_tpu.transport.engine import TransportError

from test_transport import free_port
from test_collectives import run_ranks


def make_world2():
    worlds = local_worlds(2, free_port() + 100)
    exporters = [FakeHBMExporter(), FakeHBMExporter()]
    shims = [CrossSliceAllReduce(worlds[r], exporter=exporters[r])
             for r in range(2)]
    return worlds, exporters, shims


def close_all(worlds, shims):
    for s in shims:
        s.close()
    for w in worlds:
        w.close()


def test_zero_copy_pytree_expect_zero():
    """2-rank pytree allreduce over FakeHBMExporter with zero host
    staging — the config-3 acceptance criterion as a passing test."""
    worlds, exporters, shims = make_world2()
    rng = np.random.default_rng(7)

    trees = []
    for r in range(2):
        w = device_ndarray(exporters[r], (128, 33), np.float32)
        b = device_ndarray(exporters[r], (257,), np.float32)
        n = device_ndarray(exporters[r], (50,), np.int32)
        w[:] = rng.standard_normal((128, 33)).astype(np.float32)
        b[:] = rng.standard_normal(257).astype(np.float32)
        n[:] = rng.integers(-100, 100, 50).astype(np.int32)
        trees.append({"w": w, "b": b, "n": n})

    expect = {k: trees[0][k] + trees[1][k] for k in trees[0]}

    staging.reset()
    with staging.expect_zero():
        run_ranks(worlds, lambda w, r: shims[r](trees[r]))

    for r in range(2):
        for k in expect:
            np.testing.assert_allclose(trees[r][k], expect[k],
                                       rtol=1e-5, atol=1e-5)
    close_all(worlds, shims)


def test_zero_copy_steady_state_cached_registration():
    """Second allreduce on the same buffers does no new registration
    (front-loaded registration invariant) and stays zero-staging."""
    worlds, exporters, shims = make_world2()
    bufs = [device_ndarray(exporters[r], (4096,), np.float32)
            for r in range(2)]
    for r in range(2):
        bufs[r][:] = r + 1

    run_ranks(worlds, lambda w, r: shims[r](bufs[r]))
    regs_after_first = [dict(s._regs) for s in shims]

    with staging.expect_zero():
        run_ranks(worlds, lambda w, r: shims[r](bufs[r]))

    for r in range(2):
        assert shims[r]._regs == regs_after_first[r], "re-registered"
        # sum twice: (1+2)=3 after first, 3+3=6 after second
        np.testing.assert_allclose(bufs[r], np.full(4096, 6.0), rtol=1e-6)
    close_all(worlds, shims)


def test_zero_copy_mean():
    worlds, exporters, shims = make_world2()
    for s in shims:
        s.mean = True
    bufs = [device_ndarray(exporters[r], (1000,), np.float32)
            for r in range(2)]
    bufs[0][:] = 1.0
    bufs[1][:] = 3.0
    with staging.expect_zero():
        run_ranks(worlds, lambda w, r: shims[r](bufs[r]))
    for r in range(2):
        np.testing.assert_allclose(bufs[r], np.full(1000, 2.0), rtol=1e-6)
    close_all(worlds, shims)


def test_mixed_tree_stages_only_host_leaves():
    """Device leaves ride zero-copy; a plain host leaf in the same tree
    takes the staged fallback — and only ITS bytes are charged."""
    worlds, exporters, shims = make_world2()
    dev = [device_ndarray(exporters[r], (512,), np.float32)
           for r in range(2)]
    host = [np.full(100, float(r + 1), np.float32) for r in range(2)]
    for r in range(2):
        dev[r][:] = r + 1

    staging.reset()
    out = [None, None]

    def step(w, r):
        out[r] = shims[r]({"dev": dev[r], "host": host[r]})

    run_ranks(worlds, step)

    # Exactly the host leaf's round trip was staged, on each rank.
    assert staging.bytes == 2 * (100 * 4 * 2)
    for r in range(2):
        np.testing.assert_allclose(out[r]["dev"], np.full(512, 3.0))
        np.testing.assert_allclose(out[r]["host"], np.full(100, 3.0))
        assert out[r]["dev"] is dev[r]  # reduced in place
    close_all(worlds, shims)


def test_arena_tree_coalesces_to_one_ring_op():
    """A pytree allocated from one DeviceArena reduces as a SINGLE
    registration + ring op (adjacent leaves coalesce across alignment
    gaps), still zero-staging and still correct per leaf."""
    worlds, exporters, shims = make_world2()
    rng = np.random.default_rng(3)
    arenas = [DeviceArena(exporters[r], 1 << 20) for r in range(2)]

    trees = []
    for r in range(2):
        # Odd sizes so alignment gaps exist between leaves.
        w = arenas[r].take((37, 11), np.float32)
        b = arenas[r].take((203,), np.float32)
        v = arenas[r].take((5,), np.float32)
        w[:] = rng.standard_normal((37, 11)).astype(np.float32)
        b[:] = rng.standard_normal(203).astype(np.float32)
        v[:] = rng.standard_normal(5).astype(np.float32)
        trees.append({"w": w, "b": b, "v": v})

    expect = {k: trees[0][k] + trees[1][k] for k in trees[0]}

    with staging.expect_zero():
        run_ranks(worlds, lambda w, r: shims[r](trees[r]))

    for r in range(2):
        assert len(shims[r]._regs) == 1, "leaves did not coalesce"
        for k in expect:
            np.testing.assert_allclose(trees[r][k], expect[k],
                                       rtol=1e-5, atol=1e-5)
    close_all(worlds, shims)
    for a in arenas:
        a.free()


def test_live_gap_between_leaves_not_coalesced():
    """Two device leaves with LIVE data in the gap between them must
    reduce as separate ops — coalescing would overwrite the gap bytes
    with the cross-rank sum (silent corruption). Only exporter-proven
    dead padding (DeviceArena alignment gaps) may be merged across."""
    worlds, exporters, shims = make_world2()
    vas = [exporters[r].alloc(4096) for r in range(2)]
    trees, guards = [], []
    for r in range(2):
        a = as_ndarray(vas[r], (25,), np.float32)         # [0, 100)
        g = as_ndarray(vas[r] + 100, (28,), np.uint8)     # live bytes
        b = as_ndarray(vas[r] + 128, (25,), np.float32)   # [128, 228)
        a[:] = r + 1
        b[:] = 10.0 * (r + 1)
        g[:] = 77
        trees.append([a, b])
        guards.append(g)

    with staging.expect_zero():
        run_ranks(worlds, lambda w, r: shims[r](trees[r]))

    for r in range(2):
        np.testing.assert_allclose(trees[r][0], np.full(25, 3.0))
        np.testing.assert_allclose(trees[r][1], np.full(25, 30.0))
        assert (guards[r] == 77).all(), "live gap bytes were corrupted"
        assert len(shims[r]._regs) == 2, "live gap was coalesced across"
    close_all(worlds, shims)
    for r in range(2):
        exporters[r].free(vas[r])


def test_ring_register_over_adopted_mr_rejected():
    """Re-registering a larger buffer at a key holding an ADOPTED
    (caller-owned) MR must fail instead of deregistering the owner's
    MR (which would double-free on the owner's deregister)."""
    from rocnrdma_tpu.transport.engine import Engine, Ring, loopback_pair

    e = Engine("emu")
    a, b = loopback_pair(e, free_port())
    ring = Ring(e, a, b, 0, 2)
    buf = np.zeros(1024, dtype=np.float32)
    mr = e.reg_mr(buf)
    ring.adopt_mr(buf.ctypes.data, mr)
    bigger = as_ndarray(buf.ctypes.data, (2048,), np.float32)
    with pytest.raises(TransportError, match="adopted"):
        ring.register_buffer(bigger)
    # The adopted MR is untouched: dropping + owner dereg still works.
    ring.drop_buffer(buf.ctypes.data)
    mr.deregister()
    ring.destroy()
    a.close()
    b.close()
    e.close()


def test_tied_leaf_reduced_once():
    """The same buffer appearing twice in the tree (tied weights) is
    reduced ONCE — not doubled by two in-place ring ops."""
    worlds, exporters, shims = make_world2()
    bufs = [device_ndarray(exporters[r], (256,), np.float32)
            for r in range(2)]
    for r in range(2):
        bufs[r][:] = float(r + 1)
    with staging.expect_zero():
        run_ranks(worlds,
                  lambda w, r: shims[r]({"emb": bufs[r], "out": bufs[r]}))
    for r in range(2):
        np.testing.assert_allclose(bufs[r], np.full(256, 3.0), rtol=1e-6)
    close_all(worlds, shims)


def test_revocation_invalidates_cached_registration():
    """Free-while-registered: the exporter's free_callback invalidates
    the MR (amdp2p.c:88-109); the next collective touching the dead
    region fails in re-registration — it never reads reclaimed pages."""
    worlds, exporters, shims = make_world2()
    bufs = [device_ndarray(exporters[r], (2048,), np.float32)
            for r in range(2)]
    for r in range(2):
        bufs[r][:] = 1.0
    run_ranks(worlds, lambda w, r: shims[r](bufs[r]))

    (va0, n0), = list(shims[0]._regs.keys())
    reg0 = shims[0]._regs[(va0, n0)]
    assert not reg0.ctx.revoked
    exporters[0].free(va0)
    assert reg0.ctx.revoked  # free_callback fired synchronously

    # NOTE: bufs[0] now dangles; the shim must fail before touching it.
    with pytest.raises(HbmError):
        shims[0]._ensure_registered(va0, n0)
    assert (va0, n0) not in shims[0]._regs
    close_all(worlds, shims)


def test_revocation_forced_into_landing_window(monkeypatch):
    """DETERMINISTIC free-while-landing (amdp2p.c:88-109): the fault
    injection holds the landing path between the recv match and the
    MR re-validation; the owner frees INSIDE that window. The recv
    must complete with the lifetime error — if the revocation were
    not observed at landing time (the bug this interleaving exists to
    catch), the landing would succeed and this test would fail."""
    from rocnrdma_tpu.hbm.registry import RegistrationManager
    from rocnrdma_tpu.transport.engine import (DT_F32, Engine, RED_SUM,
                                               WC_SUCCESS, loopback_pair)

    monkeypatch.setenv("TDR_FAULT_LANDING_DELAY_MS", "400")
    e = Engine("emu")
    exporter = FakeHBMExporter()
    va = exporter.alloc(4096)
    mgr = RegistrationManager(e, exporter)
    reg = mgr.register(va, 4096)
    a, b = loopback_pair(e, free_port() + 400)

    payload = np.ones(1024, dtype=np.float32)
    with e.reg_mr(payload) as pmr:
        b.post_recv_reduce(reg.mr, 0, 4096, DT_F32, RED_SUM, wr_id=1)
        t0 = time.perf_counter()
        a.post_send(pmr, 0, payload.nbytes, wr_id=2)
        # The payload is matched immediately; the landing is now
        # sleeping. Free the target inside that window.
        time.sleep(0.1)
        exporter.free(va)
        t_free = time.perf_counter() - t0
        assert t_free < 0.4, f"free happened after the window ({t_free:.2f}s)"
        assert reg.ctx.revoked  # free_callback fired
        wc = b.poll(max_wc=1, timeout_ms=10000)
        assert wc and wc[0].wr_id == 1
        assert wc[0].status != WC_SUCCESS, (
            "landing succeeded despite revocation inside the window")
    a.close()
    b.close()
    mgr.close()
    e.close()


def test_revocation_mid_collective_no_crash(monkeypatch):
    """Free a rank's buffer while a large allreduce is in flight: the
    collective either fails with a transport/lifetime error or had
    already completed — it must never crash or hang."""
    # The surviving peer detects the dead collective via the ring stall
    # deadline; shorten it so the test doesn't sit out the 30s default.
    monkeypatch.setenv("TDR_RING_TIMEOUT_MS", "2000")
    worlds, exporters, shims = make_world2()
    count = 32 << 20  # 128 MiB f32 — long enough to race against
    bufs = [device_ndarray(exporters[r], (count,), np.float32)
            for r in range(2)]
    for r in range(2):
        bufs[r][:1] = 1.0  # touch to fault pages in

    errs = [None, None]

    def step(r):
        try:
            shims[r](bufs[r])
        except (TransportError, HbmError) as e:
            errs[r] = e

    ts = [threading.Thread(target=step, args=(r,)) for r in range(2)]
    for t in ts:
        t.start()
    time.sleep(0.005)
    va0 = bufs[0].ctypes.data
    exporters[0].free(va0)
    for t in ts:
        t.join(timeout=90)
        assert not t.is_alive(), "allreduce hung after revocation"
    # Revocation must have been observed by rank 0's registration
    # whether or not the race landed mid-transfer.
    for (va, n), reg in shims[0]._regs.items():
        if va == va0:
            assert reg.ctx.revoked
    close_all(worlds, shims)
