"""Multi-host-shaped transport test: two network namespaces.

SURVEY.md §4 prescribes multi-host testing via network namespaces —
the closest hardware-free analogue of two hosts: each rank runs in its
own netns with its own interface and IP, traffic crosses a veth link,
and the CMA (same-address-space) tier is explicitly disabled so the
bytes take the STREAM path a real DCN hop would (the emu handshake
would otherwise detect same-host and shortcut through process memory).

Skips — with the observed reason — where namespace creation is not
permitted (unprivileged CI).
"""

import os
import shutil
import subprocess
import sys
import textwrap

import numpy as np
import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), os.pardir))
NS = ("tdrtest_a", "tdrtest_b")
IPS = ("10.97.3.1", "10.97.3.2")
VETH = ("tdrtest_v0", "tdrtest_v1")


def _run(cmd, **kw):
    return subprocess.run(cmd, capture_output=True, text=True, **kw)


def _netns_available():
    if shutil.which("ip") is None:
        return "iproute2 'ip' not installed"
    probe = _run(["ip", "netns", "add", "tdrtest_probe"])
    if probe.returncode != 0:
        return f"ip netns add failed: {probe.stderr.strip()}"
    _run(["ip", "netns", "del", "tdrtest_probe"])
    return None


_SKIP_REASON = _netns_available()

RANK_SCRIPT = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, {repo!r})
    sys.path[:] = [p for p in sys.path if ".axon_site" not in p]
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["TDR_NO_CMA"] = "1"   # force the stream (network) tier
    import numpy as np
    from rocnrdma_tpu.collectives.world import RingWorld
    from rocnrdma_tpu.transport.engine import Engine

    rank = int(sys.argv[1])
    world = RingWorld(Engine("emu"), rank, 2, {port}, peers={peers!r},
                      bind_host="0.0.0.0")
    buf = np.full(100003, float(rank + 1), dtype=np.float32)
    world.allreduce(buf)
    assert np.all(buf == 3.0), buf[:8]
    # Second allreduce on the same buffer: steady-state (registered)
    buf[:] = float(rank + 10)
    world.allreduce(buf)
    assert np.all(buf == 21.0), buf[:8]
    world.close()
    print(f"rank {{rank}} OK")
""")


def _cleanup():
    for ns in NS:
        _run(["ip", "netns", "del", ns])


@pytest.mark.skipif(_SKIP_REASON is not None,
                    reason=f"netns unavailable: {_SKIP_REASON}")
def test_two_netns_ring_allreduce(tmp_path):
    _cleanup()
    try:
        for ns in NS:
            r = _run(["ip", "netns", "add", ns])
            assert r.returncode == 0, r.stderr
        r = _run(["ip", "link", "add", VETH[0], "type", "veth",
                  "peer", "name", VETH[1]])
        assert r.returncode == 0, r.stderr
        for i in range(2):
            assert _run(["ip", "link", "set", VETH[i],
                         "netns", NS[i]]).returncode == 0
            assert _run(["ip", "netns", "exec", NS[i], "ip", "addr",
                         "add", f"{IPS[i]}/24", "dev",
                         VETH[i]]).returncode == 0
            assert _run(["ip", "netns", "exec", NS[i], "ip", "link",
                         "set", VETH[i], "up"]).returncode == 0
            assert _run(["ip", "netns", "exec", NS[i], "ip", "link",
                         "set", "lo", "up"]).returncode == 0

        port = 26000 + (os.getpid() % 600)
        script = tmp_path / "rank.py"
        script.write_text(RANK_SCRIPT.format(repo=REPO, port=port,
                                             peers=list(IPS)))
        procs = [
            subprocess.Popen(
                ["ip", "netns", "exec", NS[r], sys.executable,
                 str(script), str(r)],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True)
            for r in range(2)
        ]
        outs = [p.communicate(timeout=120) for p in procs]
        for r, (p, (out, err)) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, (
                f"rank {r} failed:\nstdout: {out}\nstderr: {err[-2000:]}")
            assert f"rank {r} OK" in out
    finally:
        _cleanup()
