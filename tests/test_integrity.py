"""Sealed-chunk integrity tests: detection, containment, bounded retry.

The zero-copy design's blind spot (ISSUE 2): no host copy ever touches
the bytes, so a flipped bit or a stale-incarnation ghost write lands
silently in gradients. These tests pin the whole ladder —
verify-fail → chunk NAK/retransmit → budget exhaustion →
TDR_WC_INTEGRITY_ERR → RingWorld.rebuild() → trainer quarantine — with
deterministic ``corrupt=`` fault plans whose hit counters prove every
injected corruption actually fired AND was caught.
"""

import os
import threading

import numpy as np
import pytest

from rocnrdma_tpu.transport import engine as eng
from rocnrdma_tpu.transport.engine import (
    Engine, TransportError, WC_INTEGRITY_ERR, crc32c, fault_plan_clauses,
    fault_plan_hits, fault_plan_reset, loopback_pair, note_integrity,
    seal_counters, seal_counters_reset)
from rocnrdma_tpu.utils.trace import trace

from test_transport import free_port


@pytest.fixture
def fault_plan(monkeypatch):
    """Arm a TDR_FAULT_PLAN and reset the integrity counters for one
    test; disarm and re-reset afterwards."""

    def arm(spec: str) -> None:
        monkeypatch.setenv("TDR_FAULT_PLAN", spec)
        fault_plan_reset()
        seal_counters_reset()

    yield arm
    monkeypatch.delenv("TDR_FAULT_PLAN", raising=False)
    fault_plan_reset()
    seal_counters_reset()


@pytest.fixture()
def loop():
    e = Engine("emu")
    a, b = loopback_pair(e, free_port())
    yield e, a, b
    a.close()
    b.close()
    e.close()


# ------------------------------------------------------------- crc32c


def test_crc32c_known_vector():
    # The canonical CRC32C check vector (RFC 3720 appendix B.4 family).
    assert crc32c(b"123456789") == 0xE3069283
    assert crc32c(b"") == 0


def test_crc32c_incremental_chaining():
    whole = crc32c(b"tpu-direct-rdma sealed chunks")
    part = crc32c(b" sealed chunks", crc32c(b"tpu-direct-rdma"))
    assert whole == part
    assert crc32c(b"tpu-direct-rdma") != whole


# ------------------------------------------------- negotiation & digest


def test_seal_negotiated_by_default(loop):
    e, a, b = loop
    assert a.has_seal and b.has_seal


def test_seal_opt_out_degrades_both_ends(monkeypatch):
    """TDR_NO_SEAL acts at the handshake: the pair degrades to plain
    frames (never a per-rank wire mismatch)."""
    monkeypatch.setenv("TDR_NO_SEAL", "1")
    e = Engine("emu")
    a, b = loopback_pair(e, free_port())
    assert not a.has_seal and not b.has_seal
    # Traffic still flows unsealed.
    msg = np.full(32, 5, dtype=np.uint8)
    inbox = np.zeros(32, dtype=np.uint8)
    with e.reg_mr(msg) as smr, e.reg_mr(inbox) as rmr:
        b.post_recv(rmr, 0, 32, wr_id=1)
        a.post_send(smr, 0, 32, wr_id=2)
        assert a.wait(2).ok and b.wait(1).ok
    assert (inbox == 5).all()
    a.close(); b.close(); e.close()


def test_seal_config_enters_schedule_digest():
    """A rank pair whose seal settings disagree must fail fast with a
    schedule-mismatch error — not mis-parse each other's frames."""
    from rocnrdma_tpu.collectives.jax_shim import CrossSliceAllReduce
    from rocnrdma_tpu.collectives.world import local_worlds

    worlds = local_worlds(2, free_port())
    assert all("seal=1" in w.seal_config for w in worlds)
    syncs = [CrossSliceAllReduce(w, mean=False) for w in worlds]
    # Simulate a rank whose env diverged (e.g. TDR_NO_SEAL or a
    # different TDR_SEAL_RETRY): its digest must differ.
    worlds[1].seal_config = "seal=0:retry=9"
    errs = [None, None]

    def run(r):
        try:
            syncs[r]({"g": np.ones(64, dtype=np.float32)})
        except TransportError as e:
            errs[r] = e

    ts = [threading.Thread(target=run, args=(r,)) for r in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert all(e is not None for e in errs), errs
    assert any("schedule mismatch" in str(e) for e in errs), errs
    assert all(not e.retryable for e in errs), errs
    for s in syncs:
        s.close()
    for w in worlds:
        w.close()


# ------------------------------------- detection + bounded retransmit


def test_send_corruption_detected_and_healed(fault_plan, loop):
    """``send:nth=1:corrupt=3``: the wire copy is flipped after
    sealing, the landing verify catches it, the NAK-driven
    retransmission (from the untouched source) heals it — both WRs
    complete SUCCESS and the data is intact."""
    fault_plan("send:nth=1:corrupt=3")
    e, a, b = loop
    msg = np.arange(128, dtype=np.uint8)
    inbox = np.zeros(128, dtype=np.uint8)
    with e.reg_mr(msg) as smr, e.reg_mr(inbox) as rmr:
        b.post_recv(rmr, 0, 128, wr_id=1)
        a.post_send(smr, 0, 128, wr_id=2)
        assert a.wait(2, timeout_ms=10000).ok
        assert b.wait(1, timeout_ms=10000).ok
    np.testing.assert_array_equal(inbox, msg)
    c = seal_counters()
    assert c["failed"] >= 1 and c["retransmitted"] >= 1, c
    assert fault_plan_hits(0) == 1  # the corruption demonstrably fired
    # The tracer sees the same ladder through integrity.* counters.
    note_integrity()
    assert trace.counter("integrity.failed") >= 1
    assert trace.counter("integrity.retransmitted") >= 1


def test_land_corruption_detected_and_healed(fault_plan, monkeypatch):
    """``land:nth=1:corrupt=2``: bytes flipped after materialization,
    before verification — the receive-side half of the fault model.
    Land-site detection needs the payload CRC, so this pins FULL CMA
    sealing (TDR_SEAL_CMA=1; the same-host default is tag-only)."""
    monkeypatch.setenv("TDR_SEAL_CMA", "1")
    fault_plan("land:nth=1:corrupt=2")
    e = Engine("emu")
    a, b = loopback_pair(e, free_port())
    assert a.has_seal_payload and b.has_seal_payload
    msg = np.full(256, 7, dtype=np.uint8)
    inbox = np.zeros(256, dtype=np.uint8)
    with e.reg_mr(msg) as smr, e.reg_mr(inbox) as rmr:
        b.post_recv(rmr, 0, 256, wr_id=1)
        a.post_send(smr, 0, 256, wr_id=2)
        assert a.wait(2, timeout_ms=10000).ok
        assert b.wait(1, timeout_ms=10000).ok
    assert (inbox == 7).all()
    assert fault_plan_hits(0) == 1
    assert seal_counters()["failed"] >= 1
    a.close(); b.close(); e.close()


def test_cma_seal_defaults_to_tag_only(monkeypatch):
    """The CMA tier's negotiated default is tag-only sealing (the
    kernel-memcpy "wire" has no payload bit-flip failure mode — the
    verbs ICRC rationale): has_seal stays on, has_seal_payload is off,
    and the generation fence still works. TDR_SEAL_CMA=1 on BOTH ends
    reinstates the payload CRC; the TCP stream tier (TDR_NO_CMA)
    always carries it."""
    e = Engine("emu")
    a, b = loopback_pair(e, free_port())
    assert a.has_seal and b.has_seal
    assert not a.has_seal_payload and not b.has_seal_payload
    a.close(); b.close(); e.close()

    monkeypatch.setenv("TDR_SEAL_CMA", "1")
    e = Engine("emu")
    a, b = loopback_pair(e, free_port())
    assert a.has_seal_payload and b.has_seal_payload
    a.close(); b.close(); e.close()
    monkeypatch.delenv("TDR_SEAL_CMA")

    monkeypatch.setenv("TDR_NO_CMA", "1")
    e = Engine("emu")
    a, b = loopback_pair(e, free_port())
    assert a.has_seal and a.has_seal_payload and b.has_seal_payload
    a.close(); b.close(); e.close()


def test_tag_only_send_corruption_detected_and_healed(fault_plan, loop):
    """Even in tag-only mode a send-site corrupt clause (CRC flip on
    desc frames) is detected and healed by the NAK/retransmit ladder —
    the tag CRC still travels and still gates every landing."""
    e, a, b = loop
    assert a.has_seal and not a.has_seal_payload
    fault_plan("send:nth=1:corrupt=3")
    msg = np.arange(64, dtype=np.uint8)
    inbox = np.zeros(64, dtype=np.uint8)
    with e.reg_mr(msg) as smr, e.reg_mr(inbox) as rmr:
        b.post_recv(rmr, 0, 64, wr_id=1)
        a.post_send(smr, 0, 64, wr_id=2)
        assert a.wait(2, timeout_ms=10000).ok
        assert b.wait(1, timeout_ms=10000).ok
    np.testing.assert_array_equal(inbox, msg)
    c = seal_counters()
    assert c["failed"] >= 1 and c["retransmitted"] >= 1, c
    assert fault_plan_hits(0) == 1


def test_corrupt_chunk_never_folded_before_verify(fault_plan, loop):
    """The load-bearing ordering: a reduce-recv's fold happens only
    AFTER the seal verifies, and exactly once after the retransmit —
    a premature or double fold would corrupt the accumulator in a way
    a retry cannot undo."""
    fault_plan("send:nth=1:corrupt=4")
    e, a, b = loop
    acc = np.full(512, 1.0, dtype=np.float32)
    src = np.full(512, 2.0, dtype=np.float32)
    with e.reg_mr(acc) as amr, e.reg_mr(src) as smr:
        b.post_recv_reduce(amr, 0, acc.nbytes, eng.DT_F32, wr_id=1)
        a.post_send(smr, 0, src.nbytes, wr_id=2)
        assert a.wait(2, timeout_ms=10000).ok
        assert b.wait(1, timeout_ms=10000).ok
    np.testing.assert_array_equal(acc, np.full(512, 3.0, np.float32))
    assert seal_counters()["failed"] >= 1
    assert fault_plan_hits(0) == 1


def test_write_corruption_detected_and_healed(fault_plan, loop):
    """RDMA_WRITE landings carry a piggybacked seal frame and retry
    the same way as SEND-class chunks."""
    fault_plan("send:nth=1:corrupt=2")
    e, a, b = loop
    src = np.arange(1024, dtype=np.uint8)
    dst = np.zeros(1024, dtype=np.uint8)
    with e.reg_mr(src) as smr, e.reg_mr(dst) as dmr:
        a.post_write(smr, 0, dmr.addr, dmr.rkey, 1024, wr_id=3)
        assert a.wait(3, timeout_ms=10000).ok
    np.testing.assert_array_equal(dst, src)
    c = seal_counters()
    assert c["failed"] >= 1 and c["retransmitted"] >= 1, c


def test_budget_exhaustion_completes_with_integrity_err(fault_plan,
                                                        monkeypatch,
                                                        loop):
    """``send:corrupt=2`` (always: every transmission INCLUDING
    retransmissions is corrupted): after TDR_SEAL_RETRY retransmits,
    BOTH sides complete with WC_INTEGRITY_ERR — retryable, kind
    "integrity" — instead of retrying forever or hanging."""
    monkeypatch.setenv("TDR_SEAL_RETRY", "2")
    fault_plan("send:corrupt=2")
    e = Engine("emu")  # fresh QPs pick up the tightened budget
    a, b = loopback_pair(e, free_port())
    msg = np.ones(64, dtype=np.uint8)
    inbox = np.zeros(64, dtype=np.uint8)
    with e.reg_mr(msg) as smr, e.reg_mr(inbox) as rmr:
        b.post_recv(rmr, 0, 64, wr_id=1)
        a.post_send(smr, 0, 64, wr_id=2)
        wa = a.wait(2, timeout_ms=10000)
        wb = b.wait(1, timeout_ms=10000)
    assert wa.status == WC_INTEGRITY_ERR and wb.status == WC_INTEGRITY_ERR
    c = seal_counters()
    # initial transmission + budget retransmissions, all corrupted
    assert c["retransmitted"] == 2 and c["failed"] == 3, c
    err = TransportError("completion error status "
                         f"{WC_INTEGRITY_ERR} (integrity_err)")
    assert err.retryable and err.kind == "integrity"
    a.close(); b.close(); e.close()


def test_stale_incarnation_ghost_write_fenced(fault_plan, monkeypatch):
    """Intact bytes sealed by a DIFFERENT live incarnation are a ghost
    from a stale world: the seal's generation tag fences them with an
    integrity error instead of letting them land."""
    monkeypatch.setenv("TDR_SEAL_RETRY", "0")  # fence fails every retry
    fault_plan("")  # no corruption: the GENERATION is the fault
    e1, e2 = Engine("emu"), Engine("emu")
    a, b = loopback_pair(e1, free_port(), engine2=e2)
    e1.set_seal_context(generation=4, step=0)
    e2.set_seal_context(generation=7, step=0)
    msg = np.ones(64, dtype=np.uint8)
    inbox = np.zeros(64, dtype=np.uint8)
    smr, rmr = e1.reg_mr(msg), e2.reg_mr(inbox)
    b.post_recv(rmr, 0, 64, wr_id=1)
    a.post_send(smr, 0, 64, wr_id=2)
    wa = a.wait(2, timeout_ms=10000)
    wb = b.wait(1, timeout_ms=10000)
    # Both sides surface the fence as an integrity failure: the ghost
    # can never land SILENTLY. (The recv buffer's contents are
    # undefined on an errored WR — standard RDMA completion semantics;
    # in-place plain landings may have touched it before the verify.)
    assert wa.status == WC_INTEGRITY_ERR and wb.status == WC_INTEGRITY_ERR
    assert seal_counters()["failed"] >= 1
    smr.deregister(); rmr.deregister()
    a.close(); b.close(); e1.close(); e2.close()


# ------------------------------------------------- ring-level ladder


def test_ring_corruption_heals_bitwise_equal(fault_plan, monkeypatch):
    """Deterministic corruption soak at the collective level: a
    corrupted chunk on a world-2 sealed allreduce is detected,
    retransmitted, and the result is BITWISE equal to an
    uninterrupted run — the caller never sees an error. Full CMA
    sealing is pinned (TDR_SEAL_CMA=1): the land-site clause flips
    payload bytes, which only the payload CRC can catch."""
    from rocnrdma_tpu.collectives.world import local_worlds

    monkeypatch.setenv("TDR_SEAL_CMA", "1")
    # Clean reference run first.
    worlds = local_worlds(2, free_port())
    clean = [np.full(4096, float(r + 1), dtype=np.float32)
             for r in range(2)]
    ts = [threading.Thread(target=worlds[r].allreduce, args=(clean[r],))
          for r in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()

    # Two deterministic corruption runs: one at the send site (wire
    # copy flipped after sealing), one at the land site (first landed
    # payload flipped before verification — the first land arrival is
    # always a chunk payload, never an ack). Each must heal to the
    # clean run's exact bytes with its clause demonstrably fired.
    for plan in ("send:chunk=0:nth=1:corrupt=4", "land:nth=1:corrupt=2"):
        fault_plan(plan)
        faulty = [np.full(4096, float(r + 1), dtype=np.float32)
                  for r in range(2)]
        ts = [threading.Thread(target=worlds[r].allreduce,
                               args=(faulty[r],))
              for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        for c, f in zip(clean, faulty):
            assert c.tobytes() == f.tobytes()  # bitwise, not approx
        hits = sum(fault_plan_hits(i)
                   for i in range(fault_plan_clauses()))
        assert hits >= 1, f"{plan}: injected corruption never fired"
        assert seal_counters()["failed"] >= 1
    for w in worlds:
        w.close()


def test_ring_budget_exhaustion_escalates_to_rebuild(fault_plan,
                                                     monkeypatch):
    """Exhausting the retransmit budget surfaces a RETRYABLE integrity
    error on the collective (never a hang), and once the fault clears,
    RingWorld.rebuild() brings the ring back."""
    from rocnrdma_tpu.collectives.world import local_worlds

    monkeypatch.setenv("TDR_SEAL_RETRY", "1")
    monkeypatch.setenv("TDR_RING_TIMEOUT_MS", "30000")
    fault_plan("send:chunk=0:corrupt=2")  # every chunk-0 transmission
    worlds = local_worlds(2, free_port())
    errs = [None, None]

    def run(r):
        buf = np.full(1024, float(r + 1), dtype=np.float32)
        try:
            worlds[r].allreduce(buf)
        except TransportError as e:
            errs[r] = e

    ts = [threading.Thread(target=run, args=(r,)) for r in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert all(e is not None and e.retryable for e in errs), errs
    assert any(e.kind == "integrity" for e in errs), errs
    # Clear the fault, rebuild every rank, and prove the new
    # incarnation carries correct traffic.
    monkeypatch.delenv("TDR_FAULT_PLAN")
    fault_plan_reset()
    ts = [threading.Thread(
        target=lambda r=r: worlds[r].rebuild(
            max_attempts=8, backoff_s=0.05, timeout_ms=10000))
        for r in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert [w.generation for w in worlds] == [1, 1]
    bufs = [np.full(4096, float(r + 1), dtype=np.float32)
            for r in range(2)]
    ts = [threading.Thread(target=worlds[r].allreduce, args=(bufs[r],))
          for r in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    for b in bufs:
        np.testing.assert_array_equal(b, np.full(4096, 3.0, np.float32))
    for w in worlds:
        w.close()


# --------------------------------------------- trainer quarantine rung


class _NaNOnceSync:
    """cross_slice_sync stand-in: poisons the gradients with NaN on
    selected calls — the "verified but non-finite" condition the
    quarantine rung exists for."""

    def __init__(self, poison_calls):
        self.calls = 0
        self.poison_calls = set(poison_calls)

    def __call__(self, grads):
        import jax

        self.calls += 1
        if self.calls in self.poison_calls:
            leaves, treedef = jax.tree_util.tree_flatten(grads)
            poisoned = [np.asarray(leaves[0]).copy()] + [
                np.asarray(x) for x in leaves[1:]]
            poisoned[0].reshape(-1)[0] = np.nan
            return jax.tree_util.tree_unflatten(treedef, poisoned)
        return grads


def test_trainer_quarantines_nonfinite_grads_once(tmp_path):
    """A step whose synced gradients come back non-finite is retried
    once from the pre-step state; the retry (clean sync) succeeds and
    the run matches a never-poisoned run bitwise."""
    import jax
    from rocnrdma_tpu.parallel.trainer import ElasticPolicy, Trainer

    batch = np.random.default_rng(3).integers(
        0, 255, (2, 17)).astype(np.int32)

    def run(poison):
        trace.reset()
        tr = Trainer("llama-tiny", {"dp": 1, "tp": 1}, seed=11,
                     learning_rate=1e-2,
                     cross_slice_sync=_NaNOnceSync(poison),
                     elastic=ElasticPolicy(str(tmp_path / "ck"),
                                           save_every=1))
        tr.step(batch)
        return (jax.tree_util.tree_map(np.asarray, tr.params),
                trace.counter("trainer.quarantine"))

    clean, q0 = run(poison=())
    healed, q1 = run(poison={1})  # first sync poisoned, retry clean
    assert q0 == 0 and q1 == 1
    la, lb = (jax.tree_util.tree_leaves(clean),
              jax.tree_util.tree_leaves(healed))
    assert len(la) == len(lb)
    for a, b in zip(la, lb):
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes()


def test_trainer_escalates_when_quarantine_retry_also_nonfinite(tmp_path):
    """Persistently non-finite gradients exhaust the quarantine, then
    the resume budget, and surface as a retryable TransportError — the
    elastic ladder's documented escalation order."""
    from rocnrdma_tpu.parallel.trainer import ElasticPolicy, Trainer

    trace.reset()
    tr = Trainer("llama-tiny", {"dp": 1, "tp": 1}, seed=11,
                 learning_rate=1e-2,
                 cross_slice_sync=_NaNOnceSync(range(1, 100)),
                 elastic=ElasticPolicy(str(tmp_path / "ck"),
                                       save_every=1, max_resumes=1))
    batch = np.random.default_rng(3).integers(
        0, 255, (2, 17)).astype(np.int32)
    with pytest.raises(TransportError) as ei:
        tr.step(batch)
    assert ei.value.retryable
    assert "non-finite" in str(ei.value)
    assert trace.counter("trainer.quarantine") >= 1
    assert trace.counter("trainer.resume") == 1
