"""Test configuration.

All tests run hardware-free: JAX is forced onto a virtual 8-device CPU
platform (the multi-chip sharding story is validated on a virtual mesh,
mirroring how the driver's ``dryrun_multichip`` runs), and the transport
tests use the emulated engine backend, which needs no NIC.
"""

import os
import sys

# Must be set before jax is imported anywhere in the test process.
# Hard-set (not setdefault): the ambient environment may point JAX at a
# real TPU, but the test suite is defined to be hardware-free.
os.environ["JAX_PLATFORMS"] = "cpu"

# The ambient environment may inject a TPU PJRT plugin via a
# sitecustomize hook that imports jax at interpreter startup — before
# this conftest runs — with JAX_PLATFORMS pointing at a device tunnel
# that hangs when unreachable. Env vars are too late by then; force the
# already-imported jax onto CPU through its config API.
sys.path[:] = [p for p in sys.path if ".axon_site" not in p]
os.environ["PYTHONPATH"] = ":".join(
    p for p in os.environ.get("PYTHONPATH", "").split(":")
    if p and ".axon_site" not in p)
if "jax" in sys.modules:
    import jax

    jax.config.update("jax_platforms", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Ring stall deadline for the suite. The default (30 s) is sized for
# production fail-fast, but this 1-vCPU CI box can legitimately exceed
# it when the suite runs concurrently with other load: measured round 4,
# tests/test_jax_zero_copy.py was 1 failure ("ring(fused2): poll
# timeout") in 12 runs racing bench.py, and 20/20 green unloaded.
# Tests that assert the deadline semantics set their own tight value
# via monkeypatch (tests/test_zero_copy.py).
os.environ.setdefault("TDR_RING_TIMEOUT_MS", "120000")

import glob  # noqa: E402
import subprocess  # noqa: E402

import pytest  # noqa: E402

# ------------------------------------------------------------------
# Native staleness guard: rebuild libtdr.so (and the sanitize variant,
# when present) whenever any native source/header is newer than the
# checked artifact. The Python loader (transport/engine.py) only
# builds when the .so is MISSING, so without this an ABI change —
# telemetry event structs, counter registry layout — would silently
# run the suite against a stale library and fail (or worse, pass) for
# the wrong reasons.
_NATIVE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "rocnrdma_tpu", "native")


def _native_sources():
    return (glob.glob(os.path.join(_NATIVE, "src", "*.cc"))
            + glob.glob(os.path.join(_NATIVE, "src", "*.h"))
            + glob.glob(os.path.join(_NATIVE, "include", "tdr", "*.h"))
            + [os.path.join(_NATIVE, "Makefile")])


def _stale(artifact: str) -> bool:
    art_mtime = os.path.getmtime(artifact)
    return any(os.path.getmtime(src) > art_mtime
               for src in _native_sources())


def _make(target=None) -> None:
    cmd = ["make", "-s", "-C", _NATIVE, "TUNE=native"]
    if target:
        cmd.append(target)
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=600)
    if proc.returncode != 0:
        # Surface the compiler diagnostic — an opaque CalledProcessError
        # at collection time would hide what failed to build.
        raise RuntimeError(
            f"native rebuild failed ({' '.join(cmd)}):\n"
            f"{proc.stdout[-2000:]}\n{proc.stderr[-4000:]}")


def _ensure_fresh_native() -> None:
    so = os.path.join(_NATIVE, "libtdr.so")
    if not os.path.exists(so) or _stale(so):
        _make()
    san = os.path.join(_NATIVE, "libtdr_san.so")
    # The sanitize artifact is built on demand by the slow tier; only
    # keep it fresh if it already exists (building ASan objects on
    # every tier-1 run would be pure tax).
    if os.path.exists(san) and _stale(san):
        _make("sanitize")


_ensure_fresh_native()


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: heavy tests excluded from the tier-1 gate "
        "(ROADMAP.md runs -m 'not slow' under a wall-clock budget)")


@pytest.fixture(autouse=True)
def _reset_trace():
    from rocnrdma_tpu.utils.trace import trace

    trace.reset()
    yield
