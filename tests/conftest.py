"""Test configuration.

All tests run hardware-free: JAX is forced onto a virtual 8-device CPU
platform (the multi-chip sharding story is validated on a virtual mesh,
mirroring how the driver's ``dryrun_multichip`` runs), and the transport
tests use the emulated engine backend, which needs no NIC.
"""

import os
import sys

# Must be set before jax is imported anywhere in the test process.
# Hard-set (not setdefault): the ambient environment may point JAX at a
# real TPU, but the test suite is defined to be hardware-free.
os.environ["JAX_PLATFORMS"] = "cpu"

# The ambient environment may inject a TPU PJRT plugin via a
# sitecustomize hook that imports jax at interpreter startup — before
# this conftest runs — with JAX_PLATFORMS pointing at a device tunnel
# that hangs when unreachable. Env vars are too late by then; force the
# already-imported jax onto CPU through its config API.
sys.path[:] = [p for p in sys.path if ".axon_site" not in p]
os.environ["PYTHONPATH"] = ":".join(
    p for p in os.environ.get("PYTHONPATH", "").split(":")
    if p and ".axon_site" not in p)
if "jax" in sys.modules:
    import jax

    jax.config.update("jax_platforms", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Ring stall deadline for the suite. The default (30 s) is sized for
# production fail-fast, but this 1-vCPU CI box can legitimately exceed
# it when the suite runs concurrently with other load: measured round 4,
# tests/test_jax_zero_copy.py was 1 failure ("ring(fused2): poll
# timeout") in 12 runs racing bench.py, and 20/20 green unloaded.
# Tests that assert the deadline semantics set their own tight value
# via monkeypatch (tests/test_zero_copy.py).
os.environ.setdefault("TDR_RING_TIMEOUT_MS", "120000")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: heavy tests excluded from the tier-1 gate "
        "(ROADMAP.md runs -m 'not slow' under a wall-clock budget)")


@pytest.fixture(autouse=True)
def _reset_trace():
    from rocnrdma_tpu.utils.trace import trace

    trace.reset()
    yield
