"""TPU cross-lowering checks for the Pallas kernels.

Interpret mode validates numerics but NOT the Mosaic lowering — round 4
shipped an lse output whose (1, 1, block_q) block violated Mosaic's
last-two-dims tiling rule, invisible to every interpret-mode test and
fatal on hardware. ``jax.export`` with ``platforms=["tpu"]`` runs the
Pallas→Mosaic lowering on this CPU-only host, so tiling/layout
violations fail HERE instead of on the (intermittently reachable)
chip. Shapes are the llama3-1b production geometry (head_dim 128).
"""

import jax
import jax.numpy as jnp
from jax import export

from rocnrdma_tpu.ops.attention import flash_attention
from rocnrdma_tpu.ops.rmsnorm import rmsnorm

Q = jax.ShapeDtypeStruct((1, 16, 2048, 128), jnp.bfloat16)
KV = jax.ShapeDtypeStruct((1, 8, 2048, 128), jnp.bfloat16)


def test_flash_attention_fwd_lowers_for_tpu():
    exp = export.export(
        jax.jit(lambda q, k, v: flash_attention(q, k, v, True)),
        platforms=["tpu"])(Q, KV, KV)
    assert "tpu" in [p.lower() for p in exp.platforms]


def test_flash_attention_bwd_lowers_for_tpu():
    exp = export.export(
        jax.jit(jax.grad(
            lambda q, k, v: flash_attention(q, k, v, True)
            .astype(jnp.float32).sum(), argnums=(0, 1, 2))),
        platforms=["tpu"])(Q, KV, KV)
    assert "tpu" in [p.lower() for p in exp.platforms]


def test_rmsnorm_fwd_and_bwd_lower_for_tpu():
    x = jax.ShapeDtypeStruct((8, 2048, 2048), jnp.bfloat16)
    w = jax.ShapeDtypeStruct((2048,), jnp.float32)
    export.export(jax.jit(lambda x, w: rmsnorm(x, w)),
                  platforms=["tpu"])(x, w)
    export.export(
        jax.jit(jax.grad(
            lambda x, w: rmsnorm(x, w).astype(jnp.float32).sum(),
            argnums=(0, 1))), platforms=["tpu"])(x, w)
