"""TPU cross-lowering checks for the Pallas kernels.

Interpret mode validates numerics but NOT the Mosaic lowering — round 4
shipped an lse output whose (1, 1, block_q) block violated Mosaic's
last-two-dims tiling rule, invisible to every interpret-mode test and
fatal on hardware. ``jax.export`` with ``platforms=["tpu"]`` runs the
Pallas→Mosaic lowering on this CPU-only host, so tiling/layout
violations fail HERE instead of on the (intermittently reachable)
chip. Shapes are the llama3-1b production geometry (head_dim 128).
"""

import jax
import jax.numpy as jnp
from jax import export

from rocnrdma_tpu.ops.attention import flash_attention
from rocnrdma_tpu.ops.rmsnorm import rmsnorm

Q = jax.ShapeDtypeStruct((1, 16, 2048, 128), jnp.bfloat16)
KV = jax.ShapeDtypeStruct((1, 8, 2048, 128), jnp.bfloat16)


def test_flash_attention_fwd_lowers_for_tpu():
    exp = export.export(
        jax.jit(lambda q, k, v: flash_attention(q, k, v, True)),
        platforms=["tpu"])(Q, KV, KV)
    assert "tpu" in [p.lower() for p in exp.platforms]


def test_flash_attention_bwd_lowers_for_tpu():
    exp = export.export(
        jax.jit(jax.grad(
            lambda q, k, v: flash_attention(q, k, v, True)
            .astype(jnp.float32).sum(), argnums=(0, 1, 2))),
        platforms=["tpu"])(Q, KV, KV)
    assert "tpu" in [p.lower() for p in exp.platforms]


def test_rmsnorm_fwd_and_bwd_lower_for_tpu():
    x = jax.ShapeDtypeStruct((8, 2048, 2048), jnp.bfloat16)
    w = jax.ShapeDtypeStruct((2048,), jnp.float32)
    export.export(jax.jit(lambda x, w: rmsnorm(x, w)),
                  platforms=["tpu"])(x, w)
    export.export(
        jax.jit(jax.grad(
            lambda x, w: rmsnorm(x, w).astype(jnp.float32).sum(),
            argnums=(0, 1))), platforms=["tpu"])(x, w)


def test_llama_1b_pallas_forward_lowers_for_tpu():
    """The flagship-proxy model with the Pallas kernels as compute
    path (the TPU default) cross-lowers whole — composition through
    flax, RoPE, GQA, and both kernels (~4 s on CPU)."""
    from rocnrdma_tpu.models.llama import make_model

    model = make_model("llama3-1b", use_pallas_attention=True,
                       use_pallas_rmsnorm=True)
    tokens = jax.ShapeDtypeStruct((1, 2048), jnp.int32)
    params = jax.eval_shape(
        lambda r: model.init(r, jnp.zeros((1, 8), jnp.int32)),
        jax.ShapeDtypeStruct((2,), jnp.uint32))
    export.export(jax.jit(lambda p, t: model.apply(p, t)),
                  platforms=["tpu"])(params, tokens)


def test_llama_1b_pallas_train_step_lowers_for_tpu():
    """The production train step — Pallas kernels (incl. the Pallas
    flash backward), block remat, donated params/opt — cross-lowers
    for TPU (~15 s on CPU)."""
    import optax

    from rocnrdma_tpu.models.llama import cross_entropy_loss, make_model

    model = make_model("llama3-1b", use_pallas_attention=True,
                       use_pallas_rmsnorm=True, remat=True)
    tx = optax.adamw(1e-4)
    params = jax.eval_shape(
        lambda r: model.init(r, jnp.zeros((1, 8), jnp.int32)),
        jax.ShapeDtypeStruct((2,), jnp.uint32))
    opt = jax.eval_shape(tx.init, params)
    tokens = jax.ShapeDtypeStruct((2, 2049), jnp.int32)

    def step(p, o, t):
        loss, grads = jax.value_and_grad(
            lambda p_: cross_entropy_loss(
                model.apply(p_, t[:, :-1]), t[:, 1:]))(p)
        u, o = tx.update(grads, o, p)
        return optax.apply_updates(p, u), o, loss

    export.export(jax.jit(step, donate_argnums=(0, 1)),
                  platforms=["tpu"])(params, opt, tokens)


def test_shard_map_pallas_kernels_lower_for_tpu_mesh():
    """The shard_map manual-region dispatch (batch on dp, heads on tp
    — the multi-device compute path the Trainer engages) cross-lowers
    for an 8-device TPU mesh: attention (forward and grad) and rmsnorm.
    A single real chip can never exercise this configuration. The
    lowered module must actually CONTAIN the Pallas custom call —
    run_sharded silently falls back to the XLA reference when the
    context or divisibility check fails, and a silent fallback here
    would leave the test green while validating nothing."""
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from rocnrdma_tpu.ops.attention import attention
    from rocnrdma_tpu.ops.rmsnorm import rmsnorm
    from rocnrdma_tpu.ops.sharding import pallas_sharding

    assert len(jax.devices()) >= 8
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("dp", "tp"))
    q = jax.ShapeDtypeStruct((2, 16, 2048, 128), jnp.bfloat16)
    kv = jax.ShapeDtypeStruct((2, 8, 2048, 128), jnp.bfloat16)
    spec = NamedSharding(mesh, P("dp", "tp", None, None))

    def loss(q, k, v):
        return attention(q, k, v, causal=True,
                         use_pallas=True).astype(jnp.float32).sum()

    with pallas_sharding(mesh):
        exp = export.export(
            jax.jit(jax.grad(loss, argnums=(0, 1, 2)),
                    in_shardings=(spec, spec, spec)),
            platforms=["tpu"])(q, kv, kv)
    assert exp.nr_devices == 8
    assert "tpu_custom_call" in exp.mlir_module()  # Pallas really ran

    x = jax.ShapeDtypeStruct((8, 2048, 2048), jnp.bfloat16)
    w = jax.ShapeDtypeStruct((2048,), jnp.float32)
    xspec = NamedSharding(mesh, P("dp", None, None))
    with pallas_sharding(mesh):
        exp = export.export(
            jax.jit(lambda x, w: rmsnorm(x, w, use_pallas=True),
                    in_shardings=(xspec, NamedSharding(mesh, P()))),
            platforms=["tpu"])(x, w)
    assert "tpu_custom_call" in exp.mlir_module()

    # ... and the rmsnorm BACKWARD kernel under the same mesh (its
    # per-device row counts and manual axes are a distinct Mosaic
    # configuration from the unsharded grad export above).
    with pallas_sharding(mesh):
        exp = export.export(
            jax.jit(jax.grad(
                lambda x, w: rmsnorm(x, w, use_pallas=True)
                .astype(jnp.float32).sum(), argnums=(0, 1)),
                in_shardings=(xspec, NamedSharding(mesh, P()))),
            platforms=["tpu"])(x, w)
    assert "tpu_custom_call" in exp.mlir_module()


def test_flash_attention_lse_lowers_for_tpu():
    """The two-output (out, lse) forward — the primitive ring
    attention merges on — cross-lowers with both outputs live (the
    single-output path may DCE the lse write; this one cannot)."""
    from rocnrdma_tpu.ops.attention import flash_attention_lse

    def f(q, k, v):
        out, lse = flash_attention_lse(q, k, v, True)
        return out, lse

    export.export(jax.jit(f), platforms=["tpu"])(Q, KV, KV)
