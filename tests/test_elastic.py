"""Elastic training: SIGKILL a rank mid-training, restart it, resume.

The acceptance test of the recovery layer: two single-device trainers
(separate PROCESSES, cross-slice grads over the emu ring) train N
steps; rank 1 SIGKILLs itself inside a step's gradient sync. Rank 0's
elastic policy detects the retryable failure, rebuilds the world
(``RingWorld.rebuild`` — backoff until the restarted rank re-joins
under the bumped generation), restores its checkpoint, and re-runs the
step; the restarted rank 1 restores ITS checkpoint at startup and
rejoins the same rendezvous. Final params must be BITWISE equal to an
uninterrupted run at the same step count — recovery is exact, not
approximate.
"""

import os
import signal
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

STEPS = 4
DIE_AT = 2  # rank 1 SIGKILLs itself inside step 2's gradient sync

# One rank of the elastic training job. argv: rank base_port steps
# ckpt_dir die_at (0 = never) [world_size] [coordinator_address].
# Without a coordinator address this is the LEGACY pairwise
# rendezvous — the world-2 test below deliberately pins that fallback;
# with one, rendezvous and every rebuild are arbitrated.
RANK_SCRIPT = r"""
import os, signal, sys
import numpy as np

rank = int(sys.argv[1]); base = int(sys.argv[2]); steps = int(sys.argv[3])
ckdir = sys.argv[4]; die_at = int(sys.argv[5])
world_sz = int(sys.argv[6]) if len(sys.argv) > 6 else 2
ctl = sys.argv[7] if len(sys.argv) > 7 else ""

from rocnrdma_tpu.transport.engine import Engine
from rocnrdma_tpu.collectives.world import RingWorld
from rocnrdma_tpu.collectives.jax_shim import CrossSliceAllReduce
from rocnrdma_tpu.parallel.trainer import ElasticPolicy, Trainer
from rocnrdma_tpu.parallel.checkpoint import restore_checkpoint, \
    save_checkpoint
from rocnrdma_tpu.utils.trace import trace

eng = Engine("emu")
world = RingWorld(eng, rank, world_sz, None if ctl else base,
                  timeout_ms=60000, controller=(ctl or None),
                  world_name="elastic")
sync = CrossSliceAllReduce(world, mean=True)


class KillSwitch:
    '''SIGKILL this process on its Nth gradient sync — "a rank dies
    mid-step", deterministically.'''

    def __init__(self, inner, at):
        self.inner = inner
        self.at = at
        self.n = 0

    def __call__(self, tree):
        self.n += 1
        if self.at > 0 and self.n == self.at:
            os.kill(os.getpid(), signal.SIGKILL)
        return self.inner(tree)

    def __getattr__(self, name):  # .world / .reset_transport_cache
        return getattr(self.inner, name)


sync = KillSwitch(sync, die_at)
ck = os.path.join(ckdir, f"rank{rank}")
tr = Trainer("llama-tiny", {"dp": 1, "tp": 1}, seed=5, learning_rate=1e-2,
             cross_slice_sync=sync,
             elastic=ElasticPolicy(ck, save_every=1, max_resumes=6,
                                   rebuild=dict(max_attempts=20,
                                                backoff_s=0.2,
                                                backoff_cap_s=2.0,
                                                timeout_ms=20000)))
start = 0
if os.path.exists(ck + ".npz"):
    start = restore_checkpoint(ck, tr)
    print("RESTORED", rank, start, flush=True)

rng = np.random.default_rng(17)
batches = [rng.integers(0, 255, (world_sz, 2, 17)).astype(np.int32)
           for _ in range(steps)]
for i in range(start, steps):
    tr.step(batches[i][rank])

save_checkpoint(os.path.join(ckdir, f"final{rank}"), tr, steps)
print("DONE", rank, "resume=%d" % trace.counter("trainer.resume"),
      "rebuild=%d" % trace.counter("world.rebuild"),
      "restore=%d" % trace.counter("ckpt.restore"), flush=True)
"""


def _free_base():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn(rank, base, ckdir, die_at, world=2, ctl="", steps=STEPS):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    # Dead-peer detection rides the TCP close (fast); the deadline is
    # only the wedge backstop and must stay inside the harness timeout.
    env["TDR_RING_TIMEOUT_MS"] = "30000"
    return subprocess.Popen(
        [sys.executable, "-c", RANK_SCRIPT, str(rank), str(base),
         str(steps), ckdir, str(die_at), str(world), ctl],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)


def _finish(proc, timeout=420):
    out, err = proc.communicate(timeout=timeout)
    assert proc.returncode == 0, (
        f"rc={proc.returncode}\nstdout:\n{out}\nstderr:\n{err}")
    return out


def _final_params(ckdir, rank):
    with np.load(os.path.join(ckdir, f"final{rank}.npz")) as z:
        return {k: z[k].copy() for k in z.files
                if k.startswith(("params/", "__dtype__/params/"))}


def _run_pair(ckdir, die_at):
    base = _free_base()
    p0 = _spawn(0, base, ckdir, 0)
    p1 = _spawn(1, base, ckdir, die_at)
    out1 = None
    if die_at:
        # Rank 1 kills itself mid-step; restart it, exactly as a
        # supervisor (k8s, slurm) would.
        p1.wait(timeout=300)
        assert p1.returncode == -signal.SIGKILL, p1.returncode
        p1b = _spawn(1, base, ckdir, 0)
        out1 = _finish(p1b)
        # The restarted rank must have come back from ITS checkpoint,
        # not from scratch.
        assert "RESTORED 1" in out1, out1
    else:
        out1 = _finish(p1)
    out0 = _finish(p0)
    return out0, out1


def test_sigkill_restart_resumes_bitwise_equal(tmp_path):
    clean_dir = str(tmp_path / "clean")
    faulty_dir = str(tmp_path / "faulty")
    os.makedirs(clean_dir)
    os.makedirs(faulty_dir)

    clean0, _ = _run_pair(clean_dir, die_at=0)
    faulty0, faulty1 = _run_pair(faulty_dir, die_at=DIE_AT)

    # The surviving rank recovered through the full path: resume →
    # rebuild → checkpoint restore, all observable in its counters.
    done = [l for l in faulty0.splitlines() if l.startswith("DONE 0")]
    assert done, faulty0
    assert "resume=0" not in done[0], done[0]
    assert "rebuild=0" not in done[0], done[0]
    assert "restore=0" not in done[0], done[0]

    # Bitwise parity: interrupted+recovered == uninterrupted, and the
    # two ranks of the faulty run stayed in DP lockstep.
    clean = _final_params(clean_dir, 0)
    faulty = _final_params(faulty_dir, 0)
    faulty_r1 = _final_params(faulty_dir, 1)
    assert set(clean) == set(faulty)
    for key in clean:
        assert clean[key].tobytes() == faulty[key].tobytes(), key
    for key in faulty:
        assert faulty[key].tobytes() == faulty_r1[key].tobytes(), key


W8 = 8
W8_STEPS = 3
W8_DIE = (3, 6)  # two ranks SIGKILL themselves at the same step


def _run_world8(ckdir, die, steps=W8_STEPS, timeout=900):
    """One arbitrated world-8 run: coordinator in this process,
    subprocess ranks; ``die`` ranks SIGKILL themselves inside step 2's
    gradient sync and are restarted by the supervisor."""
    from rocnrdma_tpu.control.coordinator import Coordinator

    coord = Coordinator(port=0, lease_ms=8000,
                        port_base=_free_base()).start()
    try:
        procs = {r: _spawn(r, 0, ckdir, die_at=2 if r in die else 0,
                           world=W8, ctl=coord.address, steps=steps)
                 for r in range(W8)}
        outs = {}
        for r in die:
            procs[r].wait(timeout=timeout)
            assert procs[r].returncode == -signal.SIGKILL, \
                procs[r].returncode
        # Restart the killed ranks, exactly as a supervisor would; the
        # coordinator lease-expires (or supersedes) their dead
        # incarnations and re-admits them under a bumped generation.
        for r in die:
            procs[r] = _spawn(r, 0, ckdir, die_at=0, world=W8,
                              ctl=coord.address, steps=steps)
        for r in range(W8):
            outs[r] = _finish(procs[r], timeout=timeout)
        return outs
    finally:
        coord.stop()


@pytest.mark.slow
def test_world8_two_simultaneous_kills_rejoin_bitwise(tmp_path):
    """World 8 under the arbitrated control plane with TWO ranks
    SIGKILLed at the same step and restarted: the coordinator declares
    them dead, bumps the generation, re-admits the new incarnations,
    and the run converges bitwise-equal to the uninterrupted world-8
    run — kill + rejoin mid-training at the ROADMAP item-5 scale."""
    clean_dir = str(tmp_path / "clean")
    faulty_dir = str(tmp_path / "faulty")
    os.makedirs(clean_dir)
    os.makedirs(faulty_dir)

    _run_world8(clean_dir, die=())
    outs = _run_world8(faulty_dir, die=W8_DIE)

    # Both restarted ranks came back from THEIR checkpoints.
    for r in W8_DIE:
        assert f"RESTORED {r}" in outs[r], outs[r]
    # A surviving rank recovered through the full arbitrated path.
    done = [l for l in outs[0].splitlines() if l.startswith("DONE 0")]
    assert done, outs[0]
    assert "resume=0" not in done[0], done[0]
    assert "rebuild=0" not in done[0], done[0]

    clean = _final_params(clean_dir, 0)
    faulty = _final_params(faulty_dir, 0)
    assert set(clean) == set(faulty)
    for key in clean:
        assert clean[key].tobytes() == faulty[key].tobytes(), key
    # And every rank of the faulty run stayed in DP lockstep.
    for r in range(1, W8):
        other = _final_params(faulty_dir, r)
        for key in faulty:
            assert faulty[key].tobytes() == other[key].tobytes(), \
                (r, key)


def test_world4_shrink_to_3_resize_bitwise_parity(tmp_path):
    """World RESIZE, the shrink side: a 4-rank resizable world loses
    rank 3 to a clean leave; the survivors' next collective fails
    retryable, ``rebuild()`` re-arbitrates the SAME incarnations as a
    contiguous world-3 (no process restart, no checkpoint), and the
    post-shrink allreduce is bitwise-exact at the new size. The resize
    count lands in the schedule digest, so a membership-view split can
    never silently agree."""
    import threading

    from rocnrdma_tpu.collectives.world import RingWorld
    from rocnrdma_tpu.control.coordinator import Coordinator
    from rocnrdma_tpu.transport.engine import Engine, TransportError

    coord = Coordinator(port=0, lease_ms=2000,
                        port_base=_free_base()).start()
    engines = [Engine("emu") for _ in range(4)]
    worlds = [None] * 4
    try:
        errs = [None] * 4

        def boot(r):
            try:
                worlds[r] = RingWorld(engines[r], r, 4, None,
                                      controller=coord.address,
                                      world_name="shrink",
                                      timeout_ms=20000, resizable=True)
            except Exception as e:
                errs[r] = e

        ts = [threading.Thread(target=boot, args=(r,)) for r in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert all(e is None for e in errs), errs

        # Round 1 at world 4: payload rank+1, bitwise-checked.
        r1 = [None] * 4

        def ar4(r):
            buf = np.full(512, 3 * (r + 1), np.int32)
            worlds[r].allreduce(buf)
            r1[r] = buf

        ts = [threading.Thread(target=ar4, args=(r,)) for r in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        want4 = np.full(512, 3 * 10, np.int32)  # 3 * (1+2+3+4)
        for r in range(4):
            assert r1[r].tobytes() == want4.tobytes(), r

        # Rank 3 leaves cleanly (autoscaler scale-down).
        worlds[3].close()
        worlds[3] = None

        # The next heartbeat response carries the resize hint to EVERY
        # survivor — including rank 1, which is not ring-adjacent to
        # the departed rank and would otherwise stall a full ring
        # timeout before noticing. With the hint set, the first
        # collective attempt fails fast at entry instead.
        import time
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            if all(worlds[r]._resize_pending for r in range(3)):
                break
            time.sleep(0.05)
        assert all(worlds[r]._resize_pending for r in range(3))

        # Survivors: the next collective fails retryable; rebuild()
        # re-arbitrates and the coordinator answers with the SHRUNK
        # shape. Payload is recomputed from the post-rebuild rank.
        r2 = [None] * 3
        fails = [None] * 3

        def recover(r):
            w = worlds[r]
            try:
                for attempt in range(8):
                    buf = np.full(512, 7 * (w.rank + 1), np.int32)
                    try:
                        w.allreduce(buf)
                        r2[r] = buf
                        return
                    except TransportError as e:
                        if not getattr(e, "retryable", False):
                            raise
                        w.rebuild(max_attempts=10, backoff_s=0.2,
                                  timeout_ms=10000,
                                  reason="rank 3 left (shrink)")
                raise AssertionError("no successful post-shrink round")
            except BaseException as e:
                fails[r] = e

        ts = [threading.Thread(target=recover, args=(r,))
              for r in range(3)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert all(f is None for f in fails), fails

        want3 = np.full(512, 7 * 6, np.int32)  # 7 * (1+2+3)
        for r in range(3):
            w = worlds[r]
            assert w.world == 3 and w.rank == r, (r, w.world, w.rank)
            assert w._ctl_resizes == 1
            assert ":r1" in w.control_stamp, w.control_stamp
            assert r2[r].tobytes() == want3.tobytes(), r
    finally:
        for w in worlds:
            if w is not None:
                w.close()
        coord.stop()
        for eng in engines:
            eng.close()
