"""KV-cache incremental decode (models.llama.generate)."""

import jax
import jax.numpy as jnp
import numpy as np

from rocnrdma_tpu.models.llama import (
    generate, init_cache, init_params, make_model)


def _tiny():
    model = make_model("llama-tiny")
    params = init_params(model, jax.random.PRNGKey(0))
    return model, params


def test_cached_prefill_matches_full_forward():
    model, params = _tiny()
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 255, (2, 12)), jnp.int32)
    full = model.apply(params, tokens)
    cache = init_cache(model.cfg, 2, 64)
    cached, _ = model.apply(params, tokens, cache=cache, pos=0)
    np.testing.assert_allclose(np.asarray(full), np.asarray(cached),
                               rtol=2e-4, atol=2e-4)


def test_incremental_decode_matches_full_forward():
    """Feeding tokens one at a time through the cache must reproduce
    the full-sequence forward at every position."""
    model, params = _tiny()
    rng = np.random.default_rng(1)
    tokens = jnp.asarray(rng.integers(0, 255, (1, 10)), jnp.int32)
    full = model.apply(params, tokens)  # (1, 10, V)

    cache = init_cache(model.cfg, 1, 64)
    outs = []
    for i in range(10):
        logits, cache = model.apply(params, tokens[:, i:i + 1],
                                    cache=cache, pos=i)
        outs.append(np.asarray(logits[:, 0]))
    inc = np.stack(outs, axis=1)
    np.testing.assert_allclose(np.asarray(full), inc, rtol=2e-4, atol=2e-4)


def test_generate_greedy_matches_no_cache_loop():
    """generate() (prefill + scan decode) must emit exactly the tokens
    a naive full-forward argmax loop emits."""
    model, params = _tiny()
    prompt = jnp.asarray(
        np.random.default_rng(2).integers(0, 255, (2, 5)), jnp.int32)
    got = np.asarray(generate(model, params, prompt, max_new_tokens=6))

    seq = prompt
    want = []
    for _ in range(6):
        logits = model.apply(params, seq)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        want.append(np.asarray(nxt))
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    want = np.stack(want, axis=1)
    np.testing.assert_array_equal(got, want)


def test_generate_sampled_shapes_and_determinism():
    model, params = _tiny()
    prompt = jnp.ones((1, 3), jnp.int32)
    a = np.asarray(generate(model, params, prompt, 4, temperature=0.8,
                            rng=jax.random.PRNGKey(7)))
    b = np.asarray(generate(model, params, prompt, 4, temperature=0.8,
                            rng=jax.random.PRNGKey(7)))
    c = np.asarray(generate(model, params, prompt, 4, temperature=0.8,
                            rng=jax.random.PRNGKey(8)))
    assert a.shape == (1, 4)
    np.testing.assert_array_equal(a, b)       # same key -> same tokens
    assert a.dtype == np.int32
    del c  # different keys may legitimately coincide on a tiny model


def test_generate_respects_max_seq_len():
    model, params = _tiny()
    prompt = jnp.ones((1, 120), jnp.int32)
    import pytest

    with pytest.raises(ValueError):
        generate(model, params, prompt, max_new_tokens=64)  # 184 > 128
