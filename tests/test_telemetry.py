"""Flight-recorder tests: the native telemetry subsystem end to end.

Covers the contracts ISSUE 3 pins:
- TDR_TELEMETRY=0 leaves ZERO events (the one-branch guard);
- a sealed chunk's full lifecycle (post → tx → rx → verify-fail →
  NAK → retransmit → verify-ok → completion) is visible as ORDERED
  events on the correct engine/QP tracks;
- the event ring stays bounded under a soak with fault-plan corrupt
  riders (reusing tools/fault_soak.py's rider generator);
- log2 histogram bucket math (Python percentile estimates and the
  native bucket assignment agree);
- the Perfetto export is valid JSON, deterministic for a given
  recording, and replay-stable across two identical world-2 runs;
- the unified counter registry carries the integrity.*/fault.* names
  and one clock domain spans native and Python events.
"""

import json
import os
import socket
import sys
import threading
from collections import Counter

import numpy as np
import pytest

from rocnrdma_tpu import telemetry
from rocnrdma_tpu.transport.engine import (
    Engine, fault_plan_reset, loopback_pair, native_counters,
    telemetry_dropped, telemetry_recorded, telemetry_reset)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture(autouse=True)
def _telemetry_env():
    """Restore the telemetry/fault env and clear both registries
    around every test — recording state must never leak."""
    keys = ("TDR_TELEMETRY", "TDR_TELEMETRY_RING", "TDR_FAULT_PLAN")
    saved = {k: os.environ.get(k) for k in keys}
    yield
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    telemetry_reset()
    fault_plan_reset()


def _send_recv(a, b, e1, e2, nbytes=4096, wr=1):
    """One sealed SEND/RECV exchange over a loopback pair."""
    src = np.full(nbytes, 0x5A, dtype=np.uint8)
    dst = np.zeros(nbytes, dtype=np.uint8)
    smr, dmr = e1.reg_mr(src), e2.reg_mr(dst)
    try:
        b.post_recv(dmr, 0, nbytes, wr_id=wr)
        a.post_send(smr, 0, nbytes, wr_id=wr)
        assert a.wait(wr, timeout_ms=30000).ok
        assert b.wait(wr, timeout_ms=30000).ok
        np.testing.assert_array_equal(src, dst)
    finally:
        smr.deregister()
        dmr.deregister()


def test_disabled_records_nothing():
    """TDR_TELEMETRY=0: the entire transport path must record zero
    events and zero drops — the one-branch-per-site contract."""
    os.environ["TDR_TELEMETRY"] = "0"
    telemetry_reset()
    e1, e2 = Engine("emu"), Engine("emu")
    a, b = loopback_pair(e1, free_port(), e2)
    try:
        _send_recv(a, b, e1, e2)
    finally:
        a.close(); b.close(); e1.close(); e2.close()
    assert telemetry_recorded() == 0
    assert telemetry_dropped() == 0
    assert telemetry.drain() == []


def test_chunk_lifecycle_with_nak_ordering():
    """A seal-NAK'd chunk's full lifecycle — post → tx → rx →
    verify-fail → NAK → retransmit → verify-ok → completion — appears
    as ordered events on the correct sender/receiver tracks."""
    os.environ["TDR_FAULT_PLAN"] = "send:nth=1:corrupt=2"
    fault_plan_reset()
    telemetry.enable()
    e_tx, e_rx = Engine("emu"), Engine("emu")
    a, b = loopback_pair(e_tx, free_port(), e_rx)
    tx_eng, rx_eng = e_tx.telemetry_id, e_rx.telemetry_id
    try:
        assert a.has_seal, "seal must be on for the NAK lifecycle"
        _send_recv(a, b, e_tx, e_rx)
        events = telemetry.drain()
    finally:
        a.close(); b.close(); e_tx.close(); e_rx.close()

    def first(name, engine=None):
        for ev in events:
            if ev.name == name and (engine is None or ev.engine == engine):
                return ev
        raise AssertionError(
            f"event {name} (engine={engine}) missing from "
            f"{[(e.name, e.engine) for e in events]}")

    post = first("post_send", tx_eng)
    tx = first("wire_tx", tx_eng)
    rx = first("wire_rx", rx_eng)
    vfail = first("verify_fail", rx_eng)
    nak = first("nak", rx_eng)
    retx = first("retx", tx_eng)
    vok = first("verify_ok", rx_eng)
    wc = first("wc", tx_eng)
    # One clock domain + causal chain => monotonic timestamps.
    chain = [post, tx, rx, vfail, nak, retx, vok]
    for earlier, later in zip(chain, chain[1:]):
        assert earlier.ts_ns <= later.ts_ns, (
            f"{earlier.name} after {later.name}")
    assert wc.ts_ns >= vok.ts_ns
    # The NAK'd frame and its retransmission name the same chunk seq.
    assert nak.id == retx.id == vfail.id == vok.id
    # Detection fired exactly where the registry says it did.
    counters = native_counters()
    assert counters["integrity.failed"] >= 1
    assert counters["integrity.retransmitted"] >= 1
    assert counters["fault.hits"] >= 1


def test_ring_bounded_under_soak_riders():
    """A long run with a fault_soak corrupt rider armed must keep the
    ring at its configured bound: oldest events are overwritten (and
    counted dropped), never unbounded growth."""
    sys.path.insert(0, TOOLS)
    try:
        from fault_soak import make_fault_plan
    finally:
        sys.path.remove(TOOLS)
    # steps=1 pins both riders' nth to 1: the corrupt rider fires on
    # the first sealed frame, deterministically. The ring:once clause
    # is dropped — this soak drives raw QPs, not collectives.
    rider = [c for c in make_fault_plan(seed=3, steps=1).split(",")
             if c.startswith("send:")][0]
    os.environ["TDR_FAULT_PLAN"] = rider
    fault_plan_reset()
    os.environ["TDR_TELEMETRY_RING"] = "1024"
    telemetry.enable()
    e1, e2 = Engine("emu"), Engine("emu")
    a, b = loopback_pair(e1, free_port(), e2)
    try:
        for i in range(150):
            _send_recv(a, b, e1, e2, nbytes=512, wr=i + 1)
    finally:
        a.close(); b.close(); e1.close(); e2.close()
    recorded, dropped = telemetry_recorded(), telemetry_dropped()
    events = telemetry.drain()
    assert recorded > 1024, "soak too small to exercise the bound"
    assert len(events) <= 1024, "ring exceeded its configured bound"
    assert dropped > 0 and recorded == len(events) + dropped
    # The rider actually fired and its healing shows in the registry
    # (the retx EVENT itself was near the soak's start and may have
    # been overwritten — that is the flight-recorder contract; the
    # registry is the lossless record).
    counters = native_counters()
    assert counters["integrity.retransmitted"] >= 1


def test_histogram_bucket_math():
    """Log2 bucket edges and percentile estimates, Python vs native —
    octave view AND the fine (log2 × 8) rows the percentiles now read
    (the BENCH_r06 saturation fix: estimates are real numbers, not
    octave edges)."""
    from rocnrdma_tpu.telemetry.recorder import (bucket_upper,
                                                 fine_bucket_upper,
                                                 hist_percentile)
    from rocnrdma_tpu.transport.engine import (telemetry_hist_fine_buckets,
                                               telemetry_hist_fine_upper)

    # Octave upper edges: bucket b holds [2^(b-1), 2^b).
    assert bucket_upper(0) == 0
    assert bucket_upper(1) == 1
    assert bucket_upper(13) == 8191
    buckets = [0] * 64
    buckets[3] = 10   # ten values in [4, 8)
    buckets[10] = 10  # ten values in [512, 1024)
    assert hist_percentile(buckets, 50) == bucket_upper(3)
    assert hist_percentile(buckets, 99) == bucket_upper(10)
    assert hist_percentile([0] * 64, 50) == 0

    # Fine edges: values 0..15 are exact; above that 8 sub-buckets per
    # octave, and the PYTHON mirror must agree with the NATIVE edge
    # function bucket-for-bucket (the percentile math reads these).
    nfine = telemetry_hist_fine_buckets()
    assert nfine >= 496
    for idx in list(range(0, 48)) + [80, 81, 87, 100, 495]:
        assert fine_bucket_upper(idx) == telemetry_hist_fine_upper(idx), idx
    assert fine_bucket_upper(15) == 15
    assert fine_bucket_upper(16) == 17   # first sub-bucket of [16, 32)
    # Sub-octave percentiles: a fine row concentrated at ~5000 (octave
    # [4096, 8192)) reports an edge INSIDE the octave, not 8191 — the
    # saturation signature this fix kills.
    fine = [0] * nfine
    # 5000 has bit_length 13, sub = (5000 >> 9) & 7 = 1 -> idx 81.
    fine[81] = 10
    p = hist_percentile(fine, 50)
    assert p == fine_bucket_upper(81) == 5119  # inside [4096, 8192)
    assert p != bucket_upper(13)

    # Native bucket assignment: a 4096-byte op lands in octave 13
    # (4096.bit_length() == 13) of chunk_bytes — and in fine bucket 80
    # (sub-bucket 0 of that octave).
    telemetry.enable()
    e1, e2 = Engine("emu"), Engine("emu")
    a, b = loopback_pair(e1, free_port(), e2)
    try:
        _send_recv(a, b, e1, e2, nbytes=4096)
    finally:
        a.close(); b.close(); e1.close(); e2.close()
    hist = telemetry.histograms()
    assert hist["chunk_bytes"][4096 .bit_length()] >= 1
    assert sum(hist["chunk_lat_us"]) >= 1
    from rocnrdma_tpu.transport.engine import telemetry_histograms_fine

    fine_h = telemetry_histograms_fine()
    assert fine_h["chunk_bytes"][80] >= 1
    # The folded octave view is exactly the fine view summed.
    assert sum(fine_h["chunk_bytes"]) == sum(hist["chunk_bytes"])


def test_snapshot_percentiles_not_saturated():
    """snapshot() percentiles come from the FINE rows: they must equal
    a recomputation from telemetry_histograms_fine() (never the coarse
    octave rows), so a spread of real latencies cannot collapse onto
    one octave upper edge — the BENCH_r06 record pinned p50/p90/p99 at
    8191/32767/65535 because the estimator had octave resolution."""
    from rocnrdma_tpu.telemetry.recorder import hist_percentiles
    from rocnrdma_tpu.transport.engine import telemetry_histograms_fine

    telemetry.enable()
    e1, e2 = Engine("emu"), Engine("emu")
    a, b = loopback_pair(e1, free_port(), e2)
    try:
        for i in range(20):  # a spread of op sizes -> a spread of lats
            _send_recv(a, b, e1, e2, nbytes=1024 << (i % 6), wr=i + 1)
    finally:
        a.close(); b.close(); e1.close(); e2.close()
    snap = telemetry.snapshot()
    fine = telemetry_histograms_fine()
    for name, buckets in fine.items():
        assert snap["percentiles"][name] == hist_percentiles(buckets), name
    # chunk_bytes spans octaves with sub-octave occupancy: its fine
    # row must occupy more buckets than its octave fold — the extra
    # resolution is real, not relabeled.
    octave = snap["histograms"]["chunk_bytes"]
    occupied_fine = sum(1 for v in fine["chunk_bytes"] if v)
    occupied_oct = sum(1 for v in octave if v)
    assert occupied_fine >= occupied_oct


def _world2_run():
    """One telemetry-on world-2 emu allreduce; returns (events,
    {engine_id: rank}) with events drained before teardown."""
    from rocnrdma_tpu.collectives.world import local_worlds

    telemetry.enable()
    worlds = local_worlds(2, free_port())
    labels = {w.engine.telemetry_id: w.rank for w in worlds}
    bufs = [np.full(32768, float(r + 1), dtype=np.float32)
            for r in range(2)]
    ts = [threading.Thread(target=worlds[r].allreduce, args=(bufs[r],))
          for r in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    for buf in bufs:
        np.testing.assert_array_equal(
            buf, np.full(32768, 3.0, dtype=np.float32))
    events = telemetry.timeline()
    for w in worlds:
        w.close()
    return events, labels


def test_perfetto_export_valid_and_replay_stable(tmp_path):
    """The export is schema-valid JSON, byte-deterministic for a given
    recording, and two identical world-2 runs produce the same
    per-rank event-name counts (replay stability)."""
    from rocnrdma_tpu.telemetry.perfetto import dumps, export_trace

    runs = []
    for i in range(2):
        events, labels = _world2_run()
        runs.append((events, labels))

    events, labels = runs[0]
    path = tmp_path / "trace.json"
    doc = export_trace(str(path), events=events,
                       engine_labels={e: f"rank{r}"
                                      for e, r in labels.items()})
    with open(path) as f:
        loaded = json.load(f)  # valid JSON or this raises
    assert loaded["traceEvents"], "export is empty"
    for ev in loaded["traceEvents"]:
        assert {"ph", "ts", "pid", "name"} <= set(ev)
    # Same recording in, byte-identical JSON out.
    assert dumps(doc) == dumps(export_trace(
        events=events, engine_labels={e: f"rank{r}"
                                      for e, r in labels.items()}))

    # Replay stability: identical runs produce identical per-rank
    # native event-name counts (timestamps and raw track ids differ;
    # the SHAPE of the recording must not). Engine-less events (the
    # copy pool's) ride thread timing, so the per-rank lifecycle set
    # is the stable contract.
    def shape(events, labels):
        return Counter((labels[ev.engine], ev.name) for ev in events
                       if ev.source == "native" and ev.engine in labels)

    s0, s1 = (shape(*run) for run in runs)
    assert s0 == s1, f"run shapes diverged: {s0 ^ s1}"
    # And the lifecycle is actually in there.
    for needed in ("post_send", "post_recv", "wire_tx", "wire_rx",
                   "verify_ok", "wc", "ring_begin", "ring_end"):
        assert any(name == needed for _, name in s0), f"missing {needed}"


def test_counter_registry_and_clock_anchor():
    """Registry names are stable (integrity.*/fault.*/copy.*/
    telemetry.*) and the native clock is the Python monotonic clock."""
    names = set(native_counters())
    assert {"integrity.sealed", "integrity.verified", "integrity.failed",
            "integrity.retransmitted", "fault.seen", "fault.hits",
            "copy.nt_bytes", "copy.plain_bytes", "telemetry.recorded",
            "telemetry.dropped", "fold.jobs", "fold.busy_us",
            "fold.pending", "progress.shards", "progress.wakeups",
            "progress.wc"} <= names
    from rocnrdma_tpu.telemetry.recorder import anchor

    a = anchor()
    assert a["python_ns_lo"] <= a["native_ns"] <= a["python_ns_hi"], a


def test_python_spans_merge_into_timeline():
    """Python tracer spans (trainer/collective tiers) merge with
    native events on one clock and export as duration slices."""
    from rocnrdma_tpu.utils.trace import trace

    telemetry.enable()
    e1, e2 = Engine("emu"), Engine("emu")
    a, b = loopback_pair(e1, free_port(), e2)
    try:
        with trace.span("test.outer", step=1):
            _send_recv(a, b, e1, e2)
    finally:
        a.close(); b.close(); e1.close(); e2.close()
    events = telemetry.timeline()
    span = [ev for ev in events if ev.name == "test.outer"]
    assert span and span[0].source == "python"
    native = [ev for ev in events if ev.source == "native"]
    assert native
    # The span END timestamp bounds the native events it contains.
    assert span[0].ts_ns >= min(ev.ts_ns for ev in native)
    doc = telemetry.export_trace(events=events)
    slices = [ev for ev in doc["traceEvents"]
              if ev.get("ph") == "X" and ev["name"] == "test.outer"]
    assert slices and slices[0]["dur"] >= 0


def test_tdr_top_renders_snapshot():
    """The live-view renderer produces a frame from a snapshot."""
    sys.path.insert(0, TOOLS)
    try:
        import tdr_top
    finally:
        sys.path.remove(TOOLS)
    telemetry.enable()
    e1, e2 = Engine("emu"), Engine("emu")
    a, b = loopback_pair(e1, free_port(), e2)
    try:
        _send_recv(a, b, e1, e2)
    finally:
        a.close(); b.close(); e1.close(); e2.close()
    frame = tdr_top.render(telemetry.snapshot())
    assert "flight recorder" in frame
    assert "chunk_lat_us" in frame
    assert "integrity.sealed" in frame
