"""Sequence-parallel trainer: parity vs the single-host model.

Each rank holds a contiguous token shard; attention reaches the full
sequence via the transport-rotated K/V ring; parameter gradients
average over the same transport. The whole path — layerwise jitted
halves + ring attention middle + stitched backward + mean-allreduce —
must reproduce the single-host full-sequence model: logits, loss, and
the trained parameters themselves.
"""

import threading

import numpy as np
import pytest

from test_transport import free_port


def _tiny(**kw):
    from rocnrdma_tpu.models.llama import LLAMA_TINY, make_model

    return make_model(LLAMA_TINY, **kw)


def _run_ranks(world_size, fn, base_port):
    """fn(rank, worlds) in one thread per rank; surfaces exceptions."""
    from rocnrdma_tpu.collectives.world import local_worlds

    worlds = local_worlds(world_size, base_port)
    results = [None] * world_size
    errs = []

    def go(r):
        try:
            results[r] = fn(r, worlds[r])
        except Exception as e:  # noqa: BLE001 — surfaced below
            import traceback

            errs.append((r, e, traceback.format_exc()))

    ts = [threading.Thread(target=go, args=(r,))
          for r in range(world_size)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    for w in worlds:
        w.close()
    assert not errs, errs[0][2]
    return results


def test_seq_parallel_forward_logits_parity():
    """Per-rank seq-parallel logits, concatenated, equal the
    single-host full-sequence forward."""
    import jax
    import jax.numpy as jnp

    from rocnrdma_tpu.parallel.seq_parallel import SeqParallelTrainer

    world_size, s_local, batch = 2, 16, 2
    S = world_size * s_local
    rng = np.random.default_rng(0)
    inputs = rng.integers(0, 255, size=(batch, S)).astype(np.int32)

    def rank_fn(r, world):
        tr = SeqParallelTrainer("llama-tiny", world, seed=0,
                                interpret=True)
        sl = slice(r * s_local, (r + 1) * s_local)
        logits = np.asarray(tr.forward(tr.params, inputs[:, sl]))
        params = tr.params
        tr.close()
        return logits, params

    results = _run_ranks(world_size, rank_fn, free_port() + 100)
    got = np.concatenate([lg for lg, _ in results], axis=1)

    model = _tiny()
    params = results[0][1]  # identical across ranks (same seed)
    want = np.asarray(model.apply(params, jnp.asarray(inputs)))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


# Interpret-mode Pallas makes the >2-rank training parities the
# heaviest tests in the repo (70-130 s each on the 1-vCPU CI box);
# world 2 gates the path in tier-1, the wider rings run in the slow
# tier (ROADMAP's -m 'not slow' budget).
@pytest.mark.parametrize(
    "world_size",
    [2, pytest.param(3, marks=pytest.mark.slow),
     pytest.param(4, marks=pytest.mark.slow)])
def test_seq_parallel_training_matches_single_host(world_size):
    """N optimizer steps of the seq-parallel trainer reproduce
    single-host full-sequence training: per-step global losses AND the
    final parameters (ranks stay replicated)."""
    _training_parity(world_size, "ring")


@pytest.mark.slow
def test_seq_parallel_training_ulysses_mode():
    """The same parity contract holds with sp_mode='ulysses' (the
    all-to-all strategy; llama-tiny's 2 KV heads divide world 2)."""
    _training_parity(2, "ulysses")


def test_seq_parallel_ulysses_rejects_indivisible_heads():
    """llama-tiny has 2 KV heads: world 3 must fail at construction
    on every rank, not stall mid-ring."""
    from rocnrdma_tpu.parallel.seq_parallel import SeqParallelTrainer

    def rank_fn(r, world):
        with pytest.raises(ValueError, match="divide"):
            SeqParallelTrainer("llama-tiny", world, sp_mode="ulysses",
                               interpret=True)
        return True

    assert all(_run_ranks(3, rank_fn, free_port() + 700))


def _training_parity(world_size, sp_mode):
    import jax
    import jax.numpy as jnp
    import optax

    from rocnrdma_tpu.models.llama import cross_entropy_loss
    from rocnrdma_tpu.parallel.seq_parallel import SeqParallelTrainer

    # SGD, not adamw: updates are LINEAR in the gradients, so the
    # fp-reordering-scale differences between the stitched and fused
    # backwards stay that scale in the trained params. (Adaptive
    # optimizers divide by sqrt(second moment); for a weight whose v≈0
    # a 1e-7 gradient difference flips the whole ±lr update — param
    # comparison after adamw steps measures chaos, not correctness.)
    s_local, batch, steps, lr = 16, 2, 3, 5e-2
    S = world_size * s_local
    rng = np.random.default_rng(world_size)
    data = [rng.integers(0, 255, size=(batch, S + 1)).astype(np.int32)
            for _ in range(steps)]

    def rank_fn(r, world):
        tr = SeqParallelTrainer("llama-tiny", world, seed=0,
                                interpret=True, optimizer=optax.sgd(lr),
                                sp_mode=sp_mode)
        sl = slice(r * s_local, (r + 1) * s_local)
        losses = []
        for tok in data:
            inputs = tok[:, :-1][:, sl]
            targets = tok[:, 1:][:, sl]
            losses.append(tr.step(inputs, targets))
        params = tr.params
        tr.close()
        return losses, params

    results = _run_ranks(world_size, rank_fn, free_port() + 200)
    # Every rank reports the same global loss and holds identical
    # params (the replication contract).
    for losses, params in results[1:]:
        np.testing.assert_allclose(losses, results[0][0], rtol=1e-6)
        for a, b in zip(jax.tree_util.tree_leaves(params),
                        jax.tree_util.tree_leaves(results[0][1])):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # Single-host reference: same init, same optimizer, full sequence.
    model = _tiny()
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), dtype=jnp.int32))
    tx = optax.sgd(lr)
    opt = tx.init(params)

    @jax.jit
    def ref_step(p, o, tok):
        def loss_fn(p_):
            logits = model.apply(p_, tok[:, :-1])
            return cross_entropy_loss(logits, tok[:, 1:])

        loss, g = jax.value_and_grad(loss_fn)(p)
        up, o = tx.update(g, o, p)
        return optax.apply_updates(p, up), o, loss

    ref_losses = []
    for tok in data:
        params, opt, loss = ref_step(params, opt, jnp.asarray(tok))
        ref_losses.append(float(loss))

    np.testing.assert_allclose(results[0][0], ref_losses,
                               rtol=2e-4, atol=2e-4)
    got_leaves = jax.tree_util.tree_leaves(results[0][1])
    want_leaves = jax.tree_util.tree_leaves(params)
    for a, b in zip(got_leaves, want_leaves):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-4)


def test_trainer_seq_parallel_front_door():
    """Trainer(cfg, seq_parallel=world) constructs the seq-parallel
    runner (the VERDICT's requested spelling)."""
    from rocnrdma_tpu.parallel.seq_parallel import SeqParallelTrainer
    from rocnrdma_tpu.parallel.trainer import Trainer

    def rank_fn(r, world):
        tr = Trainer("llama-tiny", seq_parallel=world, interpret=True)
        ok = isinstance(tr, SeqParallelTrainer)
        tr.close()
        return ok

    assert all(_run_ranks(2, rank_fn, free_port() + 300))


@pytest.mark.slow
def test_seq_parallel_remat_gradients_match():
    """remat=True (jax.checkpoint around the jitted halves) must not
    change the computed gradients — only when they are recomputed.
    Asserted exactly: same params, same batch, grads with and without
    remat are identical."""
    import jax

    from rocnrdma_tpu.parallel.seq_parallel import SeqParallelTrainer

    world_size, s_local = 2, 16
    rng = np.random.default_rng(11)
    tok = rng.integers(
        0, 255, size=(1, world_size * s_local + 1)).astype(np.int32)

    def run(remat):
        def rank_fn(r, world):
            tr = SeqParallelTrainer("llama-tiny", world, seed=0,
                                    interpret=True, remat=remat)
            sl = slice(r * s_local, (r + 1) * s_local)
            loss, grads = tr.forward_backward(
                tr.params, tok[:, :-1][:, sl], tok[:, 1:][:, sl])
            flat = [np.asarray(g) for g in
                    jax.tree_util.tree_leaves(grads)]
            tr.close()
            return float(loss), flat

        return _run_ranks(world_size, rank_fn, free_port() + 500)

    plain = run(False)
    remat = run(True)
    for (l0, g0), (l1, g1) in zip(plain, remat):
        assert l0 == l1
        for a, b in zip(g0, g1):
            np.testing.assert_array_equal(a, b)


@pytest.mark.slow
def test_seq_parallel_checkpoint_roundtrip(tmp_path):
    """Checkpoint/resume works for the seq-parallel trainer: save →
    diverge → restore round-trips params and step on every rank, and
    collective training continues (ranks replicated, so each rank's
    checkpoint is the same model — restore keeps them in lockstep)."""
    import jax
    import optax

    from rocnrdma_tpu.parallel.checkpoint import (
        restore_checkpoint, save_checkpoint)
    from rocnrdma_tpu.parallel.seq_parallel import SeqParallelTrainer

    world_size, s_local = 2, 16
    rng = np.random.default_rng(5)
    tok = rng.integers(
        0, 255, size=(1, world_size * s_local + 1)).astype(np.int32)

    def rank_fn(r, world):
        tr = SeqParallelTrainer("llama-tiny", world, seed=0,
                                interpret=True,
                                optimizer=optax.sgd(1e-2))
        sl = slice(r * s_local, (r + 1) * s_local)
        inputs, targets = tok[:, :-1][:, sl], tok[:, 1:][:, sl]
        tr.step(inputs, targets)
        snap = jax.tree_util.tree_map(np.asarray, tr.params)
        path = str(tmp_path / f"ckpt_r{r}")
        save_checkpoint(path, tr, step=1)
        tr.step(inputs, targets)  # diverge
        step = restore_checkpoint(path, tr)
        assert step == 1
        for a, b in zip(jax.tree_util.tree_leaves(snap),
                        jax.tree_util.tree_leaves(tr.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        loss = tr.step(inputs, targets)  # training continues, in sync
        tr.close()
        return loss

    losses = _run_ranks(world_size, rank_fn, free_port() + 400)
    assert np.isfinite(losses[0]) and losses[0] == losses[1]
