"""Kernel-module validation via the mock-kernel harness.

The reference's kernel code was only testable on Fiji+ConnectX hardware
(SURVEY.md §4); our kernel modules get a hardware-free CI leg instead:
``kernelmod/mock`` compiles the unmodified ``tpup2p.c``/``tpup2ptest.c``
against mock kernel headers and drives the full claim → acquire → pin →
map → revoke → teardown lifecycle (SURVEY.md §3 call stacks) with leak
counters. This test builds and runs that harness.
"""

import os
import shutil
import subprocess

import pytest

MOCK_DIR = os.path.join(os.path.dirname(__file__), os.pardir,
                        "kernelmod", "mock")


@pytest.mark.skipif(shutil.which("cc") is None and shutil.which("gcc") is None,
                    reason="no C compiler")
def test_mock_kernel_harness():
    env = dict(os.environ)
    if shutil.which("cc") is None:
        env["CC"] = "gcc"
    proc = subprocess.run(
        ["make", "-s", "-C", os.path.abspath(MOCK_DIR), "check"],
        capture_output=True, text=True, timeout=300, env=env)
    assert proc.returncode == 0, (
        f"harness failed\nstdout:\n{proc.stdout}\nstderr:\n{proc.stderr}")
    assert "MOCK-KERNEL HARNESS PASS" in proc.stdout
