"""Tier-1 wiring of the fault-soak runner (tools/fault_soak.py).

A short seeded configuration: 3 steps of 2-rank elastic DP training
with one injected transient collective fault, asserted bitwise-equal
to the clean run. The soak's CLI runs bigger/randomized plans; this
pins the contract in every tier-1 run.
"""

import importlib.util
import os

import jax
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_fault_soak():
    spec = importlib.util.spec_from_file_location(
        "fault_soak", os.path.join(REPO, "tools", "fault_soak.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


fs = _load_fault_soak()


def test_make_fault_plan_is_seeded():
    assert fs.make_fault_plan(7, 4) == fs.make_fault_plan(7, 4)
    assert fs.make_fault_plan(7, 4).startswith("ring:nth=")
    # The seeded plans now always carry a sealed-path corruption rider.
    assert ",send:nth=" in fs.make_fault_plan(7, 4)
    assert ":corrupt=" in fs.make_fault_plan(7, 4)


def test_soak_short_seeded_parity_mixed_plan(tmp_path):
    """Clean vs injected-fault elastic training under a MIXED plan —
    transient collective fault + sealed-payload corruption + a
    connection drop: identical final params, every clause demonstrably
    fired, and the corruption was detected (not silently averaged)."""
    steps, seed = 3, 1
    # make_fault_plan already mixes a ring fault with a corrupt rider;
    # add a deterministic connection drop (the 13th SEND-class post
    # lands mid-training for this config) for the full mixture.
    plan = fs.make_fault_plan(seed, steps) + ",conn:drop_after=12"
    clean, _ = fs.run_soak(steps=steps, seed=seed,
                           ckpt_dir=str(tmp_path / "clean"))
    faulty, stats = fs.run_soak(steps=steps, seed=seed,
                                ckpt_dir=str(tmp_path / "faulty"),
                                fault_plan=plan)
    # ring fault + corruption are nth-bounded within the run, so both
    # fire; the conn drop may add a third hit.
    assert stats["fault_hits"] >= 2, stats
    assert stats["resumes"] >= 1, stats
    assert stats["rebuilds"] >= 2, stats  # begin/ok traced per rank
    # The injected corruption was CAUGHT by the seal (and healed by
    # retransmit or by the elastic resume — either way, detected).
    assert stats["integrity_failed"] >= 1, stats
    la, lb = (jax.tree_util.tree_leaves(clean),
              jax.tree_util.tree_leaves(faulty))
    assert len(la) == len(lb)
    for a, b in zip(la, lb):
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes()


@pytest.mark.slow
def test_soak_world4_arbitrated_flap_parity(tmp_path):
    """The arbitrated control-plane path at world 4: rank 1 flaps
    (tears its transport down mid-step and rejoins), every rebuild is
    arbitrated by an in-process coordinator, and the run converges
    bitwise-equal to the clean run — with every generation bump a
    coordinator decision (ctl.* counters prove no rank guessed)."""
    steps, seed = 2, 5
    clean, _ = fs.run_soak(steps=steps, seed=seed, world=4,
                           ckpt_dir=str(tmp_path / "clean"))
    faulty, stats = fs.run_soak(steps=steps, seed=seed, world=4,
                                ckpt_dir=str(tmp_path / "faulty"),
                                coordinator=True, flap=(1, 2))
    assert fs.params_equal(clean, faulty)
    assert stats["resumes"] >= 1, stats
    assert stats["ctl"].get("ctl.report", 0) >= 1, stats
    assert stats["ctl"].get("ctl.rebuild", 0) >= 1, stats
    assert stats["ctl"].get("ctl.release", 0) >= 2, stats
    # All ranks ended on ONE coordinator-decided generation, > 0.
    assert len(stats["generations"]) == 1, stats
    assert stats["generations"][0] >= 1, stats


@pytest.mark.slow
def test_soak_world8_flap_two_faults_concurrent_parity(tmp_path):
    """The ROADMAP item-5 acceptance soak, in-process: world 8 with a
    flapping rank plus a second simultaneous failure class (sealed-
    payload corruptions, healed by NAK/retransmit), TWO concurrent
    named worlds sharing the engines, and every rebuild arbitrated —
    bitwise-equal to the clean run. The riders are deliberately the
    SELF-HEALING kind: process-wide ring/conn faults could land on
    the deliberately-elastic-free side world (see _run_side_world);
    the two-simultaneous-KILL case is the subprocess world-8 test in
    test_elastic.py."""
    steps, seed = 3, 8
    plan = "send:nth=6:corrupt=3,send:nth=55:corrupt=2"
    clean, _ = fs.run_soak(steps=steps, seed=seed, world=8,
                           ckpt_dir=str(tmp_path / "clean"))
    faulty, stats = fs.run_soak(steps=steps, seed=seed, world=8,
                                ckpt_dir=str(tmp_path / "faulty"),
                                fault_plan=plan, coordinator=True,
                                flap=(3, 2), concurrent=True)
    assert fs.params_equal(clean, faulty)
    assert stats["fault_hits"] >= 2, stats
    assert stats["resumes"] >= 1, stats
    assert stats["integrity_failed"] >= 1, stats
    assert stats["side_ok"], stats
    assert stats["ctl"].get("ctl.rebuild", 0) >= 1, stats
    assert len(stats["generations"]) == 1, stats
    assert stats["generations"][0] >= 1, stats


@pytest.mark.slow
def test_soak_topology_delegate_flap_parity(tmp_path):
    """The hierarchical elastic ladder (ROADMAP item 1 / PR 9
    satellite): a world-4 two-host-emulated soak (``--topology
    a,a,b,b``) where rank 2 — host b's delegate for shard 0, a member
    of BOTH its intra ring and an inter-host delegate ring — tears its
    transport down mid-step. Peers surface retryable tier failures,
    the rebuild brings the flat ring AND both tier rings back under
    the next generation, and the run converges bitwise-equal to the
    clean (also hierarchical) run. ``hier_collectives`` proves the
    two-tier schedule actually carried the gradient syncs."""
    steps, seed = 2, 21
    clean, cstats = fs.run_soak(steps=steps, seed=seed, world=4,
                                ckpt_dir=str(tmp_path / "clean"),
                                topology="a,a,b,b")
    assert cstats["hier_collectives"] >= 1, cstats
    faulty, stats = fs.run_soak(steps=steps, seed=seed, world=4,
                                ckpt_dir=str(tmp_path / "faulty"),
                                flap=(2, 2), topology="a,a,b,b")
    assert fs.params_equal(clean, faulty)
    assert stats["resumes"] >= 1, stats
    assert stats["rebuilds"] >= 1, stats
    assert stats["hier_collectives"] >= 1, stats
    assert stats["flapped"] and stats["topology"] == "a,a,b,b"
