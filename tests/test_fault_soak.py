"""Tier-1 wiring of the fault-soak runner (tools/fault_soak.py).

A short seeded configuration: 3 steps of 2-rank elastic DP training
with one injected transient collective fault, asserted bitwise-equal
to the clean run. The soak's CLI runs bigger/randomized plans; this
pins the contract in every tier-1 run.
"""

import importlib.util
import os

import jax
import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_fault_soak():
    spec = importlib.util.spec_from_file_location(
        "fault_soak", os.path.join(REPO, "tools", "fault_soak.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


fs = _load_fault_soak()


def test_make_fault_plan_is_seeded():
    assert fs.make_fault_plan(7, 4) == fs.make_fault_plan(7, 4)
    assert fs.make_fault_plan(7, 4).startswith("ring:nth=")


def test_soak_short_seeded_parity(tmp_path):
    """Clean vs injected-fault elastic training: identical final
    params, and the fault demonstrably fired + was recovered from."""
    steps, seed = 3, 1
    plan = fs.make_fault_plan(seed, steps)
    clean, _ = fs.run_soak(steps=steps, seed=seed,
                           ckpt_dir=str(tmp_path / "clean"))
    faulty, stats = fs.run_soak(steps=steps, seed=seed,
                                ckpt_dir=str(tmp_path / "faulty"),
                                fault_plan=plan)
    assert stats["fault_hits"] == 1, stats
    assert stats["resumes"] >= 1, stats
    assert stats["rebuilds"] >= 2, stats  # begin/ok traced per rank
    la, lb = (jax.tree_util.tree_leaves(clean),
              jax.tree_util.tree_leaves(faulty))
    assert len(la) == len(lb)
    for a, b in zip(la, lb):
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes()
