"""Staged-pipeline overlap tests.

BENCH_r05 showed staged "pipelined" == serial in throughput; the fix
is asserted here STRUCTURALLY, not by timing a ratio: the Python
tracer's stage spans (xslice.stage_gather / stage_ring /
stage_scatter, all on the flight-recorder clock) must show segment
k+1's gather STARTING before segment k's ring op ENDS — the copy for
the next chunk is issued while the previous chunk is on the wire.
Throughput ratios on a CPU-saturated host are ~1 by construction (see
bench.py's staged_note); interleaving is the invariant that transfers
to hosts where the staging copies ride a DMA engine.
"""

import threading

import numpy as np
import pytest

from rocnrdma_tpu.collectives.jax_shim import CrossSliceAllReduce
from rocnrdma_tpu.collectives.world import local_worlds
from rocnrdma_tpu.utils.trace import trace

from test_transport import free_port


def _spans(name):
    """[(rank, seg, start, end)] for one span family (span events are
    recorded at END with dur_s)."""
    out = []
    for ts, _, fields in trace.events(name):
        out.append((fields.get("rank"), fields["seg"],
                    ts - fields["dur_s"], ts))
    return out


def _run_staged(nleaves, leaf_elems, pipelined, monkeypatch):
    monkeypatch.setenv("TDR_STAGE_PIPELINE", "1" if pipelined else "0")
    worlds = local_worlds(2, free_port())
    shims = [CrossSliceAllReduce(w) for w in worlds]
    trees = [[(np.arange(leaf_elems, dtype=np.float32) % 353) * (r + 1)
              for _ in range(nleaves)] for r in range(2)]
    outs = [None, None]

    def run(r):
        outs[r] = shims[r](trees[r])

    ts = [threading.Thread(target=run, args=(r,)) for r in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    for s in shims:
        s.close()
    for w in worlds:
        w.close()
    return outs


def test_pipelined_gather_overlaps_ring(monkeypatch):
    """For every rank there is at least one (k, k+1) pair where
    gather(k+1) starts before ring(k) ends — and by construction of
    the loop, many: the gather is issued at ring-op SUBMIT time."""
    monkeypatch.setenv("TDR_STAGE_CHUNK", str(256 << 10))
    _run_staged(nleaves=8, leaf_elems=(256 << 10) // 4,
                pipelined=True, monkeypatch=monkeypatch)
    rings = _spans("xslice.stage_ring")
    gathers = _spans("xslice.stage_gather")
    assert len({s for _, s, _, _ in rings}) >= 4, \
        "need several segments for an overlap claim"
    for rank in (0, 1):
        ring_end = {s: e for rk, s, _, e in rings if rk == rank}
        gather_start = {s: b for rk, s, b, _ in gathers if rk == rank}
        overlapped = [k for k in ring_end
                      if k + 1 in gather_start
                      and gather_start[k + 1] < ring_end[k]]
        assert overlapped, (
            f"rank {rank}: no gather(k+1) started before ring(k) "
            f"ended — the staged pipeline is serialized again")


def test_serial_mode_does_not_overlap(monkeypatch):
    """The control: with TDR_STAGE_PIPELINE off the same spans are
    strictly ordered (gather k+1 starts only after ring k ends) — so
    the overlap assertion above measures the pipeline, not span
    bookkeeping noise."""
    monkeypatch.setenv("TDR_STAGE_CHUNK", str(256 << 10))
    _run_staged(nleaves=8, leaf_elems=(256 << 10) // 4,
                pipelined=False, monkeypatch=monkeypatch)
    rings = _spans("xslice.stage_ring")
    gathers = _spans("xslice.stage_gather")
    for rank in (0, 1):
        ring_end = {s: e for rk, s, _, e in rings if rk == rank}
        gather_start = {s: b for rk, s, b, _ in gathers if rk == rank}
        for k, end in ring_end.items():
            if k + 1 in gather_start:
                assert gather_start[k + 1] >= end


@pytest.mark.parametrize("pipelined", [False, True])
def test_staged_modes_bitwise_equal(pipelined, monkeypatch):
    """Pipelined and serial staged syncs produce byte-identical trees
    (the ring ops run in the same deterministic segment order)."""
    monkeypatch.setenv("TDR_STAGE_CHUNK", str(128 << 10))
    outs = _run_staged(nleaves=6, leaf_elems=(128 << 10) // 4,
                       pipelined=pipelined, monkeypatch=monkeypatch)
    expect = sum(((np.arange((128 << 10) // 4, dtype=np.float32) % 353)
                  * (r + 1) for r in range(2)),
                 np.zeros((128 << 10) // 4, dtype=np.float32))
    for r in range(2):
        for leaf in outs[r]:
            assert np.asarray(leaf).tobytes() == expect.tobytes()
